"""On-TPU exact-mode engine sweep (round-5 measurement plan).

Times, with the chained-chunk discipline (tpusim.profiling.time_chained_chunks,
>= 12 chunk programs inside one jit, min of 3 repeats), every candidate
configuration of the exact-mode execution stack on the two configs production
sweeps actually run — the reference's 40 % selfish benchmark and the honest
10 s-propagation roster (README.md:51-107) — plus a fast-mode status-quo
control:

  * pallas vs scan (the r4 open question: a 4-miner smoke hinted exact pallas
    may be 0.78x scan after the lazy-diagonal rewrite; this decides
    make_engine's exact routing from data)
  * group_slots 2 (the auto default since round 5; the split-slot kernel
    specialization that bought the fast path 1.58x) vs 4 (the pre-round-5
    exact default, the generic K-slot machinery)
  * tile_runs 256 (VMEM-guard limit) vs 512 with the guard bypassed (the
    lazy-diagonal rewrite shrank contraction temporaries; only the real
    compiler can say whether 512 now fits)
  * step_block 32 / 64 / 128

Appends one JSON row per point to artifacts/exact_sweep_r5.jsonl and prints a
ranked summary. Run it the moment the tunnel is back:

    python scripts/tpu_exact_sweep.py [--runs 2048] [--n-chunks 12]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", type=int, default=2048)
    ap.add_argument("--n-chunks", type=int, default=12)
    ap.add_argument("--out", type=Path,
                    default=Path(__file__).resolve().parent.parent
                    / "artifacts" / "exact_sweep_r5.jsonl")
    ap.add_argument("--skip-fast-control", action="store_true")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    print("platform:", dev)
    if dev.platform != "tpu":
        print("refusing to sweep off-TPU: these numbers gate engine routing",
              file=sys.stderr)
        return 1

    from tpusim.config import SimConfig, default_network, reference_selfish_network
    from tpusim.engine import Engine
    from tpusim.pallas_engine import PallasEngine
    from tpusim.profiling import time_chained_chunks
    from tpusim.runner import make_run_keys

    SELFISH40 = reference_selfish_network()
    HONEST10S = default_network(propagation_ms=10_000)

    points: list[dict] = []
    for cfg_name, net in (("selfish40", SELFISH40), ("honest10s", HONEST10S)):
        for k in (4, 2):
            points.append(dict(cfg=cfg_name, net=net, mode="exact", k=k, engine="scan"))
            # K=2 shrinks the exact state enough that tile 384 passes even
            # the conservative VMEM guard; 512 still needs the real
            # compiler's judgment (guard off).
            for tile, guard in ((256, True), (384, True), (512, False)):
                sbs = (32, 64, 128) if tile == 256 else (64,)
                for sb in sbs:
                    points.append(dict(cfg=cfg_name, net=net, mode="exact", k=k,
                                       engine="pallas", tile=tile, sb=sb, guard=guard))
    if not args.skip_fast_control:
        points.append(dict(cfg="honest1s", net=default_network(propagation_ms=1000),
                           mode="fast", k=2, engine="pallas", tile=512, sb=64, guard=True))
    # Guard-bypassed (t512 exact) compiles crash the remote compile helper
    # (HTTP 500, first r5 capture) — and the tunnel died minutes after the
    # third crash. Keep the exploratory points LAST so a helper wedge cannot
    # cost any guarded measurement.
    points.sort(key=lambda p: not p.get("guard", True))

    # Rows append to the JSONL as they are measured: this sweep runs in
    # scarce tunnel-up windows, and a mid-sweep tunnel drop (or an OOM-kill
    # from a guard-bypassed tiling) must not discard finished points.
    args.out.parent.mkdir(parents=True, exist_ok=True)

    def record(row: dict) -> None:
        rows.append(row)
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")

    keys = None
    rows = []
    for p in points:
        # Per-point feasibility (learned from the first r5 capture, where
        # these points errored instead of measuring): the batch must be a
        # multiple of tile_runs (384 at 2048 -> run 1920 instead), and the
        # pallas engine needs chunk_steps % step_block == 0 (the auto 1856
        # is 64-aligned only; round up for step_block 128).
        runs_p = args.runs
        if p["engine"] == "pallas" and runs_p % p["tile"]:
            runs_p = max(p["tile"], (runs_p // p["tile"]) * p["tile"])
        cfg = SimConfig(network=p["net"], duration_ms=12 * 2_629_746 * 1000,
                        runs=runs_p, batch_size=runs_p, seed=7,
                        mode=p["mode"], group_slots=p["k"])
        label = (f"{p['cfg']}/{p['engine']}/K{p['k']}"
                 + (f"/t{p['tile']}x{p['sb']}" if p["engine"] == "pallas" else ""))
        try:
            if p["engine"] == "pallas":
                # Probe the auto chunk_steps with a throwaway scan engine
                # (inside the try: a failing point must not kill the sweep).
                auto_steps = Engine(cfg).chunk_steps
                if auto_steps % p["sb"]:
                    cfg = dataclasses.replace(
                        cfg,
                        chunk_steps=((auto_steps + p["sb"] - 1) // p["sb"]) * p["sb"],
                    )
                eng = PallasEngine(cfg, tile_runs=p["tile"], step_block=p["sb"],
                                   vmem_guard=p["guard"])
            else:
                eng = Engine(cfg)
            if keys is None or keys.shape[0] != runs_p:
                keys = make_run_keys(7, 0, runs_p)
            t0 = time.time()
            r = time_chained_chunks(eng, keys, n_chunks=args.n_chunks)
        except Exception as e:  # noqa: BLE001 — a failing point must not kill the sweep
            print(f"[{label}] FAILED: {type(e).__name__}: {str(e)[:300]}", flush=True)
            record({"date": time.strftime("%Y-%m-%d"), "chip": str(dev),
                    "label": label, "error": str(e)[:300]})
            continue
        # us/step at R runs -> sim-years/s estimate: one batch-step advances
        # all R runs by ~interval/2.05 s of sim time (chunk sizing, engine.py:
        # ~2.05 events per block).
        interval_s = cfg.network.block_interval_s
        sim_years_per_s = (
            runs_p * (interval_s / 2.05) / (r["us_per_step"] * 1e-6)
        ) / (365.2425 * 86_400)
        row = {"date": time.strftime("%Y-%m-%d"), "chip": str(dev), "label": label,
               "wall_s": round(time.time() - t0, 1),
               "est_sim_years_per_s": round(sim_years_per_s, 1), **r}
        print(f"[{label}] {r['us_per_step']} us/step, spread {r['spread_pct']}%, "
              f"~{row['est_sim_years_per_s']} sim-years/s", flush=True)
        record(row)

    # Rank by the runs-normalized rate, NOT raw us_per_step: tile-divisibility
    # trims some points to a smaller batch (e.g. t384 runs 1920 of 2048), and
    # us_per_step scales with per-step work — a 6% batch difference is larger
    # than the margins this sweep decides.
    ok = [r for r in rows if "us_per_step" in r]
    for r in sorted(ok, key=lambda r: -r["est_sim_years_per_s"]):
        print(f"{r['est_sim_years_per_s']:>10.1f} sim-years/s "
              f"({r['us_per_step']:.3f} us/step @ {r.get('runs', '?')} runs)  "
              f"{r['label']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
