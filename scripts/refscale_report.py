"""Assemble artifacts/refscale_*.json into REFSCALE.md and fill
BASELINE.json's `published` block.

Checks, per config, the BASELINE.json cross-validation criterion: TPU-engine
per-miner stale rates within ±1e-4 absolute of (a) the reference README
tables (reference README.md:51-107, 32768 runs x 365 d) and (b) the native
C++ oracle run at the same scale, where its artifact exists.
"""
from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
ART = REPO / "artifacts"

# Reference README tables, transcribed verbatim (32768 runs x 365 d;
# reference README.md:51-107).
README_TABLES = {
    "prop10s": {
        "stale_rate": [0.010092, 0.0104315, 0.0162079, 0.0165404, 0.0175598,
                       0.0185974, 0.0192927, 0.0199286, 0.0199886],
        "source": "README.md:51-64 (10 s propagation)",
    },
    "prop100ms": {
        "stale_rate": [0.000101929, 0.000105712, 0.000162978, 0.000168355,
                       0.000176048, 0.000190155, 0.000193449, 0.000196773,
                       0.000204597],
        "source": "README.md:66-80 (100 ms propagation)",
    },
    "selfish40": {
        "share0": 0.466844,
        "stale0": 0.274658,
        "honest_stale": [None, 0.674269, 0.67498, 0.674999, 0.675386,
                         0.675667, 0.676207, 0.677416, 0.677529],
        "source": "README.md:89-107 (40% selfish, gamma=0)",
    },
}

TOL = 1e-4


def load(config: str, backend: str) -> dict | None:
    p = ART / f"refscale_{config}_{backend}.json"
    return json.loads(p.read_text()) if p.exists() else None


def main() -> int:
    rows = []
    ok = True
    published = {}
    for config in ("default1s", "prop10s", "prop100ms", "selfish40"):
        tpu = load(config, "tpu")
        native = load(config, "native")
        if tpu is None:
            continue
        entry = {
            "runs": tpu["runs"],
            "tpu_sim_years_per_s_incl_compile": tpu["sim_years_per_s"],
            "tpu_stale_rates": [round(m["stale_rate_mean"], 6) for m in tpu["miners"]],
            "tpu_shares": [round(m["blocks_share_mean"], 6) for m in tpu["miners"]],
        }
        if native is not None:
            entry["native_sim_years_per_s"] = native["sim_years_per_s"]
            # Per-miner tolerance: the flat 1e-4 for the honest configs'
            # small stale rates, widened to the Monte-Carlo envelope where it
            # is the binding constraint — stale_rate is a per-run ratio of
            # ~independent Poisson counts (stale/found), var ≈ R(1+R)/found,
            # and the two backends are two independent 32768-run estimates
            # (diff σ = √2·σ_mean). Selfish configs' honest miners sit at
            # R ≈ 0.675 with ~314 found blocks, where σ_diff ≈ 2.9e-4.
            max_d = max_sigma = 0.0
            for a, b in zip(tpu["miners"], native["miners"]):
                d = abs(a["stale_rate_mean"] - b["stale_rate_mean"])
                r = b["stale_rate_mean"]
                sigma = (r * (1 + r) / max(b["blocks_found_mean"], 1.0)) ** 0.5
                env = max(TOL, 4 * (2 ** 0.5) * sigma / tpu["runs"] ** 0.5)
                max_d = max(max_d, d)
                max_sigma = max(max_sigma, d / env)
            max_share_d = max(
                abs(a["blocks_share_mean"] - b["blocks_share_mean"])
                for a, b in zip(tpu["miners"], native["miners"])
            )
            entry["max_abs_stale_diff_vs_native"] = round(max_d, 8)
            entry["max_abs_share_diff_vs_native"] = round(max_share_d, 8)
            entry["stale_vs_native_worst_envelope_fraction"] = round(max_sigma, 3)
            entry["within_tolerance_of_native"] = bool(
                max_sigma <= 1.0 and max_share_d <= TOL
            )
            ok &= max_sigma <= 1.0 and max_share_d <= TOL
        readme = README_TABLES.get(config)
        if readme and "stale_rate" in readme:
            diffs = [
                abs(m["stale_rate_mean"] - want)
                for m, want in zip(tpu["miners"], readme["stale_rate"])
                if want is not None
            ]
            entry["max_abs_stale_diff_vs_README"] = round(max(diffs), 8)
            entry["within_1e-4_of_README"] = bool(max(diffs) <= TOL)
            ok &= max(diffs) <= TOL
        if readme and "share0" in readme:
            d_share = abs(tpu["miners"][0]["blocks_share_mean"] - readme["share0"])
            d_stale = abs(tpu["miners"][0]["stale_rate_mean"] - readme["stale0"])
            entry["selfish_share_diff_vs_README"] = round(d_share, 6)
            entry["selfish_stale_diff_vs_README"] = round(d_stale, 6)
            ok &= d_share <= 1e-4 and d_stale <= 1e-4
            # Honest miners' ~67.5% stale rates carry real Monte-Carlo
            # variance: stale_rate is the ratio of two ~independent Poisson
            # counts (stale / blocks-in-best-chain), so one run has
            # var ≈ R(1+R)/found — for a 1%-hashrate miner (~314 found, R
            # ≈ 0.675) that is σ_run ≈ 0.06, σ_mean ≈ 3.3e-4 at 32768 runs.
            # Two independent estimates (ours vs the README's own run)
            # differ by up to ~4√2·σ_mean; the honest-column criterion is
            # that per-miner statistical envelope, not the flat 1e-4.
            worst = 0.0
            for m, want in zip(tpu["miners"], readme["honest_stale"]):
                if want is None:
                    continue
                sigma = (want * (1 + want) / max(m["blocks_found_mean"], 1.0)) ** 0.5
                envelope = 4 * (2 ** 0.5) * sigma / tpu["runs"] ** 0.5
                worst = max(worst, abs(m["stale_rate_mean"] - want) / envelope)
            entry["max_honest_stale_diff_vs_README_in_4sigma_units"] = round(worst, 3)
            entry["honest_stale_within_envelope"] = bool(worst <= 1.0)
            ok &= worst <= 1.0
        rows.append((config, entry))
        published[config] = entry

    if not rows:
        print(json.dumps({"ok": False, "error": "no refscale TPU artifacts found"}))
        return 1

    baseline = json.loads((REPO / "BASELINE.json").read_text())
    # Preserve sibling evidence blocks other scripts maintain under
    # `published` (update_fullscale_published.py owns `full_scale_grids`).
    prior = baseline.get("published", {})
    extra = {
        k: v
        for k, v in prior.items()
        if k not in ("scale", "criterion", "all_within_tolerance", "configs")
    }
    baseline["published"] = {
        "scale": "32768 runs x 365.2425 d per config (reference main.cpp:7-10)",
        "criterion": (
            f"per-miner stale-rate abs diff <= {TOL}, widened to the per-miner "
            f"4*sqrt(2)*sigma Monte-Carlo envelope where two independent "
            f"finite-sample estimates make the flat bound unattainable "
            f"(selfish configs' honest miners, sigma_diff ~ 3e-4); shares "
            f"always <= {TOL}"
        ),
        "all_within_tolerance": ok,
        "configs": published,
        **extra,
    }
    # indent=1 matches update_fullscale_published.py so alternating runs of
    # the two scripts don't re-indent (and churn) the whole file.
    (REPO / "BASELINE.json").write_text(json.dumps(baseline, indent=1) + "\n")

    lines = [
        "# REFSCALE — full-scale reproduction of the reference tables",
        "",
        "Every config at the reference's own scale (32 768 runs × 365.2425 d,",
        "reference main.cpp:7-10), TPU engine (v5e, single chip) vs the native",
        "C++ oracle vs the published README tables. Artifacts under",
        "`artifacts/refscale_*.json`; regenerate with `scripts/refscale.py`,",
        "re-assemble with `scripts/refscale_report.py`.",
        "",
    ]
    for config, entry in rows:
        lines.append(f"## {config}")
        lines.append("```json")
        lines.append(json.dumps(entry, indent=2))
        lines.append("```")
        lines.append("")
    lines.append(
        "**Overall: "
        + (
            "ALL WITHIN TOLERANCE** (flat ±1e-4 on honest-config stale rates "
            "and all shares; per-miner 4√2σ Monte-Carlo envelope on selfish "
            "configs' honest-miner stale rates, where two independent "
            "32768-run estimates cannot meet a flat 1e-4)"
            if ok
            else "TOLERANCE EXCEEDED**"
        )
    )
    (REPO / "REFSCALE.md").write_text("\n".join(lines) + "\n")
    print(json.dumps({"ok": ok, "configs": [c for c, _ in rows]}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
