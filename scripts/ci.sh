#!/usr/bin/env bash
# Single CI entry point: the full Python test pyramid on the forced-CPU
# 8-virtual-device backend (tests/conftest.py) plus the native backend's
# sanitizer legs. Run from anywhere; exits nonzero on the first red leg so
# a failing test can never land silently.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tpusim lint =="
# Pure-AST static analysis (tpusim.lint): fails on any NEW finding — the
# committed baseline grandfathers old ones. Runs first because it needs no
# jax import and catches donated-buffer/host-sync/recompile mistakes in
# seconds, before the expensive legs spin up. The per-module JAX rules
# (JX001-JX009), the cross-module contract pass (JX010-JX014: telemetry
# span/attr contracts, chaos seam registry, finalize leaf naming, CLI docs
# drift, metrics/SLO registry contract) AND the concurrency pass
# (JX015-JX019: unsynchronized shared state, thread lifecycle, lock-order
# conflicts, blocking calls under a lock, fork/signal hazards) run in this
# one gate.
python -m tpusim.cli lint --baseline .tpusim-lint-baseline.json
# Registration floor: the contract passes must actually be REGISTERED *and*
# ENABLED — a rule-table slip (a deleted registry row, a pyproject
# enabled-rules regression) would otherwise rot this gate into a tautology
# that greens while checking nothing. --list-rules annotates disabled rules,
# so the floor counts rules that will actually RUN in the gate above.
rule_count=$(python -m tpusim.cli lint --list-rules | grep -cv "(disabled)")
if [ "$rule_count" -lt 20 ]; then
  echo "lint gate degraded: only $rule_count rules enabled (need >= 20)" >&2
  exit 1
fi
for contract_rule in JX013 JX014 JX015 JX016 JX017 JX018 JX019 JX020; do
  python -m tpusim.cli lint --list-rules | grep "^$contract_rule" | grep -qv "(disabled)" \
    || { echo "contract rule $contract_rule missing/disabled in --list-rules" >&2; exit 1; }
done

echo "== native: build + ASan/UBSan/TSan smoke =="
make -C native check

echo "== pytest =="
python -m pytest tests/ -q "$@"

echo "== chaos degradation matrix =="
# The fault-injection matrix (tpusim.chaos + tests/test_chaos.py): every
# documented recovery path driven by deterministic injected faults, each
# recovered run pinned bit-equal to the fault-free run. Runs as its own leg
# so a chaos regression is named in CI output even when someone runs the
# pytest leg with a filter.
env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q -m 'not slow'

echo "== concurrency runtime guard (thread-leak + scrape-under-load) =="
# The runtime complement of the JX015-JX019 static pass: the fleet
# supervisor's fake-worker path, the reusable fetch watchdog, and the
# metrics scrape server each run under tpusim.testing.thread_leak_guard
# (the `thread_guard` fixture) — every thread the code spawns must be
# joined or accounted for by exit. The scrape drill additionally hammers
# /metrics from concurrent scrapers while a writer tears JSONL appends
# mid-line: every response must be a parseable OpenMetrics 200. Runs as
# its own leg so a thread leak is named in CI output even when the pytest
# leg runs filtered.
env JAX_PLATFORMS=cpu python -m pytest -q \
  "tests/test_fleet.py::test_fleet_completes_rows_in_point_order" \
  "tests/test_chaos.py::test_fetch_with_deadline_bounded_watchdog_threads" \
  "tests/test_metrics.py::test_scrape_under_concurrent_torn_writes"

echo "== chaos drill smoke =="
# One CLI-surface drill end-to-end: inject a transient dispatch fault via
# --chaos, survive it through the retry path, and render the fault ledger.
chaos_dir=$(mktemp -d)
cat > "$chaos_dir/plan.json" <<'EOF'
{"faults": [{"point": "engine.dispatch", "kind": "transient", "count": 1,
             "when": {"batch": 0}, "note": "ci drill"}]}
EOF
env JAX_PLATFORMS=cpu python -m tpusim --runs 4 --batch-size 4 \
  --duration-ms 86400000 --single-device --quiet \
  --chaos "$chaos_dir/plan.json" --telemetry "$chaos_dir/drill.jsonl"
env JAX_PLATFORMS=cpu python -m tpusim report "$chaos_dir/drill.jsonl" \
  | grep -q "Fault ledger (injected chaos)"
rm -rf "$chaos_dir"

echo "== perf guard (batched RNG + packed state + gathers + count rebase) =="
# The PR-6/PR-10 hot-path contracts, as a standalone leg so a regression is
# named in CI output: (a) the default (flight_capacity=0) device-loop
# program still carries ZERO recorder machinery with the packed/batched
# state leaves (jaxpr program-text check — no ring tensor, no slot modulo);
# (b) the warmed batched-RNG dispatch paths recompile exactly never;
# (c) the consensus_gather program carries NO legacy one-hot contraction
# muls over the (R, M, M[, M]) consensus tensors (and the legacy program
# still does — the check cannot rot into a tautology); (d) gather reads and
# per-chunk count re-basing are bit-equal to the legacy one-hot / un-rebased
# int32 programs, fast AND exact-selfish.
env JAX_PLATFORMS=cpu python - <<'EOF'
import dataclasses, re
import numpy as np
import jax
from tpusim.config import SimConfig, default_network, reference_selfish_network
from tpusim.engine import Engine
from tpusim.flight import N_FIELDS
from tpusim.runner import make_run_keys
from tpusim.testing import compile_count_guard

cfg = SimConfig(network=default_network(), duration_ms=86_400_000, runs=8,
                batch_size=8, chunk_steps=64)
assert cfg.rng_batch and cfg.resolved_count_dtype == "int16", (
    cfg.rng_batch, cfg.resolved_count_dtype)
keys = make_run_keys(0, 0, 8)

def loop_jaxpr(c, n=8):
    eng = Engine(c)
    hi, lo = eng._ledger_init(n)
    return str(jax.make_jaxpr(lambda k: eng._device_loop(k, hi, lo, eng.params))(keys))

off = loop_jaxpr(cfg)
on = loop_jaxpr(dataclasses.replace(cfg, flight_capacity=7))
marker = f"7,{N_FIELDS}]"
assert " rem " not in off and marker not in off, "recorder leaked into cap=0 program"
assert " rem " in on and marker in on, "recorder missing from cap>0 program"

# (c) one-hot contraction ops absent when consensus_gather is on.
exact = SimConfig(network=reference_selfish_network(), mode="exact",
                  duration_ms=4 * 86_400_000, runs=8, batch_size=8,
                  chunk_steps=64, seed=3, count_rebase=False)
contraction = re.compile(r":i16\[8,9,9(,9)?\] = mul")
gat = loop_jaxpr(exact)
leg = loop_jaxpr(dataclasses.replace(exact, consensus_gather=False))
assert not contraction.search(gat) and " gather[" in gat, \
    "one-hot contraction leaked into the gather program"
assert contraction.search(leg) and " gather[" not in leg, \
    "legacy program lost its contraction signature (dead check)"

# (d) gather + count-rebase bit-equality pins.
for name, base in (("fast", dataclasses.replace(cfg, duration_ms=4 * 86_400_000)),
                   ("exact", exact)):
    kk = make_run_keys(base.seed, 0, 8)
    legacy = Engine(dataclasses.replace(
        base, consensus_gather=False, count_rebase=False,
        state_dtype="int32")).run_batch(kk)
    new = Engine(dataclasses.replace(base, count_rebase=True)).run_batch(kk)
    assert legacy.keys() == new.keys()
    for key in legacy:
        np.testing.assert_array_equal(
            np.asarray(legacy[key]), np.asarray(new[key]),
            err_msg=f"{name}: {key}")

eng = Engine(cfg)
eng.run_batch(keys)
eng.run_batch(keys, pipelined=True)
with compile_count_guard(exact=0):
    eng.run_batch(keys)
    eng.run_batch(keys, pipelined=True)
print("perf guard: compiled-out recorder + gather/rebase pins + zero warm recompiles OK")
EOF

echo "== telemetry smoke =="
# One tiny batch end-to-end through the telemetry path: the JSONL ledger must
# parse and `tpusim report` must render it (exit 0) — the cheapest guard
# against a span-schema or dashboard regression landing silently.
tele_dir=$(mktemp -d)
trap 'rm -rf "$tele_dir"' EXIT
# Arm the provenance plane for every artifact-producing leg from here on
# (the env var is inherited by sweep/fleet/perf subprocesses AND their
# workers): rows, perf rows, checkpoints and flight exports all append
# content-addressed lineage records the audit leg below joins and gates.
export TPUSIM_PROVENANCE="$tele_dir/provenance/lineage.jsonl"
env JAX_PLATFORMS=cpu python -m tpusim --runs 4 --batch-size 4 \
  --duration-ms 86400000 --single-device --quiet \
  --telemetry "$tele_dir/smoke.jsonl"
env JAX_PLATFORMS=cpu python - "$tele_dir/smoke.jsonl" <<'EOF'
import sys
from tpusim.telemetry import load_spans
spans = load_spans(sys.argv[1])
names = {s["span"] for s in spans}
assert "batch" in names and "run" in names, names
# Perf-observability smoke: a cold run MUST record its compiles (the
# CompileLedger spans) and per-batch memory watermarks — a silently dead
# compile listener or memory probe would otherwise stay green forever.
assert "compile" in names, names
batch = next(s for s in spans if s["span"] == "batch")
assert batch["attrs"].get("mem_live_bytes", 0) > 0, batch["attrs"]
run = next(s for s in spans if s["span"] == "run")
assert run["attrs"].get("compiles", 0) > 0, run["attrs"]
EOF
env JAX_PLATFORMS=cpu python -m tpusim report "$tele_dir/smoke.jsonl" > /dev/null

echo "== watch --once smoke =="
# The live dashboard's snapshot mode on the fresh smoke ledger: must render
# the convergence panel (the runner's per-batch `stats` spans) and exit 0 —
# this is the dead-terminal / CI usage mode. Deliberately NO JAX_PLATFORMS:
# `tpusim watch` is jax-free by design and must stay that way. The grep
# targets a string only the POPULATED panel emits ("target rel hw") — a
# bare "convergence" would also match the no-stats-spans fallback line and
# let a dead stats pipeline slip through green.
python -m tpusim watch --once "$tele_dir/smoke.jsonl" | grep -q "target rel hw"

echo "== perf observability (regression ledger + noise gate) =="
# The repo's canonical perf ritual as a command (tpusim.perf): a quick
# chained-chunk run appends schema-validated ledger rows, and the
# spread-aware compare gates them against the calibration baseline committed
# from this container. Exit nonzero only on a regression beyond measured
# noise — the margin floor is 50% because this 2-core host's quick min-of-3
# shape still swings (the committed baseline's own spread is ~26%); a real
# regression like the synthetic 2x pinned in tests/test_perf_obs.py clears
# that floor either way.
# The quick run includes the packed_sweep scenario (sweep_sequential +
# sweep_packed points/sec on the scaled reference selfish-threshold grid),
# so the compare below also gates the grid-packing speedup against its
# regenerated calibration row.
env JAX_PLATFORMS=cpu python -m tpusim.cli perf run --quick \
  --out "$tele_dir/perf_quick.jsonl"
env JAX_PLATFORMS=cpu python -m tpusim.cli perf compare \
  artifacts/perf/calibration_cpu.jsonl "$tele_dir/perf_quick.jsonl" \
  --min-margin 0.5
python -m tpusim.cli perf report "$tele_dir/perf_quick.jsonl" > /dev/null

echo "== packed-sweep leg (grid packing bit-equality) =="
# Device-side grid packing (tpusim.packed): the same small selfish-threshold
# grid through the sequential and the packed run_sweep paths, output files
# diffed LINE-FOR-LINE minus the wall-clock fields (elapsed_s/compile_s —
# the fleet-leg strip), and the packed per-point convergence panel rendered
# by BOTH dashboards. The points/sec perf gate for packing rides the
# perf-observability leg above. The leg runs ARMED: the packed pass writes
# per-point piece checkpoints (--checkpoint-dir no longer disables packing)
# and the grid repeats under rng=xoroshiro (per-run stream seeds pack too) —
# both formerly fallback carve-outs, now diffed bit-for-bit against the
# sequential path. A resumed packed pass over the finished checkpoint dir
# must reproduce the same rows without recomputing.
packed_dir="$tele_dir/packed"
mkdir -p "$packed_dir"
env JAX_PLATFORMS=cpu python - "$packed_dir" <<'EOF'
import json, sys
from pathlib import Path
from tpusim.config import NetworkConfig, SimConfig
from tpusim.sweep import _selfish_network, run_sweep

out = Path(sys.argv[1])

def grid(rng):
    pts = []
    for interval_s in (300.0, 600.0):
        for pct in (30, 40):
            net = _selfish_network(pct)
            net = NetworkConfig(miners=net.miners, block_interval_s=interval_s)
            pts.append((f"i{int(interval_s)}-s{pct}",
                        SimConfig(network=net, runs=8, duration_ms=86_400_000,
                                  batch_size=8, rng=rng)))
    return pts

cache: dict = {}
run_sweep(grid("threefry"), quiet=True, engine_cache=cache,
          out_path=out / "seq.jsonl")
run_sweep(grid("threefry"), quiet=True, engine_cache=cache, packed=True,
          out_path=out / "packed.jsonl",
          telemetry_path=out / "packed.tele.jsonl",
          checkpoint_dir=out / "ckpt")
assert sorted(p.name for p in (out / "ckpt").glob("*.npz")), "no piece ckpts"
# Resume over the complete checkpoint dir: zero new dispatches, same rows.
run_sweep(grid("threefry"), quiet=True, engine_cache=cache, packed=True,
          out_path=out / "packed_resume.jsonl", checkpoint_dir=out / "ckpt")
# The xoroshiro carve-out is gone: per-run stream seeds pack bit-for-bit.
run_sweep(grid("xoroshiro"), quiet=True, engine_cache=cache,
          out_path=out / "seq_xoro.jsonl")
run_sweep(grid("xoroshiro"), quiet=True, engine_cache=cache, packed=True,
          out_path=out / "packed_xoro.jsonl")
for name in ("seq", "packed", "packed_resume", "seq_xoro", "packed_xoro"):
    rows = [json.loads(ln) for ln in (out / f"{name}.jsonl").open()]
    for r in rows:
        r.pop("elapsed_s", None); r.pop("compile_s", None)
    (out / f"{name}.stripped").write_text(
        "\n".join(json.dumps(r) for r in rows) + "\n")
EOF
diff "$packed_dir/seq.stripped" "$packed_dir/packed.stripped"
diff "$packed_dir/seq.stripped" "$packed_dir/packed_resume.stripped"
diff "$packed_dir/seq_xoro.stripped" "$packed_dir/packed_xoro.stripped"
python -m tpusim watch --once "$packed_dir/packed.tele.jsonl" \
  | grep -q "by grid point"
env JAX_PLATFORMS=cpu python -m tpusim report "$packed_dir/packed.tele.jsonl" \
  | grep -q "Convergence by grid point"
echo "packed sweep: rows line-identical + per-point panels rendered"

echo "== fleet kill-drill smoke =="
# The elastic-fleet healing contract end to end (tpusim.fleet): two
# supervisor runs over the same 2-point grid — one clean and sequential, one
# PACKED (both points as one sub-grid unit) with the COMMITTED worker-kill
# drill plan (drills/fleet-worker-kill.json: SIGKILL the attempt-0 worker
# right after its first piece checkpoint turns durable) — must produce
# IDENTICAL rows minus wall-clock (cross-path: drilled packed == clean
# sequential), the supervisor must requeue exactly once and quarantine
# nothing, the replacement worker must heal MID-PACK via the shared piece
# checkpoints (a `checkpoint_load` span with packed=true in the ledger),
# `tpusim watch` (started BEFORE the ledger exists, via --wait-for-file)
# must follow the drill live and exit on the closing span, and
# `tpusim report` must render the fleet panel.
fleet_dir="$tele_dir/fleet"
mkdir -p "$fleet_dir"
# The drill supervisor's ledger lives INSIDE its state dir so the
# orchestration-timeline leg below can merge supervisor + worker ledgers
# from one root (`tpusim trace timeline STATE_DIR`).
timeout 420 python -m tpusim watch --no-clear --interval 1 \
  --wait-for-file 300 "$fleet_dir/drill/fleet.tele.jsonl" > "$fleet_dir/watch.txt" &
watch_pid=$!
env JAX_PLATFORMS=cpu python -m tpusim.cli fleet propagation --max-points 2 \
  --runs-scale 3e-6 --batch-size 2 --workers 2 --single-device --no-probe \
  --quiet --state-dir "$fleet_dir/ref" --lease-s 120
env JAX_PLATFORMS=cpu python -m tpusim.cli fleet propagation --max-points 2 \
  --runs-scale 3e-6 --batch-size 2 --workers 2 --single-device --no-probe \
  --quiet --state-dir "$fleet_dir/drill" --lease-s 120 \
  --telemetry "$fleet_dir/drill/fleet.tele.jsonl" \
  --packed --grid-size 2 \
  --worker-chaos drills/fleet-worker-kill.json --worker-chaos-point prop-100ms
wait "$watch_pid"
grep -q "fleet:" "$fleet_dir/watch.txt"
env JAX_PLATFORMS=cpu python - "$fleet_dir/ref/rows.jsonl" \
  "$fleet_dir/drill/rows.jsonl" "$fleet_dir/drill/fleet-ledger.jsonl" <<'EOF'
import json, sys
rows = []
for path in sys.argv[1:3]:
    parsed = [json.loads(ln) for ln in open(path) if ln.strip()]
    for r in parsed:
        r.pop("elapsed_s", None); r.pop("compile_s", None)
    rows.append(parsed)
ref, drill = rows
assert [r["point"] for r in ref] == [r["point"] for r in drill], (ref, drill)
assert ref == drill, "drilled fleet rows diverged from the uninterrupted run"
events = [json.loads(ln)["event"] for ln in open(sys.argv[3]) if ln.strip()]
assert events.count("requeue") == 1 and events.count("quarantine") == 0, events
print(f"fleet kill drill: {len(drill)} rows bit-equal after 1 requeue")
EOF
# The healed sub-grid must have resumed MID-PACK from the shared piece
# checkpoints, not recomputed from scratch: the replacement worker's own
# ledger (state-dir/workers/*.tele.jsonl — the files `trace timeline`
# merges) carries a packed checkpoint_load span.
python - "$fleet_dir/drill" <<'EOF'
import json, sys
from pathlib import Path
loads = [
    row
    for path in sorted(Path(sys.argv[1], "workers").glob("*.tele.jsonl"))
    for row in map(json.loads, path.open())
    if row.get("span") == "checkpoint_load"
    and (row.get("attrs") or {}).get("packed")
]
assert loads, "no packed checkpoint_load span: the healed sub-grid recomputed"
print(f"fleet kill drill: healed mid-pack ({len(loads)} piece-checkpoint loads)")
EOF
env JAX_PLATFORMS=cpu python -m tpusim report "$fleet_dir/drill/fleet.tele.jsonl" \
  | grep -q "Fleet (worker supervisor)"

echo "== orchestration timeline (distributed tracing) =="
# The cross-process span tree of the drill above (tpusim.tracing): merge the
# supervisor + worker ledgers, render the critical-path attribution, export
# the orchestration Perfetto trace — then gate the acceptance contract:
# per-category attribution accounts for >= 90% of the supervisor-measured
# fleet wall-clock (remainder explicit as "unattributed"), and the exported
# trace passes the shared validate_perfetto schema check. Jax-free on
# purpose: `trace timeline` must work on a host with no backend.
python -m tpusim trace timeline "$fleet_dir/drill" \
  --out "$fleet_dir/orchestration.trace.json" > "$fleet_dir/timeline.txt"
grep -q "Wall-clock attribution (critical path)" "$fleet_dir/timeline.txt"
grep -q "Per-worker utilization" "$fleet_dir/timeline.txt"
python - "$fleet_dir/orchestration.trace.json" <<'EOF'
import json, sys
from tpusim.tracing import validate_perfetto
trace = json.load(open(sys.argv[1]))
n = validate_perfetto(trace)
att = trace["otherData"]["attribution"]
total = sum(att["categories"].values())
assert abs(total - att["total_s"]) < 1e-6, (total, att["total_s"])
assert att["coverage"] >= 0.9, f"attribution covers only {att['coverage']:.1%}: {att}"
assert att["categories"]["backoff"] > 0, att  # the drill's requeue backoff
print(f"orchestration trace: {n} events, {100 * att['coverage']:.1f}% of "
      f"{att['total_s']:.1f}s fleet wall-clock attributed")
EOF
# The merged state-dir report renders the critical-path panel next to the
# per-(run_id, process) throughput groups.
env JAX_PLATFORMS=cpu python -m tpusim report "$fleet_dir/drill" \
  | grep -q "Fleet time attribution (critical path)"

echo "== metrics & SLO plane =="
# The live metrics/SLO plane (tpusim.metrics) against the drill state dir
# the fleet leg just produced: feed the query-latency histogram with real
# concurrent packed queries (scripts/loadgen.py appends perf rows INTO the
# state dir), export + strictly validate the OpenMetrics exposition
# (declared families, _total counters, cumulative buckets, +Inf == _count,
# terminal # EOF), smoke the live endpoint with a --once self-scrape,
# render the shared-evaluator SLO panels in report AND watch, then gate the
# committed [tool.tpusim-slo] objectives — `slo check` must exit 0. The
# dead-gate discipline is drilled too: `slo check` over an EMPTY state dir
# must exit 2 (an empty ledger can never pass green).
env JAX_PLATFORMS=cpu python scripts/loadgen.py --queries 3 --concurrency 2 \
  --quiet --out "$fleet_dir/drill/perf/loadgen.jsonl"
python -m tpusim metrics export "$fleet_dir/drill" \
  --out "$fleet_dir/metrics.prom" > /dev/null
python - "$fleet_dir/metrics.prom" <<'EOF'
from sys import argv
from tpusim.metrics import validate_openmetrics
n = validate_openmetrics(open(argv[1]).read())
assert n > 0, "empty exposition"
print(f"metrics export: {n} samples validated")
EOF
python -m tpusim metrics serve --state-dir "$fleet_dir/drill" --port 0 --once \
  > "$fleet_dir/scrape.txt"
grep -q "scrape OK" "$fleet_dir/scrape.txt"
env JAX_PLATFORMS=cpu python -m tpusim report "$fleet_dir/drill" \
  --slo-config pyproject.toml | grep -q "SLO status"
python -m tpusim watch --once "$fleet_dir/drill/fleet.tele.jsonl" \
  --slo-config pyproject.toml | grep -q "SLO status"
python -m tpusim slo check "$fleet_dir/drill"
slo_empty=$(mktemp -d)
slo_rc=0; python -m tpusim slo check "$slo_empty" > /dev/null 2>&1 || slo_rc=$?
[ "$slo_rc" -eq 2 ] \
  || { echo "SLO dead-gate drill: empty state dir exited $slo_rc, want 2" >&2; exit 1; }
rm -rf "$slo_empty"
echo "metrics & SLO plane: exposition valid, endpoint scraped, objectives green"

echo "== serve leg (crash-only service: loadgen storm, SLO profile, drain) =="
# The crash-only simulation service end to end (tpusim.serve): a live daemon
# on an ephemeral port, the HTTP loadgen storm (warmup compiles, then a
# timed mixed-shape/cache-hit storm — compiles_per_query must stay 0), the
# serve SLO profile gated over the daemon's own state dir, then the graceful
# drain drill: a SECOND storm is TERMed mid-load and the daemon must exit 0
# with closed accounting (accepted == served + shed, drain.json clean) —
# never a lost accepted query. The daemon inherits TPUSIM_PROVENANCE, so
# `tpusim audit` then resolves every served row to a served_query record.
serve_dir="$tele_dir/serve"
mkdir -p "$serve_dir"
env JAX_PLATFORMS=cpu python -m tpusim serve --state-dir "$serve_dir" \
  --port 0 > "$serve_dir/daemon.log" 2>&1 &
serve_pid=$!
for _ in $(seq 1 150); do
  [ -f "$serve_dir/endpoint.json" ] && break
  sleep 0.2
done
[ -f "$serve_dir/endpoint.json" ] \
  || { echo "serve daemon never wrote endpoint.json" >&2; cat "$serve_dir/daemon.log" >&2; exit 1; }
serve_url=$(python - "$serve_dir/endpoint.json" <<'EOF'
import json, sys
print(json.load(open(sys.argv[1]))["url"])
EOF
)
python scripts/loadgen.py --serve "$serve_url" --queries 6 --concurrency 3 \
  --out "$serve_dir/perf/loadgen.jsonl"
python -m tpusim slo check "$serve_dir" --profile serve
# Mid-load drain: storm the daemon again (fresh seed: real cache-miss work
# in flight), TERM it mid-storm, require exit 0 + clean accounting. The
# drain 503s the storm's unadmitted tail (that is admission control working,
# not a failure), so the background loadgen's own exit code is not gated.
python scripts/loadgen.py --serve "$serve_url" --queries 6 --concurrency 3 \
  --seed 100 --quiet --out "$serve_dir/perf/loadgen2.jsonl" \
  > /dev/null 2>&1 &
loadgen_pid=$!
sleep 2
kill -TERM "$serve_pid"
serve_rc=0; wait "$serve_pid" || serve_rc=$?
wait "$loadgen_pid" 2>/dev/null || true
[ "$serve_rc" -eq 0 ] \
  || { echo "serve drain: daemon exited $serve_rc, want 0" >&2; cat "$serve_dir/daemon.log" >&2; exit 1; }
python - "$serve_dir/drain.json" <<'EOF'
import json, sys
summary = json.load(open(sys.argv[1]))
assert summary["clean"] is True, summary
assert summary["accepted"] == summary["served"] + summary["shed"], summary
print(f"serve drain: accepted={summary['accepted']} served={summary['served']} "
      f"shed={summary['shed']} rejected={summary['rejected']} clean")
EOF
python -m tpusim audit "$serve_dir"

echo "== flight-recorder trace smoke =="
# One tiny flight-enabled run end-to-end: export the Perfetto trace + JSONL
# event log, validate the trace schema, and cross-check the event rows
# against the scalar counters' vocabulary — the cheapest guard against a
# recorder/export regression landing silently.
env JAX_PLATFORMS=cpu python -m tpusim trace --runs 2 --batch-size 2 \
  --duration-ms 86400000 --single-device --quiet --flight-capacity 512 \
  --trace-out "$tele_dir/smoke.trace.json" --events-out "$tele_dir/events.jsonl"
env JAX_PLATFORMS=cpu python - "$tele_dir/smoke.trace.json" "$tele_dir/events.jsonl" <<'EOF'
import json, sys
from tpusim.flight import KIND_NAMES
from tpusim.flight_export import validate_perfetto
trace = json.load(open(sys.argv[1]))
n = validate_perfetto(trace)
events = [json.loads(ln) for ln in open(sys.argv[2])]
assert n == len(events) > 0, (n, len(events))
assert all(e["kind"] in KIND_NAMES for e in events)
assert events == sorted(events, key=lambda e: (e["run"], e["seq"]))
EOF

echo "== cross-backend trace diff (JAX vs native) =="
# The README "Event tracing" diff recipe end to end, no hand-rolled harness:
# the scan engine under rng=xoroshiro (JAX_ENABLE_X64: the interval mapping
# is bit-exact only in float64) and the native backend's trace producer
# (simcore_run_events) must emit the SAME event sequence for the same seed;
# `tpusim trace diff` localizes any divergence and exits nonzero on one.
# 30 s propagation at a 6 h duration forces real races so the arrival/stale
# classification paths are exercised, not just finds.
env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python -m tpusim trace --runs 2 \
  --batch-size 2 --duration-ms 21600000 --single-device --quiet \
  --rng xoroshiro --seed 11 --propagation-ms 30000 --flight-capacity 2048 \
  --trace-out "$tele_dir/xoro.trace.json" --events-out "$tele_dir/jax_events.jsonl"
python -m tpusim trace --backend cpp --runs 2 --duration-ms 21600000 \
  --seed 11 --propagation-ms 30000 --quiet \
  --events-out "$tele_dir/native_events.jsonl"
python -m tpusim trace diff "$tele_dir/jax_events.jsonl" "$tele_dir/native_events.jsonl"

echo "== native sanitizer harness (ASan/UBSan under ctypes) =="
# The same xoroshiro A/B + trace-diff recipe, but the native side runs the
# ASan/UBSan-INSTRUMENTED library inside the real Python harness
# (TPUSIM_SIMCORE_LIB override + preloaded sanitizer runtimes): the event
# stream must stay byte-identical to the JAX engine's AND the sanitizers
# must stay silent — `make check`'s standalone smoke cannot see bugs that
# only the ctypes ABI surface (array lifetimes, int widths) provokes.
# detect_leaks=0: CPython leaks by design at exit; halt_on_error=1 turns a
# UBSan diagnostic into a red leg instead of a scrolled-past warning.
asan_rt=$("${CXX:-g++}" -print-file-name=libasan.so 2>/dev/null || true)
ubsan_rt=$("${CXX:-g++}" -print-file-name=libubsan.so 2>/dev/null || true)
if [ -f "$asan_rt" ] && [ -f "$ubsan_rt" ] && make -C native sanitize; then
  san_env="ASAN_OPTIONS=detect_leaks=0 UBSAN_OPTIONS=halt_on_error=1"
  env LD_PRELOAD="$asan_rt $ubsan_rt" $san_env \
    TPUSIM_SIMCORE_LIB=native/libsimcore_san.so \
    python -m tpusim trace --backend cpp --runs 2 --duration-ms 21600000 \
    --seed 11 --propagation-ms 30000 --quiet \
    --events-out "$tele_dir/native_events_san.jsonl"
  python -m tpusim trace diff \
    "$tele_dir/jax_events.jsonl" "$tele_dir/native_events_san.jsonl"
  # Threaded partitioning path under the sanitizers (the smoke binary runs
  # it standalone; this drives it through run_simulation_cpp's ctypes ABI).
  env LD_PRELOAD="$asan_rt $ubsan_rt" $san_env \
    TPUSIM_SIMCORE_LIB=native/libsimcore_san.so \
    python -m tpusim --backend cpp --runs 8 --threads 4 \
    --duration-ms 86400000 --quiet > /dev/null
else
  # Loud skip, never silent: a missing sanitizer runtime must be visible in
  # the CI log, not quietly green.
  echo "SKIP: sanitizer harness leg NOT run (compiler lacks libasan/libubsan" \
       "runtimes or the sanitize build failed)" >&2
fi

echo "== provenance audit (cross-plane consistency gate) =="
# Every artifact-producing leg above ran ARMED (TPUSIM_PROVENANCE exported
# with the telemetry-smoke leg), so one lineage ledger now spans the smoke
# run, both sweeps (sequential + packed + resumed), the fleet drill's
# workers, the perf/loadgen rows, the piece checkpoints and the flight
# exports. `tpusim audit` joins all of it — lineage + spans + fleet ledger
# + perf ledger + checkpoint npz fingerprints — and verifies the audit
# invariants. Deliberately NO JAX_PLATFORMS: the audit plane is jax-free by
# design and must stay that way (the `tpusim watch` rule).
python -m tpusim audit "$tele_dir"
# The gate must be able to turn RED: mutate one value in one on-disk sweep
# row (its content hash then resolves to no lineage record), require exit 1,
# restore, require exit 0 again. A gate that cannot fail is a dead gate.
cp "$packed_dir/seq.jsonl" "$packed_dir/seq.jsonl.orig"
sed -i '1s/"runs": 8/"runs": 9/' "$packed_dir/seq.jsonl"
audit_rc=0; python -m tpusim audit "$tele_dir" --quiet >/dev/null 2>&1 || audit_rc=$?
[ "$audit_rc" -eq 1 ] \
  || { echo "audit mutation drill: mutated row exited $audit_rc, want 1" >&2; exit 1; }
mv "$packed_dir/seq.jsonl.orig" "$packed_dir/seq.jsonl"
python -m tpusim audit "$tele_dir" --quiet
# Dead-gate drill: with the env ledger masked, an artifact root holding ZERO
# lineage records must exit 2 — an empty ledger can never pass green.
audit_empty=$(mktemp -d)
audit_rc=0; env -u TPUSIM_PROVENANCE python -m tpusim audit "$audit_empty" \
  >/dev/null 2>&1 || audit_rc=$?
[ "$audit_rc" -eq 2 ] \
  || { echo "audit dead-gate drill: empty root exited $audit_rc, want 2" >&2; exit 1; }
rm -rf "$audit_empty"
# The lineage tree walks from a real on-disk row back through the run that
# produced it, and the sealed evidence bundle round-trips offline.
python -m tpusim lineage show "$packed_dir/seq.jsonl" | grep -q "sweep_row"
env JAX_PLATFORMS=cpu python -m tpusim report "$tele_dir/smoke.jsonl" \
  --lineage "$TPUSIM_PROVENANCE" | grep -q "Provenance (lineage ledger)"
python -m tpusim bundle create "$tele_dir/evidence.tar.gz" \
  "$tele_dir/provenance" "$tele_dir/smoke.jsonl" "$tele_dir/perf_quick.jsonl"
python -m tpusim bundle verify "$tele_dir/evidence.tar.gz"
echo "provenance audit: gate green, mutation drill red/green, bundle sealed"

echo "== CI green =="
