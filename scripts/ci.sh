#!/usr/bin/env bash
# Single CI entry point: the full Python test pyramid on the forced-CPU
# 8-virtual-device backend (tests/conftest.py) plus the native backend's
# sanitizer legs. Run from anywhere; exits nonzero on the first red leg so
# a failing test can never land silently.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== native: build + ASan/UBSan/TSan smoke =="
make -C native check

echo "== pytest =="
python -m pytest tests/ -q "$@"

echo "== CI green =="
