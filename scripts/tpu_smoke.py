"""On-TPU smoke for the Pallas engine: lower, run, cross-check vs the scan
twin bit-for-bit, and time both. Used interactively during hardware bring-up;
the committed artifacts of these runs are artifacts/perf_tpu.jsonl and the
hardware table in BASELINE.md."""
import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np
import jax


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", type=int, default=512)
    ap.add_argument("--days", type=int, default=30)
    ap.add_argument("--selfish", action="store_true")
    ap.add_argument("--tile-runs", type=int, default=512)
    ap.add_argument("--step-block", type=int, default=64)
    ap.add_argument("--chunk-steps", type=int, default=None,
                    help="explicit chunk_steps (must be a multiple of step-block; "
                         "the auto value is 64-aligned only)")
    ap.add_argument("--skip-scan", action="store_true")
    ap.add_argument("--no-vmem-guard", action="store_true",
                    help="bypass the VMEM footprint guard (bring-up: let the "
                         "real compiler judge an oversized tiling)")
    args = ap.parse_args()

    from tpusim import SimConfig, default_network
    from tpusim.config import MinerConfig, NetworkConfig
    from tpusim.pallas_engine import PallasEngine
    from tpusim.runner import make_run_keys

    print("platform:", jax.devices()[0])
    if args.selfish:
        net = NetworkConfig(miners=(
            MinerConfig(hashrate_pct=40, propagation_ms=1000, selfish=True),
            MinerConfig(hashrate_pct=30, propagation_ms=1000),
            MinerConfig(hashrate_pct=20, propagation_ms=1000),
            MinerConfig(hashrate_pct=10, propagation_ms=1000),
        ))
    else:
        net = default_network(propagation_ms=1000)
    cfg = SimConfig(network=net, duration_ms=args.days * 86_400_000,
                    runs=args.runs, batch_size=args.runs, seed=7,
                    chunk_steps=args.chunk_steps)
    eng = PallasEngine(cfg, tile_runs=args.tile_runs, step_block=args.step_block,
                       vmem_guard=not args.no_vmem_guard)
    years = args.runs * args.days / 365.2425

    t0 = time.time()
    out = eng.run_batch(make_run_keys(7, 0, args.runs))
    print(f"pallas compile+run {time.time()-t0:.2f}s")
    t0 = time.time()
    out = eng.run_batch(make_run_keys(7, args.runs, args.runs))
    dt_p = time.time() - t0
    print(f"pallas steady {dt_p:.3f}s  ({years/dt_p:,.0f} sim-years/s)")

    if args.skip_scan:
        return
    tw = eng.scan_twin()
    t0 = time.time()
    out2 = tw.run_batch(make_run_keys(7, args.runs, args.runs))
    print(f"scan compile+run {time.time()-t0:.2f}s")
    t0 = time.time()
    out2 = tw.run_batch(make_run_keys(7, args.runs, args.runs))
    dt_s = time.time() - t0
    print(f"scan steady {dt_s:.3f}s  ({years/dt_s:,.0f} sim-years/s)")
    print(f"pallas/scan speedup: {dt_s/dt_p:.2f}x")
    ok = True
    for k in out:
        if k == "runs":
            continue
        same = np.array_equal(np.asarray(out[k]), np.asarray(out2[k]))
        ok &= same
        if not same:
            print(k, "MISMATCH", np.asarray(out[k]), np.asarray(out2[k]))
    print("bit-identical:", ok)
    print(json.dumps({"pallas_sim_years_per_s": years / dt_p,
                      "scan_sim_years_per_s": years / dt_s,
                      "speedup": dt_s / dt_p, "bit_identical": bool(ok)}))


if __name__ == "__main__":
    main()
