#!/usr/bin/env bash
# Looping TPU tunnel watcher: probes every ~7 min and re-launches the given
# playbook on EVERY tunnel-up probe (not one-shot like tpu_watch.sh) — the
# playbook must make re-runs cheap (tpu_r5d_plan.sh: done-markers per step +
# --resume sweeps), so each short window resumes exactly where the last one
# died. A run is started at most once per probe cycle and never concurrently.
#
#   setsid nohup bash scripts/tpu_watch_loop.sh scripts/tpu_r5d_plan.sh >/dev/null 2>&1 &
#
# Log: /tmp/tpu_watch.log. Stop: touch /tmp/tpu_watch_stop.
cd "$(dirname "$0")/.."
PLAN="${1:-scripts/tpu_r5d_plan.sh}"
while true; do
  [ -f /tmp/tpu_watch_stop ] && { echo "$(date -u +%FT%TZ) stop requested" >> /tmp/tpu_watch.log; exit 0; }
  if timeout -k 5 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) TPU UP; running $PLAN" >> /tmp/tpu_watch.log
    bash "$PLAN" >> /tmp/tpu_watch.log 2>&1
    echo "$(date -u +%FT%TZ) $PLAN pass finished" >> /tmp/tpu_watch.log
  else
    echo "$(date -u +%FT%TZ) tpu down" >> /tmp/tpu_watch.log
  fi
  sleep 420
done
