"""Roofline + superstep/batch ablation harness.

Measures events/s for the scan engine (and the Pallas kernel when a real TPU
is attached) with the chained-chunk timing discipline
(tpusim.profiling.time_chained_chunks), derives the memory-bandwidth-bound
event rate from the engines' traffic models (tpusim.profiling.bytes_per_event)
against a STREAM-style measured copy bandwidth, and emits:

  * one machine-readable JSON document (--out, default
    artifacts/roofline_<platform>.json) with every measured point and the
    bandwidth measurement, and
  * an optional committed markdown report (--md ROOFLINE.md) stating how far
    each engine sits from its bandwidth roof plus the K x batch ablation
    table.

When the harness runs on CPU, the Pallas side of the report falls back to the
last builder-measured on-chip rates in artifacts/perf_tpu.jsonl (the same
cache bench.py serves when the TPU tunnel is down) against the v5e HBM
datasheet bandwidth, clearly labelled as cached.

Run on local CPU:  JAX_PLATFORMS=cpu python scripts/roofline.py --md ROOFLINE.md
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

#: v5e HBM bandwidth (GB/s, datasheet) — the roof for cached on-chip rates.
V5E_HBM_GBPS = 819.0

YEAR_MS = 365.2425 * 86_400_000.0


def log(msg: str) -> None:
    print(f"[roofline] {msg}", file=sys.stderr, flush=True)


def cached_tpu_points(bandwidth_gbps: float) -> list[dict]:
    """Pallas roofline points reconstructed from the perf log's end-to-end
    headline rows (mode + sim_years_per_s) — served when this harness cannot
    reach a TPU, so the committed report never loses the on-chip story."""
    from bench import cached_tpu_numbers

    from tpusim.config import (
        SimConfig, default_network, reference_selfish_network,
    )
    from tpusim.pallas_engine import PallasEngine
    from tpusim.profiling import bytes_per_event

    cached = cached_tpu_numbers()
    if cached is None:
        return []
    nets = {
        "fast": default_network(propagation_ms=1000),
        "exact": reference_selfish_network(),
    }
    points = []
    for mode, row in (("fast", cached.get("fast")), ("exact", cached.get("exact"))):
        if not row:
            continue
        cfg = SimConfig(network=nets[mode], runs=8192, batch_size=8192)
        try:
            eng = PallasEngine(cfg, interpret=True)  # traffic model only
        except ValueError:
            continue
        model = bytes_per_event(eng)
        events_per_year = 2.0 * cfg.duration_ms / (
            cfg.network.block_interval_s * 1000.0
        )
        events_per_s = row["sim_years_per_s"] * events_per_year
        roof = bandwidth_gbps * 1e9 / model["pallas"]
        points.append({
            "engine": "PallasEngine",
            "measurement": "cached (artifacts/perf_tpu.jsonl, "
                           + str(row.get("date", "?")) + ")",
            "chip": row.get("chip"),
            "mode": mode,
            "state_dtype": cfg.resolved_count_dtype,
            "runs": None,
            "chunk_steps": eng.chunk_steps,
            "superstep": eng.superstep,
            "traffic_model": "pallas",
            "state_bytes_per_run": model["state_bytes_per_run"],
            "bytes_per_event": round(model["pallas"], 2),
            "sim_years_per_s": row["sim_years_per_s"],
            "events_per_s": round(events_per_s, 1),
            "bandwidth_gbps": bandwidth_gbps,
            "roof_events_per_s": round(roof, 1),
            "fraction_of_roof": round(events_per_s / roof, 4),
        })
    return points


def measure_points(args, platform: str, bandwidth_gbps: float) -> list[dict]:
    import jax

    from tpusim.config import (
        SimConfig, default_network, reference_selfish_network,
    )
    from tpusim.engine import Engine
    from tpusim.profiling import roofline_point
    from tpusim.runner import make_run_keys

    nets = {
        "fast": default_network(propagation_ms=1000),
        "exact": reference_selfish_network(),
    }
    # Headline duration (365 d — int16-REBASED under the default
    # count_rebase: per-chunk count re-basing keeps the bound per-chunk, so
    # "auto" packs year-long runs) plus two comparison variants at the
    # largest batch: the legacy int32 un-rebased year-long layout (the
    # pre-rebase program, kept so the report shows what re-basing bought)
    # and the short-duration packed row (int16 WITHOUT re-basing — the
    # historical packed domain). The chained-chunk timing itself is
    # duration-independent (every chunk runs at the full TIME_CAP cap), so
    # these rows isolate exactly the layout effect.
    variants = [(365 * 86_400_000, args.batch_list, {})]
    variants.append((
        365 * 86_400_000, [max(args.batch_list)],
        {"state_dtype": "int32", "count_rebase": False},
    ))
    if args.packed_days > 0:
        variants.append((
            args.packed_days * 86_400_000, [max(args.batch_list)],
            {"count_rebase": False},
        ))
    points = []
    for mode in args.modes:
        net = nets[mode]
        for duration_ms, batches, overrides in variants:
            for batch in batches:
                keys = make_run_keys(7, 0, batch)
                for k in args.k_list:
                    cfg = SimConfig(
                        network=net, duration_ms=duration_ms, runs=batch,
                        batch_size=batch, seed=7, chunk_steps=args.chunk_steps,
                        superstep=k, **overrides,
                    )
                    engines = [Engine(cfg)]
                    if platform == "tpu":
                        from tpusim.pallas_engine import PallasEngine

                        try:
                            engines.append(PallasEngine(cfg))
                        except ValueError as e:
                            log(f"no pallas point for {mode}/{batch}/K={k}: {e}")
                    for eng in engines:
                        t0 = time.monotonic()
                        p = roofline_point(
                            eng, keys, bandwidth_gbps=bandwidth_gbps,
                            n_chunks=args.n_chunks, repeats=args.repeats,
                        )
                        if p.get("degenerate_timing"):
                            # Sub-resolution timing (profiling.roofline_point):
                            # the rates are meaningless — drop the row loudly
                            # rather than render a 0-events/s point.
                            log(
                                f"{mode}/{type(eng).__name__} batch={batch} "
                                f"K={k}: degenerate timing, dropped"
                            )
                            continue
                        p.update(
                            platform=platform, batch=batch,
                            duration_days=round(duration_ms / 86_400_000.0),
                        )
                        points.append(p)
                        log(
                            f"{mode}/{type(eng).__name__}[{p['state_dtype']}] "
                            f"batch={batch} K={k}: "
                            f"{p['events_per_s']:.0f} ev/s "
                            f"({100 * p['fraction_of_roof']:.1f}% of roof, "
                            f"{time.monotonic() - t0:.1f}s)"
                        )
    return points


def render_md(doc: dict) -> str:
    plat = doc["platform"]
    bw = doc["bandwidth_gbps"]
    lines = [
        "# Roofline: measured event rate vs the memory-bandwidth bound",
        "",
        f"Generated by `scripts/roofline.py` on platform `{plat}` "
        f"({doc['chip']}), {doc['date']}.",
        "",
        "## Traffic model",
        "",
        "An *event* is one scan step of one run (a potential block find plus",
        "the notify sweep). The bandwidth bound counts unavoidable memory",
        "traffic only:",
        "",
        "- **scan engine** — the `lax.scan` carry round-trips the whole",
        "  per-run state tree through memory every event:",
        "  `bytes/event = 2 x state + 8` (8 = the streamed per-event pair —",
        "  two raw uint32 words, or two pre-mapped int32 draws under the",
        "  default batched wide generation, `SimConfig.rng_batch`).",
        "- **Pallas kernel** — state is VMEM-resident for a whole chunk and",
        "  crosses HBM once per chunk each way:",
        "  `bytes/event = 2 x state / chunk_steps + 8`.",
        "",
        "`state` is dtype-aware: packed-state rows (`SimConfig.state_dtype`,",
        "int16 count leaves whenever the count bound provably fits — up to",
        "~106.8 d at the 600 s interval un-rebased, and year-long-plus under",
        "the default `SimConfig.count_rebase`, which re-bases the count",
        "leaves per chunk so the bound stops growing with duration) carry",
        "roughly half the count-leaf bytes, i.e. packing RAISES the roof",
        "where it applies, while batched RNG and supersteps close the",
        "distance to it. `int16+rebase` rows are the year-long packed",
        "layout; plain `int16` rows are the short-duration packed domain.",
        "",
        f"Measured copy bandwidth (STREAM-style jitted saxpy, read+write): "
        f"**{bw:.1f} GB/s** on this host"
        + (f"; cached TPU rows use the v5e datasheet {V5E_HBM_GBPS:.0f} GB/s."
           if doc.get("cached_tpu_points") else "."),
        "",
        "The *superstep* width K (events unrolled per scan step / kernel loop",
        "iteration) does not change the model — it attacks per-step control",
        "overhead, i.e. the distance from the roof, not the roof itself.",
        "",
        "## Measured points",
        "",
        "| engine | mode | dtype | days | batch | K | events/s | bytes/event | roof events/s | % of roof |",
        "|---|---|---|---:|---:|---:|---:|---:|---:|---:|",
    ]

    def dtype_cell(p):
        # int16 appears in TWO domains now: the short-duration packed rows
        # and the year-long count-rebased ones — mark the re-based layout.
        d = p.get("state_dtype", "int32")
        return f"{d}+rebase" if p.get("count_rebase") else d

    for p in doc["points"]:
        days = p.get("duration_days")
        lines.append(
            f"| {p['engine']} | {p['mode']} | {dtype_cell(p)} "
            f"| {days if days is not None else ''} "
            f"| {p.get('batch') or ''} "
            f"| {p['superstep']} | {p['events_per_s']:,.0f} "
            f"| {p['bytes_per_event']:.0f} | {p['roof_events_per_s']:,.0f} "
            f"| {100 * p['fraction_of_roof']:.2f}% |"
        )
    for p in doc.get("cached_tpu_points", []):
        lines.append(
            f"| {p['engine']} ({p['measurement']}) | {p['mode']} "
            f"| {dtype_cell(p)} |  |  "
            f"| {p['superstep']} | {p['events_per_s']:,.0f} "
            f"| {p['bytes_per_event']:.0f} | {p['roof_events_per_s']:,.0f} "
            f"| {100 * p['fraction_of_roof']:.2f}% |"
        )
    scan_points = [p for p in doc["points"] if p["traffic_model"] == "scan"]
    best = max(scan_points, key=lambda p: p["fraction_of_roof"], default=None)
    if best is not None:
        lines += [
            "",
            "## Reading",
            "",
            f"The best measured scan point reaches "
            f"**{100 * best['fraction_of_roof']:.1f}%** of the bandwidth-bound"
            f" event rate ({best['roof_events_per_s']:,.0f} events/s at "
            f"{best['bytes_per_event']:.0f} bytes/event). The PR-6 batched "
            "wide RNG (sampler mapping hoisted out of the event loop, "
            "`SimConfig.rng_batch`) and the fused adoption select attacked "
            "the control/compute gap; packed int16 state "
            "(`SimConfig.state_dtype`) attacks the traffic itself, and the "
            "`int16+rebase` rows extend it to year-long runs "
            "(`SimConfig.count_rebase`). The per-event consensus compute "
            "that ablation put at ~60% of the fast step is now addressed by "
            "the miner-axis gather reads (`SimConfig.consensus_gather`): "
            "the one-hot contract-and-sum reads of the best owner's rows "
            "became dynamic-index moves (O(M^2) -> O(M) fast, O(M^3) -> "
            "O(M^2) exact).",
        ]
    pallas_rows = [
        p for p in doc["points"] + doc.get("cached_tpu_points", [])
        if p.get("traffic_model") == "pallas"
    ]
    if pallas_rows:
        frac = max(p["fraction_of_roof"] for p in pallas_rows)
        lines += [
            "",
            f"The Pallas kernel sits at **{100 * frac:.2f}%** of its HBM "
            "roof: VMEM residency already removed per-event state traffic "
            "(~8-12 streamed bytes/event remain), so the kernel is "
            "compute-bound, not bandwidth-bound — closing the north-star gap "
            "is about per-event VPU work (miner-axis contractions, notify "
            "selects), not memory layout.",
        ]
    lines += [
        "",
        "Run-level evidence now flows through the unified telemetry sink",
        "(`tpusim.telemetry` + `python -m tpusim report`, README \"Telemetry\"): batch",
        "spans, stall histograms and the device-side occupancy counter land in",
        "`artifacts/telemetry/*.jsonl`, and the chained-chunk timings this report is",
        "built from deliberately force the always-on telemetry counters so a measured",
        "point is the program production actually runs. The traffic model above",
        "excludes the counters' 12 bytes/run — three orders below the state tree.",
    ]
    lines.append("")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--modes", default="fast,exact",
                    type=lambda s: s.split(","))
    ap.add_argument("--k-list", default="1,2,4,8",
                    type=lambda s: [int(x) for x in s.split(",")])
    ap.add_argument("--batch-list", default="64,256",
                    type=lambda s: [int(x) for x in s.split(",")])
    ap.add_argument("--chunk-steps", type=int, default=256,
                    help="pinned chunk_steps for comparable K points")
    ap.add_argument("--packed-days", type=int, default=45,
                    help="duration (days) for the packed-state (int16) rows "
                         "at the largest batch; 0 disables them")
    ap.add_argument("--n-chunks", type=int, default=12)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--out", type=Path, default=None,
                    help="JSON output (default artifacts/roofline_<platform>.json)")
    ap.add_argument("--md", type=Path, default=None,
                    help="also render the markdown report here (e.g. ROOFLINE.md)")
    args = ap.parse_args()

    import jax

    from tpusim.profiling import measure_copy_bandwidth_gbps

    platform = jax.devices()[0].platform
    log(f"platform: {platform}")
    bw = measure_copy_bandwidth_gbps()
    log(f"measured copy bandwidth: {bw:.2f} GB/s")

    points = measure_points(args, platform, bw)
    doc = {
        "date": time.strftime("%Y-%m-%d"),
        "platform": platform,
        "chip": str(jax.devices()[0]),
        "bandwidth_gbps": round(bw, 2),
        "chunk_steps": args.chunk_steps,
        "points": points,
    }
    if platform != "tpu":
        doc["cached_tpu_points"] = cached_tpu_points(V5E_HBM_GBPS)

    out = args.out or REPO / "artifacts" / f"roofline_{platform}.json"
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(doc, indent=2) + "\n")
    log(f"wrote {out}")
    if args.md is not None:
        args.md.write_text(render_md(doc))
        log(f"wrote {args.md}")
    print(json.dumps({
        "points": len(points),
        "bandwidth_gbps": doc["bandwidth_gbps"],
        "out": str(out),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
