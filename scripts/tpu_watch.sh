#!/bin/bash
# TPU tunnel watcher: probes the backend every ~7 min (SIGKILL-backed
# timeout — the wedged tunnel ignores SIGTERM in C land) and, on the first
# UP, runs the given playbook exactly once.
#
#   setsid nohup bash scripts/tpu_watch.sh scripts/tpu_r5b_plan.sh r5b >/dev/null 2>&1 &
#
# Log: /tmp/tpu_watch.log. One-shot latch: /tmp/<tag>_plan_started.
cd "$(dirname "$0")/.."
PLAN="${1:-scripts/tpu_r5_plan.sh}"
# Default the latch tag to the plan's basename so a new plan never silently
# reuses an older plan's one-shot latch (which would eat the tunnel window).
TAG="${2:-$(basename "$PLAN" .sh)}"
LATCH="/tmp/${TAG}_plan_started"
while true; do
  if timeout -k 5 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) TPU UP" >> /tmp/tpu_watch.log
    if [ ! -f "$LATCH" ]; then
      touch "$LATCH"
      echo "$(date -u +%FT%TZ) launching $PLAN" >> /tmp/tpu_watch.log
      bash "$PLAN" >> /tmp/tpu_watch.log 2>&1
      echo "$(date -u +%FT%TZ) $PLAN finished; watcher exiting" >> /tmp/tpu_watch.log
      exit 0
    fi
  else
    echo "$(date -u +%FT%TZ) tpu down" >> /tmp/tpu_watch.log
  fi
  sleep 420
done
