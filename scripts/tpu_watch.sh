#!/bin/bash
# TPU tunnel watcher: probes the backend every ~7 min (SIGKILL-backed
# timeout — the wedged tunnel ignores SIGTERM in C land) and, on the first
# UP, runs the round's measurement playbook exactly once.
#
#   setsid nohup bash scripts/tpu_watch.sh >/dev/null 2>&1 &
#
# Log: /tmp/tpu_watch.log. One-shot latch: /tmp/r5_plan_started.
cd "$(dirname "$0")/.."
while true; do
  if timeout -k 5 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) TPU UP" >> /tmp/tpu_watch.log
    if [ ! -f /tmp/r5_plan_started ]; then
      touch /tmp/r5_plan_started
      echo "$(date -u +%FT%TZ) launching r5 plan" >> /tmp/tpu_watch.log
      bash scripts/tpu_r5_plan.sh artifacts/r5_tpu_logs >> /tmp/tpu_watch.log 2>&1
      echo "$(date -u +%FT%TZ) r5 plan finished; watcher exiting" >> /tmp/tpu_watch.log
      exit 0
    fi
  else
    echo "$(date -u +%FT%TZ) tpu down" >> /tmp/tpu_watch.log
  fi
  sleep 420
done
