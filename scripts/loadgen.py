"""Load generator for the metrics/SLO plane: N concurrent quick-shape sweep
queries, appending per-query latency rows to the perf ledger.

Two modes, one ledger shape:

* **In-process** (default): each "query" is a small selfish-threshold grid
  (the ci.sh packed-leg shape) dispatched through
  ``run_sweep(..., packed=True)`` against a SHARED engine cache. One untimed
  warmup query compiles the engines; the timed queries then run concurrently
  across ``--concurrency`` worker threads, so the recorded latencies include
  real dispatch contention — the number the p50/p99 SLO gate must hold.
* **Service** (``--serve URL``): the same mixed-shape query stream is driven
  as concurrent HTTP ``POST /api/query`` calls against a live ``tpusim
  serve`` daemon — some queries repeat configs (exact cache hits), some
  alternate pack shapes (coalescing + engine-cache reuse) — so the SLO
  evaluator gates the REAL service path, not just the in-process proxy.
  Retryable rejections — 503 backpressure and 504 shed — are retried with
  backoff and still count inside the query's recorded latency. Timed-phase
  compiles are read from the daemon's ``GET /api/stats`` counter delta.

Two perf-ledger rows land per invocation (tpusim.perf schema, scenario
``loadgen``):

  query_latency_s    value = fastest query, samples = every query's
                     wall-clock seconds (the metrics plane folds these into
                     the tpusim_query_latency_seconds histogram)
  compiles_per_query value = backend compiles observed during the TIMED
                     phase / queries — the warmed path must not compile, so
                     the default AND serve SLO profiles pin this == 0

Usage:
    JAX_PLATFORMS=cpu python scripts/loadgen.py --queries 4 --concurrency 2 \
        --out artifacts/perf/loadgen.jsonl
    python scripts/loadgen.py --serve http://127.0.0.1:8700 --queries 8 \
        --concurrency 4 --out serve/perf.jsonl
    python -m tpusim slo check artifacts/perf/
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))  # runnable as `python scripts/loadgen.py`


def query_points(seed: int, rng: str = "threefry"):
    """One query's grid: the ci.sh packed-leg quick shape (2 intervals x
    1 selfish pct, 8 runs x 1 day, batch 8) — small enough to answer in
    seconds on CPU, shaped exactly like the real sweep path."""
    from tpusim.config import NetworkConfig, SimConfig
    from tpusim.sweep import _selfish_network

    pts = []
    for interval_s in (300.0, 600.0):
        net = _selfish_network(30)
        net = NetworkConfig(miners=net.miners, block_interval_s=interval_s)
        pts.append((
            f"q{seed}-i{int(interval_s)}",
            SimConfig(network=net, runs=8, duration_ms=86_400_000,
                      batch_size=8, seed=seed, rng=rng),
        ))
    return pts


def serve_payloads(seed: int, queries: int, rng: str = "threefry"):
    """The service-mode query stream: ``queries`` POST bodies cycling over
    three distinct configs — two block intervals at batch 8 (one pack
    shape) plus a batch-4 variant (a SECOND pack shape), so a storm
    exercises shape grouping, and every repeat of a config is an exact
    result-cache hit."""
    from tpusim.config import NetworkConfig, SimConfig
    from tpusim.sweep import _selfish_network

    base = []
    for j, (interval_s, batch) in enumerate(
        ((300.0, 8), (600.0, 8), (300.0, 4))
    ):
        net = _selfish_network(30)
        net = NetworkConfig(miners=net.miners, block_interval_s=interval_s)
        cfg = SimConfig(network=net, runs=8, duration_ms=86_400_000,
                        batch_size=batch, seed=seed + 1 + j, rng=rng)
        base.append((f"sq{seed}-i{int(interval_s)}-b{batch}",
                     json.loads(cfg.to_json())))
    return [
        {"name": f"{base[i % len(base)][0]}-{i}",
         "config": base[i % len(base)][1]}
        for i in range(queries)
    ]


def _http_json(url: str, payload: dict | None = None, timeout: float = 180.0):
    """(status, decoded-JSON body) for one GET (payload None) or POST."""
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    data = json.dumps(payload).encode() if payload is not None else None
    req = Request(url, data=data,
                  headers={"Content-Type": "application/json"} if data else {})
    try:
        with urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except HTTPError as e:
        body = e.read()
        try:
            decoded = json.loads(body or b"{}")
        except json.JSONDecodeError:
            decoded = {"error": body.decode(errors="replace")}
        return e.code, decoded


def _serve_query(base_url: str, payload: dict, *, attempts: int = 6) -> float:
    """POST one query, riding out every retryable rejection the daemon's
    crash-only contract documents: 503 backpressure (sleep the advertised
    eta_s) and 504 shed (a drilled/wedged pack whose fault is spent — the
    retry is served). Returns the query's total wall-clock (retries
    included — backpressure IS service latency); raises on a non-retryable
    or exhausted query."""
    t0 = time.perf_counter()
    last: dict = {}
    for _ in range(attempts):
        status, body = _http_json(base_url + "/api/query", payload)
        if status == 200 and body.get("status") == "served":
            return time.perf_counter() - t0
        last = {"http": status, **(body if isinstance(body, dict) else {})}
        if status in (503, 504) and body.get("retryable"):
            eta = body.get("eta_s")
            time.sleep(min(float(eta), 5.0) if isinstance(eta, (int, float))
                       else 0.5)
            continue
        break
    raise RuntimeError(
        f"query {payload.get('name')!r} not served: {last}"
    )


def _run_serve_mode(args) -> int:
    from tpusim.perf import append_rows, perf_row

    base_url = args.serve.rstrip("/")
    payloads = serve_payloads(args.seed, args.queries)
    distinct = {json.dumps(p["config"], sort_keys=True): p for p in payloads}

    # Warmup: each DISTINCT config once, sequentially and untimed — the
    # daemon's engine cache compiles here, so a compile counted during the
    # timed storm is a genuine warmed-path cache miss.
    if not args.quiet:
        print(f"[loadgen] warmup: {len(distinct)} distinct config(s) "
              f"against {base_url} (untimed, compiles expected)...")
    for p in distinct.values():
        _serve_query(base_url, p)

    status, stats0 = _http_json(base_url + "/api/stats", timeout=30.0)
    if status != 200:
        print(f"error: GET /api/stats -> {status}", file=sys.stderr)
        return 1
    compiles0 = int((stats0.get("counters") or {}).get("compiles") or 0)

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=args.concurrency) as pool:
        latencies = list(pool.map(
            lambda p: _serve_query(base_url, p), payloads
        ))
    wall = time.perf_counter() - t0

    status, stats1 = _http_json(base_url + "/api/stats", timeout=30.0)
    if status != 200:
        print(f"error: GET /api/stats -> {status}", file=sys.stderr)
        return 1
    counters = stats1.get("counters") or {}
    compiles = int(counters.get("compiles") or 0) - compiles0

    latencies.sort()
    shape = {"queries": args.queries, "concurrency": args.concurrency,
             "mode": "serve"}
    rows = [
        perf_row("loadgen", "query_latency_s", latencies[0], unit="s",
                 samples=latencies, shape=shape),
        perf_row("loadgen", "compiles_per_query",
                 compiles / args.queries, unit="count", shape=shape),
    ]
    append_rows(args.out, rows)
    if not args.quiet:
        mid = latencies[len(latencies) // 2]
        print(f"[loadgen] {args.queries} queries x {args.concurrency} "
              f"threads over HTTP in {wall:.2f}s wall: p50~{mid:.2f}s "
              f"min {latencies[0]:.2f}s max {latencies[-1]:.2f}s, "
              f"{compiles} timed-phase compile(s), daemon counters "
              f"served={counters.get('served')} "
              f"cache_hits={counters.get('cache_hits')} "
              f"coalesced={counters.get('coalesced')}")
        print(f"[loadgen] appended 2 rows to {args.out}")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--queries", type=int, default=4, metavar="N",
                    help="timed queries to dispatch (default 4)")
    ap.add_argument("--concurrency", type=int, default=2, metavar="C",
                    help="concurrent query threads (default 2)")
    ap.add_argument("--out", type=Path,
                    default=REPO / "artifacts" / "perf" / "loadgen.jsonl",
                    help="perf ledger to append the two loadgen rows to")
    ap.add_argument("--seed", type=int, default=0,
                    help="base seed; query i runs with seed+1+i")
    ap.add_argument("--serve", metavar="URL",
                    help="drive a live `tpusim serve` daemon over HTTP "
                    "instead of the in-process packed path")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    if args.queries < 1 or args.concurrency < 1:
        ap.error("--queries and --concurrency must be >= 1")
    if args.serve:
        return _run_serve_mode(args)

    from tpusim.perf import append_rows, perf_row
    from tpusim.sweep import run_sweep
    from tpusim.testing import subscribe_backend_compiles

    cache: dict = {}

    def run_query(seed: int) -> float:
        t0 = time.perf_counter()
        run_sweep(query_points(seed), quiet=True, engine_cache=cache,
                  packed=True)
        return time.perf_counter() - t0

    # Warmup: compiles land here, NOT in the timed window. Same shapes as
    # every timed query, so a compile observed later is a genuine cache
    # miss on the warmed path — the `compiles_per_query == 0` objective.
    if not args.quiet:
        print("[loadgen] warmup query (untimed, compiles expected)...")
    run_query(args.seed)

    compiles = 0

    def on_compile(_name: str, _secs: float) -> None:
        nonlocal compiles
        compiles += 1

    unsubscribe = subscribe_backend_compiles(on_compile)
    try:
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=args.concurrency) as pool:
            latencies = list(pool.map(
                run_query,
                [args.seed + 1 + i for i in range(args.queries)],
            ))
        wall = time.perf_counter() - t0
    finally:
        unsubscribe()

    latencies.sort()
    shape = {"queries": args.queries, "concurrency": args.concurrency}
    rows = [
        perf_row("loadgen", "query_latency_s", latencies[0], unit="s",
                 samples=latencies, shape=shape),
        perf_row("loadgen", "compiles_per_query",
                 compiles / args.queries, unit="count", shape=shape),
    ]
    append_rows(args.out, rows)
    if not args.quiet:
        mid = latencies[len(latencies) // 2]
        print(f"[loadgen] {args.queries} queries x {args.concurrency} "
              f"threads in {wall:.2f}s wall: p50~{mid:.2f}s "
              f"min {latencies[0]:.2f}s max {latencies[-1]:.2f}s, "
              f"{compiles} timed-phase compile(s)")
        print(f"[loadgen] appended 2 rows to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
