"""Load generator for the metrics/SLO plane: N concurrent quick-shape sweep
queries through the packed path, appending per-query latency rows to the
perf ledger.

Each "query" is what the future serve daemon will answer: a small
selfish-threshold grid (the ci.sh packed-leg shape) dispatched through
``run_sweep(..., packed=True)`` against a SHARED engine cache. One untimed
warmup query compiles the engines; the timed queries then run concurrently
across ``--concurrency`` worker threads, so the recorded latencies include
real dispatch contention — the number the p50/p99 SLO gate must hold.

Two perf-ledger rows land per invocation (tpusim.perf schema, scenario
``loadgen``):

  query_latency_s    value = fastest query, samples = every query's
                     wall-clock seconds (the metrics plane folds these into
                     the tpusim_query_latency_seconds histogram)
  compiles_per_query value = backend compiles observed during the TIMED
                     phase / queries — the warmed path must not compile, so
                     the default SLO pins this == 0

Usage:
    JAX_PLATFORMS=cpu python scripts/loadgen.py --queries 4 --concurrency 2 \
        --out artifacts/perf/loadgen.jsonl
    python -m tpusim slo check artifacts/perf/
"""

from __future__ import annotations

import argparse
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))  # runnable as `python scripts/loadgen.py`


def query_points(seed: int, rng: str = "threefry"):
    """One query's grid: the ci.sh packed-leg quick shape (2 intervals x
    1 selfish pct, 8 runs x 1 day, batch 8) — small enough to answer in
    seconds on CPU, shaped exactly like the real sweep path."""
    from tpusim.config import NetworkConfig, SimConfig
    from tpusim.sweep import _selfish_network

    pts = []
    for interval_s in (300.0, 600.0):
        net = _selfish_network(30)
        net = NetworkConfig(miners=net.miners, block_interval_s=interval_s)
        pts.append((
            f"q{seed}-i{int(interval_s)}",
            SimConfig(network=net, runs=8, duration_ms=86_400_000,
                      batch_size=8, seed=seed, rng=rng),
        ))
    return pts


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--queries", type=int, default=4, metavar="N",
                    help="timed queries to dispatch (default 4)")
    ap.add_argument("--concurrency", type=int, default=2, metavar="C",
                    help="concurrent query threads (default 2)")
    ap.add_argument("--out", type=Path,
                    default=REPO / "artifacts" / "perf" / "loadgen.jsonl",
                    help="perf ledger to append the two loadgen rows to")
    ap.add_argument("--seed", type=int, default=0,
                    help="base seed; query i runs with seed+1+i")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    if args.queries < 1 or args.concurrency < 1:
        ap.error("--queries and --concurrency must be >= 1")

    from tpusim.perf import append_rows, perf_row
    from tpusim.sweep import run_sweep
    from tpusim.testing import subscribe_backend_compiles

    cache: dict = {}

    def run_query(seed: int) -> float:
        t0 = time.perf_counter()
        run_sweep(query_points(seed), quiet=True, engine_cache=cache,
                  packed=True)
        return time.perf_counter() - t0

    # Warmup: compiles land here, NOT in the timed window. Same shapes as
    # every timed query, so a compile observed later is a genuine cache
    # miss on the warmed path — the `compiles_per_query == 0` objective.
    if not args.quiet:
        print("[loadgen] warmup query (untimed, compiles expected)...")
    run_query(args.seed)

    compiles = 0

    def on_compile() -> None:
        nonlocal compiles
        compiles += 1

    unsubscribe = subscribe_backend_compiles(on_compile)
    try:
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=args.concurrency) as pool:
            latencies = list(pool.map(
                run_query,
                [args.seed + 1 + i for i in range(args.queries)],
            ))
        wall = time.perf_counter() - t0
    finally:
        unsubscribe()

    latencies.sort()
    shape = {"queries": args.queries, "concurrency": args.concurrency}
    rows = [
        perf_row("loadgen", "query_latency_s", latencies[0], unit="s",
                 samples=latencies, shape=shape),
        perf_row("loadgen", "compiles_per_query",
                 compiles / args.queries, unit="count", shape=shape),
    ]
    append_rows(args.out, rows)
    if not args.quiet:
        mid = latencies[len(latencies) // 2]
        print(f"[loadgen] {args.queries} queries x {args.concurrency} "
              f"threads in {wall:.2f}s wall: p50~{mid:.2f}s "
              f"min {latencies[0]:.2f}s max {latencies[-1]:.2f}s, "
              f"{compiles} timed-phase compile(s)")
        print(f"[loadgen] appended 2 rows to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
