#!/usr/bin/env bash
# Round-5 SECOND-WINDOW playbook: the steps the 03:47-03:50 window did not
# reach before the tunnel died (plus the fixed sweep/micro harnesses).
#
#   bash scripts/tpu_r5b_plan.sh [logdir]
#
# Value order (highest first, same rationale as tpu_r5_plan.sh):
#   1. bench headline    — driver-format JSON, both modes (bench.py is now
#                          wedge-proof: thread watchdog + partial emission)
#   2. refscale default1s — float64-finalize share-diff evidence on TPU
#   3. full-scale grid   — selfish-hashrate configs[2] 2 points at 2^20 runs,
#                          checkpointed (resumable across windows)
#   4. full-scale grid   — propagation configs[0] 2 points
#   5. mosaic micro      — flattening decision, now with the iter-scaling
#                          self-check (first capture was floor-limited)
#   6. exact sweep       — re-run incl. the fixed t384/step128 points;
#                          guard-off t512 points run last (helper-crash risk)
#   7. kernel traces     — XLA device traces of a short run per mode
#                          (op-level attribution; 2 x <=900 s budget)
set -u
LOG="${1:-artifacts/r5b_tpu_logs}"
cd "$(dirname "$0")/.."
mkdir -p "$LOG"

run_step() {
  local name="$1"; shift
  echo "=== [$(date -u +%H:%M:%S)] $name: $*" | tee -a "$LOG/plan.log"
  if "$@" >"$LOG/$name.out" 2>"$LOG/$name.err"; then
    echo "=== $name OK" | tee -a "$LOG/plan.log"
  else
    echo "=== $name FAILED rc=$? (continuing)" | tee -a "$LOG/plan.log"
  fi
}

run_step bench       python bench.py --target-seconds 30 --exact-target-seconds 20 \
                       --probe-retries 1 --hard-timeout 900
run_step refscale    timeout -k 10 1200 python scripts/refscale.py --backend tpu --config default1s
run_step gridpoint   timeout -k 10 3600 python -m tpusim.sweep selfish-hashrate --runs-scale 1.0 \
                       --max-points 2 \
                       --out artifacts/sweep_selfish_hashrate_full_r5.jsonl \
                       --checkpoint-dir artifacts/ck_sh_full --quiet
run_step gridfast    timeout -k 10 3600 python -m tpusim.sweep propagation --runs-scale 1.0 \
                       --max-points 2 \
                       --out artifacts/sweep_propagation_full_r5.jsonl \
                       --checkpoint-dir artifacts/ck_prop_full --quiet
run_step micro       timeout -k 10 1200 python scripts/mosaic_micro.py --iters 4096
run_step exactsweep  timeout -k 10 2400 python scripts/tpu_exact_sweep.py --runs 2048 --n-chunks 12
# Op-level attribution of the post-split-slot kernels: XLA device traces of
# a short run in each mode (chrome-trace JSON inside, parseable offline).
run_step tracefast   timeout -k 10 900 python -m tpusim --runs 8192 --days 30 \
                       --batch-size 8192 --propagation-ms 1000 \
                       --trace-dir artifacts/trace_fast_r5
run_step traceexact  timeout -k 10 900 python -m tpusim --runs 2048 --days 30 \
                       --batch-size 2048 --propagation-ms 1000 \
                       --selfish 0 --hashrates 40,19,12,11,8,5,3,1,1 \
                       --trace-dir artifacts/trace_exact_r5
echo "=== plan complete; see $LOG" | tee -a "$LOG/plan.log"
