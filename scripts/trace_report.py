"""Offline op-level attribution from an XLA/XProf device trace.

`python -m tpusim ... --trace-dir artifacts/trace_fast_r5` (run by
scripts/tpu_r5b_plan.sh on hardware) writes a TensorBoard profile directory;
this script needs no TensorBoard: it reads the chrome-trace JSON
(`*.trace.json.gz`) inside, keeps the device-side tracks, and prints total
time per op name — the post-split-slot step attribution that decides where
the next kernel lever goes (BASELINE.md round-5 notes).

    python scripts/trace_report.py artifacts/trace_fast_r5 [--top 25]

Works on any trace dir produced by jax.profiler.trace / tpusim --trace-dir.
Note: attribution is meaningful on DEVICE tracks (flat, non-overlapping op
spans); host Python tracks nest caller inside callee, so their sums
overcount — the tool prefers device tracks automatically when present.
"""

from __future__ import annotations

import argparse
import gzip
import json
import sys
from collections import defaultdict
from pathlib import Path


def find_trace_files(root: Path) -> list[Path]:
    return sorted(root.rglob("*.trace.json.gz")) + sorted(root.rglob("*.trace.json"))


def load_events(path: Path) -> list[dict]:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rt") as f:
        data = json.load(f)
    return data.get("traceEvents", data if isinstance(data, list) else [])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir", type=Path)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--track-filter", default="",
                    help="only sum events whose process/track name contains "
                         "this substring (default: prefer TPU/TensorCore "
                         "tracks when present, else everything)")
    args = ap.parse_args()

    files = find_trace_files(args.trace_dir)
    if not files:
        print(f"no *.trace.json(.gz) under {args.trace_dir}", file=sys.stderr)
        return 1

    for path in files:
        events = load_events(path)
        # Map pid/tid to track names from metadata events.
        proc_names: dict[int, str] = {}
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                proc_names[ev.get("pid")] = ev.get("args", {}).get("name", "")

        def track(ev: dict) -> str:
            return proc_names.get(ev.get("pid"), "")

        device_markers = ("TPU", "TensorCore", "Device", "/device:")
        has_device = any(
            any(m in name for m in device_markers) for name in proc_names.values()
        )
        wanted = args.track_filter or None

        totals: dict[tuple[str, str], float] = defaultdict(float)
        counts: dict[tuple[str, str], int] = defaultdict(int)
        for ev in events:
            if ev.get("ph") != "X":  # complete events carry durations
                continue
            name = track(ev)
            if wanted is not None:
                if wanted not in name:
                    continue
            elif has_device and not any(m in name for m in device_markers):
                continue
            key = (name, ev.get("name", "?"))
            totals[key] += float(ev.get("dur", 0.0))
            counts[key] += 1

        grand = sum(totals.values())
        print(f"\n== {path.relative_to(args.trace_dir)}  "
              f"({len(events)} events, {grand / 1e3:.3f} ms summed on "
              f"{'filtered' if wanted else ('device' if has_device else 'all')} tracks)")
        for (name, op), us in sorted(totals.items(), key=lambda kv: -kv[1])[: args.top]:
            pct = 100.0 * us / grand if grand else 0.0
            print(f"  {us / 1e3:10.3f} ms  {pct:5.1f}%  x{counts[(name, op)]:<6d} "
                  f"{op}  [{name}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
