"""Thin shim: the XLA trace-dir op attribution moved into ``tpusim.report``
(the ``tpusim report`` subcommand renders both telemetry JSONL ledgers and
trace directories). Kept so committed plan scripts and docs that call
``python scripts/trace_report.py <dir>`` keep working.

    python -m tpusim report artifacts/trace_fast_r5 [--top 25]
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tpusim.report import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
