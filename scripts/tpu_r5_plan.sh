#!/usr/bin/env bash
# Round-5 hardware measurement playbook. Run the moment the tunnel is up:
#
#   bash scripts/tpu_r5_plan.sh [logdir]
#
# Ordered so the highest-value measurements land first if the tunnel dies
# mid-run (it has, twice):
#   1. bench --ablate         — exact pallas-vs-scan routing data (VERDICT #1)
#   2. mosaic_micro           — the (M,M,M,R)->(729,R) flattening decision
#   3. tpu_exact_sweep        — engine x K x tile x step_block grid
#   4. bench (headline)       — driver-format JSON, both modes
#   5. refscale default1s     — float64-finalize share-diff evidence
#   6. full-scale grid point  — selfish-hashrate configs[2] at 2^20 runs,
#                               checkpointed (resumable across windows)
# Each step logs to $logdir and failures do not stop later steps.
set -u
LOG="${1:-artifacts/r5_tpu_logs}"
cd "$(dirname "$0")/.."
mkdir -p "$LOG"

run_step() {
  local name="$1"; shift
  echo "=== [$(date -u +%H:%M:%S)] $name: $*" | tee -a "$LOG/plan.log"
  if "$@" >"$LOG/$name.out" 2>"$LOG/$name.err"; then
    echo "=== $name OK" | tee -a "$LOG/plan.log"
  else
    echo "=== $name FAILED rc=$? (continuing)" | tee -a "$LOG/plan.log"
  fi
}

run_step ablate      python bench.py --ablate 12 --skip-smoke --probe-retries 1 \
                       --hard-timeout 1200
run_step micro       python scripts/mosaic_micro.py --iters 512
run_step exactsweep  python scripts/tpu_exact_sweep.py --runs 2048 --n-chunks 12
run_step bench       python bench.py --target-seconds 30 --exact-target-seconds 20 \
                       --probe-retries 1
run_step refscale    python scripts/refscale.py --backend tpu --config default1s
run_step gridfast    python -m tpusim.sweep propagation --runs-scale 1.0 \
                       --max-points 2 \
                       --out artifacts/sweep_propagation_full_r5.jsonl \
                       --checkpoint-dir artifacts/ck_prop_full --quiet
run_step gridpoint   python -m tpusim.sweep selfish-hashrate --runs-scale 1.0 \
                       --max-points 2 \
                       --out artifacts/sweep_selfish_hashrate_full_r5.jsonl \
                       --checkpoint-dir artifacts/ck_sh_full --quiet
echo "=== plan complete; see $LOG" | tee -a "$LOG/plan.log"
