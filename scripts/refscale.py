"""Reference-scale reproduction driver: run the reference's README configs at
the reference's own scale (32 768 runs x 365.2425 d, main.cpp:7-10) on a chosen
backend and write a JSON artifact per (backend, config) into artifacts/.

The committed artifacts are compared by scripts/refscale_report.py against the
reference README tables (README.md:51-107) and against each other
(TPU engine vs native C++ oracle) under the BASELINE.json +-1e-4 stale-rate
criterion — the first full-scale statistical cross-validation of the
framework.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))  # runnable as `python scripts/refscale.py`


def build_config(name: str, runs: int):
    from tpusim import SimConfig, default_network
    from tpusim.config import DEFAULT_DURATION_MS, MinerConfig, NetworkConfig

    if name == "prop10s":
        net = default_network(propagation_ms=10_000)
    elif name == "prop100ms":
        net = default_network(propagation_ms=100)
    elif name == "default1s":
        net = default_network(propagation_ms=1000)
    elif name == "selfish40":
        # README.md:89-107: miner 0 at 40%, gamma=0 selfish, everyone 1 s.
        pcts = (40, 19, 12, 11, 8, 5, 3, 1, 1)
        net = NetworkConfig(
            miners=tuple(
                MinerConfig(hashrate_pct=p, propagation_ms=1000, selfish=(i == 0))
                for i, p in enumerate(pcts)
            )
        )
    else:
        raise SystemExit(f"unknown config {name!r}")
    return SimConfig(
        network=net,
        duration_ms=DEFAULT_DURATION_MS,
        runs=runs,
        batch_size=8192,
        seed=20260729,
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", choices=["tpu", "native"], required=True)
    ap.add_argument(
        "--config", choices=["prop10s", "prop100ms", "default1s", "selfish40"],
        required=True,
    )
    ap.add_argument("--runs", type=int, default=32768)
    ap.add_argument("--out-dir", default=str(REPO / "artifacts"))
    args = ap.parse_args()

    config = build_config(args.config, args.runs)
    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    out_path = out_dir / f"refscale_{args.config}_{args.backend}.json"

    t0 = time.monotonic()
    if args.backend == "native":
        from tpusim.backend.cpp import run_simulation_cpp

        res = run_simulation_cpp(config, threads=1)
        platform = "cpu-native"
    else:
        import jax
        from tpusim.runner import run_simulation_config

        platform = jax.devices()[0].platform
        ck = out_dir / f"refscale_{args.config}_tpu.ck.npz"
        res = run_simulation_config(
            config, use_all_devices=False, checkpoint_path=ck,
            progress=lambda done, total: print(f"  {done}/{total}", flush=True),
        )
        ck.unlink(missing_ok=True)
    wall_s = time.monotonic() - t0

    payload = {
        "config": args.config,
        "backend": args.backend,
        "platform": platform,
        "runs": res.runs,
        "duration_ms": config.duration_ms,
        "mode": res.mode,
        "seed": config.seed,
        "wall_s": round(wall_s, 2),
        "elapsed_s": round(res.elapsed_s, 2) if res.elapsed_s else None,
        "sim_years_per_s": round(
            res.runs * config.duration_ms / (365.2425 * 86_400_000.0) / wall_s, 1
        ),
        "miners": [
            {
                "hashrate_pct": mc.hashrate_pct,
                "selfish": mc.selfish,
                "blocks_found_mean": ms.blocks_found_mean,
                "blocks_share_mean": ms.blocks_share_mean,
                "stale_rate_mean": ms.stale_rate_mean,
                "stale_blocks_mean": ms.stale_blocks_mean,
            }
            for mc, ms in zip(config.network.miners, res.miners)
        ],
    }
    out_path.write_text(json.dumps(payload, indent=2) + "\n")
    print(json.dumps({"written": str(out_path), "wall_s": payload["wall_s"],
                      "sim_years_per_s": payload["sim_years_per_s"]}))


if __name__ == "__main__":
    main()
