#!/usr/bin/env bash
# Round-5 THIRD-WINDOW playbook: extend the full-scale grids beyond
# tpu_r5b_plan.sh's first points, while the tunnel holds.
#
#   bash scripts/tpu_r5c_plan.sh [logdir]
#
# Every sweep below resumes from its per-point checkpoints, so re-running
# after a tunnel death continues at point granularity. Value order:
#   1. selfish-hashrate remaining points (exact mode, ~12 min/point at
#      ~1.4k sim-years/s) — the grid the profitability-crossing evidence
#      lives in; --max-points raised stepwise so each completed point is
#      flushed to the JSONL before the next starts.
#   2. propagation 10 s / 60 s points (exact mode).
#   3. hetero32 at 2^20 (quarter of the BASELINE 2^22 target; 32-miner
#      exact off-kernel config — measures the scan engine at scale).
set -u
LOG="${1:-artifacts/r5c_tpu_logs}"
cd "$(dirname "$0")/.."
mkdir -p "$LOG"

run_step() {
  local name="$1"; shift
  echo "=== [$(date -u +%H:%M:%S)] $name: $*" | tee -a "$LOG/plan.log"
  if "$@" >"$LOG/$name.out" 2>"$LOG/$name.err"; then
    echo "=== $name OK" | tee -a "$LOG/plan.log"
  else
    echo "=== $name FAILED rc=$? (continuing)" | tee -a "$LOG/plan.log"
  fi
}

# --resume skips rows already in the JSONL, so each pass fills exactly the
# missing points (incl. any point r5b's steps left half-done in checkpoints);
# the stepped --max-points keeps a per-step timeout bound on one point's work
# while earlier completed points cost only a file read.
for n in 2 3 4 5 6 7 8 9; do
  run_step "selfish_p$n" timeout -k 10 2400 python -m tpusim.sweep selfish-hashrate \
    --runs-scale 1.0 --max-points "$n" --resume \
    --out artifacts/sweep_selfish_hashrate_full_r5.jsonl \
    --checkpoint-dir artifacts/ck_sh_full --quiet
done
for n in 2 3 4; do
  run_step "prop_p$n" timeout -k 10 2400 python -m tpusim.sweep propagation \
    --runs-scale 1.0 --max-points "$n" --resume \
    --out artifacts/sweep_propagation_full_r5.jsonl \
    --checkpoint-dir artifacts/ck_prop_full --quiet
done
run_step hetero32 timeout -k 10 7200 python -m tpusim.sweep hetero32 \
  --runs-scale 0.25 --resume \
  --out artifacts/sweep_hetero32_2e20_r5.jsonl \
  --checkpoint-dir artifacts/ck_h32 --quiet
echo "=== plan complete; see $LOG" | tee -a "$LOG/plan.log"
