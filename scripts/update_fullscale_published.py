"""Regenerate BASELINE.json's ``published.full_scale_grids`` from artifacts.

Reads every committed full-production-scale artifact (2^20-run grid points)
and rewrites the summary block in place, so the published evidence can never
drift from the artifact files it cites:

  * artifacts/sweep_selfish_hashrate_full_native.jsonl — one row per native
    selfish-hashrate point (rows carry no name; identified by miner 0's
    hashrate), plus, when present,
  * artifacts/sweep_selfish_hashrate_full_r5.jsonl — TPU-engine points,
  * artifacts/prop1s_full_2e20.json — the TPU propagation point,
  * artifacts/sweep_propagation_full_r5.jsonl — further TPU prop points.

Run after any new full-scale point lands:  python scripts/update_fullscale_published.py
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def selfish_points(path: Path, backend: str) -> dict[str, dict]:
    pts: dict[str, dict] = {}
    if not path.exists():
        return pts
    for line in path.read_text().splitlines():
        if not line.strip():
            continue
        r = json.loads(line)
        m0 = r["miners"][0]
        if not m0.get("selfish"):
            continue
        name = f"selfish-{m0['hashrate_pct']}pct"
        if name in pts and pts[name]["runs"] >= r["runs"]:
            # The file can legitimately hold the same point at several
            # scales (--resume re-measures on a runs_scale change); only the
            # highest-run row is publication evidence.
            continue
        pts[name] = {
            "runs": r["runs"],
            "backend": backend,
            "elapsed_s": round(r["elapsed_s"], 1),
            "selfish_share": round(m0["blocks_share_mean"], 5),
            "_share_raw": m0["blocks_share_mean"],
            "_chain_blocks": r.get("best_height_mean"),
            "selfish_hashrate_frac": m0["hashrate_pct"] / 100.0,
            "profitable": m0["blocks_share_mean"] > m0["hashrate_pct"] / 100.0,
        }
    return pts


def crossing_bracket(pts: dict[str, dict]) -> str:
    below = [p["selfish_hashrate_frac"] for p in pts.values() if not p["profitable"]]
    above = [p["selfish_hashrate_frac"] for p in pts.values() if p["profitable"]]
    if not below or not above:
        return "unbracketed"
    lo, hi = max(below), min(above)
    return f"({lo * 100:.0f}%, {hi * 100:.0f}%)"


def main() -> int:
    base_path = REPO / "BASELINE.json"
    d = json.loads(base_path.read_text())

    pts = selfish_points(
        REPO / "artifacts" / "sweep_selfish_hashrate_full_native.jsonl", "cpp"
    )
    tpu_pts = selfish_points(
        REPO / "artifacts" / "sweep_selfish_hashrate_full_r5.jsonl", "tpu"
    )
    for name, tpu in tpu_pts.items():
        prior = pts.get(name)
        if prior is not None and prior["runs"] > tpu["runs"]:
            # Never let a reduced-scale TPU row evict higher-run evidence
            # (the crossing bracket's stated 2^20-run precision depends on it).
            continue
        if prior is not None and prior["runs"] == tpu["runs"]:
            # Same point at the same full scale on both backends: publish the
            # TPU row annotated with the independent native share — two
            # 2^20-run estimates agreeing is the cross-validation story. The
            # diff comes from the unrounded means so its last digit is real,
            # and it is scored against the Monte-Carlo envelope of two
            # independent estimates: per-run share variance ≈ s(1-s)/chain,
            # where chain is the run's actual main-chain length (the
            # artifact's best_height_mean — materially below the ideal
            # 600 s-interval count under selfish staling), σ_mean =
            # σ_run/√runs, σ_diff = √2·σ_mean.
            s = tpu["_share_raw"]
            blocks_per_run = (
                tpu.get("_chain_blocks")
                or prior.get("_chain_blocks")
                or 365.2425 * 86400 / 600.0
            )
            diff = abs(s - prior["_share_raw"])
            tpu["selfish_share_native"] = prior["selfish_share"]
            tpu["share_abs_diff_vs_native"] = round(diff, 7)
            # A degenerate row (share exactly 0 or 1, or a zero chain
            # length) has no defined Monte-Carlo envelope; publish a null
            # sigma annotation instead of aborting the whole pass on a
            # division by zero.
            if s * (1 - s) > 0 and blocks_per_run > 0:
                sigma_diff = (
                    (2 * s * (1 - s) / blocks_per_run) ** 0.5 / tpu["runs"] ** 0.5
                )
                tpu["share_diff_in_sigma_units"] = round(diff / sigma_diff, 2)
            else:
                tpu["share_diff_in_sigma_units"] = None
            tpu["native_elapsed_s"] = prior["elapsed_s"]
        pts[name] = tpu
    for p in pts.values():
        p.pop("_share_raw", None)
        p.pop("_chain_blocks", None)
    bracket = crossing_bracket(pts)

    grids: dict = {
        "note": (
            "BASELINE configs[1]/configs[2] grid points at FULL production scale "
            "(2^20 year-long runs per point), regenerated from the committed "
            "artifacts by scripts/update_fullscale_published.py. The gamma=0 "
            f"selfish profitability crossing is bracketed inside {bracket} "
            "hashrate at 2^20-run precision (theory point: 1/3)."
        ),
        "selfish_hashrate": dict(sorted(pts.items())),
    }

    prop_path = REPO / "artifacts" / "prop1s_full_2e20.json"
    if prop_path.exists():
        prop = json.loads(prop_path.read_text())
        grids["prop1s_tpu"] = {
            "runs": prop["runs"],
            "elapsed_s": round(prop["elapsed_s"], 1),
            "sim_years_per_s_sustained": round(prop["runs"] / prop["elapsed_s"], 1),
            "miner0_stale_rate": round(prop["miners"][0]["stale_rate_mean"], 6),
        }
    prop_sweep = REPO / "artifacts" / "sweep_propagation_full_r5.jsonl"
    if prop_sweep.exists():
        prop_pts = {}
        for line in prop_sweep.read_text().splitlines():
            if not line.strip():
                continue
            r = json.loads(line)
            # run_sweep rows carry their grid-point name since round 5;
            # fall back to an index for older writers.
            key = f"{r.get('point', f'prop-point-{len(prop_pts)}')}-tpu"
            prop_pts[key] = {
                "runs": r["runs"],
                "elapsed_s": round(r["elapsed_s"], 1),
                "miner0_stale_rate": round(r["miners"][0]["stale_rate_mean"], 6),
            }
        if prop_pts:
            grids["propagation_tpu"] = prop_pts

    d["published"]["full_scale_grids"] = grids
    base_path.write_text(json.dumps(d, indent=1) + "\n")
    print(f"selfish points: {sorted(pts)}; crossing {bracket}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
