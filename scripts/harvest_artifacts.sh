#!/usr/bin/env bash
# After any TPU window (tpu_r5*_plan.sh run), fold the new artifacts into
# the published evidence in one deterministic pass:
#   * BASELINE.json published.configs        <- refscale_report.py
#   * BASELINE.json published.full_scale_grids <- update_fullscale_published.py
#   * REFSCALE.md                            <- refscale_report.py
#   * artifacts/plots/selfish_crossing.png   <- tpusim.analysis --selfish-grid
# Everything re-derives from committed artifact files, so running this twice
# is a no-op. Review `git diff` and commit afterwards.
set -eu
cd "$(dirname "$0")/.."
python scripts/update_fullscale_published.py
python scripts/refscale_report.py
grids=(artifacts/sweep_selfish_hashrate_full_native.jsonl
       artifacts/sweep_selfish_hashrate_full_r5.jsonl
       artifacts/sweep_selfish_hashrate_scale0.015625.jsonl)
existing=()
for g in "${grids[@]}"; do [ -f "$g" ] && existing+=("$g"); done
if [ "${#existing[@]}" -gt 0 ]; then
  # --only-selfish-grid: the committed stale_rates.png carries a --simulate
  # overlay this script must not silently strip.
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m tpusim.analysis --out-dir artifacts/plots --only-selfish-grid \
    --selfish-grid "${existing[@]}"
fi
git status --short BASELINE.json REFSCALE.md artifacts/
