#!/usr/bin/env bash
# After any TPU window (tpu_r5*_plan.sh run), fold the new artifacts into
# the published evidence in one deterministic pass:
#   * BASELINE.json published.configs        <- refscale_report.py
#   * BASELINE.json published.full_scale_grids <- update_fullscale_published.py
#   * REFSCALE.md                            <- refscale_report.py
#   * artifacts/plots/selfish_crossing.png   <- tpusim.analysis --selfish-grid
# Everything re-derives from committed artifact files, so running this twice
# is a no-op. Review `git diff` and commit afterwards.
set -eu
cd "$(dirname "$0")/.."
python scripts/update_fullscale_published.py
python scripts/refscale_report.py
grids=(artifacts/sweep_selfish_hashrate_full_native.jsonl
       artifacts/sweep_selfish_hashrate_full_r5.jsonl
       artifacts/sweep_selfish_hashrate_scale0.015625.jsonl)
existing=()
for g in "${grids[@]}"; do [ -f "$g" ] && existing+=("$g"); done
# --only-selfish-grid suppresses the propagation figures (the committed
# stale_rates.png carries a --simulate overlay this script must not
# silently strip); the crossing and hetero-validation figures regenerate
# independently, each from whichever of its inputs exist. The hetero one
# prefers the full-scale TPU artifact once a window produces it.
selfish=()
[ "${#existing[@]}" -gt 0 ] && selfish=(--selfish-grid "${existing[@]}")
hetero=()
for h in artifacts/sweep_hetero32_2e20_r5.jsonl \
         artifacts/sweep_hetero32_cpp_scale0.0039.jsonl; do
  [ -f "$h" ] && { hetero=(--hetero-grid "$h"); break; }
done
if [ "${#selfish[@]}" -gt 0 ] || [ "${#hetero[@]}" -gt 0 ]; then
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m tpusim.analysis --out-dir artifacts/plots --only-selfish-grid \
    "${selfish[@]}" "${hetero[@]}"
fi
# Telemetry ledgers (--telemetry runs on hardware write here, or into /tmp on
# the TPU host — tpu_watch.sh rsyncs them back): refresh the committed sample
# dashboard from the newest ledger so the evidence trail stays renderable.
mkdir -p artifacts/telemetry
newest=$(ls -t artifacts/telemetry/*.jsonl 2>/dev/null | head -1 || true)
if [ -n "$newest" ]; then
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m tpusim report "$newest" --format md \
    --out artifacts/telemetry/sample_report.md > /dev/null
fi
# Orchestration timeline (tpusim.tracing): re-derive the committed sample
# timeline + Perfetto trace from the committed sample fleet ledgers (a tiny
# worker-kill drill's supervisor + worker telemetry under sample_fleet/), so
# the evidence artifacts always match the current merger/exporter. Hardware
# fleet runs rsync their STATE_DIRs next to it; every *.trace.json written
# here is schema-validated by the block below. Jax-free.
if [ -d artifacts/telemetry/sample_fleet ]; then
  python -m tpusim trace timeline artifacts/telemetry/sample_fleet \
    --format md --out artifacts/telemetry/sample.orchestration.trace.json \
    > artifacts/telemetry/sample_timeline.md
fi
# Flight-recorder traces (`tpusim trace --trace-out` exports from hardware
# windows land next to the ledgers): schema-validate whatever is collected so
# a corrupt export can't sit silently in the evidence trail.
traces=$(ls artifacts/telemetry/*.trace.json 2>/dev/null || true)
if [ -n "$traces" ]; then
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python - $traces <<'EOF'
import json, sys
from tpusim.flight_export import validate_perfetto
for path in sys.argv[1:]:
    print(f"[harvest] {path}: {validate_perfetto(json.load(open(path)))} events")
EOF
fi
# Perf regression ledgers (`tpusim perf run` / bench.py append here; TPU
# windows rsync theirs back next to the telemetry ledgers): schema-validate
# every collected row so a malformed producer can't silently poison the
# baseline the CI noise gate compares against. Strict by design — a bad row
# fails the harvest, exactly like a corrupt trace. jax-free (tpusim.perf
# imports no backend for loading/validation).
perf_ledgers=$(ls artifacts/perf/*.jsonl 2>/dev/null || true)
if [ -n "$perf_ledgers" ]; then
  python - $perf_ledgers <<'EOF'
import sys
from tpusim.perf import load_rows
for path in sys.argv[1:]:
    print(f"[harvest] {path}: {len(load_rows(path))} perf rows OK")
EOF
fi
# OpenMetrics expositions (`tpusim metrics export --out` from CI legs or
# hardware windows land under artifacts/metrics/): re-derive a sample
# exposition from the committed sample fleet ledgers so the evidence stays
# scrapeable, then strictly validate EVERY collected *.prom file (declared
# families, _total counters, cumulative buckets, +Inf == _count, terminal
# # EOF) — a malformed exposition fails the harvest, exactly like a corrupt
# trace or perf row. jax-free (tpusim.metrics imports no backend).
mkdir -p artifacts/metrics
if [ -d artifacts/telemetry/sample_fleet ]; then
  python -m tpusim metrics export artifacts/telemetry/sample_fleet \
    --out artifacts/metrics/sample_fleet.prom > /dev/null
fi
expositions=$(ls artifacts/metrics/*.prom 2>/dev/null || true)
if [ -n "$expositions" ]; then
  python - $expositions <<'EOF'
import sys
from tpusim.metrics import validate_openmetrics
for path in sys.argv[1:]:
    print(f"[harvest] {path}: {validate_openmetrics(open(path).read())} samples OK")
EOF
fi
# Lineage ledgers (armed runs — TPUSIM_PROVENANCE — append content-addressed
# records here; TPU windows rsync their provenance/ dirs back next to the
# telemetry they attest): strictly re-verify every record hash so a mutated
# or torn ledger fails the harvest, exactly like a corrupt trace or perf row.
# Strict load refuses tampered records outright — the audit CLI (`tpusim
# audit artifacts/`) is the richer cross-plane gate; this is the cheap
# integrity floor every harvest pays. jax-free (tpusim.provenance imports no
# backend).
lineage_ledgers=$(find artifacts -name "lineage.jsonl" 2>/dev/null || true)
if [ -n "$lineage_ledgers" ]; then
  python - $lineage_ledgers <<'EOF'
import sys
from tpusim.provenance import load_lineage
for path in sys.argv[1:]:
    print(f"[harvest] {path}: {len(load_lineage(path, strict=True))} "
          "lineage records OK")
EOF
fi
git status --short BASELINE.json REFSCALE.md artifacts/
