#!/usr/bin/env bash
# Wait for the tunneled TPU to come back (killable subprocess probes every
# 5 min, tpusim.probe), then run the queued TPU jobs sequentially. Used when
# the tunnel wedges mid-session; safe to re-run — sweep points resume from
# their per-point checkpoints. Re-probes before every job (the tunnel can
# wedge again between jobs — launching in-process against a dead backend is
# the unkillable hang tpusim/probe.py documents), stops the queue on the
# first failed job, and exits nonzero so wrappers chaining on it see it.
set -u
cd "$(dirname "$0")/.."

wait_for_tpu() {
  until python - <<'EOF'
import sys
from tpusim.probe import probe_backend
sys.exit(0 if probe_backend(timeout_s=120, retries=1) == "tpu" else 1)
EOF
  do
    echo "[queue] TPU unavailable; retrying in 300s"
    sleep 300
  done
}

run_job() {
  echo "[queue] waiting for TPU backend..."
  wait_for_tpu
  echo "[queue] running: $*"
  "$@"
  local rc=$?
  if [ $rc -ne 0 ]; then
    echo "[queue] FAILED (rc=$rc): $*" >&2
    exit "$rc"
  fi
}

run_job python -m tpusim.sweep hetero32 --runs-scale 0.00390625 \
  --out artifacts/sweep_hetero32_scale0.0039.jsonl \
  --checkpoint-dir artifacts/ck_h32b --quiet
run_job python -m tpusim.sweep selfish-threshold --runs-scale 0.0002 \
  --out artifacts/sweep_selfish_threshold_scale2e-4.jsonl \
  --checkpoint-dir artifacts/ck_thr --quiet
run_job bash -c 'python bench.py --target-seconds 30 > /tmp/bench_requeue.json 2>/tmp/bench_requeue.log'
echo "[queue] done"
