#!/usr/bin/env bash
# Wait for the tunneled TPU to come back (killable subprocess probes every
# 5 min, tpusim.probe), then run the queued TPU jobs sequentially. Used when
# the tunnel wedges mid-session; safe to re-run — sweep points resume from
# their per-point checkpoints.
set -u
cd "$(dirname "$0")/.."

echo "[queue] waiting for TPU backend..."
until python - <<'EOF'
import sys
from tpusim.probe import probe_backend
sys.exit(0 if probe_backend(timeout_s=120, retries=1) == "tpu" else 1)
EOF
do
  echo "[queue] TPU still unavailable; retrying in 300s"
  sleep 300
done
echo "[queue] TPU is back; running queued jobs"

python -m tpusim.sweep hetero32 --runs-scale 0.00390625 \
  --out artifacts/sweep_hetero32_scale0.0039.jsonl \
  --checkpoint-dir artifacts/ck_h32b --quiet
python -m tpusim.sweep selfish-threshold --runs-scale 0.0002 \
  --out artifacts/sweep_selfish_threshold_scale2e-4.jsonl \
  --checkpoint-dir artifacts/ck_thr --quiet
python bench.py --target-seconds 30 > /tmp/bench_requeue.json 2>/tmp/bench_requeue.log
echo "[queue] done"
