"""Mosaic layout micro-benchmarks for the exact-mode flattening decision.

The exact kernel's dominant ops act on (M, M, M, R) / (M, M, R) arrays whose
minor (M, R) = (9, 256) tiles idle 7 of 16 padded sublanes (56 % dense).
Flattening the leading dims to rows — (729, R) / (81, R), 92-99 % dense —
would reclaim that, IF Mosaic can cheaply (a) reshape between the forms or
(b) expand (M, R) masks/values to flat rows. Nobody knows the relayout cost
without running it; this script measures exactly that, per op, on hardware:

  1. sel3     — status-quo 3-level where on (9,9,9,R), (M,M,R)-broadcast conds
  2. sel_flat — same select count on (729,R) with PRE-BUILT flat masks
                (upper bound on the flattening gain)
  3. reshape  — (9,9,R) <-> (81,R) round-trip through jnp.reshape in-kernel
  4. repeat   — (9,R) -> (81,R) block-repeat (rows i*9+j <- src row i)
  5. tile     — (9,R) -> (81,R) tile (rows i*9+j <- src row j)
  6. segsum   — (81,R) -> (9,R) 9-row segmented sum via reshape+sum
  7. contract — status-quo cpb extraction: sum over leading axis of
                (9,9,9,R) * (9,1,1,R)

Each variant runs ``--iters`` iterations inside ONE pallas_call fori_loop
(the chained discipline; dispatch amortized), min of 3 repeats. A variant
that fails to lower prints LOWER-FAIL with the Mosaic error — that is a
result, not a bug. Appends rows to artifacts/mosaic_micro_r5.jsonl.

Decision rule (BASELINE/VERDICT round-5 plan): flatten only if
sel_flat + needed expansions/reshapes beats sel3 by enough to matter —
otherwise record the measured write-up and stop.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--r", type=int, default=256, help="lanes (exact-mode tile width)")
    ap.add_argument("--iters", type=int, default=512, help="op iterations per kernel call")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="run in interpret mode off-TPU (timing meaningless; "
                         "checks the harness itself)")
    ap.add_argument("--out", type=Path,
                    default=Path(__file__).resolve().parent.parent
                    / "artifacts" / "mosaic_micro_r5.jsonl")
    args = ap.parse_args()

    if args.allow_cpu:
        # Probe the tunnel first and only force CPU when it is unreachable:
        # a defensive --allow-cpu during a tunnel-up window must still
        # measure on the real chip. When forcing is needed, env vars alone
        # are too late (sitecustomize registered the axon plugin at
        # interpreter startup); probe_or_force_cpu's jax.config forcing is
        # what actually works — the env-only variant hangs when the tunnel
        # is down (observed this round).
        from tpusim.probe import probe_or_force_cpu

        probe_or_force_cpu(timeout_s=60.0, retries=1)

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dev = jax.devices()[0]
    interpret = dev.platform != "tpu"
    if interpret and not args.allow_cpu:
        print("not on TPU (pass --allow-cpu for an interpret-mode harness check)",
              file=sys.stderr)
        return 1
    print("platform:", dev, "interpret:", interpret)

    M, R, N = 9, args.r, args.iters
    I32 = jnp.int32

    def bench(name, shapes, body):
        """Time N iterations of ``body(*arrays) -> array`` chained inside one
        kernel; the iteration result feeds the next via addition so nothing
        can be dead-code-eliminated. A second timing at N/8 iterations is a
        scaling self-check: per-iteration cost is only trusted when time
        grows with the trip count (round-5 first capture measured 0.046
        us/iter on a padded 331k-element array — beyond the VPU throughput
        bound, i.e. the loop was elided or the timing floor dominated)."""
        def make_kernel(n_iters):
            def kernel(*refs):
                *ins, out = refs
                vals = [r[...] for r in ins]

                def it(i, acc):
                    r = body(*vals, acc)
                    return r

                acc = jax.lax.fori_loop(0, n_iters, it, jnp.zeros_like(out[...]))
                out[...] = acc
            return kernel

        rng = np.random.default_rng(0)
        in_shapes = shapes[:-1]  # last shape is the output/accumulator
        arrays = [jnp.asarray(rng.integers(0, 3, size=s, dtype=np.int32)) for s in in_shapes]
        out_shape = jax.ShapeDtypeStruct(shapes[-1], I32)

        def timed(n_iters):
            call = pl.pallas_call(
                make_kernel(n_iters),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM) for _ in in_shapes],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                out_shape=out_shape,
                interpret=interpret,
            )
            fn = jax.jit(lambda *a: call(*a))
            fn(*arrays).block_until_ready()  # compile
            times = []
            for _ in range(3):
                t0 = time.perf_counter()
                fn(*arrays).block_until_ready()
                times.append(time.perf_counter() - t0)
            return min(times), times

        try:
            best, times = timed(N)
            best_small, _ = timed(max(1, N // 8))
        except Exception as e:  # noqa: BLE001 — lowering failure IS the datum
            msg = str(e).splitlines()[-1][:300] if str(e) else type(e).__name__
            print(f"[{name}] LOWER-FAIL: {msg}", flush=True)
            return {"name": name, "lower_fail": msg}
        # Perfect work scaling gives ratio ~8; a ratio near 1 means the
        # dispatch/sync floor (or an elided loop) dominated both timings and
        # us_per_iter is an upper bound on the floor, not an op cost.
        ratio = best / best_small if best_small > 0 else float("inf")
        row = {"name": name, "us_per_iter": round(best / N * 1e6, 3),
               "repeats_s": [round(t, 5) for t in times],
               "scaling_ratio_8x": round(ratio, 2),
               "floor_limited": bool(ratio < 4.0)}
        flag = "  [FLOOR-LIMITED: not an op cost]" if row["floor_limited"] else ""
        print(f"[{name}] {row['us_per_iter']} us/iter "
              f"(8x-iter scaling ratio {row['scaling_ratio_8x']}){flag}", flush=True)
        return row

    # Shared operand shapes. `acc` is always the last shape (the output).
    rows = [{"date": time.strftime("%Y-%m-%d"), "chip": str(dev), "r": R, "iters": N}]

    # 1. Status-quo 3-level select on the cp tensor. conds are (M,M,1,R)
    #    broadcasts (built from (M,M,R) data), values broadcast per level.
    def sel3(cp, c1, c2, val, acc):
        x = jnp.where((c1 + acc[:1, :1, :1, :]) > 1, val[None, None, :, :],
                      jnp.where(c2 > 1, cp, acc))
        return x + cp

    rows.append(bench("sel3_status_quo",
                      [(M, M, M, R), (M, M, 1, R), (M, M, 1, R), (M, R), (M, M, M, R)],
                      sel3))

    # 2. Same select count, flat rows, pre-built flat masks (upper bound).
    def sel_flat(cp, c1, c2, val, acc):
        x = jnp.where((c1 + acc[:1, :]) > 1, val, jnp.where(c2 > 1, cp, acc))
        return x + cp

    rows.append(bench("sel_flat_prebuilt",
                      [(M * M * M, R), (M * M * M, R), (M * M * M, R),
                       (M * M * M, R), (M * M * M, R)],
                      sel_flat))

    # 3. Reshape round-trip (the open Mosaic question).
    def reshape_rt(x, acc):
        flat = jnp.reshape(x + acc, (M * M, R))
        return jnp.reshape(flat + 1, (M, M, R))

    rows.append(bench("reshape_roundtrip_9x9", [(M, M, R), (M, M, R)], reshape_rt))

    # 4./5. Mask expansions (9,R) -> (81,R).
    def repeat_rows(src, acc):
        # rows i*9+j <- src[i]: broadcast middle then collapse.
        return jnp.reshape(
            jnp.broadcast_to((src + acc[:M, :])[:, None, :], (M, M, R)), (M * M, R)
        )

    rows.append(bench("expand_repeat", [(M, R), (M * M, R)], repeat_rows))

    def tile_rows(src, acc):
        return jnp.reshape(
            jnp.broadcast_to((src + acc[:M, :])[None, :, :], (M, M, R)), (M * M, R)
        )

    rows.append(bench("expand_tile", [(M, R), (M * M, R)], tile_rows))

    # 6. Segmented 9-row sum (81,R) -> (9,R) via reshape.
    def segsum(x, acc):
        return jnp.sum(jnp.reshape(x + acc[:1, :], (M, M, R)), axis=1)

    rows.append(bench("segsum_reshape", [(M * M, R), (M, R)], segsum))

    # 7. Status-quo cpb contraction: sum over leading axis with a one-hot.
    def contract(cp, b, acc):
        return jnp.sum(cp * b, axis=0) + acc  # b is (M, 1, 1, R)

    rows.append(bench("contract_cpb", [(M, M, M, R), (M, 1, 1, R), (M, M, R)], contract))

    args.out.parent.mkdir(parents=True, exist_ok=True)
    with open(args.out, "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
