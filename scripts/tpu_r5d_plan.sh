#!/usr/bin/env bash
# Round-5 RECOVERY playbook (window 3+): everything the 11:41-12:04 window
# left unfinished, ordered so each marginal minute of tunnel uptime completes
# the most valuable remaining evidence. Fully resume-safe: every sweep pass
# uses --resume (skips rows already in its JSONL) + per-point checkpoints,
# so re-running this plan after another tunnel death continues, never
# duplicates. Steps:
#   1. selfish-28pct finish   — checkpoint is ~60% done from window 2
#   2. propagation 100ms/1s   — fast-mode full-scale points (~6 min each)
#   3. mosaic micro           — flattening decision (iter-scaling self-check)
#   4. exact sweep            — fixed t256x128/t384/step128 points
#   5. kernel traces          — op-level attribution, one per mode
#   6. selfish 31..49pct      — stepped, one point per pass
#   7. propagation 10s/60s    — exact-mode full-scale points
#   8. hetero32 at 2^20       — long scan-engine point, last
set -u
LOG="${1:-artifacts/r5d_tpu_logs}"
cd "$(dirname "$0")/.."
mkdir -p "$LOG"
# Persistent XLA compilation cache: every pass is a fresh process and the
# year-long engines take 15-40 s to compile; across the plan's ~25 steps
# this is many window-minutes. Harmless no-op if the remote backend
# bypasses it.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"

# Steps mark completion in $LOG/done.<name>; a re-run of the plan (the
# looping watcher re-launches it every tunnel-up window) skips completed
# steps instantly, so short windows accumulate instead of re-treading.
# Sweep passes additionally self-resume via --resume + checkpoints even
# when interrupted mid-step.
run_step() {
  local name="$1"; shift
  if [ -f "$LOG/done.$name" ]; then
    echo "=== $name already done; skipping" | tee -a "$LOG/plan.log"
    return 0
  fi
  echo "=== [$(date -u +%H:%M:%S)] $name: $*" | tee -a "$LOG/plan.log"
  if "$@" >"$LOG/$name.out" 2>"$LOG/$name.err"; then
    echo "=== $name OK" | tee -a "$LOG/plan.log"
    touch "$LOG/done.$name"
  else
    echo "=== $name FAILED rc=$? (continuing)" | tee -a "$LOG/plan.log"
  fi
}

sweep_pass() {  # sweep_pass <name> <timeout> <grid> <max-points> <out> <ckdir> [runs-scale]
  local name="$1" to="$2" grid="$3" n="$4" out="$5" ck="$6" scale="${7:-1.0}"
  run_step "$name" timeout -k 10 "$to" python -m tpusim.sweep "$grid" \
    --runs-scale "$scale" --max-points "$n" --resume \
    --out "$out" --checkpoint-dir "$ck" --quiet
}

SH_OUT=artifacts/sweep_selfish_hashrate_full_r5.jsonl
PR_OUT=artifacts/sweep_propagation_full_r5.jsonl

sweep_pass selfish_p2 1500 selfish-hashrate 2 "$SH_OUT" artifacts/ck_sh_full
sweep_pass prop_p1    1200 propagation      1 "$PR_OUT" artifacts/ck_prop_full
sweep_pass prop_p2    1200 propagation      2 "$PR_OUT" artifacts/ck_prop_full
# Re-prove the reference tables on-chip under the round-5 exact default
# (group_slots auto=2; the committed prop10s/prop100ms/selfish40 TPU rows
# predate the flip). ~40-60 s each incl. compile.
run_step refsc_selfish40 timeout -k 10 900 python scripts/refscale.py --backend tpu --config selfish40
run_step refsc_prop10s   timeout -k 10 900 python scripts/refscale.py --backend tpu --config prop10s
run_step refsc_prop100ms timeout -k 10 900 python scripts/refscale.py --backend tpu --config prop100ms
run_step micro      timeout -k 10 1200 python scripts/mosaic_micro.py --iters 4096
run_step exactsweep timeout -k 10 2400 python scripts/tpu_exact_sweep.py --runs 2048 --n-chunks 12
run_step tracefast  timeout -k 10 900 python -m tpusim --runs 8192 --days 30 \
                      --batch-size 8192 --propagation-ms 1000 \
                      --trace-dir artifacts/trace_fast_r5
run_step traceexact timeout -k 10 900 python -m tpusim --runs 2048 --days 30 \
                      --batch-size 2048 --propagation-ms 1000 \
                      --selfish 0 --hashrates 40,19,12,11,8,5,3,1,1 \
                      --trace-dir artifacts/trace_exact_r5
# Does a bigger per-dispatch batch close the 3313 end-to-end vs 4342
# kernel-rate gap, or is the gap tail/noise? Two cheap probes.
run_step bench16k timeout -k 10 600 python bench.py --batch-size 16384 \
                    --target-seconds 20 --exact-target-seconds 0 \
                    --probe-retries 1 --hard-timeout 500
run_step bench32k timeout -k 10 600 python bench.py --batch-size 32768 \
                    --target-seconds 20 --exact-target-seconds 0 \
                    --probe-retries 1 --hard-timeout 500
for n in 3 4 5 6 7 8 9; do
  sweep_pass "selfish_p$n" 1500 selfish-hashrate "$n" "$SH_OUT" artifacts/ck_sh_full
done
for n in 3 4; do
  sweep_pass "prop_p$n" 1500 propagation "$n" "$PR_OUT" artifacts/ck_prop_full
done
run_step hetero32 timeout -k 10 5400 python -m tpusim.sweep hetero32 \
  --runs-scale 0.25 --resume \
  --out artifacts/sweep_hetero32_2e20_r5.jsonl \
  --checkpoint-dir artifacts/ck_h32 --quiet
# configs[4] (block-interval x selfish-threshold) at 2^17 runs/point on the
# TPU engine — 40x the committed cpp smoke evidence; stepped and resumable
# like the other grids (15 points, ~2 min each at exact-mode rate).
for n in 3 6 9 12 15; do
  sweep_pass "threshold_p$n" 2400 selfish-threshold "$n" \
    artifacts/sweep_selfish_threshold_2e17_r5.jsonl artifacts/ck_th 0.0078125
done
echo "=== plan complete; see $LOG" | tee -a "$LOG/plan.log"
