"""Headline benchmark: sim-years/sec/chip on the reference's default config.

Config matches the reference driver (main.cpp:7-10,44-65): 9-miner 2025
hashrate distribution, 1 s propagation, honest-only, 365.2425-day runs. The
baseline is the measured C++ reference throughput of ~86 sim-years/sec on one
CPU core (BASELINE.md:20); vs_baseline is the speedup over that.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import argparse
import json
import sys
import time

CPU_CORE_BASELINE_SIM_YEARS_PER_S = 86.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=0, help="runs per jitted batch (0 = auto)")
    ap.add_argument("--target-seconds", type=float, default=30.0, help="measurement budget")
    ap.add_argument("--max-batches", type=int, default=64)
    args = ap.parse_args()

    import jax

    from tpusim import SimConfig, default_network, DEFAULT_DURATION_MS
    from tpusim.engine import Engine
    from tpusim.runner import make_engine, make_run_keys

    platform = jax.devices()[0].platform
    batch = args.batch_size or (8192 if platform != "cpu" else 256)

    config = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=DEFAULT_DURATION_MS,
        runs=batch,
        batch_size=batch,
        seed=7,
    )
    engine = make_engine(config)
    years_per_run = config.duration_ms / (365.2425 * 86_400_000.0)

    # Compile + warm up (first TPU compile is slow and must not be timed).
    # A Pallas lowering failure on this TPU generation falls back to the
    # draw-identical scan engine rather than failing the benchmark.
    try:
        engine.run_batch(make_run_keys(config.seed, 0, batch))
    except Exception:
        if not hasattr(engine, "scan_twin"):
            raise
        engine = engine.scan_twin()
        engine.run_batch(make_run_keys(config.seed, 0, batch))

    total_runs = 0
    t0 = time.perf_counter()
    for i in range(args.max_batches):
        engine.run_batch(make_run_keys(config.seed, (i + 1) * batch, batch))
        total_runs += batch
        if time.perf_counter() - t0 >= args.target_seconds:
            break
    elapsed = time.perf_counter() - t0

    sim_years_per_s = total_runs * years_per_run / elapsed
    engine_name = "pallas" if type(engine) is not Engine else "scan"
    print(
        json.dumps(
            {
                "metric": f"sim_years_per_sec_per_chip ({platform}/{engine_name}, {total_runs} runs x 365d, 9-miner honest)",
                "value": round(sim_years_per_s, 3),
                "unit": "sim-years/s/chip",
                "vs_baseline": round(sim_years_per_s / CPU_CORE_BASELINE_SIM_YEARS_PER_S, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
