"""Headline benchmark: sim-years/sec/chip on the reference's default config.

Config matches the reference driver (main.cpp:7-10,44-65): 9-miner 2025
hashrate distribution, 1 s propagation, honest-only, 365.2425-day runs. The
baseline is the measured C++ reference throughput of ~86 sim-years/sec on one
CPU core (BASELINE.md:20); vs_baseline is the speedup over that.

Always prints exactly ONE JSON line on stdout — on success:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
and on any failure a line with the same schema plus "error" and "phase"
(value 0.0), so the capture harness never records a silent null. Diagnostics
go to stderr.

Robustness (this TPU tunnel has been observed to hang jax.devices() for
minutes): the backend is probed in a SUBPROCESS with a timeout, retried with
backoff, and the whole benchmark sits under a watchdog alarm. If the TPU
backend never comes up the benchmark falls back to local CPU so a (clearly
labelled) number is still produced. A smoke run at small scale proves the
whole engine path and calibrates the headline batch size before the full
config is attempted.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

CPU_CORE_BASELINE_SIM_YEARS_PER_S = 86.0
YEAR_MS = 365.2425 * 86_400_000.0
PERF_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "artifacts", "perf_tpu.jsonl")


def log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def cached_tpu_numbers(path: str = PERF_LOG) -> dict | None:
    """Last builder-measured on-chip throughput rows from the perf log, per
    mode — emitted whenever this bench run falls back to CPU, so a wedged
    tunnel can never erase the on-hardware perf story from the round
    artifact (the CPU number alone reads as a 0.2x regression)."""
    fast = exact = None
    try:
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "TPU" not in str(row.get("chip", "")):
                    continue
                rate = row.get("sim_years_per_s")
                if not isinstance(rate, (int, float)):
                    continue
                keep = {
                    k: row[k]
                    for k in ("date", "chip", "engine", "mode", "config",
                              "sim_years_per_s", "vs_cpu_core_baseline",
                              "measurement", "note")
                    if k in row
                }
                if "exact" in str(row.get("mode", "")):
                    exact = keep
                else:
                    fast = keep
    except OSError:
        return None
    if fast is None and exact is None:
        return None
    return {
        "fast": fast,
        "exact": exact,
        "note": "last builder-measured on-chip values (artifacts/perf_tpu.jsonl); "
                "this bench run could not reach the TPU",
    }




class _Watchdog(Exception):
    pass


def append_perf_rows(rows: list[dict], measurement: str) -> None:
    """Append on-chip measurement rows to the perf log, stamping date/chip.
    Callers must only pass hardware measurements — cached_tpu_numbers()
    serves this file as the on-chip story whenever a bench run falls back
    to CPU."""
    import jax

    try:
        with open(PERF_LOG, "a") as f:
            for row in rows:
                f.write(json.dumps({
                    "date": time.strftime("%Y-%m-%d"),
                    "chip": str(jax.devices()[0]),
                    "measurement": measurement,
                    **row,
                }) + "\n")
    except OSError as e:
        log(f"could not append rows to {PERF_LOG}: {e}")


def pipelined_measure(engine, key_fn, batch: int, budget_s: float,
                      max_batches: int, depth: int,
                      recorder=None) -> tuple[int, float]:
    """Depth-``depth`` pipelined measure loop: dispatch batch i+1 (keys from
    ``key_fn(i)``), then finalize batches until at most ``depth`` remain in
    flight, so host-side key construction and stat reduction overlap device
    compute. The budget is checked after each dispatch round and the final
    drain is included in the measured wall time. Returns (total_runs,
    elapsed_s); depth 0 is the sequential (non-pipelined) loop. The wall
    time can overshoot the budget by up to ``depth + 1`` batch durations
    (the batch whose finalize reveals the budget is spent, plus the ones
    already in flight behind it) — size the batch to the budget on slow
    hosts; the --hard-timeout watchdog bounds the worst case.

    ``recorder`` (tpusim.telemetry.TelemetryRecorder) emits one ``batch``
    span per finalize, completion-to-completion — the same schema as the
    runner's pipelined batch loop, so `tpusim report` can render a bench
    ledger and the telemetry-on-vs-off overhead is measured on the exact
    span traffic production runs generate."""
    total_runs = 0
    inflight: list = []
    t0 = time.perf_counter()
    last_done = t0

    def finalize_one() -> None:
        nonlocal total_runs, last_done
        stall0 = time.perf_counter()
        out = inflight.pop(0)()
        now = time.perf_counter()
        if recorder is not None:
            recorder.emit(
                "batch", t_start=time.time() - (now - last_done),
                dur_s=now - last_done, runs=batch,
                stall_s=round(now - stall0, 6),
                reorg_depth_max=int(out["tele_reorg_depth_max"]),
                stale_events=int(out["tele_stale_events_sum"]),
                active_steps=int(out["tele_active_steps_sum"]),
                chunks=int(out["tele_chunks_max"]),
                step_slots=int(out["tele_chunks_max"]) * engine.chunk_steps * batch,
            )
        last_done = now
        total_runs += batch

    for i in range(max_batches):
        inflight.append(engine.run_batch_async(key_fn(i)))
        while len(inflight) > depth:
            finalize_one()
        # tpusim-lint: disable=JX009 -- deliberately unforced mid-pipeline
        # budget check: the sync lives inside the popped finalize callable
        # (np.asarray of the batch sums), and blocking here would serialize
        # the pipeline this loop exists to measure.
        if time.perf_counter() - t0 >= budget_s:
            break
    while inflight:
        finalize_one()
    # tpusim-lint: disable=JX009 -- the drain loop above finalized every
    # in-flight batch (the finalize callable blocks on the stat transfer),
    # so the device is idle by this read; the interval is a true wall time.
    return total_runs, time.perf_counter() - t0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=0, help="runs per jitted batch (0 = auto)")
    ap.add_argument("--target-seconds", type=float, default=30.0, help="measurement budget")
    ap.add_argument("--max-batches", type=int, default=64)
    ap.add_argument("--engine", choices=["auto", "pallas", "scan"], default="auto")
    ap.add_argument("--probe-retries", type=int, default=3)
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    ap.add_argument("--hard-timeout", type=float, default=1500.0,
                    help="watchdog for the whole benchmark, seconds")
    ap.add_argument("--skip-smoke", action="store_true")
    ap.add_argument("--exact-target-seconds", type=float, default=20.0,
                    help="measurement budget for the exact-mode (selfish) "
                         "headline; 0 skips it")
    ap.add_argument("--superstep", type=int, default=0,
                    help="events unrolled per device-loop iteration "
                         "(0 = engine auto default); bit-identical results "
                         "for every value")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="finalize each batch before dispatching the next "
                         "(the pre-pipelining measure loop, for ablation)")
    ap.add_argument("--telemetry", default="",
                    help="append a structured span ledger here "
                         "(tpusim.telemetry; render with `tpusim report`): "
                         "phase spans plus one batch span per measured batch")
    ap.add_argument("--perf-ledger", default=None, metavar="JSONL",
                    help="append the headline/exact payloads as perf-ledger "
                         "rows in the shared tpusim.perf schema (default: "
                         "artifacts/perf/perf_<platform>.jsonl; 'none' "
                         "disables) — BENCH history and the `tpusim perf` "
                         "ledger are one format")
    ap.add_argument("--ablate", type=int, default=0, metavar="N_CHUNKS",
                    help="instead of the headline, time N>=12 chained chunks "
                         "inside one jit per engine (the canonical "
                         "kernel-timing discipline) and emit us/step")
    # Test hook: block forever right after backend init so the watchdog path
    # can be exercised deterministically (tests/test_bench.py) instead of
    # racing a real compile against the timeout.
    ap.add_argument("--hang-for-test", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    phase = "backend-init"
    info: dict = {}
    partial: dict = {}  # last fully-measured payload (fast headline) if any
    done = threading.Event()
    _emit_lock = threading.Lock()
    _emitted = [False]

    def emit_once(payload: dict) -> None:
        # Exactly ONE JSON line even if the watchdog thread and the (late)
        # main thread both reach an emit path.
        with _emit_lock:
            if _emitted[0]:
                return
            _emitted[0] = True
        emit(payload)

    def fail(err: Exception | str, *, wedged: bool = False) -> int:
        if partial:
            # The fast headline DID complete on hardware; a later phase
            # failing must not replace a real measurement with a zero.
            payload = {**partial,
                       "error": str(err)[:500], "error_phase": phase}
        else:
            payload = {
                "metric": "sim_years_per_sec_per_chip (FAILED)",
                "value": 0.0,
                "unit": "sim-years/s/chip",
                "vs_baseline": 0.0,
                "error": str(err)[:500],
                "phase": phase,
                **info,
            }
        # Cached on-chip rows attach when the TPU was never reached, or when
        # a watchdog fired (wedge) — but a genuine failure ON a live chip
        # must not be dressed up as a tunnel outage with stale rows, so a
        # post-probe wedge gets an honest note: from inside the process a
        # mid-run tunnel death and an on-chip overrun are indistinguishable.
        if info.get("platform") != "tpu" or wedged:
            cached = cached_tpu_numbers()
            if cached is not None:
                if info.get("platform") == "tpu":
                    cached = {**cached, "note": (
                        "last builder-measured on-chip values "
                        "(artifacts/perf_tpu.jsonl); the watchdog fired after "
                        "the TPU probe succeeded — either the tunnel died "
                        "mid-run or the run overran the timeout on a live chip"
                    )}
                payload.setdefault("cached_tpu", cached)
        done.set()
        emit_once(payload)
        return 1

    def on_alarm(signum, frame):
        raise _Watchdog(f"watchdog: exceeded {args.hard_timeout:.0f}s in phase {phase}")

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(int(args.hard_timeout))

    def thread_watchdog():
        # SIGALRM cannot preempt a main thread blocked inside the PJRT
        # client's C wait — the observed failure mode when the tunnel dies
        # mid-run (round 5: smoke-phase run_batch futex-parked for 20+ min).
        # This daemon thread is the escape hatch that still prints the one
        # JSON line (with cached on-chip rows and any partial measurement)
        # and then hard-exits; 90 s of grace lets the alarm path win when
        # the main thread is interruptible.
        deadline = time.monotonic() + args.hard_timeout + 90.0
        while time.monotonic() < deadline:
            if done.wait(timeout=5.0):
                return
        fail(f"hard watchdog: main thread still blocked after "
             f"{args.hard_timeout + 90:.0f}s in phase {phase}", wedged=True)
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(1)

    watchdog = threading.Thread(
        target=thread_watchdog, daemon=True, name="bench-hard-watchdog"
    )
    watchdog.start()

    try:
        # --- Phase: backend init with subprocess probes + CPU fallback
        # (tpusim.probe: the tunneled backend can hang jax.devices() in-process,
        # and probe_or_force_cpu documents why env vars alone cannot fix that).
        from tpusim.probe import probe_or_force_cpu

        t0 = time.monotonic()
        platform = probe_or_force_cpu(
            timeout_s=args.probe_timeout, retries=args.probe_retries, log=log
        )
        if platform is not None:
            log(f"backend probe ok: {platform} ({time.monotonic() - t0:.1f}s)")
        else:
            log("accelerator backend unavailable after retries; forced local CPU")
            info["tpu_unavailable"] = True

        phase = "import"
        import jax

        platform = jax.devices()[0].platform
        info["platform"] = platform

        if args.hang_for_test:
            phase = "hang-for-test"
            while True:  # interruptible sleep: SIGALRM must be deliverable
                time.sleep(0.2)

        from tpusim import SimConfig, default_network, DEFAULT_DURATION_MS
        from tpusim.engine import Engine
        from tpusim.pallas_engine import FAST_TILE_RUNS, PallasEngine
        from tpusim.runner import make_engine, make_run_keys

        recorder = None
        if args.telemetry:
            from tpusim.telemetry import TelemetryRecorder

            recorder = TelemetryRecorder(args.telemetry)
            info["telemetry"] = args.telemetry

        def phase_span(name: str, dur_s: float, **attrs) -> None:
            if recorder is not None:
                recorder.emit(name, t_start=time.time() - dur_s, dur_s=dur_s,
                              **attrs)

        def build_engine(config: SimConfig):
            if args.engine == "scan":
                return Engine(config)
            if args.engine == "pallas":
                return PallasEngine(config)
            return make_engine(config)

        years_per_run = DEFAULT_DURATION_MS / YEAR_MS

        from tpusim.config import reference_selfish_network

        SELFISH_NET = reference_selfish_network()

        # --- Mode: chained-chunk ablation (not the headline). Times >= 12
        # chunk programs inside ONE jit per engine/mode — the canonical
        # kernel-timing discipline (single-chunk timings over the tunnel
        # vary +-40 %; see tpusim.profiling.time_chained_chunks).
        if args.ablate:
            phase = "ablate"
            from tpusim.profiling import time_chained_chunks

            n_chunks = max(12, args.ablate)
            runs_ab = 8192 if platform == "tpu" else 128
            csteps = None if platform == "tpu" else 256
            results: dict[str, dict] = {}
            for mode_name, net in (("fast", default_network(propagation_ms=1000)),
                                   ("exact", SELFISH_NET)):
                cfg = SimConfig(network=net, duration_ms=DEFAULT_DURATION_MS,
                                runs=runs_ab, batch_size=runs_ab, seed=7,
                                chunk_steps=csteps)
                engines = [Engine(cfg)]
                if platform == "tpu" and args.engine != "scan":
                    try:
                        engines.insert(0, PallasEngine(cfg))
                    except ValueError as e:
                        log(f"ablate: no pallas engine for {mode_name}: {e}")
                for eng_ab in engines:
                    tag = f"{mode_name}/{type(eng_ab).__name__}"
                    results[tag] = time_chained_chunks(
                        eng_ab, make_run_keys(7, 0, runs_ab), n_chunks
                    )
                    log(f"ablate {tag}: {results[tag]}")
            # Self-record on-chip rows in the perf log (the r5 window's rows
            # had to be hand-copied; a dead tunnel must never depend on a
            # human remembering to transcribe stdout). CPU rows stay out —
            # cached_tpu_numbers() reads this file and must only ever see
            # hardware measurements.
            if platform == "tpu":
                for tag, row in results.items():
                    append_perf_rows([row], f"bench.py --ablate {tag}")
            signal.alarm(0)
            done.set()
            first = next(iter(results.values()))
            emit_once({
                "metric": f"us_per_step (chained-chunk ablation, {platform})",
                "value": first["us_per_step"],
                "unit": "us/step",
                "vs_baseline": 0.0,
                "ablation": results,
                **info,
            })
            return 0

        # --- Phase: smoke — prove the full engine path at small scale and
        # calibrate the headline batch so warm-up cannot eat the budget.
        smoke_rate = None
        if not args.skip_smoke:
            phase = "smoke"
            # PallasEngine routes batches below its fast-mode tile_runs
            # wholly to its scan twin, so a smaller smoke would measure
            # — and "prove" — the wrong engine. CPU is far slower; keep its
            # smoke small (the scan engine is the only CPU engine anyway).
            smoke_runs, smoke_days = (
                (128, 14) if platform == "cpu" else (2 * FAST_TILE_RUNS, 30)
            )
            smoke_cfg = SimConfig(
                network=default_network(propagation_ms=1000),
                duration_ms=smoke_days * 86_400_000,
                runs=smoke_runs,
                batch_size=smoke_runs,
                seed=7,
            )
            smoke_engine = build_engine(smoke_cfg)
            info["smoke_engine_is_pallas"] = isinstance(smoke_engine, PallasEngine)
            t0 = time.monotonic()
            smoke_engine.run_batch(make_run_keys(7, 0, smoke_runs))  # compile
            compile_s = time.monotonic() - t0
            t0 = time.monotonic()
            out = smoke_engine.run_batch(make_run_keys(7, smoke_runs, smoke_runs))
            steady_s = time.monotonic() - t0
            smoke_years = smoke_runs * smoke_days / 365.2425
            smoke_rate = smoke_years / steady_s
            info["smoke"] = {
                "engine": type(smoke_engine).__name__,
                "compile_s": round(compile_s, 2),
                "steady_s": round(steady_s, 3),
                "sim_years_per_s": round(smoke_rate, 2),
                "blocks_found_total": int(sum(out["blocks_found_sum"])),
            }
            log(f"smoke: {info['smoke']}")
            phase_span("smoke", compile_s + steady_s, **info["smoke"])

        # --- Phase: headline config.
        phase = "headline-build"
        if args.batch_size:
            batch = args.batch_size
        elif platform == "cpu":
            # 512 amortizes the tiny-op overhead of 2-core CPU XLA far
            # better than the historical 64 (measured ~1.3x steady-state,
            # scripts/roofline.py batch ablation) and is exactly one
            # headline batch: 512 runs x 365 d.
            batch = 512
        else:
            batch = 8192
            if smoke_rate is not None:
                # Keep the (untimed) full-batch warm-up under ~4 minutes even
                # if the chip only ever reaches ~4x the smoke rate.
                # Floor at PallasEngine's fast-mode tile_runs: any smaller
                # batch routes wholly to the scan twin and would measure the
                # wrong engine.
                while batch > FAST_TILE_RUNS and \
                        batch * years_per_run / (4 * smoke_rate) > 240.0:
                    batch //= 2
        info["batch_size"] = batch

        config = SimConfig(
            network=default_network(propagation_ms=1000),
            duration_ms=DEFAULT_DURATION_MS,
            runs=batch,
            batch_size=batch,
            seed=7,
            superstep=args.superstep or None,
        )
        engine = build_engine(config)
        info["engine"] = "pallas" if isinstance(engine, PallasEngine) else "scan"
        info["superstep"] = engine.superstep
        info["pipelined"] = not args.no_pipeline
        # Attribution fields for future perf trajectories: which sampler
        # path and state layout this number was measured on.
        info["rng_batch"] = config.rng_batch
        info["state_dtype"] = config.resolved_count_dtype
        info["consensus_gather"] = config.consensus_gather
        info["count_rebase"] = config.count_rebase
        # Single-config benchmark: always the UNPACKED program (grid packing
        # is a sweep-level dispatch mode, tpusim.packed) — pinned so the
        # trajectory stays one program if bench ever grows a packed mode.
        info["packed"] = False

        phase = "headline-compile"
        # Compile + warm up (first TPU compile is slow and must not be timed).
        # A Pallas failure on this TPU generation falls back to the
        # draw-identical scan twin rather than failing the benchmark.
        t0 = time.monotonic()
        try:
            engine.run_batch(make_run_keys(config.seed, 0, batch))
        except Exception as e:
            if not hasattr(engine, "scan_twin"):
                raise
            log(f"pallas engine failed ({e!r}); falling back to scan twin")
            engine = engine.scan_twin()
            info["engine"] = "scan (pallas fallback)"
            engine.run_batch(make_run_keys(config.seed, 0, batch))
        info["warmup_s"] = round(time.monotonic() - t0, 2)
        log(f"warm-up done in {info['warmup_s']}s")
        phase_span("headline_warmup", info["warmup_s"], engine=info["engine"],
                   batch=batch)

        phase = "measure"
        # Pipelined measure loop: batch i+1 is dispatched before batch i is
        # finalized (one batch in flight), so host-side key construction and
        # stat reduction overlap device compute — the measured rate is the
        # sustained driver rate, directly comparable to the kernel-rate
        # ablation. --no-pipeline restores the sequential loop.
        depth = 0 if args.no_pipeline else 1
        total_runs, elapsed = pipelined_measure(
            engine, lambda i: make_run_keys(config.seed, (i + 1) * batch, batch),
            batch, args.target_seconds, args.max_batches, depth,
            recorder=recorder,
        )
        sim_years_per_s = total_runs * years_per_run / elapsed
        phase_span("measure", elapsed, runs=total_runs, batch=batch,
                   sim_years_per_s=round(sim_years_per_s, 3))

        def headline_payload() -> dict:
            return {
                "metric": (
                    f"sim_years_per_sec_per_chip ({platform}/{info['engine']}, "
                    f"{total_runs} runs x 365d, 9-miner honest)"
                ),
                "value": round(sim_years_per_s, 3),
                "unit": "sim-years/s/chip",
                "vs_baseline": round(
                    sim_years_per_s / CPU_CORE_BASELINE_SIM_YEARS_PER_S, 3
                ),
                "elapsed_s": round(elapsed, 2),
                **info,
            }

        # From here on the fast headline is a real on-hardware measurement;
        # if the exact phase wedges or fails, emit THIS instead of a zero.
        partial.update(headline_payload())

        # --- Phase: exact-mode headline. Every selfish and >=10s-propagation
        # production sweep resolves to exact mode, so the headline fast-mode
        # number alone cannot show regressions where the science lives. The
        # config is the reference's selfish benchmark (README.md:89-107):
        # 40 % selfish miner 0, gamma=0, 1 s propagation.
        if args.exact_target_seconds > 0:
            phase = "exact-headline"
            # 8192 (32 tiles at the exact kernel's t256) amortizes the
            # device-resident loop better than 2048: ~1585 vs ~1450
            # sim-years/s in the r5 on-chip ablation/sweep pair.
            ebatch = 8192 if platform == "tpu" else 8
            exact_cfg = SimConfig(
                network=SELFISH_NET, duration_ms=DEFAULT_DURATION_MS,
                runs=ebatch, batch_size=ebatch, seed=7,
                superstep=args.superstep or None,
            )
            eng2 = build_engine(exact_cfg)
            einfo: dict = {
                "engine": "pallas" if isinstance(eng2, PallasEngine) else "scan",
                "batch_size": ebatch,
                "mode": exact_cfg.resolved_mode,
                "superstep": eng2.superstep,
                "pipelined": not args.no_pipeline,
                "rng_batch": exact_cfg.rng_batch,
                "state_dtype": exact_cfg.resolved_count_dtype,
                "consensus_gather": exact_cfg.consensus_gather,
                "count_rebase": exact_cfg.count_rebase,
                "packed": False,
            }
            t0 = time.monotonic()
            try:
                eng2.run_batch(make_run_keys(7, 0, ebatch))
            except Exception as e:
                if not hasattr(eng2, "scan_twin"):
                    raise
                log(f"exact pallas engine failed ({e!r}); falling back to scan twin")
                eng2 = eng2.scan_twin()
                einfo["engine"] = "scan (pallas fallback)"
                eng2.run_batch(make_run_keys(7, 0, ebatch))
            einfo["warmup_s"] = round(time.monotonic() - t0, 2)
            total2, e_elapsed = pipelined_measure(
                eng2, lambda i: make_run_keys(7, (i + 1) * ebatch, ebatch),
                ebatch, args.exact_target_seconds, args.max_batches, depth,
                recorder=recorder,
            )
            e_rate = total2 * years_per_run / e_elapsed
            phase_span("exact_measure", e_elapsed, runs=total2, batch=ebatch,
                       sim_years_per_s=round(e_rate, 3))
            einfo.update(
                runs=total2,
                elapsed_s=round(e_elapsed, 2),
                sim_years_per_s=round(e_rate, 3),
                vs_baseline=round(e_rate / CPU_CORE_BASELINE_SIM_YEARS_PER_S, 3),
            )
            info["exact"] = einfo
            log(f"exact headline: {einfo}")

        signal.alarm(0)
        payload = headline_payload()  # re-built: the exact phase added info
        if platform != "tpu":
            cached = cached_tpu_numbers()
            if cached is not None:
                payload["cached_tpu"] = cached
        else:
            # Self-record the end-to-end headlines in the perf log (standard
            # schema: mode + sim_years_per_s), so a later CPU fallback's
            # cached_tpu serves the latest driver-format numbers rather than
            # only --ablate kernel rates. Gated to representative runs: the
            # kernel engine (a forced/fallback scan run or a truncated
            # budget must not overwrite the cached on-chip story with a
            # degraded number).
            rows = []
            if info["engine"] == "pallas" and elapsed >= 10.0:
                rows.append({
                    "engine": info["engine"],
                    "mode": "fast",
                    "config": f"9-miner honest, 1s prop, "
                              f"{total_runs} runs x 365d",
                    "sim_years_per_s": round(sim_years_per_s, 3),
                    "vs_cpu_core_baseline": payload["vs_baseline"],
                })
            einfo = info.get("exact", {})
            if einfo.get("engine") == "pallas" and \
                    einfo.get("elapsed_s", 0.0) >= 10.0:
                rows.append({
                    "engine": einfo["engine"],
                    "mode": "exact",
                    "config": f"40% selfish gamma=0, 1s prop, "
                              f"{einfo['runs']} runs x 365d",
                    "sim_years_per_s": einfo["sim_years_per_s"],
                    "vs_cpu_core_baseline": einfo["vs_baseline"],
                })
            if rows:
                append_perf_rows(
                    rows, "bench.py end-to-end headline (incl. dispatch)"
                )
        # Shared-schema perf-ledger rows (tpusim.perf) on EVERY platform: the
        # same append-only file `tpusim perf run` writes, so `perf report`
        # shows the end-to-end headline trajectory next to the chained-chunk
        # kernel rows and `perf compare` can gate either. Best-effort — the
        # ledger is evidence, not the stdout JSON contract.
        if args.perf_ledger != "none":
            try:
                from tpusim.perf import append_rows, default_ledger_path, perf_row

                ledger = args.perf_ledger or str(default_ledger_path(platform))
                perf_rows = [perf_row(
                    "bench_headline_fast", "sim_years_per_s",
                    round(sim_years_per_s, 3), unit="sim-years/s",
                    better="higher",
                    shape={
                        "engine": info["engine"], "mode": "fast",
                        "batch_size": batch, "superstep": info["superstep"],
                        "pipelined": info["pipelined"],
                        "rng_batch": info["rng_batch"],
                        "state_dtype": info["state_dtype"],
                        "consensus_gather": info["consensus_gather"],
                        "count_rebase": info["count_rebase"],
                        "packed": info["packed"],
                    },
                    extra={"elapsed_s": round(elapsed, 2), "runs": total_runs},
                )]
                einfo = info.get("exact")
                if einfo:
                    perf_rows.append(perf_row(
                        "bench_headline_exact", "sim_years_per_s",
                        einfo["sim_years_per_s"], unit="sim-years/s",
                        better="higher",
                        shape={
                            "engine": einfo["engine"], "mode": einfo["mode"],
                            "batch_size": einfo["batch_size"],
                            "superstep": einfo["superstep"],
                            "pipelined": einfo["pipelined"],
                            "rng_batch": einfo["rng_batch"],
                            "state_dtype": einfo["state_dtype"],
                            "consensus_gather": einfo["consensus_gather"],
                            "count_rebase": einfo["count_rebase"],
                            "packed": einfo["packed"],
                        },
                        extra={"elapsed_s": einfo["elapsed_s"],
                               "runs": einfo["runs"]},
                    ))
                append_rows(ledger, perf_rows)
                log(f"appended {len(perf_rows)} perf-ledger row(s) to {ledger}")
            except Exception as e:  # noqa: BLE001 — see comment above
                log(f"could not append perf-ledger rows: {e}")
        if recorder is not None:
            recorder.close()
        done.set()
        emit_once(payload)
        return 0
    except BaseException as e:  # noqa: BLE001 — the JSON line must always appear
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            return fail(f"interrupted: {e!r}")
        return fail(e, wedged=isinstance(e, _Watchdog))


if __name__ == "__main__":
    sys.exit(main())
