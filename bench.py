"""Headline benchmark: sim-years/sec/chip on the reference's default config.

Config matches the reference driver (main.cpp:7-10,44-65): 9-miner 2025
hashrate distribution, 1 s propagation, honest-only, 365.2425-day runs. The
baseline is the measured C++ reference throughput of ~86 sim-years/sec on one
CPU core (BASELINE.md:20); vs_baseline is the speedup over that.

Always prints exactly ONE JSON line on stdout — on success:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
and on any failure a line with the same schema plus "error" and "phase"
(value 0.0), so the capture harness never records a silent null. Diagnostics
go to stderr.

Robustness (this TPU tunnel has been observed to hang jax.devices() for
minutes): the backend is probed in a SUBPROCESS with a timeout, retried with
backoff, and the whole benchmark sits under a watchdog alarm. If the TPU
backend never comes up the benchmark falls back to local CPU so a (clearly
labelled) number is still produced. A smoke run at small scale proves the
whole engine path and calibrates the headline batch size before the full
config is attempted.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

CPU_CORE_BASELINE_SIM_YEARS_PER_S = 86.0
YEAR_MS = 365.2425 * 86_400_000.0


def log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)




class _Watchdog(Exception):
    pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=0, help="runs per jitted batch (0 = auto)")
    ap.add_argument("--target-seconds", type=float, default=30.0, help="measurement budget")
    ap.add_argument("--max-batches", type=int, default=64)
    ap.add_argument("--engine", choices=["auto", "pallas", "scan"], default="auto")
    ap.add_argument("--probe-retries", type=int, default=3)
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    ap.add_argument("--hard-timeout", type=float, default=1500.0,
                    help="watchdog for the whole benchmark, seconds")
    ap.add_argument("--skip-smoke", action="store_true")
    args = ap.parse_args()

    phase = "backend-init"
    info: dict = {}

    def fail(err: Exception | str) -> int:
        emit({
            "metric": "sim_years_per_sec_per_chip (FAILED)",
            "value": 0.0,
            "unit": "sim-years/s/chip",
            "vs_baseline": 0.0,
            "error": str(err)[:500],
            "phase": phase,
            **info,
        })
        return 1

    def on_alarm(signum, frame):
        raise _Watchdog(f"watchdog: exceeded {args.hard_timeout:.0f}s in phase {phase}")

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(int(args.hard_timeout))

    try:
        # --- Phase: backend init with subprocess probes + CPU fallback
        # (tpusim.probe: the tunneled backend can hang jax.devices() in-process,
        # and probe_or_force_cpu documents why env vars alone cannot fix that).
        from tpusim.probe import probe_or_force_cpu

        t0 = time.monotonic()
        platform = probe_or_force_cpu(
            timeout_s=args.probe_timeout, retries=args.probe_retries, log=log
        )
        if platform is not None:
            log(f"backend probe ok: {platform} ({time.monotonic() - t0:.1f}s)")
        else:
            log("accelerator backend unavailable after retries; forced local CPU")
            info["tpu_unavailable"] = True

        phase = "import"
        import jax

        platform = jax.devices()[0].platform
        info["platform"] = platform

        from tpusim import SimConfig, default_network, DEFAULT_DURATION_MS
        from tpusim.engine import Engine
        from tpusim.pallas_engine import FAST_TILE_RUNS, PallasEngine
        from tpusim.runner import make_engine, make_run_keys

        def build_engine(config: SimConfig):
            if args.engine == "scan":
                return Engine(config)
            if args.engine == "pallas":
                return PallasEngine(config)
            return make_engine(config)

        years_per_run = DEFAULT_DURATION_MS / YEAR_MS

        # --- Phase: smoke — prove the full engine path at small scale and
        # calibrate the headline batch so warm-up cannot eat the budget.
        smoke_rate = None
        if not args.skip_smoke:
            phase = "smoke"
            # PallasEngine routes batches below its fast-mode tile_runs
            # wholly to its scan twin, so a smaller smoke would measure
            # — and "prove" — the wrong engine. CPU is far slower; keep its
            # smoke small (the scan engine is the only CPU engine anyway).
            smoke_runs, smoke_days = (
                (128, 14) if platform == "cpu" else (2 * FAST_TILE_RUNS, 30)
            )
            smoke_cfg = SimConfig(
                network=default_network(propagation_ms=1000),
                duration_ms=smoke_days * 86_400_000,
                runs=smoke_runs,
                batch_size=smoke_runs,
                seed=7,
            )
            smoke_engine = build_engine(smoke_cfg)
            info["smoke_engine_is_pallas"] = isinstance(smoke_engine, PallasEngine)
            t0 = time.monotonic()
            smoke_engine.run_batch(make_run_keys(7, 0, smoke_runs))  # compile
            compile_s = time.monotonic() - t0
            t0 = time.monotonic()
            out = smoke_engine.run_batch(make_run_keys(7, smoke_runs, smoke_runs))
            steady_s = time.monotonic() - t0
            smoke_years = smoke_runs * smoke_days / 365.2425
            smoke_rate = smoke_years / steady_s
            info["smoke"] = {
                "engine": type(smoke_engine).__name__,
                "compile_s": round(compile_s, 2),
                "steady_s": round(steady_s, 3),
                "sim_years_per_s": round(smoke_rate, 2),
                "blocks_found_total": int(sum(out["blocks_found_sum"])),
            }
            log(f"smoke: {info['smoke']}")

        # --- Phase: headline config.
        phase = "headline-build"
        if args.batch_size:
            batch = args.batch_size
        elif platform == "cpu":
            batch = 64  # a 365d batch at CPU scan-engine speed must stay in budget
        else:
            batch = 8192
            if smoke_rate is not None:
                # Keep the (untimed) full-batch warm-up under ~4 minutes even
                # if the chip only ever reaches ~4x the smoke rate.
                # Floor at PallasEngine's fast-mode tile_runs: any smaller
                # batch routes wholly to the scan twin and would measure the
                # wrong engine.
                while batch > FAST_TILE_RUNS and \
                        batch * years_per_run / (4 * smoke_rate) > 240.0:
                    batch //= 2
        info["batch_size"] = batch

        config = SimConfig(
            network=default_network(propagation_ms=1000),
            duration_ms=DEFAULT_DURATION_MS,
            runs=batch,
            batch_size=batch,
            seed=7,
        )
        engine = build_engine(config)
        info["engine"] = "pallas" if isinstance(engine, PallasEngine) else "scan"

        phase = "headline-compile"
        # Compile + warm up (first TPU compile is slow and must not be timed).
        # A Pallas failure on this TPU generation falls back to the
        # draw-identical scan twin rather than failing the benchmark.
        t0 = time.monotonic()
        try:
            engine.run_batch(make_run_keys(config.seed, 0, batch))
        except Exception as e:
            if not hasattr(engine, "scan_twin"):
                raise
            log(f"pallas engine failed ({e!r}); falling back to scan twin")
            engine = engine.scan_twin()
            info["engine"] = "scan (pallas fallback)"
            engine.run_batch(make_run_keys(config.seed, 0, batch))
        info["warmup_s"] = round(time.monotonic() - t0, 2)
        log(f"warm-up done in {info['warmup_s']}s")

        phase = "measure"
        total_runs = 0
        t0 = time.perf_counter()
        for i in range(args.max_batches):
            engine.run_batch(make_run_keys(config.seed, (i + 1) * batch, batch))
            total_runs += batch
            if time.perf_counter() - t0 >= args.target_seconds:
                break
        elapsed = time.perf_counter() - t0
        signal.alarm(0)

        sim_years_per_s = total_runs * years_per_run / elapsed
        emit({
            "metric": (
                f"sim_years_per_sec_per_chip ({platform}/{info['engine']}, "
                f"{total_runs} runs x 365d, 9-miner honest)"
            ),
            "value": round(sim_years_per_s, 3),
            "unit": "sim-years/s/chip",
            "vs_baseline": round(sim_years_per_s / CPU_CORE_BASELINE_SIM_YEARS_PER_S, 3),
            "elapsed_s": round(elapsed, 2),
            **info,
        })
        return 0
    except BaseException as e:  # noqa: BLE001 — the JSON line must always appear
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            return fail(f"interrupted: {e!r}")
        return fail(e)


if __name__ == "__main__":
    sys.exit(main())
