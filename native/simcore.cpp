// simcore — native C++20 backend for tpusim.
//
// An independent re-implementation of the mining-simulation semantics the
// framework targets (behavioral contract documented in SURVEY.md §2.1 against
// reference simulation.h / main.cpp), exposed through a C ABI for ctypes.
// It exists as the performance-credible cross-validation oracle for the JAX
// engine and as the native equivalent of the reference's std::async runner
// (reference main.cpp:195-220).
//
// Design differences from the reference (deliberate; this is not a port):
//   * the genesis block is implicit — a chain is a vector of post-genesis
//     blocks, and an empty published chain has tip arrival 0;
//   * times are int64 milliseconds; a private (unrevealed selfish) block is
//     marked with arrival = kPrivate (-1) instead of milliseconds::max;
//   * every run is seeded deterministically from (seed, run_index), so results
//     are reproducible and independent of thread count (the reference seeds
//     from std::random_device, reference main.cpp:131-134);
//   * runs are statically partitioned over threads and written to per-run
//     slots, then reduced sequentially — bitwise-identical totals for any
//     thread count.
//
// Sampling keeps the reference's exact pipelines (SURVEY.md §2.1): exponential
// intervals drawn in nanoseconds, llround'ed, truncated to ms; winner draws
// against cumulative uint64 thresholds pct * ((2^64-1)/100).

#include <algorithm>
#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// RNG: xoroshiro128++ (Blackman & Vigna, public domain algorithm), seeded
// with two successive splitmix64 outputs.
// ---------------------------------------------------------------------------

inline uint64_t splitmix64(uint64_t& x) {
  x += 0x9e3779b97f4a7c15ull;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

inline uint64_t rotl64(uint64_t v, int k) { return (v << k) | (v >> (64 - k)); }

class Xoro {
 public:
  explicit Xoro(uint64_t seed) {
    a_ = splitmix64(seed);
    b_ = splitmix64(seed);
  }

  uint64_t next() {
    const uint64_t s0 = a_;
    uint64_t s1 = b_;
    const uint64_t out = rotl64(s0 + s1, 17) + s0;
    s1 ^= s0;
    a_ = rotl64(s0, 49) ^ s1 ^ (s1 << 21);
    b_ = rotl64(s1, 28);
    return out;
  }

  // Exponential with the given mean: inverse CDF on the top 53 bits.
  double expo(double mean) {
    const double u = static_cast<double>(next() >> 11) * 0x1.0p-53;
    return -std::log1p(-u) * mean;
  }

 private:
  uint64_t a_, b_;
};

// ---------------------------------------------------------------------------
// Domain model.
// ---------------------------------------------------------------------------

constexpr int64_t kPrivate = -1;  // arrival sentinel for unrevealed blocks
constexpr uint64_t kPctMult = ~0ull / 100u;  // percent -> uint64 threshold step

struct Bk {
  int32_t owner;
  int64_t arrival;  // absolute ms at which everyone else has it; kPrivate if secret
  bool operator==(const Bk&) const = default;
};

// Non-owning view of a published chain prefix. Valid for one notify sweep:
// the published prefix it points into cannot change during the sweep (reveals
// only stamp private blocks above it, reorgs only mutate *other* miners'
// chains, and the best-chain owner never reorgs onto itself).
struct BestView {
  const Bk* blocks;
  size_t len;
  const Bk& operator[](size_t i) const { return blocks[i]; }
};

struct MinerCfg {
  int32_t pct;
  int64_t prop_ms;
  bool selfish;
};

struct MinerRun {
  int32_t idx;
  int64_t prop_ms;
  bool selfish;
  std::vector<Bk> chain;  // post-genesis blocks only
  int64_t stale = 0;

  // Trailing private-suffix length (the paper's privateBranchLen).
  int private_len() const {
    int n = 0;
    for (auto it = chain.rbegin(); it != chain.rend() && it->arrival == kPrivate; ++it) ++n;
    return n;
  }

  // Number of trailing blocks nobody else has at time t (private or in flight).
  int unpublished(int64_t t) const {
    int n = 0;
    for (auto it = chain.rbegin(); it != chain.rend(); ++it) {
      if (it->arrival != kPrivate && it->arrival <= t) break;
      ++n;
    }
    return n;
  }

  // Arrival of the oldest in-flight published block strictly after t, or -1.
  int64_t next_arrival(int64_t t) const {
    int64_t earliest = -1;
    for (auto it = chain.rbegin(); it != chain.rend(); ++it) {
      if (it->arrival == kPrivate) continue;  // secret blocks never arrive
      if (it->arrival <= t) break;
      earliest = it->arrival;  // reverse scan: last overwrite = oldest block
    }
    return earliest;
  }

  // A new block of ours at time t. best_len = current best published length
  // (post-genesis count) captured after the previous notify sweep.
  void found_block(int64_t t, size_t best_len) {
    if (selfish) {
      // Winning a 1-block race: exactly one secret block and the public best
      // matched our length — publish the secret block and the new one.
      // Reachability: after any notify sweep, maybe_reveal guarantees
      // private_len() <= lead (it reveals whenever secret > lead), so
      // secret == 1 together with best_len == chain.size() (lead 0) cannot
      // survive a sweep and this branch never fires dynamically. It is part
      // of the behavioral contract nonetheless (reference simulation.h:62-76
      // has the identical branch with the identical invariant, unit-tested
      // as case b of the 2013 paper) and is covered the same way by
      // tests/test_selfish_automaton.py, so it is kept for exact parity.
      if (private_len() == 1 && best_len == chain.size()) {
        chain.back().arrival = t + prop_ms;
        chain.push_back({idx, t + prop_ms});
      } else {
        chain.push_back({idx, kPrivate});
      }
    } else {
      chain.push_back({idx, t + prop_ms});
    }
  }

  // Gamma=0 selective reveal: once the public chain catches up, publish just
  // enough of the oldest secret blocks — all of them when the lead collapses
  // to 1 with more than one secret block in hand.
  void maybe_reveal(const BestView& best, int64_t t) {
    if (!selfish || best.len > chain.size()) return;
    const int secret = private_len();
    const int lead = static_cast<int>(chain.size() - best.len);
    if (secret <= lead) return;
    const int reveal = (secret > 1 && lead == 1) ? secret : secret - lead;
    const size_t first = chain.size() - static_cast<size_t>(secret);
    for (size_t i = first; i < first + static_cast<size_t>(reveal); ++i)
      chain[i].arrival = t + prop_ms;
  }

  // Longest-chain reorg; every popped own block counts as stale.
  void maybe_reorg(const BestView& best) {
    if (best.len <= chain.size()) return;
    while (!chain.empty() && chain.back() != best[chain.size() - 1]) {
      if (chain.back().owner == idx) ++stale;
      chain.pop_back();
    }
    chain.insert(chain.end(), best.blocks + chain.size(), best.blocks + best.len);
  }

  void notify(const BestView& best, int64_t t) {
    maybe_reveal(best, t);  // reveal before reorg; order matters
    maybe_reorg(best);
  }
};

// Longest published chain across miners; ties go to the earlier tip arrival,
// then to roster order (the first-seen rule). Returns a view, not a copy —
// the dominant cost of the event loop would otherwise be copying a ~52k-block
// vector twice per block event.
BestView best_published(const std::vector<MinerRun>& miners, int64_t t) {
  const MinerRun* who = nullptr;
  size_t best_len = 0;
  int64_t best_tip = 0;
  for (const auto& m : miners) {
    const size_t len = m.chain.size() - static_cast<size_t>(m.unpublished(t));
    const int64_t tip = len == 0 ? 0 : m.chain[len - 1].arrival;
    if (!who || len > best_len || (len == best_len && tip < best_tip)) {
      who = &m;
      best_len = len;
      best_tip = tip;
    }
  }
  return {who->chain.data(), best_len};
}

int64_t earliest_pending(const std::vector<MinerRun>& miners, int64_t t) {
  int64_t earliest = -1;
  for (const auto& m : miners) {
    const int64_t a = m.next_arrival(t);
    if (a >= 0 && (earliest < 0 || a < earliest)) earliest = a;
  }
  return earliest;
}

struct RunOut {
  std::vector<double> found, share, stale_rate, stale_blocks;
  double best_height = 0;
};

// One flight-recorder-schema event row (tpusim/flight.py row semantics):
// kind indexes {find, arrival, stale, reorg}; the per-run sequence number is
// the row's position in the trace vector.
struct TraceEvent {
  int64_t t_ms;
  int32_t kind, miner, height, depth;
};

constexpr int32_t kKindFind = 0;
constexpr int32_t kKindArrival = 1;
constexpr int32_t kKindStale = 2;
constexpr int32_t kKindReorg = 3;

// One full Monte-Carlo run: event-driven loop with cut-through time advance.
// `trace` (optional) records the run's event sequence in the JAX engines'
// flight-recorder vocabulary — the cross-backend diff oracle. The
// classification mirrors tpusim/flight.py record_step exactly:
//   * one `find` row per drained same-ms find (miner = winner, height = its
//     post-find chain length, private blocks included);
//   * an `arrival` row only on iterations with NO find due (the
//     find-folds-arrival rule): miner owns the earliest arrival newly
//     visible in (last_sweep_t, t], lowest index on ties, height = its
//     post-sweep chain length;
//   * a `stale`/`reorg` row when the sweep made >= 1 miner adopt: depth is
//     the max own-block pops by a single adopter, `stale` iff depth > 0,
//     miner = the deepest-popping adopter (lowest index on ties), height =
//     the adopted best length.
RunOut simulate_run(const std::vector<MinerCfg>& cfg, int64_t duration_ms,
                    double interval_ns_mean, const std::vector<uint64_t>& thresholds,
                    uint64_t seed, int64_t run_idx,
                    std::vector<TraceEvent>* trace = nullptr) {
  uint64_t mix = seed;
  (void)splitmix64(mix);  // decorrelate from the Python key schedule trivially
  Xoro interval_rng(mix ^ (0x517cc1b727220a95ull * static_cast<uint64_t>(2 * run_idx + 1)));
  Xoro winner_rng(mix ^ (0x517cc1b727220a95ull * static_cast<uint64_t>(2 * run_idx + 2)));

  std::vector<MinerRun> miners;
  miners.reserve(cfg.size());
  for (size_t i = 0; i < cfg.size(); ++i)
    miners.push_back({static_cast<int32_t>(i), cfg[i].prop_ms, cfg[i].selfish, {}, 0});

  auto draw_interval = [&]() -> int64_t {
    return std::llround(interval_rng.expo(interval_ns_mean)) / 1'000'000;
  };
  auto draw_winner = [&]() -> size_t {
    const uint64_t r = winner_rng.next();
    for (size_t i = 0; i < thresholds.size(); ++i)
      if (thresholds[i] > r) return i;
    return thresholds.size() - 1;  // ~16/2^64 of draws land past 100%
  };

  int64_t t = 0;
  int64_t next_block = draw_interval();
  size_t best_len = 0;  // post-genesis length after the last notify sweep
  // Trace bookkeeping: the previous sweep time bounds the "newly arrived"
  // window (the JAX engine's groups hold only arrivals its last flush did
  // not consume), and the pre-sweep snapshots identify adopters and their
  // per-adoption pop counts.
  int64_t last_sweep_t = -1;
  std::vector<size_t> pre_h(miners.size());
  std::vector<int64_t> pre_stale(miners.size());
  while (t < duration_ms) {
    const bool find_due = (t == next_block);
    while (t == next_block) {
      const size_t w = draw_winner();
      miners[w].found_block(t, best_len);
      next_block = t + draw_interval();
      if (trace)
        trace->push_back({t, kKindFind, static_cast<int32_t>(w),
                          static_cast<int32_t>(miners[w].chain.size()), 0});
    }
    int32_t arrival_miner = -1;
    if (trace) {
      for (size_t i = 0; i < miners.size(); ++i) {
        pre_h[i] = miners[i].chain.size();
        pre_stale[i] = miners[i].stale;
      }
      if (!find_due) {
        // Arrival attribution must read the PRE-sweep chains (the JAX
        // recorder reads the step-entry groups): the sweep below may copy
        // the newly-arrived block into adopters' chains — or pop the
        // owner's own copy if the owner itself adopts — and a post-sweep
        // scan would then misattribute the event. Earliest own-block
        // arrival in (last_sweep_t, t], lowest miner on ties; the reverse
        // scan stops at the first arrived-before-the-window block (the
        // trailing region is the miner's own pushes with non-decreasing
        // arrivals; adopted blocks all arrived at or before their adoption
        // sweep).
        int64_t amin = -1;
        for (size_t i = 0; i < miners.size(); ++i) {
          const auto& ch = miners[i].chain;
          for (auto it = ch.rbegin(); it != ch.rend(); ++it) {
            if (it->arrival == kPrivate) continue;
            if (it->arrival <= last_sweep_t) break;
            if (it->owner != miners[i].idx) continue;  // groups hold own blocks
            if (it->arrival <= t && (amin < 0 || it->arrival < amin)) {
              amin = it->arrival;
              arrival_miner = static_cast<int32_t>(i);
            }
          }
        }
      }
    }
    const BestView best = best_published(miners, t);
    for (auto& m : miners) m.notify(best, t);
    best_len = best.len;
    if (trace) {
      if (arrival_miner >= 0)
        trace->push_back(
            {t, kKindArrival, arrival_miner,
             static_cast<int32_t>(miners[arrival_miner].chain.size()), 0});
      int32_t dmax = -1;
      int32_t adopter = -1;
      for (size_t i = 0; i < miners.size(); ++i) {
        if (best.len <= pre_h[i]) continue;  // maybe_reorg's adopt gate
        const auto d = static_cast<int32_t>(miners[i].stale - pre_stale[i]);
        if (d > dmax) {  // strict >: ties keep the lowest miner index
          dmax = d;
          adopter = static_cast<int32_t>(i);
        }
      }
      if (adopter >= 0)
        trace->push_back({t, dmax > 0 ? kKindStale : kKindReorg, adopter,
                          static_cast<int32_t>(best.len), dmax});
      last_sweep_t = t;
    }
    const int64_t arrival = earliest_pending(miners, t);
    t = arrival < 0 ? next_block : std::min(next_block, arrival);
  }

  // Final stats vs the best chain at the configured end time.
  const BestView final_best = best_published(miners, duration_ms);
  const auto denom = static_cast<double>(std::max<size_t>(final_best.len, 1));
  RunOut out;
  out.best_height = static_cast<double>(final_best.len);
  for (const auto& m : miners) {
    int64_t mine = 0;
    for (size_t b = 0; b < final_best.len; ++b) mine += final_best[b].owner == m.idx;
    out.found.push_back(static_cast<double>(mine));
    out.share.push_back(mine > 0 ? static_cast<double>(mine) / denom : 0.0);
    out.stale_rate.push_back(mine > 0 ? static_cast<double>(m.stale) / static_cast<double>(mine)
                                      : 0.0);
    out.stale_blocks.push_back(static_cast<double>(m.stale));
  }
  return out;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI.
// ---------------------------------------------------------------------------

extern "C" {

// First `n` raw xoroshiro128++ outputs for the given seed, split into uint32
// (hi, lo) limb pairs. Exists so the Python/JAX articulation of the generator
// (tpusim/xoroshiro.py) can be contract-tested bit-for-bit against this one.
int simcore_rng_words(uint64_t seed, int64_t n, uint32_t* hi, uint32_t* lo) {
  if (n < 0) return 1;
  Xoro rng(seed);
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t w = rng.next();
    hi[i] = static_cast<uint32_t>(w >> 32);
    lo[i] = static_cast<uint32_t>(w & 0xFFFFFFFFu);
  }
  return 0;
}

// Runs `runs` simulations single-threaded and writes their event sequences
// to `events_path` as the flight-recorder JSONL schema (tpusim/flight_export
// decode_flight row dicts): one line per event, key order
// {"run", "seq", "kind", "t_ms", "miner", "height", "depth"}, sorted by
// (run, seq) — byte-compatible with `tpusim trace --events-out`, so the
// README cross-backend diff recipe needs no hand-rolled harness. Tracing is
// a debugging mode for runs small enough to read; thread fan-out would buy
// nothing and cost ordering, so it is deliberately sequential. Returns 0 on
// success, 1/2 on invalid arguments (as simcore_run), 3 when the output
// file cannot be opened. `n_events_out` (optional) receives the total row
// count.
int simcore_run_events(int32_t n_miners, const int32_t* hashrate_pct,
                       const int64_t* prop_ms, const uint8_t* selfish,
                       int64_t duration_ms, double block_interval_s,
                       int64_t runs, uint64_t seed, const char* events_path,
                       int64_t* n_events_out) {
  if (n_miners <= 0 || runs <= 0 || duration_ms <= 0 || block_interval_s <= 0) return 1;
  std::vector<MinerCfg> cfg;
  std::vector<uint64_t> thresholds;
  uint64_t acc = 0;
  int64_t pct_total = 0;
  for (int32_t i = 0; i < n_miners; ++i) {
    cfg.push_back({hashrate_pct[i], prop_ms[i], selfish[i] != 0});
    pct_total += hashrate_pct[i];
    acc += static_cast<uint64_t>(hashrate_pct[i]) * kPctMult;
    thresholds.push_back(acc);
  }
  if (pct_total != 100) return 2;

  std::FILE* f = std::fopen(events_path, "w");
  if (!f) return 3;
  static const char* const kKindNames[] = {"find", "arrival", "stale", "reorg"};
  const double interval_ns_mean = block_interval_s * 1e9;
  int64_t total = 0;
  for (int64_t r = 0; r < runs; ++r) {
    std::vector<TraceEvent> trace;
    simulate_run(cfg, duration_ms, interval_ns_mean, thresholds, seed, r, &trace);
    for (size_t e = 0; e < trace.size(); ++e) {
      const TraceEvent& ev = trace[e];
      std::fprintf(f,
                   "{\"run\": %lld, \"seq\": %lld, \"kind\": \"%s\", "
                   "\"t_ms\": %lld, \"miner\": %d, \"height\": %d, "
                   "\"depth\": %d}\n",
                   static_cast<long long>(r), static_cast<long long>(e),
                   kKindNames[ev.kind], static_cast<long long>(ev.t_ms),
                   ev.miner, ev.height, ev.depth);
    }
    total += static_cast<int64_t>(trace.size());
  }
  // A torn log (ENOSPC mid-fprintf, failed close flush) must not report
  // success: `trace diff` would blame the truncation on a cross-backend
  // divergence. Mirror the Python exporter's fail-clean rule
  // (flight_export._write_artifact): remove the partial file, return the
  // I/O error code.
  const bool torn = std::ferror(f) != 0;
  if (std::fclose(f) != 0 || torn) {
    std::remove(events_path);
    return 3;
  }
  if (n_events_out) *n_events_out = total;
  return 0;
}

// Runs `runs` independent simulations over `threads` OS threads and writes
// per-miner sums of (found, share, stale_rate, stale_blocks) plus the summed
// best-chain height. Sums are per-run statistics added in run order, matching
// the mean-of-per-run-ratios aggregation the framework reports. Returns 0 on
// success, nonzero on invalid arguments.
int simcore_run(int32_t n_miners, const int32_t* hashrate_pct, const int64_t* prop_ms,
                const uint8_t* selfish, int64_t duration_ms, double block_interval_s,
                int64_t runs, uint64_t seed, int32_t threads, double* found_sum,
                double* share_sum, double* stale_rate_sum, double* stale_blocks_sum,
                double* best_height_sum) {
  if (n_miners <= 0 || runs <= 0 || duration_ms <= 0 || block_interval_s <= 0) return 1;
  std::vector<MinerCfg> cfg;
  std::vector<uint64_t> thresholds;
  uint64_t acc = 0;
  int64_t pct_total = 0;
  for (int32_t i = 0; i < n_miners; ++i) {
    cfg.push_back({hashrate_pct[i], prop_ms[i], selfish[i] != 0});
    pct_total += hashrate_pct[i];
    acc += static_cast<uint64_t>(hashrate_pct[i]) * kPctMult;
    thresholds.push_back(acc);
  }
  if (pct_total != 100) return 2;

  const double interval_ns_mean = block_interval_s * 1e9;
  const int nthreads =
      std::max(1, threads > 0 ? threads : static_cast<int>(std::thread::hardware_concurrency()));

  std::vector<RunOut> per_run(static_cast<size_t>(runs));
  auto worker = [&](int tid) {
    for (int64_t r = tid; r < runs; r += nthreads)
      per_run[static_cast<size_t>(r)] =
          simulate_run(cfg, duration_ms, interval_ns_mean, thresholds, seed, r);
  };
  if (nthreads == 1) {
    worker(0);
  } else {
    std::vector<std::thread> pool;
    pool.reserve(static_cast<size_t>(nthreads));
    for (int tid = 0; tid < nthreads; ++tid) pool.emplace_back(worker, tid);
    for (auto& th : pool) th.join();
  }

  for (int32_t i = 0; i < n_miners; ++i)
    found_sum[i] = share_sum[i] = stale_rate_sum[i] = stale_blocks_sum[i] = 0.0;
  *best_height_sum = 0.0;
  for (const auto& r : per_run) {  // sequential, run-order reduction
    for (int32_t i = 0; i < n_miners; ++i) {
      found_sum[i] += r.found[static_cast<size_t>(i)];
      share_sum[i] += r.share[static_cast<size_t>(i)];
      stale_rate_sum[i] += r.stale_rate[static_cast<size_t>(i)];
      stale_blocks_sum[i] += r.stale_blocks[static_cast<size_t>(i)];
    }
    *best_height_sum += r.best_height;
  }
  return 0;
}

}  // extern "C"
