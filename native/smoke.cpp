// Sanitized smoke test for simcore: runs a small honest and a selfish batch
// under ASan/UBSan (make check) and applies coarse sanity bounds. The real
// behavioral validation happens from Python (tests/test_cpp_backend.py),
// cross-checked against the JAX engine and the analytical oracle.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <vector>

extern "C" int simcore_run(int32_t, const int32_t*, const int64_t*, const uint8_t*, int64_t,
                           double, int64_t, uint64_t, int32_t, double*, double*, double*,
                           double*, double*);

static void expect(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "smoke FAILED: %s\n", what);
    std::exit(1);
  }
}

int main() {
  {
    // Honest 9-miner network, 10 s propagation, 8 runs x 30 days, 4 threads.
    const std::vector<int32_t> pct = {30, 29, 12, 11, 8, 5, 3, 1, 1};
    const std::vector<int64_t> prop(9, 10'000);
    const std::vector<uint8_t> selfish(9, 0);
    std::vector<double> found(9), share(9), rate(9), stale(9);
    double best = 0;
    const int rc = simcore_run(9, pct.data(), prop.data(), selfish.data(),
                               30ll * 86'400'000, 600.0, 8, 42, 4, found.data(),
                               share.data(), rate.data(), stale.data(), &best);
    expect(rc == 0, "honest run rc");
    expect(best / 8 > 3800 && best / 8 < 4900, "mean best height ~4320");
    expect(share[0] / 8 > 0.25 && share[0] / 8 < 0.35, "miner-0 share ~30%");
    expect(rate[0] / 8 < 0.05, "miner-0 stale rate small");
  }
  {
    // 40% selfish miner: share must exceed hashrate, honest stale rates high.
    const std::vector<int32_t> pct = {40, 19, 12, 11, 8, 5, 3, 1, 1};
    const std::vector<int64_t> prop(9, 1'000);
    std::vector<uint8_t> selfish(9, 0);
    selfish[0] = 1;
    std::vector<double> found(9), share(9), rate(9), stale(9);
    double best = 0;
    const int rc = simcore_run(9, pct.data(), prop.data(), selfish.data(),
                               60ll * 86'400'000, 600.0, 6, 7, 3, found.data(),
                               share.data(), rate.data(), stale.data(), &best);
    expect(rc == 0, "selfish run rc");
    expect(share[0] / 6 > 0.40, "selfish share above hashrate");
    expect(rate[1] / 6 > 0.5, "honest stale rate high under selfish attack");
  }
  {
    // Bad config: percentages not summing to 100 must be rejected.
    const int32_t pct[2] = {50, 49};
    const int64_t prop[2] = {1000, 1000};
    const uint8_t selfish[2] = {0, 0};
    double f[2], s[2], r[2], st[2], b;
    expect(simcore_run(2, pct, prop, selfish, 1000, 600.0, 1, 0, 1, f, s, r, st, &b) == 2,
           "pct sum validation");
  }
  std::puts("smoke ok");
  return 0;
}
