"""Cross-backend event tracing: the native backend's flight-recorder-schema
JSONL producer (native/simcore.cpp simcore_run_events) and the structured
`tpusim trace diff` localizer that replaces the README recipe's manual diff.

The headline test drives the whole recipe: the scan engine under
rng="xoroshiro" (in a JAX_ENABLE_X64 subprocess — the interval mapping is
bit-exact only in float64) and the native producer must emit IDENTICAL event
sequences for the same seed, on a roster that exercises every event kind
including the prop-0 find-folds-arrival edge.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from tpusim.config import MinerConfig, NetworkConfig, SimConfig
from tpusim.flight_export import TraceDiff, diff_event_logs, load_events_jsonl

pytestmark = pytest.mark.skipif(
    not (Path(__file__).parent.parent / "native" / "simcore.cpp").exists(),
    reason="native backend sources not present",
)

TINY = SimConfig(
    network=NetworkConfig(
        miners=(
            MinerConfig(hashrate_pct=50, propagation_ms=5000),
            MinerConfig(hashrate_pct=30, propagation_ms=2000),
            MinerConfig(hashrate_pct=20, propagation_ms=0),
        )
    ),
    duration_ms=86_400_000,
    runs=4,
    batch_size=4,
    seed=42,
    rng="xoroshiro",
)


def _row(run, seq, kind="find", t=10, miner=0, height=1, depth=0):
    return {"run": run, "seq": seq, "kind": kind, "t_ms": t, "miner": miner,
            "height": height, "depth": depth}


# ---------------------------------------------------------------------------
# The diff localizer itself (pure python).


def test_diff_identical_logs():
    a = [_row(0, 0), _row(0, 1, "arrival"), _row(1, 0)]
    d = diff_event_logs(a, [dict(r) for r in a])
    assert not d.divergent
    assert d.n_a == d.n_b == 3
    assert d.kinds_a == {"find": 2, "arrival": 1}
    assert "identical" in d.render()


def test_diff_reports_first_divergent_row_and_kind_deltas():
    a = [_row(0, 0), _row(0, 1, "arrival", miner=1), _row(2, 5, "stale", depth=2)]
    b = [_row(0, 0), _row(0, 1, "arrival", miner=2), _row(2, 5, "reorg")]
    d = diff_event_logs(a, b)
    assert d.divergent and d.first_key == (0, 1)
    assert d.first_a["miner"] == 1 and d.first_b["miner"] == 2
    text = d.render("A", "B")
    assert "FIRST DIVERGENCE at (run 0, seq 1)" in text
    assert "stale" in text and "reorg" in text  # per-kind count lines


def test_diff_localizes_missing_rows_on_either_side():
    a = [_row(0, 0), _row(0, 1, "arrival")]
    d = diff_event_logs(a, a[:1])
    assert d.first_key == (0, 1) and d.first_b is None
    d2 = diff_event_logs(a[:1], a)
    assert d2.first_key == (0, 1) and d2.first_a is None
    # Order independence: the walk sorts by (run, seq) itself.
    d3 = diff_event_logs(list(reversed(a)), [dict(r) for r in a])
    assert not d3.divergent


def test_load_events_jsonl_is_strict(tmp_path):
    p = tmp_path / "ev.jsonl"
    p.write_text(json.dumps(_row(0, 0)) + "\n{torn")
    with pytest.raises(ValueError, match="unparseable"):
        load_events_jsonl(p)
    p.write_text('{"not": "an event"}\n')
    with pytest.raises(ValueError, match="not an event row"):
        load_events_jsonl(p)


def test_trace_diff_cli_exit_codes(tmp_path, capsys):
    from tpusim.flight_export import main as trace_main

    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    a.write_text(json.dumps(_row(0, 0)) + "\n")
    b.write_text(json.dumps(_row(0, 0)) + "\n")
    assert trace_main(["diff", str(a), str(b)]) == 0
    assert "identical" in capsys.readouterr().out
    b.write_text(json.dumps(_row(0, 0, miner=3)) + "\n")
    assert trace_main(["diff", str(a), str(b)]) == 1
    assert "FIRST DIVERGENCE" in capsys.readouterr().out
    assert trace_main(["diff", str(a), str(tmp_path / "missing.jsonl")]) == 2


# ---------------------------------------------------------------------------
# The native producer.


def test_native_event_log_schema_and_order(tmp_path):
    from tpusim.backend.cpp import run_events_cpp

    out = tmp_path / "native.jsonl"
    n = run_events_cpp(TINY, out)
    events = load_events_jsonl(out)
    assert n == len(events) > 0
    # Exact key ORDER (not just key set): the README recipe's byte-level
    # diffability against `tpusim trace --events-out` depends on it.
    assert all(
        list(e) == ["run", "seq", "kind", "t_ms", "miner", "height", "depth"]
        for e in events
    )
    assert events == sorted(events, key=lambda e: (e["run"], e["seq"]))
    kinds = {e["kind"] for e in events}
    assert kinds <= {"find", "arrival", "stale", "reorg"}
    assert "find" in kinds and "arrival" in kinds
    # Per-run seqs are dense from 0.
    for r in range(TINY.runs):
        seqs = [e["seq"] for e in events if e["run"] == r]
        assert seqs == list(range(len(seqs)))


def test_native_rejects_bad_args(tmp_path):
    from tpusim.backend.cpp import run_events_cpp

    with pytest.raises(OSError):
        run_events_cpp(TINY, tmp_path / "no_such_dir" / "x.jsonl")


def test_native_matches_jax_flight_recorder(tmp_path):
    """The tentpole contract of the satellite: the README cross-backend diff
    recipe runs end to end with ZERO divergence — the JAX engine's flight
    ring under rng=xoroshiro and the native producer describe the same
    (seed, run) universe event for event."""
    from tpusim.backend.cpp import run_events_cpp
    from tpusim.probe import TUNNEL_TRIGGER_ENV

    native = tmp_path / "native.jsonl"
    run_events_cpp(TINY, native)

    jax_log = tmp_path / "jax.jsonl"
    env = os.environ.copy()
    env.pop(TUNNEL_TRIGGER_ENV, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    repo = str(Path(__file__).parent.parent)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "tpusim", "trace",
            "--runs", str(TINY.runs), "--batch-size", str(TINY.batch_size),
            "--duration-ms", str(TINY.duration_ms), "--seed", str(TINY.seed),
            "--rng", "xoroshiro", "--single-device", "--quiet",
            "--hashrates", "50,30,20", "--propagation-ms", "5000,2000,0",
            "--flight-capacity", "4096",
            "--trace-out", str(tmp_path / "jax.trace.json"),
            "--events-out", str(jax_log),
        ],
        capture_output=True, text=True, env=env, timeout=600, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = diff_event_logs(load_events_jsonl(jax_log), load_events_jsonl(native))
    assert isinstance(d, TraceDiff)
    assert not d.divergent, d.render("jax", "native")
    assert d.kinds_a.get("stale", 0) > 0  # the racy kinds are exercised
    # And the logs are byte-identical, not merely row-equal: the C++ printf
    # format matches json.dumps' separators.
    assert jax_log.read_text() == native.read_text()


def test_packed_trace_diff_native_byte_identical(tmp_path):
    """The packed twin of the recipe above: a 2-point xoroshiro flight grid
    run as ONE packed dispatch (pack_width spans both points) must decode,
    per point, an event log BYTE-identical to the native producer's for that
    point's own (seed, run) universe — the pack-position -> (point, run)
    mapping is exact."""
    import dataclasses

    from tpusim.backend.cpp import run_events_cpp
    from tpusim.config import MinerConfig
    from tpusim.probe import TUNNEL_TRIGGER_ENV

    other = dataclasses.replace(
        TINY, seed=7,
        network=NetworkConfig(
            miners=(
                MinerConfig(hashrate_pct=50, propagation_ms=1000),
                MinerConfig(hashrate_pct=30, propagation_ms=500),
                MinerConfig(hashrate_pct=20, propagation_ms=0),
            )
        ),
    )
    cfgs = [
        (name, dataclasses.replace(c, flight_capacity=4096))
        for name, c in (("tiny", TINY), ("other", other))
    ]

    env = os.environ.copy()
    env.pop(TUNNEL_TRIGGER_ENV, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    repo = str(Path(__file__).parent.parent)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    worker = Path(__file__).parent / "packed_trace_worker.py"
    argv = [sys.executable, str(worker), str(tmp_path)]
    for name, c in cfgs:
        argv += [name, c.to_json()]
    proc = subprocess.run(
        argv, capture_output=True, text=True, env=env, timeout=600, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

    for name, c in cfgs:
        native = tmp_path / f"{name}.native.jsonl"
        run_events_cpp(dataclasses.replace(c, flight_capacity=0), native)
        packed_log = tmp_path / f"{name}.events.jsonl"
        d = diff_event_logs(
            load_events_jsonl(packed_log), load_events_jsonl(native)
        )
        assert not d.divergent, d.render(f"packed:{name}", "native")
        assert packed_log.read_text() == native.read_text(), name


def test_cpp_backend_trace_cli_surface(tmp_path, capsys):
    from tpusim.flight_export import main as trace_main

    out = tmp_path / "ev.jsonl"
    rc = trace_main([
        "--backend", "cpp", "--runs", "2", "--duration-ms", "43200000",
        "--hashrates", "50,30,20", "--propagation-ms", "5000,2000,0",
        "--seed", "1", "--events-out", str(out),
    ])
    assert rc == 0
    assert "native backend wrote" in capsys.readouterr().out
    assert len(load_events_jsonl(out)) > 0
    # Flags that only mean something on the device ring are rejected loudly.
    with pytest.raises(SystemExit, match="events-out"):
        trace_main(["--backend", "cpp", "--runs", "2"])
    with pytest.raises(SystemExit, match="flight-capacity"):
        trace_main([
            "--backend", "cpp", "--runs", "2", "--flight-capacity", "8",
            "--events-out", str(out),
        ])
    with pytest.raises(SystemExit, match="trace-out"):
        trace_main([
            "--backend", "cpp", "--runs", "2",
            "--trace-out", str(tmp_path / "t.json"), "--events-out", str(out),
        ])