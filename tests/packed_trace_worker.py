"""Subprocess target for the packed cross-backend trace test
(tests/test_trace_diff.py).

Runs the named xoroshiro flight configs as ONE packed grid — pack_width
spanning every run so the points share a single packed dispatch — and
writes each point's pack-decoded event log with the byte-stable
``events_jsonl`` writer. The parent diffs each file against the native
producer's log for the same config; launched in a JAX_ENABLE_X64
subprocess because the xoroshiro interval mapping is bit-exact to the
native backend only in float64.

argv: [out_dir, name1, config_json1, name2, config_json2, ...].
"""

import sys
from pathlib import Path


def main() -> None:
    from tpusim.config import SimConfig
    from tpusim.flight_export import events_jsonl
    from tpusim.packed import run_grid

    out = Path(sys.argv[1])
    points = [
        (sys.argv[i], SimConfig.from_json(sys.argv[i + 1]))
        for i in range(2, len(sys.argv), 2)
    ]
    entries = run_grid(
        points, engine_cache={},
        pack_width=sum(c.runs for _, c in points),
    )
    for entry in entries:
        (out / f"{entry['name']}.events.jsonl").write_text(
            events_jsonl(entry["flight"].events)
        )


if __name__ == "__main__":
    main()
