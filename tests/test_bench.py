"""bench.py robustness machinery: the cached on-chip row lookup and the
always-one-JSON-line contract under the failure/watchdog paths.

Rationale (round 5): the driver captures bench.py's stdout as the round's
BENCH artifact, and the TPU tunnel has died mid-run in three rounds. The
hardened bench must (a) surface the last builder-measured on-chip numbers
whenever the chip is unreachable, and (b) emit exactly one JSON line no
matter how it dies — these tests pin both against the reference scenario of
an output-less wedge (the empty BENCH_r01/r02 failure mode).
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)


def _write_rows(path: Path, rows: list[dict]) -> None:
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def test_cached_tpu_numbers_picks_last_row_per_mode(tmp_path):
    log = tmp_path / "perf.jsonl"
    _write_rows(log, [
        {"chip": "TPU v5e", "mode": "fast", "sim_years_per_s": 100, "date": "d1"},
        {"chip": "container CPU", "mode": "fast", "sim_years_per_s": 9},  # not TPU
        {"chip": "TPU v5e", "mode": "fast", "sim_years_per_s": "broken"},  # non-numeric
        {"chip": "TPU v5e", "note": "no rate field"},
        {"chip": "TPU v5 lite0", "mode": "fast", "sim_years_per_s": 200, "date": "d2"},
        {"chip": "TPU v5 lite0", "mode": "exact", "sim_years_per_s": 50, "date": "d2"},
    ])
    cached = bench.cached_tpu_numbers(str(log))
    assert cached["fast"]["sim_years_per_s"] == 200  # last valid TPU fast row
    assert cached["fast"]["date"] == "d2"
    assert cached["exact"]["sim_years_per_s"] == 50
    assert "note" in cached


def test_cached_tpu_numbers_missing_or_empty(tmp_path):
    assert bench.cached_tpu_numbers(str(tmp_path / "nope.jsonl")) is None
    empty = tmp_path / "empty.jsonl"
    empty.write_text("not json at all\n")
    assert bench.cached_tpu_numbers(str(empty)) is None


def test_repo_perf_log_has_both_modes():
    """The committed perf log must keep feeding both cached modes: a future
    edit that drops the exact-mode rows would silently halve the fallback."""
    cached = bench.cached_tpu_numbers()
    assert cached is not None
    assert cached["fast"] and cached["fast"]["sim_years_per_s"] > 0
    assert cached["exact"] and cached["exact"]["sim_years_per_s"] > 0


def test_bench_watchdog_emits_single_json_line():
    """A bench that exceeds --hard-timeout must still print exactly one JSON
    line (schema + error + phase + cached_tpu) and exit nonzero.

    Uses the --hang-for-test hook (bench blocks right after backend init) so
    the watchdog firing is an event the bench deterministically reaches, not
    a race between the timeout and a real compile whose duration shifts
    under full-suite load."""
    env = os.environ.copy()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--hard-timeout", "3",
         "--probe-retries", "1", "--probe-timeout", "60",
         "--target-seconds", "1", "--exact-target-seconds", "0",
         "--batch-size", "8", "--hang-for-test"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert r.returncode == 1
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, r.stdout
    payload = json.loads(lines[0])
    assert payload["value"] == 0.0
    assert "watchdog" in payload["error"]
    assert payload["phase"]
    # CPU-forced run: the cached on-chip story must ride along.
    assert payload["cached_tpu"]["fast"]["sim_years_per_s"] > 0
