"""Telemetry subsystem: recorder/ledger contract, device-side counters
(scan vs pallas pinned equal), the CLI progress callback, and the
``tpusim report`` dashboard subcommand.

The counters are part of every run_batch output, so the existing engine
equality suites pin them implicitly; the tests here pin the telemetry-
specific contracts — JSONL schema, crash-tolerant read-back, span wiring
through runner/sweep, report rendering for both input kinds, and the
profiling satellites (single-batch steady flag, zero-spread guard).
"""

from __future__ import annotations

import dataclasses
import gzip
import json
import time

import numpy as np
import pytest

from tpusim.config import SimConfig, default_network, reference_selfish_network
from tpusim.engine import Engine, combine_sums
from tpusim.runner import make_run_keys, run_simulation_config
from tpusim.telemetry import (
    BatchRecord,
    TelemetryRecorder,
    load_spans,
    throughput_report,
)

SMALL = SimConfig(
    network=default_network(propagation_ms=1000),
    duration_ms=86_400_000,
    runs=8,
    batch_size=4,
    seed=3,
)


# ---------------------------------------------------------------------------
# Recorder / ledger contract.


def test_recorder_schema_and_truncation_tolerance(tmp_path):
    path = tmp_path / "t.jsonl"
    rec = TelemetryRecorder(path)
    rec.emit("batch", dur_s=1.5, runs=4, depth=np.int64(3))
    with rec.span("checkpoint_save", runs_done=8) as attrs:
        attrs["extra"] = "yes"
    rec.close()
    # Append garbage + a truncated line: load_spans must skip both, exactly
    # like the sweep --resume scanner's tolerance policy.
    with path.open("a") as fh:
        fh.write("not json\n")
        fh.write('{"run_id": "x", "span": "batc')
    spans = load_spans(path)
    assert [s["span"] for s in spans] == ["batch", "checkpoint_save"]
    for s in spans:
        assert set(s) >= {"run_id", "span", "t_start", "dur_s", "attrs"}
        assert s["run_id"] == rec.run_id  # one correlating id per recorder
    assert spans[0]["attrs"] == {"runs": 4, "depth": 3}  # np coerced to JSON int
    assert spans[1]["attrs"]["extra"] == "yes"
    assert spans[1]["dur_s"] >= 0.0


def test_throughput_report_single_batch_is_flagged():
    day = 86_400_000
    multi = throughput_report(
        [BatchRecord(4, 10.0), BatchRecord(4, 1.0)], day, 600.0
    )
    assert multi["steady_is_first_batch"] is False
    assert multi["steady_runs_per_s"] == 4.0  # compile batch excluded
    single = throughput_report([BatchRecord(4, 2.0)], day, 600.0)
    # A single batch has only compile-contaminated numbers; they are still
    # reported (better than nothing) but must carry the flag.
    assert single["steady_is_first_batch"] is True
    assert single["steady_runs_per_s"] == 2.0


def test_profiler_is_thin_client_of_registry():
    from tpusim.profiling import Profiler
    from tpusim.telemetry import MetricsRegistry

    prof = Profiler()
    assert isinstance(prof.registry, MetricsRegistry)
    prof.record(4, 2.0)
    assert prof.records == prof.registry.batches  # same storage, no copy
    rep = prof.report(86_400_000, 600.0)
    assert rep["steady_is_first_batch"] is True
    assert rep["trace_dir"] is None
    # Identical derivation to the shared throughput_report.
    shared = throughput_report(prof.registry.batches, 86_400_000, 600.0)
    assert {k: v for k, v in rep.items() if k != "trace_dir"} == shared


def test_time_chained_chunks_zero_best_guard(monkeypatch):
    """A zero best timing (degenerate fast path / coarse clock) must yield
    spread_pct None, not a ZeroDivisionError."""
    from tpusim import profiling

    config = dataclasses.replace(SMALL, runs=4, batch_size=4, chunk_steps=32)
    engine = Engine(config)
    keys = make_run_keys(config.seed, 0, 4)
    monkeypatch.setattr(profiling.time, "perf_counter", lambda: 42.0)
    r = profiling.time_chained_chunks(engine, keys, n_chunks=2, repeats=2)
    assert r["spread_pct"] is None
    assert r["s_per_chunk"] == 0.0
    json.dumps(r)  # the JSONL artifact row must stay serializable
    # roofline_point on the same degenerate timing: flagged row, no
    # ZeroDivisionError aborting a multi-point sweep.
    p = profiling.roofline_point(
        engine, keys, bandwidth_gbps=1.0, n_chunks=2, repeats=2
    )
    assert p["degenerate_timing"] is True
    assert p["events_per_s"] is None and p["fraction_of_roof"] is None
    json.dumps(p)


# ---------------------------------------------------------------------------
# Device-side counters.


def test_device_counters_scan_vs_pallas_equal():
    """The kernel accumulates SimCounters from the same masks/operands as the
    scan engine — pinned bit-equal here on the racy selfish config where all
    three counters are busy (reorgs, stale events, mid-chunk freezes)."""
    from tpusim.pallas_engine import PallasEngine

    config = SimConfig(
        network=reference_selfish_network(),
        duration_ms=2 * 86_400_000,
        runs=128,
        batch_size=128,
        mode="exact",
        chunk_steps=64,
        seed=23,
    )
    keys = make_run_keys(config.seed, 0, config.runs)
    scan = Engine(config).run_batch(keys)
    pallas = PallasEngine(config, tile_runs=128, step_block=32, interpret=True).run_batch(keys)
    tele = [k for k in scan if k.startswith("tele_")]
    assert sorted(tele) == [
        "tele_active_steps_sum", "tele_chunks_max",
        "tele_reorg_depth_hist_sum", "tele_reorg_depth_max",
        "tele_stale_by_miner_sum", "tele_stale_events_sum",
    ]
    for name in tele:
        np.testing.assert_array_equal(
            np.asarray(scan[name]), np.asarray(pallas[name]), err_msg=name
        )
    # Sanity on the semantics: a 40% selfish roster reorgs, so all three
    # counters must be live, and occupancy is a fraction of executed slots.
    assert int(scan["tele_reorg_depth_max"]) >= 1
    assert int(scan["tele_stale_events_sum"]) >= 1
    slots = int(scan["tele_chunks_max"]) * 64 * config.runs
    occ = int(scan["tele_active_steps_sum"]) / slots
    assert 0.0 < occ <= 1.0
    # Histogram counters are consistent with their scalar reductions: the
    # depth histogram's event total is the stale-event count, its deepest
    # occupied bucket matches reorg_depth_max, and every stale event shows
    # up for at least one miner.
    hist = np.asarray(scan["tele_reorg_depth_hist_sum"])
    assert hist.sum() == int(scan["tele_stale_events_sum"])
    occupied = np.nonzero(hist)[0]
    assert occupied[-1] + 1 == min(int(scan["tele_reorg_depth_max"]), len(hist))
    by_miner = np.asarray(scan["tele_stale_by_miner_sum"])
    assert by_miner.shape == (config.network.n_miners,)
    assert by_miner.sum() >= int(scan["tele_stale_events_sum"])


def test_combine_sums_merge_rule():
    a = {"blocks_found_sum": np.array([2, 3]), "tele_reorg_depth_max": np.int64(5),
         "tele_chunks_max": np.int64(7), "runs": np.int64(8)}
    b = {"blocks_found_sum": np.array([1, 1]), "tele_reorg_depth_max": np.int64(9),
         "tele_chunks_max": np.int64(4), "runs": np.int64(8)}
    m = combine_sums(a, b)
    assert m["blocks_found_sum"].tolist() == [3, 4]
    assert int(m["tele_reorg_depth_max"]) == 9
    assert int(m["tele_chunks_max"]) == 7
    assert int(m["runs"]) == 16


# ---------------------------------------------------------------------------
# Runner/sweep span wiring.


def test_runner_emits_correlated_spans(tmp_path):
    led = tmp_path / "run.jsonl"
    ck = tmp_path / "ck.npz"
    rec = TelemetryRecorder(led)
    run_simulation_config(
        SMALL, use_all_devices=False, telemetry=rec, checkpoint_path=ck
    )
    rec.close()
    spans = load_spans(led)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["span"], []).append(s)
    assert len(by_name["batch"]) == 2
    assert len(by_name["checkpoint_save"]) == 2
    assert len(by_name["run"]) == 1
    assert len({s["run_id"] for s in spans}) == 1
    batch = by_name["batch"][0]["attrs"]
    assert set(batch) >= {
        "start", "runs", "engine", "stall_s", "retries",
        "reorg_depth_max", "stale_events", "active_steps", "chunks", "step_slots",
    }
    assert isinstance(batch["stale_by_miner"], list)
    assert isinstance(batch["reorg_depth_hist"], list)
    run = by_name["run"][0]["attrs"]
    assert run["runs"] == SMALL.runs
    assert run["duration_ms"] == SMALL.duration_ms
    assert 0.0 < run["occupancy"] <= 1.0
    # The closing span is self-describing about its environment (the
    # ROADMAP's drift note, machine-readable): versions and device identity.
    import jax as _jax

    import tpusim as _tpusim

    assert run["jax_version"] == _jax.__version__
    assert run["tpusim_version"] == _tpusim.__version__
    assert run["device_count"] >= 1 and run["platform"] == "cpu"
    assert isinstance(run["device_kind"], str) and run["device_kind"]
    # Run-level histograms are the elementwise fold of the batch spans.
    assert run["stale_by_miner"] == [
        sum(v) for v in zip(*(s["attrs"]["stale_by_miner"] for s in by_name["batch"]))
    ]
    # The run-level counters are the fold of the batch spans.
    assert run["stale_events"] == sum(
        s["attrs"]["stale_events"] for s in by_name["batch"]
    )
    assert run["reorg_depth_max"] == max(
        s["attrs"]["reorg_depth_max"] for s in by_name["batch"]
    )

    # Resuming from the checkpoint emits a checkpoint_load span into the
    # same ledger (new recorder, so a fresh run_id for the second run).
    rec2 = TelemetryRecorder(led)
    run_simulation_config(
        dataclasses.replace(SMALL, runs=12), use_all_devices=False,
        telemetry=rec2, checkpoint_path=ck,
    )
    rec2.close()
    spans2 = load_spans(led)
    loads = [s for s in spans2 if s["span"] == "checkpoint_load"]
    assert len(loads) == 1 and loads[0]["attrs"]["runs_done"] == 8


def test_sweep_telemetry_ledger(tmp_path):
    from tpusim.sweep import run_sweep

    led = tmp_path / "sweep.jsonl"
    pts = [
        ("p0", dataclasses.replace(SMALL, runs=4, batch_size=4)),
        ("p1", dataclasses.replace(SMALL, runs=4, batch_size=4, seed=4)),
    ]
    run_sweep(pts, out_path=tmp_path / "out.jsonl", quiet=True, telemetry_path=led)
    spans = load_spans(led)
    points = [s for s in spans if s["span"] == "sweep_point"]
    assert [s["attrs"]["point"] for s in points] == ["p0", "p1"]
    # Backend batch spans share the sweep's run_id — one correlated ledger.
    assert any(s["span"] == "batch" for s in spans)
    assert len({s["run_id"] for s in spans}) == 1


# ---------------------------------------------------------------------------
# CLI: progress callback, --telemetry, and the report subcommand.


def test_cli_progress_callback(capsys):
    from tpusim.cli import main as cli_main

    rc = cli_main(
        ["--runs", "4", "--batch-size", "2", "--duration-ms", "86400000",
         "--single-device"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    # The reference's stdout progress format (main.cpp:219), batch-granular:
    # 2 of 4 runs -> 50%, then 100%.
    assert "50% progress.." in out
    assert "100% progress.." in out
    assert "After running 4 simulations" in out


def test_cli_telemetry_flag_and_report_subcommand(tmp_path, capsys):
    from tpusim.cli import main as cli_main

    led = tmp_path / "cli.jsonl"
    rc = cli_main(
        ["--runs", "4", "--batch-size", "2", "--duration-ms", "86400000",
         "--single-device", "--quiet", "--telemetry", str(led)]
    )
    assert rc == 0
    assert [s["span"] for s in load_spans(led)].count("batch") == 2
    capsys.readouterr()

    rc = cli_main(["report", str(led)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Phase breakdown" in out
    assert "Throughput (batch spans)" in out
    assert "stall histogram" in out
    assert "Simulation counters" in out

    md_out = tmp_path / "report.md"
    rc = cli_main(["report", str(led), "--format", "md", "--out", str(md_out)])
    assert rc == 0
    assert md_out.read_text().startswith("# tpusim telemetry report")
    assert "| span |" in md_out.read_text()

    # Missing path: loud exit code, no traceback.
    assert cli_main(["report", str(tmp_path / "nope.jsonl")]) == 2


def test_report_multi_run_ledger_groups_throughput():
    """An appended ledger holding several runs must derive throughput per
    run_id: each run's compile (first) batch is excluded from its own steady
    state, under its own duration_ms."""
    from tpusim.report import render_report

    spans = []
    for rid in ("aaa", "bbb"):
        spans.append({"run_id": rid, "span": "batch", "t_start": 0.0,
                      "dur_s": 5.0, "attrs": {"runs": 4}})
        spans.append({"run_id": rid, "span": "batch", "t_start": 5.0,
                      "dur_s": 1.0, "attrs": {"runs": 4}})
        spans.append({"run_id": rid, "span": "run", "t_start": 0.0, "dur_s": 6.0,
                      "attrs": {"duration_ms": 86_400_000,
                                "block_interval_s": 600.0}})
    text = render_report(spans)
    assert "Throughput — run aaa" in text
    assert "Throughput — run bbb" in text
    # Steady state excludes each run's own first batch: 4 runs / 1 s, twice
    # (a pooled derivation would count run bbb's 5 s compile batch as steady).
    assert text.count("4.0") >= 2
    assert '"steady_is_first_batch"' not in text  # rendered as table rows
    assert text.count("steady_runs_per_s") == 2


def test_report_spans_only_and_malformed_ledgers_render_no_data():
    """A spans-only ledger (no batch spans) and foreign spans missing
    attrs/dur_s must render 'no data' panels instead of raising."""
    from tpusim.report import render_report

    spans_only = [
        {"run_id": "x", "span": "checkpoint_save", "t_start": 1.0, "dur_s": 0.1},
        {"run_id": "x", "span": "run", "t_start": 1.0, "dur_s": 0.2},
    ]
    text = render_report(spans_only)
    assert "no data — ledger has no batch spans" in text

    malformed = [
        {"run_id": "x", "span": "batch"},          # no attrs, no dur_s
        {"run_id": "x", "span": "sweep_point"},    # same
    ]
    text = render_report(malformed)
    assert "no data — batch spans carry no stall_s attr" in text
    assert "Sweep points" in text


def test_dashboards_tolerate_partial_attrs_in_every_panel():
    """The JX010 dogfood regression: a foreign/torn ledger whose spans carry
    *partial* attrs — a run span with duration_ms but no block_interval_s
    (KeyError on the pre-fix dashboard), batch spans with null attrs or null
    watermark fields, stats spans missing runs_total — must render in both
    dashboards instead of raising. Every attr read in the dashboards is
    .get-based with a None-tolerant default; `tpusim lint` (JX010) pins the
    discipline statically, this pins it at runtime."""
    from tpusim.report import render_report
    from tpusim.watch import render_watch

    hostile = [
        {"run_id": "x", "span": "batch", "dur_s": 1.0},
        {"run_id": "x", "span": "batch", "attrs": None, "dur_s": 1.0},
        # Keys PRESENT with null values: int(None)/float(None) is the crash
        # class a .get(key, 0) default does not cover.
        {"run_id": "x", "span": "batch", "dur_s": 2.0, "attrs": {
            "mem_live_bytes": None, "mem_live_buffers": None,
            "reorg_depth_max": 2, "stall_s": None, "vmem_est_bytes": None,
            "runs": None, "retries": None, "stale_events": None,
            "active_steps": None, "step_slots": None}},
        {"run_id": "x", "span": "batch", "dur_s": None, "attrs": {"runs": 4}},
        {"run_id": "x", "span": "stats", "attrs": {"duration_ms": 1000}},
        {"run_id": "x", "span": "compile", "dur_s": 0.5},
        # Null ROW fields: run_id null must not poison the run grouping
        # (load_spans already drops "span": null rows at the source).
        {"run_id": None, "span": "checkpoint_save"},
        # The pre-fix crash: duration_ms present, block_interval_s absent.
        {"run_id": "x", "span": "run", "attrs": {"duration_ms": 86400000}},
    ]
    text = render_report(hostile)
    assert "Throughput" in text
    frame = render_watch(hostile, "hostile.jsonl", now=0.0)
    assert "run_id x" in frame


def test_load_spans_drops_null_span_rows(tmp_path):
    """A foreign line with "span": null is not a span: load_spans filters it
    at the source so no consumer ever groups on a None span name."""
    from tpusim.telemetry import load_spans

    p = tmp_path / "l.jsonl"
    p.write_text(
        '{"span": null, "run_id": "a"}\n'
        '{"span": 3, "run_id": "a"}\n'
        '{"span": "batch", "run_id": "a"}\n'
        '{"no_span": true}\n'
    )
    spans = load_spans(p)
    assert [sp["span"] for sp in spans] == ["batch"]


def test_report_renders_histogram_panels():
    from tpusim.report import render_report

    spans = [{
        "run_id": "h", "span": "batch", "t_start": 0.0, "dur_s": 1.0,
        "attrs": {"runs": 4, "reorg_depth_max": 2, "stale_events": 5,
                  "active_steps": 10, "step_slots": 20,
                  "stale_by_miner": [3, 0, 2], "reorg_depth_hist": [4, 1, 0]},
    }, {
        "run_id": "h", "span": "batch", "t_start": 1.0, "dur_s": 1.0,
        "attrs": {"runs": 4, "reorg_depth_max": 1, "stale_events": 1,
                  "active_steps": 10, "step_slots": 20,
                  "stale_by_miner": [1, 1, 0], "reorg_depth_hist": [1, 0, 0]},
    }]
    text = render_report(spans)
    assert "Stale events by miner" in text
    assert "Reorg depth histogram" in text
    # Elementwise fold across batch spans: miner 0 saw 3 + 1 stale events.
    lines = text.splitlines()
    row0 = next(ln for ln in lines if ln.strip().startswith("0 "))
    assert "4" in row0.split()
    # The open-ended last bucket is labeled as such.
    assert any("3+" in ln for ln in lines)


def test_report_renders_trace_dir(tmp_path, capsys):
    """The absorbed trace_report path: op attribution from a chrome-trace
    dump, preferring device tracks over host ones."""
    from tpusim.report import main as report_main

    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0 TensorCore"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "python host"}},
        {"ph": "X", "pid": 1, "name": "fusion.1", "dur": 700.0, "ts": 0},
        {"ph": "X", "pid": 1, "name": "fusion.1", "dur": 300.0, "ts": 800},
        {"ph": "X", "pid": 1, "name": "copy.2", "dur": 100.0, "ts": 1200},
        {"ph": "X", "pid": 2, "name": "hostloop", "dur": 9999.0, "ts": 0},
    ]
    tdir = tmp_path / "trace" / "plugins" / "profile" / "run1"
    tdir.mkdir(parents=True)
    with gzip.open(tdir / "host.trace.json.gz", "wt") as fh:
        json.dump({"traceEvents": events}, fh)
    rc = report_main([str(tmp_path / "trace")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "fusion.1" in out and "x2" in out
    assert "copy.2" in out
    assert "hostloop" not in out  # host track excluded when device tracks exist
    assert "1.100 ms summed on device tracks" in out
