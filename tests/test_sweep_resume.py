"""Sweep-driver resume semantics: re-running a sweep with ``resume`` after an
interrupted hardware window must fill exactly the missing points — skipping
any (point, runs, backend) row already in the output JSONL and never
appending duplicates (a resumed-complete checkpoint would otherwise add a row
whose elapsed_s reflects only the reload)."""

import json

from tpusim.config import SimConfig, default_network
from tpusim.sweep import baseline_sweeps, main as sweep_main, run_sweep


def _points():
    net = default_network(propagation_ms=1000)
    return [
        ("pt-a", SimConfig(network=net, runs=8, batch_size=8, duration_ms=10**8)),
        ("pt-b", SimConfig(network=net, runs=8, batch_size=8, duration_ms=10**8)),
    ]


def _rows(path):
    return [json.loads(line) for line in path.read_text().splitlines() if line.strip()]


def test_resume_skips_completed_points(tmp_path, capsys):
    out = tmp_path / "sweep.jsonl"
    run_sweep(_points()[:1], out_path=out, quiet=True)
    assert [r["point"] for r in _rows(out)] == ["pt-a"]

    # Second pass over the full grid: pt-a must be skipped, pt-b run.
    run_sweep(_points(), out_path=out, resume=True)
    assert [r["point"] for r in _rows(out)] == ["pt-a", "pt-b"]
    assert "skipping" in capsys.readouterr().out

    # Fully-complete grid: a resume pass is a no-op.
    run_sweep(_points(), out_path=out, resume=True, quiet=True)
    assert [r["point"] for r in _rows(out)] == ["pt-a", "pt-b"]


def test_resume_reruns_on_different_scale(tmp_path):
    out = tmp_path / "sweep.jsonl"
    run_sweep(_points()[:1], out_path=out, quiet=True)
    # A different runs_scale is a different measurement, not a duplicate.
    run_sweep(_points()[:1], out_path=out, resume=True, runs_scale=0.5, quiet=True)
    rows = _rows(out)
    assert [r["runs"] for r in rows] == [8, 4]


def test_append_after_truncated_line_stays_parseable(tmp_path):
    # Appending after a truncated final line must not glue the new row onto
    # the fragment: the completed point's row has to survive the next
    # --resume scan and update_fullscale_published's bare json.loads.
    out = tmp_path / "sweep.jsonl"
    out.write_text('{"point": "selfish-28pct", "ru')  # no trailing newline
    run_sweep(_points()[:1], out_path=out, resume=True, quiet=True)
    lines = out.read_text().splitlines()
    assert json.loads(lines[-1])["point"] == "pt-a"


def test_resume_tolerates_corrupt_and_legacy_rows(tmp_path):
    # A window killed mid-write (timeout -k) leaves a truncated trailing
    # line; pre-round-5 rows carry no "point" key. Both must read as
    # not-done — the point runs — rather than crashing the resume pass.
    out = tmp_path / "sweep.jsonl"
    out.write_text(json.dumps({"legacy": 1}) + "\n" + '{"point": "pt-a", "ru')
    rows = run_sweep(_points()[:1], out_path=out, resume=True, quiet=True)
    assert [r["point"] for r in rows] == ["pt-a"]


def test_cli_resume_flag_plumbed(tmp_path, capsys):
    # The CLI --resume flag must reach run_sweep: with every grid point
    # already rowed in --out, the command is a fast no-op.
    out = tmp_path / "sweep.jsonl"
    points = baseline_sweeps()["selfish-hashrate"]()
    rows = [
        {"point": name, "runs": max(1, int(c.runs * 1e-5)), "backend": "tpu"}
        for name, c in points
    ]
    out.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    rc = sweep_main(
        ["selfish-hashrate", "--runs-scale", "1e-5", "--no-probe",
         "--resume", "--out", str(out)]
    )
    assert rc == 0
    assert capsys.readouterr().out.count("skipping") == len(points)
    assert len(_rows(out)) == len(points)  # nothing appended
