"""Fleet-wide distributed tracing (tpusim.tracing): context propagation,
schema-v2 span stamping, clock rebasing, span-tree assembly, critical-path
attribution, the orchestration Perfetto export and the report/watch surfaces.

Everything here except the explicit hot-path pin is jax-free by design —
the module under test must run on a host with no backend."""

from __future__ import annotations

import json
import shutil
from pathlib import Path

import pytest

from tpusim.report import render_report
from tpusim.telemetry import SCHEMA_VERSION, TelemetryRecorder, load_spans
from tpusim.tracing import (
    TRACE_ENV,
    TraceContext,
    assemble,
    attribution,
    collect_spans,
    critical_path,
    perfetto_timeline,
    render_timeline,
    timeline_main,
    validate_perfetto,
    worker_utilization,
)
from tpusim.watch import render_watch


# ---------------------------------------------------------------------------
# Trace-context propagation + recorder stamping.


def test_trace_context_env_round_trip():
    ctx = TraceContext(trace_id="t1", parent_span="w003", run_id="r9")
    back = TraceContext.from_env({TRACE_ENV: ctx.to_env()})
    assert back == ctx
    # Optional fields stay optional.
    assert TraceContext.from_env({TRACE_ENV: '{"trace_id": "t"}'}) == TraceContext("t")


def test_trace_context_malformed_env_is_tolerated():
    # A worker must never die over its tracing: garbage reads as no context.
    for raw in ("", "not json", "[]", '{"parent_span": "x"}', '{"trace_id": 3}'):
        assert TraceContext.from_env({TRACE_ENV: raw}) is None
    assert TraceContext.from_env({}) is None


def test_recorder_stamps_schema_v2_fields(tmp_path):
    rec = TelemetryRecorder(tmp_path / "t.jsonl")
    rec.emit("batch", runs=4)
    rec.close()
    (sp,) = load_spans(tmp_path / "t.jsonl")
    assert sp["schema"] == SCHEMA_VERSION
    assert sp["trace_id"] == rec.run_id  # trace root: trace_id IS run_id
    assert sp["process"] == rec.process and sp["process"].startswith("p")
    assert isinstance(sp["t_mono"], float)
    assert "parent_span" not in sp  # root spans carry no parent


def test_recorder_adopts_env_context(tmp_path, monkeypatch):
    ctx = TraceContext(trace_id="tr-abc", parent_span="w007", run_id="run-xyz")
    monkeypatch.setenv(TRACE_ENV, ctx.to_env())
    rec = TelemetryRecorder(tmp_path / "t.jsonl")
    rec.emit("worker_start")
    rec.close()
    (sp,) = load_spans(tmp_path / "t.jsonl")
    assert sp["run_id"] == "run-xyz"
    assert sp["trace_id"] == "tr-abc"
    assert sp["parent_span"] == "w007"
    # An explicit run_id always wins over the context's.
    rec2 = TelemetryRecorder(tmp_path / "t2.jsonl", run_id="mine")
    assert rec2.run_id == "mine" and rec2.trace_id == "tr-abc"


def test_versionless_ledger_still_loads_and_groups(tmp_path):
    # A pre-tracing (schema v1) ledger: no t_mono/schema/process/trace_id.
    path = tmp_path / "old.jsonl"
    rows = [
        {"run_id": "r", "span": "batch", "t_start": 10.0, "dur_s": 2.0,
         "attrs": {"runs": 4}},
        {"run_id": "r", "span": "run", "t_start": 8.0, "dur_s": 5.0,
         "attrs": {"duration_ms": 86_400_000, "block_interval_s": 600.0}},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    spans = load_spans(path)
    assert len(spans) == 2
    report = render_report(spans)
    assert "Throughput" in report  # the (run_id, "") group renders as before
    assert assemble(spans) is None  # no fleet spans -> nothing to correlate


# ---------------------------------------------------------------------------
# Handcrafted two-worker fleet: assembly, skew rebasing, attribution.

RID = "ridfleet"


def _mk(span, t_start, t_mono, dur, process, parent=None, **attrs):
    row = {
        "run_id": RID, "span": span, "t_start": t_start, "t_mono": t_mono,
        "dur_s": dur, "schema": 2, "process": process, "trace_id": RID,
        "attrs": attrs,
    }
    if parent is not None:
        row["parent_span"] = parent
    return row


def _supervisor_spans():
    # Supervisor clock: wall = mono + 49000. Fleet window [50000, 50020].
    def sup(span, mono, dur=0.0, **attrs):
        return _mk(span, 49000 + mono + dur, mono + dur, dur, "psup", **attrs)

    return [
        sup("fleet_spawn", 1001, worker="w000", target="pt-a", attempt=0),
        sup("fleet_spawn", 1002, worker="w001", target="pt-b", attempt=0),
        sup("fleet_requeue", 1010, worker="w000", target="pt-a",
            reason="exit:-9", failures=1, backoff_s=2.0),
        sup("fleet_done", 1012, worker="w001", target="pt-b", attempt=0),
        sup("fleet_spawn", 1013, worker="w002", target="pt-a", attempt=1),
        sup("fleet_quarantine", 1018, target="pt-zz", failures=3,
            reason="exit:1"),
        sup("fleet_done", 1019, worker="w002", target="pt-a", attempt=1),
        sup("run", 1000, dur=20.0, fleet=True, points_done=2),
    ]


def _worker_spans():
    # Helpers take the span's END on the process's own monotonic clock (the
    # t_mono write-time convention). w000's wall clock runs 500 s BEHIND the
    # supervisor — its raw t_start values would place it before its own
    # spawn; true wall = 50001 + mono, reported wall = 49501 + mono.
    def w0(span, mono_end, dur=0.0, **attrs):
        return _mk(span, 49501.0 + mono_end, mono_end, dur, "pw0",
                   parent="w000", **attrs)

    # w001: honest clock, wall = mono + 49981.5 (spawned at 50002).
    def w1(span, mono_end, dur=0.0, **attrs):
        return _mk(span, 49981.5 + mono_end, mono_end, dur, "pw1",
                   parent="w001", **attrs)

    # w002 (the healer): honest clock, wall = mono + 50008.2 (spawn 50013).
    def w2(span, mono_end, dur=0.0, **attrs):
        return _mk(span, 50008.2 + mono_end, mono_end, dur, "pw2",
                   parent="w002", **attrs)

    return [
        w0("worker_start", 0.2, pid=100, point="pt-a"),
        w0("compile", 3.0, dur=0.5),                       # [50003.3, 50003.8]
        w0("batch", 7.0, dur=3.5, runs=2, stall_s=0.5),    # [50004.3, 50007.8]
        w0("checkpoint_save", 7.4, dur=0.3, runs_done=2),  # [50007.9, 50008.2]
        w0("chaos", 7.5, point="checkpoint.save", kind="sigkill"),
        w1("worker_start", 21.0, pid=101, point="pt-b"),   # 50002.5
        w1("compile", 26.0, dur=1.0),                      # [50006.5, 50007.5]
        w1("batch", 30.0, dur=6.0, runs=4, stall_s=1.0),   # [50005.5, 50011.5]
        w1("run", 30.2, dur=9.0, runs=4),
        w2("worker_start", 5.0, pid=102, point="pt-a"),    # 50013.2
        w2("checkpoint_load", 7.0, dur=0.4, runs_done=2),  # [50014.8, 50015.2]
        w2("batch", 10.5, dur=3.0, runs=2),                # [50015.7, 50018.7]
        w2("run", 10.7, dur=5.5, runs=2),
    ]


@pytest.fixture()
def fleet_spans():
    return _supervisor_spans() + _worker_spans()


def test_assemble_builds_the_span_tree(fleet_spans):
    trace = assemble(fleet_spans)
    assert trace is not None
    assert trace.trace_id == RID and trace.run_id == RID
    assert set(trace.workers) == {"w000", "w001", "w002"}
    assert trace.workers["w000"].process == "pw0"
    assert trace.workers["w002"].process == "pw2"
    assert trace.workers["w000"].end_reason == "requeue"
    assert trace.workers["w001"].end_reason == "done"
    assert (trace.t0, trace.t1) == (50000.0, 50020.0)
    # The quarantine and the worker's chaos fault land as instants.
    assert {i["span"] for i in trace.instants} == {"chaos", "fleet_quarantine"}


def test_clock_skew_rebased_on_the_spawn_handshake(fleet_spans):
    trace = assemble(fleet_spans)
    # w000's wall clock ran 500 s behind: the merger must shift the whole
    # process forward so its handshake span sits at its fleet_spawn...
    assert trace.processes["pw0"]["skew_s"] == pytest.approx(500.0, abs=0.5)
    ws = next(
        sp for sp in trace.spans
        if sp["span"] == "worker_start" and sp["process"] == "pw0"
    )
    assert ws["_t1"] >= 50001.0 - 1e-6
    # ...so no w000 span can precede the spawn and no duration is negative.
    for sp in trace.spans:
        assert sp["_t1"] >= sp["_t0"]
        if sp["process"] == "pw0":
            assert sp["_t0"] >= 50001.0 - 1e-6
    # The honest clocks are NOT shifted.
    assert trace.processes["pw1"]["skew_s"] == 0.0
    assert trace.processes["pw2"]["skew_s"] == 0.0


def test_stepped_wall_clock_cannot_reorder_a_timeline():
    # One process whose wall clock steps BACKWARD 300 s mid-run while the
    # monotonic readings advance: rebased order must follow t_mono.
    spans = _supervisor_spans() + [
        _mk("worker_start", 50001.3, 1.3, 0.0, "pw0", parent="w000"),
        _mk("batch", 50004.0, 4.0, 2.0, "pw0", parent="w000", runs=2),
        _mk("batch", 49706.5, 6.5, 2.0, "pw0", parent="w000", runs=2),  # step!
    ]
    trace = assemble(spans)
    w0 = sorted(
        (sp for sp in trace.spans if sp["process"] == "pw0"),
        key=lambda sp: sp["_t0"],
    )
    assert [sp["t_mono"] for sp in w0] == sorted(sp["t_mono"] for sp in w0)
    assert all(sp["_t1"] >= sp["_t0"] for sp in w0)


def test_category_attribution_and_critical_path(fleet_spans):
    trace = assemble(fleet_spans)
    att = attribution(trace)
    cats = att["categories"]
    assert att["total_s"] == pytest.approx(20.0)
    # Every category seconds sums exactly to the fleet window.
    assert sum(cats.values()) == pytest.approx(20.0)
    # The requeue backoff window is attributed...
    assert cats["backoff"] == pytest.approx(2.0, abs=0.2)
    # ...spawn covers process start -> first work, per worker...
    assert cats["spawn"] > 2.0
    # ...the pre-spawn setup and the post-fleet drain are supervisor idle...
    assert cats["supervisor_idle"] >= 1.0
    # ...and the remainder is explicit and small here.
    assert cats["unattributed"] < 2.0
    assert att["coverage"] > 0.9
    # The healer's checkpoint_load sits ON the timeline (the healing
    # evidence): a checkpoint interval from pw2 exists and the critical
    # path walk covers the window end-to-end.
    assert any(
        iv.category == "checkpoint" and iv.span == "checkpoint_load"
        and iv.process == "pw2"
        for iv in trace.intervals
    )
    segs = critical_path(trace)
    assert segs[0].start == pytest.approx(trace.t0)
    assert segs[-1].end == pytest.approx(trace.t1)
    for a, b in zip(segs, segs[1:]):
        assert b.start == pytest.approx(a.end)


def test_batch_intervals_carve_out_compile_and_stall(fleet_spans):
    trace = assemble(fleet_spans)
    w1 = [iv for iv in trace.intervals if iv.process == "pw1"]
    stall = [iv for iv in w1 if iv.category == "host_stall"]
    assert len(stall) == 1 and stall[0].end - stall[0].start == pytest.approx(1.0)
    # w1's compile [50006.5, 50007.5] lies inside its batch [50005.5,
    # 50011.5]: the dispatch pieces must not double-cover it.
    compile_iv = next(iv for iv in w1 if iv.category == "compile")
    for iv in w1:
        if iv.category == "dispatch":
            assert iv.end <= compile_iv.start + 1e-9 or iv.start >= compile_iv.end - 1e-9


def test_worker_utilization_rows(fleet_spans):
    trace = assemble(fleet_spans)
    rows = {r["worker"]: r for r in worker_utilization(trace)}
    assert rows["w001"]["point"] == "pt-b" and rows["w001"]["end_reason"] == "done"
    assert rows["w001"]["alive_s"] == pytest.approx(10.0)  # spawn 1002 -> done 1012
    assert 0.0 < rows["w001"]["utilization"] <= 1.0
    assert set(rows["w001"]["by_category"]) >= {"dispatch", "compile", "spawn"}
    # Supervisor-only ledger (tpusim watch's view): lease windows known,
    # busy unknown — rendered n/a, never invented.
    sup_only = assemble(_supervisor_spans())
    rows2 = worker_utilization(sup_only)
    assert all(r["busy_s"] is None and r["utilization"] is None for r in rows2)


# ---------------------------------------------------------------------------
# Ledger collection: directory scan, dedupe, torn/foreign tolerance.


def _write_ledgers(root: Path, fleet_spans) -> Path:
    (root / "workers").mkdir(parents=True, exist_ok=True)
    by_proc: dict[str, list[dict]] = {}
    for sp in fleet_spans:
        by_proc.setdefault(sp["process"], []).append(sp)
    for proc, group in by_proc.items():
        name = "fleet.tele.jsonl" if proc == "psup" else f"workers/{proc}.tele.jsonl"
        (root / name).write_text(
            "".join(json.dumps(sp) + "\n" for sp in group)
        )
    return root


def test_collect_spans_merges_dedupes_and_tolerates_foreign(tmp_path, fleet_spans):
    root = _write_ledgers(tmp_path / "state", fleet_spans)
    # Foreign JSONL files a real state dir holds: the fleet work ledger
    # (event rows), heartbeat files, sweep rows — plus a torn trailing line.
    (root / "fleet-ledger.jsonl").write_text(
        '{"event": "lease", "point": "pt-a", "t": 1.0}\n{"event": "done"'
    )
    (root / "workers" / "w000.hb.jsonl").write_text('{"t": 1.0, "beats": 3}\n')
    (root / "rows.jsonl").write_text('{"point": "pt-a", "runs": 4}\n')
    with (root / "fleet.tele.jsonl").open("a") as fh:
        fh.write('{"run_id": "x", "span": "batc')  # torn mid-write
    spans = collect_spans([root])
    assert len(spans) == len(fleet_spans)
    # The supervisor ledger passed AGAIN explicitly must not double-count.
    spans2 = collect_spans([root, root / "fleet.tele.jsonl"])
    assert len(spans2) == len(spans)
    # A copied ledger inside the dir (an artifact harvest) dedupes too.
    shutil.copy(root / "fleet.tele.jsonl", root / "copy.tele.jsonl")
    assert len(collect_spans([root])) == len(spans)


def test_assemble_tolerates_partial_and_foreign_spans(fleet_spans):
    # Attribute-less, t_mono-less and unknown spans must degrade, not raise.
    spans = fleet_spans + [
        {"run_id": RID, "span": "mystery", "t_start": 50003.0, "dur_s": 0.5,
         "trace_id": RID, "process": "pw1", "attrs": None},
        {"run_id": RID, "span": "batch", "t_start": 50004.0, "dur_s": 0.0,
         "trace_id": RID, "process": "pother"},  # no parent, no t_mono
        {"span": "orphan"},
    ]
    trace = assemble(spans)
    assert trace is not None
    assert attribution(trace)["total_s"] == pytest.approx(20.0)
    render_timeline(trace)  # renders without raising


# ---------------------------------------------------------------------------
# Perfetto export + CLI.


def test_perfetto_timeline_validates_and_carries_the_tree(fleet_spans):
    trace = assemble(fleet_spans)
    exported = perfetto_timeline(trace)
    n = validate_perfetto(exported)
    assert n > 0
    assert exported["otherData"]["trace_id"] == RID
    assert exported["otherData"]["attribution"]["coverage"] > 0.9
    names = [ev.get("name") for ev in exported["traceEvents"]]
    # One lease slice per worker, a backoff slice, and the fault instants.
    assert sum(1 for x in names if str(x).startswith("lease ")) == 3
    assert "requeue backoff" in names
    assert any(str(x).startswith("chaos ") for x in names)
    assert "fleet_quarantine" in names
    # Slices are X events with numeric dur (the validator now requires it).
    assert all(
        isinstance(ev.get("dur"), int)
        for ev in exported["traceEvents"] if ev.get("ph") == "X"
    )


def test_validate_perfetto_rejects_x_without_dur():
    bad = {"traceEvents": [
        {"ph": "X", "name": "s", "ts": 1, "pid": 0, "tid": 0},
    ]}
    with pytest.raises(ValueError, match="dur"):
        validate_perfetto(bad)


def test_timeline_cli_end_to_end(tmp_path, fleet_spans, capsys):
    root = _write_ledgers(tmp_path / "state", fleet_spans)
    out = tmp_path / "orch.trace.json"
    rc = timeline_main([str(root), "--out", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "Wall-clock attribution (critical path)" in text
    assert "backoff" in text and "checkpoint" in text
    assert "clock skew corrected" in text  # pw0's +500 s shift is narrated
    exported = json.loads(out.read_text())
    assert validate_perfetto(exported) > 0


def test_timeline_cli_errors(tmp_path, capsys):
    assert timeline_main([str(tmp_path / "nope")]) == 2
    # A dir with ledgers but no fleet spans: nothing to correlate.
    led = tmp_path / "plain.jsonl"
    led.write_text(json.dumps(
        {"run_id": "r", "span": "batch", "t_start": 1.0, "dur_s": 1.0}
    ) + "\n")
    assert timeline_main([str(tmp_path)]) == 2
    assert "no fleet trace" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Report / watch surfaces.


def test_report_partitions_by_run_id_and_process(fleet_spans):
    # THE regression guard for merged fleet ledgers: every process of a
    # traced fleet shares ONE run_id, so a bare run_id grouping would blend
    # (and double-count) the workers' batch streams into one panel.
    report = render_report(fleet_spans)
    assert report.count("Throughput — run") == 3  # one per worker process
    assert f"{RID} · pw0" in report and f"{RID} · pw1" in report
    # Each panel derives from ITS worker's batches only (1 batch each).
    assert '| batches' not in report  # text mode sanity
    for line in report.splitlines():
        if line.strip().startswith("batches"):
            assert line.split()[-1] == "1"


def test_report_merged_fleet_dir_renders_attribution(tmp_path, fleet_spans):
    root = _write_ledgers(tmp_path / "state", fleet_spans)
    from tpusim.report import main as report_main

    assert report_main([str(root)]) == 0
    report = render_report(collect_spans([root]))
    assert "Fleet time attribution (critical path)" in report
    assert "Per-worker utilization" in report
    assert "attributed" in report
    # The duplicate-ledger dedupe keeps the phase breakdown honest.
    shutil.copy(root / "fleet.tele.jsonl", root / "copy.tele.jsonl")
    assert render_report(collect_spans([root])) == report


def test_watch_renders_worker_lease_utilization(fleet_spans):
    frame = render_watch(_supervisor_spans(), "sup.jsonl", now=50021.0)
    assert "worker leases (share of fleet window):" in frame
    assert "w001 pt-b 10.0s" in frame
    # And the full merged view still renders (watch is jax-free, so is this).
    render_watch(fleet_spans, "merged", now=50021.0)


# ---------------------------------------------------------------------------
# Hot-path pin: tracing armed changes NOTHING the device sees.


def test_device_hot_path_byte_identical_with_tracing_armed(tmp_path, monkeypatch):
    import jax

    from tpusim.config import SimConfig, default_network
    from tpusim.engine import Engine
    from tpusim.runner import make_run_keys
    from tpusim.testing import compile_count_guard

    cfg = SimConfig(
        network=default_network(), duration_ms=86_400_000, runs=4,
        batch_size=4, chunk_steps=64,
    )
    keys = make_run_keys(0, 0, 4)

    def loop_jaxpr():
        eng = Engine(cfg)
        hi, lo = eng._ledger_init(4)
        return str(jax.make_jaxpr(
            lambda k: eng._device_loop(k, hi, lo, eng.params)
        )(keys))

    plain = loop_jaxpr()
    monkeypatch.setenv(
        TRACE_ENV,
        TraceContext(trace_id="t", parent_span="w000", run_id="r").to_env(),
    )
    rec = TelemetryRecorder(tmp_path / "t.jsonl")
    rec.emit("worker_start")
    armed = loop_jaxpr()
    assert armed == plain
    eng = Engine(cfg)
    eng.run_batch(keys)
    with compile_count_guard(exact=0):
        eng.run_batch(keys)
        rec.emit("batch", runs=4)
    rec.close()
