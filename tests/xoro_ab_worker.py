"""Subprocess worker for the xoroshiro engine<->native bit-level A/B test.

Runs in its own interpreter with JAX_ENABLE_X64=1 JAX_PLATFORMS=cpu (set by
the parent test): float64 is required for the bit-exact interval mapping
(tpusim.xoroshiro.interval_ms_from_word) and must not leak into the main test
process, whose conftest configures the shared 8-virtual-device CPU backend.

Prints one JSON line: the engine's raw stat sums for the config serialized in
argv[1].
"""
import json
import sys

import numpy as np


def main() -> None:
    from tpusim.config import SimConfig
    from tpusim.engine import Engine

    config = SimConfig.from_json(sys.argv[1])
    engine = Engine(config)
    sums = engine.run_batch(engine.make_keys(0, config.runs))
    print(json.dumps({
        k: (np.asarray(v).tolist()) for k, v in sums.items()
    }))


if __name__ == "__main__":
    main()
