"""Jax-free fake fleet worker (tests/test_fleet.py).

Stands in for ``python -m tpusim.fleet --worker`` so the supervisor's queue /
lease / requeue / quarantine / resume logic can be driven in milliseconds
instead of seconds-per-jax-process. Behaviors (selected per point by the
test's ``worker_cmd`` factory):

  * ``ok``            — beat once, publish a row, exit 0
  * ``fail``          — beat once, exit 1 (a crashing worker)
  * ``hang``          — beat once, then freeze forever (a wedged worker: the
                        supervisor's lease watchdog must SIGKILL it)
  * ``fail-then-ok``  / ``hang-then-ok`` — misbehave on attempt 0 only, so
                        the requeued attempt heals

The published row records ``attempt`` and whether the worker-chaos env var
was present, so tests can pin which attempt healed and that replacement
workers run clean.
"""

import argparse
import json
import os
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--point", required=True)
    p.add_argument("--result", required=True)
    p.add_argument("--heartbeat", required=True)
    p.add_argument("--attempt", type=int, default=0)
    p.add_argument("--behavior", default="ok")
    p.add_argument("--runs", type=int, default=4)
    p.add_argument(
        "--grid", default=None,
        help="packed sub-grid manifest (tpusim.packed units): publish one "
        "row per member point in a {'rows': [...]} payload",
    )
    args = p.parse_args()

    with open(args.heartbeat, "a") as fh:
        fh.write(json.dumps({
            "t": time.time(), "beats": 0,
            "runs_done": 0, "runs_total": args.runs,
        }) + "\n")

    behavior = args.behavior
    if behavior == "fail-then-ok":
        behavior = "fail" if args.attempt == 0 else "ok"
    if behavior == "hang-then-ok":
        behavior = "hang" if args.attempt == 0 else "ok"
    if behavior == "fail":
        return 1
    if behavior == "hang":
        while True:
            time.sleep(60)

    def row_for(point: str) -> dict:
        return {
            "runs": args.runs, "point": point, "backend": "tpu",
            "elapsed_s": 0.01, "attempt": args.attempt,
            "chaos_env": "TPUSIM_FLEET_WORKER_CHAOS" in os.environ,
        }

    if args.grid is not None:
        with open(args.grid) as fh:
            manifest = json.load(fh)
        payload = {"rows": [row_for(e["point"]) for e in manifest["points"]]}
    else:
        payload = row_for(args.point)
    tmp = args.result + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, args.result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
