"""Native C++ backend: build, determinism, and cross-validation vs the JAX
engine and the reference golden values.

This is the framework's two-backend check (the SimBackend boundary): one
config, two independent implementations — the JAX O(1)-automaton engine and
the native materialized-chain simulator — must agree within Monte-Carlo
tolerance. The reference has no such harness; its README tables play this
role manually (SURVEY.md §4).
"""

from __future__ import annotations

import math
import shutil

import numpy as np
import pytest

from tpusim.config import SimConfig, default_network
from tpusim.engine import Engine
from tpusim.runner import make_run_keys

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="native toolchain unavailable",
)


@pytest.fixture(scope="module")
def cpp_run():
    from tpusim.backend.cpp import NativeBuildError, run_simulation_cpp

    try:
        probe = SimConfig(
            network=default_network(), duration_ms=3_600_000, runs=1, batch_size=1
        )
        run_simulation_cpp(probe, threads=1)
    except NativeBuildError as e:  # pragma: no cover - toolchain-specific
        pytest.skip(f"native build failed: {e}")
    return run_simulation_cpp


HONEST_10S = SimConfig(
    network=default_network(propagation_ms=10_000),
    duration_ms=30 * 86_400_000,
    runs=256,
    seed=11,
)


def test_deterministic_and_thread_invariant(cpp_run):
    a = cpp_run(HONEST_10S, threads=1)
    b = cpp_run(HONEST_10S, threads=1)
    c = cpp_run(HONEST_10S, threads=4)
    for x, y, z in zip(a.miners, b.miners, c.miners):
        assert x.blocks_found_mean == y.blocks_found_mean == z.blocks_found_mean
        assert x.stale_rate_mean == y.stale_rate_mean == z.stale_rate_mean
        assert x.blocks_share_mean == y.blocks_share_mean == z.blocks_share_mean


def test_cpp_matches_jax_engine_honest(cpp_run):
    """Same honest config on both backends: per-miner stale rates and shares
    agree within a combined 5-sigma Monte-Carlo envelope."""
    res_cpp = cpp_run(HONEST_10S, threads=4)

    jax_runs = 128
    config = SimConfig(
        network=HONEST_10S.network,
        duration_ms=HONEST_10S.duration_ms,
        runs=jax_runs,
        batch_size=jax_runs,
        seed=19,
    )
    sums = Engine(config).run_batch(make_run_keys(config.seed, 0, jax_runs))
    stale_jax = np.asarray(sums["stale_rate_sum"]) / jax_runs
    share_jax = np.asarray(sums["blocks_share_sum"]) / jax_runs

    blocks_per_run = HONEST_10S.duration_ms / 600_000.0
    for i, mc in enumerate(HONEST_10S.network.miners):
        h = mc.hashrate_pct / 100.0
        own = blocks_per_run * h
        p = res_cpp.miners[i].stale_rate_mean
        sigma = math.sqrt(max(p, 1e-5) / own) * math.sqrt(1 / HONEST_10S.runs + 1 / jax_runs)
        assert abs(p - stale_jax[i]) < 5 * sigma + 0.1 * p, (i, p, stale_jax[i])
        se_share = math.sqrt(h * (1 - h) / blocks_per_run) * math.sqrt(
            1 / HONEST_10S.runs + 1 / jax_runs
        )
        assert abs(res_cpp.miners[i].blocks_share_mean - share_jax[i]) < 5 * se_share


def test_cpp_selfish_matches_golden(cpp_run):
    """40% gamma=0 selfish miner on the native backend reproduces the
    reference README table (README.md:89-107): share ~46.7%, selfish stale
    ~27.5%, honest stale ~67.5%."""
    config = SimConfig(
        network=default_network(
            propagation_ms=1000, selfish_ids=(0,), hashrates=(40, 19, 12, 11, 8, 5, 3, 1, 1)
        ),
        duration_ms=90 * 86_400_000,
        runs=128,
        seed=13,
    )
    res = cpp_run(config, threads=4)
    assert abs(res.miners[0].blocks_share_mean - 0.467) < 0.015
    assert abs(res.miners[0].stale_rate_mean - 0.275) < 0.02
    honest = [m.stale_rate_mean for m in res.miners[1:]]
    assert abs(float(np.mean(honest)) - 0.675) < 0.02


def test_sanitized_build_and_smoke(cpp_run):
    """The race/memory CI leg (SURVEY.md §5): build and run the native smoke
    under ASan+UBSan and under TSan (the latter exercises the threaded
    runner). The reference has no sanitizer coverage at all."""
    import subprocess
    from pathlib import Path

    native = Path(__file__).resolve().parent.parent / "native"
    proc = subprocess.run(
        ["make", "-C", str(native), "check"], capture_output=True, text=True, timeout=600
    )
    assert proc.returncode == 0, f"sanitized check failed:\n{proc.stdout}\n{proc.stderr}"
    assert proc.stdout.count("smoke ok") == 2


def test_backend_registry_roundtrip(cpp_run):
    from tpusim.backend import get_backend

    assert get_backend("cpp") is not None
    assert get_backend("pychain") is not None
    assert get_backend("tpu") is not None
    with pytest.raises(KeyError):
        get_backend("cuda")
