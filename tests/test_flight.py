"""Flight recorder (tpusim.flight / tpusim.flight_export): consistency with
the PR-2 scalar counters, scan-vs-pallas bit-equality, ring overflow
semantics, the zero-capacity compiled-out guarantee, and the ``tpusim
trace`` export pipeline."""

from __future__ import annotations

import dataclasses
import json

import numpy as np
import jax
import pytest

from tpusim.config import SimConfig, default_network, reference_selfish_network
from tpusim.engine import DEPTH_BUCKETS, Engine, combine_sums
from tpusim.flight import FLIGHT_TIME_BASE, KIND_NAMES, N_FIELDS
from tpusim.flight_export import (
    decode_flight,
    events_jsonl,
    perfetto_trace,
    validate_perfetto,
)
from tpusim.runner import make_run_keys
from tpusim.testing import compile_count_guard

#: Racy selfish roster: reorgs, multi-deep pops, mid-chunk freezes — every
#: event kind and both histogram counters are exercised.
RACY = SimConfig(
    network=reference_selfish_network(),
    duration_ms=2 * 86_400_000,
    runs=32,
    batch_size=32,
    mode="exact",
    chunk_steps=64,
    seed=23,
    flight_capacity=2048,
)


def _decode_all(out, runs):
    buf = np.asarray(out["flight_buf"])
    cnt = np.asarray(out["flight_count"])
    return buf, cnt


# ---------------------------------------------------------------------------
# Consistency against the scalar counters.


def test_flight_rows_tie_out_against_counters():
    """The trace IS the counters, event by event: stale-row count equals
    tele_stale_events_sum and the per-depth tally of stale rows equals the
    reorg-depth histogram counter — the cross-check that makes the ring a
    trustworthy debugging oracle rather than a second opinion."""
    eng = Engine(RACY)
    keys = make_run_keys(RACY.seed, 0, RACY.runs)
    out = eng.run_batch(keys)
    log = decode_flight(out, start=0)
    assert not log.dropped  # capacity sized above the 2-day event count

    stale_rows = [e for e in log.events if e["kind"] == "stale"]
    assert len(stale_rows) == int(out["tele_stale_events_sum"]) > 0

    hist = np.zeros(DEPTH_BUCKETS, np.int64)
    for e in stale_rows:
        assert e["depth"] >= 1
        hist[min(e["depth"], DEPTH_BUCKETS) - 1] += 1
    np.testing.assert_array_equal(hist, np.asarray(out["tele_reorg_depth_hist_sum"]))
    assert max(e["depth"] for e in stale_rows) == int(out["tele_reorg_depth_max"])

    # Reorg rows (adoption without losses) carry depth 0 by definition.
    assert all(e["depth"] == 0 for e in log.events if e["kind"] != "stale")

    # Per-run event times are nondecreasing and bounded by the duration;
    # kinds decode to the documented vocabulary.
    by_run: dict[int, list] = {}
    for e in log.events:
        by_run.setdefault(e["run"], []).append(e)
        assert e["kind"] in KIND_NAMES
        assert 0 <= e["miner"] < RACY.network.n_miners
    assert sorted(by_run) == list(range(RACY.runs))
    for r, evs in by_run.items():
        assert [e["seq"] for e in evs] == list(range(len(evs)))
        t = [e["t_ms"] for e in evs]
        assert all(a <= b for a, b in zip(t, t[1:]))
        assert 0 <= t[-1] <= RACY.duration_ms


def test_flight_stats_and_dispatch_paths_unchanged():
    """Recording must be purely observational: every statistic and counter is
    bit-identical with the recorder on or off, and the ring itself is
    dispatch-path-invariant (device loop / pipelined / host loop)."""
    keys = make_run_keys(RACY.seed, 0, RACY.runs)
    eng = Engine(RACY)
    out = eng.run_batch(keys)
    off = Engine(dataclasses.replace(RACY, flight_capacity=0)).run_batch(keys)
    assert not any(k.startswith("flight_") for k in off)
    for k in off:
        np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(off[k]), err_msg=k)
    for kwargs in ({"pipelined": True}, {"host_loop": True}):
        alt = eng.run_batch(keys, **kwargs)
        np.testing.assert_array_equal(out["flight_buf"], alt["flight_buf"])
        np.testing.assert_array_equal(out["flight_count"], alt["flight_count"])


def test_flight_scan_vs_pallas_bit_equal():
    """Same masks, same operands, runs-last: the kernel's ring is bit-equal
    to the scan engine's — on the racy exact config AND on the fast-mode
    split-slot path."""
    from tpusim.pallas_engine import PallasEngine

    for config in (
        dataclasses.replace(RACY, runs=128, batch_size=128, flight_capacity=1024),
        SimConfig(
            network=default_network(propagation_ms=10_000),
            duration_ms=86_400_000, runs=128, batch_size=128, mode="fast",
            chunk_steps=64, seed=7, flight_capacity=256,
        ),
    ):
        keys = make_run_keys(config.seed, 0, config.runs)
        scan = Engine(config).run_batch(keys)
        pallas = PallasEngine(
            config, tile_runs=128, step_block=32, interpret=True
        ).run_batch(keys)
        for k in scan:
            np.testing.assert_array_equal(
                np.asarray(scan[k]), np.asarray(pallas[k]), err_msg=k
            )


def test_flight_xoroshiro_records_too():
    """The sequential-stream A/B mode records through the same plumbing —
    the cross-backend diff story depends on it (xoroshiro draws are
    bit-compatible with the native backend)."""
    config = SimConfig(
        network=default_network(), duration_ms=86_400_000, runs=8, batch_size=8,
        chunk_steps=64, seed=5, rng="xoroshiro", flight_capacity=1024,
    )
    eng = Engine(config)
    keys = eng.make_keys(0, config.runs)
    out = eng.run_batch(keys)
    hl = eng.run_batch(keys, host_loop=True)
    np.testing.assert_array_equal(out["flight_buf"], hl["flight_buf"])
    assert int(np.asarray(out["flight_count"]).min()) > 0


# ---------------------------------------------------------------------------
# Ring overflow.


def test_overflow_keeps_newest_rows_with_explicit_dropped():
    small_cap = 32
    big = Engine(RACY)
    small = Engine(dataclasses.replace(RACY, flight_capacity=small_cap))
    keys = make_run_keys(RACY.seed, 0, RACY.runs)
    full = decode_flight(big.run_batch(keys), start=0)
    clipped = decode_flight(small.run_batch(keys), start=0)
    assert not full.dropped
    by_run_full: dict[int, list] = {}
    for e in full.events:
        by_run_full.setdefault(e["run"], []).append(e)
    by_run_clip: dict[int, list] = {}
    for e in clipped.events:
        by_run_clip.setdefault(e["run"], []).append(e)
    for r, evs in by_run_full.items():
        kept = by_run_clip[r]
        assert len(kept) == small_cap
        # The NEWEST rows survive, sequence numbers intact, and the dropped
        # count is explicit — a reader can never mistake a clipped ring for
        # a complete log.
        assert clipped.dropped[r] == len(evs) - small_cap > 0
        assert kept == evs[-small_cap:]


def test_combine_sums_concatenates_flight_leaves():
    a = {"blocks_found_sum": np.array([1]), "tele_chunks_max": np.int64(2),
         "flight_buf": np.zeros((2, 4, N_FIELDS), np.int32),
         "flight_count": np.array([3, 4], np.int32)}
    b = {"blocks_found_sum": np.array([2]), "tele_chunks_max": np.int64(5),
         "flight_buf": np.ones((1, 4, N_FIELDS), np.int32),
         "flight_count": np.array([7], np.int32)}
    m = combine_sums(a, b)
    assert m["flight_buf"].shape == (3, 4, N_FIELDS)
    assert m["flight_count"].tolist() == [3, 4, 7]
    assert int(m["tele_chunks_max"]) == 5
    assert m["blocks_found_sum"].tolist() == [3]


def test_pallas_misaligned_batch_head_tail_split_keeps_flight_rows():
    """A tile-misaligned batch routes its remainder through the scan twin;
    the merged output must still carry every run's ring in run order."""
    from tpusim.pallas_engine import PallasEngine

    config = dataclasses.replace(RACY, runs=160, batch_size=160, flight_capacity=1024)
    keys = make_run_keys(config.seed, 0, 160)
    pallas = PallasEngine(config, tile_runs=128, step_block=32, interpret=True)
    out = pallas.run_batch(keys)  # 128 on the kernel + 32 on the scan twin
    scan = Engine(config).run_batch(keys)
    np.testing.assert_array_equal(out["flight_buf"], scan["flight_buf"])
    np.testing.assert_array_equal(out["flight_count"], scan["flight_count"])


# ---------------------------------------------------------------------------
# Zero-capacity: compiled out, zero cost.


def test_capacity_zero_has_no_recorder_ops():
    """flight_capacity=0 must not merely skip recording — the recorder must
    not exist in the program: no ring-shaped tensor (the distinctive
    (7, N_FIELDS) marker), no slot modulo, and a program identical to the
    default config's."""
    base = SimConfig(
        network=default_network(), duration_ms=86_400_000, runs=4, batch_size=4,
        chunk_steps=64,
    )
    keys = make_run_keys(0, 0, 4)

    def loop_jaxpr(config):
        eng = Engine(config)
        hi, lo = eng._ledger_init(4)
        return str(jax.make_jaxpr(lambda k: eng._device_loop(k, hi, lo, eng.params))(keys))

    off = loop_jaxpr(base)
    off_explicit = loop_jaxpr(dataclasses.replace(base, flight_capacity=0))
    on = loop_jaxpr(dataclasses.replace(base, flight_capacity=7))
    marker = f"7,{N_FIELDS}]"  # the (capacity, N_FIELDS) ring leaf shape
    assert marker in on
    assert marker not in off
    assert " rem " not in off  # the slot modulo is the recorder's signature op
    assert " rem " in on
    assert off == off_explicit  # default config IS the recorder-less program

    # And the warmed default path stays recompile-free.
    eng = Engine(base)
    eng.run_batch(keys)
    with compile_count_guard(exact=0):
        eng.run_batch(keys)


# ---------------------------------------------------------------------------
# Export: decode, JSONL, Perfetto, CLI.


def test_events_jsonl_is_sorted_and_stable():
    events = [
        {"run": 1, "seq": 0, "kind": "find", "t_ms": 5, "miner": 0, "height": 1, "depth": 0},
        {"run": 0, "seq": 1, "kind": "stale", "t_ms": 9, "miner": 2, "height": 3, "depth": 2},
        {"run": 0, "seq": 0, "kind": "find", "t_ms": 3, "miner": 1, "height": 1, "depth": 0},
    ]
    lines = events_jsonl(events).splitlines()
    decoded = [json.loads(ln) for ln in lines]
    assert [(e["run"], e["seq"]) for e in decoded] == [(0, 0), (0, 1), (1, 0)]
    # Stable key order — the property that makes two backends' logs diffable.
    assert all(list(e) == ["run", "seq", "kind", "t_ms", "miner", "height", "depth"]
               for e in decoded)


def test_perfetto_trace_schema_and_tracks():
    eng = Engine(dataclasses.replace(RACY, runs=4, batch_size=4))
    log = decode_flight(eng.run_batch(make_run_keys(RACY.seed, 0, 4)), start=0)
    trace = perfetto_trace(
        log.events, n_miners=RACY.network.n_miners, run_id="abc123",
    )
    n = validate_perfetto(trace)
    assert n == len(log.events) > 0
    assert trace["otherData"]["run_id"] == "abc123"
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    # One process per run, one named track per miner.
    assert sum(1 for e in meta if e["name"] == "process_name") == 4
    assert sum(1 for e in meta if e["name"] == "thread_name") == 4 * RACY.network.n_miners
    inst = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert all(e["ts"] == 1000 * next(
        ev["t_ms"] for ev in log.events
        if (ev["run"], ev["seq"]) == (e["pid"], e["args"]["seq"])
    ) for e in inst[:50])

    with pytest.raises(ValueError):
        validate_perfetto({"traceEvents": [{"no": "ph"}]})
    with pytest.raises(ValueError):
        validate_perfetto([])


def test_trace_cli_end_to_end(tmp_path, capsys):
    from tpusim.cli import main as cli_main

    trace_out = tmp_path / "t.trace.json"
    events_out = tmp_path / "ev.jsonl"
    led = tmp_path / "led.jsonl"
    rc = cli_main([
        "trace", "--runs", "3", "--batch-size", "2", "--duration-ms", "86400000",
        "--single-device", "--flight-capacity", "64",
        "--trace-out", str(trace_out), "--events-out", str(events_out),
        "--telemetry", str(led),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ui.perfetto.dev" in out

    # --quiet silences the whole summary (scripted CI consumers).
    rc = cli_main([
        "trace", "--runs", "1", "--batch-size", "1", "--duration-ms", "86400000",
        "--single-device", "--quiet", "--flight-capacity", "64",
        "--trace-out", str(tmp_path / "quiet.trace.json"),
    ])
    assert rc == 0
    assert capsys.readouterr().out == ""

    trace = json.loads(trace_out.read_text())
    validate_perfetto(trace)
    # Batching must not break run identity: all three global runs present.
    pids = {e["pid"] for e in trace["traceEvents"] if e["ph"] != "M"}
    assert pids == {0, 1, 2}

    events = [json.loads(ln) for ln in events_out.read_text().splitlines()]
    assert {e["run"] for e in events} == {0, 1, 2}
    assert all(e["t_ms"] <= 86_400_000 for e in events)

    # The span ledger correlates through the SAME run_id as the trace file.
    from tpusim.telemetry import load_spans

    spans = load_spans(led)
    assert [s["span"] for s in spans] == ["trace"]
    assert spans[0]["run_id"] == trace["otherData"]["run_id"]

    # cpp backend is the diff target, not a recording engine.
    with pytest.raises(SystemExit):
        cli_main(["trace", "--backend", "cpp", "--runs", "1"])


def test_trace_cli_capacity_precedence(tmp_path, capsys):
    """--flight-capacity wins over the config file, the config file over the
    1024 default — a config that sized its own ring is never clobbered."""
    from tpusim.cli import main as cli_main

    cfg = SimConfig(
        network=default_network(), duration_ms=86_400_000, runs=1,
        batch_size=1, flight_capacity=128,
    )
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(cfg.to_json())
    led = tmp_path / "led.jsonl"

    def trace_capacity(extra):
        rc = cli_main([
            "trace", "--config", str(cfg_path), "--single-device", "--quiet",
            "--trace-out", str(tmp_path / "t.trace.json"),
            "--telemetry", str(led), *extra,
        ])
        assert rc == 0
        capsys.readouterr()
        from tpusim.telemetry import load_spans

        return load_spans(led)[-1]["attrs"]["capacity"]

    assert trace_capacity([]) == 128              # config file honored
    assert trace_capacity(["--flight-capacity", "64"]) == 64  # flag wins


def test_time_limbs_decode_past_int32_chunk_horizon():
    """A 14-day run crosses the 2^30 ms limb boundary: decoded absolute
    times must keep increasing monotonically through it (the re-base
    accumulation carried in the recorder's base limbs)."""
    config = SimConfig(
        network=default_network(), duration_ms=14 * 86_400_000, runs=2,
        batch_size=2, seed=11, flight_capacity=8192,
    )
    eng = Engine(config)
    log = decode_flight(eng.run_batch(eng.make_keys(0, 2)), start=0)
    assert not log.dropped
    crossed = False
    for r in (0, 1):
        t = [e["t_ms"] for e in log.events if e["run"] == r]
        assert all(a <= b for a, b in zip(t, t[1:]))
        assert t[-1] <= config.duration_ms
        crossed |= t[-1] > FLIGHT_TIME_BASE
    assert crossed
