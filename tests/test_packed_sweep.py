"""Device-side grid packing (tpusim.packed): whole sweep grids as ONE
compiled device program, bit-equal to the sequential sweep.

The contract under test, per layer:

  * **Planning (jax-free)** — shape-agreement grouping (``pack_shape_key``),
    the fallback rules (``packable``), and the worst-case count-dtype
    resolution (``packed_count_dtype``) including its fail-loud int16 rule.
  * **Dispatch** — packed rows/moments/counters BIT-equal to the sequential
    sweep on both engines and all dispatch paths; ragged horizons; pad
    lanes; exactly one compile for a whole same-shape grid
    (``compile_count_guard(exact=0)`` on the second grid).
  * **combine_sums segment rules** — the ``*_per_run`` concat branch:
    split-vs-whole bit-equality (512-vs-256), associativity, and
    permutation invariance of the downstream per-point folds.
  * **Drivers** — ``run_sweep(packed=True)`` row schema/order and fallback
    mixing, the adaptive ``ci_target_stat`` lane allocator, the fleet's
    packed sub-grid units, and the watch/report per-point panels.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from tpusim.config import NetworkConfig, SimConfig, default_network
from tpusim.convergence import MomentAccumulator, point_snapshot_rows
from tpusim.engine import Engine, combine_sums
from tpusim.packed import (
    _dispatch,
    _fold_piece,
    _make_packed_engine,
    _Piece,
    _resolved_chunk_steps,
    _zero_point_sums,
    _zero_point_tele,
    pack_shape_key,
    packable,
    packed_count_dtype,
    plan_packs,
    run_grid,
    run_grid_adaptive,
)
from tpusim.runner import make_run_keys
from tpusim.sweep import _selfish_network, run_sweep
from tpusim.telemetry import TelemetryRecorder, load_spans
from tpusim.testing import compile_count_guard

DAY = 86_400_000

#: Module-shared compiled-engine cache: the packed program for the reference
#: grid shape compiles once for the whole file (the tier-1 affordability
#: discipline of tests/test_chaos.py).
CACHE: dict = {}

#: Wall-clock row fields stripped before bit-equality comparisons — the same
#: strip scripts/ci.sh applies to fleet rows.
_WALL = ("elapsed_s", "compile_s")


def _grid(runs: int = 12, batch: int = 8, duration: int = DAY):
    """2 intervals x 2 selfish pcts — a small selfish-threshold grid whose
    points all share one pack_shape_key."""
    pts = []
    for interval_s in (300.0, 600.0):
        for pct in (30, 40):
            net = _selfish_network(pct)
            net = NetworkConfig(miners=net.miners, block_interval_s=interval_s)
            pts.append((
                f"i{int(interval_s)}-s{pct}",
                SimConfig(network=net, runs=runs, duration_ms=duration,
                          batch_size=batch),
            ))
    return pts


def _strip(rows: list[dict]) -> list[dict]:
    return [{k: v for k, v in r.items() if k not in _WALL} for r in rows]


def _run_grid_all(pts, **kw):
    """plan_packs + run_grid per pack, entries stitched back in point order
    (what run_sweep(packed=True) does, minus the row plumbing)."""
    packs, sequential = plan_packs(pts)
    assert sequential == []
    entries: dict[int, dict] = {}
    for pack in packs:
        group = [pts[i] for i in pack.indices]
        for i, e in zip(pack.indices, run_grid(group, **kw)):
            entries[i] = e
    return [entries[i] for i in range(len(pts))]


@pytest.fixture(scope="module")
def seq_rows():
    return run_sweep(_grid(), quiet=True, engine_cache=CACHE)


@pytest.fixture(scope="module")
def packed_entries():
    return _run_grid_all(_grid(), engine_cache=CACHE)


# ---------------------------------------------------------------------------
# Planning (jax-free).


def test_planner_groups_same_shape_and_routes_fallbacks():
    pts = _grid()
    packs, sequential = plan_packs(pts)
    # The grid spans two block intervals -> two resolved chunk budgets (the
    # budget is sampling identity: packing must not change any point's
    # draws), so the planner forms one pack PER interval; the two rosters
    # within an interval share one pack (they differ only in runtime
    # params).
    assert len(packs) == 2 and sequential == []
    assert [p.indices for p in packs] == [[0, 1], [2, 3]]
    # xoroshiro and flight-recorder points PACK (the former carve-outs are
    # gone) — but rng and an armed recorder are program shape, so each forms
    # its own shape group rather than riding the threefry pack.
    xoro = dataclasses.replace(pts[0][1], rng="xoroshiro")
    flight = dataclasses.replace(pts[1][1], flight_capacity=64)
    assert packable(xoro) and packable(flight)
    packs, sequential = plan_packs(
        [pts[0], ("x", xoro), ("f", flight), pts[1]]
    )
    assert sequential == []
    assert [p.indices for p in packs] == [[0, 3], [1], [2]]
    assert pack_shape_key(xoro) != pack_shape_key(pts[0][1])
    # Two same-shape xoroshiro points share one pack.
    xoro2 = dataclasses.replace(pts[1][1], rng="xoroshiro")
    packs, sequential = plan_packs([("x0", xoro), ("x1", xoro2)])
    assert [p.indices for p in packs] == [[0, 1]] and sequential == []
    # A different miner count is a different program shape -> its own pack.
    other = SimConfig(network=default_network(), runs=8,
                      duration_ms=DAY, batch_size=8)
    packs, _ = plan_packs([pts[0], ("honest", other)])
    assert len(packs) == 2
    assert pack_shape_key(pts[0][1]) != pack_shape_key(other)


def test_chunk_steps_twin_pinned_to_engine():
    """The jax-free chunk-budget twin must equal Engine's resolution — the
    same twin discipline as SimConfig._event_bound vs default_n_steps."""
    for cfg in (
        _grid()[0][1],
        _grid(duration=2 * DAY)[1][1],
        dataclasses.replace(_grid()[2][1], chunk_steps=256),
        SimConfig(network=default_network(), runs=8, duration_ms=365 * DAY),
    ):
        assert _resolved_chunk_steps(cfg) == Engine(cfg).chunk_steps, cfg


def test_packed_count_dtype_worst_case_rules():
    small = _grid()[0][1]                      # rebased 1-day: int16 domain
    assert small.resolved_count_dtype == "int16"
    assert packed_count_dtype([small, small]) == "int16"
    # A selfish MAJORITY gets the full divergence budget back (PR 10) and
    # exceeds int16 at year length — the pack's worst case widens EVERYONE.
    majority = SimConfig(
        network=_selfish_network(55), runs=4, duration_ms=365 * DAY,
        batch_size=4,
    )
    assert majority.resolved_count_dtype == "int32"
    minority = dataclasses.replace(majority, network=_selfish_network(30))
    assert minority.resolved_count_dtype == "int16"
    assert packed_count_dtype([minority, majority]) == "int32"
    # Explicit int16 the pack cannot honor fails LOUD, never silently wide.
    explicit16 = dataclasses.replace(minority, state_dtype="int16")
    with pytest.raises(ValueError, match="worst-case"):
        packed_count_dtype([explicit16, majority])
    # Explicit int32 anywhere forces the pack wide; mixing it with an
    # explicit int16 request is a contradiction, not a preference.
    explicit32 = dataclasses.replace(small, state_dtype="int32")
    assert packed_count_dtype([small, explicit32]) == "int32"
    with pytest.raises(ValueError, match="mixes"):
        packed_count_dtype([explicit16, explicit32])


def test_pack_chunk_limit_covers_shorter_interval_members():
    """pack_shape_key omits the block interval (the 4096 clamp makes
    short-interval chunk budgets coincide), so one pack can mix intervals —
    the representative must take the worst-event-bound member's network, or
    a shorter-interval member than configs[0] exhausts the chunk loop
    ('batch did not finish within N chunks')."""
    miners = _selfish_network(40).miners
    a = SimConfig(
        network=NetworkConfig(miners=miners, block_interval_s=240.0),
        runs=4, duration_ms=365 * DAY, batch_size=4,
    )
    b = dataclasses.replace(
        a, network=NetworkConfig(miners=miners, block_interval_s=60.0)
    )
    assert pack_shape_key(a) == pack_shape_key(b)
    eng = _make_packed_engine([a, b])
    for member in (a, b):
        assert eng.max_chunks >= Engine(member).max_chunks, member


def test_synthetic_representative_overflow_widens_not_raises():
    """A pack whose members all fit int16 individually can still have a
    synthetic representative (first roster x the pack-max duration) whose
    count bound does not — the engine builder must widen to int32, not
    crash in SimConfig.__post_init__ before its widening check runs."""
    net = _selfish_network(40)
    a = SimConfig(
        network=NetworkConfig(miners=net.miners, block_interval_s=10.0),
        runs=4, duration_ms=DAY, batch_size=4, count_rebase=False,
    )
    b = dataclasses.replace(
        a, network=NetworkConfig(miners=net.miners, block_interval_s=40.0),
        duration_ms=4 * DAY,
    )
    # Preconditions that make this the overflow case: one pack, each
    # member's own bound fits int16, the representative's does not.
    assert pack_shape_key(a) == pack_shape_key(b)
    assert packed_count_dtype([a, b]) == "int16"
    rep_probe = dataclasses.replace(
        a, duration_ms=b.duration_ms, chunk_steps=_resolved_chunk_steps(a)
    )
    assert not rep_probe._count_bound_fits_int16
    eng = _make_packed_engine([a, b])
    assert eng.config.resolved_count_dtype == "int32"


# ---------------------------------------------------------------------------
# Packed dispatch: bit-equality with the sequential sweep.


def test_packed_rows_bit_equal_sequential(seq_rows, packed_entries):
    """Every per-point row (SimResults payload) lands bit-equal to the
    sequential sweep, in point order."""
    assert [e["name"] for e in packed_entries] == [r["point"] for r in seq_rows]
    for row, entry in zip(seq_rows, packed_entries):
        got = entry["results"].to_dict()
        for k, v in row.items():
            if k in _WALL or k in ("point", "backend"):
                continue
            assert got[k] == v, (entry["name"], k)


def test_packed_moments_and_counters_bit_equal_sequential(packed_entries):
    """The int64 moment accumulators and SimCounters land per-point
    bit-equal to a sequential per-point fold of the same batches. One point
    per pack (the grid spans two) pins both compiled programs at half the
    tier-1 cost — the rows test covers all four points."""
    from tpusim.runner import make_engine

    probe = [(_grid()[i], packed_entries[i]) for i in (0, 3)]
    for (name, cfg), entry in probe:
        eng = make_engine(cfg, cache=CACHE)
        acc = MomentAccumulator()
        tele = _zero_point_tele(cfg.network.n_miners)
        for start in range(0, cfg.runs, cfg.batch_size):
            n = min(cfg.batch_size, cfg.runs - start)
            out = eng.run_batch(make_run_keys(cfg.seed, start, n))
            acc.add(out)
            tele["reorg_depth_max"] = max(
                tele["reorg_depth_max"], int(out["tele_reorg_depth_max"])
            )
            tele["stale_events"] += int(out["tele_stale_events_sum"])
            tele["active_steps"] += int(out["tele_active_steps_sum"])
            tele["stale_by_miner"] = (
                tele["stale_by_miner"] + out["tele_stale_by_miner_sum"]
            )
            tele["reorg_depth_hist"] = (
                tele["reorg_depth_hist"] + out["tele_reorg_depth_hist_sum"]
            )
        got_m, got_t = entry["moments"], entry["tele"]
        assert got_m.n == acc.n == cfg.runs
        for stat in acc.m1:
            assert np.array_equal(got_m.m1[stat], acc.m1[stat]), (name, stat)
            assert np.array_equal(got_m.m2[stat], acc.m2[stat]), (name, stat)
        for k in tele:
            assert np.array_equal(got_t[k], tele[k]), (name, k)


def test_second_same_shape_grid_compiles_nothing(seq_rows, packed_entries):
    """The acceptance pin: a second same-shape grid through the warmed cache
    dispatches with ZERO XLA compiles, and run_sweep(packed=True) rows are
    the fixture rows bit-for-bit. The ride-along ``progress`` callback must
    arrive SWEEP-cumulative across the grid's two packs (run_sweep wraps
    each group's callback with a running base) without costing a compile."""
    calls: list[tuple[int, int]] = []
    with compile_count_guard(exact=0):
        rows = run_sweep(_grid(), quiet=True, packed=True, engine_cache=CACHE,
                         progress=lambda d, t: calls.append((d, t)))
    assert _strip(rows) == _strip(seq_rows)
    total = sum(c.runs for _, c in _grid())
    assert calls[-1] == (total, total)
    assert all(t == total for _, t in calls)
    assert [d for d, _ in calls] == sorted(d for d, _ in calls)


def test_packed_dispatch_paths_bit_equal(packed_entries):
    """host-loop and pipelined packed dispatches produce the same rows as
    the device-loop path (the engines' three-path contract, packed). One
    pack is enough — the path split is per-program, not per-pack."""
    for kw in ({"host_loop": True}, {"pipelined": True}):
        out = run_grid(_grid()[2:], engine_cache=CACHE, **kw)
        for a, b in zip(packed_entries[2:], out):
            assert a["sums"].keys() == b["sums"].keys()
            for k in a["sums"]:
                assert np.array_equal(a["sums"][k], b["sums"][k]), (kw, k)


def test_ragged_horizons_pack_and_match_sequential():
    """Points with DIFFERENT durations pack together when their resolved
    chunk budgets agree (explicit chunk_steps): each run carries its own
    horizon through the per-run ledger, bit-equal to sequential."""
    net = _selfish_network(35)
    pts = [
        (f"d{d}", SimConfig(network=net, runs=5, duration_ms=d * DAY // 2,
                            batch_size=8, chunk_steps=128))
        for d in (1, 2)
    ]
    packs, sequential = plan_packs(pts)
    assert len(packs) == 1 and sequential == []
    cache: dict = {}
    seq = run_sweep(pts, quiet=True, engine_cache=cache)
    entries = run_grid(pts, engine_cache=cache)
    for row, entry in zip(seq, entries):
        got = entry["results"].to_dict()
        for k, v in row.items():
            if k not in _WALL and k not in ("point", "backend"):
                assert got[k] == v, (entry["name"], k)


# Slow tier (ci.sh's unfiltered pytest leg): the widening RULES ride tier-1
# jax-free (test_packed_count_dtype_worst_case_rules and the synthetic-
# representative overflow test); this adds the end-to-end bit-equality belt
# on a 120-day widened pack.
@pytest.mark.slow
def test_pack_widens_mixed_dtype_grid_and_stays_bit_equal():
    """A pack mixing an int16-domain point with an int32 point runs the
    WHOLE batch int32 — and the int16 point's results are still bit-equal
    to its sequential (int16) run, because the count dtype is not part of
    the sampling identity."""
    majority = SimConfig(
        network=_selfish_network(55), runs=4, duration_ms=120 * DAY,
        batch_size=4,
    )
    minority = dataclasses.replace(majority, network=_selfish_network(30))
    pts = [("min30", minority), ("maj55", majority)]
    packs, sequential = plan_packs(pts)
    assert len(packs) == 1 and sequential == []
    eng = _make_packed_engine([minority, majority])
    assert eng.config.resolved_count_dtype == "int32"
    cache: dict = {}
    seq = run_sweep(pts, quiet=True, engine_cache=cache)
    entries = run_grid(pts, engine_cache=cache)
    for row, entry in zip(seq, entries):
        got = entry["results"].to_dict()
        for k, v in row.items():
            if k not in _WALL and k not in ("point", "backend"):
                assert got[k] == v, (entry["name"], k)


def test_packed_engine_validation():
    cfg = _grid()[0][1]
    # The xoroshiro carve-out is GONE: a packed xoroshiro engine builds.
    Engine(dataclasses.replace(cfg, rng="xoroshiro"), packed=True)
    with pytest.raises(ValueError, match="tpu backend"):
        run_sweep(_grid(), backend="cpp", packed=True, quiet=True)


def test_checkpoint_dir_packs_with_piece_checkpoints(tmp_path, caplog, seq_rows):
    """--checkpoint-dir no longer disables packing: the packed path writes
    the sequential runner's own fingerprinted per-point npz after every
    dispatch, rows stay bit-equal to the sequential sweep, and a re-run over
    the completed checkpoint dir reproduces the same rows from the saved
    sums alone."""
    pts = _grid()
    ckdir = tmp_path / "ckpt"
    with caplog.at_level("WARNING", logger="tpusim"):
        rows = run_sweep(
            pts, quiet=True, packed=True, engine_cache=CACHE,
            checkpoint_dir=ckdir,
        )
    assert "falls back" not in caplog.text
    assert _strip(rows) == _strip(seq_rows)
    assert sorted(p.name for p in ckdir.glob("*.npz")) == sorted(
        f"{name}.npz" for name, _ in pts
    )
    for name, cfg in pts:
        with np.load(ckdir / f"{name}.npz") as saved:
            assert int(saved["__runs_done__"]) == cfg.runs
    resumed = run_sweep(
        pts, quiet=True, packed=True, engine_cache=CACHE, checkpoint_dir=ckdir,
    )
    assert _strip(resumed) == _strip(seq_rows)


def test_checkpoint_cross_path_resume_bit_equal(tmp_path, seq_rows):
    """Packed piece checkpoints ARE sequential checkpoints: a sequential
    sweep resumes what a packed sweep saved (and vice versa), bit-equal to
    an uninterrupted run either way."""
    pts = _grid()
    packed_dir = tmp_path / "from-packed"
    run_sweep(pts, quiet=True, packed=True, engine_cache=CACHE,
              checkpoint_dir=packed_dir)
    rows = run_sweep(pts, quiet=True, engine_cache=CACHE,
                     checkpoint_dir=packed_dir)
    assert _strip(rows) == _strip(seq_rows)
    seq_dir = tmp_path / "from-seq"
    run_sweep(pts, quiet=True, engine_cache=CACHE, checkpoint_dir=seq_dir)
    rows = run_sweep(pts, quiet=True, packed=True, engine_cache=CACHE,
                     checkpoint_dir=seq_dir)
    assert _strip(rows) == _strip(seq_rows)


def test_mixed_grid_packs_per_shape_group_in_order(seq_rows):
    """A grid mixing threefry and xoroshiro points keeps the EXACT output
    point order; the xoroshiro point packs in its own shape group with its
    row equal to its own sequential run."""
    pts = _grid()
    xoro_cfg = dataclasses.replace(pts[1][1], rng="xoroshiro")
    mixed = [pts[0], ("xoro", xoro_cfg), pts[2]]
    packs, sequential = plan_packs(mixed)
    assert sequential == [] and len(packs) == 3
    rows = run_sweep(mixed, quiet=True, packed=True, engine_cache=CACHE)
    assert [r["point"] for r in rows] == [pts[0][0], "xoro", pts[2][0]]
    by_point = {r["point"]: r for r in _strip(rows)}
    want = {r["point"]: r for r in _strip(seq_rows)}
    assert by_point[pts[0][0]] == want[pts[0][0]]
    assert by_point[pts[2][0]] == want[pts[2][0]]
    seq_xoro = run_sweep([("xoro", xoro_cfg)], quiet=True, engine_cache=CACHE)
    assert by_point["xoro"] == _strip(seq_xoro)[0]


def test_packed_xoroshiro_bit_equal_sequential():
    """Per-run xoroshiro stream packing at the engine level: a whole
    xoroshiro grid through run_grid lands every result field bit-equal to
    the sequential sweep — the stacked (runs, 8) stream rows reproduce the
    native backend's per-run (seed, run) derivation exactly, and the f64
    mean-interval leaf keeps the interval mapping identical."""
    pts = [(n, dataclasses.replace(c, rng="xoroshiro")) for n, c in _grid()]
    seq = run_sweep(pts, quiet=True, engine_cache=CACHE)
    entries = _run_grid_all(pts, engine_cache=CACHE)
    for row, entry in zip(seq, entries):
        got = entry["results"].to_dict()
        for k, v in row.items():
            if k not in _WALL and k not in ("point", "backend"):
                assert got[k] == v, (entry["name"], k)


def test_packed_sigkill_mid_pack_resume_bit_equal(tmp_path, seq_rows):
    """The mid-pack durability drill: SIGKILL a packed sweep right after its
    FIRST piece checkpoint turns durable (post_replace — one point saved
    partway, the rest unsaved), then resume packed over the same checkpoint
    dir; the healed rows must be bit-equal to an uninterrupted sequential
    sweep."""
    from tpusim.probe import TUNNEL_TRIGGER_ENV

    ckdir = tmp_path / "ckpt"
    repo = str(Path(__file__).parent.parent)
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop(TUNNEL_TRIGGER_ENV, None)
    worker = Path(__file__).parent / "packed_kill_worker.py"
    r = subprocess.run(
        [sys.executable, str(worker), str(ckdir)],
        capture_output=True, text=True, timeout=420, env=env, cwd=repo,
    )
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stdout, r.stderr)
    assert "UNREACHABLE" not in r.stdout
    # The kill landed MID-PACK: at least one durable piece checkpoint holds
    # a partial run cursor.
    done = {}
    for p in sorted(ckdir.glob("*.npz")):
        with np.load(p) as saved:
            done[p.stem] = int(saved["__runs_done__"])
    assert done and any(0 < v < 12 for v in done.values()), done
    rows = run_sweep(
        _grid(), quiet=True, packed=True, engine_cache=CACHE,
        checkpoint_dir=ckdir,
    )
    assert _strip(rows) == _strip(seq_rows)


def test_packed_flight_decode_run_id_round_trip():
    """Pack-aware flight decode: the per-run event rings ride the pack's
    runs axis and decode_flight_packed maps every pack position back to its
    (point, run) — each point's packed event log is identical to its own
    sequential batched decode, absolute run ids intact across pieces."""
    from tpusim.flight_export import decode_flight

    pts = [
        ("f-a", SimConfig(network=default_network(propagation_ms=10_000),
                          runs=8, batch_size=4, duration_ms=DAY,
                          flight_capacity=512, seed=3)),
        ("f-b", SimConfig(network=default_network(propagation_ms=1000),
                          runs=8, batch_size=4, duration_ms=DAY,
                          flight_capacity=512, seed=9)),
    ]
    entries = _run_grid_all(pts, engine_cache=CACHE)
    for (name, cfg), entry in zip(pts, entries):
        eng = Engine(cfg)
        events: list[dict] = []
        for start in range(0, cfg.runs, cfg.batch_size):
            out = eng.run_batch(make_run_keys(cfg.seed, start, cfg.batch_size))
            events.extend(decode_flight(out, start=start).events)
        events.sort(key=lambda e: (e["run"], e["seq"]))
        assert events and entry["flight"].events == events, name
        assert {e["run"] for e in entry["flight"].events} <= set(range(cfg.runs))


# ---------------------------------------------------------------------------
# combine_sums segment-axis rules.


def _packed_raw(members, pieces, width, cache=CACHE):
    eng = _make_packed_engine([c for _, c in members], engine_cache=cache)
    return eng, _dispatch(eng, [c for _, c in members], pieces, width)


def test_split_dispatch_concat_bit_equal_512_vs_256():
    """One 512-run packed dispatch (2 points x 256) == two 256-run
    dispatches combine_sums'd, BIT-equal on every raw leaf — the
    ``*_per_run`` concat rule plus the additive/max rules with segments
    attached."""
    net = default_network(propagation_ms=1000)
    members = [
        ("a", SimConfig(network=net, runs=256, batch_size=256, seed=3,
                        duration_ms=3_600_000)),
        ("b", SimConfig(network=net, runs=256, batch_size=256, seed=7,
                        duration_ms=3_600_000)),
    ]
    cache: dict = {}
    _, whole = _packed_raw(
        members, [_Piece(0, 0, 256), _Piece(1, 0, 256)], 512, cache
    )
    _, half_a = _packed_raw(members, [_Piece(0, 0, 256)], 256, cache)
    _, half_b = _packed_raw(members, [_Piece(1, 0, 256)], 256, cache)
    merged = combine_sums(half_a, half_b)
    assert merged.keys() == whole.keys()
    for k in whole:
        assert np.array_equal(merged[k], whole[k]), k


def test_combine_sums_segment_rules_associative_and_permutation():
    """Associativity of the merge on raw packed outputs, and permutation
    invariance of the downstream per-point segment folds (the property that
    lets dispatch order never matter). Built entirely on the module CACHE's
    width-8 pack program (pad lanes included) — zero extra compiles."""
    members = _grid()[:2]
    pieces = [_Piece(0, 0, 2), _Piece(1, 0, 2), _Piece(0, 2, 2)]
    parts = [_packed_raw(members, [p], 8)[1] for p in pieces]
    ab_c = combine_sums(combine_sums(parts[0], parts[1]), parts[2])
    a_bc = combine_sums(parts[0], combine_sums(parts[1], parts[2]))
    assert ab_c.keys() == a_bc.keys()
    for k in ab_c:
        assert np.array_equal(ab_c[k], a_bc[k]), k

    # Per-point folds are permutation-invariant over pieces: folding the
    # same segments in any dispatch order yields identical accumulators
    # (point 0 receives TWO pieces, so cross- and within-point order are
    # both exercised).
    m = members[0][1].network.n_miners
    raw = _packed_raw(members, pieces, 8)[1]
    offs = [0, 2, 4]

    def fold(order):
        st = [
            {"sums": _zero_point_sums(m), "moments": MomentAccumulator(),
             "tele": _zero_point_tele(m)}
            for _ in range(2)
        ]
        for j in order:
            _fold_piece(st[pieces[j].point], raw, slice(offs[j], offs[j] + 2))
        return st

    fwd, rev = fold([0, 1, 2]), fold([2, 0, 1])
    for sf, sr in zip(fwd, rev):
        for k in sf["sums"]:
            assert np.array_equal(sf["sums"][k], sr["sums"][k]), k
        assert sf["moments"].n == sr["moments"].n
        for stat in sf["moments"].m1:
            assert np.array_equal(sf["moments"].m1[stat], sr["moments"].m1[stat])
            assert np.array_equal(sf["moments"].m2[stat], sr["moments"].m2[stat])


def test_packed_big_seed_matches_sequential_and_reports_progress():
    """Seeds past uint32: ``jax.random.key`` WRAPS out-of-range Python ints,
    so the sequential path accepts them — the packed key build must inherit
    that construction (a raw ``np.uint32`` cast raises under numpy 2.x
    instead of wrapping). The point's 8+4 pieces also span two dispatches,
    pinning ``run_grid``'s per-dispatch grid-cumulative ``progress``
    callback (the runner's contract, so fleet heartbeats carry packed
    progress)."""
    pts = [
        (n, dataclasses.replace(c, seed=2**32 + 7)) for n, c in _grid()[2:3]
    ]
    seq = run_sweep(pts, quiet=True, engine_cache=CACHE)
    calls: list[tuple[int, int]] = []
    entries = _run_grid_all(pts, engine_cache=CACHE,
                            progress=lambda d, t: calls.append((d, t)))
    for row, entry in zip(seq, entries):
        got = entry["results"].to_dict()
        for k, v in row.items():
            if k not in _WALL and k not in ("point", "backend"):
                assert got[k] == v, (entry["name"], k)
    total = pts[0][1].runs
    assert len(calls) > 1 and calls[-1] == (total, total)
    assert [d for d, _ in calls] == sorted(d for d, _ in calls)


# ---------------------------------------------------------------------------
# Pallas engine.


def test_pallas_packed_bit_equal_scan(packed_entries):
    """The packed pallas kernel (per-run (M, R) prop/selfish refs, pad
    lanes up to the 128 tile) lands bit-equal to the packed scan engine —
    which the fixtures pin bit-equal to the sequential sweep."""
    # One interval's pack is enough to pin the kernel path (the interpret
    # twin is slow; the 600 s-interval pack has the fewest steps).
    out = _run_grid_all(
        _grid()[2:], engine="pallas", pallas_kwargs={"interpret": True},
    )
    for a, b in zip(packed_entries[2:], out):
        for k in a["sums"]:
            assert np.array_equal(a["sums"][k], b["sums"][k]), k
        assert a["moments"].n == b["moments"].n
        for stat in a["moments"].m1:
            assert np.array_equal(a["moments"].m1[stat], b["moments"].m1[stat])


def test_pallas_packed_guards():
    from tpusim.pallas_engine import PallasEngine

    cfg = dataclasses.replace(
        _grid()[0][1], batch_size=128, runs=128,
    )
    with pytest.raises(ValueError, match="rng_batch"):
        PallasEngine(dataclasses.replace(cfg, rng_batch=False),
                     packed=True, interpret=True)
    # A packed dispatch not padded to the run tile is a caller bug: the
    # per-run params would silently misalign under a head/tail split.
    eng = PallasEngine(cfg, tile_runs=128, step_block=64,
                       interpret=True, packed=True)
    with pytest.raises(ValueError, match="pad the pack width"):
        eng.run_batch(make_run_keys(0, 0, 130))


# ---------------------------------------------------------------------------
# Adaptive runs-per-point allocation.


def test_adaptive_allocates_lanes_to_wide_ci_points(tmp_path):
    """The ci_target_stat driver inside the packed batch: with an
    unreachable target, round 2 must allocate MORE lanes to the point whose
    round-1 CI was widest, and every point's moments cover exactly the runs
    it executed."""
    pts = _grid(runs=64, batch=16)[:2]
    tele = tmp_path / "adaptive.jsonl"
    rec = TelemetryRecorder(tele)
    out = run_grid_adaptive(
        pts, ci_target_stat="blocks_share", ci_target_rel=1e-4,
        lanes=16, max_rounds=2, engine_cache=CACHE, telemetry=rec,
    )
    rec.close()
    for (name, cfg), entry in zip(pts, out):
        assert entry["results"].runs == entry["moments"].n <= cfg.runs
        assert entry["converged"] is False  # 1e-4 is unreachable in 2 rounds
    spans = [s for s in load_spans(tele) if s["span"] == "stats"]
    r2 = {s["attrs"]["point"]: s["attrs"] for s in spans
          if s["attrs"].get("round") == 2}
    r1 = {s["attrs"]["point"]: s["attrs"] for s in spans
          if s["attrs"].get("round") == 1}
    assert set(r2) == {pts[0][0], pts[1][0]}
    rel1 = {
        p: a["stats"]["blocks_share"]["rel_hw_max"] for p, a in r1.items()
    }
    wide = max(rel1, key=rel1.get)
    narrow = min(rel1, key=rel1.get)
    if rel1[wide] > rel1[narrow]:
        assert r2[wide]["lanes"] >= r2[narrow]["lanes"]


def test_allocate_lanes_respects_min_runs_floor():
    """Integer-rounding overshoot is trimmed from the smallest-need points
    but never below the min_runs floor (a 1-run round yields no usable CI),
    and a point whose remaining budget is under the floor just takes what it
    has left."""
    from tpusim.packed import _allocate_lanes

    # Rounding pushes the raw allocation to 8 > lanes=6; the two floor
    # points must NOT be trimmed to 1 — only the wide point gives back.
    alloc = _allocate_lanes(
        [0, 1, 2], {0: 5.0, 1: 1.0, 2: 1.0},
        {0: 64, 1: 64, 2: 64}, lanes=6, min_runs=2,
    )
    assert sum(alloc.values()) <= 6
    assert all(v >= 2 for v in alloc.values())
    assert alloc[0] >= alloc[1] == alloc[2] == 2
    # remaining < min_runs: the clamp wins (budget ceilings are hard).
    alloc = _allocate_lanes(
        [0, 1], {0: 1.0, 1: 1.0}, {0: 1, 1: 64}, lanes=4, min_runs=2,
    )
    assert alloc[0] == 1 and alloc[1] >= 2


def test_adaptive_layouts_do_not_grow_engine_cache():
    """Adaptive rounds produce one-shot (config, count) layouts; caching
    their stacked params in the session-lived engine cache would leak —
    they go in a per-call cache instead (run_grid's static layouts still
    share the engine cache)."""
    pts = _grid(runs=32, batch=16)[:2]
    before = {k for k in CACHE if isinstance(k, tuple)
              and k and k[0] == "packed_params"}
    run_grid_adaptive(
        pts, ci_target_stat="blocks_found", ci_target_rel=2.0,
        lanes=16, engine_cache=CACHE,
    )
    after = {k for k in CACHE if isinstance(k, tuple)
             and k and k[0] == "packed_params"}
    assert after == before


def test_adaptive_converges_and_stops(tmp_path):
    """A reachable target stops the loop early with converged points, and
    the budget ceiling (config.runs) is never exceeded."""
    pts = _grid(runs=32, batch=16)[:2]
    out = run_grid_adaptive(
        pts, ci_target_stat="blocks_found", ci_target_rel=2.0,
        lanes=16, engine_cache=CACHE,
    )
    for entry in out:
        assert entry["converged"] is True
        assert entry["rounds"] <= 2
    with pytest.raises(ValueError, match="unknown ci_target_stat"):
        run_grid_adaptive(pts, ci_target_stat="nope")


# ---------------------------------------------------------------------------
# Dashboards: segment-aware stats spans.


def test_watch_and_report_render_per_point_panels(tmp_path):
    from tpusim.report import render_report
    from tpusim.watch import render_watch

    tele = tmp_path / "packed.tele.jsonl"
    run_sweep(_grid()[:2], quiet=True, packed=True, engine_cache=CACHE,
              telemetry_path=tele)
    spans = load_spans(tele)
    # The packed sweep owns the closing "run" span (watch exits on it).
    assert any(s["span"] == "run" for s in spans)
    rows = point_snapshot_rows([s for s in spans if s["span"] == "stats"])
    assert [r[0] for r in rows] == [n for n, _ in _grid()[:2]]
    watch = render_watch(spans, "t")
    report = render_report(spans)
    for txt in (watch, report):
        assert "by grid point" in txt
        for name, _ in _grid()[:2]:
            assert name in txt
    # A plain (non-packed) ledger has no point attrs: both dashboards fall
    # back to the blended table.
    assert point_snapshot_rows(
        [{"span": "stats", "attrs": {"runs": 4}}]
    ) is None


def test_mixed_sweep_dashboards_render_both_tables():
    """A MIXED packed sweep's ledger carries per-point segment spans AND
    plain spans from unpackable fallback points — both dashboards must
    render both tables (the fallback points' narrowing must not vanish
    behind the per-point panel). Synthetic spans: no compute."""
    from tpusim.report import render_report
    from tpusim.watch import render_watch

    stats = {"blocks_share": {"rel_hw_max": 0.02, "hw_max": 0.01}}
    spans = [
        {"span": "stats", "run_id": "r", "t": 1.0,
         "attrs": {"point": "packed-pt", "runs": 8, "runs_done": 8,
                   "runs_total": 8, "packed": True, "stats": stats}},
        {"span": "stats", "run_id": "r", "t": 2.0,
         "attrs": {"runs": 4, "runs_done": 4, "runs_total": 8,
                   "stats": stats}},
    ]
    watch, report = render_watch(spans, "t"), render_report(spans)
    for txt in (watch, report):
        assert "by grid point" in txt and "packed-pt" in txt
    assert "convergence (95% CI" in watch
    assert "Convergence (stats spans)" in report


# ---------------------------------------------------------------------------
# Fleet: packed sub-grid units.


def test_fleet_packed_units_dispatch_and_flush_in_order(tmp_path):
    """The supervisor plans packed sub-grid units (fake grid worker), rows
    land per-point in point order, and a crashed unit requeues WHOLE."""
    from test_fleet import fake_cmd, fake_points, make_sup, rows_of

    behaviors: dict[str, str] = {}
    base_cmd = fake_cmd(behaviors)

    def cmd(asg):
        argv = base_cmd(asg)
        if asg.get("grid_manifest") is not None:
            argv += ["--grid", str(asg["grid_manifest"])]
        return argv

    pts = fake_points("pt-a", "pt-b", "pt-c")
    sup = make_sup(tmp_path, pts, worker_cmd=cmd, workers=2, packed=True)
    summary = sup.run()
    # ceil(3/2)=2 -> one grid unit of 2 points + one plain point.
    assert len(sup._units) == 1
    unit, members = next(iter(sup._units.items()))
    assert unit.startswith("grid-") and members == ["pt-a", "pt-b"]
    manifest = json.loads(
        (sup.state_dir / "points" / f"{unit}.grid.json").read_text()
    )
    assert [e["point"] for e in manifest["points"]] == members
    assert summary["quarantined"] == []
    assert [r["point"] for r in rows_of(sup)] == ["pt-a", "pt-b", "pt-c"]

    # A unit whose worker dies requeues as a UNIT and heals whole.
    behaviors2 = {}

    def cmd2(asg):
        argv = base_cmd(asg)
        if asg.get("grid_manifest") is not None:
            argv[argv.index("--behavior") + 1] = (
                "fail" if asg["attempt"] == 0 else "ok"
            )
            argv += ["--grid", str(asg["grid_manifest"])]
        return argv

    sup2 = make_sup(tmp_path / "g2", fake_points("pt-a", "pt-b", "pt-c"),
                    worker_cmd=cmd2, workers=2, packed=True)
    summary2 = sup2.run()
    assert summary2["requeues"] == 1 and summary2["quarantined"] == []
    assert [r["point"] for r in rows_of(sup2)] == ["pt-a", "pt-b", "pt-c"]
    healed = [r for r in rows_of(sup2) if r["point"] in ("pt-a", "pt-b")]
    assert all(r["attempt"] == 1 for r in healed)


def test_fleet_worker_chaos_targets_packed_unit_members():
    """A chaos plan aimed at a point name must arm the packed sub-grid UNIT
    that carries the point (units spawn under synthetic grid-… names)."""
    from tpusim.fleet import FleetSupervisor

    sup = object.__new__(FleetSupervisor)
    plan = object()
    sup._units = {"grid-abc": ["pt-a", "pt-b"]}
    sup.worker_chaos, sup.worker_chaos_point = plan, "pt-b"
    assert FleetSupervisor._worker_plan(sup, "grid-abc", 0) is plan
    assert FleetSupervisor._worker_plan(sup, "pt-b", 0) is plan
    assert FleetSupervisor._worker_plan(sup, "pt-c", 0) is None
    assert FleetSupervisor._worker_plan(sup, "grid-abc", 1) is None
    sup.worker_chaos, sup.worker_chaos_point = {"pt-b": plan}, None
    assert FleetSupervisor._worker_plan(sup, "grid-abc", 0) is plan
    assert FleetSupervisor._worker_plan(sup, "pt-a", 0) is None


def test_fleet_worker_main_grid_manifest(tmp_path):
    """The REAL packed grid worker: one worker_main --grid call runs the
    whole sub-grid via run_sweep(packed=True) and publishes every member
    row (exact sweep schema) in one atomic result object."""
    from tpusim.fleet import worker_main

    pts = _grid(runs=4, batch=4)[:2]
    pdir = tmp_path / "points"
    pdir.mkdir()
    for name, cfg in pts:
        (pdir / f"{name}.json").write_text(cfg.to_json())
    manifest = tmp_path / "unit.grid.json"
    manifest.write_text(json.dumps({
        "unit": "grid-test",
        "points": [
            {"point": n, "config": str(pdir / f"{n}.json")} for n, _ in pts
        ],
    }))
    result = tmp_path / "result.json"
    rc = worker_main([
        "--grid", str(manifest), "--result", str(result),
        "--heartbeat", str(tmp_path / "beat.jsonl"),
    ])
    assert rc == 0
    payload = json.loads(result.read_text())
    rows = payload["rows"]
    assert [r["point"] for r in rows] == [n for n, _ in pts]
    ref = run_sweep(pts, quiet=True, engine_cache=CACHE)
    assert _strip(rows) == _strip(ref)
    with pytest.raises(SystemExit):
        worker_main(["--result", "r", "--heartbeat", "h"])  # neither mode
