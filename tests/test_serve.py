"""The crash-only simulation service (tpusim.serve): served answers
bit-equal to a direct packed sweep (rows and exact int64 moments, cache
hits and coalesced queries included), the service chaos matrix (wedged
dispatch sheds only its pack, queue-full 503 then recovery, ENOSPC on the
result-cache write keeps serving, transient admission faults are
retryable), SIGTERM-style drain accounting with zero lost accepted
queries, the warmed mixed-shape storm compile pin, the `served_query`
provenance chain, and the serve SLO profile. Every daemon test runs under
the thread-leak guard — the runtime half of the JX015-JX019 gate.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import urllib.request
from pathlib import Path
from urllib.error import HTTPError

import pytest

import tpusim.provenance as provenance
from tpusim.config import MinerConfig, NetworkConfig, SimConfig
from tpusim.metrics import (
    SloConfigError,
    evaluate_slos,
    load_objectives,
    slo_exit_code,
    snapshot_from_spans,
)
from tpusim.packed import run_grid
from tpusim.provenance import PROVENANCE_ENV, load_lineage
from tpusim.serve import ServeDaemon, ServeReject
from tpusim.sweep import run_sweep
from tpusim.testing import compile_count_guard

REPO = Path(__file__).resolve().parent.parent

#: Wall-clock-independent row comparison: everything but the timing fields.
TIMING_KEYS = ("elapsed_s", "compile_s")


def _cfg(
    seed: int, *, batch: int = 8, interval_s: float = 600.0,
    miners: tuple[int, ...] = (60, 40),
) -> SimConfig:
    net = NetworkConfig(miners=tuple(
        MinerConfig(hashrate_pct=pct, propagation_ms=1000) for pct in miners
    ), block_interval_s=interval_s)
    return SimConfig(network=net, runs=8, duration_ms=3_600_000,
                     batch_size=batch, seed=seed)


def _strip(row: dict) -> dict:
    return {k: v for k, v in row.items() if k not in TIMING_KEYS}


def _ask(daemon: ServeDaemon, name: str, cfg: SimConfig, **kw):
    q = daemon.submit(name, cfg, **kw)
    assert q.done.wait(timeout=180), f"query {name} never resolved"
    return q


def _post(url: str, payload: dict, timeout: float = 180.0):
    req = urllib.request.Request(
        url + "/api/query", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


@contextlib.contextmanager
def _daemon(tmp_path: Path, **kw):
    d = ServeDaemon(tmp_path / "serve", **kw)
    try:
        yield d
    finally:
        d.drain()


@contextlib.contextmanager
def _armed(ledger: Path):
    os.environ[PROVENANCE_ENV] = str(ledger)
    provenance._WRITERS.clear()
    try:
        yield
    finally:
        os.environ.pop(PROVENANCE_ENV, None)
        provenance._WRITERS.clear()


# ---------------------------------------------------------------------------
# Bit-equality: served == direct packed sweep, coalescing included.


def test_served_rows_bit_equal_to_direct_sweep(tmp_path, thread_guard):
    """Three HTTP queries — two distinct configs sharing one pack shape
    plus an exact duplicate — admitted BEFORE the worker starts, so they
    ride one coalesced batch. Every answer must be bit-equal to a direct
    ``run_sweep(packed=True)`` of the same configs (rows minus wall-clock
    timing) and carry the exact int64 moment state of ``run_grid``."""
    c1, c2 = _cfg(11), _cfg(12, interval_s=300.0)
    with _daemon(tmp_path) as daemon:
        daemon.start_http()
        results: dict[str, tuple] = {}

        def go(name: str, cfg: SimConfig) -> None:
            results[name] = _post(daemon.url, {
                "name": name, "config": json.loads(cfg.to_json()),
            })

        threads = [
            threading.Thread(target=go, args=(n, c))
            for n, c in (("p1", c1), ("p2", c2), ("p1-again", c1))
        ]
        for t in threads:
            t.start()
        # All three must be queued before dispatch begins, or coalescing
        # would depend on HTTP timing.
        for _ in range(200):
            if daemon.stats_snapshot()["queue_depth"] == 3:
                break
            threading.Event().wait(0.05)
        assert daemon.stats_snapshot()["queue_depth"] == 3
        daemon.start_worker()
        for t in threads:
            t.join(timeout=180)
        counters = daemon.stats_snapshot()["counters"]

    for name, (status, body) in results.items():
        assert status == 200 and body["status"] == "served", (name, body)
    # The duplicate coalesced onto p1's computation and got the same row.
    assert counters["coalesced"] >= 1
    assert results["p1-again"][1]["row"] == results["p1"][1]["row"]

    direct = run_sweep([("p1", c1), ("p2", c2)], packed=True, quiet=True)
    by_point = {r["point"]: r for r in direct}
    for name, point in (("p1", "p1"), ("p2", "p2"), ("p1-again", "p1")):
        served = dict(results[name][1]["row"])
        served["point"] = point  # the duplicate served p1's named row
        assert _strip(served) == _strip(by_point[point])

    grid = run_grid([("p1", c1), ("p2", c2)])
    for entry, name in zip(grid, ("p1", "p2")):
        acc = entry["moments"]
        want = {
            "n": int(acc.n),
            "m1": {k: [int(x) for x in v] for k, v in acc.m1.items()},
            "m2": {k: [int(x) for x in v] for k, v in acc.m2.items()},
        }
        assert results[name][1]["moments"] == want


def test_cache_hit_bit_equal_with_provenance_chain(tmp_path, thread_guard):
    """A repeated query is an exact result-cache hit: identical row bytes,
    and its ``served_query`` lineage record cites the original answer as
    parent (the provenance the audit gate resolves)."""
    ledger = tmp_path / "lineage.jsonl"
    cfg = _cfg(21)
    with _armed(ledger):
        with _daemon(tmp_path) as daemon:
            daemon.start()
            q1 = _ask(daemon, "c1", cfg)
            q2 = _ask(daemon, "c1", cfg)
    assert q1.status == q2.status == "served"
    assert not q1.cache_hit and q2.cache_hit
    assert q2.row == q1.row  # bit-equal, not just statistically equal
    records = load_lineage(ledger)
    served = [r for r in records if r.get("kind") == "served_query"]
    assert len(served) == 2
    fresh = next(r for r in served if not r.get("cache_hit"))
    hit = next(r for r in served if r.get("cache_hit"))
    assert hit["content_sha256"] == fresh["content_sha256"]
    assert fresh["artifact_id"] in (hit.get("parents") or []) or (
        fresh["content_sha256"] in (hit.get("parents") or [])
    )
    assert q2.address in (hit.get("artifact_id"), hit.get("content_sha256"))


# ---------------------------------------------------------------------------
# The chaos matrix.


def test_queue_full_rejects_retryable_503_then_recovers(tmp_path, thread_guard):
    """Admission beyond the bounded queue is a loud, retryable 503 with
    depth and ETA — and once the worker drains the queue, the same query
    is admitted and served (recovery, zero silent drops)."""
    cfg = _cfg(31)
    with _daemon(tmp_path, queue_depth=1) as daemon:
        daemon.start_http()  # no worker yet: the queue cannot drain
        held = daemon.submit("held", cfg)
        status, body = _post(daemon.url, {
            "name": "overflow", "config": json.loads(cfg.to_json()),
        })
        assert status == 503
        assert body["status"] == "rejected" and body["retryable"] is True
        assert body["queue_depth"] >= 1 and body["eta_s"] is not None
        daemon.start_worker()
        assert held.done.wait(timeout=180) and held.status == "served"
        status2, body2 = _post(daemon.url, {
            "name": "overflow", "config": json.loads(cfg.to_json()),
        })
        assert status2 == 200 and body2["status"] == "served"
        counters = daemon.stats_snapshot()["counters"]
    assert counters["rejected"] == 1
    assert counters["served"] == 2


def test_wedged_dispatch_sheds_only_that_pack(tmp_path, thread_guard):
    """The committed serve-dispatch-hang drill: the FIRST packed dispatch
    wedges past its deadline. Only the queries riding that pack shed; a
    concurrent query in a different pack — and every later query — is
    served. The daemon never dies with its dispatch."""
    # A different miner count is a different pack_shape_key: "other" rides
    # its own pack, outside the wedged dispatch's blast radius.
    wedged_cfg, other_cfg = _cfg(41), _cfg(42, miners=(50, 30, 20))
    with _daemon(
        tmp_path, chaos=REPO / "drills" / "serve-dispatch-hang.json",
    ) as daemon:
        daemon.start_http()
        q_wedged = daemon.submit("wedged", wedged_cfg, deadline_s=30.0)
        q_rider = daemon.submit("rider", wedged_cfg, deadline_s=30.0)
        q_other = daemon.submit("other", other_cfg)
        daemon.start_worker()
        for q in (q_wedged, q_rider, q_other):
            assert q.done.wait(timeout=180)
        assert q_wedged.status == "shed" and "wedged" in q_wedged.reason
        assert q_rider.status == "shed"  # same pack, same blast radius
        assert q_other.status == "served"  # different pack: untouched
        # The drill's count is spent: the same shape now serves fine.
        q_retry = _ask(daemon, "retry", wedged_cfg)
        assert q_retry.status == "served"
        counters = daemon.stats_snapshot()["counters"]
    assert counters["shed"] == 2 and counters["served"] == 2


def test_cache_write_enospc_keeps_serving(tmp_path, thread_guard):
    """The committed serve-cache-enospc drill: a full disk at the served-row
    append disables persistence with one warning; the answer — and every
    later answer — is still served from memory."""
    with _daemon(
        tmp_path, chaos=REPO / "drills" / "serve-cache-enospc.json",
    ) as daemon:
        daemon.start_worker()
        q1 = _ask(daemon, "e1", _cfg(51))
        q2 = _ask(daemon, "e2", _cfg(52))
        snap = daemon.stats_snapshot()
        rows_path = daemon.state_dir / "rows.jsonl"
    assert q1.status == q2.status == "served"
    assert snap["counters"]["cache_write_failures"] == 1
    assert snap["rows_persisted"] is False
    assert not rows_path.exists()


def test_accept_transient_is_retryable_then_served(tmp_path, thread_guard):
    """The committed serve-accept-transient drill: one admission fault is a
    retryable rejection; the retry is admitted and served."""
    cfg = _cfg(61)
    with _daemon(
        tmp_path, chaos=REPO / "drills" / "serve-accept-transient.json",
    ) as daemon:
        daemon.start_worker()
        with pytest.raises(ServeReject) as exc:
            daemon.submit("t1", cfg)
        assert exc.value.retryable
        q = _ask(daemon, "t1", cfg)
        assert q.status == "served"
        counters = daemon.stats_snapshot()["counters"]
    assert counters["rejected"] == 1 and counters["served"] == 1


def test_deadline_expired_in_queue_is_shed_not_lost(tmp_path, thread_guard):
    """A query whose deadline passes while still queued is explicitly shed
    (loud), never silently dropped — and never dispatched."""
    with _daemon(tmp_path) as daemon:
        q = daemon.submit("late", _cfg(71), deadline_s=0.05)
        threading.Event().wait(0.2)  # let the deadline lapse pre-worker
        daemon.start_worker()
        assert q.done.wait(timeout=60)
        assert q.status == "shed" and "deadline" in q.reason


# ---------------------------------------------------------------------------
# Drain accounting.


def test_drain_accounts_for_every_accepted_query(tmp_path, thread_guard):
    """Graceful drain (what the SIGTERM handler triggers): admission stops
    (retryable rejection), the backlog finishes, and the accounting closes
    exactly — accepted == served + shed, written to drain.json."""
    cfgs = [_cfg(81), _cfg(82), _cfg(81, interval_s=300.0)]
    daemon = ServeDaemon(tmp_path / "serve")
    daemon.start()
    queries = [daemon.submit(f"d{i}", c) for i, c in enumerate(cfgs)]
    summary = daemon.drain()
    assert summary["clean"] is True
    assert summary["accepted"] == 3
    assert summary["accepted"] == summary["served"] + summary["shed"]
    for q in queries:
        assert q.done.is_set() and q.status in ("served", "shed")
    on_disk = json.loads((tmp_path / "serve" / "drain.json").read_text())
    assert on_disk == summary
    with pytest.raises(ServeReject):
        daemon.submit("post-drain", cfgs[0])


# ---------------------------------------------------------------------------
# The compile pin: a warmed mixed-shape storm compiles nothing.


def test_warmed_mixed_shape_storm_compiles_nothing(tmp_path, thread_guard):
    """After one warmup query per pack shape, a mixed-shape storm of fresh
    seeds (cache misses, both shapes interleaved) must stay at ZERO
    compiles — the engine cache, keyed by ``Engine.reuse_key``, is doing
    the serving."""
    with _daemon(tmp_path) as daemon:
        daemon.start_worker()
        _ask(daemon, "warm-8", _cfg(91, batch=8))
        _ask(daemon, "warm-4", _cfg(92, batch=4))
        with compile_count_guard(exact=0):
            for i in range(4):
                q = _ask(daemon, f"storm-{i}",
                         _cfg(100 + i, batch=8 if i % 2 == 0 else 4))
                assert q.status == "served" and not q.cache_hit


# ---------------------------------------------------------------------------
# Budgeted queries ride run_grid_adaptive.


def test_budgeted_query_converges_under_ci_target(tmp_path, thread_guard):
    with _daemon(tmp_path) as daemon:
        daemon.start_worker()
        q = _ask(daemon, "b1", _cfg(111), ci_target_stat="blocks_found",
                 ci_target_rel=0.5)
        assert q.status == "served"
        assert q.extra.get("converged") is True
        assert q.extra.get("rounds", 0) >= 1
        assert q.moments["n"] <= _cfg(111).runs


# ---------------------------------------------------------------------------
# The serve SLO profile + metrics derivation (jax-free).


def test_serve_slo_profile_partitions_the_gate():
    all_objs = load_objectives(root=REPO)
    serve_objs = load_objectives(root=REPO, profile="serve")
    default_objs = load_objectives(root=REPO, profile="default")
    assert {o.name for o in serve_objs} == {
        "serve-latency-p99", "serve-queue-depth-p99", "serve-shed-ratio",
        "serve-warmed-compiles",
    }
    assert len(default_objs) + len(serve_objs) == len(all_objs)
    assert all(o.profile == "default" for o in default_objs)
    with pytest.raises(SloConfigError):
        load_objectives(root=REPO, profile="no-such-profile")


def test_serve_spans_feed_the_serve_metrics():
    spans = [
        {"span": "serve_accept", "dur_s": 0.0, "attrs": {"depth": 3}},
        {"span": "serve_accept", "dur_s": 0.0, "attrs": {"depth": 1}},
        {"span": "serve_query", "dur_s": 1.5,
         "attrs": {"status": "served", "point": "a"}},
        {"span": "serve_query", "dur_s": 9.0,
         "attrs": {"status": "shed", "reason": "deadline"}},
        {"span": "serve_reject", "dur_s": 0.0, "attrs": {"depth": 5}},
        {"span": "serve_query", "dur_s": 0.1, "attrs": {}},  # torn: tolerated
    ]
    snap = snapshot_from_spans(spans, now=0.0)
    lat = snap.merged_hist("tpusim_serve_latency_seconds")
    assert lat.count == 1  # only served queries measure latency
    depth = snap.merged_hist("tpusim_serve_queue_depth")
    assert depth.count == 2
    by_status = {
        dict(k).get("status"): v
        for k, v in snap.counters["tpusim_serve_queries"].items()
    }
    assert by_status == {"served": 1.0, "shed": 1.0, "rejected": 1.0,
                         "unknown": 1.0}
    # The shed ratio counts resolved queries only: rejections are admission
    # control doing its job, torn spans contribute nothing.
    assert snap.gauges["tpusim_serve_shed_ratio"][()] == 0.5


def test_serve_profile_gates_green_on_a_healthy_snapshot():
    """The committed serve objectives pass a healthy synthetic snapshot —
    the same evaluation ``tpusim slo check --profile serve`` runs in the
    ci.sh serve leg."""
    spans = [
        {"span": "serve_accept", "dur_s": 0.0, "attrs": {"depth": 1}},
        {"span": "serve_query", "dur_s": 2.0,
         "attrs": {"status": "served", "point": "a"}},
    ]
    perf = [{"scenario": "loadgen", "metric": "compiles_per_query",
             "value": 0.0}]
    snap = snapshot_from_spans(spans, perf_rows=perf, now=0.0)
    results = evaluate_slos(load_objectives(root=REPO, profile="serve"), snap)
    assert slo_exit_code(results) == 0, results
