"""State-equivalence suite: the O(1) automaton vs the literal-chain oracle.

Two layers of evidence that the fixed-shape automaton (tpusim.state) is
observationally equivalent to the reference's materialized-chain model
(reference simulation.h:41-202, main.cpp:68-192, reproduced in
tpusim.backend.pychain):

1. ``test_event_stream_equivalence``: both models consume identical injected
   (interval, winner) event streams; the final automaton state must match the
   oracle's final chains block for block (exact mode) and the final per-miner
   statistics must agree exactly.

2. ``test_engine_matches_pychain_replay``: the full jitted engine — lax.scan
   chunks, re-basing, freezing, vmapped runs — is compared against a host-side
   replica that drives the chain oracle with the *same counter-based RNG
   draws* (same threefry bits, same step->draw mapping), so chunking and the
   32-bit relative-time scheme are covered end to end, not just the per-event
   kernels.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpusim.backend.pychain import ChainMiner, best_chain, earliest_arrival as chain_earliest
from tpusim.backend.pychain import run_chain_sim
from tpusim.config import MinerConfig, NetworkConfig, SimConfig
from tpusim.engine import Engine
from tpusim.runner import make_run_keys
from tpusim.sampling import interval_from_bits, winner_from_bits
from tpusim.state import TIME_CAP, make_params
from tpusim.testing import assert_state_matches_chains, drive_state_events

TIME_CAP_I = int(TIME_CAP)


def _draw_events(rng, config, n_events, zero_frac=0.0):
    """Pre-drawn (intervals, winners) with the reference's ns->ms truncation."""
    mean_ns = config.network.block_interval_s * 1e9
    intervals = np.rint(rng.exponential(mean_ns, size=n_events)).astype(np.int64) // 1_000_000
    if zero_frac:
        zeros = rng.random(n_events) < zero_frac
        intervals = np.where(zeros, 0, intervals)
    pcts = np.array([m.hashrate_pct for m in config.network.miners], dtype=np.float64)
    winners = rng.choice(len(pcts), size=n_events, p=pcts / pcts.sum())
    return intervals.tolist(), winners.tolist()


HONEST_3 = NetworkConfig(
    miners=(
        MinerConfig(hashrate_pct=50, propagation_ms=2000),
        MinerConfig(hashrate_pct=30, propagation_ms=2000),
        MinerConfig(hashrate_pct=20, propagation_ms=2000),
    ),
    block_interval_s=20.0,
)
HETERO_4 = NetworkConfig(
    miners=(
        MinerConfig(hashrate_pct=40, propagation_ms=5000),
        MinerConfig(hashrate_pct=30, propagation_ms=100),
        MinerConfig(hashrate_pct=20, propagation_ms=1500),
        MinerConfig(hashrate_pct=10, propagation_ms=0),
    ),
    block_interval_s=20.0,
)
SELFISH_3 = NetworkConfig(
    miners=(
        MinerConfig(hashrate_pct=40, propagation_ms=500, selfish=True),
        MinerConfig(hashrate_pct=35, propagation_ms=500),
        MinerConfig(hashrate_pct=25, propagation_ms=500),
    ),
    block_interval_s=20.0,
)


@pytest.mark.parametrize(
    "network,mode,zero_frac",
    [
        (HONEST_3, "exact", 0.0),
        (HONEST_3, "exact", 0.15),  # 0 ms interval draws: the while-drain path
        (HONEST_3, "fast", 0.0),
        (HETERO_4, "exact", 0.0),
        (HETERO_4, "fast", 0.0),
        (SELFISH_3, "exact", 0.0),
        (SELFISH_3, "exact", 0.1),
    ],
)
@pytest.mark.parametrize("seed", [0, 1])
def test_event_stream_equivalence(network, mode, zero_frac, seed):
    config = SimConfig(
        network=network,
        duration_ms=1_200_000,  # 20 min at 20 s interval: ~60 blocks, many races
        runs=1,
        mode=mode,
        group_slots=8,
    )
    rng = np.random.default_rng(100 * seed + len(network.miners) + int(zero_frac * 100))
    intervals, winners = _draw_events(rng, config, 400, zero_frac)
    state, stats = drive_state_events(config, intervals, winners)
    oracle = run_chain_sim(config, intervals, winners)

    assert np.asarray(stats["blocks_found"]).tolist() == oracle["blocks_found"]
    assert np.asarray(stats["stale_blocks"]).tolist() == oracle["stale_blocks"]
    assert int(stats["best_height"]) == oracle["best_height"]
    np.testing.assert_allclose(stats["blocks_share"], oracle["blocks_share"], rtol=1e-6)
    np.testing.assert_allclose(stats["stale_rate"], oracle["stale_rate"], rtol=1e-6)
    assert int(state.overflow) == 0

    if mode == "exact":
        # Full chain-level state equivalence, not just the stats projection.
        assert_state_matches_chains(state, oracle["chains"], config.duration_ms, config)


def _replay_pychain_with_engine_draws(config: SimConfig, run_idx: int, steps: int) -> dict:
    """Host-side replica of Engine.run_batch for ONE run, driving the literal
    chain model with the exact same threefry draws and step structure
    (tpusim.engine._step + chunking/re-basing expressed in absolute time).
    ``steps`` must be the engine's *resolved* chunk_steps — the engine clamps
    the configured value to the Poisson bound, and a mismatched step count
    silently shifts the chunk->key mapping."""
    params = make_params(config)
    run_key = make_run_keys(config.seed, run_idx, 1)[0]

    bits0 = jax.random.bits(jax.random.fold_in(run_key, 0), (2,), jnp.uint32)
    next_block = int(interval_from_bits(bits0[1], params.mean_interval_ms))

    miners = [
        ChainMiner(idx=i, propagation_ms=mc.propagation_ms, selfish=mc.selfish)
        for i, mc in enumerate(config.network.miners)
    ]
    duration = config.duration_ms
    t = 0
    base = 0  # absolute time of the current chunk's origin
    best_len_prev = 0
    chunk = 0
    while duration - base > 0:
        cap_abs = base + min(duration - base, TIME_CAP_I)
        key = jax.random.fold_in(run_key, 1 + chunk)
        bits = np.asarray(jax.random.bits(key, (steps, 2), jnp.uint32))
        ws = np.asarray(jax.vmap(winner_from_bits, in_axes=(0, None))(bits[:, 0], params.thresholds))
        dts = np.asarray(
            jax.vmap(interval_from_bits, in_axes=(0, None))(bits[:, 1], params.mean_interval_ms)
        )
        for s in range(steps):
            if t >= cap_abs:
                break  # frozen for the rest of this chunk (bits still consumed)
            found_due = t == next_block
            if found_due:
                miners[int(ws[s])].found_block(t, best_len_prev)
                next_block = t + int(dts[s])
            if not (found_due and next_block == t):
                best = best_chain(miners, t)
                for miner in miners:
                    miner.notify(best, t)
                best_len_prev = len(best)
            arrival = chain_earliest(miners, t)
            t = max(min(next_block, arrival if arrival is not None else next_block), t)
        base = t  # rebase: elapsed-this-chunk = t - base_old
        chunk += 1

    final_best = best_chain(miners, duration)
    found = [sum(1 for owner, _ in final_best if owner == m.idx) for m in miners]
    denom = max(len(final_best), 1)
    return {
        "blocks_found": found,
        "blocks_share": [f / denom if f > 0 else 0.0 for f in found],
        "stale_rate": [m.stale / f if f > 0 else 0.0 for m, f in zip(miners, found)],
        "stale_blocks": [m.stale for m in miners],
        "best_height": len(final_best),
    }


@pytest.mark.parametrize(
    "network,mode",
    [(HONEST_3, "fast"), (HONEST_3, "exact"), (SELFISH_3, "exact"), (HETERO_4, "fast")],
)
def test_engine_matches_pychain_replay(network, mode):
    runs = 4
    config = SimConfig(
        network=network,
        duration_ms=1_200_000,
        runs=runs,
        batch_size=runs,
        mode=mode,
        group_slots=8,
        chunk_steps=48,  # force several chunks so re-basing is on the path
        seed=13,
    )
    engine = Engine(config)
    sums = engine.run_batch(make_run_keys(config.seed, 0, runs))

    expect = [
        _replay_pychain_with_engine_draws(config, i, engine.chunk_steps) for i in range(runs)
    ]
    n_m = config.network.n_miners
    for name, key in [
        ("blocks_found_sum", "blocks_found"),
        ("stale_blocks_sum", "stale_blocks"),
    ]:
        want = [sum(e[key][i] for e in expect) for i in range(n_m)]
        assert np.asarray(sums[name]).tolist() == want, name
    assert int(sums["best_height_sum"]) == sum(e["best_height"] for e in expect)
    for name, key in [("blocks_share_sum", "blocks_share"), ("stale_rate_sum", "stale_rate")]:
        want = [sum(e[key][i] for e in expect) for i in range(n_m)]
        np.testing.assert_allclose(np.asarray(sums[name]), want, rtol=1e-5, err_msg=name)
    assert int(sums["overflow_sum"]) == 0
