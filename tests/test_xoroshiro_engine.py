"""End-to-end tests of the rng="xoroshiro" engine mode: the reference's
sequential xoroshiro128++ streams surfaced as an engine sampling mode, giving
a draw-for-draw A/B between the JAX engine and the native C++ backend on tiny
configs (VERDICT r3 item 9; reference RNG: xoroshiro128++.h:1-40, per-run
streams main.cpp:131-134 re-done deterministically in native/simcore.cpp).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from tpusim.config import MinerConfig, NetworkConfig, SimConfig
from tpusim.engine import Engine

TINY = SimConfig(
    network=NetworkConfig(
        miners=(
            MinerConfig(hashrate_pct=50, propagation_ms=5000),
            MinerConfig(hashrate_pct=30, propagation_ms=2000),
            MinerConfig(hashrate_pct=20, propagation_ms=0),
        )
    ),
    duration_ms=2 * 86_400_000,
    runs=16,
    batch_size=16,
    seed=42,
    rng="xoroshiro",
)


def test_bit_level_ab_vs_native_backend():
    """The contract this mode exists for: with float64 (subprocess under
    JAX_ENABLE_X64) every integer observable — per-miner blocks found, stale
    blocks, best height — is bit-identical between the JAX engine and the
    native backend on the same (seed, run) streams; the per-run ratio means
    differ only by float32-vs-double accumulation (~1e-7)."""
    from tpusim.backend.cpp import run_simulation_cpp
    from tpusim.probe import TUNNEL_TRIGGER_ENV

    env = os.environ.copy()
    env.pop(TUNNEL_TRIGGER_ENV, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    repo = str(Path(__file__).parent.parent)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, str(Path(__file__).parent / "xoro_ab_worker.py"), TINY.to_json()],
        capture_output=True, text=True, env=env, timeout=600,
        cwd=str(Path(__file__).parent.parent),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    jax_sums = json.loads(proc.stdout.strip().splitlines()[-1])

    cpp = run_simulation_cpp(TINY, threads=1)
    runs = TINY.runs
    np.testing.assert_array_equal(
        np.asarray(jax_sums["blocks_found_sum"], dtype=np.int64),
        np.asarray([m.blocks_found_mean * runs for m in cpp.miners], dtype=np.int64),
    )
    np.testing.assert_array_equal(
        np.asarray(jax_sums["stale_blocks_sum"], dtype=np.int64),
        np.asarray([m.stale_blocks_mean * runs for m in cpp.miners], dtype=np.int64),
    )
    assert int(jax_sums["best_height_sum"]) == round(cpp.best_height_mean * runs)
    np.testing.assert_allclose(
        np.asarray(jax_sums["blocks_share_sum"]) / runs,
        np.asarray([m.blocks_share_mean for m in cpp.miners]),
        atol=5e-7, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(jax_sums["stale_rate_sum"]) / runs,
        np.asarray([m.stale_rate_mean for m in cpp.miners]),
        atol=5e-7, rtol=0,
    )


def test_xoro_device_loop_matches_host_loop():
    engine = Engine(TINY)
    keys = engine.make_keys(0, TINY.runs)
    device = engine.run_batch(keys)
    host = engine.run_batch(keys, host_loop=True)
    for name in device:
        np.testing.assert_array_equal(
            np.asarray(device[name]), np.asarray(host[name]), err_msg=name
        )


def test_xoro_batch_split_is_batching_invariant():
    """Per-run streams are keyed by the GLOBAL run index, so two batches of 8
    must combine to one batch of 16 — additive stats sum, *_max telemetry
    keys (deepest reorg, busy-chunk count) combine by max, i.e. exactly the
    engine.combine_sums merge rule."""
    engine = Engine(TINY)
    whole = engine.run_batch(engine.make_keys(0, 16))
    a = engine.run_batch(engine.make_keys(0, 8))
    b = engine.run_batch(engine.make_keys(8, 8))
    from tpusim.engine import combine_sums

    merged = combine_sums(a, b)
    for name in whole:
        if name == "runs":
            continue
        np.testing.assert_allclose(
            np.asarray(whole[name]), np.asarray(merged[name]),
            rtol=1e-6, err_msg=name,
        )


def test_pallas_refuses_xoroshiro():
    pytest.importorskip("jax.experimental.pallas")
    from tpusim.pallas_engine import PallasEngine

    with pytest.raises(ValueError, match="xoroshiro"):
        PallasEngine(TINY)


def test_rng_is_part_of_config_serialization_and_fingerprint(tmp_path):
    """A checkpoint written under one generator must not merge with the
    other's sums."""
    from tpusim.runner import run_simulation_config

    ck = tmp_path / "ck.npz"
    small = dataclasses.replace(TINY, runs=4, batch_size=4)
    assert SimConfig.from_json(small.to_json()).rng == "xoroshiro"
    run_simulation_config(small, use_all_devices=False, checkpoint_path=ck)
    with pytest.raises(ValueError, match="different config"):
        run_simulation_config(
            dataclasses.replace(small, rng="threefry"),
            use_all_devices=False, checkpoint_path=ck,
        )


def test_cli_rng_flag(capsys):
    from tpusim.cli import main as cli_main

    rc = cli_main(
        [
            "--runs", "2", "--days", "1", "--hashrates", "60,40",
            "--batch-size", "2", "--rng", "xoroshiro", "--quiet",
            "--single-device",
        ]
    )
    assert rc == 0
    assert "After running 2 simulations" in capsys.readouterr().out
