"""Test environment: force CPU with 8 virtual devices so multi-chip sharding
paths are exercised without TPU hardware (the driver validates the real
multi-chip path separately via __graft_entry__.dryrun_multichip).

This container's sitecustomize imports jax and registers a remote TPU PJRT
plugin at interpreter startup, so env vars alone are too late — use
jax.config.update before any backend is initialized. Eager per-op dispatch
through the remote TPU tunnel is also catastrophically slow, which is its own
reason tests must run on local CPU.
"""

import os

from tpusim.probe import TUNNEL_TRIGGER_ENV

os.environ.pop(TUNNEL_TRIGGER_ENV, None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tpusim.compat import set_cpu_device_count  # noqa: E402

set_cpu_device_count(8)

import pytest  # noqa: E402

from tpusim.testing import thread_leak_guard  # noqa: E402


@pytest.fixture
def thread_guard():
    """Opt-in thread-leak guard (the runtime half of lint JX015-JX019):
    the test must leave zero new non-daemon threads and at most one new
    daemon thread — the allowance covers the process-wide reusable fetch
    watchdog (tpusim.chaos) the first guarded test may lazily spawn."""
    with thread_leak_guard(max_daemon_delta=1) as census:
        yield census
