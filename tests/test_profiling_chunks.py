"""tpusim.profiling.time_chained_chunks and runner.make_engine strictness.

The chained-chunk timer is the canonical kernel-timing discipline (every
round-5 routing decision rests on its numbers), and make_engine's
tuning-override strictness protects on-hardware sweeps from silently
measuring the wrong engine — both deserve contract tests, not just use.
"""

from __future__ import annotations

import pytest

from tpusim import SimConfig, default_network
from tpusim.engine import Engine
from tpusim.profiling import time_chained_chunks
from tpusim.runner import make_engine, make_run_keys


def _small_config() -> SimConfig:
    return SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=86_400_000,
        runs=16,
        batch_size=16,
        seed=3,
        chunk_steps=32,
    )


def test_time_chained_chunks_contract():
    config = _small_config()
    engine = Engine(config)
    keys = make_run_keys(config.seed, 0, config.runs)
    r = time_chained_chunks(engine, keys, n_chunks=3, repeats=2)
    assert r["engine"] == "Engine"
    assert r["runs"] == 16
    assert r["n_chunks"] == 3
    assert r["chunk_steps"] == 32
    # The program must actually run: a dead-code-eliminated loop shows up as
    # a microsecond-scale per-chunk time (documented failure mode in the
    # profiling docstring); 32 steps x 16 runs cannot finish in under 10 us
    # even on a fast CPU.
    assert r["s_per_chunk"] > 1e-5
    # Both fields are independently rounded for the JSONL artifact, so the
    # identity only holds to rounding precision.
    assert r["us_per_step"] == pytest.approx(r["s_per_chunk"] / 32 * 1e6, rel=1e-2)
    assert len(r["repeats_s"]) == 2
    assert r["spread_pct"] >= 0.0


def test_make_engine_rejects_tuning_overrides_off_tpu():
    """On a platform that auto-routes to the scan engine, kernel-tuning
    overrides must raise instead of silently measuring the scan engine
    (runner.make_engine) — the failure mode that would corrupt every
    on-hardware sweep point captured through the runner."""
    config = _small_config()
    with pytest.raises(ValueError, match="auto-routes"):
        make_engine(config, tile_runs=256)
    with pytest.raises(ValueError, match="auto-routes"):
        make_engine(config, step_block=32)
    # Without overrides the auto route quietly picks the scan engine.
    assert type(make_engine(config)) is Engine
