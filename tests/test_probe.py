"""tpusim.probe: the killable subprocess backend probe."""

from __future__ import annotations

import sys

from tpusim.probe import TUNNEL_TRIGGER_ENV, probe_backend


def test_probe_reports_cpu_platform(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv(TUNNEL_TRIGGER_ENV, raising=False)
    msgs = []
    assert probe_backend(timeout_s=120, retries=1, log=msgs.append) == "cpu"
    assert not msgs


def test_probe_failure_returns_none_with_log(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "definitely-not-a-platform")
    monkeypatch.delenv(TUNNEL_TRIGGER_ENV, raising=False)
    msgs = []
    assert probe_backend(timeout_s=120, retries=1, log=msgs.append) is None
    assert msgs and "probe failed" in msgs[0]


def test_probe_timeout_path(monkeypatch):
    # A probe that cannot finish in time must be killed and reported, not
    # hang the caller — simulate with an interpreter that sleeps in
    # sitecustomize-equivalent position via PYTHONSTARTUP-independent trick:
    # point PYTHONPATH at nothing and give the real probe far too little
    # time to even start the interpreter+jax import.
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv(TUNNEL_TRIGGER_ENV, raising=False)
    msgs = []
    assert probe_backend(timeout_s=0.01, retries=1, log=msgs.append) is None
    assert msgs and "timed out" in msgs[0]
    assert sys.executable  # smoke: the probe used this interpreter
