"""Chaos degradation matrix (tpusim.chaos): every documented recovery path —
batch retry with backoff, retry exhaustion failing loud, pallas->scan
engine_fallback, pipelined-fetch watchdog degradation, checkpoint resume
after SIGKILL at each save boundary, truncated-checkpoint restart, sweep
resume around a poisoned point, probe timeout fallback, telemetry ENOSPC
degradation — driven by deterministic injected faults, with every recovered
run pinned BIT-EQUAL to the fault-free run at the same seed. Plus the
zero-overhead guarantee: with no chaos plan the compiled programs are
unchanged and warmed dispatch stays recompile-free.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import jax
import pytest

from tpusim.chaos import (
    ChaosError,
    ChaosInjector,
    ChaosPermanentError,
    ChaosPlan,
    FaultSpec,
    PipelineStallError,
    as_injector,
    fetch_with_deadline,
)
from tpusim.cli import main as cli_main
from tpusim.config import SimConfig, default_network
from tpusim.engine import Engine
from tpusim.probe import TUNNEL_TRIGGER_ENV, probe_backend, probe_or_force_cpu
from tpusim.runner import run_simulation_config
from tpusim.sweep import run_sweep
from tpusim.telemetry import TelemetryRecorder, load_spans
from tpusim.testing import compile_count_guard

SMALL = SimConfig(
    network=default_network(propagation_ms=1000),
    duration_ms=10**8,
    runs=16,
    batch_size=8,
    seed=3,
)

#: Shared across the module (tpusim.runner.make_engine reuse cache): every
#: same-shape run_simulation_config call rebinds one warm engine instead of
#: recompiling, which is what keeps this matrix tier-1-affordable.
ENGINE_CACHE: dict = {}


def plan(*faults: dict) -> ChaosPlan:
    return ChaosPlan(faults=[FaultSpec(**f) for f in faults])


def run_small(**kw):
    kw.setdefault("use_all_devices", False)
    kw.setdefault("engine_cache", ENGINE_CACHE)
    return run_simulation_config(SMALL, **kw)


@pytest.fixture(scope="module")
def baseline():
    """The fault-free run every recovered run must match bit-for-bit."""
    return run_small()


def assert_results_equal(a, b):
    assert a.runs == b.runs
    assert a.table() == b.table()
    assert a.best_height_mean == b.best_height_mean
    assert a.overflow_total == b.overflow_total
    for ma, mb in zip(a.miners, b.miners):
        assert ma == mb  # exact float equality: the bit-equality discipline


# ---------------------------------------------------------------------------
# Retry policy: transient faults retried with backoff, bit-equal recovery;
# exhaustion and permanent faults fail loud.


def test_retry_then_succeed_bit_equal(baseline, tmp_path):
    sleeps: list[float] = []
    rec = TelemetryRecorder(tmp_path / "led.jsonl")
    res = run_small(
        chaos=plan({"point": "engine.dispatch", "kind": "transient",
                    "count": 2, "when": {"batch": 1}}),
        sleeper=sleeps.append, telemetry=rec,
    )
    rec.close()
    assert_results_equal(res, baseline)
    # Bounded exponential backoff with deterministic jitter: base 0.5 s
    # doubling per attempt, jitter in [0, 25%].
    assert len(sleeps) == 2
    assert 0.5 <= sleeps[0] <= 0.5 * 1.25
    assert 1.0 <= sleeps[1] <= 1.0 * 1.25
    spans = load_spans(rec.path)
    retries = [s for s in spans if s["span"] == "retry"]
    assert [r["attrs"]["backoff_s"] for r in retries] == [
        round(s, 3) for s in sleeps
    ]
    assert sum(1 for s in spans if s["span"] == "chaos") == 2
    # The jitter is a pure function of (seed, start, attempt): a re-drill
    # backs off identically.
    sleeps2: list[float] = []
    res2 = run_small(
        chaos=plan({"point": "engine.dispatch", "kind": "transient",
                    "count": 2, "when": {"batch": 1}}),
        sleeper=sleeps2.append,
    )
    assert sleeps2 == sleeps
    assert_results_equal(res2, baseline)


def test_retry_exhaustion_fails_loud():
    sleeps: list[float] = []
    with pytest.raises(ChaosError, match="injected transient"):
        run_small(
            chaos=plan({"point": "engine.dispatch", "kind": "transient",
                        "count": -1}),
            max_retries=1, sleeper=sleeps.append,
        )
    assert len(sleeps) == 1  # one backoff, then exhausted -> raise


def test_permanent_fault_fails_fast_no_retry():
    sleeps: list[float] = []
    with pytest.raises(ChaosPermanentError, match="injected permanent"):
        run_small(
            chaos=plan({"point": "engine.dispatch", "kind": "permanent"}),
            sleeper=sleeps.append,
        )
    assert sleeps == []  # config-class errors never consume a retry


def test_async_dispatch_fault_retried_synchronously(baseline, caplog, tmp_path):
    """A fault at the pipelined dispatch stage is absorbed without consuming
    a retry attempt: the finalize stage re-dispatches synchronously."""
    rec = TelemetryRecorder(tmp_path / "led.jsonl")
    with caplog.at_level("ERROR", logger="tpusim"):
        res = run_small(
            chaos=plan({"point": "engine.dispatch_async", "kind": "transient",
                        "count": 1}),
            telemetry=rec,
        )
    rec.close()
    assert_results_equal(res, baseline)
    assert any("will retry synchronously" in r.message for r in caplog.records)
    spans = load_spans(rec.path)
    assert not [s for s in spans if s["span"] == "retry"]
    assert [s for s in spans if s["span"] == "chaos"]


def test_permanent_fault_fails_fast_on_pallas_too():
    """The pallas->scan fallback exists for real Mosaic ValueErrors; it must
    NOT absorb an injected permanent fault — fail-fast holds on every
    engine, or a drill that must fail loud reports a recovery."""
    config = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=86_400_000, runs=512, batch_size=512, seed=9,
    )
    with pytest.raises(ChaosPermanentError, match="injected permanent"):
        run_simulation_config(
            config, engine="pallas", use_all_devices=False,
            chaos=plan({"point": "engine.dispatch", "kind": "permanent",
                        "when": {"engine": "PallasEngine"}}),
        )


# ---------------------------------------------------------------------------
# Engine fallback: an injected pallas-side fault lands on the scan twin.


def test_engine_fallback_bit_equal(tmp_path):
    config = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=86_400_000, runs=512, batch_size=512, seed=9,
    )
    scan = run_simulation_config(config, engine="scan", use_all_devices=False,
                                 engine_cache=ENGINE_CACHE)
    rec = TelemetryRecorder(tmp_path / "led.jsonl")
    via_pallas = run_simulation_config(
        config, engine="pallas", use_all_devices=False,
        chaos=plan({"point": "engine.dispatch", "kind": "transient",
                    "count": 1, "when": {"engine": "PallasEngine"}}),
        telemetry=rec,
    )
    rec.close()
    assert scan.table() == via_pallas.table()
    assert scan.best_height_mean == via_pallas.best_height_mean
    spans = load_spans(rec.path)
    assert [s for s in spans if s["span"] == "chaos"]
    assert [s for s in spans if s["span"] == "engine_fallback"]


# ---------------------------------------------------------------------------
# Pipelined dispatch: injected hang and live watchdog, both bit-equal.


PIPE = dataclasses.replace(SMALL, runs=16, batch_size=16, chunk_steps=64)


@pytest.fixture(scope="module")
def pipe_engine():
    return Engine(PIPE)


def test_pipelined_hang_degrades_to_synchronous(pipe_engine, caplog):
    keys = pipe_engine.make_keys(0, PIPE.runs)
    base = pipe_engine.run_batch(keys)
    inj = ChaosInjector(plan({"point": "pipeline.flag_fetch", "kind": "hang",
                              "count": 1}))
    pipe_engine.chaos = inj
    try:
        with caplog.at_level("WARNING", logger="tpusim"):
            out = pipe_engine.run_batch(keys, pipelined=True)
    finally:
        pipe_engine.chaos = None
    assert len(inj.fired) == 1
    assert any("re-running the batch synchronously" in r.message
               for r in caplog.records)
    assert base.keys() == out.keys()
    for k in base:
        np.testing.assert_array_equal(np.asarray(base[k]), np.asarray(out[k]),
                                      err_msg=k)


def test_pipelined_watchdog_live_fetch_bit_equal(pipe_engine):
    """With a (generous) deadline armed, every done-flag fetch really goes
    through fetch_with_deadline's watchdog thread — and stays bit-equal."""
    keys = pipe_engine.make_keys(0, PIPE.runs)
    base = pipe_engine.run_batch(keys)
    pipe_engine.flag_fetch_timeout_s = 60.0
    try:
        out = pipe_engine.run_batch(keys, pipelined=True)
    finally:
        pipe_engine.flag_fetch_timeout_s = None
    for k in base:
        np.testing.assert_array_equal(np.asarray(base[k]), np.asarray(out[k]),
                                      err_msg=k)


def test_fetch_with_deadline_unit():
    assert fetch_with_deadline(lambda: 7, 5.0) == 7
    with pytest.raises(KeyError):  # exceptions relay unchanged
        fetch_with_deadline(lambda: {}[0], 5.0)
    release = threading.Event()
    try:
        with pytest.raises(PipelineStallError, match="watchdog deadline"):
            fetch_with_deadline(lambda: release.wait(30.0), 0.05)
    finally:
        release.set()  # unblock the abandoned worker thread


def _watchdog_threads():
    return [
        t for t in threading.enumerate()
        if t.name == "tpusim-fetch-watchdog" and t.is_alive()
    ]


def _await_watchdog_count(n, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if len(_watchdog_threads()) <= n:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"expected <= {n} fetch-watchdog thread(s), have "
        f"{[t.name for t in _watchdog_threads()]}"
    )


def test_fetch_with_deadline_bounded_watchdog_threads(thread_guard):
    # The historical bug class: one spawned thread per call. The reusable
    # worker must serve many calls from ONE daemon thread (thread_guard's
    # max_daemon_delta=1 allowance IS that worker).
    for i in range(32):
        assert fetch_with_deadline(lambda i=i: i * i, 5.0) == i * i
    assert len(_watchdog_threads()) <= 1


def test_fetch_with_deadline_stall_abandons_then_reaps(thread_guard):
    # A deadline miss abandons the wedged worker; the next call spawns a
    # fresh one (bounded: at most stalled+1 alive while wedged), and the
    # abandoned worker retires ON ITS OWN once its fetch unwedges — the
    # fix for the documented leaked-thread-per-batch caveat.
    release = threading.Event()
    with pytest.raises(PipelineStallError, match="watchdog deadline"):
        fetch_with_deadline(lambda: release.wait(30.0), 0.05)
    assert fetch_with_deadline(lambda: 11, 5.0) == 11  # service restored
    assert len(_watchdog_threads()) <= 2  # one wedged + one live, never more
    release.set()  # unwedge: the abandoned worker must now exit by itself
    _await_watchdog_count(1)
    # The stale result was dropped, not delivered to a later caller.
    assert fetch_with_deadline(lambda: 13, 5.0) == 13


# ---------------------------------------------------------------------------
# Checkpoint durability: SIGKILL at each save boundary, truncated npz.


@pytest.mark.parametrize("phase", ["begin", "pre_replace", "post_replace"])
def test_checkpoint_resume_after_sigkill(phase, baseline, tmp_path, caplog):
    ck = tmp_path / "ck.npz"
    tmp_file = ck.with_suffix(".tmp.npz")
    repo = str(Path(__file__).parent.parent)
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop(TUNNEL_TRIGGER_ENV, None)
    worker = Path(__file__).parent / "chaos_kill_worker.py"
    r = subprocess.run(
        [sys.executable, str(worker), SMALL.to_json(), phase, str(ck)],
        capture_output=True, text=True, timeout=240, env=env, cwd=repo,
    )
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stdout, r.stderr)
    assert "UNREACHABLE" not in r.stdout
    if phase == "begin":
        assert not ck.exists() and not tmp_file.exists()
    elif phase == "pre_replace":
        # The crash window the stale-tmp sweep exists for.
        assert tmp_file.exists() and not ck.exists()
    else:
        assert ck.exists() and not tmp_file.exists()
    with caplog.at_level("WARNING", logger="tpusim"):
        resumed = run_small(checkpoint_path=ck)
    assert_results_equal(resumed, baseline)
    assert not tmp_file.exists()
    if phase == "pre_replace":
        assert any("stale checkpoint temp file" in rec.message
                   for rec in caplog.records)


def test_checkpoint_truncated_npz_restarts_from_zero(baseline, tmp_path, caplog):
    ck = tmp_path / "ck.npz"
    run_small(checkpoint_path=ck)
    data = ck.read_bytes()
    ck.write_bytes(data[: int(len(data) * 0.6)])  # killed window mid-write
    with caplog.at_level("WARNING", logger="tpusim"):
        res = run_small(checkpoint_path=ck)
    assert any("restarting this point from zero" in rec.message
               for rec in caplog.records)
    assert_results_equal(res, baseline)


def test_checkpoint_foreign_npz_still_fails_loud(tmp_path):
    """Corruption tolerance must not extend to a structurally intact npz
    that simply is not our checkpoint (wrong file / future schema): the zip
    central directory is written last, so a truncated file can never parse
    as a valid zip missing only our keys — a missing __config__ means a
    FOREIGN file, which must never be silently overwritten."""
    ck = tmp_path / "ck.npz"
    np.savez(ck, something_else=np.arange(3))
    with pytest.raises(KeyError):
        run_small(checkpoint_path=ck)


# ---------------------------------------------------------------------------
# Sweep: a poisoned point fails loud; --resume fills exactly the hole.


def _sweep_points():
    net = default_network(propagation_ms=1000)
    return [
        (name, SimConfig(network=net, runs=8, batch_size=8, duration_ms=10**8))
        for name in ("pt-a", "pt-b", "pt-c")
    ]


def _rows(path: Path) -> list[dict]:
    return [json.loads(ln) for ln in path.read_text().splitlines() if ln.strip()]


def test_sweep_poisoned_point_then_resume_bit_equal(tmp_path):
    fresh_out = tmp_path / "fresh.jsonl"
    run_sweep(_sweep_points(), out_path=fresh_out, quiet=True,
              engine_cache=ENGINE_CACHE)

    out = tmp_path / "sweep.jsonl"
    with pytest.raises(ChaosPermanentError):
        run_sweep(
            _sweep_points(), out_path=out, quiet=True,
            engine_cache=ENGINE_CACHE,
            chaos=plan({"point": "sweep.point", "kind": "permanent",
                        "when": {"target": "pt-b"}}),
        )
    assert [r["point"] for r in _rows(out)] == ["pt-a"]

    # The drill's recovery: identical command, --resume, no chaos.
    run_sweep(_sweep_points(), out_path=out, resume=True, quiet=True,
              engine_cache=ENGINE_CACHE)
    got, want = _rows(out), _rows(fresh_out)
    assert [r["point"] for r in got] == ["pt-a", "pt-b", "pt-c"]
    for g, w in zip(got, want):
        for r in (g, w):  # wall-clock attrs differ; statistics must not
            r.pop("elapsed_s", None)
            r.pop("compile_s", None)
        assert g == w


# ---------------------------------------------------------------------------
# Probe: injected dead tunnel -> retries with backoff -> CPU fallback.


def test_probe_injected_timeouts_then_none(monkeypatch):
    msgs: list[str] = []
    sleeps: list[float] = []
    inj = ChaosInjector(plan({"point": "probe.attempt", "kind": "hang",
                              "count": -1}))
    assert probe_backend(retries=3, log=msgs.append, chaos=inj,
                         sleeper=sleeps.append) is None
    assert len(inj.fired) == 3
    assert "timed out" in msgs[0]
    assert sleeps == [10.0, 20.0]  # linear probe backoff, injectable sleeper


def test_probe_transient_fault_then_real_success(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv(TUNNEL_TRIGGER_ENV, raising=False)
    msgs: list[str] = []
    inj = ChaosInjector(plan({"point": "probe.attempt", "kind": "transient",
                              "count": 1}))
    assert probe_backend(timeout_s=120, retries=2, log=msgs.append,
                         chaos=inj, sleeper=lambda s: None) == "cpu"
    assert "probe failed" in msgs[0]


def test_probe_or_force_cpu_on_injected_dead_tunnel(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv(TUNNEL_TRIGGER_ENV, "10.0.0.1")
    inj = ChaosInjector(plan({"point": "probe.attempt", "kind": "hang",
                              "count": -1}))
    assert probe_or_force_cpu(retries=2, log=lambda m: None, chaos=inj,
                              sleeper=lambda s: None) is None
    # The fallback cleared the tunnel trigger and pinned this process to CPU.
    assert TUNNEL_TRIGGER_ENV not in os.environ
    assert os.environ["JAX_PLATFORMS"] == "cpu"


# ---------------------------------------------------------------------------
# Telemetry: write-side faults degrade the recorder, never the run; a torn
# ledger stays readable and reportable.


def test_telemetry_enospc_degrades_not_dies(baseline, tmp_path, caplog):
    rec = TelemetryRecorder(tmp_path / "led.jsonl")
    with caplog.at_level("WARNING", logger="tpusim"):
        res = run_small(
            chaos=plan({"point": "telemetry.write", "kind": "enospc",
                        "count": 1}),
            telemetry=rec,
        )
    rec.close()
    assert_results_equal(res, baseline)
    assert any("disabling the recorder" in r.message for r in caplog.records)
    spans = load_spans(rec.path)
    # The injector's own span (written before the fault acted) survives; the
    # faulted span and everything after are dropped, not torn.
    assert [s["span"] for s in spans] == ["chaos"]


def test_export_write_failure_is_clean(tmp_path):
    """A torn trace-export write (ENOSPC, bad target) fails as one clean
    line with the partial artifact removed — never a half-written JSON that
    looks like a deliverable."""
    from tpusim.flight_export import _write_artifact

    target = tmp_path / "trace.json"
    target.mkdir()  # write_text -> IsADirectoryError, an OSError
    with pytest.raises(SystemExit, match="partial file removed"):
        _write_artifact(target, "{}")


def test_torn_ledger_loads_and_reports(tmp_path, capsys):
    led = tmp_path / "led.jsonl"
    rec = TelemetryRecorder(led)
    rec.emit("batch", runs=4, dur_s=0.5)
    rec.emit("run", runs=4, dur_s=1.0)
    rec.close()
    # ENOSPC / SIGKILL mid-write: a trailing fragment cut inside a
    # multi-byte sequence.
    with led.open("ab") as fh:
        fh.write(b'{"run_id": "x", "span": "batch", "attrs"\xe2\x82')
    spans = load_spans(led)
    assert [s["span"] for s in spans] == ["batch", "run"]
    assert cli_main(["report", str(led)]) == 0
    out = capsys.readouterr().out
    assert "Phase breakdown" in out


# ---------------------------------------------------------------------------
# Zero overhead when disabled + plan surface.


def test_chaos_disabled_compiles_identical_programs(pipe_engine):
    """No chaos plan => the jitted programs are byte-identical to a chaos-less
    build (the injector lives entirely outside the traced code), and warmed
    dispatch stays recompile-free even with an injector attached."""
    keys_small = Engine(PIPE).make_keys(0, 4)[:4]

    def loop_jaxpr(eng):
        hi, lo = eng._ledger_init(4)
        return str(jax.make_jaxpr(
            lambda k: eng._device_loop(k, hi, lo, eng.params)
        )(keys_small))

    plain = Engine(PIPE)
    armed = Engine(PIPE)
    armed.chaos = ChaosInjector(plan({"point": "engine.run_batch",
                                      "kind": "transient", "count": 1,
                                      "when": {"runs": -1}}))  # never matches
    assert loop_jaxpr(plain) == loop_jaxpr(armed)

    keys = pipe_engine.make_keys(0, PIPE.runs)
    base = pipe_engine.run_batch(keys)  # warm
    pipe_engine.chaos = ChaosInjector(plan({"point": "engine.run_batch",
                                            "kind": "transient", "count": 1,
                                            "when": {"runs": -1}}))
    try:
        with compile_count_guard(exact=0):
            out = pipe_engine.run_batch(keys)
    finally:
        pipe_engine.chaos = None
    for k in base:
        np.testing.assert_array_equal(np.asarray(base[k]), np.asarray(out[k]),
                                      err_msg=k)


def test_plan_json_roundtrip_and_validation(tmp_path):
    p = plan(
        {"point": "engine.dispatch", "kind": "transient", "count": 2,
         "when": {"batch": 1}, "note": "drill"},
        {"point": "checkpoint.save", "kind": "sigkill",
         "when": {"phase": "pre_replace"}},
    )
    assert ChaosPlan.from_json(p.to_json()) == p
    with pytest.raises(ValueError, match="unknown fault kind"):
        plan({"point": "x", "kind": "meteor-strike"})
    with pytest.raises(ValueError, match="count=0"):
        plan({"point": "x", "count": 0})
    with pytest.raises(ValueError, match="unknown fault keys"):
        ChaosPlan.from_dict({"faults": [{"point": "x", "color": "red"}]})
    with pytest.raises(ValueError, match="needs a point"):
        plan({"point": ""})
    # as_injector accepts a plan, an injector, a path, and None.
    path = tmp_path / "plan.json"
    path.write_text(p.to_json())
    assert as_injector(None) is None
    inj = as_injector(p)
    assert as_injector(inj) is inj
    assert as_injector(path).plan == p


def test_injector_counts_and_triggers():
    inj = ChaosInjector(plan(
        {"point": "a", "kind": "transient", "count": 1, "when": {"k": 1}},
    ))
    inj.fire("a", k=2)  # trigger mismatch: no fault
    inj.fire("b", k=1)  # point mismatch
    with pytest.raises(ChaosError):
        inj.fire("a", k=1)
    inj.fire("a", k=1)  # count exhausted: no fault
    assert len(inj.fired) == 1


def test_cli_chaos_drill_end_to_end(tmp_path, capsys):
    plan_path = tmp_path / "plan.json"
    plan_path.write_text(plan(
        {"point": "engine.dispatch", "kind": "transient", "count": 1}
    ).to_json())
    led = tmp_path / "led.jsonl"
    rc = cli_main([
        "--runs", "4", "--batch-size", "4", "--duration-ms", "100000000",
        "--single-device", "--quiet", "--chaos", str(plan_path),
        "--telemetry", str(led),
    ])
    assert rc == 0
    capsys.readouterr()
    assert cli_main(["report", str(led)]) == 0
    out = capsys.readouterr().out
    assert "Fault ledger (injected chaos)" in out
    assert "engine.dispatch" in out
    # The cpp backend has no orchestration seams to poison.
    with pytest.raises(SystemExit):
        cli_main(["--backend", "cpp", "--runs", "1", "--chaos", str(plan_path)])
