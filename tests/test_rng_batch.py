"""Batched wide RNG generation (SimConfig.rng_batch) and packed VMEM state
(SimConfig.state_dtype): both are pure compile-time performance knobs, pinned
here to be observationally invisible — every statistic, counter and flight row
is bit-identical to the legacy per-event / int32 programs, the wide xoroshiro
draw preserves per-stream word-consumption order (the native-backend
bit-compat contract), and the packed dtypes fail loud before they can wrap.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpusim.config import SimConfig, default_network, reference_selfish_network
from tpusim.engine import Engine, default_n_steps
from tpusim.runner import make_run_keys
from tpusim.testing import compile_count_guard

FAST = SimConfig(
    network=default_network(propagation_ms=10_000),  # racy: arrivals matter
    duration_ms=4 * 86_400_000,
    runs=32,
    batch_size=32,
    chunk_steps=128,
    seed=23,
)
EXACT = dataclasses.replace(
    FAST, network=reference_selfish_network(), mode="exact", runs=16,
    batch_size=16, superstep=2,
)


def _assert_sums_equal(a: dict, b: dict, msg: str) -> None:
    assert a.keys() == b.keys()
    for name in a:
        np.testing.assert_array_equal(
            np.asarray(a[name]), np.asarray(b[name]), err_msg=f"{msg}: {name}"
        )


# ---------------------------------------------------------------------------
# Batched wide generation == legacy per-event draws, engine level.


@pytest.mark.parametrize("config", [FAST, EXACT], ids=["fast", "exact-selfish"])
def test_threefry_batched_equals_per_event(config):
    keys = make_run_keys(config.seed, 0, config.runs)
    legacy = Engine(dataclasses.replace(config, rng_batch=False)).run_batch(keys)
    out = Engine(config).run_batch(keys)
    _assert_sums_equal(legacy, out, "rng_batch")


def test_xoroshiro_wide_equals_sequential_consumption():
    """The K-wide lookahead must replay the conditional-advance stream order
    exactly: rng_batch=False is the per-event path already pinned bit-equal
    to the native backend (tests/test_xoroshiro_engine.py), so equality here
    extends the native bit-compat contract to the wide path."""
    config = dataclasses.replace(FAST, rng="xoroshiro", superstep=4, runs=16,
                                 batch_size=16)
    legacy = Engine(dataclasses.replace(config, rng_batch=False))
    wide = Engine(config)
    keys = legacy.make_keys(0, 16)
    _assert_sums_equal(
        legacy.run_batch(keys), wide.run_batch(keys), "xoroshiro wide"
    )


def test_next_words_wide_is_k_sequential_draws():
    """Unit pin of the wide primitive for BOTH rngs' building blocks: K-wide
    xoroshiro lookahead == K sequential next_words calls (words AND states),
    and the vectorized winner maps == their scalar forms."""
    from tpusim import xoroshiro as xo
    from tpusim.sampling import winner_from_bits, winners_from_bits

    streams = xo.seed_streams(np.arange(8, dtype=np.uint64))
    states, his, los = xo.next_words_wide(streams, 4)
    s = streams
    for c in range(4):
        s, h, l = xo.next_words(s)
        np.testing.assert_array_equal(np.asarray(his[c]), np.asarray(h))
        np.testing.assert_array_equal(np.asarray(los[c]), np.asarray(l))
        for limb_wide, limb_seq in zip(states[c], s):
            np.testing.assert_array_equal(np.asarray(limb_wide), np.asarray(limb_seq))

    # select_stream_by_count: count c lands on the c-th advanced state.
    for c in range(5):
        sel = xo.select_stream_by_count(jnp.int32(c), streams, states)
        want = streams if c == 0 else states[c - 1]
        for a, b in zip(sel, want):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Vectorized winner maps == scalar maps, word for word.
    thr = np.cumsum([40, 30, 30]).astype(np.uint32) * ((2**32 - 1) // 100)
    bits = jax.random.bits(jax.random.PRNGKey(0), (64,), jnp.uint32)
    wide = winners_from_bits(bits, jnp.asarray(thr))
    for i in range(64):
        assert int(wide[i]) == int(winner_from_bits(bits[i], jnp.asarray(thr)))

    from tpusim.sampling import winner_thresholds
    from tpusim.xoroshiro import (
        thresholds64_limbs,
        winner_from_word64,
        winners_from_words64,
    )

    t_hi, t_lo = thresholds64_limbs(winner_thresholds(np.array([40, 30, 30])))
    thr_hi, thr_lo = jnp.asarray(t_hi), jnp.asarray(t_lo)
    w = winners_from_words64(his, los, thr_hi, thr_lo)
    for c in range(4):
        for i in range(8):
            assert int(w[c, i]) == int(
                winner_from_word64(his[c, i], los[c, i], thr_hi, thr_lo)
            )


# ---------------------------------------------------------------------------
# Packed state dtype: resolution rule, loud overflow guard, bit-equality.


def test_count_dtype_resolution_and_overflow_guard():
    # Short durations pack; without re-basing the bound is the full-duration
    # event bound, i.e. exactly engine.default_n_steps (the jax-free twin).
    assert FAST.resolved_count_dtype == "int16"
    plain = dataclasses.replace(FAST, count_rebase=False)
    assert plain.resolved_count_dtype == "int16"
    assert plain.count_bound == default_n_steps(
        FAST.duration_ms, FAST.network.block_interval_s
    )
    # A year-long run cannot fit int16 heights WITHOUT re-basing: auto
    # widens, and an explicit int16 request FAILS LOUD instead of wrapping,
    # naming the max duration of both modes.
    year = dataclasses.replace(FAST, duration_ms=365 * 86_400_000)
    year_plain = dataclasses.replace(year, count_rebase=False)
    assert year_plain.resolved_count_dtype == "int32"
    with pytest.raises(ValueError, match="count_rebase"):
        dataclasses.replace(year_plain, state_dtype="int16")
    # With the default per-chunk count re-basing the bound is per-chunk and
    # the year-long run packs (the tentpole domain extension; bit-equality
    # pinned in tests/test_consensus_gather.py).
    assert year.resolved_count_dtype == "int16"
    # Serialization round-trips both knobs.
    rt = SimConfig.from_json(
        dataclasses.replace(FAST, rng_batch=False, state_dtype="int32").to_json()
    )
    assert rt.rng_batch is False and rt.state_dtype == "int32"


@pytest.mark.parametrize("config", [FAST, EXACT], ids=["fast", "exact-selfish"])
def test_packed_state_bit_equal_to_int32(config):
    assert config.resolved_count_dtype == "int16"  # the packed regime
    keys = make_run_keys(config.seed, 0, config.runs)
    wide = Engine(dataclasses.replace(config, state_dtype="int32")).run_batch(keys)
    packed = Engine(config).run_batch(keys)
    _assert_sums_equal(wide, packed, "state_dtype")


def test_packed_state_scan_vs_pallas_bit_equal():
    from tpusim.pallas_engine import PallasEngine

    config = dataclasses.replace(
        EXACT, runs=128, batch_size=128, duration_ms=2 * 86_400_000,
        flight_capacity=512,
    )
    assert config.resolved_count_dtype == "int16"
    keys = make_run_keys(config.seed, 0, config.runs)
    scan = Engine(config).run_batch(keys)
    pallas = PallasEngine(
        config, tile_runs=128, step_block=32, interpret=True
    ).run_batch(keys)
    _assert_sums_equal(scan, pallas, "packed scan-vs-pallas")


def test_packed_state_checkpoint_resumes_across_dtypes(tmp_path):
    """rng_batch/state_dtype are NOT sampling identity: a checkpoint written
    by the packed batched engine must resume under the legacy knobs with
    bit-identical statistics."""
    from tpusim.runner import run_simulation_config

    ck = tmp_path / "ck.npz"
    small = dataclasses.replace(FAST, runs=16, batch_size=8, duration_ms=86_400_000)
    partial = dataclasses.replace(small, runs=8)
    run_simulation_config(partial, checkpoint_path=ck)
    resumed = run_simulation_config(
        dataclasses.replace(small, rng_batch=False, state_dtype="int32"),
        checkpoint_path=ck,
    )
    direct = run_simulation_config(small)
    for mr, md in zip(resumed.miners, direct.miners):
        assert mr.blocks_found_mean == md.blocks_found_mean
        assert mr.stale_rate_mean == md.stale_rate_mean


# ---------------------------------------------------------------------------
# Small-batch Pallas grid: the auto tile shrinks so the kernel still runs.


def test_pallas_auto_tile_serves_small_batches():
    from tpusim.pallas_engine import FAST_TILE_RUNS, PallasEngine

    config = SimConfig(
        network=default_network(propagation_ms=10_000),
        duration_ms=86_400_000, runs=256, batch_size=256, mode="fast",
        chunk_steps=64, seed=7,
    )
    eng = PallasEngine(config, step_block=32, interpret=True)
    assert eng.tile_runs == 256 < FAST_TILE_RUNS
    keys = make_run_keys(7, 0, 256)
    _assert_sums_equal(
        Engine(config).run_batch(keys), eng.run_batch(keys), "small batch"
    )
    # An explicit tile_runs is never overridden.
    assert PallasEngine(config, tile_runs=128, step_block=32,
                        interpret=True).tile_runs == 128


# ---------------------------------------------------------------------------
# Compile hygiene: the batched programs compile once and the recorder-less
# program still carries no flight machinery with the new state leaves.


def test_batched_dispatch_compiles_once_warm():
    engine = Engine(FAST)
    keys = make_run_keys(FAST.seed, 0, FAST.runs)
    engine.run_batch(keys)  # warm the device loop
    engine.run_batch(keys, pipelined=True)  # warm the pipelined chunk program
    with compile_count_guard(exact=0):
        engine.run_batch(keys)
        engine.run_batch(keys, pipelined=True)


def test_flight_capacity_zero_still_compiles_out():
    """The jaxpr program-text pin from tests/test_flight.py, re-asserted on
    the NEW state leaves (packed int16 counts, dropped honest-roster
    n_private/bhp, precomputed draws): no (C, N_FIELDS) ring tensor and no
    ``rem`` op in the default (cap=0, batched, packed) device-loop program,
    and the ring marker appears the moment capacity is nonzero."""
    from tpusim.flight import N_FIELDS

    base = dataclasses.replace(FAST, runs=8, batch_size=8)
    keys = make_run_keys(base.seed, 0, 8)

    def loop_jaxpr(config):
        eng = Engine(config)
        hi, lo = eng._ledger_init(8)
        return str(
            jax.make_jaxpr(lambda k: eng._device_loop(k, hi, lo, eng.params))(keys)
        )

    off = loop_jaxpr(base)
    on = loop_jaxpr(dataclasses.replace(base, flight_capacity=7))
    marker = f"7,{N_FIELDS}]"
    assert " rem " not in off and marker not in off
    assert " rem " in on and marker in on
