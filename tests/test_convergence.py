"""Streaming convergence telemetry: the moment-key pipeline (engine finalize
-> host int64 fixed-point sums -> combine_sums), the runner's per-batch
``stats`` spans, the CI/ETA derivation, and the `tpusim watch` / report
convergence surfaces.

The load-bearing invariant everything here leans on: the moment keys are
EXACT integer sums of per-run quantized values, so their merge is
associative and permutation/batching-invariant bit-for-bit — unlike the
float64 ``*_sum`` folds, which need a tolerance.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np
import pytest

from tpusim.config import SimConfig, default_network, reference_selfish_network
from tpusim.convergence import (
    STATS,
    Z95,
    MomentAccumulator,
    derive_moments,
    moment_keys,
    quantize,
)
from tpusim.engine import Engine, combine_sums
from tpusim.runner import make_run_keys, run_simulation_config
from tpusim.telemetry import TelemetryRecorder, load_spans

SMALL = SimConfig(
    network=default_network(propagation_ms=1000),
    duration_ms=86_400_000,
    runs=8,
    batch_size=4,
    seed=3,
)

MOMENT_KEYS = sorted(
    ["stats_n"]
    + [f"stats_{s}_{w}" for s, _, _ in STATS for w in ("m1", "m2")]
)


# ---------------------------------------------------------------------------
# The quantized-moment derivation itself.


def test_derive_moments_matches_numpy():
    rng = np.random.default_rng(0)
    x = rng.integers(0, 50, size=(64, 3)).astype(np.int64)
    q = quantize("blocks_found", x)
    np.testing.assert_array_equal(q, x)  # scale 1: integers pass through
    mean, se = derive_moments(64, q.sum(0), (q * q).sum(0), 1)
    np.testing.assert_allclose(mean, x.mean(0), rtol=1e-12)
    np.testing.assert_allclose(
        se, x.std(0, ddof=1) / np.sqrt(64), rtol=1e-9
    )
    # n < 2: no variance estimate, se must be None (not a fake zero).
    _, se1 = derive_moments(1, q[:1].sum(0), (q[:1] * q[:1]).sum(0), 1)
    assert se1 is None


def test_moment_merge_is_associative_and_permutation_invariant():
    """combine_sums on moment keys is plain int64 addition, so any grouping
    and any order of the same batches merges to the SAME bits — the property
    that lets sweeps/resumes accumulate batches in whatever order dispatch
    produces them."""
    rng = np.random.default_rng(1)

    def fake(n):
        out = {"stats_n": np.int64(n)}
        for s, _, _ in STATS:
            out[f"stats_{s}_m1"] = rng.integers(0, 2**40, size=4)
            out[f"stats_{s}_m2"] = rng.integers(0, 2**50, size=4)
        return out

    a, b, c = fake(4), fake(8), fake(2)
    left = combine_sums(combine_sums(a, b), c)
    right = combine_sums(a, combine_sums(b, c))
    swapped = combine_sums(combine_sums(b, a), c)
    for k in left:
        np.testing.assert_array_equal(left[k], right[k], err_msg=k)
        np.testing.assert_array_equal(left[k], swapped[k], err_msg=k)


def test_accumulator_fold_and_snapshot_schema():
    acc = MomentAccumulator()
    x = np.array([[1.0], [2.0], [3.0], [4.0]], dtype=np.float32)
    per = {
        "blocks_found": x.astype(np.int32),
        "blocks_share": x / 8.0,
        "stale_rate": x / 16.0,
    }
    acc.add(moment_keys(per))
    acc.add(moment_keys(per))
    assert acc.n == 8
    snap = acc.snapshot(target_rel_hw=0.01, rate_runs_per_s=100.0)
    assert set(snap) == {s for s, _, _ in STATS}
    entry = snap["blocks_found"]
    # Two copies of [1..4]: mean 2.5, sd ~1.195 (ddof=1), hw = Z95 * sd/sqrt(8)
    assert entry["mean"] == [2.5]
    sd = np.std([1, 2, 3, 4] * 2, ddof=1)
    np.testing.assert_allclose(entry["hw95"][0], Z95 * sd / np.sqrt(8), rtol=1e-4)
    assert entry["rel_hw_max"] == pytest.approx(entry["hw95"][0] / 2.5, rel=1e-4)
    assert entry["eta_runs"] > 0 and entry["eta_s"] > 0
    # ETA scaling law: runs needed = n * (rel/target)^2.
    assert entry["eta_runs"] == pytest.approx(
        8 * (entry["rel_hw_max"] / 0.01) ** 2 - 8, rel=1e-3
    )


def test_stale_rate_clamp_bounds_the_quantized_range():
    from tpusim.convergence import STALE_RATE_CLAMP

    q = quantize("stale_rate", np.array([1e9, STALE_RATE_CLAMP, 0.25]))
    assert q[0] == q[1]  # pathological ratio clamps instead of overflowing
    assert q[2] == round(0.25 * (1 << 14))  # in-range values quantize exactly


# ---------------------------------------------------------------------------
# Engine wiring: keys present, split/dispatch invariant, scan == pallas.


def test_run_batch_emits_moment_keys_and_batch_split_is_bit_invariant():
    """One 512-run batch == two 256-run batches, BIT-equal on every moment
    key (the satellite's headline pin) — and the m1 of blocks_found must
    equal the device's own exact stat sum, tying the new telemetry to the
    existing statistics."""
    config = dataclasses.replace(
        SMALL, duration_ms=43_200_000, runs=512, batch_size=512
    )
    eng = Engine(config)
    whole = eng.run_batch(make_run_keys(config.seed, 0, 512))
    assert sorted(k for k in whole if k.startswith("stats_")) == MOMENT_KEYS
    assert int(whole["stats_n"]) == 512
    a = eng.run_batch(make_run_keys(config.seed, 0, 256))
    b = eng.run_batch(make_run_keys(config.seed, 256, 256))
    merged = combine_sums(a, b)
    for k in MOMENT_KEYS:
        assert np.asarray(whole[k]).dtype == np.int64, k
        np.testing.assert_array_equal(
            np.asarray(whole[k]), np.asarray(merged[k]), err_msg=k
        )
    np.testing.assert_array_equal(
        np.asarray(whole["stats_blocks_found_m1"]),
        np.asarray(whole["blocks_found_sum"]).astype(np.int64),
    )


def test_moment_keys_equal_across_dispatch_paths():
    eng = Engine(SMALL)
    keys = make_run_keys(SMALL.seed, 0, 8)
    device = eng.run_batch(keys)
    host = eng.run_batch(keys, host_loop=True)
    pipelined = eng.run_batch(keys, pipelined=True)
    for k in MOMENT_KEYS:
        np.testing.assert_array_equal(np.asarray(device[k]), np.asarray(host[k]), err_msg=k)
        np.testing.assert_array_equal(np.asarray(device[k]), np.asarray(pipelined[k]), err_msg=k)


def test_moment_keys_scan_vs_pallas_bit_equal():
    """The moments derive from the engines' SHARED finalize over bit-equal
    final state, so the kernel path must produce identical moment keys —
    pinned on the racy selfish config where stale_rate is busy, including
    the head/tail-split merge (batch 160 = one 128 tile + 32 scan runs)."""
    from tpusim.pallas_engine import PallasEngine

    config = SimConfig(
        network=reference_selfish_network(),
        duration_ms=86_400_000,
        runs=160,
        batch_size=160,
        mode="exact",
        chunk_steps=64,
        seed=23,
    )
    keys = make_run_keys(config.seed, 0, config.runs)
    scan = Engine(config).run_batch(keys)
    pallas = PallasEngine(
        config, tile_runs=128, step_block=32, interpret=True
    ).run_batch(keys)
    assert int(scan["stats_stale_rate_m2"].sum()) > 0  # the stat is live
    for k in MOMENT_KEYS:
        np.testing.assert_array_equal(
            np.asarray(scan[k]), np.asarray(pallas[k]), err_msg=k
        )


def test_no_recompile_on_warmed_dispatch_with_stats():
    from tpusim.testing import compile_count_guard

    eng = Engine(SMALL)
    keys = make_run_keys(SMALL.seed, 0, 8)
    eng.run_batch(keys)
    eng.run_batch(keys, pipelined=True)
    with compile_count_guard(exact=0):
        out = eng.run_batch(keys)
        out_p = eng.run_batch(keys, pipelined=True)
    assert "stats_n" in out and "stats_n" in out_p


# ---------------------------------------------------------------------------
# Runner span wiring + the dashboards.


def _run_with_ledger(tmp_path, config, **kw):
    led = tmp_path / "run.jsonl"
    rec = TelemetryRecorder(led)
    res = run_simulation_config(
        config, use_all_devices=False, telemetry=rec, **kw
    )
    rec.close()
    return led, load_spans(led), res


def test_runner_emits_stats_spans(tmp_path):
    led, spans, res = _run_with_ledger(tmp_path, SMALL)
    sstats = [sp for sp in spans if sp["span"] == "stats"]
    assert len(sstats) == 2  # one per batch
    runs_seen = [sp["attrs"]["runs"] for sp in sstats]
    assert runs_seen == [4, 8]
    last = sstats[-1]["attrs"]
    assert last["runs_total"] == SMALL.runs
    assert last["duration_ms"] == SMALL.duration_ms
    assert last["target_rel_hw"] == 0.01
    assert last["rate_runs_per_s"] > 0
    assert last["rate_is_first_batch"] is False  # batch 1 measured post-compile
    assert sstats[0]["attrs"]["rate_is_first_batch"] is True
    per = last["stats"]
    assert set(per) == {s for s, _, _ in STATS}
    m = SMALL.network.n_miners
    for entry in per.values():
        assert len(entry["mean"]) == m
    # Cross-check against the run's own aggregated statistics: blocks_found
    # is unquantized, so the streaming mean must equal the reported mean
    # exactly; share agrees within the documented 2^-18 quantization.
    found_mean = [ms.blocks_found_mean for ms in res.miners]
    assert per["blocks_found"]["mean"] == pytest.approx(found_mean, abs=1e-9)
    share_mean = [ms.blocks_share_mean for ms in res.miners]
    assert per["blocks_share"]["mean"] == pytest.approx(share_mean, abs=2**-16)
    # Same run_id correlation as every other span.
    assert {sp["run_id"] for sp in sstats} == {spans[0]["run_id"]}


def test_report_renders_convergence_panels(tmp_path):
    from tpusim.report import render_report

    led, spans, _ = _run_with_ledger(tmp_path, SMALL)
    text = render_report(spans)
    assert "Convergence (stats spans)" in text
    assert "CI narrowing" in text
    assert "blocks_share" in text
    md = render_report(spans, fmt="md")
    assert "## Convergence (stats spans)" in md


def test_report_single_batch_ledger_is_flagged_not_raising(tmp_path):
    """A single-batch ledger (runs == batch_size) has only the compile-
    contaminated batch: the report must render a flagged estimate — in
    prose, not just a table row — and the stats span must flag its rate
    the same way (the steady_is_first_batch discipline)."""
    from tpusim.report import render_report

    cfg = dataclasses.replace(SMALL, runs=4, batch_size=4)
    led, spans, _ = _run_with_ledger(tmp_path, cfg)
    assert len([sp for sp in spans if sp["span"] == "batch"]) == 1
    text = render_report(spans)
    assert "single-batch ledger" in text
    sstats = [sp for sp in spans if sp["span"] == "stats"]
    assert sstats[-1]["attrs"]["rate_is_first_batch"] is True
    assert "compile-contaminated" in render_report(spans)


def test_single_run_ledger_renders_na_not_crash(tmp_path):
    """n=1: no variance estimate exists; every surface must say n/a."""
    from tpusim.report import render_report
    from tpusim.watch import render_watch

    cfg = dataclasses.replace(SMALL, runs=1, batch_size=1)
    led, spans, _ = _run_with_ledger(tmp_path, cfg)
    entry = [sp for sp in spans if sp["span"] == "stats"][-1]["attrs"]["stats"]
    assert entry["blocks_found"]["se"] is None
    assert entry["blocks_found"]["eta_runs"] is None
    assert "n/a" in render_report(spans)
    assert "n/a" in render_watch(spans, "x")


def test_watch_once_and_live_exit(tmp_path, capsys):
    from tpusim.watch import main as watch_main

    led, spans, _ = _run_with_ledger(tmp_path, SMALL)
    assert watch_main(["--once", str(led)]) == 0
    out = capsys.readouterr().out
    assert "convergence" in out
    assert "COMPLETED" in out
    assert "runs 8/8" in out
    # Live mode exits by itself once the ledger's newest run has closed.
    assert watch_main([str(led), "--interval", "0.01", "--no-clear"]) == 0
    # Missing ledger in --once mode: explicit error, exit 2.
    assert watch_main(["--once", str(tmp_path / "nope.jsonl")]) == 2


def test_watch_renders_empty_and_foreign_ledgers(tmp_path):
    from tpusim.watch import render_watch

    assert "no parseable spans" in render_watch([], "x")
    foreign = [{"run_id": "z", "span": "batch", "t_start": 0.0, "dur_s": 1.0,
                "attrs": {"runs": 4}}]
    text = render_watch(foreign, "x")
    assert "no stats spans" in text
    assert "SINGLE BATCH" in text  # flagged, mirroring steady_is_first_batch
    # Partial/foreign stats entries (all-None hw95, non-dict values) render
    # n/a on BOTH surfaces via the shared row builder instead of raising.
    from tpusim.convergence import snapshot_rows
    from tpusim.report import render_report

    weird = [{"run_id": "z", "span": "stats", "t_start": 0.0, "dur_s": 0.0,
              "attrs": {"runs": 2, "stats": {
                  "blocks_found": {"hw95": [None, None]},
                  "junk": "not-a-dict",
              }}}]
    assert snapshot_rows(weird[0]["attrs"]["stats"]) == [
        ["blocks_found", "n/a", "n/a", "n/a"]
    ]
    assert "n/a" in render_watch(weird, "x")
    assert "n/a" in render_report(weird)


def test_cli_watch_dispatch(tmp_path, capsys):
    from tpusim.cli import main as cli_main

    led, _, _ = _run_with_ledger(tmp_path, SMALL)
    assert cli_main(["watch", "--once", str(led)]) == 0
    assert "tpusim watch" in capsys.readouterr().out


def test_checkpoint_resume_restarts_accumulator(tmp_path):
    """A checkpoint resume restarts the accumulator (moments are session
    telemetry): the resumed session's stats spans count only its own runs,
    while the checkpointed statistics still cover all of them."""
    ck = tmp_path / "ck.npz"
    cfg = dataclasses.replace(SMALL, runs=4, batch_size=4)
    _run_with_ledger(tmp_path, cfg, checkpoint_path=ck)
    led2 = tmp_path / "resume.jsonl"
    rec = TelemetryRecorder(led2)
    res = run_simulation_config(
        dataclasses.replace(SMALL, runs=8, batch_size=4),
        use_all_devices=False, telemetry=rec, checkpoint_path=ck,
    )
    rec.close()
    spans = load_spans(led2)
    sstats = [sp for sp in spans if sp["span"] == "stats"]
    assert [sp["attrs"]["runs"] for sp in sstats] == [4]  # fresh accumulator
    # ... but the run-level progress stays truthful: runs_done counts the
    # resumed checkpoint's base, so watch's progress bar shows 8/8, not 4/8.
    assert [sp["attrs"]["runs_done"] for sp in sstats] == [8]
    from tpusim.watch import render_watch

    assert "runs 8/8" in render_watch(spans, "x")
    assert res.runs == 8  # statistics still resumed


# ---------------------------------------------------------------------------
# The adaptive-precision DRIVER: ci_target_stat wires the per-batch CI to an
# actual stop condition (run-until-confident), not just an ETA display.

#: Shared compiled-engine cache across the driver tests (all SMALL-shaped).
DRIVER_ENGINE_CACHE: dict = {}


def test_ci_target_stop_by_target(tmp_path):
    # A 1000% relative-half-width target is met by the very first batch
    # (n=4 gives a variance estimate), so the 64-run budget stops at 4.
    cfg = dataclasses.replace(SMALL, runs=64)
    led, spans, res = _run_with_ledger(
        tmp_path, cfg, engine_cache=DRIVER_ENGINE_CACHE,
        ci_target_rel=10.0, ci_target_stat="blocks_share",
    )
    assert res.runs == 4  # statistics cover exactly the folded runs
    run = next(sp for sp in spans if sp["span"] == "run")
    assert run["attrs"]["stop_reason"] == "ci_target"
    assert run["attrs"]["converged"] is True
    assert run["attrs"]["ci_target_stat"] == "blocks_share"
    assert run["attrs"]["runs"] == 4
    # One stats span per EXECUTED batch; the abandoned in-flight batch left
    # no trace.
    assert sum(1 for sp in spans if sp["span"] == "stats") == 1


def test_ci_target_stop_by_runs_exhausted(tmp_path):
    led, spans, res = _run_with_ledger(
        tmp_path, SMALL, engine_cache=DRIVER_ENGINE_CACHE,
        ci_target_rel=1e-9, ci_target_stat="blocks_share",
    )
    assert res.runs == SMALL.runs  # budget exhausted without the target
    run = next(sp for sp in spans if sp["span"] == "run")
    assert run["attrs"]["stop_reason"] == "runs_exhausted"
    assert run["attrs"]["converged"] is False


def test_ci_target_stop_without_telemetry(tmp_path):
    # The driver must not depend on a recorder being armed.
    cfg = dataclasses.replace(SMALL, runs=64)
    res = run_simulation_config(
        cfg, use_all_devices=False, engine_cache=DRIVER_ENGINE_CACHE,
        ci_target_rel=10.0, ci_target_stat="blocks_share",
    )
    assert res.runs == 4


def test_ci_target_stat_validated(monkeypatch):
    with pytest.raises(ValueError, match="unknown ci_target_stat"):
        run_simulation_config(SMALL, ci_target_stat="nope")
    with pytest.raises(ValueError, match="positive ci_target_rel"):
        run_simulation_config(SMALL, ci_target_rel=0.0,
                              ci_target_stat="blocks_share")
    # Multi-controller meshes emit no moments, so the stop condition could
    # never fire — must refuse loudly, not burn the budget silently.
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="multi-controller"):
        run_simulation_config(SMALL, ci_target_stat="blocks_share")
    from tpusim.cli import main as cli_main

    with pytest.raises(SystemExit, match="ci-target-stat"):
        cli_main(["--backend", "cpp", "--ci-target-stat", "blocks_share"])


def test_run_span_default_stop_reason(tmp_path):
    # Without a target stat armed the closing span still narrates the stop:
    # runs_exhausted, converged null (nothing was being targeted).
    led, spans, res = _run_with_ledger(
        tmp_path, SMALL, engine_cache=DRIVER_ENGINE_CACHE
    )
    run = next(sp for sp in spans if sp["span"] == "run")
    assert run["attrs"]["stop_reason"] == "runs_exhausted"
    assert run["attrs"]["converged"] is None
