"""Miner-axis consensus gathers (SimConfig.consensus_gather) and per-chunk
count re-basing (SimConfig.count_rebase): both pure compile-time performance
knobs, pinned here to be observationally invisible — every statistic, counter,
streaming moment and flight row is bit-identical to the legacy one-hot /
un-rebased int32 programs, checkpoints resume across both knobs, and the
gather program provably carries no one-hot contraction ops.

The re-basing pins are the int16 domain extension's safety net: a year-long
reference run (which the un-rebased bound rejects at ~106.8 d) must resolve
``resolved_count_dtype == "int16"`` and reproduce the int32 un-rebased run
bit for bit after the final_stats re-add.
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np
import jax
import pytest

from tpusim.config import (
    INT16_MAX_DURATION_MS_600S,
    TIME_CAP_MS,
    SimConfig,
    default_network,
    reference_selfish_network,
)
from tpusim.engine import Engine
from tpusim.runner import make_run_keys

FAST = SimConfig(
    network=default_network(propagation_ms=10_000),  # racy: arrivals matter
    duration_ms=4 * 86_400_000,
    runs=32,
    batch_size=32,
    chunk_steps=128,
    seed=23,
)
EXACT = dataclasses.replace(
    FAST, network=reference_selfish_network(), mode="exact", runs=16,
    batch_size=16,
)

#: The pre-knob program: one-hot reads, un-rebased int32 counts.
LEGACY = dict(consensus_gather=False, count_rebase=False, state_dtype="int32")


def _assert_sums_equal(a: dict, b: dict, msg: str) -> None:
    assert a.keys() == b.keys()
    for name in a:
        np.testing.assert_array_equal(
            np.asarray(a[name]), np.asarray(b[name]), err_msg=f"{msg}: {name}"
        )


# ---------------------------------------------------------------------------
# Gather reads == one-hot contractions, bit for bit.


@pytest.mark.parametrize("config", [FAST, EXACT], ids=["fast", "exact-selfish"])
# K=2 rides the slow tier (ci.sh's unfiltered pytest leg): the K-lookahead
# consumption-order equivalence has its own pins in test_rng_batch, so
# tier-1 keeps the K=1 gather-vs-onehot pair only.
@pytest.mark.parametrize(
    "k", [1, pytest.param(2, marks=pytest.mark.slow)]
)
def test_gather_vs_onehot_bit_equal(config, k):
    """The gather path reads exactly the entries the one-hot contraction
    summed, across honest and selfish rosters and superstep widths."""
    cfg = dataclasses.replace(config, superstep=k, count_rebase=False)
    keys = make_run_keys(cfg.seed, 0, cfg.runs)
    onehot = Engine(dataclasses.replace(cfg, consensus_gather=False)).run_batch(keys)
    gather = Engine(cfg).run_batch(keys)
    _assert_sums_equal(onehot, gather, f"gather K={k}")


def test_gather_vs_onehot_xoroshiro():
    """The sequential-stream rng path threads the same gather flag (its
    notify is the same code), extending the native bit-compat contract."""
    cfg = dataclasses.replace(FAST, rng="xoroshiro", runs=8, batch_size=8)
    eng = Engine(cfg)
    keys = eng.make_keys(0, 8)
    _assert_sums_equal(
        Engine(dataclasses.replace(cfg, **LEGACY)).run_batch(keys),
        eng.run_batch(keys),
        "xoroshiro knobs",
    )


def test_gather_program_has_no_onehot_contractions():
    """The jaxpr pin the CI perf-guard leg mirrors: with the knob on the
    device-loop program contains dynamic gathers and ZERO one-hot
    contraction muls over the (R, M, M[, M]) consensus tensors; with the
    knob off, the legacy muls are present and no gather is traced. The mul
    shapes are the contraction signatures — selects lower to select_n, so a
    rank-3/4 int16 mul only ever comes from the one-hot read path."""
    cfg = dataclasses.replace(EXACT, runs=8, batch_size=8, chunk_steps=64,
                              count_rebase=False)
    keys = make_run_keys(cfg.seed, 0, 8)

    def loop_jaxpr(c):
        eng = Engine(c)
        hi, lo = eng._ledger_init(8)
        return str(
            jax.make_jaxpr(lambda kk: eng._device_loop(kk, hi, lo, eng.params))(keys)
        )

    on = loop_jaxpr(cfg)
    off = loop_jaxpr(dataclasses.replace(cfg, consensus_gather=False))
    contraction = re.compile(r":i16\[8,9,9(,9)?\] = mul")
    assert not contraction.search(on), "one-hot contraction leaked into gather program"
    assert " gather[" in on
    assert len(contraction.findall(off)) >= 4  # cp plane + own_cp/own_in/diag
    assert " gather[" not in off


# ---------------------------------------------------------------------------
# Count re-basing: round trip across many chunk boundaries, year-long domain.


@pytest.mark.parametrize("config", [FAST, EXACT], ids=["fast", "exact-selfish"])
def test_count_rebase_round_trip_bit_equal(config):
    """>= 3 chunk boundaries (4 d at chunk_steps=128 is ~18 busy chunks):
    the re-based int16 run must equal the un-rebased int32 run bit for bit
    after the final_stats re-add — statistics, counters and moments alike."""
    assert config.resolved_count_dtype == "int16"
    keys = make_run_keys(config.seed, 0, config.runs)
    wide = Engine(dataclasses.replace(
        config, count_rebase=False, state_dtype="int32")).run_batch(keys)
    rebased = Engine(config).run_batch(keys)
    assert int(rebased["tele_chunks_max"]) >= 3
    _assert_sums_equal(wide, rebased, "count rebase round trip")


def test_yearlong_reference_packs_int16_and_matches_int32():
    """THE acceptance pin of the domain extension: the 365 d reference
    configs resolve int16 with re-basing on (the un-rebased bound dies at
    ~106.8 d) and reproduce the int32 un-rebased run bit for bit across
    ~59 chunk re-bases."""
    for net, seed in ((default_network(propagation_ms=1000), 3),
                      (reference_selfish_network(), 5)):
        year = SimConfig(network=net, runs=2, batch_size=2, seed=seed)
        assert year.duration_ms >= 365 * 86_400_000
        assert year.resolved_count_dtype == "int16", year.count_bound
        assert dataclasses.replace(
            year, count_rebase=False).resolved_count_dtype == "int32"
        keys = make_run_keys(seed, 0, 2)
        rebased = Engine(year).run_batch(keys)
        wide = Engine(dataclasses.replace(
            year, count_rebase=False, state_dtype="int32")).run_batch(keys)
        _assert_sums_equal(wide, rebased, f"year-long {year.resolved_mode}")


def test_rebased_flight_rows_stay_absolute():
    """Flight rows carry absolute chain heights via the recorder's h_base
    limb (the height twin of the time base limbs): the ring written by a
    re-based run must be byte-identical to the un-rebased run's."""
    cfg = dataclasses.replace(EXACT, runs=8, batch_size=8, flight_capacity=512)
    keys = make_run_keys(cfg.seed, 0, 8)
    rebased = Engine(cfg).run_batch(keys)
    plain = Engine(dataclasses.replace(
        cfg, count_rebase=False, state_dtype="int32")).run_batch(keys)
    assert int(rebased["tele_chunks_max"]) >= 3
    np.testing.assert_array_equal(plain["flight_buf"], rebased["flight_buf"])
    np.testing.assert_array_equal(plain["flight_count"], rebased["flight_count"])


def test_dispatch_paths_bit_identical_with_knobs():
    """device loop == pipelined == host loop == async under gather+rebase —
    including the pipelined path's overshoot no-op chunks, which re-base
    again (a second re-base subtracts a refreshed-diagonal delta at most;
    the final re-add makes it invisible)."""
    cfg = dataclasses.replace(FAST, runs=16, batch_size=16)
    eng = Engine(cfg)
    keys = make_run_keys(cfg.seed, 0, 16)
    device = eng.run_batch(keys)
    _assert_sums_equal(device, eng.run_batch(keys, pipelined=True), "pipelined")
    _assert_sums_equal(device, eng.run_batch(keys, host_loop=True), "host loop")
    _assert_sums_equal(device, eng.run_batch_async(keys)(), "async")


# Slow tier (ci.sh's unfiltered pytest leg): scan-vs-pallas parity under the
# DEFAULT knobs already rides tier-1 via test_pallas_engine; this adds the
# legacy one-hot kernel path and the flight-armed densest-leaf combo.
@pytest.mark.slow
def test_scan_vs_pallas_gather_and_rebase():
    """The kernel's take_along_axis gather reads and the (outside-kernel)
    count re-base are pinned bit-equal to the scan engine AND to the
    kernel's own legacy one-hot path, exact-selfish with the flight ring
    armed (the densest leaf set)."""
    from tpusim.pallas_engine import PallasEngine

    cfg = dataclasses.replace(
        EXACT, runs=128, batch_size=128, duration_ms=2 * 86_400_000,
        flight_capacity=256,
    )
    assert cfg.resolved_count_dtype == "int16"
    keys = make_run_keys(cfg.seed, 0, 128)
    scan = Engine(cfg).run_batch(keys)
    pallas = PallasEngine(
        cfg, tile_runs=128, step_block=32, interpret=True
    ).run_batch(keys)
    _assert_sums_equal(scan, pallas, "scan-vs-pallas knobs on")
    pallas_legacy = PallasEngine(
        dataclasses.replace(cfg, **LEGACY),
        tile_runs=128, step_block=32, interpret=True,
    ).run_batch(keys)
    _assert_sums_equal(pallas_legacy, pallas, "pallas gather-vs-onehot")


# ---------------------------------------------------------------------------
# Checkpoint resume across the knobs; config-level contracts.


def test_resume_from_rebased_checkpoint(tmp_path):
    """consensus_gather/count_rebase are NOT sampling identity: a checkpoint
    written by the re-based gather engine must resume under the full legacy
    knob set with bit-identical statistics."""
    from tpusim.runner import run_simulation_config

    ck = tmp_path / "ck.npz"
    small = dataclasses.replace(FAST, runs=16, batch_size=8, duration_ms=86_400_000)
    partial = dataclasses.replace(small, runs=8)
    run_simulation_config(partial, checkpoint_path=ck)  # re-based writer
    resumed = run_simulation_config(
        dataclasses.replace(small, **LEGACY), checkpoint_path=ck
    )
    direct = run_simulation_config(small)
    for mr, md in zip(resumed.miners, direct.miners):
        assert mr.blocks_found_mean == md.blocks_found_mean
        assert mr.stale_rate_mean == md.stale_rate_mean


def test_count_bound_contracts():
    """TIME_CAP twin, the rebased bound's shape, and the loud int16 error
    naming both domain maxima."""
    from tpusim.state import TIME_CAP

    assert TIME_CAP_MS == int(TIME_CAP)

    year = SimConfig(network=reference_selfish_network(), runs=2)
    plain = dataclasses.replace(year, count_rebase=False)
    # Re-basing turns the duration bound into a per-chunk one.
    assert year.count_bound < plain.count_bound
    assert year.count_bound <= 2**15 - 1 < plain.count_bound
    # The documented domain edge: ~106.8 d un-rebased at the 600 s interval.
    # Pinned against the CONSTANT the docs cite, so the two cannot drift
    # apart (the "~113 d" rot this PR reconciled), and against the literal
    # so the constant cannot silently move either.
    assert plain.max_int16_duration_ms() == INT16_MAX_DURATION_MS_600S
    assert INT16_MAX_DURATION_MS_600S == 9_230_231_273
    with pytest.raises(ValueError) as ei:
        dataclasses.replace(plain, state_dtype="int16")
    assert "106.8 d" in str(ei.value) and "count_rebase" in str(ei.value)
    # A selfish MAJORITY defeats re-basing (its private lead grows linearly,
    # so no per-chunk bound exists): auto stays int32, loudly not wrongly.
    maj = SimConfig(network=default_network(
        selfish_ids=(0,), hashrates=(60, 10, 10, 10, 5, 3, 1, 1, 0)))
    assert maj.resolved_count_dtype == "int32"
    # Serialization round-trips the knobs.
    rt = SimConfig.from_json(dataclasses.replace(year, **LEGACY).to_json())
    assert rt.consensus_gather is False and rt.count_rebase is False
