"""Statistical sampling tests with programmatic tolerances.

Upgrades the reference's print-and-eyeball statistical checks into seeded
z-tests (the reference prints moments for manual comparison: winner-draw
binomials at test.cpp:15-63 and test.cpp:68-119, interval moments at
test.cpp:191-208, a simplified end-to-end share check at test.cpp:122-187).
Every bound below is a +-5 sigma envelope on a fixed seed, so failures mean a
real distribution change, not noise (5 sigma two-sided is ~6e-7 per check).
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpusim.config import MinerConfig, NetworkConfig, SimConfig
from tpusim.engine import Engine
from tpusim.runner import make_run_keys
from tpusim.sampling import interval_from_bits, winner_from_bits, winner_thresholds32

N_DRAWS = 1_000_000
SIGMAS = 5.0


def _bits(seed: int, n: int) -> jax.Array:
    return jax.random.bits(jax.random.key(seed), (n,), jnp.uint32)


def _winner_counts(pcts: list[int], seed: int) -> np.ndarray:
    thresholds = jnp.asarray(winner_thresholds32(np.array(pcts)))
    w = jax.jit(jax.vmap(winner_from_bits, in_axes=(0, None)))(_bits(seed, N_DRAWS), thresholds)
    return np.bincount(np.asarray(w), minlength=len(pcts))


@pytest.mark.parametrize("seed", [0, 7])
def test_winner_draw_uniform_100x1pct(seed):
    """100 miners at 1% each: every count is Binomial(N, 0.01)
    (reference test.cpp:15-63 upgraded from printed moments to a z-test)."""
    pcts = [1] * 100
    counts = _winner_counts(pcts, seed)
    p = 0.01
    sigma = math.sqrt(N_DRAWS * p * (1 - p))
    np.testing.assert_array_less(np.abs(counts - N_DRAWS * p), SIGMAS * sigma)


@pytest.mark.parametrize("seed", [1, 11])
def test_winner_draw_heterogeneous(seed):
    """12/18/20/15/35 split (reference test.cpp:68-119): per-miner z-test."""
    pcts = [12, 18, 20, 15, 35]
    counts = _winner_counts(pcts, seed)
    for c, pct in zip(counts, pcts):
        p = pct / 100.0
        sigma = math.sqrt(N_DRAWS * p * (1 - p))
        assert abs(c - N_DRAWS * p) < SIGMAS * sigma, (c, pct)


def test_interval_moments():
    """floor(Exp(600 s)) in ms: mean ~ sigma ~ 600 000 ms (reference
    test.cpp:191-208). The floor shifts the mean by ~-0.5 ms, far below the
    +-5 sigma/sqrt(N) = +-3000 ms envelope; sigma gets a two-sided 5-sigma
    bound via the fourth-moment standard error sigma^2*sqrt(8/N)."""
    mean_ms = 600_000.0
    dts = np.asarray(
        jax.jit(jax.vmap(interval_from_bits, in_axes=(0, None)))(_bits(3, N_DRAWS), mean_ms),
        dtype=np.float64,
    )
    assert (dts >= 0).all()
    se_mean = mean_ms / math.sqrt(N_DRAWS)
    assert abs(dts.mean() - mean_ms) < SIGMAS * se_mean
    se_var = mean_ms**2 * math.sqrt(8.0 / N_DRAWS)
    assert abs(dts.var() - mean_ms**2) < SIGMAS * se_var


def test_interval_tail_capped():
    """The 24-bit uniform caps a single draw at ~16.6 means; nothing may reach
    the int32-envelope clamp at the reference interval (exceedance e^-223)."""
    mean_ms = 600_000.0
    dts = np.asarray(jax.vmap(interval_from_bits, in_axes=(0, None))(_bits(4, N_DRAWS), mean_ms))
    assert dts.max() < 2**27


def test_end_to_end_shares_match_hashrates():
    """Block shares converge to hashrate shares in an honest network — the
    reference's SimpleSim check (test.cpp:122-187) with a programmatic bound.

    With 1 ms propagation races are ~0, so each run's share vector is a
    multinomial over ~blocks draws; the cross-run mean-of-shares z-test uses
    the empirical per-run share variance."""
    runs = 64
    config = SimConfig(
        network=NetworkConfig(
            miners=(
                MinerConfig(hashrate_pct=50, propagation_ms=1),
                MinerConfig(hashrate_pct=30, propagation_ms=1),
                MinerConfig(hashrate_pct=20, propagation_ms=1),
            ),
            block_interval_s=600.0,
        ),
        duration_ms=30 * 86_400_000,  # 30 days ~ 4320 blocks/run
        runs=runs,
        batch_size=runs,
        seed=5,
    )
    sums = Engine(config).run_batch(make_run_keys(config.seed, 0, runs))
    share_mean = np.asarray(sums["blocks_share_sum"], dtype=np.float64) / runs
    blocks = config.duration_ms / (600.0 * 1000.0)
    for i, m in enumerate(config.network.miners):
        p = m.hashrate_pct / 100.0
        se = math.sqrt(p * (1 - p) / blocks / runs)
        assert abs(share_mean[i] - p) < SIGMAS * se, (i, share_mean[i], p)
    # Essentially no stale blocks at 1 ms propagation and 600 s intervals.
    assert np.asarray(sums["stale_rate_sum"]).sum() / runs < 1e-3
