"""scripts/refscale_report.py must merge, not replace, BASELINE.json's
``published`` block: `full_scale_grids` is owned by
scripts/update_fullscale_published.py, and a report re-run after a grid
update must not erase it (regression: round 5, where a re-run dropped the
committed full-scale evidence)."""

import importlib.util
import json
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _load_report_module():
    spec = importlib.util.spec_from_file_location(
        "refscale_report", REPO / "scripts" / "refscale_report.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _miner(hashrate_pct, selfish=False):
    return {
        "hashrate_pct": hashrate_pct,
        "selfish": selfish,
        "blocks_found_mean": 1000.0 * hashrate_pct,
        "blocks_share_mean": hashrate_pct / 100.0,
        "stale_rate_mean": 0.001,
        "stale_blocks_mean": 1.0,
    }


def test_report_preserves_full_scale_grids(tmp_path, monkeypatch, capsys):
    mod = _load_report_module()
    art = tmp_path / "artifacts"
    art.mkdir()
    miners = [_miner(h) for h in (30, 29, 12, 11, 8, 5, 3, 1, 1)]
    (art / "refscale_default1s_tpu.json").write_text(
        json.dumps({"runs": 32768, "sim_years_per_s": 1000.0, "miners": miners})
    )
    grids = {"note": "owned by update_fullscale_published.py", "selfish_hashrate": {}}
    (tmp_path / "BASELINE.json").write_text(
        json.dumps({"metric": "m", "published": {"full_scale_grids": grids}})
    )
    monkeypatch.setattr(mod, "REPO", tmp_path)
    monkeypatch.setattr(mod, "ART", art)

    assert mod.main() == 0
    out = json.loads((tmp_path / "BASELINE.json").read_text())
    assert out["metric"] == "m"  # top-level keys untouched
    pub = out["published"]
    assert pub["full_scale_grids"] == grids  # sibling evidence preserved
    assert "default1s" in pub["configs"]  # report's own block written
    assert (tmp_path / "REFSCALE.md").exists()
