"""Property-based equivalence: the O(1) automaton vs the literal-chain oracle
on hypothesis-generated adversarial configurations and event streams.

The hand-picked configurations in test_state_equivalence.py found one real
semantic divergence already (the block-stepping stale-accounting hole, see
tpusim/engine.py's design note); this suite searches the configuration space
systematically: random rosters (including 0% miners, 0 ms propagation and
multiple selfish miners), interval streams with heavy mass at 0 and at
race-window scales, and both consensus representations.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

# The container image does not ship hypothesis and nothing may be installed;
# skip the whole property suite rather than fail collection.
pytest.importorskip("hypothesis")
from hypothesis import assume, given, settings, strategies as st  # noqa: E402

from tpusim.backend.pychain import run_chain_sim
from tpusim.config import (
    FAST_MODE_MAX_RACE_RATIO,
    MinerConfig,
    NetworkConfig,
    SimConfig,
    default_network,
)
from tpusim.testing import assert_state_matches_chains, drive_state_events

DURATION_MS = 400_000  # ~20 blocks at the 20 s interval used below

# CI default 100; raise for deep fuzz sessions (idle hardware windows), e.g.
#   TPUSIM_HYPOTHESIS_EXAMPLES=2000 pytest tests/test_property_equivalence.py
# Test-level @settings overrides hypothesis profiles, so the knob lives here.
MAX_EXAMPLES = int(os.environ.get("TPUSIM_HYPOTHESIS_EXAMPLES", "100"))


@st.composite
def networks(draw):
    n = draw(st.integers(2, 5))
    # Random integer split of 100% that allows 0% miners.
    cuts = sorted(draw(st.lists(st.integers(0, 100), min_size=n - 1, max_size=n - 1)))
    pcts = [b - a for a, b in zip([0] + cuts, cuts + [100])]
    props = draw(
        st.lists(st.sampled_from([0, 1, 7, 350, 2000, 6000]), min_size=n, max_size=n)
    )
    n_selfish = draw(st.integers(0, 2))
    selfish_ids = draw(
        st.lists(st.integers(0, n - 1), min_size=n_selfish, max_size=n_selfish, unique=True)
    )
    miners = tuple(
        MinerConfig(hashrate_pct=p, propagation_ms=pr, selfish=(i in selfish_ids))
        for i, (p, pr) in enumerate(zip(pcts, props))
    )
    return NetworkConfig(miners=miners, block_interval_s=20.0)


@st.composite
def event_streams(draw, n_events: int, n_miners: int):
    # Intervals: heavy mass at 0 (same-ms drain) and at race-window scales.
    intervals = draw(
        st.lists(
            st.one_of(
                st.just(0),
                st.integers(1, 400),  # inside most propagation windows
                st.integers(5_000, 60_000),
            ),
            min_size=n_events,
            max_size=n_events,
        )
    )
    winners = draw(
        st.lists(st.integers(0, n_miners - 1), min_size=n_events, max_size=n_events)
    )
    return intervals, winners


def _prepare_case(data, mode):
    network = data.draw(networks())
    if mode == "fast" and network.any_selfish:
        # The fast representation's contract covers honest rosters only.
        network = NetworkConfig(
            miners=tuple(
                MinerConfig(m.hashrate_pct, m.propagation_ms, selfish=False)
                for m in network.miners
            ),
            block_interval_s=network.block_interval_s,
        )
    intervals, winners = data.draw(event_streams(120, network.n_miners))
    # The driver consumes one interval per find and zero-interval finds do
    # not advance time, so the duration must be covered by the *time* of the
    # first ~90 events (leaving stream headroom for same-ms drains).
    duration_ms = min(DURATION_MS, int(sum(intervals[:90])))
    assume(duration_ms > 0)
    config = SimConfig(
        network=network,
        duration_ms=duration_ms,
        runs=1,
        mode=mode,
        group_slots=32,  # bound high enough that overflow never triggers here
    )
    # The winner draw can never pick a 0% miner (its threshold interval is
    # empty); map any such draw to a nonzero-hashrate miner.
    eligible = [i for i, mc in enumerate(network.miners) if mc.hashrate_pct > 0]
    winners = [w if network.miners[w].hashrate_pct > 0 else eligible[w % len(eligible)]
               for w in winners]
    return config, intervals, winners


@settings(max_examples=MAX_EXAMPLES, deadline=None)
@given(data=st.data())
def test_exact_mode_matches_chain_oracle(data):
    """Exact mode is observationally identical to the literal-chain oracle on
    adversarial streams — full state, stats, and stale equality."""
    config, intervals, winners = _prepare_case(data, "exact")
    state, stats = drive_state_events(config, intervals, winners)
    oracle = run_chain_sim(config, intervals, winners)

    assert np.asarray(stats["blocks_found"]).tolist() == oracle["blocks_found"]
    assert np.asarray(stats["stale_blocks"]).tolist() == oracle["stale_blocks"]
    assert int(stats["best_height"]) == oracle["best_height"]
    np.testing.assert_allclose(stats["blocks_share"], oracle["blocks_share"], rtol=1e-6)
    np.testing.assert_allclose(stats["stale_rate"], oracle["stale_rate"], rtol=1e-6)
    assert int(state.overflow) == 0
    assert_state_matches_chains(state, oracle["chains"], config.duration_ms, config)


@settings(max_examples=MAX_EXAMPLES, deadline=None)
@given(data=st.data())
def test_fast_mode_contract_vs_chain_oracle(data):
    """Fast mode's documented contract (tpusim.state docstring), held even on
    streams far outside its auto-routing domain: consensus observables
    (blocks found, shares, best height) are EXACT, and the stale counter is
    an elementwise LOWER BOUND of the oracle's. Exact stale equality on these
    adversarial compound-race streams is deliberately NOT asserted — that is
    what mode="auto"'s routing to exact (config.FAST_MODE_MAX_RACE_RATIO)
    exists for, and test_fast_mode_exact_inside_domain covers the domain."""
    config, intervals, winners = _prepare_case(data, "fast")
    state, stats = drive_state_events(config, intervals, winners)
    oracle = run_chain_sim(config, intervals, winners)

    assert np.asarray(stats["blocks_found"]).tolist() == oracle["blocks_found"]
    assert int(stats["best_height"]) == oracle["best_height"]
    np.testing.assert_allclose(stats["blocks_share"], oracle["blocks_share"], rtol=1e-6)
    stale = np.asarray(stats["stale_blocks"])
    assert np.all(stale <= np.asarray(oracle["stale_blocks"])), (
        f"fast-mode stale must lower-bound the oracle: {stale.tolist()} vs "
        f"{oracle['stale_blocks']}"
    )
    assert int(state.overflow) == 0


def test_auto_mode_routes_by_race_ratio():
    """mode="auto" keeps fast only inside the documented accuracy domain."""
    fast_cfg = SimConfig(network=default_network(propagation_ms=1000), runs=1)
    assert fast_cfg.max_race_ratio < FAST_MODE_MAX_RACE_RATIO
    assert fast_cfg.resolved_mode == "fast"
    # The reference README's 10 s-propagation table: ratio 0.0167 > 0.01.
    exact_cfg = SimConfig(network=default_network(propagation_ms=10_000), runs=1)
    assert exact_cfg.max_race_ratio > FAST_MODE_MAX_RACE_RATIO
    assert exact_cfg.resolved_mode == "exact"
    selfish_cfg = SimConfig(
        network=default_network(propagation_ms=1000, selfish_ids=(0,)), runs=1
    )
    assert selfish_cfg.resolved_mode == "exact"
    # Explicit modes are never overridden.
    assert SimConfig(
        network=default_network(propagation_ms=10_000), runs=1, mode="fast"
    ).resolved_mode == "fast"


def test_fast_mode_exact_inside_domain():
    """Quantitative accuracy check inside fast mode's auto-routing domain:
    at 100 ms propagation (race ratio 1.7e-4) the expected stale shortfall
    over this test's ~92k simulated blocks is ~ blocks * ratio^2 = 3e-3, so
    fast and exact modes must agree bit-for-bit — the draws are identical by
    construction, leaving state representation as the only variable."""
    from tpusim.engine import Engine
    from tpusim.runner import make_run_keys

    base = dict(
        network=default_network(propagation_ms=100),
        duration_ms=20 * 86_400_000,
        runs=32,
        batch_size=32,
        seed=11,
    )
    keys = make_run_keys(11, 0, 32)
    out = {}
    for mode in ("fast", "exact"):
        out[mode] = Engine(SimConfig(mode=mode, **base)).run_batch(keys)
    np.testing.assert_array_equal(
        out["fast"]["stale_blocks_sum"], out["exact"]["stale_blocks_sum"]
    )
    np.testing.assert_array_equal(
        out["fast"]["blocks_found_sum"], out["exact"]["blocks_found_sum"]
    )
    np.testing.assert_allclose(
        out["fast"]["stale_rate_sum"], out["exact"]["stale_rate_sum"], rtol=1e-6
    )


def test_fast_mode_rate_error_bounded_at_reference_default():
    """At the reference default (1 s propagation, ratio 1.7e-3) fast mode's
    stale-*rate* shortfall per run must stay below the ±1e-4 cross-validation
    tolerance: expected shortfall is ~ratio^2 = 3e-6 stale blocks per block,
    two orders below the tolerance. Consensus stays bit-exact."""
    from tpusim.engine import Engine
    from tpusim.runner import make_run_keys

    base = dict(
        network=default_network(propagation_ms=1000),
        duration_ms=20 * 86_400_000,
        runs=32,
        batch_size=32,
        seed=12,
    )
    keys = make_run_keys(12, 0, 32)
    out = {}
    for mode in ("fast", "exact"):
        out[mode] = Engine(SimConfig(mode=mode, **base)).run_batch(keys)
    np.testing.assert_array_equal(
        out["fast"]["blocks_found_sum"], out["exact"]["blocks_found_sum"]
    )
    runs = out["fast"]["runs"]
    fast_rate = out["fast"]["stale_rate_sum"] / runs
    exact_rate = out["exact"]["stale_rate_sum"] / runs
    diff = exact_rate - fast_rate
    assert np.all(diff >= -1e-9), "fast stale rate must lower-bound exact"
    assert np.all(diff <= 1e-4), f"stale-rate shortfall {diff} exceeds tolerance"
