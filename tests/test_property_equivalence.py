"""Property-based equivalence: the O(1) automaton vs the literal-chain oracle
on hypothesis-generated adversarial configurations and event streams.

The hand-picked configurations in test_state_equivalence.py found one real
semantic divergence already (the block-stepping stale-accounting hole, see
tpusim/engine.py's design note); this suite searches the configuration space
systematically: random rosters (including 0% miners, 0 ms propagation and
multiple selfish miners), interval streams with heavy mass at 0 and at
race-window scales, and both consensus representations.
"""

from __future__ import annotations

import numpy as np
import pytest
from hypothesis import assume, given, settings, strategies as st

from tpusim.backend.pychain import run_chain_sim
from tpusim.config import MinerConfig, NetworkConfig, SimConfig
from tpusim.testing import assert_state_matches_chains, drive_state_events

DURATION_MS = 400_000  # ~20 blocks at the 20 s interval used below


@st.composite
def networks(draw):
    n = draw(st.integers(2, 5))
    # Random integer split of 100% that allows 0% miners.
    cuts = sorted(draw(st.lists(st.integers(0, 100), min_size=n - 1, max_size=n - 1)))
    pcts = [b - a for a, b in zip([0] + cuts, cuts + [100])]
    props = draw(
        st.lists(st.sampled_from([0, 1, 7, 350, 2000, 6000]), min_size=n, max_size=n)
    )
    n_selfish = draw(st.integers(0, 2))
    selfish_ids = draw(
        st.lists(st.integers(0, n - 1), min_size=n_selfish, max_size=n_selfish, unique=True)
    )
    miners = tuple(
        MinerConfig(hashrate_pct=p, propagation_ms=pr, selfish=(i in selfish_ids))
        for i, (p, pr) in enumerate(zip(pcts, props))
    )
    return NetworkConfig(miners=miners, block_interval_s=20.0)


@st.composite
def event_streams(draw, n_events: int, n_miners: int):
    # Intervals: heavy mass at 0 (same-ms drain) and at race-window scales.
    intervals = draw(
        st.lists(
            st.one_of(
                st.just(0),
                st.integers(1, 400),  # inside most propagation windows
                st.integers(5_000, 60_000),
            ),
            min_size=n_events,
            max_size=n_events,
        )
    )
    winners = draw(
        st.lists(st.integers(0, n_miners - 1), min_size=n_events, max_size=n_events)
    )
    return intervals, winners


@settings(max_examples=40, deadline=None)
@given(data=st.data())
@pytest.mark.parametrize("mode", ["exact", "fast"])
def test_random_streams_match_chain_oracle(mode, data):
    network = data.draw(networks())
    if mode == "fast" and network.any_selfish:
        # The fast representation is only claimed exact for honest rosters.
        network = NetworkConfig(
            miners=tuple(
                MinerConfig(m.hashrate_pct, m.propagation_ms, selfish=False)
                for m in network.miners
            ),
            block_interval_s=network.block_interval_s,
        )
    intervals, winners = data.draw(event_streams(120, network.n_miners))
    # The driver consumes one interval per find and zero-interval finds do
    # not advance time, so the duration must be covered by the *time* of the
    # first ~90 events (leaving stream headroom for same-ms drains).
    duration_ms = min(DURATION_MS, int(sum(intervals[:90])))
    assume(duration_ms > 0)
    config = SimConfig(
        network=network,
        duration_ms=duration_ms,
        runs=1,
        mode=mode,
        group_slots=32,  # bound high enough that overflow never triggers here
    )
    # The winner draw can never pick a 0% miner (its threshold interval is
    # empty); map any such draw to a nonzero-hashrate miner.
    eligible = [i for i, mc in enumerate(network.miners) if mc.hashrate_pct > 0]
    winners = [w if network.miners[w].hashrate_pct > 0 else eligible[w % len(eligible)]
               for w in winners]

    state, stats = drive_state_events(config, intervals, winners)
    oracle = run_chain_sim(config, intervals, winners)

    assert np.asarray(stats["blocks_found"]).tolist() == oracle["blocks_found"]
    assert np.asarray(stats["stale_blocks"]).tolist() == oracle["stale_blocks"]
    assert int(stats["best_height"]) == oracle["best_height"]
    np.testing.assert_allclose(stats["blocks_share"], oracle["blocks_share"], rtol=1e-6)
    np.testing.assert_allclose(stats["stale_rate"], oracle["stale_rate"], rtol=1e-6)
    assert int(state.overflow) == 0

    if mode == "exact":
        assert_state_matches_chains(state, oracle["chains"], config.duration_ms, config)
