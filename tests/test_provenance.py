"""Provenance & audit plane (tpusim.provenance): content addressing, the
lineage writer, the `tpusim audit` cross-plane gate's exit-code matrix
(0 pass / 1 per-invariant violation / 2 structural-or-dead-gate), the
`lineage show` tree, sealed evidence bundles — and the LIVE drills: a real
armed sweep whose on-disk row mutation turns the gate red, a checkpointed
resume whose run record chains to the checkpoint it healed from, and the
zero-overhead pin (armed lineage changes no compiled program and stays
recompile-free on warmed dispatch — the chaos/flight discipline).
"""

from __future__ import annotations

import contextlib
import json
import os
from pathlib import Path
from types import SimpleNamespace

import numpy as np
import jax
import pytest

import tpusim.provenance as provenance
from tpusim.cli import main as cli_main
from tpusim.config import SimConfig, default_network
from tpusim.engine import Engine
from tpusim.provenance import (
    PROVENANCE_ENV,
    LineageWriter,
    audit_main,
    bundle_main,
    canonical_json,
    checkpoint_address,
    checkpoint_content,
    content_address,
    emit_lineage,
    lineage_armed,
    lineage_last,
    lineage_main,
    load_lineage,
    run_audit,
    scan_artifacts,
    summarize_lineage,
)
from tpusim.runner import run_simulation_config
from tpusim.sweep import run_sweep
from tpusim.testing import compile_count_guard

CFG = SimConfig(
    network=default_network(propagation_ms=1000),
    duration_ms=10**8,
    runs=8,
    batch_size=4,
    seed=5,
)

#: Shared warm-engine cache (the test_chaos discipline): every same-shape
#: run in this module rebinds one compiled engine.
ENGINE_CACHE: dict = {}


@contextlib.contextmanager
def armed(ledger: Path):
    """Arm the provenance plane at ``ledger`` for the enclosed block; the
    writer cache is cleared both ways so per-path writer state never leaks
    between tests."""
    os.environ[PROVENANCE_ENV] = str(ledger)
    provenance._WRITERS.clear()
    try:
        yield
    finally:
        os.environ.pop(PROVENANCE_ENV, None)
        provenance._WRITERS.clear()


def _addr_map(records: list[dict]) -> dict[str, dict]:
    by: dict[str, dict] = {}
    for rec in records:
        for a in (rec.get("content_sha256"), rec.get("artifact_id")):
            if isinstance(a, str):
                by.setdefault(a, rec)
    return by


# ---------------------------------------------------------------------------
# Content addressing + the writer (jax-free units).


def test_content_address_ignores_key_order_not_values():
    assert content_address({"a": 1, "b": 2}) == content_address({"b": 2, "a": 1})
    assert content_address({"a": 1}) != content_address({"a": 2})
    # The canonical form is whitespace-free and key-sorted: a row written
    # with json.dumps defaults re-reads to the same address.
    assert canonical_json({"b": 2, "a": 1}) == '{"a":1,"b":2}'
    row = {"point": "pt-a", "elapsed_s": 1.0 / 3.0}
    assert content_address(json.loads(json.dumps(row))) == content_address(row)


def test_checkpoint_address_is_deterministic_cross_process():
    # A replacement worker recomputes the dead worker's checkpoint address
    # from (fingerprint, runs_done) alone — no ledger read required.
    assert checkpoint_address("fp-1", 4) == content_address(
        checkpoint_content("fp-1", 4)
    )
    assert checkpoint_address("fp-1", 4) != checkpoint_address("fp-1", 8)
    assert checkpoint_address("fp-1", 4) != checkpoint_address("fp-2", 4)


def test_emit_round_trip_record_hash_and_env_identity(tmp_path):
    ledger = tmp_path / "lineage.jsonl"
    with armed(ledger):
        assert lineage_armed()
        addr = emit_lineage("run", content={"x": 1}, runs=4, seed=1)
        assert addr == content_address({"x": 1})
        assert lineage_last("run") == addr
    records = load_lineage(ledger, strict=True)  # strict: re-hashes each
    (rec,) = records
    assert rec["kind"] == "run" and rec["content_sha256"] == addr
    assert rec["runs"] == 4 and rec["schema"] == provenance.SCHEMA
    # Environment identity rides on every record (the perf-ledger rule).
    assert "git_rev" in rec and "env_sha256" in rec
    assert isinstance(rec.get("git_dirty"), bool)


def test_emit_unknown_kind_raises_even_when_armed(tmp_path):
    with armed(tmp_path / "lineage.jsonl"):
        with pytest.raises(ValueError, match="register it in KINDS"):
            emit_lineage("not-a-kind")


def test_parent_mailbox_files_and_drains_by_key(tmp_path):
    with armed(tmp_path / "lineage.jsonl"):
        a = emit_lineage("checkpoint_load", key="pt-a", runs_done=4)
        provenance.lineage_note_parents("pt-a", None, lineage_last("checkpoint_load"))
        assert provenance.lineage_take_parents("pt-a") == [a, a]
        assert provenance.lineage_take_parents("pt-a") == []  # drained


def test_disarmed_seams_are_total_noops(tmp_path):
    assert not lineage_armed()
    assert provenance.active_writer() is None
    assert emit_lineage("run", content={"x": 1}) is None
    assert lineage_last("run") is None
    assert provenance.lineage_take_parents("pt-a") == []
    provenance.lineage_note_parents("pt-a", "deadbeef")  # swallowed
    assert not (tmp_path / "lineage.jsonl").exists()


def test_write_failure_disarms_writer_and_run_continues(tmp_path, caplog):
    target = tmp_path / "ledger"
    target.mkdir()  # opening a directory for append raises OSError
    w = LineageWriter(target)
    with caplog.at_level("WARNING", logger="tpusim"):
        assert w.emit("run", content={"x": 1}) is None
    assert w.disabled
    assert any("disabling lineage ledger" in r.message for r in caplog.records)
    assert w.emit("run", content={"x": 2}) is None  # stays disarmed, no raise


def test_load_lineage_tolerant_skips_torn_tail_strict_raises(tmp_path):
    ledger = tmp_path / "lineage.jsonl"
    with armed(ledger):
        emit_lineage("run", content={"x": 1})
        emit_lineage("run", content={"x": 2})
    with ledger.open("a") as fh:
        fh.write('{"kind": "run", "artifact_id": "torn')  # no newline
    assert len(load_lineage(ledger)) == 2  # the live-writer tolerance
    with pytest.raises(ValueError, match="unparseable lineage line"):
        load_lineage(ledger, strict=True)
    # And the shared append repairs the torn tail before the next record.
    with armed(ledger):
        emit_lineage("run", content={"x": 3})
    assert len(load_lineage(ledger)) == 3
    assert ledger.read_bytes().endswith(b"\n")


def test_strict_load_catches_mutated_record(tmp_path):
    ledger = tmp_path / "lineage.jsonl"
    with armed(ledger):
        emit_lineage("run", content={"x": 1}, runs=4)
    rec = json.loads(ledger.read_text())
    rec["runs"] = 999  # doctor the ledger without re-hashing
    ledger.write_text(json.dumps(rec) + "\n")
    with pytest.raises(ValueError, match="mutated ledger"):
        load_lineage(ledger, strict=True)
    assert len(load_lineage(ledger)) == 1  # tolerant load still returns it


def test_summarize_lineage_digest(tmp_path):
    assert summarize_lineage([]) is None
    ledger = tmp_path / "lineage.jsonl"
    with armed(ledger):
        a = emit_lineage("checkpoint", content=checkpoint_content("fp", 4),
                         runs_done=4)
        emit_lineage("checkpoint_load", parents=(a,), runs_done=4)
    s = summarize_lineage(load_lineage(ledger))
    assert s["records"] == 2 and s["edges"] == 1
    assert s["kinds"] == {"checkpoint": 1, "checkpoint_load": 1}


# ---------------------------------------------------------------------------
# The audit gate, synthetically: one world per invariant, each join covered.


def build_world(root: Path) -> SimpleNamespace:
    """A synthetic, jax-free artifact set exercising every audit join: the
    checkpoint -> checkpoint_load -> run -> sweep_row chain with its row on
    disk, a perf row, the closing run span, a healed fleet ledger, and a
    checkpoint npz."""
    root.mkdir(parents=True, exist_ok=True)
    ledger = root / "provenance" / "lineage.jsonl"
    with armed(ledger):
        env = provenance.active_writer()._env_attrs()
        ck = emit_lineage("checkpoint", content=checkpoint_content("fp-1", 4),
                          config_fingerprint="fp-1", runs_done=4)
        ld = emit_lineage("checkpoint_load", parents=(ck,),
                          config_fingerprint="fp-1", runs_done=4)
        run = emit_lineage("run", content={"best_height_mean": 1.5},
                           parents=(ld,), runs=8, run_id="r-1", backend="tpu")
        row = {"point": "pt-a", "runs": 8, "backend": "tpu",
               "elapsed_s": 1.25, "best_height_mean": 1.5}
        emit_lineage("sweep_row", content=row, parents=(run,),
                     point="pt-a", runs=8, backend="tpu")
        perf_row = {"scenario": "sweep-smoke", "metric": "wall_s",
                    "samples": [1.0, 1.1],
                    "env": {"git_rev": env["git_rev"],
                            "git_dirty": env["git_dirty"]}}
        emit_lineage("perf_row", content=perf_row, parents=(run,),
                     scenario="sweep-smoke", metric="wall_s")
    (root / "rows.jsonl").write_text(json.dumps(row) + "\n")
    (root / "perf.jsonl").write_text(json.dumps(perf_row) + "\n")
    (root / "tele.jsonl").write_text(json.dumps(
        {"span": "run", "run_id": "r-1", "schema": 1, "attrs": {"runs": 8}}
    ) + "\n")
    (root / "ledger.jsonl").write_text("".join(
        json.dumps(e) + "\n" for e in (
            {"event": "requeue", "point": "pt-a", "reason": "exit:-9"},
            {"event": "done", "point": "pt-a"},
        )
    ))
    np.savez(root / "ck.npz", __config__=np.array("fp-1"))
    return SimpleNamespace(root=root, ledger=ledger,
                           rows=root / "rows.jsonl", row=row,
                           perf_row=perf_row, run_addr=run)


def test_audit_green_checks_every_invariant(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    assert audit_main([str(w.root)]) == 0
    out = capsys.readouterr().out
    assert "[audit]" in out
    violations, checked = run_audit(scan_artifacts([w.root]))
    assert violations == []
    # Every invariant actually checked facts — no dead rows in the table.
    assert all(checked[name] >= 1 for name, _ in provenance.INVARIANTS), checked


def test_audit_names_record_hash_violation(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    lines = w.ledger.read_text().splitlines()
    rec = json.loads(lines[2])  # the run record
    rec["runs"] = 999
    lines[2] = json.dumps(rec)
    w.ledger.write_text("\n".join(lines) + "\n")
    assert audit_main([str(w.root)]) == 1
    assert "[record-hash]" in capsys.readouterr().err


def test_audit_names_parent_resolvable_violation(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    with armed(w.ledger):
        emit_lineage("run", content={"x": 9}, parents=("0" * 64,))
    assert audit_main([str(w.root)]) == 1
    assert "[parent-resolvable]" in capsys.readouterr().err


def test_audit_names_row_lineage_violation_for_unrecorded_row(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    foreign = {"point": "pt-ghost", "runs": 8, "backend": "tpu",
               "elapsed_s": 2.0}
    with w.rows.open("a") as fh:
        fh.write(json.dumps(foreign) + "\n")
    assert audit_main([str(w.root)]) == 1
    err = capsys.readouterr().err
    assert "[row-lineage]" in err and "pt-ghost" in err


def test_audit_names_runs_consistent_row_vs_record(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    row2 = {"point": "pt-c", "runs": 8, "backend": "tpu", "elapsed_s": 0.5}
    with armed(w.ledger):
        emit_lineage("sweep_row", content=row2, point="pt-c", runs=7,
                     backend="tpu")  # record disagrees with its own content
    with w.rows.open("a") as fh:
        fh.write(json.dumps(row2) + "\n")
    assert audit_main([str(w.root)]) == 1
    assert "[runs-consistent]" in capsys.readouterr().err


def test_audit_names_runs_consistent_span_vs_records(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    with (w.root / "tele.jsonl").open("a") as fh:
        fh.write(json.dumps({"span": "run", "run_id": "r-1", "schema": 1,
                             "attrs": {"runs": 5}}) + "\n")
    assert audit_main([str(w.root)]) == 1
    err = capsys.readouterr().err
    assert "[runs-consistent]" in err and "r-1" in err


def test_audit_names_checkpoint_fingerprint_violation(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    np.savez(w.root / "rogue.npz", __config__=np.array("fp-unknown"))
    assert audit_main([str(w.root)]) == 1
    assert "[checkpoint-fingerprint]" in capsys.readouterr().err


def test_audit_skips_swept_tmp_checkpoints(tmp_path):
    # A stale *.tmp.npz is swept, never adopted — not an artifact, so an
    # unknown fingerprint inside one must not turn the gate red.
    w = build_world(tmp_path / "world")
    np.savez(w.root / "dead.tmp.npz", __config__=np.array("fp-unknown"))
    assert audit_main([str(w.root), "--quiet"]) == 0


def test_audit_names_heal_parented_violation(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    fleet2 = w.root / "fleet2"
    fleet2.mkdir()
    row_x = {"point": "pt-x", "runs": 8, "backend": "tpu", "elapsed_s": 1.0}
    with armed(w.ledger):
        emit_lineage("sweep_row", content=row_x, point="pt-x", runs=8,
                     backend="tpu")  # recorded, but parentless
    (fleet2 / "rows.jsonl").write_text(json.dumps(row_x) + "\n")
    (fleet2 / "ledger.jsonl").write_text("".join(
        json.dumps(e) + "\n" for e in (
            {"event": "requeue", "point": "pt-x", "reason": "exit:-9"},
            {"event": "done", "point": "pt-x"},
        )
    ))
    assert audit_main([str(w.root)]) == 1
    err = capsys.readouterr().err
    assert "[heal-parented]" in err and "pt-x" in err


def test_audit_names_env_rev_violation(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    row2 = {"scenario": "s2", "metric": "wall_s", "samples": [2.0],
            "env": {"git_rev": "0000000", "git_dirty": False}}
    with armed(w.ledger):
        emit_lineage("perf_row", content=row2, scenario="s2", metric="wall_s")
    with (w.root / "perf.jsonl").open("a") as fh:
        fh.write(json.dumps(row2) + "\n")
    assert audit_main([str(w.root)]) == 1
    assert "[env-rev]" in capsys.readouterr().err


def test_audit_dead_gates_exit_2(tmp_path, capsys):
    # Missing root.
    assert audit_main([str(tmp_path / "nope")]) == 2
    assert "dead gate" in capsys.readouterr().err
    # A root with artifacts but ZERO lineage records can never pass green.
    root = tmp_path / "bare"
    root.mkdir()
    (root / "rows.jsonl").write_text(json.dumps(
        {"point": "pt-a", "runs": 8, "backend": "tpu", "elapsed_s": 1.0}
    ) + "\n")
    assert audit_main([str(root)]) == 2
    assert "empty lineage ledger" in capsys.readouterr().err
    # An empty ledger FILE is the same dead gate.
    (root / "lineage.jsonl").write_text("")
    assert audit_main([str(root)]) == 2


def test_audit_tolerates_torn_trailing_ledger_line(tmp_path):
    w = build_world(tmp_path / "world")
    with w.ledger.open("a") as fh:
        fh.write('{"kind": "run", "artifact_id": "torn-mid-wri')
    assert audit_main([str(w.root), "--quiet"]) == 0


# ---------------------------------------------------------------------------
# `tpusim lineage show`.


def test_lineage_show_by_address_prefix(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    assert lineage_main(["show", w.run_addr[:12],
                         "--lineage", str(w.ledger)]) == 0
    out = capsys.readouterr().out
    assert "run" in out and "checkpoint_load" in out and "checkpoint" in out
    assert "└─" in out  # rendered as a tree, parents indented


def test_lineage_show_by_rows_file_defaults_to_last_row(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    assert cli_main(["lineage", "show", str(w.rows),
                     "--lineage", str(w.ledger)]) == 0
    out = capsys.readouterr().out
    assert "sweep_row" in out and "point=pt-a" in out and "checkpoint" in out


def test_lineage_show_unresolvable_and_no_ledger(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    # Too-short prefix and unknown address both refuse, loud.
    assert lineage_main(["show", "abc", "--lineage", str(w.ledger)]) == 1
    assert lineage_main(["show", "f" * 64, "--lineage", str(w.ledger)]) == 1
    # A row nobody recorded names the failure mode.
    rows2 = tmp_path / "rows2.jsonl"
    rows2.write_text(json.dumps({"point": "pt-z", "runs": 1, "backend": "tpu",
                                 "elapsed_s": 1.0}) + "\n")
    assert lineage_main(["show", str(rows2), "--lineage", str(w.ledger)]) == 1
    assert "unrecorded or mutated" in capsys.readouterr().err
    # No ledger at all is structural.
    assert lineage_main(["show", "f" * 64,
                         "--lineage", str(tmp_path / "none.jsonl")]) == 2


# ---------------------------------------------------------------------------
# Sealed evidence bundles.


def test_bundle_round_trip_and_flipped_byte_fails(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    bundle = tmp_path / "evidence.tar"
    assert cli_main(["bundle", "create", str(bundle), str(w.root)]) == 0
    assert "sealed" in capsys.readouterr().out
    assert cli_main(["bundle", "verify", str(bundle)]) == 0
    assert "all hashes match" in capsys.readouterr().out
    # Flip one content byte (plain tar: the member bytes are raw, so this
    # must be caught by the manifest re-hash, not a compression checksum).
    raw = bundle.read_bytes()
    assert b"pt-a" in raw
    bundle.write_bytes(raw.replace(b"pt-a", b"pt-X", 1))
    assert bundle_main(["verify", str(bundle)]) == 1
    assert "sha256 mismatch" in capsys.readouterr().err


def test_bundle_create_refuses_broken_ledger(tmp_path, capsys):
    w = build_world(tmp_path / "world")
    rec = json.loads(w.ledger.read_text().splitlines()[0])
    rec["runs_done"] = 999
    lines = w.ledger.read_text().splitlines()
    lines[0] = json.dumps(rec)
    w.ledger.write_text("\n".join(lines) + "\n")
    assert bundle_main(["create", str(tmp_path / "b.tar"), str(w.root)]) == 2
    assert "refusing to seal" in capsys.readouterr().err


def test_bundle_structural_failures_exit_2(tmp_path, capsys):
    empty = tmp_path / "empty"
    empty.mkdir()
    assert bundle_main(["create", str(tmp_path / "b.tar"), str(empty)]) == 2
    assert bundle_main(["create", str(tmp_path / "b.tar"),
                        str(tmp_path / "nope.jsonl")]) == 2
    garbage = tmp_path / "garbage.tar"
    garbage.write_bytes(b"this is not a tar archive")
    assert bundle_main(["verify", str(garbage)]) == 2
    assert "not a verifiable bundle" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Dashboards render the provenance panel from the same summary dict.


def test_report_and_watch_render_provenance_panel(tmp_path):
    from tpusim.report import render_report
    from tpusim.telemetry import TelemetryRecorder, load_spans
    from tpusim.watch import render_watch

    w = build_world(tmp_path / "world")
    rec = TelemetryRecorder(tmp_path / "tele.jsonl")
    rec.emit("run", dur_s=1.0, runs=8)
    rec.close()
    spans = load_spans(tmp_path / "tele.jsonl")
    summary = summarize_lineage(load_lineage(w.ledger))
    report = render_report(spans, lineage=summary)
    assert "Provenance (lineage ledger)" in report
    assert "parent edges (DAG)" in report
    watch = render_watch(spans, "world", lineage=summary)
    assert "provenance: 5 lineage record(s)" in watch


# ---------------------------------------------------------------------------
# LIVE drills: a real armed run/sweep, the gate drill, zero overhead.


@pytest.fixture(scope="module")
def live(tmp_path_factory):
    """One armed live world: a warmed disarmed baseline, then — with the
    plane armed and under a zero-recompile guard — a checkpointed run, a
    resume of it, and a two-point sweep, all against one ledger."""
    tmp = tmp_path_factory.mktemp("prov_live")
    state = tmp / "state"
    state.mkdir()
    ledger = state / "provenance" / "lineage.jsonl"
    base = run_simulation_config(
        CFG, use_all_devices=False, engine_cache=ENGINE_CACHE
    )
    os.environ[PROVENANCE_ENV] = str(ledger)
    provenance._WRITERS.clear()
    try:
        ck = state / "ck.npz"
        with compile_count_guard(exact=0):  # arming must not recompile
            first = run_simulation_config(
                CFG, use_all_devices=False, engine_cache=ENGINE_CACHE,
                checkpoint_path=ck,
            )
        resumed = run_simulation_config(
            CFG, use_all_devices=False, engine_cache=ENGINE_CACHE,
            checkpoint_path=ck,
        )
        rows = state / "rows.jsonl"
        run_sweep(
            [("pt-a", CFG), ("pt-b", CFG)], out_path=rows, quiet=True,
            use_all_devices=False, engine_cache=ENGINE_CACHE,
            telemetry_path=state / "tele.jsonl",
        )
    finally:
        os.environ.pop(PROVENANCE_ENV, None)
        provenance._WRITERS.clear()
    return SimpleNamespace(tmp=tmp, state=state, ledger=ledger, rows=rows,
                           base=base, first=first, resumed=resumed)


def test_live_armed_runs_stay_bit_equal(live):
    for res in (live.first, live.resumed):
        assert res.runs == live.base.runs
        assert res.table() == live.base.table()
        assert res.best_height_mean == live.base.best_height_mean


def test_live_resume_chain_reaches_its_checkpoint(live):
    records = load_lineage(live.ledger, strict=True)
    by_addr = _addr_map(records)
    # The resumed run's record cites checkpoint_load, which cites (and the
    # loader re-attested) the durable checkpoint — the full heal chain.
    runs = [r for r in records if r["kind"] == "run"]
    # Exactly one run record cites a parent: the resumed one (the cold run
    # and the sweep's two fresh runs never loaded a checkpoint).
    (resumed_rec,) = [r for r in runs if r["parents"]]
    kinds = provenance._ancestor_kinds(resumed_rec["content_sha256"], by_addr)
    assert {"run", "checkpoint_load", "checkpoint"} <= kinds
    # The cite resolves through the DETERMINISTIC address — recomputable
    # from the npz identity alone.
    loads = [r for r in records if r["kind"] == "checkpoint_load"]
    assert loads and loads[-1]["parents"] == [
        checkpoint_address(loads[-1]["config_fingerprint"],
                           loads[-1]["runs_done"])
    ]


def test_live_sweep_rows_resolve_and_cite_their_runs(live):
    records = load_lineage(live.ledger)
    by_addr = _addr_map(records)
    rows = [json.loads(l) for l in live.rows.read_text().splitlines()]
    assert [r["point"] for r in rows] == ["pt-a", "pt-b"]
    for row in rows:
        rec = by_addr.get(content_address(row))
        assert rec is not None and rec["kind"] == "sweep_row", row["point"]
        assert "run" in provenance._ancestor_kinds(
            rec["content_sha256"], by_addr
        ), row["point"]


def test_live_audit_gate_drill_mutate_then_revert(live, capsys):
    # The gate drill: green over the real artifacts; one mutated byte in
    # one on-disk row turns it red with the invariant named; reverting the
    # mutation turns it green again.
    assert audit_main([str(live.state)]) == 0
    capsys.readouterr()
    pristine = live.rows.read_text()
    assert '"runs": 8' in pristine
    live.rows.write_text(pristine.replace('"runs": 8', '"runs": 9', 1))
    try:
        assert audit_main([str(live.state)]) == 1
        assert "[row-lineage]" in capsys.readouterr().err
    finally:
        live.rows.write_text(pristine)
    assert cli_main(["audit", str(live.state), "--quiet"]) == 0


def test_live_bundle_seals_the_evidence(live, tmp_path, capsys):
    bundle = tmp_path / "evidence.tar.gz"
    assert bundle_main(["create", str(bundle), str(live.state)]) == 0
    out = capsys.readouterr().out
    assert "lineage" in out and "record(s)" in out
    assert bundle_main(["verify", str(bundle)]) == 0


def test_provenance_arming_compiles_identical_programs(tmp_path):
    """The zero-overhead pin: TPUSIM_PROVENANCE set vs unset traces
    byte-identical device programs (the plane is host-side only), and a
    warmed engine stays recompile-free while records are being written."""
    keys_small = Engine(CFG).make_keys(0, 4)[:4]

    def loop_jaxpr(eng):
        hi, lo = eng._ledger_init(4)
        return str(jax.make_jaxpr(
            lambda k: eng._device_loop(k, hi, lo, eng.params)
        )(keys_small))

    plain_jaxpr = loop_jaxpr(Engine(CFG))
    with armed(tmp_path / "lineage.jsonl"):
        assert loop_jaxpr(Engine(CFG)) == plain_jaxpr


def test_git_dirty_rides_the_environment_fingerprint():
    # Satellite: the shared env fingerprint carries the dirty-tree flag next
    # to git_rev (a dirty tree stamping a clean-looking rev poisons both the
    # perf trajectory and the lineage env-rev join).
    from tpusim.perf import environment_fingerprint

    env = environment_fingerprint()
    if "git_rev" in env:  # absent only when git/repo is unavailable
        assert isinstance(env.get("git_dirty"), bool)
