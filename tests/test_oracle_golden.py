"""Integration checks against the two independent oracles the reference ships:

* the closed-form analytical stale-rate model (reference
  plot_stale_rate/plot.py:18-77, ported as tpusim.analysis.oracle) for
  honest-only configurations across a propagation sweep, and
* the reference README's golden result tables (reference README.md:51-107),
  which function as the project's de-facto golden integration outputs — the
  10 s / 100 ms honest tables and the 40 %-selfish table.

Run counts here are far below the reference's 32768 (CI time), so tolerances
are Monte-Carlo envelopes around the analytical/golden values, not the
±1e-4 production cross-validation bound (that bound is about backend
agreement at equal sample sizes, covered by test_state_equivalence.py).
"""

from __future__ import annotations

import math

import numpy as np
import pytest

from tpusim.analysis.oracle import analytical_net_benefits, analytical_stale_rates
from tpusim.config import SimConfig, default_network
from tpusim.engine import Engine
from tpusim.runner import make_run_keys

HASHRATES = (30, 29, 12, 11, 8, 5, 3, 1, 1)


def _run(config: SimConfig) -> dict[str, np.ndarray]:
    sums = Engine(config).run_batch(make_run_keys(config.seed, 0, config.runs))
    return {k: np.asarray(v) for k, v in sums.items()}


def _stale_tolerance(p: float, blocks_per_run: float, runs: int, hashrate: float) -> float:
    """5-sigma MC envelope on a mean of per-run stale ratios plus 10% relative
    slack for the oracle's neglected higher-order race terms."""
    own_blocks = max(blocks_per_run * hashrate, 1.0)
    sigma = math.sqrt(max(p, 1e-12) / own_blocks / runs)
    return 5.0 * sigma + 0.10 * p


@pytest.mark.parametrize("prop_ms", [1000, 10_000])
def test_honest_stale_rates_match_analytical_oracle(prop_ms):
    runs, days = 64, 45
    config = SimConfig(
        network=default_network(propagation_ms=prop_ms),
        duration_ms=days * 86_400_000,
        runs=runs,
        batch_size=runs,
        seed=17,
    )
    sums = _run(config)
    stale = sums["stale_rate_sum"] / runs
    hashrates = [h / 100.0 for h in HASHRATES]
    oracle = analytical_stale_rates(hashrates, prop_ms / 1000.0)
    blocks_per_run = config.duration_ms / 600_000.0
    for i, (got, want) in enumerate(zip(stale, oracle)):
        tol = _stale_tolerance(want, blocks_per_run, runs, hashrates[i])
        assert abs(got - want) < tol, (i, got, want, tol)


def test_golden_table_10s_propagation():
    """Reference README.md:51-66: miner-0 stale ~1.01%, miner-8 ~2.0%."""
    runs, days = 64, 45
    config = SimConfig(
        network=default_network(propagation_ms=10_000),
        duration_ms=days * 86_400_000,
        runs=runs,
        batch_size=runs,
        seed=23,
    )
    sums = _run(config)
    stale = sums["stale_rate_sum"] / runs
    share = sums["blocks_share_sum"] / runs
    blocks_per_run = config.duration_ms / 600_000.0
    assert abs(stale[0] - 0.0101) < _stale_tolerance(0.0101, blocks_per_run, runs, 0.30)
    assert abs(stale[8] - 0.0200) < _stale_tolerance(0.0200, blocks_per_run, runs, 0.01)
    # Shares stay within 5 sigma of hashrate (propagation losses cancel in the
    # share because every miner loses proportionally; README table col 2).
    for i, h in enumerate(HASHRATES):
        p = h / 100.0
        se = math.sqrt(p * (1 - p) / blocks_per_run / runs)
        assert abs(share[i] - p) < 5 * se + 0.01 * p, (i, share[i], p)


def test_golden_table_100ms_propagation():
    """Reference README.md:68-87: miner-0 stale ~0.0102%, miner-8 ~0.0205%.

    Rates this small need large samples; with the 5-sigma envelope this is a
    magnitude check (no stale-rate inflation, correct ~100x drop vs 10 s)."""
    runs, days = 96, 45
    config = SimConfig(
        network=default_network(propagation_ms=100),
        duration_ms=days * 86_400_000,
        runs=runs,
        batch_size=runs,
        seed=29,
    )
    sums = _run(config)
    stale = sums["stale_rate_sum"] / runs
    blocks_per_run = config.duration_ms / 600_000.0
    assert abs(stale[0] - 0.000102) < _stale_tolerance(0.000102, blocks_per_run, runs, 0.30)
    assert stale.max() < 0.0015  # two orders below the 10 s table across the board


def test_golden_table_selfish_40pct():
    """Reference README.md:89-107: a 40% gamma=0 selfish miner earns ~46.7% of
    blocks (~+16% revenue), its stale rate ~27.5%, honest miners' ~67.5%."""
    runs, days = 32, 90
    config = SimConfig(
        network=default_network(
            propagation_ms=1000,
            selfish_ids=(0,),
            hashrates=(40, 19, 12, 11, 8, 5, 3, 1, 1),
        ),
        duration_ms=days * 86_400_000,
        runs=runs,
        batch_size=runs,
        seed=31,
    )
    sums = _run(config)
    share = sums["blocks_share_sum"] / runs
    stale = sums["stale_rate_sum"] / runs
    # Best-chain growth halves during duels; per-run share variance is wide at
    # 32 runs, so use generous 5-sigma-ish windows around the README values.
    assert abs(share[0] - 0.467) < 0.02, share[0]
    assert abs(stale[0] - 0.275) < 0.03, stale[0]
    honest = stale[1:]
    assert abs(honest.mean() - 0.675) < 0.03, honest
    assert share[1:].sum() < 0.55


def test_analytical_net_benefits_sign_structure():
    """Large miners gain from slow propagation relative to small ones once
    difficulty retargets (reference plot.py:58-77): benefits are monotone
    non-increasing in hashrate order and the largest miner's is positive."""
    hashrates = [h / 100.0 for h in HASHRATES]
    ben = analytical_net_benefits(hashrates, 10.0)
    assert ben[0] > 0
    assert ben[0] > ben[-1]
    # Equal-hashrate miners see equal benefit.
    assert math.isclose(ben[7], ben[8], rel_tol=1e-12)
