"""Distributed layer: single-process degeneracy + 8-virtual-device mesh.

Real DCN multi-host needs multiple hosts; what is testable here is that the
distributed entry points compose correctly on the virtual 8-device mesh
(conftest) and that the single-process path is exactly the plain runner."""

from __future__ import annotations

import numpy as np
import jax

from tpusim.config import SimConfig, default_network
from tpusim.distributed import (
    global_mesh,
    initialize,
    make_global_keys,
    run_simulation_distributed,
)
from tpusim.runner import make_run_keys, run_simulation_config


def _small(runs):
    return SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=5 * 86_400_000,
        runs=runs,
        batch_size=runs,
        seed=9,
    )


def test_initialize_single_process_noop():
    initialize(num_processes=1)  # must not raise or try to reach a coordinator
    assert jax.process_count() == 1


def test_global_mesh_spans_devices():
    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices()) == 8
    assert mesh.axis_names == ("runs",)


def test_make_global_keys_matches_local():
    mesh = global_mesh()
    got = make_global_keys(9, 16, 32, mesh)
    want = make_run_keys(9, 16, 32)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(got)), np.asarray(jax.random.key_data(want))
    )


def test_distributed_equals_plain_runner():
    config = _small(32)
    a = run_simulation_distributed(config)
    b = run_simulation_config(config, use_all_devices=True)
    for ma, mb in zip(a.miners, b.miners):
        assert ma.stale_rate_mean == mb.stale_rate_mean
        assert ma.blocks_found_mean == mb.blocks_found_mean


def test_two_process_distributed_matches_single(tmp_path):
    """Spawn TWO real OS processes (4 virtual CPU devices each) under
    jax.distributed: make_global_keys' non-addressable shard assembly and the
    cross-process psum actually execute, and both controllers must return the
    same statistics as a plain single-process run of the identical config."""
    import json
    import socket
    import subprocess
    import sys
    from pathlib import Path

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    worker = Path(__file__).parent / "distributed_worker.py"
    # Strip PYTHONPATH: the container's sitecustomize (/root/.axon_site)
    # initializes the XLA backend at interpreter startup, which forbids
    # jax.distributed.initialize in the worker. The worker adds the repo
    # root to sys.path itself.
    env = {
        k: v for k, v in __import__("os").environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH")
    }
    # Output goes to files, not PIPEs: both workers block in collectives, so
    # draining one worker's pipe while the other fills its 64 KB buffer could
    # deadlock the pair until the timeout.
    logs = []
    procs = []
    for i in range(2):
        out_f = open(tmp_path / f"worker{i}.out", "w+")
        err_f = open(tmp_path / f"worker{i}.err", "w+")
        logs.append((out_f, err_f))
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker), coordinator, "2", str(i)],
                stdout=out_f, stderr=err_f, text=True, env=env,
            )
        )
    outs = []
    try:
        for p, (out_f, err_f) in zip(procs, logs):
            rc = p.wait(timeout=420)
            out_f.seek(0)
            err_f.seek(0)
            out, err = out_f.read(), err_f.read()
            if rc != 0 and "Multiprocess computations aren't implemented" in err:
                # Older jaxlib CPU backends cannot execute multi-process SPMD
                # programs at all — an environment capability gap, not a
                # regression in the distributed layer.
                import pytest

                pytest.skip("this jaxlib's CPU backend lacks multiprocess support")
            assert rc == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for out_f, err_f in logs:
            out_f.close()
            err_f.close()

    payloads = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT=")]
        assert lines, f"no RESULT line in worker output: {out[-500:]}"
        payloads.append(json.loads(lines[0][len("RESULT="):]))

    assert payloads[0]["runs"] == payloads[1]["runs"] == 32
    for key in ("blocks_found_mean", "blocks_share_mean", "stale_rate_mean"):
        np.testing.assert_allclose(payloads[0][key], payloads[1][key], rtol=0, atol=0)

    # Same config, plain single-process runner (this process, 8-device mesh):
    # identical statistics — the process layout must be observationally
    # invisible (same per-run keys, same mean-of-ratios reduction).
    config = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=5 * 86_400_000,
        runs=32,
        batch_size=16,
        seed=9,
    )
    local = run_simulation_config(config, use_all_devices=False)
    np.testing.assert_allclose(
        payloads[0]["blocks_found_mean"],
        [m.blocks_found_mean for m in local.miners], rtol=1e-12,
    )
    np.testing.assert_allclose(
        payloads[0]["stale_rate_mean"],
        [m.stale_rate_mean for m in local.miners], rtol=1e-6,
    )
