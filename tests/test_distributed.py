"""Distributed layer: single-process degeneracy + 8-virtual-device mesh.

Real DCN multi-host needs multiple hosts; what is testable here is that the
distributed entry points compose correctly on the virtual 8-device mesh
(conftest) and that the single-process path is exactly the plain runner."""

from __future__ import annotations

import numpy as np
import jax

from tpusim.config import SimConfig, default_network
from tpusim.distributed import (
    global_mesh,
    initialize,
    make_global_keys,
    run_simulation_distributed,
)
from tpusim.runner import make_run_keys, run_simulation_config


def _small(runs):
    return SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=5 * 86_400_000,
        runs=runs,
        batch_size=runs,
        seed=9,
    )


def test_initialize_single_process_noop():
    initialize(num_processes=1)  # must not raise or try to reach a coordinator
    assert jax.process_count() == 1


def test_global_mesh_spans_devices():
    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices()) == 8
    assert mesh.axis_names == ("runs",)


def test_make_global_keys_matches_local():
    mesh = global_mesh()
    got = make_global_keys(9, 16, 32, mesh)
    want = make_run_keys(9, 16, 32)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(got)), np.asarray(jax.random.key_data(want))
    )


def test_distributed_equals_plain_runner():
    config = _small(32)
    a = run_simulation_distributed(config)
    b = run_simulation_config(config, use_all_devices=True)
    for ma, mb in zip(a.miners, b.miners):
        assert ma.stale_rate_mean == mb.stale_rate_mean
        assert ma.blocks_found_mean == mb.blocks_found_mean
