"""Runner, sharding, checkpoint, CLI, and sampling-primitive unit tests.

The multi-device cases run on the 8 virtual CPU devices conftest.py forces, so
the shard_map/psum path of the engine (the reference's run-level parallelism,
main.cpp:195-220, re-expressed over a device mesh) is exercised in every CI
run, not only by the driver's separate dry-run entry point.
"""

from __future__ import annotations

import dataclasses
import json
import re

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from tpusim.cli import main as cli_main
from tpusim.config import MinerConfig, NetworkConfig, SimConfig, default_network
from tpusim.engine import Engine
from tpusim.runner import make_run_keys, run_simulation_config
from tpusim.sampling import (
    PERC_MULTIPLIER32,
    interval_from_bits,
    winner_from_bits,
    winner_thresholds,
    winner_thresholds32,
)

SMALL = SimConfig(
    network=default_network(propagation_ms=5000),
    duration_ms=3 * 86_400_000,
    runs=16,
    batch_size=16,
    seed=3,
)


def test_sharded_matches_single_device():
    keys = make_run_keys(SMALL.seed, 0, SMALL.runs)
    mesh = Mesh(np.array(jax.devices()[:8]), ("runs",))
    sharded = Engine(SMALL, mesh).run_batch(keys)
    single = Engine(SMALL, None).run_batch(keys)
    for name in single:
        np.testing.assert_allclose(
            np.asarray(sharded[name]), np.asarray(single[name]), rtol=1e-6, err_msg=name
        )


def test_device_loop_matches_host_loop():
    """The single-device whole-batch device loop (lax.while_loop over chunks,
    int32-pair ledger) must be bit-identical to the per-chunk host loop (int64
    numpy ledger) — both honest/fast and selfish/exact, across several
    re-bases (duration > TIME_CAP would be ideal but slow; several chunks of
    a small chunk_steps exercise the same ledger path)."""
    selfish_net = NetworkConfig(
        miners=(
            MinerConfig(hashrate_pct=40, propagation_ms=1000, selfish=True),
            MinerConfig(hashrate_pct=35, propagation_ms=1000),
            MinerConfig(hashrate_pct=25, propagation_ms=1000),
        )
    )
    for config in (
        dataclasses.replace(SMALL, chunk_steps=64),
        dataclasses.replace(SMALL, network=selfish_net, chunk_steps=64),
        # 14 days > 2^30 ms: hi0 starts > 0, so the hi limb, the borrow
        # (lo < 0 & hi > 0), and the hi*base+lo t_end reconstruction of the
        # device ledger are all live — not just the single-limb fast path.
        dataclasses.replace(SMALL, runs=8, batch_size=8, duration_ms=14 * 86_400_000),
        # 26 days > 2^31 ms: the duration no longer fits int32 at all and
        # hi0 = 2, so the ledger borrows more than once per run (~4 TIME_CAP
        # window crossings each).
        dataclasses.replace(
            SMALL,
            runs=4,
            batch_size=4,
            duration_ms=26 * 86_400_000,
            network=NetworkConfig(
                miners=(
                    MinerConfig(hashrate_pct=60, propagation_ms=2000),
                    MinerConfig(hashrate_pct=40, propagation_ms=500),
                ),
                # 30 min interval: ~1250 blocks (~2550 events) over 26 d keeps
                # every window busy while the whole case stays under a minute.
                block_interval_s=3600.0 / 2,
            ),
        ),
    ):
        engine = Engine(config)
        keys = make_run_keys(config.seed, 0, config.runs)
        device = engine.run_batch(keys)
        host = engine.run_batch(keys, host_loop=True)
        assert device.keys() == host.keys()
        for name in device:
            np.testing.assert_array_equal(
                np.asarray(device[name]), np.asarray(host[name]), err_msg=name
            )


def test_runner_remainder_batch_not_divisible_by_mesh():
    """runs % n_devices != 0: the trailing remainder runs unsharded, and the
    result equals a single-device run of the same config."""
    config = dataclasses.replace(SMALL, runs=20, batch_size=8)
    res_multi = run_simulation_config(config, use_all_devices=True)
    res_single = run_simulation_config(config, use_all_devices=False)
    assert res_multi.runs == res_single.runs == 20
    for a, b in zip(res_multi.miners, res_single.miners):
        assert a.blocks_found_mean == b.blocks_found_mean
        np.testing.assert_allclose(a.stale_rate_mean, b.stale_rate_mean, rtol=1e-6)


def test_checkpoint_resume_extends_sweep(tmp_path):
    """A checkpointed 16-run sweep extended to 32 runs equals a fresh 32-run
    sweep batch for batch (keys are global-run-indexed; sums are additive)."""
    ck = tmp_path / "ck.npz"
    cfg16 = dataclasses.replace(SMALL, runs=16, batch_size=8)
    cfg32 = dataclasses.replace(SMALL, runs=32, batch_size=8)
    run_simulation_config(cfg16, use_all_devices=False, checkpoint_path=ck)
    resumed = run_simulation_config(cfg32, use_all_devices=False, checkpoint_path=ck)
    fresh = run_simulation_config(cfg32, use_all_devices=False)
    assert resumed.runs == fresh.runs == 32
    for a, b in zip(resumed.miners, fresh.miners):
        assert a.blocks_found_mean == b.blocks_found_mean
        assert a.stale_blocks_mean == b.stale_blocks_mean
        np.testing.assert_allclose(a.blocks_share_mean, b.blocks_share_mean, rtol=0, atol=1e-12)


def test_checkpoint_rejects_different_config(tmp_path):
    ck = tmp_path / "ck.npz"
    run_simulation_config(SMALL, use_all_devices=False, checkpoint_path=ck)
    other = dataclasses.replace(SMALL, duration_ms=86_400_000)
    with pytest.raises(ValueError, match="different config"):
        run_simulation_config(other, use_all_devices=False, checkpoint_path=ck)


def test_checkpoint_allows_rebatching(tmp_path):
    """batch_size and runs are excluded from the fingerprint by design."""
    ck = tmp_path / "ck.npz"
    run_simulation_config(SMALL, use_all_devices=False, checkpoint_path=ck)
    rebatched = dataclasses.replace(SMALL, runs=24, batch_size=4)
    res = run_simulation_config(rebatched, use_all_devices=False, checkpoint_path=ck)
    assert res.runs == 24


# --- CLI ------------------------------------------------------------------


def test_cli_table_format(tmp_path, capsys):
    out_json = tmp_path / "out.json"
    rc = cli_main(
        [
            "--runs", "4", "--days", "2", "--propagation-ms", "1000",
            "--batch-size", "4", "--quiet", "--single-device",
            "--json", str(out_json),
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "After running 4 simulations for 2d each, on average:" in out
    # Canonical per-miner line (reference main.cpp:227-234).
    assert re.search(
        r"  - Miner 0 \(30% of network hashrate\) found \d+ blocks "
        r"i\.e\. [\d.]+% of blocks\. Stale rate: [\d.e-]+%\.",
        out,
    ), out
    data = json.loads(out_json.read_text())
    assert data["runs"] == 4 and len(data["miners"]) == 9


def test_cli_selfish_flag_marks_miner(capsys):
    rc = cli_main(
        [
            "--runs", "2", "--days", "2", "--hashrates", "40,60", "--selfish", "0",
            "--batch-size", "2", "--quiet", "--single-device",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "('selfish mining' strategy)" in out
    assert out.count("selfish mining") == 1


def test_cli_rejects_bad_hashrates():
    with pytest.raises(SystemExit):
        cli_main(["--hashrates", "50,49"])  # sums to 99


def test_cli_config_file_roundtrip(tmp_path, capsys):
    cfg = dataclasses.replace(SMALL, runs=2, duration_ms=86_400_000)
    path = tmp_path / "cfg.json"
    path.write_text(cfg.to_json())
    rc = cli_main(["--config", str(path), "--quiet", "--single-device"])
    assert rc == 0
    assert "After running 2 simulations" in capsys.readouterr().out


# --- sampling primitives ---------------------------------------------------


def test_winner_thresholds_u64_exact():
    t = winner_thresholds(np.array([30, 29, 12, 11, 8, 5, 3, 1, 1]))
    assert t.dtype == np.uint64
    assert int(t[-1]) == 100 * ((2**64 - 1) // 100)
    assert (np.diff(t.astype(object)) > 0).all()


def test_winner_from_bits_boundaries():
    thresholds = jnp.asarray(winner_thresholds32(np.array([50, 50])))
    assert int(winner_from_bits(jnp.uint32(0), thresholds)) == 0
    assert int(winner_from_bits(jnp.uint32(50 * PERC_MULTIPLIER32 - 1), thresholds)) == 0
    assert int(winner_from_bits(jnp.uint32(50 * PERC_MULTIPLIER32), thresholds)) == 1
    # Draws past the 100% threshold clamp to the last miner (the reference
    # asserts instead, simulation.h:220).
    assert int(winner_from_bits(jnp.uint32(2**32 - 1), thresholds)) == 1


def test_interval_from_bits_zero_and_positive():
    assert int(interval_from_bits(jnp.uint32(0), 600_000.0)) == 0
    assert int(interval_from_bits(jnp.uint32(2**32 - 1), 600_000.0)) > 0


def test_group_slots_auto_resolution_and_roundtrip():
    """group_slots=None resolves 2 in both modes (round 5: exact flipped
    from 4 on measured overflow/accuracy evidence, see
    SimConfig.resolved_group_slots), survives JSON round-trip as None, and
    an explicit value is respected everywhere."""
    fast = SimConfig(network=default_network(propagation_ms=1000))
    assert fast.resolved_mode == "fast" and fast.resolved_group_slots == 2
    exact = dataclasses.replace(fast, mode="exact")
    assert exact.resolved_group_slots == 2
    assert SimConfig.from_json(fast.to_json()).group_slots is None
    explicit = dataclasses.replace(fast, group_slots=8)
    assert explicit.resolved_group_slots == 8
    assert SimConfig.from_json(explicit.to_json()).resolved_group_slots == 8
    assert Engine(explicit).params is not None  # builds with explicit K
    with pytest.raises(ValueError, match="group_slots"):
        dataclasses.replace(fast, group_slots=1)


def test_config_validation_errors():
    with pytest.raises(ValueError, match="sum to 100"):
        NetworkConfig(miners=(MinerConfig(hashrate_pct=50),))
    with pytest.raises(ValueError, match="hashrate_pct"):
        MinerConfig(hashrate_pct=101)
    with pytest.raises(ValueError, match="int32 time envelope"):
        SimConfig(
            network=NetworkConfig(
                miners=(MinerConfig(hashrate_pct=100),), block_interval_s=7200.0
            )
        )


def test_engine_override_and_pallas_cpu_fallback(caplog):
    """engine="scan" and engine="pallas" must agree: on CPU the forced
    Pallas engine passes construction (512 runs = one full fast-mode tile,
    so run_batch reaches the kernel instead of the small-batch scan-twin
    route), fails lowering at run time ("Only interpret mode is supported
    on CPU backend"), and the runner's batch-level fallback reruns on the
    draw-identical scan twin — so the sums come out equal and the fallback
    is logged. An unknown engine name is rejected."""
    config = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=86_400_000,
        runs=512,
        batch_size=512,
        seed=9,
    )
    scan = run_simulation_config(config, engine="scan", use_all_devices=False)
    with caplog.at_level("ERROR", logger="tpusim"):
        via_pallas = run_simulation_config(config, engine="pallas", use_all_devices=False)
    # Pinned assumption: jax currently refuses to lower a non-interpret
    # pallas_call on the CPU backend, which is what exercises the runtime
    # fallback path. If a future jax version lowers it (or fails before
    # run_batch), this assert fires and the test must find a new way to
    # force a runtime kernel failure — do not just delete the assert.
    assert any("falling back to the scan engine" in r.message for r in caplog.records)
    # to_json() embeds wall-clock timing; compare the statistics only.
    assert scan.table() == via_pallas.table()
    assert scan.overflow_total == via_pallas.overflow_total
    assert scan.best_height_mean == via_pallas.best_height_mean
    with pytest.raises(ValueError, match="unknown engine"):
        run_simulation_config(config, engine="mosaic")
    # Forced pallas is strict: an ineligible config raises the engine's own
    # error instead of silently downgrading (auto would downgrade quietly).
    selfish_fast = dataclasses.replace(
        config,
        network=default_network(
            propagation_ms=1000, selfish_ids=(0,), hashrates=(40, 19, 12, 11, 8, 5, 3, 1, 1)
        ),
        mode="fast",
    )
    with pytest.raises(ValueError, match="exact mode"):
        run_simulation_config(selfish_fast, engine="pallas", use_all_devices=False)
