"""Performance observability: the CompileLedger's `compile` spans against
compile_count_guard across cold/warmed/pipelined dispatch, per-batch memory
watermark attrs, the zero-hot-path-overhead pin (jaxpr byte-identical with
telemetry armed), and the `tpusim perf` ledger schema + spread-aware noise
gate (self-vs-self passes, a synthetic 2x regression fails).
"""

from __future__ import annotations

import json

import jax
import pytest

from tpusim import perf
from tpusim.config import SimConfig, default_network
from tpusim.engine import Engine
from tpusim.runner import make_engine, run_simulation_config
from tpusim.telemetry import (
    CompileLedger,
    TelemetryRecorder,
    device_memory_attrs,
    load_spans,
)
from tpusim.testing import compile_count_guard

SMALL = SimConfig(
    network=default_network(propagation_ms=1000),
    duration_ms=86_400_000,
    runs=8,
    batch_size=4,
    seed=3,
)


def _compile_spans(path) -> list[dict]:
    # The recorder opens its file lazily: in a warmed full-suite process the
    # eager-op caches mean zero compiles may have fired yet, so no file is
    # a valid "no spans yet" state, not an error.
    if not path.exists():
        return []
    return [s for s in load_spans(path) if s["span"] == "compile"]


# ---------------------------------------------------------------------------
# Compile spans vs. the guard.


def test_compile_spans_agree_with_guard_across_dispatch_paths(tmp_path):
    """The observability half (CompileLedger spans) and the assertion half
    (compile_count_guard) ride the SAME listener, so their counts must agree
    event-for-event: cold dispatch emits exactly as many spans as the guard
    counts, warmed dispatch emits none — on both the device-loop and the
    pipelined path."""
    path = tmp_path / "t.jsonl"
    rec = TelemetryRecorder(path)
    ledger = CompileLedger(rec).install()
    try:
        # The ledger is session-scoped: engine construction and key building
        # compile helper programs too, and every one must land as a span —
        # so the guard comparison is on the DELTA around each guarded block.
        eng = Engine(SMALL)
        keys = eng.make_keys(0, 8)
        assert len(_compile_spans(path)) == ledger.compiles

        n0 = len(_compile_spans(path))
        with compile_count_guard() as cold:
            eng.run_batch(keys)
        assert cold.count > 0
        assert len(_compile_spans(path)) - n0 == cold.count

        n1 = len(_compile_spans(path))
        with compile_count_guard(exact=0):
            eng.run_batch(keys)
        assert len(_compile_spans(path)) == n1  # warmed: no new spans

        with compile_count_guard() as pipe_cold:
            eng.run_batch(keys, pipelined=True)
        assert pipe_cold.count > 0  # the donating _pipe_chunk program
        assert len(_compile_spans(path)) - n1 == pipe_cold.count

        n2 = len(_compile_spans(path))
        with compile_count_guard(exact=0):
            eng.run_batch(keys, pipelined=True)
        assert len(_compile_spans(path)) == n2
    finally:
        ledger.uninstall()
        rec.close()
    # Uninstalled: further compiles must not reach this recorder's ledger.
    n_before = len(_compile_spans(path))
    Engine(SMALL).run_batch(Engine(SMALL).make_keys(0, 8))
    assert len(_compile_spans(path)) == n_before


def test_compile_ledger_context_and_cache_events(tmp_path):
    path = tmp_path / "t.jsonl"
    rec = TelemetryRecorder(path)
    ledger = CompileLedger(rec).install()
    try:
        ledger.set_context(dispatch="unit-test", engine="Engine")
        cache: dict = {}
        e1 = make_engine(SMALL, cache=cache, compile_ledger=ledger)
        e2 = make_engine(SMALL, cache=cache, compile_ledger=ledger)
        assert e2 is e1  # same reuse_key: the hit rebinds the same object
        assert ledger.cache_hits == 1 and ledger.cache_misses == 1
        e1.run_batch(e1.make_keys(0, 8))
    finally:
        ledger.uninstall()
        rec.close()
    spans = load_spans(path)
    cache_spans = [s for s in spans if s["span"] == "engine_cache"]
    assert [s["attrs"]["hit"] for s in cache_spans] == [False, True]
    comp = _compile_spans(path)
    assert comp and all(
        s["attrs"]["dispatch"] == "unit-test" and s["attrs"]["engine"] == "Engine"
        for s in comp
    )
    summary = ledger.summary_attrs()
    assert summary["compiles"] == len(comp)
    assert summary["compile_span_s"] >= 0.0
    assert summary["engine_cache_hits"] == 1
    assert summary["engine_cache_misses"] == 1


# ---------------------------------------------------------------------------
# Memory attrs.


def test_device_memory_attrs_present_and_sane():
    import jax.numpy as jnp

    anchor = jnp.arange(1024, dtype=jnp.int32)
    attrs = device_memory_attrs()
    assert attrs["mem_live_buffers"] >= 1
    # The watermark must at least cover the buffer we are provably holding.
    assert attrs["mem_live_bytes"] >= anchor.nbytes
    # Allocator stats are platform-optional (absent on CPU) — but when
    # present they must be positive.
    for key in ("mem_bytes_in_use", "mem_peak_bytes"):
        if key in attrs:
            assert attrs[key] > 0


def test_engine_memory_attrs_models():
    from tpusim.pallas_engine import VMEM_BUDGET, PallasEngine
    from tpusim.profiling import state_bytes_per_run

    eng = Engine(SMALL)
    attrs = eng.memory_attrs()
    assert attrs["state_bytes_per_run"] == state_bytes_per_run(eng)
    assert attrs["state_bytes_per_run"] > 0
    # The pallas engine adds its VMEM estimate vs. the guard's budget
    # (interpret mode: CPU containers have no Mosaic).
    cfg = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=86_400_000, runs=128, batch_size=128, seed=3,
    )
    peng = PallasEngine(cfg, interpret=True)
    pattrs = peng.memory_attrs()
    assert pattrs["vmem_est_bytes"] == peng.vmem_est > 0
    assert pattrs["vmem_budget_bytes"] == VMEM_BUDGET
    assert pattrs["state_bytes_per_run"] > 0


def test_runner_batch_spans_carry_memory_and_run_span_totals(tmp_path):
    path = tmp_path / "run.jsonl"
    rec = TelemetryRecorder(path)
    run_simulation_config(SMALL, use_all_devices=False, telemetry=rec)
    rec.close()
    spans = load_spans(path)
    batches = [s for s in spans if s["span"] == "batch"]
    assert batches
    for sp in batches:
        attrs = sp["attrs"]
        assert attrs["mem_live_bytes"] > 0
        assert attrs["mem_live_buffers"] >= 1
        assert attrs["state_bytes_per_run"] > 0
    run = next(s for s in spans if s["span"] == "run")["attrs"]
    comp = _compile_spans(path)
    assert run["compiles"] == len(comp) > 0
    assert run["compile_span_s"] > 0.0
    assert run["engine_cache_hits"] == 0 and run["engine_cache_misses"] == 0
    # Context attribution: the compiles provoked by the first dispatch carry
    # the dispatch path and the engine's reuse_key.
    dispatched = [s for s in comp if s["attrs"].get("dispatch")]
    assert dispatched and all(
        s["attrs"]["dispatch"] == "run_batch_async" for s in dispatched
    )
    assert all("reuse_key" in s["attrs"] for s in dispatched)


# ---------------------------------------------------------------------------
# Zero hot-path overhead.


def test_chunk_program_byte_identical_with_telemetry_armed(tmp_path):
    """The perf-observability layer is host-side listeners and batch-boundary
    probes ONLY: the device-loop program must be byte-identical with a
    ledger armed, and warmed dispatch must stay at exactly zero compiles."""
    keys = Engine(SMALL).make_keys(0, 8)

    def loop_jaxpr() -> str:
        eng = Engine(SMALL)
        hi, lo = eng._ledger_init(8)
        return str(jax.make_jaxpr(
            lambda k: eng._device_loop(k, hi, lo, eng.params)
        )(keys))

    plain = loop_jaxpr()
    rec = TelemetryRecorder(tmp_path / "armed.jsonl")
    ledger = CompileLedger(rec).install()
    try:
        assert loop_jaxpr() == plain
        eng = Engine(SMALL)
        eng.run_batch(keys)
        with compile_count_guard(exact=0):
            eng.run_batch(keys)
    finally:
        ledger.uninstall()
        rec.close()


# ---------------------------------------------------------------------------
# The perf ledger schema.


def _row(value: float = 1.0, samples=None, scenario="chained_fast", **over):
    row = perf.perf_row(
        scenario, "s_per_chunk", value, unit="s/chunk",
        samples=samples if samples is not None else [value, value * 1.02],
        shape={"runs": 128, "n_chunks": 4, "chunk_steps": 256,
               "superstep": 2, "engine": "Engine", "mode": "fast",
               "rng_batch": True, "state_dtype": "int32"},
    )
    row.update(over)
    return row


def test_perf_row_schema_and_env_fingerprint():
    row = _row()
    perf.validate_row(row)  # must not raise
    env = row["env"]
    assert env["cpu_count"] >= 1
    assert "date" in env
    assert env["platform"] == "cpu"
    # jax_version rides along so cross-host rows are self-describing.
    assert env["jax_version"] == jax.__version__


@pytest.mark.parametrize("mutate, match", [
    (lambda r: r.pop("samples"), "missing required"),
    (lambda r: r.update(schema=99), "schema"),
    (lambda r: r.update(better="sideways"), "lower|higher"),
    (lambda r: r.update(value="fast"), "number"),
    (lambda r: r.update(samples=[]), "non-empty"),
    (lambda r: r.update(samples=[1.0, "x"]), "number list"),
    (lambda r: r.update(env="cpu"), "env"),
])
def test_validate_row_rejects(mutate, match):
    row = _row()
    mutate(row)
    with pytest.raises(ValueError, match=match):
        perf.validate_row(row)


def test_append_load_roundtrip_and_strict_loader(tmp_path):
    path = tmp_path / "ledger.jsonl"
    rows = [_row(1.0), _row(2.0, scenario="chained_exact")]
    perf.append_rows(path, rows)
    assert perf.load_rows(path) == rows
    # A torn line is corrupted evidence: the loader is strict, unlike
    # telemetry.load_spans (nothing writes a perf ledger concurrently).
    with path.open("a") as fh:
        fh.write('{"schema": 1, "scenario": "torn...\n')
    with pytest.raises(ValueError, match="unparseable"):
        perf.load_rows(path)


# ---------------------------------------------------------------------------
# The noise gate.


def _write(path, rows):
    with open(path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")


def test_compare_self_vs_self_passes(tmp_path):
    a = tmp_path / "a.jsonl"
    _write(a, [_row(1.0), _row(0.25, scenario="chained_exact")])
    results = perf.compare_rows(perf.load_rows(a), perf.load_rows(a))
    assert [r["status"] for r in results] == ["ok", "ok"]
    assert perf.main(["compare", str(a), str(a)]) == 0


def test_compare_flags_synthetic_2x_regression(tmp_path):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write(a, [_row(1.0, samples=[1.0, 1.05, 1.1])])
    _write(b, [_row(2.0, samples=[2.0, 2.1, 2.2])])
    results = perf.compare_rows(perf.load_rows(a), perf.load_rows(b))
    assert results[0]["status"] == "regression"
    assert results[0]["ratio"] == pytest.approx(2.0)
    assert perf.main(["compare", str(a), str(b)]) == 1
    # The improvement direction must NOT fail the gate.
    assert perf.main(["compare", str(b), str(a)]) == 0
    results = perf.compare_rows(perf.load_rows(b), perf.load_rows(a))
    assert results[0]["status"] == "improved"


def test_compare_noise_model_widens_margin(tmp_path):
    """A ratio past the floor but inside the measured sample spread is
    noise, not a regression — the property that keeps the CI gate alive on
    a noisy shared host without going blind to real regressions."""
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write(a, [_row(1.0, samples=[1.0, 1.6])])  # 60% measured spread
    _write(b, [_row(1.4, samples=[1.4, 1.5])])
    results = perf.compare_rows(perf.load_rows(a), perf.load_rows(b))
    # margin = max(0.25, 2 * 0.6) = 1.2 > ratio-1 = 0.4
    assert results[0]["status"] == "ok"
    assert results[0]["margin"] == pytest.approx(1.2)
    # The same ratio with tight samples IS a regression.
    _write(a, [_row(1.0, samples=[1.0, 1.02])])
    results = perf.compare_rows(perf.load_rows(a), perf.load_rows(b))
    assert results[0]["status"] == "regression"


def test_compare_refuses_empty_baseline(tmp_path):
    """A truncated/empty baseline marks every candidate row 'new' and
    compares NOTHING — that must fail the gate (exit 2), not turn it green
    (artifacts/README.md tells operators to truncate before regenerating;
    the half-done state must be loud)."""
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    a.write_text("")
    _write(b, [_row(1.0)])
    assert perf.main(["compare", str(a), str(b)]) == 2
    b.write_text("")  # both empty: still nothing gated
    assert perf.main(["compare", str(a), str(b)]) == 2


def test_compile_ledger_uninstalled_on_setup_failure(tmp_path):
    """A run that fails BETWEEN ledger install and the batch loop (here:
    make_engine's tuning-override strictness) must still unsubscribe — a
    leaked subscriber would narrate every later run's compiles into the
    dead run's ledger with a stale run_id."""
    from tpusim import testing as t

    rec = TelemetryRecorder(tmp_path / "x.jsonl")
    before = len(t._compile_subscribers)
    with pytest.raises(ValueError, match="auto-routes"):
        run_simulation_config(
            SMALL, use_all_devices=False, telemetry=rec, tile_runs=256
        )
    rec.close()
    assert len(t._compile_subscribers) == before


def test_compare_refuses_missing_and_incomparable(tmp_path):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write(a, [_row(1.0), _row(0.25, scenario="chained_exact")])
    _write(b, [_row(1.0)])  # exact scenario missing from the candidate
    assert perf.main(["compare", str(a), str(b)]) == 2
    # Shape drift (different pinned runs) is a category error, not noise.
    changed = _row(1.0)
    changed["shape"]["runs"] = 512
    _write(b, [changed, _row(0.25, scenario="chained_exact")])
    results = perf.compare_rows(perf.load_rows(a), perf.load_rows(b))
    by_scenario = {r["scenario"]: r for r in results}
    assert by_scenario["chained_fast"]["status"] == "incomparable"
    assert by_scenario["chained_exact"]["status"] == "ok"
    assert perf.main(["compare", str(a), str(b)]) == 2


def test_latest_row_per_scenario_wins(tmp_path):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write(a, [_row(5.0), _row(1.0)])  # append-only: the NEWER row gates
    _write(b, [_row(1.0)])
    results = perf.compare_rows(perf.load_rows(a), perf.load_rows(b))
    assert results[0]["status"] == "ok"
    assert results[0]["base_value"] == 1.0


# ---------------------------------------------------------------------------
# perf run end-to-end (tiny shape) + CLI dispatch.


def test_perf_run_compare_report_end_to_end(tmp_path):
    """The CI leg's exact flow at a test-sized shape: run appends
    schema-valid rows, self-compare passes the gate, report renders."""
    out = tmp_path / "perf.jsonl"
    rc = perf.main([
        "run", "--quick", "--runs", "8", "--n-chunks", "2", "--repeats", "2",
        "--scenarios", "fast", "--out", str(out),
    ])
    assert rc == 0
    rows = perf.load_rows(out)
    assert len(rows) == 1
    row = rows[0]
    assert row["scenario"] == "chained_fast"
    assert len(row["samples"]) == 2  # ALL samples recorded, not just best
    assert row["value"] > 0
    assert row["shape"]["runs"] == 8 and row["shape"]["n_chunks"] == 2
    assert perf.main(["compare", str(out), str(out)]) == 0
    assert perf.main(["report", str(out)]) == 0
    # Subcommand dispatch through the umbrella CLI (jax-free for report).
    from tpusim.cli import main as cli_main

    assert cli_main(["perf", "report", str(out)]) == 0


def test_perf_run_engine_pin_excludes_sweep_scenario(tmp_path, monkeypatch, capsys):
    """run_sweep_protocol always measures the auto-selected engine pair, so
    a pinned --engine must never mislabel its ledger rows: the default
    scenario set silently drops packed_sweep (with a notice), an explicit
    --scenarios request fails loud, and --engine auto still runs it."""
    calls = []
    monkeypatch.setattr(
        perf, "run_protocol", lambda **kw: calls.append(("chained", kw)) or []
    )
    monkeypatch.setattr(
        perf, "run_sweep_protocol",
        lambda **kw: calls.append(("sweep", kw)) or [],
    )
    out = tmp_path / "perf.jsonl"
    rc = perf.main(["run", "--quick", "--engine", "scan", "--out", str(out)])
    assert rc == 0
    assert [c[0] for c in calls] == ["chained"]
    assert "skipping packed_sweep" in capsys.readouterr().out
    with pytest.raises(SystemExit) as ei:
        perf.main(["run", "--engine", "scan", "--scenarios", "packed_sweep",
                   "--out", str(out)])
    assert ei.value.code == 2
    calls.clear()
    assert perf.main(["run", "--quick", "--out", str(out)]) == 0
    # The default set runs the base sweep pair plus the ckpt/xoro variants.
    assert [c[0] for c in calls] == ["chained", "sweep", "sweep", "sweep"]
    assert [c[1].get("variant") for c in calls[1:]] == [None, "ckpt", "xoro"]


def test_committed_calibration_baseline_is_valid():
    """The baseline ci.sh gates against must stay schema-valid and carry
    both canonical scenarios at the quick shape."""
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "artifacts" / "perf" / "calibration_cpu.jsonl"
    rows = perf.load_rows(path)
    latest = perf.latest_by_scenario(rows)
    assert ("chained_fast", "s_per_chunk") in latest
    assert ("chained_exact", "s_per_chunk") in latest
    # The year-long int16-rebased domain must stay gated too (the PR-10
    # scenario: state_dtype pinned "int16" is only legal there because
    # count_rebase makes the 365 d bound fit).
    assert ("chained_fast_yearlong", "s_per_chunk") in latest
    yl = latest[("chained_fast_yearlong", "s_per_chunk")]
    assert yl["shape"]["state_dtype"] == "int16" and yl["shape"]["count_rebase"]
    # The grid-packing pair (PR-11 scenario) gates packed dispatch: the
    # packed row must keep its sequential before-twin so the speedup claim
    # stays anchored, and both must be at the quick sweep shape.
    assert ("sweep_sequential", "points_per_s") in latest
    assert ("sweep_packed", "points_per_s") in latest
    # The PR-16 variant rows gate the retired carve-outs: a checkpointed
    # packed grid and a per-run-xoroshiro packed grid each keep their own
    # calibration row so a regression back to the sequential fallback
    # (a ~2x slowdown at this shape) reddens `perf compare`.
    ck = latest[("sweep_packed_ckpt", "points_per_s")]
    assert ck["shape"]["checkpointed"] and ck["shape"]["rng"] == "threefry"
    xo = latest[("sweep_packed_xoro", "points_per_s")]
    assert not xo["shape"]["checkpointed"] and xo["shape"]["rng"] == "xoroshiro"
    sweep_quick = perf.SWEEP_PROTOCOL["quick"]
    n_points = len(sweep_quick["intervals"]) * len(sweep_quick["pcts"])
    for row in latest.values():
        assert row["env"]["platform"] == "cpu"
        if row["scenario"].startswith("sweep_"):
            assert row["better"] == "higher"
            assert row["shape"]["points"] == n_points
            assert row["shape"]["runs_per_point"] == sweep_quick["runs"]
            assert row["shape"]["packed"] == row["scenario"].startswith("sweep_packed")
            assert len(row["samples"]) == sweep_quick["repeats"]
        else:
            assert row["shape"]["runs"] == perf.PROTOCOL["quick"]["runs"]
            assert len(row["samples"]) == perf.PROTOCOL["quick"]["repeats"]
