"""Elastic sweep fleet (tpusim.fleet): the preemption-tolerant worker
supervisor and its chaos drills.

Two tiers, mirroring the module's design:

  * **Supervisor logic** driven by a jax-free fake worker
    (tests/fleet_fake_worker.py) — queue/lease/requeue/backoff/quarantine/
    resume semantics in milliseconds per test;
  * **End-to-end healing** driven by REAL ``run_simulation_config`` workers:
    one fleet run whose attempt-0 workers are killed at every checkpoint
    save phase, wedged past the lease deadline, and hit with ENOSPC — the
    healed rows pinned BIT-EQUAL to an uninterrupted run at the same seed
    (the tests/test_chaos.py contract, across process boundaries).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path
from types import SimpleNamespace

import pytest

import tpusim.provenance as provenance
from tpusim.chaos import ChaosInjector, ChaosPlan, FaultSpec, load_plan
from tpusim.config import SimConfig, default_network
from tpusim.fleet import WORKER_CHAOS_ENV, FleetSupervisor
from tpusim.provenance import PROVENANCE_ENV, content_address, load_lineage
from tpusim.report import render_report
from tpusim.runner import run_simulation_config
from tpusim.telemetry import load_spans
from tpusim.watch import main as watch_main
from tpusim.watch import render_watch

FAKE_WORKER = Path(__file__).with_name("fleet_fake_worker.py")

#: Shared compiled-engine cache for the in-process reference runs.
ENGINE_CACHE: dict = {}


def fake_points(*names: str) -> list[tuple[str, SimConfig]]:
    net = default_network(propagation_ms=1000)
    return [(n, SimConfig(network=net, runs=4, batch_size=4)) for n in names]


def fake_cmd(behaviors: dict[str, str] | None = None, log: list | None = None):
    """A ``worker_cmd`` override launching the fake worker with a per-point
    behavior; ``log`` records every (point, attempt) the supervisor spawned."""
    behaviors = behaviors or {}

    def cmd(asg: dict) -> list[str]:
        if log is not None:
            log.append((asg["point"], asg["attempt"]))
        return [
            sys.executable, str(FAKE_WORKER),
            "--point", asg["point"],
            "--result", str(asg["result_path"]),
            "--heartbeat", str(asg["heartbeat_path"]),
            "--attempt", str(asg["attempt"]),
            "--behavior", behaviors.get(asg["point"], "ok"),
        ]

    return cmd


def make_sup(tmp_path: Path, points, **kw) -> FleetSupervisor:
    kw.setdefault("workers", 2)
    kw.setdefault("backoff_s", 0.05)
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("quiet", True)
    kw.setdefault("state_dir", tmp_path / "fleet")
    kw.setdefault("telemetry_path", tmp_path / "fleet" / "tele.jsonl")
    return FleetSupervisor(points, **kw)


def rows_of(sup: FleetSupervisor) -> list[dict]:
    out = []
    for line in sup.out_path.read_text().splitlines():
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return out


def events_of(sup: FleetSupervisor) -> list[dict]:
    return [
        json.loads(line)
        for line in sup.ledger_path.read_text().splitlines()
        if line.strip()
    ]


def plan(*faults: dict) -> ChaosPlan:
    return ChaosPlan(faults=[FaultSpec(**f) for f in faults])


# ---------------------------------------------------------------------------
# Supervisor logic (fake workers).


def test_fleet_completes_rows_in_point_order(tmp_path, thread_guard):
    # thread_guard: the supervisor's heartbeat daemon and worker subprocess
    # plumbing must leave the process thread-clean (lint JX016's runtime
    # half) — this is also the ci.sh thread-leak leg's target test.
    sup = make_sup(
        tmp_path, fake_points("pt-a", "pt-b", "pt-c"),
        worker_cmd=fake_cmd(),
        worker_chaos={"pt-a": plan({"point": "never.fires"})},
    )
    summary = sup.run()
    assert summary["points_done"] == 3
    assert summary["requeues"] == 0 and summary["quarantined"] == []
    rows = rows_of(sup)
    # Out-of-order completions are buffered and flushed in POINT order, so
    # the file is line-for-line comparable with run_sweep's.
    assert [r["point"] for r in rows] == ["pt-a", "pt-b", "pt-c"]
    # Worker-chaos plans ride the env into the matching point only.
    assert [r["chaos_env"] for r in rows] == [True, False, False]
    ev = [e["event"] for e in events_of(sup)]
    assert ev[0] == "fleet_start" and ev[-1] == "fleet_finish"
    assert ev.count("lease") == 3 and ev.count("done") == 3
    spans = load_spans(sup.recorder.path)
    assert {"fleet_spawn", "fleet_done", "fleet_status", "run"} <= {
        s["span"] for s in spans
    }
    # The closing span is named "run" so `tpusim watch` exits on completion.
    run = next(s for s in spans if s["span"] == "run")
    assert run["attrs"]["fleet"] is True and run["attrs"]["points_done"] == 3


def test_worker_crash_requeued_with_backoff_then_heals(tmp_path, thread_guard):
    sup = make_sup(
        tmp_path, fake_points("pt-a", "pt-b"),
        worker_cmd=fake_cmd({"pt-b": "fail-then-ok"}),
        worker_chaos={"pt-b": plan({"point": "never.fires"})},
    )
    summary = sup.run()
    assert summary["points_done"] == 2 and summary["requeues"] == 1
    rq = next(e for e in events_of(sup) if e["event"] == "requeue")
    assert rq["point"] == "pt-b" and rq["reason"] == "exit:1"
    assert rq["failures"] == 1 and rq["backoff_s"] > 0
    healed = next(r for r in rows_of(sup) if r["point"] == "pt-b")
    # The replacement worker is attempt 1 and runs WITHOUT the chaos env —
    # a fresh process would re-arm every fault count and die forever.
    assert healed["attempt"] == 1 and healed["chaos_env"] is False
    spans = load_spans(sup.recorder.path)
    rq_span = next(s for s in spans if s["span"] == "fleet_requeue")
    assert rq_span["attrs"]["target"] == "pt-b"


def test_poison_point_quarantined_loud_grid_drains(tmp_path, capsys):
    sup = make_sup(
        tmp_path, fake_points("pt-a", "pt-poison", "pt-c"),
        worker_cmd=fake_cmd({"pt-poison": "fail"}),
        max_point_failures=2,
    )
    summary = sup.run()
    # Bounded: K consecutive deaths quarantine the point by NAME; the rest
    # of the grid still drains and the summary is nonzero-worthy. The
    # requeue counter matches the ledger's requeue EVENTS (the quarantined
    # final death is not a requeue).
    assert summary["quarantined"] == ["pt-poison"]
    assert summary["points_done"] == 2 and summary["requeues"] == 1
    assert "QUARANTINED point 'pt-poison'" in capsys.readouterr().err
    assert [r["point"] for r in rows_of(sup)] == ["pt-a", "pt-c"]
    q = next(e for e in events_of(sup) if e["event"] == "quarantine")
    assert q["point"] == "pt-poison" and q["failures"] == 2
    spans = load_spans(sup.recorder.path)
    assert any(s["span"] == "fleet_quarantine" for s in spans)


def test_lease_expiry_kills_hung_worker(tmp_path):
    t0 = time.monotonic()
    sup = make_sup(
        tmp_path, fake_points("pt-hang"),
        worker_cmd=fake_cmd({"pt-hang": "hang-then-ok"}),
        lease_s=1.0,
    )
    summary = sup.run()
    # The wall-clock watchdog: one beat, then silence past lease_s ->
    # SIGKILL + requeue; the replacement attempt heals.
    assert summary["points_done"] == 1 and summary["requeues"] == 1
    rq = next(e for e in events_of(sup) if e["event"] == "requeue")
    assert rq["reason"] == "lease_expired"
    assert rows_of(sup)[0]["attempt"] == 1
    assert time.monotonic() - t0 < 30.0


def test_supervisor_heartbeat_hang_seam_expires_lease_in_chaos_time(tmp_path):
    # The supervisor-side fleet.heartbeat drill: an injected hang makes the
    # lease read as ALREADY expired, so the expiry path runs deterministically
    # without waiting out a real 60 s lease.
    t0 = time.monotonic()
    sup = make_sup(
        tmp_path, fake_points("pt-a"),
        worker_cmd=fake_cmd({"pt-a": "hang-then-ok"}),
        lease_s=60.0,
        chaos=ChaosInjector(plan({
            "point": "fleet.heartbeat", "kind": "hang", "count": -1,
            "when": {"target": "pt-a", "attempt": 0},
        })),
    )
    summary = sup.run()
    assert summary["points_done"] == 1 and summary["requeues"] == 1
    assert time.monotonic() - t0 < 30.0  # nowhere near the 60 s lease
    rq = next(e for e in events_of(sup) if e["event"] == "requeue")
    assert rq["reason"] == "lease_expired"
    spans = load_spans(sup.recorder.path)
    assert any(s["span"] == "chaos" for s in spans)  # the drill left its span


def test_spawn_seam_transient_fault_requeued(tmp_path):
    sup = make_sup(
        tmp_path, fake_points("pt-a", "pt-b"),
        worker_cmd=fake_cmd(),
        chaos=ChaosInjector(plan({
            "point": "fleet.spawn", "kind": "transient", "count": 1,
            "when": {"target": "pt-a", "attempt": 0},
        })),
    )
    summary = sup.run()
    assert summary["points_done"] == 2 and summary["requeues"] == 1
    rq = next(e for e in events_of(sup) if e["event"] == "requeue")
    assert rq["point"] == "pt-a" and rq["reason"].startswith("spawn_failed")
    assert [r["point"] for r in rows_of(sup)] == ["pt-a", "pt-b"]


def test_supervisor_resume_adopts_orphaned_lease(tmp_path):
    state = tmp_path / "fleet"
    state.mkdir(parents=True)
    # A previous supervisor's remains: pt-a's row landed, pt-b was leased
    # when the supervisor died (no done event), pt-c never started.
    (state / "rows.jsonl").write_text(json.dumps(
        {"runs": 4, "point": "pt-a", "backend": "tpu", "elapsed_s": 1.0}
    ) + "\n")
    (state / "fleet-ledger.jsonl").write_text("\n".join([
        json.dumps({"event": "fleet_start", "t": 0.0, "points": 3}),
        json.dumps({"event": "lease", "t": 0.0, "point": "pt-a", "worker": "w000"}),
        json.dumps({"event": "done", "t": 0.0, "point": "pt-a", "worker": "w000"}),
        json.dumps({"event": "lease", "t": 0.0, "point": "pt-b", "worker": "w001",
                    "pid": 99999}),
    ]) + "\n")
    spawned: list = []
    sup = make_sup(
        tmp_path, fake_points("pt-a", "pt-b", "pt-c"),
        worker_cmd=fake_cmd(log=spawned), resume=True,
    )
    summary = sup.run()
    # Only the orphaned and never-started points run; pt-a is skipped.
    assert sorted(p for p, _ in spawned) == ["pt-b", "pt-c"]
    assert summary["points_done"] == 3
    ev = events_of(sup)
    adopt = next(e for e in ev if e["event"] == "adopt")
    assert adopt["point"] == "pt-b" and adopt["prior_worker"] == "w001"
    rows = rows_of(sup)
    assert [r["point"] for r in rows] == ["pt-a", "pt-b", "pt-c"]


def test_supervisor_resume_reaps_live_orphan_worker(tmp_path):
    state = tmp_path / "fleet"
    state.mkdir(parents=True)
    # A dead supervisor's worker that is STILL RUNNING (the fleet.spawn
    # sigkill drill kills only the supervisor): its argv carries BOTH the
    # fleet-worker marker and the point name, like a real worker's does —
    # the reap guard requires both before it will SIGKILL a recorded pid.
    orphan = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(300)",
         "tpusim.fleet", "pt-b"]
    )
    try:
        (state / "fleet-ledger.jsonl").write_text(json.dumps(
            {"event": "lease", "t": 0.0, "point": "pt-b", "worker": "w009",
             "pid": orphan.pid}
        ) + "\n")
        sup = make_sup(
            tmp_path, fake_points("pt-b"), worker_cmd=fake_cmd(), resume=True,
        )
        summary = sup.run()
        assert summary["points_done"] == 1
        # The orphan was reaped BEFORE its replacement ran — no unsupervised
        # process racing the new worker on the same checkpoint.
        assert orphan.wait(timeout=10) == -signal.SIGKILL
        adopt = next(e for e in events_of(sup) if e["event"] == "adopt")
        assert adopt["reaped"] is True and adopt["prior_pid"] == orphan.pid
    finally:
        if orphan.poll() is None:
            orphan.kill()


def test_torn_ledger_and_out_lines_tolerated(tmp_path):
    state = tmp_path / "fleet"
    state.mkdir(parents=True)
    # A killed supervisor can tear the final line of both files mid-write;
    # resume must skip the fragments and the next append must repair the
    # missing newline instead of gluing onto them.
    (state / "rows.jsonl").write_text(
        json.dumps({"runs": 4, "point": "pt-a", "backend": "tpu"})
        + "\n" + '{"runs": 4, "point": "pt-'
    )
    (state / "fleet-ledger.jsonl").write_text(
        json.dumps({"event": "lease", "t": 0.0, "point": "pt-b"})
        + "\n" + '{"event": "don'
    )
    sup = make_sup(
        tmp_path, fake_points("pt-a", "pt-b"),
        worker_cmd=fake_cmd(), resume=True,
    )
    summary = sup.run()
    assert summary["points_done"] == 2
    raw = sup.out_path.read_text().splitlines()
    parsed = rows_of(sup)
    # Fragment line survives (newline-terminated, unparseable, skipped);
    # pt-b's fresh row landed on its own line.
    assert len(raw) == 3 and [r["point"] for r in parsed] == ["pt-a", "pt-b"]


def test_duplicate_point_names_rejected(tmp_path):
    with pytest.raises(ValueError, match="unique"):
        make_sup(tmp_path, fake_points("pt-a", "pt-a"))


# ---------------------------------------------------------------------------
# The committed drill plans.


def test_committed_drill_plans_load_and_name_known_seams():
    drills = Path(__file__).parent.parent / "drills"
    plans = sorted(drills.glob("*.json"))
    assert len(plans) >= 5, plans
    known = {
        "engine.run_batch", "engine.dispatch", "engine.dispatch_async",
        "pipeline.flag_fetch", "checkpoint.save", "checkpoint.load",
        "telemetry.write", "probe.attempt", "sweep.point",
        "fleet.spawn", "fleet.heartbeat",
        "serve.accept", "serve.dispatch", "serve.cache", "serve.drain",
    }
    for p in plans:
        for fault in load_plan(p).faults:
            assert fault.point in known, (p.name, fault.point)
    names = {p.name for p in plans}
    assert {"sigkill-pre-replace.json", "hang-fetch.json",
            "enospc-telemetry.json", "fleet-worker-kill.json",
            "fleet-worker-hang.json"} <= names


# ---------------------------------------------------------------------------
# `tpusim watch --wait-for-file` (the fleet-drill watcher satellite).


def test_watch_wait_for_file_times_out_bounded(tmp_path, capsys):
    t0 = time.monotonic()
    rc = watch_main([
        "--once", "--wait-for-file", "0.3", str(tmp_path / "never.jsonl")
    ])
    assert rc == 2
    assert time.monotonic() - t0 < 5.0
    assert "does not exist" in capsys.readouterr().err


def test_watch_wait_for_file_picks_up_late_ledger(tmp_path, capsys):
    led = tmp_path / "late.jsonl"

    def writer():
        time.sleep(0.4)
        led.write_text(json.dumps({
            "run_id": "abc", "span": "fleet_status", "t_start": time.time(),
            "dur_s": 0.0, "attrs": {"workers_alive": 2, "points_done": 0,
                                    "points_total": 3, "queued": 1},
        }) + "\n")

    th = threading.Thread(target=writer)
    th.start()
    rc = watch_main(["--once", "--wait-for-file", "10", str(led)])
    th.join()
    assert rc == 0
    assert "fleet: 2 worker(s) alive" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# End-to-end healing with REAL workers: SIGKILL at every checkpoint save
# phase, a full wedge past the lease deadline, and a checkpoint-write ENOSPC
# — every point requeued exactly once, every healed row bit-equal.

DRILL_CONFIG = SimConfig(
    network=default_network(propagation_ms=1000),
    duration_ms=10**8,
    runs=8,
    batch_size=4,
    seed=3,
)


def _kill_at(phase: str) -> ChaosPlan:
    return plan({"point": "checkpoint.save", "kind": "sigkill", "count": 1,
                 "when": {"phase": phase}})


DRILL_PLANS = {
    "pt-kill-begin": _kill_at("begin"),
    "pt-kill-pre": _kill_at("pre_replace"),
    "pt-kill-post": _kill_at("post_replace"),
    "pt-hang": plan({"point": "fleet.heartbeat", "kind": "hang", "count": 1,
                     "when": {"beats": 1}}),
    "pt-enospc": plan({"point": "checkpoint.save", "kind": "enospc",
                       "count": 1, "when": {"phase": "begin"}}),
}


@pytest.fixture(scope="module")
def drill(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fleet_drill")
    points = [(name, DRILL_CONFIG) for name in DRILL_PLANS]
    # Arm the provenance plane for the whole fleet (workers inherit the
    # env var), so the drill doubles as the lineage kill drill: SIGKILLed
    # writers must leave every fsync'd ledger record whole-or-absent, and
    # each healed row must chain back to the checkpoint it resumed from.
    lineage_path = tmp / "fleet" / "provenance" / "lineage.jsonl"
    os.environ[PROVENANCE_ENV] = str(lineage_path)
    provenance._WRITERS.clear()
    try:
        sup = FleetSupervisor(
            points,
            workers=2,
            state_dir=tmp / "fleet",
            telemetry_path=tmp / "fleet" / "tele.jsonl",
            worker_chaos=DRILL_PLANS,
            single_device=True,
            lease_s=10.0,
            heartbeat_s=0.25,
            backoff_s=0.05,
            poll_s=0.1,
            quiet=True,
        )
        summary = sup.run()
    finally:
        # Disarm BEFORE the reference run: the ref row is never written to
        # disk, so recording it would only pad the ledger.
        os.environ.pop(PROVENANCE_ENV, None)
        provenance._WRITERS.clear()
    ref = run_simulation_config(
        DRILL_CONFIG, use_all_devices=False, engine_cache=ENGINE_CACHE
    )
    return SimpleNamespace(
        sup=sup, summary=summary, lineage_path=lineage_path,
        ref_row={**ref.to_dict(), "backend": "tpu"},
    )


def test_drill_grid_heals_every_failure_mode(drill):
    assert drill.summary["quarantined"] == []
    assert drill.summary["points_done"] == len(DRILL_PLANS)
    # Exactly one requeue per drilled point — and the documented reason each:
    # a SIGKILLed/ENOSPC'd worker dies (nonzero/-9 exit), the wedged one is
    # killed by the lease watchdog.
    reasons = {
        e["point"]: e["reason"]
        for e in events_of(drill.sup) if e["event"] == "requeue"
    }
    assert reasons == {
        "pt-kill-begin": "exit:-9",
        "pt-kill-pre": "exit:-9",
        "pt-kill-post": "exit:-9",
        "pt-hang": "lease_expired",
        "pt-enospc": "exit:1",
    }


def test_drill_rows_bit_equal_to_uninterrupted(drill):
    rows = rows_of(drill.sup)
    assert [r["point"] for r in rows] == list(DRILL_PLANS)
    for row in rows:
        got, want = dict(row), dict(drill.ref_row, point=row["point"])
        for d in (got, want):  # wall-clock attrs differ; statistics must not
            d.pop("elapsed_s", None)
            d.pop("compile_s", None)
        assert got == want, row["point"]


def test_drill_healing_workers_resume_from_durable_checkpoints(drill):
    # Which worker healed each point, from the done events.
    healer = {
        e["point"]: e["worker"]
        for e in events_of(drill.sup) if e["event"] == "done"
    }
    workers_dir = drill.sup.state_dir / "workers"

    def loads(point):
        return load_spans(workers_dir / f"{healer[point]}.tele.jsonl")

    # post_replace / the hang both died AFTER a durable 4-run checkpoint:
    # the healing worker must RESUME it, not redo the point.
    for point in ("pt-kill-post", "pt-hang"):
        ld = [s for s in loads(point) if s["span"] == "checkpoint_load"]
        assert len(ld) == 1 and ld[0]["attrs"]["runs_done"] == 4, point
    # begin / pre_replace / enospc died with NO durable checkpoint: the
    # healing worker restarts from zero (no checkpoint_load span)...
    for point in ("pt-kill-begin", "pt-kill-pre", "pt-enospc"):
        assert not any(s["span"] == "checkpoint_load" for s in loads(point)), point
    # ...and pre_replace's stale tmp file was swept with the warning.
    pre_log = (workers_dir / f"{healer['pt-kill-pre']}.log").read_text()
    assert "removing stale checkpoint temp file" in pre_log


def test_drill_lineage_ledger_survives_the_kills_whole(drill):
    # The lineage kill drill: five worker processes (two at a time) appended
    # to ONE fsync'd ledger while being SIGKILLed, wedged and ENOSPC'd —
    # every surviving record must be whole (strict load re-hashes each
    # record; a torn or interleaved line raises), and the file must end on
    # a newline: whole-or-absent, never torn.
    raw = drill.lineage_path.read_bytes()
    assert raw.endswith(b"\n")
    records = load_lineage(drill.lineage_path, strict=True)
    kinds = {r["kind"] for r in records}
    assert {"run", "fleet_row", "checkpoint", "checkpoint_load"} <= kinds
    # Every point published a row through the fleet_row seam.
    assert {r.get("point") for r in records if r["kind"] == "fleet_row"} == set(
        DRILL_PLANS
    )


def test_drill_healed_rows_chain_to_their_checkpoints(drill):
    # The heal lineage, walked by hand: a published row's content address
    # resolves to its fleet_row record, whose parent chain reaches the
    # checkpoint the replacement worker resumed from — while a point killed
    # BEFORE its first durable save restarts from zero, parentless.
    records = load_lineage(drill.lineage_path)
    by_addr: dict[str, dict] = {}
    for rec in records:
        for a in (rec.get("content_sha256"), rec.get("artifact_id")):
            if isinstance(a, str):
                by_addr.setdefault(a, rec)
    chains = {}
    for row in rows_of(drill.sup):
        addr = content_address(row)
        assert addr in by_addr, row["point"]  # row-lineage, by hand
        chains[row["point"]] = provenance._ancestor_kinds(addr, by_addr)
    for point in ("pt-kill-post", "pt-hang"):  # died AFTER a durable save
        assert {"checkpoint_load", "checkpoint"} <= chains[point], point
    for point in ("pt-kill-begin", "pt-kill-pre", "pt-enospc"):
        assert "checkpoint_load" not in chains[point], point


def test_drill_audit_gate_green_with_heal_facts_checked(drill):
    # `tpusim audit` over the drilled state dir: all invariants green, and
    # the fleet-specific ones actually CHECKED facts (a zero-checked
    # invariant would make this a dead gate for the fleet plane).
    scan = provenance.scan_artifacts([drill.sup.state_dir])
    violations, checked = provenance.run_audit(scan)
    assert violations == []
    assert checked["heal-parented"] >= 1
    assert checked["runs-consistent"] >= 1
    assert checked["checkpoint-fingerprint"] >= 1
    assert checked["row-lineage"] >= len(DRILL_PLANS)
    assert provenance.audit_main([str(drill.sup.state_dir), "--quiet"]) == 0


def test_drill_dashboards_render_fleet_panels(drill):
    spans = load_spans(drill.sup.recorder.path)
    report = render_report(spans)
    assert "Fleet (worker supervisor)" in report
    assert "lease_expired" in report  # the requeue table names the reason
    watch = render_watch(spans, "drill")
    assert "fleet:" in watch and "5/5 points" in watch


def test_drill_timeline_spans_one_correlated_tree(drill):
    # Trace-context propagation end to end: every worker ledger under
    # STATE_DIR/workers carries the supervisor's trace_id/run_id and the
    # parent_span naming its fleet_spawn — one span tree for the whole fleet.
    from tpusim.tracing import assemble, collect_spans

    spans = collect_spans([drill.sup.state_dir])
    trace = assemble(spans)
    assert trace is not None
    assert trace.trace_id == drill.sup.recorder.trace_id
    assert trace.run_id == drill.sup.recorder.run_id
    # One worker node per spawn (attempt-0 + its replacement for all 5
    # drilled points), and every ATTEMPT'S process correlated via its own
    # worker_start handshake.
    assert len(trace.workers) == drill.summary["workers_spawned"] == 10
    correlated = [w for w in trace.workers.values() if w.process is not None]
    assert len(correlated) == 10


def test_drill_timeline_attribution_and_critical_path(drill):
    from tpusim.tracing import assemble, attribution, collect_spans

    trace = assemble(collect_spans([drill.sup.state_dir]))
    att = attribution(trace)
    # The category seconds partition the supervisor-measured fleet window
    # exactly; the remainder is explicit.
    assert sum(att["categories"].values()) == pytest.approx(att["total_s"])
    assert att["coverage"] >= 0.5  # the wedged (pt-hang) worker's frozen
    # lease is honest dead time; the ci.sh kill-only drill gates >= 0.9
    # The requeue backoff windows sit on the timeline...
    assert any(iv.category == "backoff" for iv in trace.intervals)
    # ...and so does the healing evidence: the REPLACEMENT workers that
    # resumed a durable checkpoint show their checkpoint_load interval.
    healer = {
        e["point"]: e["worker"]
        for e in events_of(drill.sup) if e["event"] == "done"
    }
    load_workers = {
        iv.worker for iv in trace.intervals if iv.span == "checkpoint_load"
    }
    assert {healer["pt-kill-post"], healer["pt-hang"]} <= load_workers
    # Real compile/dispatch work was attributed, not lumped into spawn.
    cats = att["categories"]
    assert cats["spawn"] > 0 and cats["compile"] + cats["dispatch"] > 0


def test_drill_timeline_cli_and_perfetto_export(drill, tmp_path):
    from tpusim.tracing import timeline_main, validate_perfetto

    out = tmp_path / "orch.trace.json"
    rc = timeline_main([str(drill.sup.state_dir), "--out", str(out)])
    assert rc == 0
    exported = json.loads(out.read_text())
    assert validate_perfetto(exported) > 0
    names = [ev.get("name") for ev in exported["traceEvents"]]
    # One lease slice per worker attempt; the worker-side chaos faults of
    # the drill plans land as instants.
    assert sum(1 for x in names if str(x).startswith("lease ")) == 10
    assert any(str(x).startswith("chaos ") for x in names)


def test_drill_report_merged_state_dir_renders_attribution(drill):
    # `tpusim report STATE_DIR` merges supervisor + worker ledgers: the
    # fleet panel grows the attribution and per-worker utilization tables,
    # and the shared fleet run_id partitions by (run_id, process) instead of
    # blending ten workers' batch streams into one bogus panel.
    from tpusim.tracing import collect_spans

    spans = collect_spans([drill.sup.state_dir])
    report = render_report(spans)
    assert "Fleet time attribution (critical path)" in report
    assert "Per-worker utilization" in report
    assert "attributed" in report
    assert report.count("Throughput — run") >= 10
