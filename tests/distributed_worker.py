"""Worker for the real multi-process jax.distributed test.

Launched as a subprocess (NOT collected by pytest): one OS process per
controller, CPU platform with 4 virtual devices each, so a 2-process run
exercises the genuinely multi-controller paths — make_global_keys' shard
assembly over non-addressable devices and the cross-process psum — that the
in-process virtual-8-device tests cannot reach.

Usage: python distributed_worker.py <coordinator> <num_processes> <process_id>
Prints one line: RESULT=<json of per-miner sums + runs>.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()
# tpusim.probe.TUNNEL_TRIGGER_ENV, inlined: this standalone worker runs
# before tpusim is importable (the launcher only sets cwd, not PYTHONPATH).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def main() -> int:
    coordinator, num_processes, process_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    )
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    from tpusim.config import SimConfig, default_network
    from tpusim.distributed import initialize, run_simulation_distributed

    initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    import jax

    assert jax.process_count() == num_processes, jax.process_count()
    assert len(jax.devices()) == 4 * num_processes

    config = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=5 * 86_400_000,
        runs=32,
        batch_size=16,  # two sharded batches of 16 (2 runs per device)
        seed=9,
    )
    results = run_simulation_distributed(config)
    payload = {
        "process_id": process_id,
        "runs": results.runs,
        "blocks_found_mean": [m.blocks_found_mean for m in results.miners],
        "blocks_share_mean": [m.blocks_share_mean for m in results.miners],
        "stale_rate_mean": [m.stale_rate_mean for m in results.miners],
    }
    print("RESULT=" + json.dumps(payload), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
