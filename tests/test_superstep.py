"""Superstep (K events per device-loop iteration) and pipelined dispatch:
pure performance knobs, pinned here to be observationally invisible.

The per-event RNG word mapping is the sampling identity: event e of chunk c
consumes word pair e of that chunk's threefry block regardless of how many
events one scan step / kernel loop iteration unrolls. So every statistic must
be bit-identical across K — and across the device-loop, host-loop, pipelined
and async dispatch paths, which share one chunk program.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from tpusim.config import SimConfig, default_network, reference_selfish_network
from tpusim.engine import Engine, resolve_superstep
from tpusim.runner import make_run_keys


def _assert_sums_equal(a: dict, b: dict, msg: str) -> None:
    assert a.keys() == b.keys()
    for name in a:
        np.testing.assert_array_equal(
            np.asarray(a[name]), np.asarray(b[name]), err_msg=f"{msg}: {name}"
        )


FAST = SimConfig(
    network=default_network(propagation_ms=10_000),  # racy: arrivals matter
    duration_ms=4 * 86_400_000,
    runs=48,
    batch_size=48,
    chunk_steps=128,
    seed=23,
)
EXACT = dataclasses.replace(
    FAST, network=reference_selfish_network(), mode="exact", runs=24, batch_size=24
)


@pytest.mark.parametrize("config", [FAST, EXACT], ids=["fast", "exact-selfish"])
@pytest.mark.parametrize("k", [2, 8])
def test_superstep_bit_exact_vs_k1(config, k):
    keys = make_run_keys(config.seed, 0, config.runs)
    base = Engine(dataclasses.replace(config, superstep=1)).run_batch(keys)
    out = Engine(dataclasses.replace(config, superstep=k)).run_batch(keys)
    _assert_sums_equal(base, out, f"K={k}")


def test_superstep_bit_exact_xoroshiro():
    config = dataclasses.replace(FAST, rng="xoroshiro", runs=16, batch_size=16)
    e1 = Engine(dataclasses.replace(config, superstep=1))
    e4 = Engine(dataclasses.replace(config, superstep=4))
    keys = e1.make_keys(0, 16)
    _assert_sums_equal(e1.run_batch(keys), e4.run_batch(keys), "xoroshiro K=4")


def test_pallas_superstep_matches_scan_k1():
    """The kernel's event unroll consumes bits row e for event e exactly like
    sb-granular stepping: a K>1 Pallas run must match the K=1 scan engine bit
    for bit (interpret mode; the draws are identical by construction)."""
    from tpusim.pallas_engine import PallasEngine

    config = dataclasses.replace(
        EXACT, runs=128, batch_size=128, duration_ms=2 * 86_400_000
    )
    keys = make_run_keys(config.seed, 0, config.runs)
    scan_sums = Engine(dataclasses.replace(config, superstep=1)).run_batch(keys)
    pallas = PallasEngine(
        dataclasses.replace(config, superstep=4),
        tile_runs=128, step_block=32, interpret=True,
    )
    assert pallas.superstep == 4
    _assert_sums_equal(scan_sums, pallas.run_batch(keys), "pallas K=4")


def test_dispatch_paths_bit_identical():
    """device loop == pipelined chunk dispatch == legacy host loop == async
    batch dispatch, on the same keys."""
    engine = Engine(FAST)
    keys = make_run_keys(FAST.seed, 0, FAST.runs)
    device = engine.run_batch(keys)
    _assert_sums_equal(device, engine.run_batch(keys, pipelined=True), "pipelined")
    _assert_sums_equal(device, engine.run_batch(keys, host_loop=True), "host loop")
    _assert_sums_equal(device, engine.run_batch_async(keys)(), "async")


def test_resolve_superstep_rules():
    # Explicit K must divide the step budget exactly.
    assert resolve_superstep(4, 128) == 4
    with pytest.raises(ValueError, match="superstep"):
        resolve_superstep(3, 128)
    # Auto comes from the measured per-platform table and halves down to a
    # divisor; any 64-aligned budget takes the table value unreduced.
    from tpusim.engine import AUTO_SUPERSTEP_TABLE, auto_superstep

    assert resolve_superstep(None, 192) == auto_superstep(exact=False)
    assert resolve_superstep(None, 192, exact=True) == auto_superstep(exact=True)
    assert resolve_superstep(None, 4) in (1, 2, 4)
    assert 4 % resolve_superstep(None, 4) == 0
    assert resolve_superstep(None, 1) == 1
    # The table is the documented re-tune surface: every entry is a power of
    # two (so halving always terminates at a divisor) for a known platform.
    for (platform, kind), k in AUTO_SUPERSTEP_TABLE.items():
        assert platform in ("cpu", "tpu", "gpu") and kind in ("fast", "exact")
        assert k >= 1 and (k & (k - 1)) == 0


def test_superstep_serializes_and_stays_out_of_fingerprint(tmp_path):
    cfg = dataclasses.replace(FAST, superstep=4)
    assert SimConfig.from_json(cfg.to_json()).superstep == 4
    # Checkpoints written at one K must resume at another: the fingerprint
    # excludes K (runner pops it), so a K=1 checkpoint continues under K=8
    # with bit-identical statistics.
    from tpusim.runner import run_simulation_config

    ckpt = tmp_path / "ck.npz"
    small = dataclasses.replace(
        FAST, runs=16, batch_size=8, superstep=1, duration_ms=86_400_000
    )
    partial = dataclasses.replace(small, runs=8)
    run_simulation_config(partial, checkpoint_path=ckpt)
    resumed = run_simulation_config(
        dataclasses.replace(small, superstep=8), checkpoint_path=ckpt
    )
    direct = run_simulation_config(small)
    for mr, md in zip(resumed.miners, direct.miners):
        assert mr.blocks_found_mean == md.blocks_found_mean
        assert mr.stale_rate_mean == md.stale_rate_mean
