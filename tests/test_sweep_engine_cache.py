"""Engine sharing across same-shape sweep points (the ROADMAP follow-up on
sweep.py's per-point ``get_backend("tpu")(config)`` rebuilds): a grid that
varies only runtime inputs — roster percentages, seed — must compile once,
and the rebind must actually apply the new point's parameters."""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from tpusim.config import SimConfig, default_network
from tpusim.engine import Engine
from tpusim.runner import make_engine, run_simulation_config
from tpusim.sweep import _selfish_network, run_sweep
from tpusim.testing import compile_count_guard


def _cfg(pct: int) -> SimConfig:
    return SimConfig(
        network=_selfish_network(pct), duration_ms=86_400_000, runs=8, batch_size=8
    )


def test_same_shape_points_share_one_engine_zero_recompiles():
    cache: dict = {}
    a = run_simulation_config(_cfg(25), use_all_devices=False, engine_cache=cache)
    assert len(cache) == 1
    # Point two differs only in roster percentages — runtime inputs of the
    # jitted programs — so the warmed engine serves it without ANY compile.
    with compile_count_guard(exact=0):
        b = run_simulation_config(_cfg(40), use_all_devices=False, engine_cache=cache)
    assert len(cache) == 1
    # The rebind applied the new params: miner 0's share tracks its hashrate.
    assert b.miners[0].blocks_share_mean > a.miners[0].blocks_share_mean


def test_shape_change_gets_its_own_cache_entry():
    cache: dict = {}
    make_engine(_cfg(25), cache=cache)
    # Different duration -> different chunk budget -> different program.
    make_engine(dataclasses.replace(_cfg(25), duration_ms=2 * 86_400_000), cache=cache)
    # Different miner count -> different shapes.
    make_engine(
        SimConfig(network=default_network(), duration_ms=86_400_000, runs=8),
        cache=cache,
    )
    assert len(cache) == 3


def test_rebind_refuses_cross_shape():
    eng = make_engine(_cfg(25))
    other = Engine(SimConfig(network=default_network(), duration_ms=86_400_000, runs=8))
    with pytest.raises(ValueError, match="rebind across engine shapes"):
        eng.rebind(other.config, other.reuse_key())


def test_run_sweep_uses_shared_cache(tmp_path):
    """The sweep driver wires the cache through get_backend: an externally
    provided cache comes back holding the one shared engine, and both
    points' rows land with their own statistics."""
    cache: dict = {}
    points = [("s25", _cfg(25)), ("s40", _cfg(40))]
    rows = run_sweep(
        points, out_path=tmp_path / "out.jsonl", quiet=True, engine_cache=cache
    )
    assert len(cache) == 1
    assert [r["point"] for r in rows] == ["s25", "s40"]
    share = {r["point"]: r["miners"][0]["blocks_share_mean"] for r in rows}
    assert share["s40"] > share["s25"]


def test_pallas_reuse_key_bakes_roster():
    """The kernel captures thresholds/propagation/selfish as constants, so
    pallas engines must NOT be shared across rosters — their keys differ
    where the scan engines' agree."""
    from tpusim.pallas_engine import PallasEngine

    kw = dict(tile_runs=128, step_block=32, interpret=True)
    cfg25 = dataclasses.replace(_cfg(25), mode="exact", chunk_steps=64)
    cfg40 = dataclasses.replace(_cfg(40), mode="exact", chunk_steps=64)
    assert Engine(cfg25).reuse_key() == Engine(cfg40).reuse_key()
    k25 = PallasEngine(cfg25, **kw).reuse_key()
    k40 = PallasEngine(cfg40, **kw).reuse_key()
    assert k25 != k40
    assert k25 == PallasEngine(cfg25, **kw).reuse_key()
