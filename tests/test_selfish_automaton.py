"""Port of the reference's gamma=0 selfish-strategy state-machine suite.

Every case of ``TestSelfishStrategy`` (reference test.cpp:210-367) — the 2013
paper's section 4.2 states a, b, d-h plus the reference's two extra scenarios —
is reproduced as an exact-state test of the vectorized automaton: the initial
chains are converted to automaton state, one FoundBlock/NotifyBestChain event
is applied through the real kernels, and the result is asserted equal to the
expected chains, block for block (case c is unreachable at gamma=0,
test.cpp:249-250).

Miner 0 is the selfish miner (35% hashrate, 100ms propagation, matching
test.cpp:216-217); miner 1 stands for the rest of the network.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpusim.config import MinerConfig, NetworkConfig, SimConfig
from tpusim.state import I32, I64, found_block, make_params, notify
from tpusim.testing import assert_state_matches_chains, state_from_chains

S = 0  # selfish miner id
O = 1  # "others" id
PROP = 100  # SM_PROP_TIME, test.cpp:216
SEC = 1000


def sec(x: float) -> int:
    return int(x * SEC)


@pytest.fixture
def config() -> SimConfig:
    return SimConfig(
        network=NetworkConfig(
            miners=(
                MinerConfig(hashrate_pct=35, propagation_ms=PROP, selfish=True),
                MinerConfig(hashrate_pct=65, propagation_ms=1000),
            )
        ),
        duration_ms=10_000_000,
        runs=1,
        mode="exact",
    )


def apply_found(config, chains, t, best_len_with_genesis, winner=S):
    """FoundBlock on the automaton; best_len_with_genesis mirrors the
    reference's chain.size()-convention argument (test.cpp:226,232,242)."""
    state = state_from_chains(chains, t, config, best_height_prev=best_len_with_genesis - 1)
    state = state._replace(t=jnp.asarray(t, I64))
    return found_block(state, make_params(config), jnp.asarray(winner, I32))


def apply_notify(config, chains, t):
    state = state_from_chains(chains, t, config)
    state = state._replace(t=jnp.asarray(t, I64))
    return notify(state, make_params(config))


def test_case_a_pool_finds_block_extends_private_branch(config):
    """test.cpp:219-235: any state but a 1-block race — appending stays private."""
    sm = [(O, sec(600)), (S, sec(1200))]
    others = [(O, sec(600)), (S, sec(1200))]

    # Private fork of 0 blocks: pool appends one private block.
    state = apply_found(config, [sm, others], sec(1800), best_len_with_genesis=3)
    sm_after = sm + [(S, None)]
    assert_state_matches_chains(state, [sm_after, others], sec(1800), config)

    # Private chain of 1 block: the lead grows by one more private block.
    state = apply_found(config, [sm_after, others], sec(2400), best_len_with_genesis=3)
    assert_state_matches_chains(state, [sm_after + [(S, None)], others], sec(2400), config)


def test_case_b_one_block_race_pool_wins_publishes_both(config):
    """test.cpp:237-247: two branches of length 1, pool finds a block —
    it publishes its secret branch of length two."""
    sm = [(O, sec(600)), (S, sec(1200)), (O, sec(1800)), (S, None)]
    others = [(O, sec(600)), (S, sec(1200)), (O, sec(1800)), (O, sec(2400))]
    state = apply_found(config, [sm, others], sec(3600), best_len_with_genesis=5)
    sm_after = [
        (O, sec(600)),
        (S, sec(1200)),
        (O, sec(1800)),
        (S, sec(3600) + PROP),
        (S, sec(3600) + PROP),
    ]
    assert_state_matches_chains(state, [sm_after, others], sec(3600), config)
    assert int(state.n_private[S]) == 0


def test_case_d_race_others_extend_their_head(config):
    """test.cpp:252-260: others find a block on their own head during the race;
    the pool switches to the longer chain, its private block goes stale."""
    sm = [(O, sec(600)), (S, sec(1200)), (O, sec(1800)), (S, None)]
    best = [(O, sec(600)), (S, sec(1200)), (O, sec(1800)), (O, sec(2400)), (O, sec(3000))]
    state = apply_notify(config, [sm, best], sec(3000))
    assert_state_matches_chains(state, [best, best], sec(3000), config)
    assert np.asarray(state.stale).tolist() == [1, 0]


def test_case_e_no_private_branch_others_find_block(config):
    """test.cpp:262-271: nothing private; the pool simply adopts, no stale."""
    sm = [(O, sec(600)), (S, sec(1200)), (O, sec(1800)), (S, sec(2400))]
    best = sm + [(O, sec(3000))]
    state = apply_notify(config, [sm, best], sec(3000))
    assert_state_matches_chains(state, [best, best], sec(3000), config)
    assert np.asarray(state.stale).tolist() == [0, 0]


def test_case_f_lead_was_1_others_catch_up_reveal_single(config):
    """test.cpp:273-283: lead 1 and others catch up — the pool publishes its
    single secret block and keeps mining on it."""
    sm = [(O, sec(600)), (S, sec(1200)), (S, None)]
    others = [(O, sec(600)), (S, sec(1200)), (O, sec(1800))]
    state = apply_notify(config, [sm, others], sec(1800))
    sm_after = [(O, sec(600)), (S, sec(1200)), (S, sec(1800) + PROP)]
    assert_state_matches_chains(state, [sm_after, others], sec(1800), config)
    assert np.asarray(state.stale).tolist() == [0, 0]


def test_case_g_lead_was_2_reveal_all(config):
    """test.cpp:285-296: lead drops to 1 — the pool reveals everything to
    avoid a race."""
    sm = [(O, sec(600)), (S, sec(1200)), (S, None), (S, None)]
    others = [(O, sec(600)), (S, sec(1200)), (O, sec(1800))]
    state = apply_notify(config, [sm, others], sec(1800))
    sm_after = [(O, sec(600)), (S, sec(1200)), (S, sec(1800) + PROP), (S, sec(1800) + PROP)]
    assert_state_matches_chains(state, [sm_after, others], sec(1800), config)


def test_case_h_lead_over_2_reveal_oldest(config):
    """test.cpp:298-314: lead stays >= 2 — reveal only the oldest block."""
    sm = [(O, sec(600)), (S, sec(1200)), (S, None), (S, None), (S, None)]
    others = [(O, sec(600)), (S, sec(1200)), (O, sec(1800))]
    state = apply_notify(config, [sm, others], sec(1800))
    sm_after = [(O, sec(600)), (S, sec(1200)), (S, sec(1800) + PROP), (S, None), (S, None)]
    assert_state_matches_chains(state, [sm_after, others], sec(1800), config)


def test_case_h_long_fork_reveal_oldest(config):
    """test.cpp:316-330: 5-block private fork, best 4 — reveal one."""
    sm = [(O, sec(600)), (S, sec(1200)), (O, sec(1800))] + [(S, None)] * 5
    others = [(O, sec(600)), (S, sec(1200)), (O, sec(1800)), (O, sec(2400))]
    state = apply_notify(config, [sm, others], sec(2400))
    sm_after = (
        [(O, sec(600)), (S, sec(1200)), (O, sec(1800)), (S, sec(2400) + PROP)]
        + [(S, None)] * 4
    )
    assert_state_matches_chains(state, [sm_after, others], sec(2400), config)


def test_extra_case_two_blocks_in_a_row_reveal_two(config):
    """test.cpp:332-350 (absent from the paper): others found two blocks in a
    row — the pool reveals two of its oldest private blocks."""
    sm = [(O, sec(600)), (S, sec(1200)), (O, sec(1800))] + [(S, None)] * 5
    others = [
        (O, sec(600)),
        (S, sec(1200)),
        (O, sec(1800)),
        (O, sec(2400)),
        (O, sec(3000)),
    ]
    state = apply_notify(config, [sm, others], sec(3000))
    sm_after = (
        [
            (O, sec(600)),
            (S, sec(1200)),
            (O, sec(1800)),
            (S, sec(3000) + PROP),
            (S, sec(3000) + PROP),
        ]
        + [(S, None)] * 3
    )
    assert_state_matches_chains(state, [sm_after, others], sec(3000), config)


def test_extra_case_lead_1_others_find_two_switch(config):
    """test.cpp:352-364 (absent from the paper): lead 1, others find two in a
    row — the pool switches to the longer public chain."""
    sm = [(O, sec(600)), (S, sec(1200)), (O, sec(1800)), (S, None)]
    best = [
        (O, sec(600)),
        (S, sec(1200)),
        (O, sec(1800)),
        (O, sec(2400)),
        (O, sec(3000)),
    ]
    state = apply_notify(config, [sm, best], sec(3000))
    assert_state_matches_chains(state, [best, best], sec(3000), config)
    assert np.asarray(state.stale).tolist() == [1, 0]
