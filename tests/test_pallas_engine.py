"""PallasEngine vs scan Engine: bit-identical results on shared draws.

The Pallas kernel consumes the exact same threefry bits with the exact same
step->draw mapping as the scan engine, so on any supported config — honest
fast mode, exact mode, and exact mode with gamma=0 selfish miners — the
two must produce *identical* statistic sums, not statistically close ones.
Run in interpret mode on CPU (the kernel logic is pure JAX; TPU lowering is
exercised on hardware by bench.py's engine selection)."""

from __future__ import annotations

import numpy as np
import pytest

from tpusim.config import (
    MinerConfig, NetworkConfig, SimConfig, default_network, reference_selfish_network,
)
from tpusim.engine import Engine
from tpusim.pallas_engine import PallasEngine
from tpusim.runner import make_run_keys

HETERO = NetworkConfig(
    miners=(
        MinerConfig(hashrate_pct=40, propagation_ms=5000),
        MinerConfig(hashrate_pct=30, propagation_ms=100),
        MinerConfig(hashrate_pct=20, propagation_ms=1500),
        MinerConfig(hashrate_pct=10, propagation_ms=0),
    ),
    block_interval_s=20.0,
)


SELFISH40 = reference_selfish_network()


@pytest.mark.parametrize(
    "network,duration_ms,chunk_steps,mode,group_slots",
    [
        (default_network(propagation_ms=10_000), 4 * 86_400_000, 128, "fast", None),  # chunked, racy
        (HETERO, 1_200_000, 64, "fast", None),  # heterogeneous + 0 ms propagation edge
        # Explicit K=4 exact rows: the generic K-slot group machinery
        # (one-hot push/flush/compact and the generic reveal push), which
        # the K=2 auto default otherwise routes around — still reachable
        # via group_slots=4 (the pre-round-5 resolved configs).
        (default_network(propagation_ms=10_000), 2 * 86_400_000, 64, "exact", 4),
        (SELFISH40, 4 * 86_400_000, 128, "exact", 4),
        # Non-default K=4 fast: same generic machinery in the fast kernel.
        (default_network(propagation_ms=10_000), 2 * 86_400_000, 64, "fast", 4),
        # Auto (K=2) exact: the split-slot specialization incl. the
        # split-slot reveal push — what production selfish/10s sweeps run.
        (SELFISH40, 4 * 86_400_000, 128, "exact", None),
        (default_network(propagation_ms=10_000), 2 * 86_400_000, 64, "exact", None),
    ],
)
def test_pallas_matches_scan_engine_exactly(network, duration_ms, chunk_steps, mode, group_slots):
    # 160 runs with tile_runs=128: the aligned prefix takes the kernel, the
    # 32-run remainder takes the scan twin — both paths must agree with the
    # scan engine bit for bit.
    config = SimConfig(
        network=network,
        duration_ms=duration_ms,
        runs=160,
        batch_size=160,
        mode=mode,
        chunk_steps=chunk_steps,
        group_slots=group_slots,
        seed=23,
    )
    keys = make_run_keys(config.seed, 0, config.runs)
    scan_sums = Engine(config).run_batch(keys)
    pallas = PallasEngine(config, tile_runs=128, step_block=32, interpret=True)
    assert pallas.chunk_steps == chunk_steps, "alignment must not change the draw identity"
    pallas_sums = pallas.run_batch(keys)

    assert scan_sums.keys() == pallas_sums.keys()
    for name in scan_sums:
        a, b = np.asarray(scan_sums[name]), np.asarray(pallas_sums[name])
        if a.dtype.kind == "f":
            # Per-run values are bit-identical; the head+tail split sums them
            # in a different order, which can move float32 sums by 1 ulp.
            np.testing.assert_allclose(a, b, rtol=2e-7, err_msg=name)
        else:
            np.testing.assert_array_equal(a, b, err_msg=name)


def test_pallas_refuses_fast_selfish_and_multicontroller_mesh(monkeypatch):
    fast_selfish = SimConfig(
        network=SELFISH40,
        runs=128,
        mode="fast",  # the selfish approximation stays on the scan engine
    )
    with pytest.raises(ValueError):
        PallasEngine(fast_selfish)
    # Single-controller meshes are supported; multi-controller ones are not
    # (per-run leaves cannot be gathered across controllers, and the CPU
    # multi-process path has no TPU kernel to run anyway).
    import jax
    from jax.sharding import Mesh

    honest = SimConfig(network=default_network(), runs=128)
    mesh = Mesh(np.array(jax.devices()), ("runs",))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="multi-controller"):
        PallasEngine(honest, mesh=mesh)


def test_pallas_mesh_shards_kernel_and_matches_single_device():
    """A single-controller mesh runs the kernel per device on its local run
    shard (the whole device-resident batch loop is shard-mapped); the result
    must be bit-identical to the single-device scan engine — integer sums via
    exact int psums, ratio means via the gathered per-run float64 host sum."""
    import jax
    from jax.sharding import Mesh

    config = SimConfig(
        network=SELFISH40,
        duration_ms=6_000_000,
        runs=1024,  # 8 devices x one 128-run tile each
        batch_size=1024,
        mode="exact",
        chunk_steps=64,
        seed=11,
    )
    keys = make_run_keys(config.seed, 0, config.runs)
    mesh = Mesh(np.array(jax.devices()), ("runs",))
    pallas_mesh = PallasEngine(config, mesh, tile_runs=128, interpret=True)
    out_mesh = pallas_mesh.run_batch(keys)
    out_single = Engine(config, None).run_batch(keys)
    assert out_mesh.keys() == out_single.keys()
    for name in out_single:
        np.testing.assert_array_equal(
            np.asarray(out_mesh[name]), np.asarray(out_single[name]), err_msg=name
        )


def test_pallas_refuses_oversized_vmem_config():
    """A 32-miner exact config's cp block cannot fit scoped VMEM at any tile;
    the guard must reject it in __init__ (before Mosaic can hang on it) so
    make_engine falls back to the scan engine — except under interpret=True,
    the no-VMEM-limit debug path."""
    from tpusim.sweep import _hetero32_network

    big = SimConfig(network=_hetero32_network(), runs=128, duration_ms=600_000)
    assert big.resolved_mode == "exact"
    with pytest.raises(ValueError, match="VMEM"):
        PallasEngine(big, tile_runs=128)
    PallasEngine(big, tile_runs=128, interpret=True)  # debug path still builds
    # The bring-up escape hatch builds too (the real compiler then judges).
    PallasEngine(big, tile_runs=128, vmem_guard=False)


def test_scan_twin_shares_resolved_chunk_steps_with_auto_sizing():
    """With chunk_steps=None and a short duration, the auto path 64-aligns the
    resolved value possibly above the raw event bound; the scan twin pins that
    value explicitly, and Engine's explicit-path clamp must resolve it to the
    same number — otherwise the twin samples with a different step->key
    identity than the kernel (and than the checkpoint fingerprint)."""
    config = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=86_400_000,  # 1 day: raw bound ~496, aligned 512
        runs=128,
        batch_size=128,
        mode="fast",
        seed=5,
    )
    pallas = PallasEngine(config, tile_runs=128, step_block=64, interpret=True)
    twin = pallas.scan_twin()
    assert pallas.chunk_steps % 64 == 0
    assert twin.chunk_steps == pallas.chunk_steps
    # And a directly-built Engine with the same explicit value agrees too.
    import dataclasses

    direct = Engine(dataclasses.replace(config, chunk_steps=pallas.chunk_steps))
    assert direct.chunk_steps == pallas.chunk_steps
