"""tpusim lint: every rule catches its seeded violation and passes the clean
twin; suppression comments and the baseline round-trip behave; a fresh JX003
use-after-donation introduced into the REAL engine.py source fails the gate
(the CI-leg contract); and compile_count_guard pins one-compile-per-shape on
Engine.run_batch (the runtime half of JX006).
"""

from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from tpusim.lint import Baseline, Finding, LintConfig, lint_source
from tpusim.lint.cli import main as lint_main

REPO = Path(__file__).resolve().parent.parent

#: Fixture config: fixture paths double as the project's special module sets.
CFG = LintConfig(
    hot_modules=("hot.py",),
    device_modules=("device.py",),
    unused_globs=("scripts/*.py",),
    measurement_modules=("bench_like.py",),
)


def rules_of(findings: list[Finding]) -> set[str]:
    return {f.rule for f in findings}


def lint(src: str, path: str = "mod.py", rules=None) -> list[Finding]:
    return lint_source(textwrap.dedent(src), path, config=CFG, rules=rules)


# ---------------------------------------------------------------------------
# JX001 — tracer branch.

_JX001_BAD = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        if x > 0:
            return x + 1
        return x
"""

_JX001_CLEAN = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return jnp.where(x > 0, x + 1, x)
"""


def test_jx001_seeded_and_clean():
    assert rules_of(lint(_JX001_BAD)) == {"JX001"}
    assert lint(_JX001_CLEAN) == []


def test_jx001_static_annotations_and_shape_reads_are_exempt():
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, exact: bool):
            if exact:                  # static-by-convention Python bool
                x = x * 2
            if x.shape[0] > 4:         # shape metadata is static
                x = x + 1
            if x is not None:          # trace-time None check
                x = x - 1
            while x.ndim > 2:
                x = x.sum(0)
            return x
    """
    assert lint(src) == []


def test_jx001_reaches_scan_bodies_transitively():
    src = """
        import jax

        def outer(carry, xs):
            return helper(carry, xs), None

        def helper(c, x):
            if c:                      # tracer: helper is scan-reachable
                return c
            return x

        def run(init, xs):
            return jax.lax.scan(outer, init, xs)
    """
    found = lint(src)
    assert rules_of(found) == {"JX001"}
    assert all("helper" in f.message for f in found)


# ---------------------------------------------------------------------------
# JX002 — implicit host sync in hot loops.

_JX002_BAD = """
    import numpy as np

    class Driver:
        def run(self, keys):
            flags = []
            for i in range(8):
                state, flag = self._pipe_chunk(keys, i)
                flags.append(flag)
                if int(flags.pop(0)) == 0:
                    break
            for s in state:
                rows = np.asarray(s)
            return rows
"""

_JX002_CLEAN = """
    import numpy as np

    class Driver:
        def run(self, keys):
            flags = []
            for i in range(8):
                state, flag = self._pipe_chunk(keys, i)
                flags.append(flag)
            done = np.asarray(flags)  # ONE batch-end transfer, after the loop
            return state, done
"""


def test_jx002_seeded_and_clean():
    found = lint(_JX002_BAD, path="hot.py")
    assert rules_of(found) == {"JX002"}
    assert len(found) == 2  # the int() flag fetch and the in-loop asarray
    # The batch-end transfer comprehension outside the dispatch loop is not
    # a per-iteration sync — but comprehensions that ARE the loop still
    # count, so the clean twin moves the fetch after the loop entirely.
    assert lint(_JX002_CLEAN, path="hot.py") == []


def test_jx002_only_applies_to_hot_modules():
    assert lint(_JX002_BAD, path="cold.py") == []


def test_jx002_block_until_ready_flagged_anywhere_in_hot_module():
    src = """
        def warmup(engine, keys):
            out = engine.run_batch_async(keys)()
            out.block_until_ready()
    """
    assert rules_of(lint(src, path="hot.py")) == {"JX002"}


# ---------------------------------------------------------------------------
# JX003 — use-after-donation.

_JX003_BAD = """
    import jax

    step = jax.jit(_step_impl, donate_argnums=(0, 1))

    def drive(state, aux, keys):
        out_state, out_aux = step(state, aux)
        return state.t, out_state       # `state` was donated above
"""

_JX003_CLEAN = """
    import jax

    step = jax.jit(_step_impl, donate_argnums=(0, 1))

    def drive(state, aux, keys):
        state, aux = step(state, aux)   # donated names rebound by the call
        return state.t, aux
"""


def test_jx003_seeded_and_clean():
    found = lint(_JX003_BAD)
    assert rules_of(found) == {"JX003"}
    assert "donated" in found[0].message and "state" in found[0].message
    assert lint(_JX003_CLEAN) == []


def test_jx003_partial_jit_decorator_form():
    bad = """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(buf, x):
            return buf + x

        def drive(buf, x):
            out = step(buf, x)
            return buf, out             # `buf` was donated to step
    """
    clean = """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(buf, x):
            return buf + x

        def drive(buf, x):
            buf = step(buf, x)
            return buf
    """
    assert rules_of(lint(bad)) == {"JX003"}
    assert lint(clean) == []


def test_jx003_reads_in_opposite_if_arm_are_not_flagged():
    clean = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(buf, keys, fast: bool):
            if fast:
                out = step(buf, keys)
                return out
            else:
                return buf.copy()       # step never ran on this path
    """
    assert lint(clean) == []


def test_jx003_multiline_call_args_and_nested_closures():
    # A black-formatted multi-line donating call: its own argument reads on
    # continuation lines are the donation itself, not a use-after.
    clean = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(state, keys):
            out = step(
                state,
                keys,
            )
            return out
    """
    assert lint(clean) == []
    # A same-named local in a nested closure is a different binding and must
    # not mask the real use-after-donation in the outer scope.
    bad = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(state, keys):
            out = step(state, keys)

            def helper():
                state = make()
                return state

            return state.t, out, helper
    """
    assert rules_of(lint(bad)) == {"JX003"}


def test_module_scope_is_scanned():
    # JX002 at script top level (hot module): the exact host-sync pattern,
    # just not wrapped in a def.
    bad = """
        import numpy as np

        flags = []
        for i in range(8):
            state, flag = engine._pipe_chunk(keys, i)
            flags.append(flag)
            done = int(flags.pop(0))
    """
    assert rules_of(lint(bad, path="hot.py")) == {"JX002"}
    # JX004 at module scope.
    bad_key = """
        import jax

        a = jax.random.normal(key, (4,))
        b = jax.random.uniform(key, (4,))
    """
    assert rules_of(lint(bad_key)) == {"JX004"}


def test_suppression_covers_multiline_statement():
    src = """
        import jax

        @jax.jit
        def step(x, lo):
            # tpusim-lint: disable=JX001 -- covers the whole statement below
            if (
                x > lo
            ):
                return x + 1
            return x
    """
    assert lint(src) == []


def test_jx003_attribute_assigned_jit_with_int_donate():
    src = """
        import jax

        class Eng:
            def __init__(self):
                self._go = jax.jit(self._impl, donate_argnums=0)

            def run(self, buf, keys):
                out = self._go(buf, keys)
                return buf + out
    """
    found = lint(src)
    assert rules_of(found) == {"JX003"}


# ---------------------------------------------------------------------------
# JX004 — PRNG state reuse.

_JX004_BAD = """
    import jax

    def draw(key):
        a = jax.random.normal(key, (4,))
        b = jax.random.uniform(key, (4,))
        return a, b
"""

_JX004_CLEAN = """
    import jax

    def draw(key):
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, (4,))
        b = jax.random.uniform(k2, (4,))
        return a, b
"""


def test_jx004_seeded_and_clean():
    found = lint(_JX004_BAD)
    assert rules_of(found) == {"JX004"}
    assert lint(_JX004_CLEAN) == []


def test_jx004_loop_reuse_and_per_iteration_split():
    bad = """
        import jax

        def draw(key, n):
            out = []
            for i in range(n):
                out.append(jax.random.bits(key, (2,)))
            return out
    """
    clean = """
        import jax

        def draw(key, n):
            out = []
            for i in range(n):
                key, sub = jax.random.split(key)
                out.append(jax.random.bits(sub, (2,)))
            return out
    """
    assert rules_of(lint(bad)) == {"JX004"}
    assert lint(clean) == []


def test_jx004_if_else_arms_are_not_reuse():
    clean = """
        import jax

        def draw(key, cond: bool):
            if cond:
                return jax.random.uniform(key, (4,))
            else:
                return jax.random.normal(key, (4,))
    """
    assert lint(clean) == []
    # ...but a consumption AFTER the if/else still conflicts with both arms.
    bad = """
        import jax

        def draw(key, cond: bool):
            if cond:
                a = jax.random.uniform(key, (4,))
            else:
                a = jax.random.normal(key, (4,))
            return a + jax.random.bits(key, (4,))
    """
    assert rules_of(lint(bad)) == {"JX004"}


def test_jx004_sibling_nested_functions_do_not_conflate():
    clean = """
        import jax

        def make(key):
            def one():
                return jax.random.uniform(key, (2,))

            def two(key):
                return jax.random.normal(key, (2,))

            return one, two
    """
    assert lint(clean) == []


def test_jx004_xoroshiro_consumer_from_config():
    bad = """
        def step(xi):
            s1, hi, lo = next_words(xi)
            s2, h2, l2 = next_words(xi)   # same stream consumed twice
            return hi, h2
    """
    clean = """
        def step(xi):
            xi, hi, lo = next_words(xi)
            xi, h2, l2 = next_words(xi)
            return hi, h2
    """
    assert rules_of(lint(bad)) == {"JX004"}
    assert lint(clean) == []


# ---------------------------------------------------------------------------
# JX005 — dtype drift.

_JX005_BAD = """
    import jax
    import numpy as np
    import jax.numpy as jnp

    @jax.jit
    def scale(x):
        return x * np.float64(2.0)
"""

_JX005_CLEAN = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def scale(x):
        return x * jnp.float32(2.0)
"""


def test_jx005_seeded_and_clean():
    found = lint(_JX005_BAD)
    assert rules_of(found) == {"JX005"}
    assert lint(_JX005_CLEAN) == []


def test_jx005_builtin_dtype_and_bare_float_literal():
    bad = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def make(x):
            a = jnp.zeros(4, dtype=float)
            b = jnp.asarray(0.5)
            return a, b, x
    """
    clean = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def make(x):
            a = jnp.zeros(4, dtype=jnp.float32)
            b = jnp.asarray(0.5, jnp.float32)
            return a, b, x
    """
    found = lint(bad)
    assert rules_of(found) == {"JX005"} and len(found) == 2
    assert lint(clean) == []


# ---------------------------------------------------------------------------
# JX006 — recompilation risk.

_JX006_BAD = """
    import jax

    chunk = jax.jit(_chunk_impl)

    def run(state, n):
        for i in range(n):
            state = chunk(state, i)
        return state
"""

_JX006_CLEAN = """
    import jax
    import jax.numpy as jnp

    chunk = jax.jit(_chunk_impl)

    def run(state, n):
        for i in range(n):
            state = chunk(state, jnp.asarray(i, jnp.uint32))
        return state
"""


def test_jx006_seeded_and_clean():
    found = lint(_JX006_BAD)
    assert rules_of(found) == {"JX006"}
    assert "loop variable" in found[0].message
    assert lint(_JX006_CLEAN) == []


def test_jx006_bare_jit_decorator_is_registered():
    bad = """
        import jax

        @jax.jit
        def step(state, i):
            return state

        def run(state, n):
            for i in range(n):
                state = step(state, i)
            return state
    """
    assert rules_of(lint(bad)) == {"JX006"}


def test_jx003_next_iteration_read_of_donated_buffer_in_loop():
    bad = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(state, n):
            for i in range(n):
                probe = state.sum()      # iteration 2 reads a donated buffer
                out = step(state, probe)
            return out
    """
    clean = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(state, n):
            for i in range(n):
                probe = state.sum()
                state = step(state, probe)   # rebound every iteration
            return state
    """
    found = lint(bad)
    assert rules_of(found) == {"JX003"}
    assert any("next iteration" in f.message for f in found)
    assert lint(clean) == []


def test_jx006_scalar_literal_in_loop():
    bad = """
        import jax

        step = jax.jit(_impl)

        def run(state):
            while state is not None:
                state = step(state, 0.5)
            return state
    """
    assert rules_of(lint(bad)) == {"JX006"}


# ---------------------------------------------------------------------------
# JX007 — nondeterministic host calls in device modules.

_JX007_BAD = """
    import time

    def step(state):
        t0 = time.perf_counter()
        return state, t0
"""

_JX007_CLEAN = """
    def step(state, now):
        return state, now
"""


def test_jx007_seeded_and_clean():
    found = lint(_JX007_BAD, path="device.py")
    assert rules_of(found) == {"JX007"}
    assert lint(_JX007_CLEAN, path="device.py") == []
    # Host orchestration modules may use time freely.
    assert lint(_JX007_BAD, path="runner_like.py") == []


# ---------------------------------------------------------------------------
# JX008 — unused reachability (scripts only).

_JX008_BAD = """
    import json
    import os

    def helper(x):
        return x + 1

    def main():
        return json.dumps({})
"""

_JX008_CLEAN = """
    import json

    def helper(x):
        return x + 1

    def main():
        return json.dumps(helper(1))
"""


def test_jx008_seeded_and_clean():
    found = lint(_JX008_BAD, path="scripts/tool.py")
    assert rules_of(found) == {"JX008"}
    assert len(found) == 2  # `os` import and `helper`
    assert lint(_JX008_CLEAN, path="scripts/tool.py") == []
    # Package modules are out of scope: public API is invisible reachability.
    assert lint(_JX008_BAD, path="mod.py") == []


# ---------------------------------------------------------------------------
# JX009 — unblocked timing (measurement modules only).

_JX009_BAD = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        out = engine.run_batch_async(keys)
        return time.perf_counter() - t0
"""

_JX009_CLEAN = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        out = engine.run_batch_async(keys)
        out().block_until_ready()
        return time.perf_counter() - t0
"""

#: Synchronous timing: no device-dispatch call in the bracket at all.
_JX009_CLEAN_SYNC = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        out = engine.run_batch(keys)
        return time.perf_counter() - t0
"""

#: Module top level is a timed scope too (benchmark scripts time inline).
_JX009_BAD_TOPLEVEL = """
    import time

    t0 = time.monotonic()
    out = eng._run_device(keys, hi, lo, params)
    elapsed = time.monotonic() - t0
"""

#: The delta's left side may be a name holding a later clock reading.
_JX009_BAD_NAMED_NOW = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        engine.run_batch_async(keys)
        now = time.perf_counter()
        dur = now - t0
        return dur
"""

#: A re-mark between the dispatch and the delta narrows the bracket: the
#: dispatch is OUTSIDE the re-marked interval.
_JX009_CLEAN_REMARK = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        fin = engine.run_batch_async(keys)
        result = fin()
        t0 = time.perf_counter()
        host_work(result)
        return time.perf_counter() - t0
"""


def test_jx009_seeded_and_clean():
    found = lint(_JX009_BAD, path="bench_like.py")
    assert rules_of(found) == {"JX009"}
    assert "run_batch_async" in found[0].message
    assert lint(_JX009_CLEAN, path="bench_like.py") == []
    assert lint(_JX009_CLEAN_SYNC, path="bench_like.py") == []
    assert lint(_JX009_CLEAN_REMARK, path="bench_like.py") == []
    found = lint(_JX009_BAD_TOPLEVEL, path="bench_like.py")
    assert rules_of(found) == {"JX009"}
    found = lint(_JX009_BAD_NAMED_NOW, path="bench_like.py")
    assert rules_of(found) == {"JX009"}


def test_jx009_scoped_to_measurement_modules():
    """Orchestration code times unforced intervals deliberately (pipelined
    stall accounting); the rule must stay inside the measurement set."""
    assert lint(_JX009_BAD, path="mod.py") == []
    assert lint(_JX009_BAD, path="hot.py") == []


def test_jx009_suppression():
    src = _JX009_BAD.replace(
        "return time.perf_counter() - t0",
        "return time.perf_counter() - t0  "
        "# tpusim-lint: disable=JX009 -- sync lives in a callable",
    )
    assert lint(src, path="bench_like.py") == []


# ---------------------------------------------------------------------------
# Suppressions.


def test_suppression_same_line_and_line_above():
    same_line = """
        import jax

        @jax.jit
        def step(x):
            if x > 0:  # tpusim-lint: disable=JX001 -- trace-time constant here
                return x + 1
            return x
    """
    assert lint(same_line) == []
    above = """
        import jax

        @jax.jit
        def step(x):
            # tpusim-lint: disable=JX001 -- reason strings may wrap over
            # several comment lines before the code they cover.
            if x > 0:
                return x + 1
            return x
    """
    assert lint(above) == []


def test_suppression_is_rule_specific():
    src = """
        import jax

        @jax.jit
        def step(x):
            if x > 0:  # tpusim-lint: disable=JX005 -- wrong rule id
                return x + 1
            return x
    """
    assert rules_of(lint(src)) == {"JX001"}


# ---------------------------------------------------------------------------
# Baseline round-trip + the CI gate contract.


def test_baseline_round_trip(tmp_path):
    findings = lint(_JX001_BAD)
    assert findings
    path = tmp_path / "baseline.json"
    Baseline.write(path, findings)
    bl = Baseline.load(path)
    new, old = bl.split(findings)
    assert new == [] and len(old) == len(findings)
    # A fresh violation in another file is NOT grandfathered.
    fresh = lint(_JX004_BAD, path="other.py")
    new, old = bl.split(findings + fresh)
    assert {f.rule for f in new} == {"JX004"} and len(old) == len(findings)


def test_baseline_survives_line_shift(tmp_path):
    findings = lint(_JX001_BAD)
    path = tmp_path / "baseline.json"
    Baseline.write(path, findings)
    shifted = lint("\n# a new comment line\n\n" + textwrap.dedent(_JX001_BAD))
    new, _ = Baseline.load(path).split(shifted)
    assert new == []


def test_committed_baseline_gate_is_green():
    """The acceptance invariant: `tpusim lint --baseline ...` exits 0 on the
    repo as committed."""
    rc = lint_main(["--baseline", str(REPO / ".tpusim-lint-baseline.json"), "--quiet"])
    assert rc == 0


def test_fresh_jx003_in_engine_fails_the_gate():
    """Simulates the CI contract end-to-end on the REAL engine source: a
    use-after-donation freshly introduced into engine.py must produce a new
    (non-baselined) JX003 finding, i.e. fail the lint leg."""
    src = (REPO / "tpusim" / "engine.py").read_text()
    src += textwrap.dedent("""

        def _bad_drive(engine, state, aux, hi, lo, keys, params):
            engine._pipe_chunk(state, aux, hi, lo, keys, 0, params)
            return state, hi
    """)
    from tpusim.lint import load_config

    findings = lint_source(src, "tpusim/engine.py", config=load_config())
    jx003 = [f for f in findings if f.rule == "JX003"]
    assert jx003, "seeded use-after-donation not caught"
    assert {"state", "hi"} <= {f.message.split("`")[1] for f in jx003}
    bl = Baseline.load(REPO / ".tpusim-lint-baseline.json")
    new, _ = bl.split(findings)
    assert any(f.rule == "JX003" for f in new)


def test_cli_rules_filter_and_list(tmp_path, capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("JX001", "JX007", "JX008"):
        assert rule_id in out
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    if x > 0:\n        return x\n    return -x\n")
    # Path outside the repo root: lint it via the paths argument.
    rc = lint_main([str(bad), "--rules", "JX004", "--quiet"])
    assert rc == 0  # JX001 not in the requested rule set
    rc = lint_main([str(bad), "--rules", "JX001", "--quiet"])
    assert rc == 1
    assert lint_main([str(bad), "--rules", "JX999"]) == 2


def test_cli_directory_args_respect_config_and_dedupe(tmp_path, capsys):
    """`lint tpusim` must agree with the bare CI invocation's file set (the
    config-excluded lint package stays out), and repeating a path must not
    duplicate findings."""
    import argparse

    from tpusim.lint.cli import _collect_files, _repo_root
    from tpusim.lint import load_config

    root = _repo_root()
    cfg = load_config(root / "pyproject.toml")
    by_dir = _collect_files(
        argparse.Namespace(paths=[Path("tpusim")]), root, cfg
    )
    assert by_dir, "directory expansion found nothing"
    assert not any("lint" in f.parts[-2] for f in by_dir)
    doubled = _collect_files(
        argparse.Namespace(paths=[Path("tpusim"), Path("tpusim")]), root, cfg
    )
    assert doubled == by_dir
    # An explicitly named single file is linted even if config-excluded.
    direct = _collect_files(
        argparse.Namespace(paths=[Path("tpusim/lint/rules.py")]), root, cfg
    )
    assert len(direct) == 1


def test_repo_root_follows_cwd(tmp_path, monkeypatch):
    """An installed tpusim must lint the project it is run IN: the root is
    the nearest CWD ancestor with a pyproject.toml, so a checkout-less CWD
    falls back to the package checkout instead of silently linting 0 files."""
    from tpusim.lint.cli import _repo_root

    proj = tmp_path / "proj" / "sub"
    proj.mkdir(parents=True)
    (tmp_path / "proj" / "pyproject.toml").write_text("[tool.tpusim-lint]\n")
    monkeypatch.chdir(proj)
    assert _repo_root() == (tmp_path / "proj").resolve()
    monkeypatch.chdir(REPO)
    assert _repo_root() == REPO


def test_cli_subcommand_dispatch(capsys):
    from tpusim.cli import main as tpusim_main

    assert tpusim_main(["lint", "--list-rules"]) == 0
    assert "JX001" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# compile_count_guard: the runtime complement of JX006.


def test_compile_count_guard_counts_and_asserts():
    import jax
    import jax.numpy as jnp

    from tpusim.testing import compile_count_guard

    f = jax.jit(lambda x: x * 3 + 1)
    shape_probe = jnp.ones(4)  # compile jnp.ones outside the guarded block
    with compile_count_guard() as cold:
        f(shape_probe).block_until_ready()
    assert cold.count >= 1
    with compile_count_guard(exact=0):
        f(jnp.ones(4))
    with pytest.raises(AssertionError, match="expected exactly 0"):
        with compile_count_guard(exact=0):
            f(jnp.ones(16))  # new shape: must recompile


def test_run_batch_compiles_once_per_shape():
    """The enforced JX006 invariant on the headline path: after one warm-up
    batch, further same-shape batches of Engine.run_batch must not trigger a
    single XLA compilation — the device-loop program is compiled exactly once
    per (batch shape, config) and reused for every subsequent batch."""
    from tpusim.config import SimConfig, default_network
    from tpusim.engine import Engine
    from tpusim.runner import make_run_keys
    from tpusim.testing import compile_count_guard

    config = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=4 * 86_400_000,
        runs=8,
        batch_size=8,
        seed=11,
    )
    engine = Engine(config)
    # Keys are *inputs* to run_batch and are built outside the guard: arange
    # with a nonzero start traces a different (tiny) program than arange(0, n),
    # which is key-construction cost, not an engine recompile.
    keys = [make_run_keys(11, start, 8) for start in (0, 8, 16, 24)]
    warm = engine.run_batch(keys[0])
    with compile_count_guard(exact=0):
        out = engine.run_batch(keys[1])
    assert out["runs"] == 8
    assert warm["blocks_found_sum"].shape == out["blocks_found_sum"].shape
    # The pipelined dispatch path compiles its own (donating) chunk executable
    # on first use, but a SECOND pipelined batch must be compile-free too.
    engine.run_batch(keys[2], pipelined=True)
    with compile_count_guard(exact=0):
        engine.run_batch(keys[3], pipelined=True)
