"""tpusim lint: every rule catches its seeded violation and passes the clean
twin; suppression comments and the baseline round-trip behave; a fresh JX003
use-after-donation introduced into the REAL engine.py source fails the gate
(the CI-leg contract); and compile_count_guard pins one-compile-per-shape on
Engine.run_batch (the runtime half of JX006).

The contract pass (tpusim.lint.contracts, JX010-JX014) gets the same
treatment on synthetic whole-project trees — seeded + clean twin per rule,
interprocedural **spread resolution, baseline round-trip over the doc/drill
finding shapes — plus the live CI-gate drill: a span-attr drift and an
unregistered chaos seam written into the REAL tree on disk must each exit 1
against the committed EMPTY baseline.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from tpusim.lint import Baseline, Finding, LintConfig, lint_source
from tpusim.lint.cli import main as lint_main

REPO = Path(__file__).resolve().parent.parent

#: Fixture config: fixture paths double as the project's special module sets.
CFG = LintConfig(
    hot_modules=("hot.py",),
    device_modules=("device.py",),
    unused_globs=("scripts/*.py",),
    measurement_modules=("bench_like.py",),
)


def rules_of(findings: list[Finding]) -> set[str]:
    return {f.rule for f in findings}


def lint(src: str, path: str = "mod.py", rules=None) -> list[Finding]:
    return lint_source(textwrap.dedent(src), path, config=CFG, rules=rules)


# ---------------------------------------------------------------------------
# JX001 — tracer branch.

_JX001_BAD = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        if x > 0:
            return x + 1
        return x
"""

_JX001_CLEAN = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return jnp.where(x > 0, x + 1, x)
"""


def test_jx001_seeded_and_clean():
    assert rules_of(lint(_JX001_BAD)) == {"JX001"}
    assert lint(_JX001_CLEAN) == []


def test_jx001_static_annotations_and_shape_reads_are_exempt():
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, exact: bool):
            if exact:                  # static-by-convention Python bool
                x = x * 2
            if x.shape[0] > 4:         # shape metadata is static
                x = x + 1
            if x is not None:          # trace-time None check
                x = x - 1
            while x.ndim > 2:
                x = x.sum(0)
            return x
    """
    assert lint(src) == []


def test_jx001_reaches_scan_bodies_transitively():
    src = """
        import jax

        def outer(carry, xs):
            return helper(carry, xs), None

        def helper(c, x):
            if c:                      # tracer: helper is scan-reachable
                return c
            return x

        def run(init, xs):
            return jax.lax.scan(outer, init, xs)
    """
    found = lint(src)
    assert rules_of(found) == {"JX001"}
    assert all("helper" in f.message for f in found)


# ---------------------------------------------------------------------------
# JX002 — implicit host sync in hot loops.

_JX002_BAD = """
    import numpy as np

    class Driver:
        def run(self, keys):
            flags = []
            for i in range(8):
                state, flag = self._pipe_chunk(keys, i)
                flags.append(flag)
                if int(flags.pop(0)) == 0:
                    break
            for s in state:
                rows = np.asarray(s)
            return rows
"""

_JX002_CLEAN = """
    import numpy as np

    class Driver:
        def run(self, keys):
            flags = []
            for i in range(8):
                state, flag = self._pipe_chunk(keys, i)
                flags.append(flag)
            done = np.asarray(flags)  # ONE batch-end transfer, after the loop
            return state, done
"""


def test_jx002_seeded_and_clean():
    found = lint(_JX002_BAD, path="hot.py")
    assert rules_of(found) == {"JX002"}
    assert len(found) == 2  # the int() flag fetch and the in-loop asarray
    # The batch-end transfer comprehension outside the dispatch loop is not
    # a per-iteration sync — but comprehensions that ARE the loop still
    # count, so the clean twin moves the fetch after the loop entirely.
    assert lint(_JX002_CLEAN, path="hot.py") == []


def test_jx002_only_applies_to_hot_modules():
    assert lint(_JX002_BAD, path="cold.py") == []


def test_jx002_block_until_ready_flagged_anywhere_in_hot_module():
    src = """
        def warmup(engine, keys):
            out = engine.run_batch_async(keys)()
            out.block_until_ready()
    """
    assert rules_of(lint(src, path="hot.py")) == {"JX002"}


# ---------------------------------------------------------------------------
# JX003 — use-after-donation.

_JX003_BAD = """
    import jax

    step = jax.jit(_step_impl, donate_argnums=(0, 1))

    def drive(state, aux, keys):
        out_state, out_aux = step(state, aux)
        return state.t, out_state       # `state` was donated above
"""

_JX003_CLEAN = """
    import jax

    step = jax.jit(_step_impl, donate_argnums=(0, 1))

    def drive(state, aux, keys):
        state, aux = step(state, aux)   # donated names rebound by the call
        return state.t, aux
"""


def test_jx003_seeded_and_clean():
    found = lint(_JX003_BAD)
    assert rules_of(found) == {"JX003"}
    assert "donated" in found[0].message and "state" in found[0].message
    assert lint(_JX003_CLEAN) == []


def test_jx003_partial_jit_decorator_form():
    bad = """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(buf, x):
            return buf + x

        def drive(buf, x):
            out = step(buf, x)
            return buf, out             # `buf` was donated to step
    """
    clean = """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(buf, x):
            return buf + x

        def drive(buf, x):
            buf = step(buf, x)
            return buf
    """
    assert rules_of(lint(bad)) == {"JX003"}
    assert lint(clean) == []


def test_jx003_reads_in_opposite_if_arm_are_not_flagged():
    clean = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(buf, keys, fast: bool):
            if fast:
                out = step(buf, keys)
                return out
            else:
                return buf.copy()       # step never ran on this path
    """
    assert lint(clean) == []


def test_jx003_multiline_call_args_and_nested_closures():
    # A black-formatted multi-line donating call: its own argument reads on
    # continuation lines are the donation itself, not a use-after.
    clean = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(state, keys):
            out = step(
                state,
                keys,
            )
            return out
    """
    assert lint(clean) == []
    # A same-named local in a nested closure is a different binding and must
    # not mask the real use-after-donation in the outer scope.
    bad = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(state, keys):
            out = step(state, keys)

            def helper():
                state = make()
                return state

            return state.t, out, helper
    """
    assert rules_of(lint(bad)) == {"JX003"}


def test_module_scope_is_scanned():
    # JX002 at script top level (hot module): the exact host-sync pattern,
    # just not wrapped in a def.
    bad = """
        import numpy as np

        flags = []
        for i in range(8):
            state, flag = engine._pipe_chunk(keys, i)
            flags.append(flag)
            done = int(flags.pop(0))
    """
    assert rules_of(lint(bad, path="hot.py")) == {"JX002"}
    # JX004 at module scope.
    bad_key = """
        import jax

        a = jax.random.normal(key, (4,))
        b = jax.random.uniform(key, (4,))
    """
    assert rules_of(lint(bad_key)) == {"JX004"}


def test_suppression_covers_multiline_statement():
    src = """
        import jax

        @jax.jit
        def step(x, lo):
            # tpusim-lint: disable=JX001 -- covers the whole statement below
            if (
                x > lo
            ):
                return x + 1
            return x
    """
    assert lint(src) == []


def test_jx003_attribute_assigned_jit_with_int_donate():
    src = """
        import jax

        class Eng:
            def __init__(self):
                self._go = jax.jit(self._impl, donate_argnums=0)

            def run(self, buf, keys):
                out = self._go(buf, keys)
                return buf + out
    """
    found = lint(src)
    assert rules_of(found) == {"JX003"}


# ---------------------------------------------------------------------------
# JX004 — PRNG state reuse.

_JX004_BAD = """
    import jax

    def draw(key):
        a = jax.random.normal(key, (4,))
        b = jax.random.uniform(key, (4,))
        return a, b
"""

_JX004_CLEAN = """
    import jax

    def draw(key):
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, (4,))
        b = jax.random.uniform(k2, (4,))
        return a, b
"""


def test_jx004_seeded_and_clean():
    found = lint(_JX004_BAD)
    assert rules_of(found) == {"JX004"}
    assert lint(_JX004_CLEAN) == []


def test_jx004_loop_reuse_and_per_iteration_split():
    bad = """
        import jax

        def draw(key, n):
            out = []
            for i in range(n):
                out.append(jax.random.bits(key, (2,)))
            return out
    """
    clean = """
        import jax

        def draw(key, n):
            out = []
            for i in range(n):
                key, sub = jax.random.split(key)
                out.append(jax.random.bits(sub, (2,)))
            return out
    """
    assert rules_of(lint(bad)) == {"JX004"}
    assert lint(clean) == []


def test_jx004_if_else_arms_are_not_reuse():
    clean = """
        import jax

        def draw(key, cond: bool):
            if cond:
                return jax.random.uniform(key, (4,))
            else:
                return jax.random.normal(key, (4,))
    """
    assert lint(clean) == []
    # ...but a consumption AFTER the if/else still conflicts with both arms.
    bad = """
        import jax

        def draw(key, cond: bool):
            if cond:
                a = jax.random.uniform(key, (4,))
            else:
                a = jax.random.normal(key, (4,))
            return a + jax.random.bits(key, (4,))
    """
    assert rules_of(lint(bad)) == {"JX004"}


def test_jx004_sibling_nested_functions_do_not_conflate():
    clean = """
        import jax

        def make(key):
            def one():
                return jax.random.uniform(key, (2,))

            def two(key):
                return jax.random.normal(key, (2,))

            return one, two
    """
    assert lint(clean) == []


def test_jx004_xoroshiro_consumer_from_config():
    bad = """
        def step(xi):
            s1, hi, lo = next_words(xi)
            s2, h2, l2 = next_words(xi)   # same stream consumed twice
            return hi, h2
    """
    clean = """
        def step(xi):
            xi, hi, lo = next_words(xi)
            xi, h2, l2 = next_words(xi)
            return hi, h2
    """
    assert rules_of(lint(bad)) == {"JX004"}
    assert lint(clean) == []


# ---------------------------------------------------------------------------
# JX005 — dtype drift.

_JX005_BAD = """
    import jax
    import numpy as np
    import jax.numpy as jnp

    @jax.jit
    def scale(x):
        return x * np.float64(2.0)
"""

_JX005_CLEAN = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def scale(x):
        return x * jnp.float32(2.0)
"""


def test_jx005_seeded_and_clean():
    found = lint(_JX005_BAD)
    assert rules_of(found) == {"JX005"}
    assert lint(_JX005_CLEAN) == []


def test_jx005_builtin_dtype_and_bare_float_literal():
    bad = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def make(x):
            a = jnp.zeros(4, dtype=float)
            b = jnp.asarray(0.5)
            return a, b, x
    """
    clean = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def make(x):
            a = jnp.zeros(4, dtype=jnp.float32)
            b = jnp.asarray(0.5, jnp.float32)
            return a, b, x
    """
    found = lint(bad)
    assert rules_of(found) == {"JX005"} and len(found) == 2
    assert lint(clean) == []


# ---------------------------------------------------------------------------
# JX006 — recompilation risk.

_JX006_BAD = """
    import jax

    chunk = jax.jit(_chunk_impl)

    def run(state, n):
        for i in range(n):
            state = chunk(state, i)
        return state
"""

_JX006_CLEAN = """
    import jax
    import jax.numpy as jnp

    chunk = jax.jit(_chunk_impl)

    def run(state, n):
        for i in range(n):
            state = chunk(state, jnp.asarray(i, jnp.uint32))
        return state
"""


def test_jx006_seeded_and_clean():
    found = lint(_JX006_BAD)
    assert rules_of(found) == {"JX006"}
    assert "loop variable" in found[0].message
    assert lint(_JX006_CLEAN) == []


def test_jx006_bare_jit_decorator_is_registered():
    bad = """
        import jax

        @jax.jit
        def step(state, i):
            return state

        def run(state, n):
            for i in range(n):
                state = step(state, i)
            return state
    """
    assert rules_of(lint(bad)) == {"JX006"}


def test_jx003_next_iteration_read_of_donated_buffer_in_loop():
    bad = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(state, n):
            for i in range(n):
                probe = state.sum()      # iteration 2 reads a donated buffer
                out = step(state, probe)
            return out
    """
    clean = """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def drive(state, n):
            for i in range(n):
                probe = state.sum()
                state = step(state, probe)   # rebound every iteration
            return state
    """
    found = lint(bad)
    assert rules_of(found) == {"JX003"}
    assert any("next iteration" in f.message for f in found)
    assert lint(clean) == []


def test_jx006_scalar_literal_in_loop():
    bad = """
        import jax

        step = jax.jit(_impl)

        def run(state):
            while state is not None:
                state = step(state, 0.5)
            return state
    """
    assert rules_of(lint(bad)) == {"JX006"}


# ---------------------------------------------------------------------------
# JX007 — nondeterministic host calls in device modules.

_JX007_BAD = """
    import time

    def step(state):
        t0 = time.perf_counter()
        return state, t0
"""

_JX007_CLEAN = """
    def step(state, now):
        return state, now
"""


def test_jx007_seeded_and_clean():
    found = lint(_JX007_BAD, path="device.py")
    assert rules_of(found) == {"JX007"}
    assert lint(_JX007_CLEAN, path="device.py") == []
    # Host orchestration modules may use time freely.
    assert lint(_JX007_BAD, path="runner_like.py") == []


# ---------------------------------------------------------------------------
# JX008 — unused reachability (scripts only).

_JX008_BAD = """
    import json
    import os

    def helper(x):
        return x + 1

    def main():
        return json.dumps({})
"""

_JX008_CLEAN = """
    import json

    def helper(x):
        return x + 1

    def main():
        return json.dumps(helper(1))
"""


def test_jx008_seeded_and_clean():
    found = lint(_JX008_BAD, path="scripts/tool.py")
    assert rules_of(found) == {"JX008"}
    assert len(found) == 2  # `os` import and `helper`
    assert lint(_JX008_CLEAN, path="scripts/tool.py") == []
    # Package modules are out of scope: public API is invisible reachability.
    assert lint(_JX008_BAD, path="mod.py") == []


# ---------------------------------------------------------------------------
# JX009 — unblocked timing (measurement modules only).

_JX009_BAD = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        out = engine.run_batch_async(keys)
        return time.perf_counter() - t0
"""

_JX009_CLEAN = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        out = engine.run_batch_async(keys)
        out().block_until_ready()
        return time.perf_counter() - t0
"""

#: Synchronous timing: no device-dispatch call in the bracket at all.
_JX009_CLEAN_SYNC = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        out = engine.run_batch(keys)
        return time.perf_counter() - t0
"""

#: Module top level is a timed scope too (benchmark scripts time inline).
_JX009_BAD_TOPLEVEL = """
    import time

    t0 = time.monotonic()
    out = eng._run_device(keys, hi, lo, params)
    elapsed = time.monotonic() - t0
"""

#: The delta's left side may be a name holding a later clock reading.
_JX009_BAD_NAMED_NOW = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        engine.run_batch_async(keys)
        now = time.perf_counter()
        dur = now - t0
        return dur
"""

#: A re-mark between the dispatch and the delta narrows the bracket: the
#: dispatch is OUTSIDE the re-marked interval.
_JX009_CLEAN_REMARK = """
    import time

    def measure(engine, keys):
        t0 = time.perf_counter()
        fin = engine.run_batch_async(keys)
        result = fin()
        t0 = time.perf_counter()
        host_work(result)
        return time.perf_counter() - t0
"""


def test_jx009_seeded_and_clean():
    found = lint(_JX009_BAD, path="bench_like.py")
    assert rules_of(found) == {"JX009"}
    assert "run_batch_async" in found[0].message
    assert lint(_JX009_CLEAN, path="bench_like.py") == []
    assert lint(_JX009_CLEAN_SYNC, path="bench_like.py") == []
    assert lint(_JX009_CLEAN_REMARK, path="bench_like.py") == []
    found = lint(_JX009_BAD_TOPLEVEL, path="bench_like.py")
    assert rules_of(found) == {"JX009"}
    found = lint(_JX009_BAD_NAMED_NOW, path="bench_like.py")
    assert rules_of(found) == {"JX009"}


def test_jx009_scoped_to_measurement_modules():
    """Orchestration code times unforced intervals deliberately (pipelined
    stall accounting); the rule must stay inside the measurement set."""
    assert lint(_JX009_BAD, path="mod.py") == []
    assert lint(_JX009_BAD, path="hot.py") == []


def test_jx009_suppression():
    src = _JX009_BAD.replace(
        "return time.perf_counter() - t0",
        "return time.perf_counter() - t0  "
        "# tpusim-lint: disable=JX009 -- sync lives in a callable",
    )
    assert lint(src, path="bench_like.py") == []


# ---------------------------------------------------------------------------
# Suppressions.


def test_suppression_same_line_and_line_above():
    same_line = """
        import jax

        @jax.jit
        def step(x):
            if x > 0:  # tpusim-lint: disable=JX001 -- trace-time constant here
                return x + 1
            return x
    """
    assert lint(same_line) == []
    above = """
        import jax

        @jax.jit
        def step(x):
            # tpusim-lint: disable=JX001 -- reason strings may wrap over
            # several comment lines before the code they cover.
            if x > 0:
                return x + 1
            return x
    """
    assert lint(above) == []


def test_suppression_is_rule_specific():
    src = """
        import jax

        @jax.jit
        def step(x):
            if x > 0:  # tpusim-lint: disable=JX005 -- wrong rule id
                return x + 1
            return x
    """
    assert rules_of(lint(src)) == {"JX001"}


# ---------------------------------------------------------------------------
# Baseline round-trip + the CI gate contract.


def test_baseline_round_trip(tmp_path):
    findings = lint(_JX001_BAD)
    assert findings
    path = tmp_path / "baseline.json"
    Baseline.write(path, findings)
    bl = Baseline.load(path)
    new, old = bl.split(findings)
    assert new == [] and len(old) == len(findings)
    # A fresh violation in another file is NOT grandfathered.
    fresh = lint(_JX004_BAD, path="other.py")
    new, old = bl.split(findings + fresh)
    assert {f.rule for f in new} == {"JX004"} and len(old) == len(findings)


def test_baseline_survives_line_shift(tmp_path):
    findings = lint(_JX001_BAD)
    path = tmp_path / "baseline.json"
    Baseline.write(path, findings)
    shifted = lint("\n# a new comment line\n\n" + textwrap.dedent(_JX001_BAD))
    new, _ = Baseline.load(path).split(shifted)
    assert new == []


def test_committed_baseline_gate_is_green():
    """The acceptance invariant: `tpusim lint --baseline ...` exits 0 on the
    repo as committed."""
    rc = lint_main(["--baseline", str(REPO / ".tpusim-lint-baseline.json"), "--quiet"])
    assert rc == 0


def test_fresh_jx003_in_engine_fails_the_gate():
    """Simulates the CI contract end-to-end on the REAL engine source: a
    use-after-donation freshly introduced into engine.py must produce a new
    (non-baselined) JX003 finding, i.e. fail the lint leg."""
    src = (REPO / "tpusim" / "engine.py").read_text()
    src += textwrap.dedent("""

        def _bad_drive(engine, state, aux, hi, lo, keys, params):
            engine._pipe_chunk(state, aux, hi, lo, keys, 0, params)
            return state, hi
    """)
    from tpusim.lint import load_config

    findings = lint_source(src, "tpusim/engine.py", config=load_config())
    jx003 = [f for f in findings if f.rule == "JX003"]
    assert jx003, "seeded use-after-donation not caught"
    assert {"state", "hi"} <= {f.message.split("`")[1] for f in jx003}
    bl = Baseline.load(REPO / ".tpusim-lint-baseline.json")
    new, _ = bl.split(findings)
    assert any(f.rule == "JX003" for f in new)


def test_cli_rules_filter_and_list(tmp_path, capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("JX001", "JX007", "JX008"):
        assert rule_id in out
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    if x > 0:\n        return x\n    return -x\n")
    # Path outside the repo root: lint it via the paths argument.
    rc = lint_main([str(bad), "--rules", "JX004", "--quiet"])
    assert rc == 0  # JX001 not in the requested rule set
    rc = lint_main([str(bad), "--rules", "JX001", "--quiet"])
    assert rc == 1
    assert lint_main([str(bad), "--rules", "JX999"]) == 2


def test_cli_directory_args_respect_config_and_dedupe(tmp_path, capsys):
    """`lint tpusim` must agree with the bare CI invocation's file set (the
    config-excluded lint package stays out), and repeating a path must not
    duplicate findings."""
    import argparse

    from tpusim.lint.cli import _collect_files, _repo_root
    from tpusim.lint import load_config

    root = _repo_root()
    cfg = load_config(root / "pyproject.toml")
    by_dir = _collect_files(
        argparse.Namespace(paths=[Path("tpusim")]), root, cfg
    )
    assert by_dir, "directory expansion found nothing"
    assert not any("lint" in f.parts[-2] for f in by_dir)
    doubled = _collect_files(
        argparse.Namespace(paths=[Path("tpusim"), Path("tpusim")]), root, cfg
    )
    assert doubled == by_dir
    # An explicitly named single file is linted even if config-excluded.
    direct = _collect_files(
        argparse.Namespace(paths=[Path("tpusim/lint/rules.py")]), root, cfg
    )
    assert len(direct) == 1


def test_repo_root_follows_cwd(tmp_path, monkeypatch):
    """An installed tpusim must lint the project it is run IN: the root is
    the nearest CWD ancestor with a pyproject.toml, so a checkout-less CWD
    falls back to the package checkout instead of silently linting 0 files."""
    from tpusim.lint.cli import _repo_root

    proj = tmp_path / "proj" / "sub"
    proj.mkdir(parents=True)
    (tmp_path / "proj" / "pyproject.toml").write_text("[tool.tpusim-lint]\n")
    monkeypatch.chdir(proj)
    assert _repo_root() == (tmp_path / "proj").resolve()
    monkeypatch.chdir(REPO)
    assert _repo_root() == REPO


def test_cli_subcommand_dispatch(capsys):
    from tpusim.cli import main as tpusim_main

    assert tpusim_main(["lint", "--list-rules"]) == 0
    assert "JX001" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# compile_count_guard: the runtime complement of JX006.


def test_compile_count_guard_counts_and_asserts():
    import jax
    import jax.numpy as jnp

    from tpusim.testing import compile_count_guard

    f = jax.jit(lambda x: x * 3 + 1)
    shape_probe = jnp.ones(4)  # compile jnp.ones outside the guarded block
    with compile_count_guard() as cold:
        f(shape_probe).block_until_ready()
    assert cold.count >= 1
    with compile_count_guard(exact=0):
        f(jnp.ones(4))
    with pytest.raises(AssertionError, match="expected exactly 0"):
        with compile_count_guard(exact=0):
            f(jnp.ones(16))  # new shape: must recompile


def test_run_batch_compiles_once_per_shape():
    """The enforced JX006 invariant on the headline path: after one warm-up
    batch, further same-shape batches of Engine.run_batch must not trigger a
    single XLA compilation — the device-loop program is compiled exactly once
    per (batch shape, config) and reused for every subsequent batch."""
    from tpusim.config import SimConfig, default_network
    from tpusim.engine import Engine
    from tpusim.runner import make_run_keys
    from tpusim.testing import compile_count_guard

    config = SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=4 * 86_400_000,
        runs=8,
        batch_size=8,
        seed=11,
    )
    engine = Engine(config)
    # Keys are *inputs* to run_batch and are built outside the guard: arange
    # with a nonzero start traces a different (tiny) program than arange(0, n),
    # which is key-construction cost, not an engine recompile.
    keys = [make_run_keys(11, start, 8) for start in (0, 8, 16, 24)]
    warm = engine.run_batch(keys[0])
    with compile_count_guard(exact=0):
        out = engine.run_batch(keys[1])
    assert out["runs"] == 8
    assert warm["blocks_found_sum"].shape == out["blocks_found_sum"].shape
    # The pipelined dispatch path compiles its own (donating) chunk executable
    # on first use, but a SECOND pipelined batch must be compile-free too.
    engine.run_batch(keys[2], pipelined=True)
    with compile_count_guard(exact=0):
        engine.run_batch(keys[3], pipelined=True)


# ---------------------------------------------------------------------------
# Contract pass (tpusim.lint.contracts): JX010-JX014 on synthetic projects.

from tpusim.lint import CONTRACT_RULES, lint_contracts  # noqa: E402


def _contract_cfg(**over):
    base = dict(
        include=("*.py",),
        exclude=(),
        telemetry_modules=("producer.py", "consumer.py"),
        span_writer="producer.py:Recorder.emit",
        span_schema_required=("run_id", "span", "attrs"),
        context_methods=("set_context",),
        drill_globs=("drills/*.json",),
        doc_files=("README.md",),
        engine_leaf_modules=("eng.py",),
        leaf_dict_names=("sums", "out"),
        leaf_consumer_modules=("orc.py",),
        leaf_read_names=("raw",),
        leaf_strip_prefixes=("tele_",),
        leaf_merge_suffixes=("_sum", "_max", "_per_run"),
        leaf_scalar_allowlist=("runs",),
        packed_consumer_modules=("orc.py",),
        packed_leaf_strip=(),
        cli_modules=("cli_mod.py",),
        flag_ignore=(),
    )
    base.update(over)
    return LintConfig(**base)


_README_OK = """# proj

<!-- tpusim-lint: span-schema -->
- Span schema: `{"run_id", "span", "attrs"}` per line.

<!-- tpusim-lint: chaos-seam-table -->
| point | fired from |
|---|---|
| `engine.dispatch` | the runner |
"""

_PRODUCER_OK = """
class Recorder:
    def emit(self, span, **attrs):
        row = {"run_id": self.run_id, "span": span, "attrs": attrs}
        self.fh.write(row)


def run(rec, chaos):
    chaos.fire("engine.dispatch", batch=0)
    rec.emit("batch", runs=4, stall_s=0.25)
"""


def _write_contract_proj(tmp_path, producer=_PRODUCER_OK, consumer="",
                         readme=_README_OK, drills=(), **cfg_over):
    (tmp_path / "producer.py").write_text(textwrap.dedent(producer))
    (tmp_path / "consumer.py").write_text(textwrap.dedent(consumer))
    (tmp_path / "README.md").write_text(readme)
    (tmp_path / "drills").mkdir(exist_ok=True)
    for name, text in drills:
        (tmp_path / "drills" / name).write_text(text)
    return _contract_cfg(**cfg_over)


def contract_rules_of(findings):
    return {f.rule for f in findings}


def test_jx010_consumed_key_never_emitted(tmp_path):
    bad = """
        def render(spans):
            for sp in spans:
                a = sp.get("attrs") or {}
                a.get("runs")          # emitted: clean
                a.get("ghost_key")     # never emitted: JX010
    """
    cfg = _write_contract_proj(tmp_path, consumer=bad)
    findings = lint_contracts(tmp_path, cfg, rules=["JX010"])
    msgs = [f.message for f in findings]
    assert any("ghost_key" in m for m in msgs)
    assert not any("`runs`" in m for m in msgs)
    # Clean twin: emitting the key clears the finding.
    ok = _PRODUCER_OK + "\n\ndef more(rec):\n    rec.emit(\"batch\", ghost_key=1)\n"
    cfg = _write_contract_proj(tmp_path, producer=ok, consumer=bad)
    assert lint_contracts(tmp_path, cfg, rules=["JX010"]) == []


def test_jx010_spread_resolution_through_dicts_and_helpers(tmp_path):
    """**attrs spreads resolve through dict()/update()/subscript stores and
    attr-returning helper functions — the runner's real emit shape."""
    producer = """
        class Recorder:
            def emit(self, span, **attrs):
                row = {"run_id": 1, "span": span, "attrs": attrs}

        def helper_attrs():
            extra = {}
            extra["mem_bytes"] = 7
            return extra

        def run(rec):
            attrs = dict(runs=4)
            attrs.update(helper_attrs())
            attrs.update(stall_s=0.1)
            attrs["engine"] = "Engine"
            rec.emit("batch", **attrs)
    """
    consumer = """
        def render(spans):
            for sp in spans:
                a = sp.get("attrs") or {}
                a.get("runs"); a.get("mem_bytes"); a.get("stall_s"); a.get("engine")
    """
    cfg = _write_contract_proj(tmp_path, producer=producer, consumer=consumer)
    # The seam table names engine.dispatch which this producer never fires;
    # scope the run to JX010 only.
    assert lint_contracts(tmp_path, cfg, rules=["JX010"]) == []


def test_jx010_span_name_and_prefix_consumption(tmp_path):
    consumer = """
        def render(spans):
            batches = [sp for sp in spans if sp["span"] == "batch"]    # emitted
            ghosts = [sp for sp in spans if sp.get("span") == "ghost"] # JX010
            pref = [sp for sp in spans
                    if str(sp.get("span", "")).startswith("fleet_")]   # JX010
            return batches, ghosts, pref
    """
    cfg = _write_contract_proj(tmp_path, consumer=consumer)
    msgs = [f.message for f in lint_contracts(tmp_path, cfg, rules=["JX010"])]
    assert any("`ghost`" in m for m in msgs)
    assert any("`fleet_`" in m for m in msgs)
    assert not any("`batch`" in m for m in msgs)


def test_jx010_raw_attr_subscript_and_get_twin(tmp_path):
    bad = """
        def render(spans):
            for sp in spans:
                x = (sp.get("attrs") or {})["runs"]    # raw subscript: JX010
            return x
    """
    cfg = _write_contract_proj(tmp_path, consumer=bad)
    findings = lint_contracts(tmp_path, cfg, rules=["JX010"])
    assert any("raw" in f.message and "subscript" in f.message for f in findings)
    ok = bad.replace('["runs"]', '.get("runs")')
    cfg = _write_contract_proj(tmp_path, consumer=ok)
    assert lint_contracts(tmp_path, cfg, rules=["JX010"]) == []


def test_jx010_schema_required_field_omission(tmp_path):
    producer = """
        class Recorder:
            def emit(self, span, **attrs):
                row = {"run_id": 1, "span": span}   # "attrs" omitted
    """
    readme = _README_OK.replace('"attrs"}', '"attrs"}')  # doc still lists it
    cfg = _write_contract_proj(tmp_path, producer=producer, readme=readme)
    findings = lint_contracts(tmp_path, cfg, rules=["JX010"])
    assert any("omits required schema" in f.message for f in findings)
    # The doc cross-check also flags the field the writer no longer produces.
    assert any("never produces" in f.message for f in findings)


def test_jx010_schema_doc_marker_missing_is_loud(tmp_path):
    readme = _README_OK.replace("tpusim-lint: span-schema", "no marker here")
    cfg = _write_contract_proj(tmp_path, readme=readme)
    findings = lint_contracts(tmp_path, cfg, rules=["JX010"])
    assert any("span-schema` marker" in f.message for f in findings)


def test_jx011_drill_naming_unfired_seam(tmp_path):
    drill = '{"faults": [{"point": "ghost.seam", "kind": "transient"}]}'
    cfg = _write_contract_proj(tmp_path, drills=[("bad.json", drill)])
    findings = lint_contracts(tmp_path, cfg, rules=["JX011"])
    assert any(
        f.rule == "JX011" and "ghost.seam" in f.message
        and f.path == "drills/bad.json" for f in findings
    )
    ok = '{"faults": [{"point": "engine.dispatch", "kind": "transient"}]}'
    cfg = _write_contract_proj(tmp_path, drills=[("bad.json", ok)])
    assert lint_contracts(tmp_path, cfg, rules=["JX011"]) == []


def test_jx011_table_vs_code_both_directions(tmp_path):
    # Documented seam nothing fires.
    readme = _README_OK.replace("`engine.dispatch`", "`stale.seam`")
    cfg = _write_contract_proj(tmp_path, readme=readme)
    findings = lint_contracts(tmp_path, cfg, rules=["JX011"])
    assert any("`stale.seam`" in f.message and f.path == "README.md"
               for f in findings)
    # Fired seam the table omits.
    assert any("`engine.dispatch`" in f.message and f.path == "producer.py"
               for f in findings)
    # Missing marker is itself loud.
    cfg = _write_contract_proj(
        tmp_path, readme="# no marker\n", drills=()
    )
    findings = lint_contracts(tmp_path, cfg, rules=["JX011"])
    assert any("chaos-seam-table` marker" in f.message for f in findings)


_ENG_OK = """
def combine_sums(a, b):
    def merge(k):
        if k.startswith("flight_") or k.endswith("_per_run"):
            return 1
        if k.endswith("_max"):
            return 2
        return 3
    return {k: merge(k) for k in a}


def finalize_fn(state):
    return {"blocks_sum": 1, "share_per_run": 2}


def run_batch(n):
    sums = {}
    sums["tele_depth_max"] = 3
    sums["runs"] = n
    return sums
"""

_ORC_OK = """
def drive(raw):
    raw["tele_depth_max"]
    for k in list(raw):
        if k.startswith("tele_"):
            raw.pop(k)


def fold_piece(raw, start, count):
    return raw["share_per_run"][start:start + count]
"""


def test_jx012_naming_contract_and_consumed_leaves(tmp_path):
    (tmp_path / "eng.py").write_text(_ENG_OK)
    (tmp_path / "orc.py").write_text(_ORC_OK)
    cfg = _write_contract_proj(tmp_path)
    assert lint_contracts(tmp_path, cfg, rules=["JX012"]) == []
    # A leaf outside every merge class fires.
    (tmp_path / "eng.py").write_text(
        _ENG_OK + "\n\ndef extra(sums):\n    sums[\"deepest_reorg\"] = 1\n"
    )
    findings = lint_contracts(tmp_path, cfg, rules=["JX012"])
    assert any("deepest_reorg" in f.message and "merge class" in f.message
               for f in findings)
    # A consumed leaf nothing produces fires.
    (tmp_path / "eng.py").write_text(_ENG_OK)
    (tmp_path / "orc.py").write_text(
        _ORC_OK + "\n\ndef dead(raw):\n    raw[\"tele_gone_sum\"]\n"
    )
    findings = lint_contracts(tmp_path, cfg, rules=["JX012"])
    assert any("tele_gone_sum" in f.message for f in findings)


def test_jx012_merge_rule_and_strip_list_drift(tmp_path):
    # combine_sums losing a merge literal fires.
    eng = _ENG_OK.replace('k.endswith("_max")', 'k.endswith("_mx")')
    (tmp_path / "eng.py").write_text(eng)
    (tmp_path / "orc.py").write_text(_ORC_OK)
    cfg = _write_contract_proj(tmp_path)
    findings = lint_contracts(tmp_path, cfg, rules=["JX012"])
    assert any("_max" in f.message and "combine_sums" in f.message
               for f in findings)
    # The consumer module losing its strip literal fires.
    (tmp_path / "eng.py").write_text(_ENG_OK)
    (tmp_path / "orc.py").write_text(
        _ORC_OK.replace('k.startswith("tele_")', 'k.startswith("t_")')
    )
    findings = lint_contracts(tmp_path, cfg, rules=["JX012"])
    assert any("strips" in f.message and "tele_" in f.message for f in findings)


def test_jx012_packed_leaf_piece_boundary_fate(tmp_path):
    """Sub-check (5): every `*_per_run` / `flight_*` leaf an engine stores
    must be read by constant name in a packed-consumer module, or be listed
    in packed-leaf-strip as intentionally dropped at piece boundaries."""
    (tmp_path / "eng.py").write_text(_ENG_OK)
    (tmp_path / "orc.py").write_text(_ORC_OK)
    cfg = _write_contract_proj(tmp_path)
    assert lint_contracts(tmp_path, cfg, rules=["JX012"]) == []
    # A packed leaf nothing slices fires (flight_* class too).
    (tmp_path / "eng.py").write_text(
        _ENG_OK + "\n\ndef aux(sums):\n    sums[\"flight_buf\"] = 1\n"
    )
    findings = lint_contracts(tmp_path, cfg, rules=["JX012"])
    assert any("flight_buf" in f.message and "piece-boundary" in f.message
               for f in findings)
    # Declaring the drop in packed-leaf-strip clears it.
    cfg = _write_contract_proj(tmp_path, packed_leaf_strip=("flight_buf",))
    assert not any("piece-boundary" in f.message
                   for f in lint_contracts(tmp_path, cfg, rules=["JX012"]))
    # A constant-name read in the packed consumer clears it too.
    cfg = _write_contract_proj(tmp_path)
    (tmp_path / "orc.py").write_text(
        _ORC_OK + "\n\ndef decode(sums):\n    sums[\"flight_buf\"]\n"
    )
    assert not any("piece-boundary" in f.message
                   for f in lint_contracts(tmp_path, cfg, rules=["JX012"]))


def test_jx013_doc_flag_drift_and_ignore(tmp_path):
    (tmp_path / "cli_mod.py").write_text(
        "import argparse\np = argparse.ArgumentParser()\n"
        "p.add_argument(\"--runs\", type=int)\n"
    )
    readme = _README_OK + "\nRun with `--runs 4 --ghost-flag`.\n"
    cfg = _write_contract_proj(tmp_path, readme=readme)
    findings = lint_contracts(tmp_path, cfg, rules=["JX013"])
    assert any("--ghost-flag" in f.message for f in findings)
    assert not any("--runs" in f.message for f in findings)
    cfg = _write_contract_proj(
        tmp_path, readme=readme, flag_ignore=("--ghost-flag",)
    )
    assert lint_contracts(tmp_path, cfg, rules=["JX013"]) == []


_METRICS_MOD_OK = """
METRICS = (
    ("proj_spans", "counter", "spans parsed"),
    ("proj_latency_seconds", "histogram", "latency"),
)
"""

_README_METRICS = _README_OK + """
<!-- tpusim-lint: metrics-table -->
| metric | type |
|---|---|
| `proj_spans` | counter |
| `proj_latency_seconds` | histogram |
"""

_SLO_JSON_OK = (
    '{"objectives": [{"metric": "proj_spans", "op": ">=", "threshold": 1}]}'
)


def _write_metrics_proj(tmp_path, metrics_mod=_METRICS_MOD_OK,
                        readme=_README_METRICS, slo=_SLO_JSON_OK, **cfg_over):
    (tmp_path / "metrics_mod.py").write_text(textwrap.dedent(metrics_mod))
    (tmp_path / "slo.json").write_text(slo)
    return _write_contract_proj(
        tmp_path, readme=readme,
        metrics_module="metrics_mod.py", slo_config_files=("slo.json",),
        **cfg_over,
    )


def test_jx014_clean_project(tmp_path):
    cfg = _write_metrics_proj(tmp_path)
    assert lint_contracts(tmp_path, cfg, rules=["JX014"]) == []


def test_jx014_unregistered_slo_metric_fires(tmp_path):
    """Direction 1: an objective over a metric the registry never emits is
    a permanent rc-2 dead gate — flagged statically, at the config line."""
    slo = ('{"objectives": [\n'
           '  {"metric": "proj_spans", "op": ">=", "threshold": 1},\n'
           '  {"metric": "proj_ghost", "op": "<=", "threshold": 9}\n'
           ']}')
    cfg = _write_metrics_proj(tmp_path, slo=slo)
    findings = lint_contracts(tmp_path, cfg, rules=["JX014"])
    assert any("proj_ghost" in f.message and "no-data" in f.message
               for f in findings)
    assert not any("proj_spans" in f.message for f in findings)
    (hit,) = [f for f in findings if "proj_ghost" in f.message]
    assert hit.path == "slo.json" and hit.line == 3  # the referencing line


def test_jx014_registry_readme_drift_both_directions(tmp_path):
    # Registry family absent from the documented table fires...
    readme = _README_METRICS.replace("| `proj_latency_seconds` | histogram |\n", "")
    cfg = _write_metrics_proj(tmp_path, readme=readme)
    findings = lint_contracts(tmp_path, cfg, rules=["JX014"])
    assert any("proj_latency_seconds" in f.message and "missing from" in f.message
               and f.path == "metrics_mod.py" for f in findings)
    # ...and a stale table row the registry no longer emits fires too.
    readme = _README_METRICS + "| `proj_stale` | counter |\n"
    cfg = _write_metrics_proj(tmp_path, readme=readme)
    findings = lint_contracts(tmp_path, cfg, rules=["JX014"])
    assert any("proj_stale" in f.message and "stale" in f.message
               and f.path == "README.md" for f in findings)


def test_jx014_structural_findings(tmp_path):
    # Missing metrics module: the contract has no registry to pin.
    cfg = _write_metrics_proj(tmp_path)
    (tmp_path / "metrics_mod.py").unlink()
    findings = lint_contracts(tmp_path, cfg, rules=["JX014"])
    assert any("no registry to pin" in f.message for f in findings)
    # Module present but no METRICS literal.
    cfg = _write_metrics_proj(tmp_path, metrics_mod="OTHER = 1\n")
    findings = lint_contracts(tmp_path, cfg, rules=["JX014"])
    assert any("METRICS" in f.message for f in findings)
    # Objective-less SLO config: the runtime gate would exit 2 on it.
    cfg = _write_metrics_proj(tmp_path, slo='{"objectives": []}')
    findings = lint_contracts(tmp_path, cfg, rules=["JX014"])
    assert any("dead gate" in f.message and f.path == "slo.json"
               for f in findings)
    # README without the metrics-table marker: cross-check impossible.
    cfg = _write_metrics_proj(tmp_path, readme=_README_OK)
    findings = lint_contracts(tmp_path, cfg, rules=["JX014"])
    assert any("metrics-table" in f.message for f in findings)


def test_contract_findings_baseline_round_trip_and_line_shift(tmp_path):
    """Contract findings (including doc/drill ones) ride the same
    line-number-free fingerprints as the per-module rules."""
    drill = (
        '{"faults": [\n'
        '  {"point": "ghost.seam", "kind": "transient"}\n'
        ']}'
    )
    bad = """
        def render(spans):
            for sp in spans:
                (sp.get("attrs") or {}).get("ghost_key")
    """
    cfg = _write_contract_proj(tmp_path, consumer=bad, drills=[("d.json", drill)])
    findings = lint_contracts(tmp_path, cfg)
    assert {"JX010", "JX011"} <= contract_rules_of(findings)
    path = tmp_path / "bl.json"
    Baseline.write(path, findings)
    # Shift every finding down WITHOUT changing the offending lines' text:
    # fingerprints key on (rule, path, normalized line, occurrence).
    (tmp_path / "consumer.py").write_text(
        "# pad\n# pad\n" + textwrap.dedent(bad)
    )
    (tmp_path / "drills" / "d.json").write_text("\n\n" + drill)
    shifted = lint_contracts(tmp_path, cfg)
    new, old = Baseline.load(path).split(shifted)
    assert new == [] and len(old) == len(shifted) > 0


def test_contract_suppression_comment_in_python(tmp_path):
    bad = """
        def render(spans):
            for sp in spans:
                # tpusim-lint: disable=JX010 -- probing a foreign emitter's key
                (sp.get("attrs") or {}).get("ghost_key")
    """
    cfg = _write_contract_proj(tmp_path, consumer=bad)
    assert lint_contracts(tmp_path, cfg, rules=["JX010"]) == []


def test_contract_rules_listed_and_registered(capsys):
    """The CI floor's unit twin: >= 14 rules listed AND enabled for this
    repo's config (the floor greps out "(disabled)" annotations, so a
    pyproject enabled-rules regression shows up here, not just a registry
    slip)."""
    assert set(CONTRACT_RULES) == {
        "JX010", "JX011", "JX012", "JX013", "JX014", "JX020",
    }
    assert set(CONCURRENCY_RULES) == {
        "JX015", "JX016", "JX017", "JX018", "JX019",
    }
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    enabled_lines = [
        ln for ln in out.splitlines() if ln.strip() and "(disabled)" not in ln
    ]
    assert len(enabled_lines) >= 20
    for rid in (*CONTRACT_RULES, *CONCURRENCY_RULES):
        assert any(ln.startswith(rid) for ln in enabled_lines)


def test_list_rules_annotates_disabled(tmp_path, capsys, monkeypatch):
    """A pyproject that disables a contract rule must show it as (disabled)
    — the CI rule-count floor counts only enabled rules."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "pyproject.toml").write_text(
        "[tool.tpusim-lint]\nenabled-rules = [\"JX001\"]\n"
    )
    monkeypatch.chdir(proj)
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "JX013  (disabled)" in out
    assert not out.splitlines()[0].startswith("JX001  (disabled)")


def test_jx010_no_cross_function_name_bleed(tmp_path):
    """Scopes are per-function: an unrelated function's same-named local
    must neither be classified as span attrs (false positive) nor inflate
    the emitted-key set through its own dict stores (false negative)."""
    consumer = """
        def f(spans):
            for sp in spans:
                a = sp.get("attrs") or {}
                a.get("runs")

        def g(cfg):
            a = dict(cfg)
            a["paths"]          # NOT span attrs: no JX010 here
            return a
    """
    cfg = _write_contract_proj(tmp_path, consumer=consumer)
    assert lint_contracts(tmp_path, cfg, rules=["JX010"]) == []
    # False-negative direction: a producer module whose unrelated function
    # stores "ghost" into its own local `attrs` must NOT count as emitting
    # it — the consumer read stays flagged.
    producer = _PRODUCER_OK + """

def unrelated():
    attrs = {}
    attrs["ghost"] = 1
    return attrs["ghost"]
"""
    consumer = """
        def render(spans):
            for sp in spans:
                (sp.get("attrs") or {}).get("ghost")
    """
    cfg = _write_contract_proj(tmp_path, producer=producer, consumer=consumer)
    findings = lint_contracts(tmp_path, cfg, rules=["JX010"])
    assert any("`ghost`" in f.message for f in findings)


def test_jx011_malformed_drill_shapes_are_findings_not_crashes(tmp_path):
    """Valid JSON of the wrong shape (top-level list, string fault entry)
    must yield the broken-drill finding, not an analyzer traceback."""
    for payload in (
        '[{"point": "engine.dispatch"}]',
        '{"faults": "engine.dispatch"}',
        '{"faults": ["engine.dispatch"]}',
        "not json at all {",
    ):
        cfg = _write_contract_proj(tmp_path, drills=[("bad.json", payload)])
        findings = lint_contracts(tmp_path, cfg, rules=["JX011"])
        assert any(
            f.path == "drills/bad.json" and "certifies nothing" in f.message
            for f in findings
        ), payload


def test_contract_rules_match_case_insensitively(tmp_path):
    """Lowercase ids in an enabled-rules config must still run the contract
    pass (lint_source upper-cases; the contract trigger must agree) — else
    the gate silently degrades while --list-rules reports all-enabled."""
    bad = """
        def render(spans):
            for sp in spans:
                (sp.get("attrs") or {}).get("ghost_key")
    """
    cfg = _write_contract_proj(tmp_path, consumer=bad,
                               enabled_rules=("jx010",))
    findings = lint_contracts(tmp_path, cfg)
    assert any("ghost_key" in f.message for f in findings)


def test_jx010_two_defects_at_one_node_both_survive(tmp_path):
    """A raw subscript of a never-emitted key is TWO defects at one
    position; the dedup key includes the message so neither is dropped."""
    bad = """
        def render(spans):
            for sp in spans:
                (sp.get("attrs") or {})["ghost_key"]
    """
    cfg = _write_contract_proj(tmp_path, consumer=bad)
    findings = lint_contracts(tmp_path, cfg, rules=["JX010"])
    msgs = [f.message for f in findings]
    assert any("ghost_key" in m and "no emit site" in m for m in msgs)
    assert any("raw" in m and "subscript" in m for m in msgs)


def test_live_injected_drift_fails_the_gate(capsys):
    """The CI-leg contract end-to-end on the REAL tree: a synthetic span-attr
    drift written into report.py on disk and an unregistered chaos seam
    written into probe.py must each fail the lint gate (exit 1) against the
    committed EMPTY baseline, and the reverted tree must pass again."""
    baseline = str(REPO / ".tpusim-lint-baseline.json")
    report = REPO / "tpusim" / "report.py"
    probe = REPO / "tpusim" / "probe.py"
    orig_report, orig_probe = report.read_text(), probe.read_text()
    try:
        report.write_text(orig_report + textwrap.dedent("""

            def _drifted_consumer(sp):
                return (sp.get("attrs") or {}).get("attr_key_nobody_emits")
        """))
        assert lint_main(["--baseline", baseline, "--quiet"]) == 1
        out = capsys.readouterr().out
        assert "attr_key_nobody_emits" in out and "JX010" in out
    finally:
        report.write_text(orig_report)
    try:
        probe.write_text(orig_probe + textwrap.dedent("""

            def _unregistered_seam(chaos):
                chaos.fire("drill.seam_nobody_documents")
        """))
        assert lint_main(["--baseline", baseline, "--quiet"]) == 1
        out = capsys.readouterr().out
        assert "seam_nobody_documents" in out and "JX011" in out
    finally:
        probe.write_text(orig_probe)
    assert lint_main(["--baseline", baseline, "--quiet"]) == 0


def test_cli_github_format(tmp_path, capsys, monkeypatch):
    """--format github emits workflow-annotation lines the Actions runner
    renders inline on the diff."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "pyproject.toml").write_text(
        "[tool.tpusim-lint]\ninclude = [\"*.py\"]\nexclude = []\n"
        "enabled-rules = [\"JX001\"]\n"
    )
    (proj / "bad.py").write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    if x > 0:\n        return x\n"
        "    return -x\n"
    )
    monkeypatch.chdir(proj)
    rc = lint_main(["--format", "github", "--quiet"])
    assert rc == 1
    out = capsys.readouterr().out
    assert out.startswith("::error file=bad.py,line=")
    assert "title=JX001" in out


# ---------------------------------------------------------------------------
# Thread-safety pass (tpusim.lint.concurrency): JX015-JX019 on synthetic
# projects — one seeded+clean twin per rule — plus the live injected-race
# gate on the real tree.

from tpusim.lint import CONCURRENCY_RULES, lint_concurrency  # noqa: E402


def _thread_proj(tmp_path, src, **over):
    (tmp_path / "mod.py").write_text(textwrap.dedent(src))
    base = dict(include=("*.py",), exclude=(), thread_modules=("mod.py",))
    base.update(over)
    return LintConfig(**base)


def conc_rules_of(findings):
    return {f.rule for f in findings}


def test_jx015_unsynchronized_shared_write_seeded_and_clean(tmp_path):
    bad = """
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                self.count += 1

            def poll(self):
                return self.count
    """
    cfg = _thread_proj(tmp_path, bad)
    findings = lint_concurrency(tmp_path, cfg)
    assert conc_rules_of(findings) == {"JX015"}
    assert any("Worker.count" in f.message for f in findings)
    # Clean twin: one lock guarding BOTH sites clears the finding.
    ok = """
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._lock = threading.Lock()
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                with self._lock:
                    self.count += 1

            def poll(self):
                with self._lock:
                    return self.count
    """
    assert lint_concurrency(tmp_path, _thread_proj(tmp_path, ok)) == []


def test_jx016_lifecycle_seeded_and_clean(tmp_path):
    bad = """
        import threading

        def work():
            pass

        def dropped_handle():
            threading.Thread(target=work, daemon=True).start()

        def never_joined():
            runner = threading.Thread(target=work)
            runner.start()

        def daemon_file_io():
            def beat():
                with open("beat.jsonl", "a") as fh:
                    fh.write("x")
            t = threading.Thread(target=beat, daemon=True)
            t.start()
            t.join()
    """
    cfg = _thread_proj(tmp_path, bad)
    findings = lint_concurrency(tmp_path, cfg)
    assert conc_rules_of(findings) == {"JX016"}
    msgs = [f.message for f in findings]
    assert any("dropped at start()" in m for m in msgs)
    assert any("never join()ed" in m for m in msgs)
    assert any("try/except OSError" in m for m in msgs)
    ok = """
        import threading

        def work():
            pass

        def lifecycle_ok():
            t = threading.Thread(target=work)
            t.start()
            t.join()

        def daemon_beat_ok():
            def beat():
                try:
                    with open("beat.jsonl", "a") as fh:
                        fh.write("x")
                except OSError:
                    pass
            d = threading.Thread(target=beat, daemon=True)
            d.start()
    """
    assert lint_concurrency(tmp_path, _thread_proj(tmp_path, ok)) == []


def test_jx017_lock_order_seeded_and_clean(tmp_path):
    bad = """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def f():
            with A:
                with B:
                    pass

        def g():
            with B:
                with A:
                    pass
    """
    cfg = _thread_proj(tmp_path, bad)
    findings = lint_concurrency(tmp_path, cfg)
    assert conc_rules_of(findings) == {"JX017"}
    assert len(findings) == 1  # one finding per conflicting pair, not four
    assert "both orders" in findings[0].message
    ok = """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def f():
            with A:
                with B:
                    pass

        def g():
            with A:
                with B:
                    pass
    """
    assert lint_concurrency(tmp_path, _thread_proj(tmp_path, ok)) == []


def test_jx018_blocking_under_lock_seeded_and_clean(tmp_path):
    bad = """
        import queue
        import subprocess
        import threading

        L = threading.Lock()
        q = queue.Queue()

        def flush(cmd):
            with L:
                subprocess.check_output(cmd)

        def drain():
            with L:
                return q.get()
    """
    cfg = _thread_proj(tmp_path, bad)
    findings = lint_concurrency(tmp_path, cfg)
    assert conc_rules_of(findings) == {"JX018"}
    msgs = [f.message for f in findings]
    assert any("subprocess.check_output" in m for m in msgs)
    assert any("untimed" in m for m in msgs)
    # Clean twin: blocking work hoisted out of the critical section, and a
    # TIMED get is bounded — not deadlock fuel.
    ok = """
        import queue
        import subprocess
        import threading

        L = threading.Lock()
        q = queue.Queue()

        def flush(cmd):
            with L:
                data = list(cmd)
            subprocess.check_output(data)

        def drain():
            with L:
                return q.get(timeout=1.0)
    """
    assert lint_concurrency(tmp_path, _thread_proj(tmp_path, ok)) == []


def test_jx019_fork_and_signal_seeded_and_clean(tmp_path):
    bad_spawn = """
        import subprocess
        import threading

        def work():
            subprocess.run(["true"])

        def launch():
            t = threading.Thread(target=work, daemon=True)
            t.start()
    """
    cfg = _thread_proj(tmp_path, bad_spawn)
    findings = lint_concurrency(tmp_path, cfg)
    assert conc_rules_of(findings) == {"JX019"}
    assert any("thread context" in f.message for f in findings)
    bad_signal = """
        import signal
        import threading

        L = threading.Lock()

        def handler(signum, frame):
            with L:
                pass

        signal.signal(signal.SIGTERM, handler)
    """
    cfg = _thread_proj(tmp_path, bad_signal)
    findings = lint_concurrency(tmp_path, cfg)
    assert conc_rules_of(findings) == {"JX019"}
    assert any("signal handler" in f.message for f in findings)
    # Clean twins: subprocess from the MAIN context is the supervisor's
    # legitimate shape, and an Event.set() handler is async-signal-safe.
    ok = """
        import signal
        import subprocess
        import threading

        EV = threading.Event()

        def work():
            pass

        def launch():
            t = threading.Thread(target=work, daemon=True)
            t.start()

        def main():
            subprocess.run(["true"])

        def handler(signum, frame):
            EV.set()

        signal.signal(signal.SIGTERM, handler)
    """
    assert lint_concurrency(tmp_path, _thread_proj(tmp_path, ok)) == []


def test_jx015_suppression_comment_is_honored(tmp_path):
    src = """
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                self.count += 1  # tpusim-lint: disable=JX015 -- test reason

            def poll(self):
                return self.count
    """
    assert lint_concurrency(tmp_path, _thread_proj(tmp_path, src)) == []


def test_live_injected_race_fails_the_gate(capsys):
    """The thread-safety end-to-end on the REAL tree: an unsynchronized
    shared write injected into fleet.py source must fail `tpusim lint`
    (exit 1) against the committed EMPTY baseline, and the reverted tree
    must pass again."""
    baseline = str(REPO / ".tpusim-lint-baseline.json")
    fleet = REPO / "tpusim" / "fleet.py"
    orig = fleet.read_text()
    try:
        fleet.write_text(orig + textwrap.dedent("""

            class _InjectedScrapeCache:
                def __init__(self):
                    self.rows = 0
                    self._t = threading.Thread(target=self._pump, daemon=True)
                    self._t.start()

                def _pump(self):
                    self.rows += 1

                def snapshot(self):
                    return self.rows
        """))
        assert lint_main(["--baseline", baseline, "--quiet"]) == 1
        out = capsys.readouterr().out
        assert "JX015" in out and "_InjectedScrapeCache.rows" in out
    finally:
        fleet.write_text(orig)
    assert lint_main(["--baseline", baseline, "--quiet"]) == 0
