"""Subprocess target for the packed mid-pack SIGKILL drill
(tests/test_packed_sweep.py).

Runs the test module's reference selfish-threshold grid PACKED with
per-point piece checkpoints and a chaos plan that SIGKILLs this process at
``post_replace`` of the FIRST checkpoint save — i.e. right after one
point's partial run cursor turns durable and before any other point saves —
so the parent test can resume the pack from whatever the kill left on disk
and pin the healed rows bit-equal to an uninterrupted sequential sweep.
SIGKILL is unmaskable: if this script prints UNREACHABLE, the injection did
not fire and the test must fail.

argv: [checkpoint_dir]. The parent sets JAX_PLATFORMS=cpu and clears the
tunnel trigger env.
"""

import sys


def main() -> None:
    from tpusim.chaos import ChaosInjector, ChaosPlan, FaultSpec
    from tpusim.config import NetworkConfig, SimConfig
    from tpusim.sweep import _selfish_network, run_sweep

    # The exact _grid() of tests/test_packed_sweep.py (runs=12, batch=8:
    # two pieces per point, so the first save is genuinely mid-pack).
    pts = []
    for interval_s in (300.0, 600.0):
        for pct in (30, 40):
            net = _selfish_network(pct)
            net = NetworkConfig(miners=net.miners, block_interval_s=interval_s)
            pts.append((
                f"i{int(interval_s)}-s{pct}",
                SimConfig(network=net, runs=12, duration_ms=86_400_000,
                          batch_size=8),
            ))
    plan = ChaosPlan(faults=[
        FaultSpec(point="checkpoint.save", kind="sigkill", count=1,
                  when={"phase": "post_replace"}),
    ])
    run_sweep(
        pts, quiet=True, packed=True, engine_cache={},
        checkpoint_dir=sys.argv[1], chaos=ChaosInjector(plan),
    )
    print("UNREACHABLE: sigkill fault never fired")


if __name__ == "__main__":
    main()
