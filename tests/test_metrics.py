"""Metrics & SLO plane (tpusim.metrics): log-bucketed histograms, ledger ->
snapshot derivation with EXACT tallies, the OpenMetrics rendition + strict
validator, the stdlib scrape endpoint over a live state dir, and the
declarative SLO gate's full exit matrix (0 pass / 1 violation / 2 dead gate).

Everything here is jax-free by design — the module under test must run on a
host with no backend.
"""

from __future__ import annotations

import json
import math
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from tpusim.metrics import (
    CONTENT_TYPE,
    HIST_BASE,
    METRICS,
    LogHistogram,
    MetricsSnapshot,
    Objective,
    SloConfigError,
    collect_heartbeats,
    collect_perf_rows,
    derive_state,
    evaluate_slos,
    load_objectives,
    main,
    render_openmetrics,
    serve_metrics,
    slo_exit_code,
    slo_main,
    snapshot_from_spans,
    validate_openmetrics,
)
from tpusim.perf import perf_row
from tpusim.report import render_report
from tpusim.watch import render_watch


# ---------------------------------------------------------------------------
# Synthetic ledgers.

RID = "ridmetrics"


def _mk(span, t_start, t_mono, dur, process, parent=None, **attrs):
    row = {
        "run_id": RID, "span": span, "t_start": t_start, "t_mono": t_mono,
        "dur_s": dur, "schema": 2, "process": process, "trace_id": RID,
        "attrs": attrs,
    }
    if parent is not None:
        row["parent_span"] = parent
    return row


def _spans():
    """A handcrafted ledger with knowable tallies: 2 batch + 1 packed
    dispatch (7 runs — both span names feed the one dispatch histogram), 2
    compile, 1 save + 1 load checkpoint, 1 retry, fleet activity (2 spawns,
    1 requeue, 2 done, 1 quarantine) and a final stats span."""
    sp = [
        _mk("batch", 1000.0, 0.0, 0.5, "p0", runs=2),
        _mk("batch", 1001.0, 1.0, 1.25, "p0", runs=4),
        _mk("packed_dispatch", 1002.0, 2.0, 3.0, "p0", runs=1, dispatch=0),
        _mk("compile", 1000.0, 0.0, 2.0, "p0", key="k1"),
        _mk("compile", 1003.0, 3.0, 0.25, "p0", key="k2"),
        _mk("checkpoint_save", 1004.0, 4.0, 0.1, "p0"),
        _mk("checkpoint_load", 1005.0, 5.0, 0.05, "p0"),
        _mk("retry", 1006.0, 6.0, 0.0, "p0", attempt=1),
        _mk("fleet_spawn", 1000.0, 0.0, 0.0, "psup", worker="w000", target="a"),
        _mk("fleet_spawn", 1000.5, 0.5, 0.0, "psup", worker="w001", target="b"),
        _mk("fleet_requeue", 1002.0, 2.0, 0.0, "psup", worker="w000",
            target="a", reason="exit:-9"),
        _mk("fleet_done", 1003.0, 3.0, 0.0, "psup", worker="w001", target="b"),
        _mk("fleet_done", 1004.0, 4.0, 0.0, "psup", worker="w000", target="a"),
        _mk("fleet_quarantine", 1005.0, 5.0, 0.0, "psup", target="zz",
            failures=3, reason="exit:1"),
        _mk("stats", 1007.0, 7.0, 0.0, "p0",
            stats={"revenue": {"rel_hw_max": 0.04},
                   "orphans": {"rel_hw_max": 0.12}}),
    ]
    return sp


def _write_state(tmp_path: Path, now: float = 2000.0) -> Path:
    """A full synthetic state dir: supervisor + worker ledgers, a heartbeat
    file, a loadgen perf ledger, plus one torn line and one foreign file."""
    state = tmp_path / "state"
    (state / "workers").mkdir(parents=True)
    (state / "perf").mkdir()
    spans = _spans()
    sup = [sp for sp in spans if sp["process"] == "psup"]
    wrk = [sp for sp in spans if sp["process"] != "psup"]
    (state / "fleet.tele.jsonl").write_text(
        "".join(json.dumps(sp) + "\n" for sp in sup)
    )
    # Worker ledger ends on a TORN line (killed mid-append): tolerated,
    # contributes zero spans.
    (state / "workers" / "w000.tele.jsonl").write_text(
        "".join(json.dumps(sp) + "\n" for sp in wrk)
        + '{"span": "batch", "dur_s": 0.5'
    )
    (state / "workers" / "w000.hb.jsonl").write_text(
        json.dumps({"t": now - 30.0, "beats": 1}) + "\n"
        + json.dumps({"t": now - 3.0, "beats": 2}) + "\n"
    )
    # Foreign JSONL (sweep rows — no span key): zero spans, zero perf rows.
    (state / "rows.jsonl").write_text('{"label": "pt-a", "stale": 0.1}\n')
    (state / "perf" / "loadgen.jsonl").write_text(
        json.dumps(perf_row(
            "loadgen", "query_latency_s", 0.8, unit="s",
            samples=[0.8, 1.1, 2.0], shape={"queries": 3, "concurrency": 2},
        )) + "\n"
        + json.dumps(perf_row(
            "loadgen", "compiles_per_query", 0.0, unit="count",
            shape={"queries": 3},
        )) + "\n"
    )
    return state


# ---------------------------------------------------------------------------
# LogHistogram: exact counts, merge identity, bounded quantile error.


def test_histogram_counts_exact_and_merge_identity():
    values = [0.013, 0.4, 0.5, 1.7, 3.14, 9.9, 42.0, 123.4, 0.0, -1.0]
    one = LogHistogram()
    a, b = LogHistogram(), LogHistogram()
    for i, v in enumerate(values):
        one.observe(v)
        (a if i % 2 == 0 else b).observe(v)
    a.merge(b)
    assert one.count == a.count == len(values)
    assert one.zero == a.zero == 2  # 0.0 and -1.0
    assert one.counts == a.counts  # per-bucket EXACT equality
    assert one.sum == pytest.approx(a.sum)
    # Cumulative buckets tally back to the exact count.
    assert one.buckets()[-1][1] == len(values)


def test_histogram_quantile_error_bound():
    values = sorted([0.013, 0.4, 0.5, 1.7, 3.14, 9.9, 42.0, 123.4])
    h = LogHistogram()
    for v in values:
        h.observe(v)
    for q in (0.5, 0.95, 0.99, 1.0):
        rank = max(1, math.ceil(q * len(values)))
        true = values[rank - 1]
        est = h.quantile(q)
        # Upper bound of the sample's bucket: >= the true sample, and over
        # by at most HIST_BASE - 1 relative (the documented bucket error).
        assert est >= true * (1 - 1e-9)
        assert est <= true * HIST_BASE * (1 + 1e-9)


def test_histogram_edge_quantiles():
    h = LogHistogram()
    assert h.quantile(0.5) is None  # empty => no-data, never a fake zero
    h.observe(0.0)
    assert h.quantile(0.5) == 0.0  # zero bucket
    # An exact power of the base stays in its own bucket (log() noise must
    # not push base**i into bucket i+1).
    h2 = LogHistogram()
    h2.observe(HIST_BASE ** 3)
    assert h2.quantile(1.0) == pytest.approx(HIST_BASE ** 3, rel=1e-12)


def test_snapshot_rejects_unregistered_names():
    snap = MetricsSnapshot()
    with pytest.raises(ValueError, match="not a registered"):
        snap.counter_add("tpusim_typo", 1)
    with pytest.raises(ValueError, match="not a registered"):
        snap.observe("tpusim_spans", 1.0)  # registered, but not a histogram


# ---------------------------------------------------------------------------
# Derivation: histogram tallies pinned EXACTLY to independent span tallies.


def test_snapshot_tallies_equal_independent_span_tallies():
    spans = _spans()
    snap = snapshot_from_spans(spans, now=2000.0)

    # Independent tallies straight off the raw ledger rows.
    by_name: dict[str, int] = {}
    for sp in spans:
        by_name[sp["span"]] = by_name.get(sp["span"], 0) + 1

    assert snap.counters["tpusim_spans"][()] == len(spans)
    dispatches = by_name["batch"] + by_name["packed_dispatch"]
    assert snap.merged_hist("tpusim_batch_latency_seconds").count == dispatches
    assert snap.merged_hist("tpusim_compile_seconds").count == by_name["compile"]
    saves = snap.merged_hist("tpusim_checkpoint_seconds", (("op", "save"),))
    loads = snap.merged_hist("tpusim_checkpoint_seconds", (("op", "load"),))
    assert saves.count == by_name["checkpoint_save"]
    assert loads.count == by_name["checkpoint_load"]
    assert snap.counters["tpusim_retries"][()] == by_name["retry"]
    assert snap.counters["tpusim_fleet_spawns"][()] == by_name["fleet_spawn"]
    assert snap.counters["tpusim_fleet_requeues"][()] == by_name["fleet_requeue"]
    assert snap.counters["tpusim_fleet_quarantines"][()] == by_name["fleet_quarantine"]
    # Runs counter sums the batch attrs; sum tracks durations exactly.
    assert snap.counters["tpusim_runs"][()] == 2 + 4 + 1
    batch = snap.merged_hist("tpusim_batch_latency_seconds")
    assert batch.sum == pytest.approx(0.5 + 1.25 + 3.0)
    # Requeue rate: 1 requeue / 2 points done (fleet_done fallback).
    assert snap.gauges["tpusim_requeue_rate"][()] == pytest.approx(0.5)
    # Newest stats span -> per-stat gauges.
    rel = snap.gauges["tpusim_stat_rel_halfwidth"]
    assert rel[(("stat", "revenue"),)] == pytest.approx(0.04)
    assert rel[(("stat", "orphans"),)] == pytest.approx(0.12)


def test_snapshot_folds_perf_rows_and_heartbeats():
    rows = [
        perf_row("loadgen", "query_latency_s", 0.8, unit="s",
                 samples=[0.8, 1.1, 2.0]),
        perf_row("loadgen", "compiles_per_query", 0.0, unit="count"),
        perf_row("bench", "query_latency_s", 9.0, unit="s"),  # foreign scenario
    ]
    snap = snapshot_from_spans(
        [], perf_rows=rows, heartbeats=[("w000", 1997.0)], now=2000.0
    )
    q = snap.merged_hist("tpusim_query_latency_seconds")
    assert q.count == 3  # EXACTLY the loadgen samples, never the bench row
    assert q.sum == pytest.approx(0.8 + 1.1 + 2.0)
    assert snap.gauges["tpusim_compiles_per_query"][()] == 0.0
    age = snap.gauges["tpusim_heartbeat_age_seconds"][(("worker", "w000"),)]
    assert age == pytest.approx(3.0)


def test_snapshot_tolerates_foreign_and_partial_spans():
    spans = [
        {"span": "batch"},  # no dur_s, no attrs
        {"span": "batch", "dur_s": None, "attrs": None},
        {"span": "mystery", "attrs": {"x": 1}},
        {"span": "stats", "attrs": {}},  # stats span with no per-stat dict
    ]
    snap = snapshot_from_spans(spans, now=0.0)
    assert snap.merged_hist("tpusim_batch_latency_seconds").count == 2
    assert "tpusim_stat_rel_halfwidth" not in snap.gauges


# ---------------------------------------------------------------------------
# State-dir collectors + derive_state: torn lines, foreign files, missing dir.


def test_derive_state_full_dir_exact_cross_check(tmp_path):
    state = _write_state(tmp_path)
    snap = derive_state(state, now=2000.0)
    # Cross-check against an INDEPENDENT tally of the ledger lines.
    batch_lines = compile_lines = span_lines = 0
    for path in state.rglob("*.tele.jsonl"):
        for line in path.read_text().splitlines():
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # the torn line
            span_lines += 1
            batch_lines += row["span"] in ("batch", "packed_dispatch")
            compile_lines += row["span"] == "compile"
    assert snap.counters["tpusim_spans"][()] == span_lines
    assert snap.merged_hist("tpusim_batch_latency_seconds").count == batch_lines
    assert snap.merged_hist("tpusim_compile_seconds").count == compile_lines
    # Perf ledger folded in; heartbeat age from the NEWEST beat.
    assert snap.merged_hist("tpusim_query_latency_seconds").count == 3
    assert snap.gauges["tpusim_compiles_per_query"][()] == 0.0
    age = snap.gauges["tpusim_heartbeat_age_seconds"][(("worker", "w000"),)]
    assert age == pytest.approx(3.0)
    assert snap.meta["source"] == str(state)


def test_collectors_tolerate_torn_and_missing(tmp_path):
    assert collect_heartbeats(tmp_path / "nope") == []
    assert collect_perf_rows(tmp_path / "nope") == []
    d = tmp_path / "d"
    d.mkdir()
    (d / "w.hb.jsonl").write_text('{"t": 10.0}\n{"t": 12.0\n{"beats": 3}\n')
    assert collect_heartbeats(d) == [("w", 10.0)]  # torn + t-less skipped
    (d / "mixed.jsonl").write_text(
        json.dumps(perf_row("loadgen", "query_latency_s", 1.0, unit="s")) + "\n"
        + '{"schema": 1, "scenario": "x"}\n'  # schema 1 but invalid row
        + json.dumps(_mk("batch", 0.0, 0.0, 1.0, "p0")) + "\n"  # telemetry
        + "{torn"
    )
    rows = collect_perf_rows(d)
    assert len(rows) == 1 and rows[0]["metric"] == "query_latency_s"


def test_derive_state_missing_path_is_empty_not_error(tmp_path):
    snap = derive_state(tmp_path / "never_created")
    assert snap.counters["tpusim_spans"][()] == 0
    # And the empty snapshot still renders a valid exposition.
    assert validate_openmetrics(render_openmetrics(snap)) >= 1


# ---------------------------------------------------------------------------
# OpenMetrics rendition + strict validator.


def test_render_openmetrics_shape(tmp_path):
    snap = derive_state(_write_state(tmp_path), now=2000.0)
    text = render_openmetrics(snap)
    assert text.splitlines()[-1] == "# EOF"
    for name, kind, _ in METRICS:
        assert f"# TYPE {name} {kind}" in text
    assert f"tpusim_spans_total {snap.counters['tpusim_spans'][()]:g}" in text
    # Histogram triple with +Inf == _count.
    assert 'tpusim_batch_latency_seconds_bucket{le="+Inf"} 3' in text
    assert "tpusim_batch_latency_seconds_count 3" in text
    assert 'tpusim_checkpoint_seconds_bucket{op="save",le="+Inf"} 1' in text
    assert validate_openmetrics(text) > 0


def test_validator_rejects_malformed_expositions():
    ok = "# TYPE m counter\nm_total 1\n# EOF"
    assert validate_openmetrics(ok) == 1
    with pytest.raises(ValueError, match="EOF"):
        validate_openmetrics("# TYPE m counter\nm_total 1")
    with pytest.raises(ValueError, match="undeclared"):
        validate_openmetrics("other_total 1\n# EOF")
    with pytest.raises(ValueError, match="_total"):
        validate_openmetrics("# TYPE m counter\nm 1\n# EOF")
    with pytest.raises(ValueError, match="bare-named"):
        validate_openmetrics("# TYPE g gauge\ng_total 1\n# EOF")
    with pytest.raises(ValueError, match="non-cumulative"):
        validate_openmetrics(
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\n'
            'h_bucket{le="+Inf"} 5\nh_sum 4\nh_count 5\n# EOF'
        )
    with pytest.raises(ValueError, match="!= _count"):
        validate_openmetrics(
            "# TYPE h histogram\n"
            'h_bucket{le="+Inf"} 5\nh_sum 4\nh_count 4\n# EOF'
        )
    with pytest.raises(ValueError, match="missing"):
        validate_openmetrics("# TYPE h histogram\nh_count 4\n# EOF")


# ---------------------------------------------------------------------------
# Scrape endpoint: live re-reads, content types, route matrix.


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), resp.read().decode()


def test_endpoint_routes_against_live_state_dir(tmp_path):
    state = _write_state(tmp_path)
    objectives = [Objective(metric="tpusim_spans", op=">=", threshold=1.0)]
    server = serve_metrics(state, port=0, objectives=objectives)
    host, port = server.server_address[:2]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        base = f"http://{host}:{port}"
        status, ctype, body = _get(f"{base}/metrics")
        assert status == 200 and ctype == CONTENT_TYPE
        assert validate_openmetrics(body) > 0
        n0 = int(body.split("tpusim_spans_total ", 1)[1].split("\n", 1)[0])

        # The dir is LIVE: append a span mid-serve, the next scrape sees it
        # (every request re-derives; torn/appended lines never need locks).
        with (state / "fleet.tele.jsonl").open("a") as fh:
            fh.write(json.dumps(_mk("retry", 1100.0, 100.0, 0.0, "psup")) + "\n")
        _, _, body2 = _get(f"{base}/metrics")
        n1 = int(body2.split("tpusim_spans_total ", 1)[1].split("\n", 1)[0])
        assert n1 == n0 + 1

        status, ctype, body = _get(f"{base}/healthz")
        health = json.loads(body)
        assert status == 200 and ctype == "application/json"
        assert health["ok"] and health["ready"] and health["spans"] == n1

        status, _, body = _get(f"{base}/api/summary")
        summary = json.loads(body)
        assert status == 200
        assert summary["counters"]["tpusim_spans"] == n1
        assert summary["histograms"]["tpusim_batch_latency_seconds"]["count"] == 3
        assert summary["slo"][0]["status"] == "pass"

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/nope")
        assert ei.value.code == 404
    finally:
        server.shutdown()
        server.server_close()


def test_endpoint_tolerates_missing_state_dir(tmp_path):
    server = serve_metrics(tmp_path / "not_yet", port=0)
    host, port = server.server_address[:2]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        status, ctype, body = _get(f"http://{host}:{port}/metrics")
        assert status == 200 and validate_openmetrics(body) >= 1
        _, _, body = _get(f"http://{host}:{port}/healthz")
        health = json.loads(body)
        assert health["ok"] and not health["ready"] and not health["state_dir_exists"]
    finally:
        server.shutdown()
        server.server_close()


def test_scrape_under_concurrent_torn_writes(tmp_path, thread_guard):
    """The tolerant-re-read claim exercised under real concurrency: several
    clients hammer /metrics and /healthz while a writer keeps appending to
    the live state dir, leaving a torn (newline-less) tail after every row
    so successive appends glue valid JSON onto garbage — exactly what a
    worker killed mid-append produces. Every response must be a parseable
    200; the server must never 500 or serve a half-derived snapshot."""
    state = _write_state(tmp_path)
    server = serve_metrics(state, port=0)
    host, port = server.server_address[:2]
    srv = threading.Thread(target=server.serve_forever, daemon=True)
    srv.start()
    base = f"http://{host}:{port}"
    stop = threading.Event()
    errors: list = []

    def writer():
        led = state / "fleet.tele.jsonl"
        i = 0
        while not stop.is_set():
            with led.open("a") as fh:
                fh.write(json.dumps(
                    _mk("retry", 1200.0 + i, 200.0 + i, 0.0, "psup")) + "\n")
                fh.write('{"span": "batch", "t_start": 12')  # torn tail
            i += 1
            time.sleep(0.001)

    def scraper(k):
        try:
            for j in range(15):
                status, ctype, body = _get(f"{base}/metrics")
                if status != 200 or ctype != CONTENT_TYPE:
                    errors.append((k, j, "metrics", status, ctype))
                    return
                validate_openmetrics(body)  # raises on a torn exposition
                status, _, body = _get(f"{base}/healthz")
                if status != 200 or not json.loads(body)["ok"]:
                    errors.append((k, j, "healthz", status, body[:200]))
                    return
        except Exception as e:  # noqa: BLE001 — an HTTPError(500) lands here
            errors.append((k, "exception", repr(e)))

    w = threading.Thread(target=writer, name="torn-writer")
    scrapers = [
        threading.Thread(target=scraper, args=(k,), name=f"scraper-{k}")
        for k in range(4)
    ]
    w.start()
    try:
        for t in scrapers:
            t.start()
        for t in scrapers:
            t.join(timeout=120)
    finally:
        stop.set()
        w.join(timeout=30)
        server.shutdown()
        server.server_close()
    assert not errors, errors
    assert not w.is_alive() and not any(t.is_alive() for t in scrapers)


def test_metrics_cli_export_and_once_smoke(tmp_path, capsys):
    state = _write_state(tmp_path)
    out = tmp_path / "artifacts" / "m.prom"
    assert main(["export", str(state), "--out", str(out)]) == 0
    assert validate_openmetrics(out.read_text()) > 0
    assert validate_openmetrics(capsys.readouterr().out) > 0
    assert main(["export", str(tmp_path / "nope")]) == 2

    # --once: bind ephemeral, self-scrape /metrics + /healthz, validate, exit.
    assert main(["serve", "--state-dir", str(state), "--port", "0", "--once"]) == 0
    once = capsys.readouterr().out
    assert "--once scrape OK" in once and "# EOF" in once


# ---------------------------------------------------------------------------
# SLO engine: config loading, evaluation semantics, the full exit matrix.


def _snap(tmp_path) -> MetricsSnapshot:
    return derive_state(_write_state(tmp_path), now=2000.0)


def test_load_objectives_json_and_toml(tmp_path):
    cfg = tmp_path / "slo.json"
    cfg.write_text(json.dumps({"objectives": [
        {"name": "b99", "metric": "tpusim_batch_latency_seconds",
         "stat": "p99", "op": "<=", "threshold": 5.0},
    ]}))
    (obj,) = load_objectives(cfg)
    assert obj.name == "b99" and obj.stat == "p99" and obj.threshold == 5.0

    from tpusim.lint.config import _toml

    if _toml is None:
        pytest.skip("no TOML parser in this environment")
    toml_cfg = tmp_path / "slo.toml"
    toml_cfg.write_text(
        '[[tool.tpusim-slo.objectives]]\n'
        'name = "spans"\nmetric = "tpusim_spans"\nop = ">="\nthreshold = 1.0\n'
    )
    (obj,) = load_objectives(toml_cfg)
    assert obj.metric == "tpusim_spans" and obj.op == ">="


def test_repo_pyproject_objectives_load_and_reference_registry():
    # The committed [tool.tpusim-slo] block must parse and only reference
    # registered families (the JX014 contract, checked live here).
    names = {name for name, _, _ in METRICS}
    objectives = load_objectives()
    assert objectives and all(o.metric in names for o in objectives)


def test_load_objectives_structural_errors(tmp_path):
    with pytest.raises(SloConfigError, match="does not exist"):
        load_objectives(tmp_path / "missing.json")
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(SloConfigError, match="unparseable"):
        load_objectives(bad)
    empty = tmp_path / "empty.json"
    empty.write_text('{"objectives": []}')
    with pytest.raises(SloConfigError, match="dead gate"):
        load_objectives(empty)
    shapes = tmp_path / "shapes.json"
    for row in ({"metric": "m", "op": "<<", "threshold": 1},
                {"metric": "m", "threshold": "x"},
                {"metric": "m", "threshold": 1, "stat": "p42"},
                {"op": "<=", "threshold": 1}):
        shapes.write_text(json.dumps({"objectives": [row]}))
        with pytest.raises(SloConfigError):
            load_objectives(shapes)


def test_evaluate_slos_stats_and_worst_side_gauges(tmp_path):
    snap = _snap(tmp_path)
    results = evaluate_slos([
        Objective(metric="tpusim_batch_latency_seconds", stat="count",
                  op="==", threshold=3.0),
        Objective(metric="tpusim_batch_latency_seconds", stat="mean",
                  op="<=", threshold=2.0),
        Objective(metric="tpusim_retries", op="<=", threshold=1.0),
    ], snap)
    assert [r["status"] for r in results] == ["pass", "pass", "pass"]
    # Gauge with several labeled series aggregates to the WORST side: a
    # passing aggregate must imply every series passes.
    wide = Objective(metric="tpusim_stat_rel_halfwidth", op="<=", threshold=0.05)
    (r,) = evaluate_slos([wide], snap)
    assert r["status"] == "violation" and r["observed"] == pytest.approx(0.12)
    narrow = Objective(metric="tpusim_stat_rel_halfwidth", op="<=",
                       threshold=0.05, labels=(("stat", "revenue"),))
    (r,) = evaluate_slos([narrow], snap)
    assert r["status"] == "pass" and r["observed"] == pytest.approx(0.04)


def test_slo_exit_matrix(tmp_path):
    snap = _snap(tmp_path)
    passing = [Objective(metric="tpusim_spans", op=">=", threshold=1.0)]
    violating = [Objective(metric="tpusim_spans", op="<=", threshold=0.0)]
    unknown = [Objective(metric="tpusim_not_a_metric", op="<=", threshold=1.0)]
    assert slo_exit_code(evaluate_slos(passing, snap)) == 0
    assert slo_exit_code(evaluate_slos(violating, snap)) == 1
    # Structural dominates violation: an unknown metric alongside a
    # violation still exits 2, never 1.
    assert slo_exit_code(evaluate_slos(unknown + violating, snap)) == 2
    (r,) = evaluate_slos(unknown, snap)
    assert r["status"] == "no-data" and "registry" in r["reason"]
    # An EMPTY snapshot can never pass green: every objective is no-data.
    empty = snapshot_from_spans([], now=0.0)
    assert slo_exit_code(evaluate_slos(
        [Objective(metric="tpusim_batch_latency_seconds", stat="p99",
                   op="<=", threshold=1e9)], empty)) == 2
    # No objectives at all is itself a dead gate.
    assert slo_exit_code([]) == 2


def test_slo_check_cli_exit_matrix(tmp_path, capsys):
    state = _write_state(tmp_path)
    cfg = tmp_path / "slo.json"
    cfg.write_text(json.dumps({"objectives": [
        {"name": "spans-present", "metric": "tpusim_spans",
         "op": ">=", "threshold": 1.0},
    ]}))
    assert slo_main(["check", str(state), "--config", str(cfg)]) == 0
    out = capsys.readouterr().out
    assert "spans-present" in out and "PASS" in out

    cfg.write_text(json.dumps({"objectives": [
        {"name": "impossible", "metric": "tpusim_spans",
         "op": "<=", "threshold": 0.0},
    ]}))
    assert slo_main(["check", str(state), "--config", str(cfg)]) == 1
    assert "violation" in capsys.readouterr().err

    # Dead gates, all exit 2: missing state dir, empty-but-existing state
    # dir (no-data), unparseable config.
    assert slo_main(["check", str(tmp_path / "gone"), "--config", str(cfg)]) == 2
    empty_state = tmp_path / "empty_state"
    empty_state.mkdir()
    cfg.write_text(json.dumps({"objectives": [
        {"metric": "tpusim_batch_latency_seconds", "stat": "p99",
         "op": "<=", "threshold": 1e9},
    ]}))
    assert slo_main(["check", str(empty_state), "--config", str(cfg)]) == 2
    assert "never pass green" in capsys.readouterr().err
    bad = tmp_path / "bad.json"
    bad.write_text("{")
    assert slo_main(["check", str(state), "--config", str(bad)]) == 2


# ---------------------------------------------------------------------------
# Dashboard panels: report and watch render the SAME evaluator's rows.


def test_report_and_watch_slo_panels(tmp_path):
    spans = _spans()
    objectives = [
        Objective(name="spans-present", metric="tpusim_spans",
                  op=">=", threshold=1.0),
        Objective(name="no-retries", metric="tpusim_retries",
                  op="<=", threshold=0.0),
    ]
    report = render_report(spans, slo=objectives)
    assert "SLO status" in report
    assert "spans-present" in report and "VIOLATION" in report
    watch = render_watch(spans, "src", now=2000.0, slo=objectives)
    assert "SLO status (VIOLATION)" in watch and "no-retries" in watch
    # Without objectives, no panel.
    assert "SLO status" not in render_report(spans)
