"""Bit-compatibility contract for the xoroshiro128++ generator.

Three articulations of the reference generator (reference xoroshiro128++.h:
1-40; Blackman & Vigna's public-domain algorithm) must be mutually bit-exact:

  * ``tpusim.xoroshiro.reference_words`` — pure numpy/int host model;
  * ``tpusim.xoroshiro.next_words``      — the vectorized 32-bit-limb JAX
    implementation (the form a TPU can execute: no 64-bit ALU);
  * ``simcore_rng_words``                — the native C++ backend's generator.
"""

from __future__ import annotations

import ctypes
import shutil

import numpy as np
import jax
import pytest

from tpusim.xoroshiro import exporand, next_uniform, next_words, reference_words, seed_streams

SEEDS = [0, 1, 42, 0xDEADBEEF, 2**63, 2**64 - 1]
N_WORDS = 64


def _jax_words(seeds: list[int], n: int) -> np.ndarray:
    state = seed_streams(np.array(seeds, dtype=np.uint64))

    def step(state, _):
        state, hi, lo = next_words(state)
        return state, (hi, lo)

    _, (hi, lo) = jax.lax.scan(step, state, None, length=n)
    return (
        np.asarray(hi, dtype=np.uint64) << np.uint64(32)
    ) | np.asarray(lo, dtype=np.uint64)  # [n, len(seeds)]


def test_jax_limbs_match_host_model():
    got = _jax_words(SEEDS, N_WORDS)
    for j, seed in enumerate(SEEDS):
        want = reference_words(seed, N_WORDS)
        np.testing.assert_array_equal(got[:, j], want, err_msg=f"seed {seed}")


@pytest.mark.skipif(shutil.which("make") is None, reason="native toolchain unavailable")
def test_native_generator_matches():
    from tpusim.backend.cpp import NativeBuildError, _load

    try:
        lib = _load()
    except NativeBuildError as e:  # pragma: no cover - toolchain-specific
        pytest.skip(f"native build failed: {e}")
    lib.simcore_rng_words.restype = ctypes.c_int
    lib.simcore_rng_words.argtypes = [
        ctypes.c_uint64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint32),
    ]
    for seed in SEEDS:
        hi = np.zeros(N_WORDS, np.uint32)
        lo = np.zeros(N_WORDS, np.uint32)
        rc = lib.simcore_rng_words(
            seed,
            N_WORDS,
            hi.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            lo.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
        assert rc == 0
        got = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
        np.testing.assert_array_equal(got, reference_words(seed, N_WORDS), err_msg=f"seed {seed}")


def test_exporand_moments():
    """Exponential draws from the bit-compat generator have the right first
    two moments (the programmatic form of reference test.cpp:191-208)."""
    n_streams, n_draws, mean = 512, 256, 600.0
    state = seed_streams(np.arange(n_streams, dtype=np.uint64) + np.uint64(99))

    def step(state, _):
        state, x = exporand(state, mean)
        return state, x

    _, draws = jax.lax.scan(step, state, None, length=n_draws)
    flat = np.asarray(draws).ravel()
    n = flat.size
    se = mean / np.sqrt(n)
    assert abs(flat.mean() - mean) < 5 * se
    assert abs(flat.std() - mean) < 6 * se


def test_next_uniform_float64_path_is_reference_exact():
    """With x64 enabled, next_uniform must reproduce the reference's exact
    top-53-bit double mapping (reference xoroshiro128++.h:17-20)."""
    from tpusim.compat import enable_x64

    with enable_x64(True):
        state = seed_streams(np.array(SEEDS, dtype=np.uint64))
        _, u = jax.jit(next_uniform)(state)
        u = np.asarray(u, dtype=np.float64)
    for j, seed in enumerate(SEEDS):
        w = int(reference_words(seed, 1)[0])
        want = (w >> 11) * 2.0**-53
        assert u[j] == want, (seed, u[j], want)


def test_streams_are_decorrelated():
    """Adjacent seeds give unrelated streams (splitmix64 seeding, not raw)."""
    words = _jax_words([7, 8], 512)
    a = (words[:, 0] >> np.uint64(32)).astype(np.float64)
    b = (words[:, 1] >> np.uint64(32)).astype(np.float64)
    corr = np.corrcoef(a, b)[0, 1]
    assert abs(corr) < 0.15
