"""Subprocess target for the checkpoint SIGKILL drills (tests/test_chaos.py).

Runs a small checkpointed simulation with a chaos plan that SIGKILLs this
process at one named boundary of the FIRST checkpoint save — ``begin``
(before the tmp write), ``pre_replace`` (tmp written + fsynced, rename not
yet done) or ``post_replace`` (checkpoint durable) — so the parent test can
resume from whatever the kill left on disk and pin the recovered statistics
bit-equal to a fault-free run. SIGKILL is unmaskable: if this script prints
UNREACHABLE, the injection did not fire and the test must fail.

argv: [config_json, phase, checkpoint_path]. The parent sets
JAX_PLATFORMS=cpu and clears the tunnel trigger env.
"""

import sys


def main() -> None:
    from tpusim.chaos import ChaosInjector, ChaosPlan, FaultSpec
    from tpusim.config import SimConfig
    from tpusim.runner import run_simulation_config

    config = SimConfig.from_json(sys.argv[1])
    phase = sys.argv[2]
    plan = ChaosPlan(faults=[
        FaultSpec(point="checkpoint.save", kind="sigkill", count=1,
                  when={"phase": phase}),
    ])
    run_simulation_config(
        config, use_all_devices=False, checkpoint_path=sys.argv[3],
        chaos=ChaosInjector(plan),
    )
    print("UNREACHABLE: sigkill fault never fired")


if __name__ == "__main__":
    main()
