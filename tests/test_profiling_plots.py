"""Profiling telemetry and the analysis plots (SURVEY.md §5 subsystems)."""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from tpusim.config import SimConfig, default_network
from tpusim.profiling import Profiler
from tpusim.runner import run_simulation_config


@pytest.fixture(scope="module")
def small_config():
    return SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=5 * 86_400_000,
        runs=24,
        batch_size=8,
        seed=3,
    )


def test_profiler_report(small_config):
    profiler = Profiler()
    run_simulation_config(small_config, profiler=profiler, use_all_devices=False)
    rep = profiler.report(small_config.duration_ms, small_config.network.block_interval_s)
    assert rep["batches"] == 3
    assert rep["total_runs"] == 24
    assert rep["total_s"] > 0
    assert rep["steady_sim_years_per_s"] > 0
    assert rep["steady_events_per_s"] > 0
    # First batch pays compilation. Structural check only: asserting a
    # wall-clock ratio against the steady batches is flaky on loaded CI.
    assert rep["first_batch_s"] > 0
    assert rep["first_batch_s"] <= rep["total_s"]
    json.loads(profiler.report_json(small_config.duration_ms, 600.0))


def test_profiler_trace_writes_files(tmp_path, small_config):
    profiler = Profiler(trace_dir=str(tmp_path / "trace"))
    with profiler.trace():
        run_simulation_config(small_config, profiler=profiler, use_all_devices=False)
    files = list((tmp_path / "trace").rglob("*"))
    assert files, "jax.profiler.trace produced no output"


def test_cli_profile_flag(capsys, tmp_path):
    from tpusim.cli import main

    rc = main(
        [
            "--runs", "4", "--duration-ms", "86400000", "--batch-size", "4",
            "--quiet", "--profile",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "[profile]" in out
    assert "steady_sim_years_per_s" in out


def test_plots_write_pngs(tmp_path):
    from tpusim.analysis.plots import plot_benefits, plot_stale_rates

    p1 = tmp_path / "stale.png"
    p2 = tmp_path / "bene.png"
    plot_stale_rates(points=12, out_path=p1, simulated={1.0: [0.01] * 10})
    plot_benefits(points=12, out_path=p2)
    assert p1.stat().st_size > 1000
    assert p2.stat().st_size > 1000


def test_plots_cli(tmp_path):
    from tpusim.analysis.plots import main

    rc = main(["--out-dir", str(tmp_path), "--prop-hi-s", "20"])
    assert rc == 0
    assert (tmp_path / "stale_rates.png").exists()
    assert (tmp_path / "net_benefits.png").exists()


def test_simulate_overlay_matches_oracle():
    from tpusim.analysis.oracle import analytical_stale_rates
    from tpusim.analysis.plots import simulate_overlay

    hashrates = (0.5, 0.3, 0.2)
    sim = simulate_overlay(hashrates, [10.0], runs=64, duration_days=20.0, seed=5)
    want = analytical_stale_rates(hashrates, 10.0)
    for got, exp in zip(sim[10.0], want):
        assert abs(got - exp) < max(0.5 * exp, 0.004), (got, exp)


def test_selfish_revenue_oracle_crossing():
    from tpusim.analysis.oracle import selfish_relative_revenue as rev

    # "Majority is not Enough" eq. 8 at gamma=0: revenue crosses hashrate
    # exactly at alpha = 1/3; below it selfish mining loses money.
    assert abs(rev(1 / 3) - 1 / 3) < 1e-12
    assert rev(0.25) < 0.25 and rev(0.30) < 0.30
    assert rev(0.35) > 0.35 and rev(0.45) > 0.45
    # gamma=0.5 lowers the crossing (attacker wins some races for free).
    assert rev(0.30, gamma=0.5) > rev(0.30, gamma=0.0)
    with pytest.raises(ValueError):
        rev(0.5)


def test_selfish_crossing_plot_and_loader(tmp_path):
    from tpusim.analysis.plots import load_selfish_grid_points, plot_selfish_crossing

    rows = [
        # max-runs preference: the 2^20 row must win over the smoke row.
        {"runs": 1 << 20, "backend": "tpu",
         "miners": [{"selfish": True, "hashrate_pct": 25,
                     "blocks_share_mean": 0.156}]},
        {"runs": 1 << 14, "backend": "tpu",
         "miners": [{"selfish": True, "hashrate_pct": 25,
                     "blocks_share_mean": 0.2}]},
        {"runs": 1 << 20, "backend": "cpp",
         "miners": [{"selfish": True, "hashrate_pct": 37,
                     "blocks_share_mean": 0.3835}]},
        # A selfish-threshold grid row (different block interval — a
        # different experiment) must NOT leak into the crossing figure.
        {"runs": 1 << 20, "backend": "cpp", "point": "interval-150s-selfish-35pct",
         "miners": [{"selfish": True, "hashrate_pct": 35,
                     "blocks_share_mean": 0.336}]},
        # Valid JSON but truncated mid-schema: tolerated, not a crash.
        {"miners": [{"selfish": True, "hashrate_pct": 25}]},
        "not json at all",  # tolerated, like the sweep --resume scanner
    ]
    path = tmp_path / "sweep_selfish_hashrate_full_x.jsonl"
    path.write_text("\n".join(
        r if isinstance(r, str) else json.dumps(r) for r in rows) + "\n")
    pts = load_selfish_grid_points([path])
    assert {(p["backend"], p["selfish_hashrate_frac"], round(p["selfish_share"], 4))
            for p in pts} == {("tpu", 0.25, 0.156), ("cpp", 0.37, 0.3835)}

    png = tmp_path / "crossing.png"
    plot_selfish_crossing(pts, out_path=png)
    assert png.stat().st_size > 1000


def test_plots_cli_selfish_grid(tmp_path):
    from tpusim.analysis.plots import main

    path = tmp_path / "grid.jsonl"
    path.write_text(json.dumps(
        {"runs": 64, "backend": "tpu",
         "miners": [{"selfish": True, "hashrate_pct": 40,
                     "blocks_share_mean": 0.46}]}) + "\n")
    rc = main(["--out-dir", str(tmp_path), "--prop-hi-s", "20",
               "--selfish-grid", str(path)])
    assert rc == 0
    assert (tmp_path / "selfish_crossing.png").exists()
    # Empty/unusable grid files fail loudly instead of silently omitting
    # the figure.
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert main(["--out-dir", str(tmp_path), "--selfish-grid", str(empty)]) == 2


def test_hetero_oracle_matches_committed_simulation():
    # The heterogeneous-propagation generalization of the oracle must track
    # the simulated 32-miner log-spaced roster (BASELINE configs[3]); the
    # committed native artifact spans stale rates 0.02%-10% and the oracle
    # sits within ~10% relative everywhere (regression: the r5 pre-fix form
    # summed competitors' windows and predicted a near-uniform ~0.6%).
    from tpusim.analysis.oracle import analytical_stale_rates
    from tpusim.sweep import baseline_sweeps

    art = (Path(__file__).resolve().parent.parent / "artifacts"
           / "sweep_hetero32_cpp_scale0.0039.jsonl")
    if not art.exists():
        pytest.skip("hetero32 artifact not present")
    # Same selection rule as the plots CLI: the max-runs hetero32-named row
    # (the file may accumulate smoke rows via --resume re-measurement).
    row = None
    for line in art.read_text().splitlines():
        r = json.loads(line)
        if r.get("point") == "hetero32" and (row is None or r["runs"] > row["runs"]):
            row = r
    assert row is not None
    ((_, cfg),) = baseline_sweeps()["hetero32"]()
    hr = [m.hashrate_pct / 100 for m in cfg.network.miners]
    props = [m.propagation_ms / 1000 for m in cfg.network.miners]
    want = analytical_stale_rates(hr, props, cfg.network.block_interval_s)
    assert len(row["miners"]) == len(want)
    for m, w in zip(row["miners"], want):
        assert abs(m["stale_rate_mean"] - w) / w < 0.25, (m, w)


def test_hetero_validation_plot(tmp_path):
    from tpusim.analysis.plots import plot_hetero_validation

    png = tmp_path / "hetero.png"
    plot_hetero_validation(
        hashrates=[0.5, 0.3, 0.2],
        props_ms=[100.0, 1000.0, 10_000.0],
        measured=[1e-4, 1e-3, 1e-2],
        runs=64,
        out_path=png,
    )
    assert png.stat().st_size > 1000


def test_plots_cli_hetero_grid(tmp_path):
    from tpusim.analysis.plots import main

    art = (Path(__file__).resolve().parent.parent / "artifacts"
           / "sweep_hetero32_cpp_scale0.0039.jsonl")
    if not art.exists():
        pytest.skip("hetero32 artifact not present")
    rc = main(["--out-dir", str(tmp_path), "--prop-hi-s", "20",
               "--hetero-grid", str(art)])
    assert rc == 0
    assert (tmp_path / "hetero32_validation.png").exists()
    assert main(["--out-dir", str(tmp_path),
                 "--hetero-grid", str(tmp_path / "nope.jsonl")]) == 2
