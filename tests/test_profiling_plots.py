"""Profiling telemetry and the analysis plots (SURVEY.md §5 subsystems)."""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from tpusim.config import SimConfig, default_network
from tpusim.profiling import Profiler
from tpusim.runner import run_simulation_config


@pytest.fixture(scope="module")
def small_config():
    return SimConfig(
        network=default_network(propagation_ms=1000),
        duration_ms=5 * 86_400_000,
        runs=24,
        batch_size=8,
        seed=3,
    )


def test_profiler_report(small_config):
    profiler = Profiler()
    run_simulation_config(small_config, profiler=profiler, use_all_devices=False)
    rep = profiler.report(small_config.duration_ms, small_config.network.block_interval_s)
    assert rep["batches"] == 3
    assert rep["total_runs"] == 24
    assert rep["total_s"] > 0
    assert rep["steady_sim_years_per_s"] > 0
    assert rep["steady_events_per_s"] > 0
    # First batch pays compilation. Structural check only: asserting a
    # wall-clock ratio against the steady batches is flaky on loaded CI.
    assert rep["first_batch_s"] > 0
    assert rep["first_batch_s"] <= rep["total_s"]
    json.loads(profiler.report_json(small_config.duration_ms, 600.0))


def test_profiler_trace_writes_files(tmp_path, small_config):
    profiler = Profiler(trace_dir=str(tmp_path / "trace"))
    with profiler.trace():
        run_simulation_config(small_config, profiler=profiler, use_all_devices=False)
    files = list((tmp_path / "trace").rglob("*"))
    assert files, "jax.profiler.trace produced no output"


def test_cli_profile_flag(capsys, tmp_path):
    from tpusim.cli import main

    rc = main(
        [
            "--runs", "4", "--duration-ms", "86400000", "--batch-size", "4",
            "--quiet", "--profile",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "[profile]" in out
    assert "steady_sim_years_per_s" in out


def test_plots_write_pngs(tmp_path):
    from tpusim.analysis.plots import plot_benefits, plot_stale_rates

    p1 = tmp_path / "stale.png"
    p2 = tmp_path / "bene.png"
    plot_stale_rates(points=12, out_path=p1, simulated={1.0: [0.01] * 10})
    plot_benefits(points=12, out_path=p2)
    assert p1.stat().st_size > 1000
    assert p2.stat().st_size > 1000


def test_plots_cli(tmp_path):
    from tpusim.analysis.plots import main

    rc = main(["--out-dir", str(tmp_path), "--prop-hi-s", "20"])
    assert rc == 0
    assert (tmp_path / "stale_rates.png").exists()
    assert (tmp_path / "net_benefits.png").exists()


def test_simulate_overlay_matches_oracle():
    from tpusim.analysis.oracle import analytical_stale_rates
    from tpusim.analysis.plots import simulate_overlay

    hashrates = (0.5, 0.3, 0.2)
    sim = simulate_overlay(hashrates, [10.0], runs=64, duration_days=20.0, seed=5)
    want = analytical_stale_rates(hashrates, 10.0)
    for got, exp in zip(sim[10.0], want):
        assert abs(got - exp) < max(0.5 * exp, 0.004), (got, exp)
