"""tpusim — a TPU-native (JAX/XLA) Bitcoin mining simulation framework.

Re-implements, TPU-first, the full capability surface of the reference C++ simulator
(darosior/miningsimulation): exponential block arrivals, hashrate-weighted winner
draws, a binary propagation model, longest-chain consensus with the first-seen
tiebreak, gamma=0 selfish mining, and per-miner revenue/stale statistics
aggregated over tens of thousands of independent Monte-Carlo runs.

Architecture (nothing here is a translation of the reference's C++):
  * every per-miner ``std::vector<Block>`` chain (reference simulation.h:41-202) is
    collapsed into O(1) fixed-shape integer state per (run, miner);
  * the event loop (reference main.cpp:128-192) becomes a ``jax.lax.scan`` state
    machine, one vectorized step per event, vmapped over a runs axis;
  * run-level parallelism (reference main.cpp:195-220, std::async threads) becomes
    sharding of the runs axis over a ``jax.sharding.Mesh`` with ``shard_map`` and
    on-device ``psum`` stat reduction;
  * an optional native C++ backend (tpusim.backend.cpp) provides the
    cross-validation oracle.

Times are integer milliseconds. Everything on device is 32-bit by design —
TPUs have no native 64-bit ALU — so year-long timelines (~3.16e10 ms) are
handled by chunked execution with per-chunk clock re-basing (tpusim.engine);
the host tracks absolute time in int64 numpy. JAX's x64 mode is never needed.
"""

from .config import (  # noqa: E402
    MinerConfig,
    NetworkConfig,
    SimConfig,
    default_network,
    BLOCK_INTERVAL_S,
    DEFAULT_DURATION_MS,
)
from .api import run_simulation  # noqa: E402
from .stats import MinerStats, SimResults  # noqa: E402

__all__ = [
    "MinerConfig",
    "NetworkConfig",
    "SimConfig",
    "default_network",
    "run_simulation",
    "MinerStats",
    "SimResults",
    "BLOCK_INTERVAL_S",
    "DEFAULT_DURATION_MS",
]

__version__ = "0.13.0"
