"""``tpusim perf`` — the performance regression ledger and its noise gate.

The repo's perf evidence discipline — chained-chunk min-of-5 at pinned
shapes, interleaved A/B runs, all samples recorded — lived in CHANGES.md
prose and ad-hoc scripts, so only a human re-running the ritual could catch
a regression. This module makes the ritual a command:

  * ``perf run`` executes the canonical noise-disciplined protocol
    (:func:`run_protocol`: chained-chunk timing of the fast and exact
    headline configs at pinned shapes, min-of-repeats with EVERY sample
    kept) and appends environment-fingerprinted rows to an append-only
    ledger, ``artifacts/perf/perf_<platform>.jsonl`` by default;
  * ``perf compare`` diffs the latest row per scenario of two ledgers with
    a spread-aware noise model (:func:`compare_rows`) and exits nonzero
    only on regressions beyond the measured noise — the CI gate
    (scripts/ci.sh) runs it against a committed calibration baseline;
  * ``perf report`` renders a ledger's trajectory per scenario, so "did
    PR N make the kernel slower" is a table, not an archaeology dig
    through CHANGES.md.

Rows share one schema with ``bench.py``'s headline payloads (which append
here too), so BENCH history and the kernel-timing ledger stop being two
formats. Schema and gate are jax-free by construction — ``perf compare``,
``perf report`` and the harvest validator must run on a host with no
backend; only ``perf run`` imports jax (lazily).

Noise model: each row keeps all its samples, so the gate derives the
relative spread (max-min)/min of BOTH rows being compared and only flags a
ratio beyond ``max(min_margin, noise_mult * spread)`` — a quiet pair of
ledgers gets a tight gate, a noisy pair a loose one, and a synthetic 2x
regression fails either way (pinned by tests/test_perf_obs.py).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path
from typing import Any

from .telemetry import environment_attrs

__all__ = [
    "SCHEMA",
    "PROTOCOL",
    "perf_row",
    "validate_row",
    "append_rows",
    "load_rows",
    "run_protocol",
    "run_sweep_protocol",
    "compare_rows",
    "render_compare",
    "render_report",
    "default_ledger_path",
    "main",
]

#: Ledger row schema version; bumped only on incompatible field changes.
SCHEMA = 1

#: Fields every ledger row must carry (validate_row). Anything else is an
#: open extension namespace — rows are self-describing JSON, not a table.
REQUIRED_FIELDS = ("schema", "scenario", "metric", "value", "unit", "better",
                   "samples", "env")

#: The canonical protocol shapes. "full" is the repo's evidence standard
#: (chained-chunk min-of-5, 12x256 steps, 512 runs — every CHANGES.md perf
#: claim since PR 4 used exactly this); "quick" is the CI calibration shape,
#: small enough for every build but still chained (single-chunk timings are
#: the ±40 % failure mode time_chained_chunks exists to kill).
PROTOCOL: dict[str, dict[str, int]] = {
    "full": {"runs": 512, "n_chunks": 12, "repeats": 5, "chunk_steps": 256},
    "quick": {"runs": 128, "n_chunks": 4, "repeats": 3, "chunk_steps": 256},
}

#: The packed-sweep protocol (run_sweep_protocol): grid points/sec on a
#: scaled reference selfish-threshold grid, sequential vs packed dispatch
#: (tpusim.packed). Deliberately dispatch-bound — few runs per point, so the
#: measurement isolates the per-point round-trip cost grid packing exists to
#: remove; repeats are INTERLEAVED sequential/packed (the worktree A/B
#: discipline, in-process).
SWEEP_PROTOCOL: dict[str, dict[str, Any]] = {
    "full": {"intervals": (150.0, 300.0, 600.0), "pcts": (25, 30, 35, 40, 45),
             "runs": 8, "duration_ms": 21_600_000, "repeats": 5},
    "quick": {"intervals": (600.0,), "pcts": (25, 30, 35, 40, 45),
              "runs": 4, "duration_ms": 21_600_000, "repeats": 3},
}

#: The sweep-protocol scenario name accepted by ``perf run --scenarios``
#: next to the chained-chunk ones; it emits BOTH the ``sweep_packed`` row
#: and its ``sweep_sequential`` before-twin.
SWEEP_SCENARIO = "packed_sweep"

#: Sweep-protocol variants (the packed-path-completion teeth): same grid,
#: same interleaved A/B discipline, but with the formerly-fallback features
#: armed — per-point checkpoints on BOTH paths ("ckpt": fresh checkpoint
#: dir per sweep call, so resume never silently skips the work being
#: timed), and the native-A/B generator ("xoro": rng="xoroshiro"). Each
#: emits ONE ledger row (``sweep_packed_ckpt`` / ``sweep_packed_xoro``)
#: whose extra records its own forced-sequential baseline and speedup.
SWEEP_VARIANTS: dict[str, str] = {
    "packed_sweep_ckpt": "ckpt",
    "packed_sweep_xoro": "xoro",
}

#: Every scenario that runs the sweep protocol (engine-unpinnable: run_sweep
#: has no engine knob, so --engine cannot pin any of these).
SWEEP_SCENARIOS = (SWEEP_SCENARIO, *SWEEP_VARIANTS)

#: ``perf run``'s default scenario set (``--scenarios`` unset).
DEFAULT_RUN_SCENARIOS = (
    "fast,exact,fast_yearlong,packed_sweep,packed_sweep_ckpt,"
    "packed_sweep_xoro"
)

def _git_rev() -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=Path(__file__).resolve().parents[1],
            capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        return None


def _git_dirty() -> bool | None:
    """Whether the working tree has uncommitted changes — None when git (or
    the repo) is unavailable, same tolerance as :func:`_git_rev`. Recorded
    next to ``git_rev``: an uncommitted tree stamping a clean-looking rev
    into the perf ledger silently poisons trajectory comparisons."""
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=Path(__file__).resolve().parents[1],
            capture_output=True, text=True, timeout=10,
        )
        if out.returncode != 0:
            return None
        return bool(out.stdout.strip())
    except (OSError, subprocess.SubprocessError):
        return None


def environment_fingerprint() -> dict[str, Any]:
    """The row's environment identity: everything needed to judge whether
    two rows are comparable at all (the ROADMAP's drift note — CPU numbers
    from different hosts/jax versions are NOT comparable — as machine-read
    fields instead of prose). Extends telemetry.environment_attrs with the
    host and revision facts a benchmark row needs. Shared with the
    provenance plane (tpusim.provenance): lineage records carry the same
    rev + dirty-flag identity, so `tpusim audit` can cross-check the two."""
    env = dict(environment_attrs())
    env["cpu_count"] = os.cpu_count()
    env["date"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    rev = _git_rev()
    if rev is not None:
        env["git_rev"] = rev
        dirty = _git_dirty()
        if dirty is not None:
            env["git_dirty"] = dirty
    return env


def perf_row(
    scenario: str,
    metric: str,
    value: float,
    *,
    unit: str,
    samples: list[float] | None = None,
    better: str = "lower",
    shape: dict[str, Any] | None = None,
    extra: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """One validated ledger row. ``samples`` is the full measurement list
    the headline ``value`` was reduced from (min for ``better="lower"``);
    a single-measurement producer (bench.py's end-to-end headline) passes
    ``[value]`` and the compare gate falls back to its margin floor."""
    row: dict[str, Any] = {
        "schema": SCHEMA,
        "scenario": scenario,
        "metric": metric,
        "value": float(value),
        "unit": unit,
        "better": better,
        "samples": [float(s) for s in (samples if samples is not None else [value])],
        "env": environment_fingerprint(),
    }
    if shape:
        row["shape"] = dict(shape)
    if extra:
        row.update(extra)
    validate_row(row)
    return row


def validate_row(row: Any) -> None:
    """Raise ValueError unless ``row`` is a structurally valid ledger row —
    the schema gate behind append_rows, the harvest validator and the
    compare loader (an append-only evidence file must never accumulate rows
    nobody can compare against)."""
    if not isinstance(row, dict):
        raise ValueError(f"perf row must be an object, got {type(row).__name__}")
    missing = [k for k in REQUIRED_FIELDS if k not in row]
    if missing:
        raise ValueError(f"perf row missing required field(s) {missing}: {row}")
    if row["schema"] != SCHEMA:
        raise ValueError(f"unknown perf row schema {row['schema']!r} (expected {SCHEMA})")
    if row["better"] not in ("lower", "higher"):
        raise ValueError(f"perf row 'better' must be lower|higher, got {row['better']!r}")
    if not isinstance(row["value"], (int, float)) or isinstance(row["value"], bool):
        raise ValueError(f"perf row value must be a number, got {row['value']!r}")
    samples = row["samples"]
    if (
        not isinstance(samples, list)
        or not samples
        or not all(isinstance(s, (int, float)) and not isinstance(s, bool) for s in samples)
    ):
        raise ValueError(f"perf row samples must be a non-empty number list, got {samples!r}")
    if not isinstance(row["env"], dict):
        raise ValueError("perf row env must be an object")


def append_rows(path: str | Path, rows: list[dict]) -> None:
    """Validate and append rows to an append-only JSONL ledger.

    THE perf-row write seam: every producer (the `perf run` CLI,
    scripts/loadgen.py) lands here, so the armed provenance plane records
    each appended row exactly once — one lineage record per row, citing the
    run record of the measurement that produced it (the scenarios dispatch
    through run_simulation_config, which records itself when armed).
    Content-addressed over the exact dict written, so the ledger line
    re-hashes to the same address."""
    for row in rows:
        validate_row(row)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("a") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    from .provenance import emit_lineage, lineage_armed, lineage_last

    if lineage_armed():
        for row in rows:
            emit_lineage(
                "perf_row", content=row,
                parents=(lineage_last("run"),),
                scenario=row["scenario"], metric=row["metric"],
            )


def load_rows(path: str | Path) -> list[dict]:
    """Read a ledger back, STRICT: a torn or foreign line in a perf ledger
    is corrupted evidence, not tolerable noise — unlike telemetry spans
    (load_spans), nothing writes here concurrently with a reader."""
    rows = []
    for i, line in enumerate(Path(path).read_text().splitlines(), start=1):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}:{i}: unparseable ledger line ({e})") from None
        validate_row(row)
        rows.append(row)
    return rows


def default_ledger_path(platform: str) -> Path:
    return Path(__file__).resolve().parents[1] / "artifacts" / "perf" / f"perf_{platform}.jsonl"


# ---------------------------------------------------------------------------
# perf run — the canonical protocol.


def run_protocol(
    *,
    quick: bool = False,
    engine: str = "auto",
    scenarios: tuple[str, ...] = ("fast", "exact", "fast_yearlong"),
    runs: int | None = None,
    n_chunks: int | None = None,
    repeats: int | None = None,
    chunk_steps: int | None = None,
) -> list[dict]:
    """Execute the canonical chained-chunk protocol and return ledger rows
    (one per scenario), every repeat sample recorded. ``fast`` (9-miner 2025
    roster, 1 s propagation, honest) and ``exact`` (the reference's 40 %
    selfish gamma=0 benchmark) pin the int32 un-rebased program these
    scenarios have always measured at the 365 d headline duration;
    ``fast_yearlong`` pins the year-long int16-REBASED domain — the
    production default since the count_rebase knob landed, and a
    combination only re-basing makes legal past ~106.8 d — so the ledger
    tracks both programs even as defaults change."""
    from .config import (
        DEFAULT_DURATION_MS,
        SimConfig,
        default_network,
        reference_selfish_network,
    )
    from .profiling import time_chained_chunks
    from .runner import make_engine

    p = dict(PROTOCOL["quick" if quick else "full"])
    for name, override in (("runs", runs), ("n_chunks", n_chunks),
                           ("repeats", repeats), ("chunk_steps", chunk_steps)):
        if override is not None:
            p[name] = override

    nets = {
        # fast/exact pin the program shape they have ALWAYS measured at the
        # 365 d headline duration — int32 counts, no re-basing (the pre-knob
        # default, now explicit so the trajectory stays one program).
        "fast": (
            lambda: default_network(propagation_ms=1000),
            {"state_dtype": "int32", "count_rebase": False},
        ),
        "exact": (
            reference_selfish_network,
            {"state_dtype": "int32", "count_rebase": False},
        ),
        "fast_yearlong": (
            lambda: default_network(propagation_ms=1000),
            {"state_dtype": "int16", "count_rebase": True},
        ),
    }
    unknown = [s for s in scenarios if s not in nets]
    if unknown:
        raise ValueError(f"unknown scenario(s) {unknown}; known: {sorted(nets)}")

    rows = []
    for name in scenarios:
        net_fn, overrides = nets[name]
        cfg = SimConfig(
            network=net_fn(), duration_ms=DEFAULT_DURATION_MS,
            runs=p["runs"], batch_size=p["runs"], seed=7,
            chunk_steps=p["chunk_steps"], **overrides,
        )
        if engine == "scan":
            from .engine import Engine

            eng = Engine(cfg)
        elif engine == "pallas":
            from .pallas_engine import PallasEngine

            eng = PallasEngine(cfg)
        else:
            eng = make_engine(cfg)
        timing = time_chained_chunks(
            eng, eng.make_keys(0, p["runs"]), n_chunks=p["n_chunks"],
            repeats=p["repeats"],
        )
        shape = {
            "runs": timing["runs"],
            "n_chunks": timing["n_chunks"],
            "chunk_steps": timing["chunk_steps"],
            "superstep": timing["superstep"],
            "engine": timing["engine"],
            "mode": cfg.resolved_mode,
            "rng_batch": cfg.rng_batch,
            "state_dtype": cfg.resolved_count_dtype,
            "consensus_gather": cfg.consensus_gather,
            "count_rebase": cfg.count_rebase,
            # The chained scenarios time ONE config's program — never the
            # packed-grid dispatch mode (that domain is sweep_packed's).
            "packed": False,
        }
        rows.append(perf_row(
            f"chained_{name}", "s_per_chunk", timing["s_per_chunk"],
            unit="s/chunk", better="lower",
            samples=[t / p["n_chunks"] for t in timing["repeats_s"]],
            shape=shape,
            extra={
                "s_per_chunk_median": timing["s_per_chunk_median"],
                "us_per_step": timing["us_per_step"],
                "spread_pct": timing["spread_pct"],
                "protocol": "quick" if quick else "full",
            },
        ))
    return rows


def run_sweep_protocol(
    *, quick: bool = False, repeats: int | None = None,
    variant: str | None = None,
) -> list[dict]:
    """Measure grid points/sec on the scaled reference selfish-threshold
    grid, sequential vs packed dispatch. With ``variant=None`` returns BOTH
    ledger rows (``sweep_sequential`` / ``sweep_packed``, better=higher,
    value = best repeat). ``variant="ckpt"`` arms per-point checkpoints on
    BOTH paths (a FRESH checkpoint dir per sweep call — a reused dir would
    resume past the work being timed and measure nothing) and
    ``variant="xoro"`` runs the grid with ``rng="xoroshiro"``; each returns
    ONE row (``sweep_packed_ckpt`` / ``sweep_packed_xoro``) whose extra
    records the variant's own forced-sequential best (same arming) and the
    measured ``speedup_x`` over it. All paths run through ``run_sweep`` on
    one shared engine cache after a warmup pass of each, so compiles are
    excluded and the repeats time pure dispatch+reduction (+ checkpoint I/O
    for the ckpt variant — that is the point: durability must not cost the
    packed win)."""
    import shutil
    import tempfile

    from .config import NetworkConfig, SimConfig
    from .sweep import _selfish_network, run_sweep

    if variant not in (None, "ckpt", "xoro"):
        raise ValueError(f"unknown sweep variant {variant!r}")
    p = dict(SWEEP_PROTOCOL["quick" if quick else "full"])
    if repeats is not None:
        p["repeats"] = repeats
    duration_ms = int(p["duration_ms"])
    batch = len(p["pcts"]) * int(p["runs"])
    rng = "xoroshiro" if variant == "xoro" else "threefry"
    points = []
    for interval_s in p["intervals"]:
        for pct in p["pcts"]:
            net = _selfish_network(pct)
            net = NetworkConfig(miners=net.miners, block_interval_s=interval_s)
            points.append((
                f"interval-{int(interval_s)}s-selfish-{pct}pct",
                SimConfig(network=net, runs=int(p["runs"]),
                          duration_ms=duration_ms, batch_size=batch, seed=7,
                          rng=rng),
            ))
    cfg0 = points[0][1]
    cache: dict = {}
    ckpt_root = (
        Path(tempfile.mkdtemp(prefix="tpusim-perf-ckpt-"))
        if variant == "ckpt" else None
    )
    calls = {"n": 0}

    def sweep(packed: bool) -> None:
        kwargs: dict[str, Any] = {}
        if ckpt_root is not None:
            calls["n"] += 1
            kwargs["checkpoint_dir"] = ckpt_root / f"call{calls['n']:03d}"
        run_sweep(points, quiet=True, engine_cache=cache, packed=packed,
                  **kwargs)

    try:
        sweep(False)
        sweep(True)  # warmup both paths: every program compiled, caches primed
        n = len(points)
        samples: dict[bool, list[float]] = {False: [], True: []}
        for _ in range(int(p["repeats"])):
            for packed in (False, True):  # interleaved A/B
                t0 = time.perf_counter()
                sweep(packed)
                samples[packed].append(n / (time.perf_counter() - t0))
    finally:
        if ckpt_root is not None:
            shutil.rmtree(ckpt_root, ignore_errors=True)
    shape = {
        "points": n,
        "runs_per_point": int(p["runs"]),
        "duration_ms": duration_ms,
        "batch_size": batch,
        "mode": cfg0.resolved_mode,
        "rng_batch": cfg0.rng_batch,
        "state_dtype": cfg0.resolved_count_dtype,
        "consensus_gather": cfg0.consensus_gather,
        "count_rebase": cfg0.count_rebase,
    }
    protocol = "quick" if quick else "full"
    speedup = round(max(samples[True]) / max(samples[False]), 3)
    if variant is not None:
        # One row per variant: its sequential baseline (with the SAME
        # arming) is evidence, not a gated scenario of its own.
        return [perf_row(
            f"sweep_packed_{variant}", "points_per_s", max(samples[True]),
            unit="points/s", better="higher", samples=samples[True],
            shape={**shape, "packed": True, "rng": rng,
                   "checkpointed": variant == "ckpt"},
            extra={"protocol": protocol, "speedup_x": speedup,
                   "sequential_best": round(max(samples[False]), 3)},
        )]
    rows = []
    for packed, scenario in ((False, "sweep_sequential"), (True, "sweep_packed")):
        extra: dict[str, Any] = {"protocol": protocol}
        if packed:
            extra["speedup_x"] = speedup
        rows.append(perf_row(
            scenario, "points_per_s", max(samples[packed]),
            unit="points/s", better="higher", samples=samples[packed],
            shape={**shape, "packed": packed}, extra=extra,
        ))
    return rows


# ---------------------------------------------------------------------------
# perf compare — the spread-aware noise gate.


def _rel_spread(samples: list[float]) -> float:
    lo = min(samples)
    if lo <= 0:
        return 0.0
    return (max(samples) - lo) / lo


def latest_by_scenario(rows: list[dict]) -> dict[tuple[str, str], dict]:
    """The newest row per (scenario, metric) — the append-only ledger's
    current state. File order IS time order (rows are appended)."""
    out: dict[tuple[str, str], dict] = {}
    for row in rows:
        out[(row["scenario"], row["metric"])] = row
    return out


def compare_rows(
    base_rows: list[dict],
    new_rows: list[dict],
    *,
    min_margin: float = 0.25,
    noise_mult: float = 2.0,
) -> list[dict]:
    """Compare the latest row per scenario of two ledgers. Returns one
    result dict per scenario with a ``status`` of:

      * ``ok`` / ``improved`` / ``regression`` — ratio vs. the noise margin
        (``max(min_margin, noise_mult * measured rel spread)``; the spread
        is the worse of the two rows' sample spreads);
      * ``missing`` — the baseline has the scenario, the candidate does not
        (a gate that passes on an empty candidate ledger is a dead gate);
      * ``incomparable`` — shape or unit fingerprints differ (a category
        error, not a measurement).

    ``ratio`` is normalized so > 1 always means worse, whatever the row's
    ``better`` direction.
    """
    base = latest_by_scenario(base_rows)
    new = latest_by_scenario(new_rows)
    results = []
    for key in sorted(set(base) | set(new)):
        scenario, metric = key
        b, n = base.get(key), new.get(key)
        res: dict[str, Any] = {"scenario": scenario, "metric": metric}
        if b is None:
            res.update(status="new", value=n["value"])
            results.append(res)
            continue
        if n is None:
            res.update(status="missing", base_value=b["value"])
            results.append(res)
            continue
        # Whole-dict shape equality, deliberately strict: every key a
        # producer pins (runs/chunks/engine/..., bench's batch_size and
        # pipelined too) is part of comparability — comparing a 512-run
        # timing against a 128-run one is a category error, not noise.
        if b.get("shape") != n.get("shape") or b["unit"] != n["unit"] \
                or b["better"] != n["better"]:
            res.update(
                status="incomparable",
                base_shape=b.get("shape"), new_shape=n.get("shape"),
            )
            results.append(res)
            continue
        worse = (
            n["value"] / b["value"] if b["better"] == "lower"
            else b["value"] / n["value"]
        ) if b["value"] > 0 and n["value"] > 0 else float("inf")
        noise = max(_rel_spread(b["samples"]), _rel_spread(n["samples"]))
        margin = max(min_margin, noise_mult * noise)
        if worse > 1.0 + margin:
            status = "regression"
        elif worse < 1.0 - min(margin, 0.99):
            status = "improved"
        else:
            status = "ok"
        res.update(
            status=status, base_value=b["value"], new_value=n["value"],
            ratio=round(worse, 4), margin=round(margin, 4),
            noise=round(noise, 4),
        )
        results.append(res)
    return results


def render_compare(results: list[dict]) -> str:
    from .report import text_table

    rows = []
    for r in results:
        detail = ""
        if "ratio" in r:
            detail = (f"{r['base_value']:g} -> {r['new_value']:g} "
                      f"(x{r['ratio']:.3f}, margin {r['margin']:.0%})")
        elif r["status"] == "missing":
            detail = f"baseline {r['base_value']:g}, no candidate row"
        elif r["status"] == "new":
            detail = f"candidate {r['value']:g}, no baseline row"
        elif r["status"] == "incomparable":
            detail = "shape/unit fingerprints differ"
        rows.append([r["scenario"], r["metric"], r["status"].upper(), detail])
    lines = text_table(["scenario", "metric", "verdict", "detail"], rows)
    return "\n".join(lines) + "\n"


def render_report(rows: list[dict], scenario: str | None = None) -> str:
    """The trajectory table: every row per scenario in ledger (= time)
    order, environment columns inline so non-comparable rows are visibly
    non-comparable."""
    from .report import text_table

    if scenario is not None:
        rows = [r for r in rows if r["scenario"] == scenario]
    if not rows:
        return "perf ledger has no rows" + (f" for scenario {scenario!r}" if scenario else "") + "\n"
    groups: dict[str, list[dict]] = {}
    for row in rows:
        groups.setdefault(row["scenario"], []).append(row)
    out = []
    for name in sorted(groups):
        out.append(f"== {name} ==")
        table_rows = []
        for r in groups[name]:
            env = r.get("env", {})
            spread = _rel_spread(r["samples"]) if len(r["samples"]) > 1 else None
            table_rows.append([
                str(env.get("date", "?")),
                str(env.get("git_rev", "?")),
                str(env.get("platform", "?")),
                str((r.get("shape") or {}).get("engine", "?")),
                f"{r['value']:g} {r['unit']}",
                f"{spread:.1%}" if spread is not None else "n/a",
                str(len(r["samples"])),
            ])
        out.extend(text_table(
            ["date", "rev", "platform", "engine", "value", "spread", "n"],
            table_rows,
        ))
        out.append("")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# CLI.


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpusim perf",
        description="Performance regression ledger: run the canonical "
        "protocol, gate against a baseline, render the trajectory.",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="execute the chained-chunk protocol, append ledger rows")
    p_run.add_argument("--out", type=Path, help="ledger path (default artifacts/perf/perf_<platform>.jsonl)")
    p_run.add_argument("--quick", action="store_true",
                       help="CI calibration shape (128 runs, 4 chunks, "
                            "min-of-3) instead of the full evidence shape "
                            "(512 runs, 12 chunks, min-of-5)")
    p_run.add_argument("--engine", choices=("auto", "scan", "pallas"), default="auto")
    p_run.add_argument("--scenarios", default=None,
                       help="comma-separated subset of "
                            f"{DEFAULT_RUN_SCENARIOS} (the default; "
                            "packed_sweep emits the sweep_sequential + "
                            "sweep_packed points/sec pair, and "
                            "packed_sweep_ckpt/packed_sweep_xoro one "
                            "sweep_packed_ckpt/sweep_packed_xoro row each "
                            "with checkpoints / rng=xoroshiro armed)")
    p_run.add_argument("--runs", type=int)
    p_run.add_argument("--n-chunks", type=int)
    p_run.add_argument("--repeats", type=int)
    p_run.add_argument("--chunk-steps", type=int)

    p_cmp = sub.add_parser("compare", help="noise-gated diff of two ledgers (exit 1 on regression)")
    p_cmp.add_argument("base", type=Path)
    p_cmp.add_argument("new", type=Path)
    p_cmp.add_argument("--min-margin", type=float, default=0.25,
                       help="regression threshold floor as a ratio fraction "
                            "(default 0.25; raise on noisy shared hosts)")
    p_cmp.add_argument("--noise-mult", type=float, default=2.0,
                       help="margin = max(min-margin, noise-mult * measured "
                            "relative sample spread)")

    p_rep = sub.add_parser("report", help="render a ledger's trajectory")
    p_rep.add_argument("path", type=Path)
    p_rep.add_argument("--scenario")

    args = ap.parse_args(argv)

    if args.cmd == "run":
        explicit = args.scenarios is not None
        scenarios = tuple(
            s for s in (args.scenarios or DEFAULT_RUN_SCENARIOS).split(",")
            if s
        )
        sweep_requested = tuple(s for s in scenarios if s in SWEEP_SCENARIOS)
        if sweep_requested and args.engine != "auto":
            # run_sweep_protocol measures the auto-selected engine pair end
            # to end (run_sweep has no engine knob); appending its rows
            # under a pinned --engine would mislabel the ledger.
            if explicit:
                ap.error(
                    f"--engine {args.engine} cannot pin the "
                    f"{'/'.join(sweep_requested)} scenario(s) (the sweep "
                    f"protocol measures the auto-selected engine); drop "
                    f"them from --scenarios or use --engine auto"
                )
            print(f"[perf] skipping {'/'.join(sweep_requested)}: --engine "
                  f"{args.engine} pins the chained scenarios only")
            scenarios = tuple(s for s in scenarios if s not in SWEEP_SCENARIOS)
            sweep_requested = ()
        chained = tuple(s for s in scenarios if s not in SWEEP_SCENARIOS)
        rows = []
        if chained:
            rows += run_protocol(
                quick=args.quick, engine=args.engine, scenarios=chained,
                runs=args.runs, n_chunks=args.n_chunks, repeats=args.repeats,
                chunk_steps=args.chunk_steps,
            )
        for scenario in sweep_requested:
            rows += run_sweep_protocol(
                quick=args.quick, repeats=args.repeats,
                variant=SWEEP_VARIANTS.get(scenario),
            )
        if args.out is not None:
            out = args.out
        else:
            import jax

            out = default_ledger_path(jax.devices()[0].platform)
        append_rows(out, rows)
        for row in rows:
            print(f"[perf] {row['scenario']}: {row['value']:g} {row['unit']} "
                  f"(samples {row['samples']})")
        print(f"[perf] appended {len(rows)} row(s) to {out}")
        return 0

    if args.cmd == "compare":
        try:
            base_rows = load_rows(args.base)
            new_rows = load_rows(args.new)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        results = compare_rows(
            base_rows, new_rows,
            min_margin=args.min_margin, noise_mult=args.noise_mult,
        )
        print(render_compare(results), end="")
        if any(r["status"] in ("missing", "incomparable") for r in results):
            print("error: ledgers are not comparable (see verdicts above)",
                  file=sys.stderr)
            return 2
        if not any(
            r["status"] in ("ok", "improved", "regression") for r in results
        ):
            # An EMPTY (or disjoint) baseline marks every candidate row
            # "new" and nothing is ever compared — a truncated calibration
            # file must fail the gate loudly, not turn it green forever.
            print("error: no comparable scenarios between the two ledgers "
                  "(empty or truncated baseline?) — nothing was gated",
                  file=sys.stderr)
            return 2
        if any(r["status"] == "regression" for r in results):
            return 1
        return 0

    try:
        rows = load_rows(args.path)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(render_report(rows, scenario=args.scenario), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
