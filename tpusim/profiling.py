"""Tracing / profiling subsystem.

The reference's only observability is a progress percentage on stdout
(reference main.cpp:219); SURVEY.md §5 mandates real telemetry for the TPU
framework: compile-vs-run phase separation, steady-state throughput counters
(sim-years/sec/chip — the headline unit of BASELINE.md), and device-level
traces. This module provides the host-timing layers on top of the shared
sink in :mod:`tpusim.telemetry`:

  * ``Profiler`` — host-side phase/batch accounting, now a thin client of
    :class:`tpusim.telemetry.MetricsRegistry`: the registry stores the batch
    records and :func:`tpusim.telemetry.throughput_report` derives the
    report, so the ``--profile`` numbers and the ``tpusim report`` dashboard
    share one implementation of "steady-state throughput". The pipelined
    runner times each device batch completion-to-completion and feeds the
    wall time to ``profiler.record(n, elapsed_s)`` (a context manager around
    finalize would double-count the dispatch/compute overlap).
  * ``Profiler.trace`` — wraps ``jax.profiler.trace`` so a sweep can emit an
    XLA device trace (viewable in TensorBoard/XProf, or attributed offline
    by ``tpusim report <trace-dir>``) without any call-site knowing profiler
    internals. No-op unless ``trace_dir`` is set.

Wired into the CLI as ``--profile`` / ``--trace-dir``; structured JSONL
spans are the CLI's ``--telemetry`` (tpusim.telemetry.TelemetryRecorder).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import statistics
import time
from typing import Any, Iterator

from .telemetry import BatchRecord, MetricsRegistry  # noqa: F401  (re-export)


@dataclasses.dataclass
class Profiler:
    """Collects per-batch timings and derives throughput telemetry."""

    trace_dir: str | None = None
    registry: MetricsRegistry = dataclasses.field(default_factory=MetricsRegistry)

    @property
    def records(self) -> list[BatchRecord]:
        return self.registry.batches

    def record(self, runs: int, elapsed_s: float) -> None:
        """Record an externally-timed batch — the pipelined runner times each
        batch as completion-to-completion wall time (dispatch of batch c+1
        overlaps finalize of batch c, so a nested context manager would
        double-count the overlap)."""
        self.registry.record_batch(runs, elapsed_s)

    @contextlib.contextmanager
    def trace(self) -> Iterator[None]:
        """Device-level XLA trace around the whole run (TensorBoard format)."""
        if self.trace_dir is None:
            yield
            return
        import jax

        with jax.profiler.trace(self.trace_dir):
            yield

    def report(self, duration_ms: int, block_interval_s: float) -> dict[str, Any]:
        """The registry's phase/throughput report (telemetry.throughput_report
        — single-batch runs are flagged ``steady_is_first_batch``: their
        "steady" numbers are compile-contaminated) plus the trace location."""
        rep = self.registry.throughput(duration_ms, block_interval_s)
        rep["trace_dir"] = self.trace_dir
        return rep

    def report_json(self, duration_ms: int, block_interval_s: float) -> str:
        return json.dumps(self.report(duration_ms, block_interval_s), indent=2)


def time_chained_chunks(
    engine, keys, n_chunks: int = 12, repeats: int = 3
) -> dict[str, Any]:
    """Per-chunk/per-step kernel timing with the chained-chunk discipline.

    Single-chunk dispatch timings over the tunneled TPU vary by ±40 %
    (artifacts/perf_tpu.jsonl); chaining ``n_chunks`` chunk programs inside
    ONE jitted fori_loop amortizes dispatch and host sync to <1/n of the
    measurement, which brought repeat spread under ~9 % on hardware. This is
    the canonical way to time kernel changes — ad-hoc single-chunk timing in
    smoke scripts is how two rounds of numbers got ±40 % error bars.

    Runs every chunk at the full TIME_CAP cap (no run freezes), so the
    measured cost is the steady-state per-step cost of the engine's chunk
    program — pallas kernel or scan — independent of simulation duration.
    Returns the min-of-repeats timing (the standard noise-floor estimator)
    PLUS the full per-repeat sample list, the median and the spread — the
    min is the headline, but a ledger row that only kept the best would be
    unauditable (the perf regression gate's noise model derives from the
    samples; tpusim.perf).
    """
    import jax
    import jax.numpy as jnp

    from .state import TIME_CAP

    n = keys.shape[0]
    cap = jnp.full((n,), int(TIME_CAP), jnp.int32)

    @jax.jit
    def prog(keys):
        state, aux = engine._init_impl(keys, engine.params)

        def body(i, carry):
            state, aux = carry
            state, aux, _ = engine._chunk_impl(
                state, aux, cap, keys, i.astype(jnp.uint32), engine.params
            )
            return (state, aux)

        state, aux = jax.lax.fori_loop(0, n_chunks, body, (state, aux))
        # A tiny output that depends on every run's state, forcing completion
        # without transferring the state tree. Must involve height/stale:
        # summing only state.t lets XLA algebraically cancel the rebase
        # (t - t = 0) and dead-code-eliminate the entire loop — observed on
        # CPU as a 12-chunk program "running" in 46 us. The telemetry
        # counters (aux[0]) are folded in for the same reason: they are
        # always-on in production batches, so a timing that let XLA
        # dead-code-eliminate them would measure a program nobody runs.
        forced = jnp.sum(state.height) + jnp.sum(state.stale) + jnp.sum(state.t)
        for leaf in jax.tree_util.tree_leaves(aux[0]):
            forced = forced + jnp.sum(leaf)
        return forced

    prog(keys).block_until_ready()  # compile + warm
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        prog(keys).block_until_ready()
        times.append(time.perf_counter() - t0)
    best = min(times)
    median = statistics.median(times)
    steps = n_chunks * engine.chunk_steps
    # A sub-resolution fast path (e.g. a dead-code-eliminated program, or a
    # clock with coarse ticks) can return best == 0; the spread is undefined
    # there, and None keeps the JSONL row parseable (inf is not valid JSON).
    spread = round(100.0 * (max(times) - best) / best, 1) if best > 0 else None
    return {
        "engine": type(engine).__name__,
        "runs": int(n),
        "n_chunks": n_chunks,
        "chunk_steps": engine.chunk_steps,
        "superstep": getattr(engine, "superstep", 1),
        "s_per_chunk": round(best / n_chunks, 6),
        "s_per_chunk_median": round(median / n_chunks, 6),
        "us_per_step": round(best / steps * 1e6, 3),
        "us_per_step_median": round(median / steps * 1e6, 3),
        "repeats_s": [round(t, 4) for t in times],
        "spread_pct": spread,
    }


# ---------------------------------------------------------------------------
# Roofline accounting (scripts/roofline.py drives these; ROOFLINE.md renders
# the committed report).


def state_bytes_per_run(engine) -> int:
    """Bytes of simulation state per run: every leaf of the engine's
    mode/roster-resolved state tree at its COMPILED dtype (the Pallas
    kernel's leaf shape/dtype lists are the authority — they enumerate
    exactly the carried leaves in both modes, and the packed-state int16
    count leaves of SimConfig.state_dtype halve their share)."""
    import math as _math

    import jax.numpy as _jnp

    from .pallas_engine import _leaf_dtypes, _leaf_shapes
    from .state import COUNT_DTYPES

    m = engine.n_miners
    k = engine.config.resolved_group_slots
    cdt = COUNT_DTYPES[engine.config.resolved_count_dtype]
    return sum(
        _math.prod(s) * _jnp.dtype(d).itemsize
        for s, d in zip(
            _leaf_shapes(m, k, engine.exact),
            # Under count_rebase the stale leaf stays int32 (the one
            # monotone accumulator the re-base does not shift) — the
            # traffic model must price the layout actually compiled.
            _leaf_dtypes(m, k, engine.exact, cdt, engine.config.count_rebase),
        )
    )


def bytes_per_event(engine) -> dict[str, float]:
    """Minimum memory traffic per simulated event for each execution style,
    from the state size alone (the roofline's traffic model, not a
    measurement):

      * ``scan``  — the lax.scan carry makes one full read + write round
        trip of the state tree per event, plus the 8-byte (winner, interval)
        pair: ``2 * state + 8`` (8 bytes either way: two raw uint32 words on
        the legacy path, two pre-mapped int32 draws under
        SimConfig.rng_batch). Supersteps do NOT change this model — K events
        per scan step still update every leaf K times; what K amortizes is
        per-step *control* overhead, which a bandwidth model deliberately
        excludes (that gap is visible as distance from the roof). State
        packing (SimConfig.state_dtype) DOES change it: int16 count leaves
        shrink ``state`` itself, i.e. they raise the roof rather than close
        the distance to it.
      * ``pallas`` — state stays resident in VMEM across a whole chunk and
        crosses HBM once per chunk each way, so the per-event share is
        ``2 * state / chunk_steps``, plus the same 8 streamed RNG bytes.

    The always-on telemetry counters (engine.SimCounters, 12 bytes per run)
    are deliberately excluded: they are not simulation state and sit three
    orders of magnitude under the state tree in both traffic models.
    """
    sb = state_bytes_per_run(engine)
    return {
        "state_bytes_per_run": sb,
        "scan": 2.0 * sb + 8.0,
        "pallas": 2.0 * sb / engine.chunk_steps + 8.0,
    }


def measure_copy_bandwidth_gbps(mib: int = 256, repeats: int = 3) -> float:
    """Sustained device memory bandwidth from a jitted saxpy-like pass
    (read + write of ``mib`` MiB), the denominator of the roofline: GB/s
    counting both directions. Deliberately simple — a STREAM-style bound,
    not a vendor spec sheet."""
    import jax
    import jax.numpy as jnp

    n = mib * (1 << 20) // 4
    x = jnp.arange(n, dtype=jnp.float32)
    f = jax.jit(lambda v: v * 1.000001 + 1.0)
    f(x).block_until_ready()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return 2.0 * n * 4 / best / 1e9


def roofline_point(
    engine, keys, *, bandwidth_gbps: float, n_chunks: int = 12, repeats: int = 3
) -> dict[str, Any]:
    """One measured roofline point: chained-chunk events/s for this engine
    against the bandwidth-bound event rate implied by its traffic model.
    ``roof_events_per_s`` uses the model matching the engine type; the
    reported fraction is how close the engine is to being memory-bound
    (small fraction = control/compute overhead dominates)."""
    from .pallas_engine import PallasEngine

    timing = time_chained_chunks(engine, keys, n_chunks=n_chunks, repeats=repeats)
    model = bytes_per_event(engine)
    kind = "pallas" if isinstance(engine, PallasEngine) else "scan"
    per_event = model[kind]
    n = int(keys.shape[0])
    roof = bandwidth_gbps * 1e9 / per_event
    row = {
        **timing,
        "mode": engine.config.resolved_mode,
        "state_dtype": engine.config.resolved_count_dtype,
        "rng_batch": engine.config.rng_batch,
        "consensus_gather": engine.config.consensus_gather,
        "count_rebase": engine.config.count_rebase,
        "traffic_model": kind,
        "state_bytes_per_run": model["state_bytes_per_run"],
        "bytes_per_event": round(per_event, 2),
        "bandwidth_gbps": round(bandwidth_gbps, 2),
        "roof_events_per_s": round(roof, 1),
    }
    if timing["us_per_step"] <= 0:
        # Same degenerate fast path time_chained_chunks guards spread_pct
        # against: a sub-resolution timing makes the rates meaningless, and a
        # raw division here would abort a whole multi-point sweep with a
        # ZeroDivisionError. Flag the row instead; measure sweeps drop it.
        row.update(events_per_s=None, fraction_of_roof=None, degenerate_timing=True)
        return row
    events_per_s = n / (timing["us_per_step"] * 1e-6)
    row.update(
        events_per_s=round(events_per_s, 1),
        fraction_of_roof=round(events_per_s / roof, 4),
    )
    return row
