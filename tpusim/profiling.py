"""Tracing / profiling subsystem.

The reference's only observability is a progress percentage on stdout
(reference main.cpp:219); SURVEY.md §5 mandates real telemetry for the TPU
framework: compile-vs-run phase separation, steady-state throughput counters
(sim-years/sec/chip — the headline unit of BASELINE.md), and device-level
traces. This module provides both layers:

  * ``Profiler`` — host-side phase/batch accounting. The runner enters
    ``profiler.batch(n)`` around every device batch; the report separates the
    first batch (which pays XLA compilation) from steady-state batches and
    derives runs/sec, sim-years/sec and events/sec.
  * ``Profiler.trace`` — wraps ``jax.profiler.trace`` so a sweep can emit an
    XLA device trace (viewable in TensorBoard/XProf) without any call-site
    knowing profiler internals. No-op unless ``trace_dir`` is set.

Wired into the CLI as ``--profile`` / ``--trace-dir``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from typing import Any, Iterator


@dataclasses.dataclass
class BatchRecord:
    runs: int
    elapsed_s: float


@dataclasses.dataclass
class Profiler:
    """Collects per-batch timings and derives throughput telemetry."""

    trace_dir: str | None = None
    records: list[BatchRecord] = dataclasses.field(default_factory=list)

    @contextlib.contextmanager
    def batch(self, runs: int) -> Iterator[None]:
        # Records only successful batches: a failed attempt that the runner
        # retries must not double-count its runs in the throughput report.
        t0 = time.perf_counter()
        yield
        self.records.append(BatchRecord(runs, time.perf_counter() - t0))

    @contextlib.contextmanager
    def trace(self) -> Iterator[None]:
        """Device-level XLA trace around the whole run (TensorBoard format)."""
        if self.trace_dir is None:
            yield
            return
        import jax

        with jax.profiler.trace(self.trace_dir):
            yield

    def report(self, duration_ms: int, block_interval_s: float) -> dict[str, Any]:
        """Phase timings + throughput. The first batch carries the jit
        compilation (compile + first execution; JAX does not expose the split
        without a trace); steady-state numbers use the remaining batches when
        there are any."""
        if not self.records:
            return {"batches": 0}
        total_runs = sum(r.runs for r in self.records)
        total_s = sum(r.elapsed_s for r in self.records)
        steady = self.records[1:] or self.records
        steady_runs = sum(r.runs for r in steady)
        steady_s = sum(r.elapsed_s for r in steady) or 1e-12
        years_per_run = duration_ms / (365.2425 * 86_400_000.0)
        events_per_run = 2.0 * duration_ms / (block_interval_s * 1000.0)
        return {
            "batches": len(self.records),
            "total_runs": total_runs,
            "total_s": round(total_s, 4),
            "first_batch_s": round(self.records[0].elapsed_s, 4),
            "steady_runs_per_s": round(steady_runs / steady_s, 3),
            "steady_sim_years_per_s": round(steady_runs * years_per_run / steady_s, 3),
            "steady_events_per_s": round(steady_runs * events_per_run / steady_s, 1),
            "trace_dir": self.trace_dir,
        }

    def report_json(self, duration_ms: int, block_interval_s: float) -> str:
        return json.dumps(self.report(duration_ms, block_interval_s), indent=2)
