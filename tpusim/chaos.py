"""Deterministic fault injection: named failure seams, drill plans, watchdogs.

The runner grew real resilience primitives for preemptible TPU windows and
flaky tunnels — batch retry, pallas→scan fallback, fingerprinted npz
checkpoints, the killable subprocess backend probe — but nothing in the repo
*exercised* those paths under failure: they were tested only by the happy
path. This module is the failure generator: a :class:`ChaosPlan` names which
seam fails, when, and how, and a :class:`ChaosInjector` threads that plan
through the orchestration layer so every documented recovery path can be
driven deterministically (tests/test_chaos.py) or drilled by hand
(``tpusim --chaos plan.json``).

Design constraints, in order:

  * **Device programs are untouched.** Every injection point is host-side
    Python at an orchestration seam — batch dispatch, done-flag fetch,
    checkpoint I/O, telemetry writes, the backend probe. Nothing here is
    traced, so with no plan the compiled programs are byte-identical to a
    chaos-less build (pinned by tests/test_chaos.py the same way
    ``flight_capacity=0`` is pinned) and the injector check at each seam is
    one ``is not None``.
  * **Deterministic.** A fault fires on an exact (point, trigger-predicate,
    remaining-count) match — "batch 1, attempt 0, twice" — never on wall
    clock or randomness, so a drill reproduces bit-for-bit and the
    degradation-matrix tests can pin recovered runs bit-equal to fault-free
    runs.
  * **Observable.** Every injected fault is one ``chaos`` telemetry span
    (when a recorder is bound), so ``tpusim report`` renders a fault ledger
    next to the retries/fallbacks it provoked.

Injection points wired through the repo (the plan's ``point`` vocabulary):

  ====================  =====================================================
  point                 fired from / context keys
  ====================  =====================================================
  engine.run_batch      Engine.run_batch(_async) entry; engine, runs
  engine.dispatch       runner finalize/retry loop; start, batch, attempt,
                        engine
  engine.dispatch_async runner pipelined dispatch stage; start
  pipeline.flag_fetch   Engine._run_batch_pipelined done-flag fetch (kind
                        "hang" simulates a wedged tunnel; the wall-clock
                        watchdog path)
  checkpoint.save       _Checkpoint.save; phase in begin | pre_replace |
                        post_replace, runs_done ("sigkill" here is the
                        kill-mid-save drill)
  checkpoint.load       _Checkpoint.load; path
  telemetry.write       TelemetryRecorder.emit; target (the span name —
                        "enospc" exercises the full-disk degradation)
  probe.attempt         probe_backend per attempt; attempt ("hang" simulates
                        a dead tunnel probe, "transient" a failing one)
  sweep.point           run_sweep per grid point; target (the point name),
                        backend
  fleet.spawn           FleetSupervisor before each worker spawn; target
                        (point name), worker, attempt ("transient" = a spawn
                        failure requeued with backoff, "sigkill" = the
                        supervisor itself dies — the --resume drill)
  fleet.heartbeat       two sides of the same liveness seam: the WORKER's
                        progress callback (beats, runs_done — "hang" wedges
                        the worker: heartbeats stop, compute freezes, the
                        supervisor's lease watchdog must kill it) and the
                        SUPERVISOR's per-poll heartbeat read (target, worker,
                        attempt — "hang" makes the lease read as already
                        expired, the deterministic-time expiry drill)
  serve.accept          ServeDaemon.submit admission, before the queue;
                        target (the query name — "transient" = a
                        retryable-503 admission fault, "enospc" = admission
                        I/O fault)
  serve.dispatch        ServeDaemon._dispatch_group inside the watchdogged
                        dispatch thunk; points, queries, adaptive ("hang"
                        wedges ONE pack past its deadline — only that
                        pack's queries shed, the daemon stays live)
  serve.cache           ServeDaemon._persist_row before the served-row
                        append; target (the point name — "enospc" =
                        full-disk result cache: persistence disables,
                        serving continues)
  serve.drain           ServeDaemon.drain entry; depth (a fault here must
                        not stop the drain — crash-only shutdown completes)
  ====================  =====================================================

This table's checkable mirror is the README "Fault injection" seam table:
`tpusim lint` (JX011, tpusim.lint.contracts) cross-checks the README rows
and every committed ``drills/*.json`` plan against the live ``fire()`` call
sites, so adding/renaming a seam here without updating both fails CI.

This module imports no jax (the probe must stay importable before any
backend touch) and nothing from the rest of the package.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import logging
import os
import queue
import signal
import threading
from pathlib import Path
from typing import Any

logger = logging.getLogger("tpusim")

__all__ = [
    "FAULT_KINDS",
    "FaultSpec",
    "ChaosPlan",
    "ChaosInjector",
    "ChaosError",
    "ChaosPermanentError",
    "InjectedHang",
    "PipelineStallError",
    "fetch_with_deadline",
    "load_plan",
    "as_injector",
]


class ChaosError(RuntimeError):
    """Injected *transient* fault — the class of failure the retry policy
    exists for (tunnel reset, preempted worker). The runner retries it."""


class ChaosPermanentError(ValueError):
    """Injected *permanent* (config-class) fault. A ``ValueError`` on purpose:
    the runner's fail-fast rule treats deterministic config errors as
    unretryable, and an injected permanent fault must take that exact path."""


class InjectedHang(Exception):
    """Marker raised at a fetch/probe seam to simulate a wall-clock hang
    without sleeping: the call site reports it exactly as a watchdog/timeout
    expiry, so the degradation path runs in deterministic test time."""


class PipelineStallError(RuntimeError):
    """The pipelined done-flag fetch outlived its wall-clock watchdog
    deadline (or an injected hang simulated that). Transient by contract:
    ``Engine.run_batch`` degrades to a synchronous re-run, and a caller that
    sees it propagate may retry the batch."""


#: What an injected fault does when it fires.
FAULT_KINDS = ("transient", "permanent", "hang", "sigkill", "enospc")


@dataclasses.dataclass
class FaultSpec:
    """One fault: where (``point``), what (``kind``), when (``when`` — every
    key must equal the fired context value, e.g. ``{"batch": 3, "attempt":
    1}``), and how many times (``count``; < 0 means unlimited)."""

    point: str
    kind: str = "transient"
    count: int = 1
    when: dict[str, Any] = dataclasses.field(default_factory=dict)
    note: str = ""

    def __post_init__(self) -> None:
        if not self.point:
            raise ValueError("fault needs a point name (see tpusim.chaos docstring)")
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; use one of {FAULT_KINDS}"
            )
        if self.count == 0:
            raise ValueError("count=0 never fires; use a positive count (or < 0 for unlimited)")

    def matches(self, ctx: dict[str, Any]) -> bool:
        return all(ctx.get(k) == v for k, v in self.when.items())


@dataclasses.dataclass
class ChaosPlan:
    """A drill: the ordered fault list. JSON shape::

        {"faults": [
          {"point": "engine.dispatch", "kind": "transient", "count": 2,
           "when": {"batch": 1}, "note": "retry drill"}
        ]}
    """

    faults: list[FaultSpec] = dataclasses.field(default_factory=list)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ChaosPlan":
        raw = d.get("faults", [])
        if not isinstance(raw, list):
            raise ValueError('chaos plan must be {"faults": [...]}')
        faults = []
        for f in raw:
            known = {"point", "kind", "count", "when", "note"}
            extra = set(f) - known
            if extra:
                raise ValueError(f"unknown fault keys {sorted(extra)}; known: {sorted(known)}")
            faults.append(FaultSpec(**f))
        return ChaosPlan(faults=faults)

    @staticmethod
    def from_json(text: str) -> "ChaosPlan":
        return ChaosPlan.from_dict(json.loads(text))

    def to_json(self) -> str:
        return json.dumps(
            {"faults": [dataclasses.asdict(f) for f in self.faults]}, indent=2
        )


def load_plan(path: str | Path) -> ChaosPlan:
    return ChaosPlan.from_json(Path(path).read_text())


class ChaosInjector:
    """The live, counted instance of a plan, threaded through one run/sweep.

    ``fire(point, **ctx)`` is called at every wired seam; it scans the plan
    for an armed fault matching (point, ctx), decrements its remaining
    count, records it on the ``fired`` ledger (and as a ``chaos`` telemetry
    span when a recorder is bound), then acts: raise
    :class:`ChaosError`/:class:`ChaosPermanentError`/:class:`InjectedHang`/
    ``OSError(ENOSPC)``, or SIGKILL this process. At most one fault fires
    per call. No match is a cheap no-op — and call sites guard with
    ``if chaos is not None`` so a chaos-less run pays nothing at all.
    """

    def __init__(self, plan: ChaosPlan, telemetry=None):
        self.plan = plan
        self.telemetry = telemetry
        self._remaining = [f.count for f in plan.faults]
        #: Ledger of fired faults, newest last: {point, kind, **ctx}.
        self.fired: list[dict[str, Any]] = []

    def bind_telemetry(self, recorder) -> None:
        """Adopt the run's recorder (first binding wins, so a CLI-built
        injector keeps the recorder it was constructed with)."""
        if self.telemetry is None:
            self.telemetry = recorder

    def fire(self, point: str, /, **ctx: Any) -> None:
        for i, fault in enumerate(self.plan.faults):
            if fault.point != point or self._remaining[i] == 0:
                continue
            if not fault.matches(ctx):
                continue
            if self._remaining[i] > 0:
                self._remaining[i] -= 1
            record = {"point": point, "kind": fault.kind, **ctx}
            self.fired.append(record)
            logger.warning("chaos: injecting %s fault at %s %s", fault.kind, point, ctx)
            if self.telemetry is not None:
                # Emitted BEFORE acting: the recorder is line-buffered, so
                # even the sigkill drill leaves its own span in the ledger.
                # (The recorder skips its telemetry.write hook for "chaos"
                # spans, so this cannot recurse into another injection.)
                self.telemetry.emit("chaos", point=point, kind=fault.kind, **ctx)
            self._act(fault, point)
            return

    def _act(self, fault: FaultSpec, point: str) -> None:
        msg = f"injected {fault.kind} fault at {point}"
        if fault.note:
            msg += f" ({fault.note})"
        if fault.kind == "transient":
            raise ChaosError(msg)
        if fault.kind == "permanent":
            raise ChaosPermanentError(msg)
        if fault.kind == "hang":
            raise InjectedHang(msg)
        if fault.kind == "enospc":
            raise OSError(errno.ENOSPC, msg)
        # sigkill: the mid-save / mid-window preemption drill. SIGKILL is
        # unmaskable — no finally blocks, no atexit, exactly like a
        # preempted TPU VM disappearing under the run.
        os.kill(os.getpid(), signal.SIGKILL)


def as_injector(chaos) -> ChaosInjector | None:
    """Coerce the public plumbing surface — None, a :class:`ChaosPlan`, an
    existing injector, or a path to a plan JSON — into the one injector
    instance threaded through a run. Shared by runner/sweep/CLI so every
    entry point accepts the same spellings."""
    if chaos is None or isinstance(chaos, ChaosInjector):
        return chaos
    if isinstance(chaos, ChaosPlan):
        return ChaosInjector(chaos)
    return ChaosInjector(load_plan(chaos))


class _FetchWorker:
    """The process-wide reusable fetch-watchdog thread.

    One daemon thread pulls (thunk, reply-queue) tasks off ``tasks`` and
    runs them. When a deadline expires the *caller* marks the worker
    ``abandoned`` and stops routing work to it: the wedged thread cannot be
    cancelled, but it exits on its own the moment the stuck fetch unwedges
    (the sentinel ``None`` task covers the raced-but-not-wedged case), and
    the stale result is dropped instead of being delivered to a caller that
    long since re-dispatched synchronously.
    """

    def __init__(self) -> None:
        self.tasks: queue.Queue = queue.Queue()
        self.abandoned = False
        self.thread = threading.Thread(
            target=self._loop, daemon=True, name="tpusim-fetch-watchdog"
        )
        self.thread.start()

    def _loop(self) -> None:
        while True:
            task = self.tasks.get()
            if task is None:  # abandonment sentinel: retire quietly
                return
            thunk, out = task
            try:
                result = (True, thunk())
            except BaseException as e:  # noqa: BLE001 — relayed to the caller
                result = (False, e)
            if self.abandoned:
                return  # stale result; the caller already gave up on us
            out.put(result)


#: Current reusable watchdog, lazily (re)spawned; the lock only guards the
#: handoff — no blocking work ever runs under it (JX018).
_fetch_worker: _FetchWorker | None = None
_fetch_worker_lock = threading.Lock()


def fetch_with_deadline(thunk, timeout_s: float, what: str = "done-flag fetch"):
    """Run a blocking device fetch with a wall-clock watchdog.

    The tunneled TPU backend can wedge a transfer inside C land where no
    signal-based timeout fires (the same failure mode tpusim.probe exists
    for, here striking mid-pipeline). The fetch therefore runs on a shared
    daemon worker thread; if it outlives ``timeout_s`` a
    :class:`PipelineStallError` is raised and the worker is abandoned — it
    cannot be cancelled, but it retires itself as soon as the stuck fetch
    unwedges, and the next call spawns a fresh worker. Results/exceptions
    from a fetch that completes in time are returned/re-raised unchanged.

    Cost: ONE persistent daemon thread reused across calls (the pipelined
    loop fetches once per multi-second chunk, serialized by construction).
    The thread population is bounded: steady state is a single idle worker;
    each stall leaves at most one abandoned worker alive only while its
    fetch stays wedged. Concurrent callers are serialized through the one
    worker — acceptable while the only client is the single pipelined
    dispatch loop per process.
    """
    global _fetch_worker
    with _fetch_worker_lock:
        if _fetch_worker is None or not _fetch_worker.thread.is_alive():
            _fetch_worker = _FetchWorker()
        worker = _fetch_worker
    out: queue.Queue = queue.Queue(maxsize=1)
    worker.tasks.put((thunk, out))
    try:
        ok, value = out.get(timeout=timeout_s)
    except queue.Empty:
        with _fetch_worker_lock:
            worker.abandoned = True
            worker.tasks.put(None)  # unblocks a raced (not wedged) worker
            if _fetch_worker is worker:
                _fetch_worker = None
        raise PipelineStallError(
            f"{what} exceeded the {timeout_s:.1f}s wall-clock watchdog deadline"
        ) from None
    if ok:
        return value
    raise value
