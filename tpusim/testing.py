"""Test utilities: drive the O(1) automaton with prescribed events and convert
between explicit chains and automaton state.

``drive_state_events`` replays the exact per-event logic of
``tpusim.engine._step`` but with injected (interval, winner) sequences instead
of keyed draws, so the automaton can be compared step-for-step against the
literal-chain oracle (tpusim.backend.pychain) on identical event streams.

``state_from_chains`` builds a SimState from explicit per-miner chains —
mirroring how the reference unit tests construct ``Miner::chain`` literally
(reference test.cpp:213-367) — so every selfish-strategy case ports as an
exact-state test of the vectorized kernel.

``compile_count_guard`` is the runtime complement of the JX006 lint rule
(tpusim.lint): the linter can only flag recompilation *risk* statically; the
guard pins the actual XLA compile count of a block, so tier-1 tests enforce
that the headline batch loop compiles exactly once per program shape.

``thread_leak_guard`` is the same pattern applied to the JX015-JX019
thread-safety pass: the linter pins lifecycle discipline statically; the
guard pins the live thread population of a block, so the fleet/chaos/metrics
suites enforce "no new non-daemon threads, bounded daemon delta" at runtime.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Sequence

import numpy as np
import jax.numpy as jnp

from .backend.pychain import Block
from .config import SimConfig
from .state import (
    I32,
    INF_TIME,
    TIME_CAP,
    TIME,
    SimState,
    earliest_arrival,
    final_stats,
    found_block,
    init_state,
    make_params,
    notify,
)


def drive_state_events(
    config: SimConfig, intervals: Sequence[int], winners: Sequence[int]
) -> tuple[SimState, dict]:
    """Run one simulation on the automaton with pre-drawn events; returns the
    final state and final stats. Mirrors engine._step exactly (found-if-due,
    deferred notify on same-ms finds, cut-through)."""
    params = make_params(config)
    exact = config.resolved_mode == "exact"
    state = init_state(config.network.n_miners, config.resolved_group_slots, exact)
    state = state._replace(next_block_time=jnp.asarray(int(intervals[0]), TIME))
    i_interval, i_winner = 1, 0
    duration = config.duration_ms
    assert duration < int(TIME_CAP), (
        "drive_state_events runs un-rebased; keep durations < TIME_CAP"
    )

    while int(state.t) < duration:
        found_due = int(state.t) == int(state.next_block_time)
        if found_due:
            state = found_block(state, params, jnp.asarray(winners[i_winner], I32))
            i_winner += 1
            state = state._replace(
                next_block_time=state.t + jnp.asarray(int(intervals[i_interval]), TIME)
            )
            i_interval += 1
        skip = found_due and int(state.next_block_time) == int(state.t)
        if not skip:
            state = notify(state, params)
        new_t = max(min(int(state.next_block_time), int(earliest_arrival(state))), int(state.t))
        state = state._replace(t=jnp.asarray(new_t, TIME))
    return state, {
        k: np.asarray(v)
        for k, v in final_stats(state, jnp.asarray(duration, TIME)).items()
    }


def _common_prefix_owner_counts(chains: Sequence[Sequence[Block]], n_miners: int) -> np.ndarray:
    m = len(chains)
    cp = np.zeros((m, m, n_miners), dtype=np.int32)
    for i in range(m):
        for j in range(m):
            for (o1, a1), (o2, a2) in zip(chains[i], chains[j]):
                if (o1, a1) != (o2, a2):
                    break
                cp[i, j, o1] += 1
    return cp


def state_from_chains(
    chains: Sequence[Sequence[Block]],
    t: int,
    config: SimConfig,
    *,
    stale: Sequence[int] | None = None,
    best_height_prev: int | None = None,
) -> SimState:
    """Build a SimState equivalent to the given explicit chains at time ``t``.

    Chains are (owner, arrival) lists excluding genesis, arrival=None for
    private blocks. Raises if a chain violates the invariants the automaton
    relies on (trailing-only private/unarrived blocks, sorted arrivals)."""
    m = len(chains)
    k = config.resolved_group_slots
    exact = config.resolved_mode == "exact"
    height = np.array([len(c) for c in chains], dtype=np.int32)
    n_private = np.zeros(m, np.int32)
    base_tip = np.zeros(m, np.int32)
    group_arrival = np.full((m, k), int(INF_TIME), np.int32)
    group_count = np.zeros((m, k), np.int32)

    for i, chain in enumerate(chains):
        idx = len(chain)
        while idx > 0 and chain[idx - 1][1] is None:
            if chain[idx - 1][0] != i:
                raise ValueError("private blocks must be own blocks")
            idx -= 1
        n_private[i] = len(chain) - idx
        groups: list[tuple[int, int]] = []
        while idx > 0 and chain[idx - 1][1] is not None and chain[idx - 1][1] > t:
            owner, arrival = chain[idx - 1]
            if owner != i:
                raise ValueError("unarrived blocks must be trailing own blocks")
            if groups and groups[0][0] == arrival:
                groups[0] = (arrival, groups[0][1] + 1)
            else:
                groups.insert(0, (arrival, 1))
            idx -= 1
        if len(groups) > k:
            raise ValueError(f"needs {len(groups)} group slots, have {k}")
        for g, (arrival, count) in enumerate(groups):
            group_arrival[i, g] = arrival
            group_count[i, g] = count
        base_tip[i] = chain[idx - 1][1] if idx > 0 else 0

    cp = _common_prefix_owner_counts(chains, m)
    own_in = np.zeros((m, m), np.int32)
    own_cp = np.zeros((m, m), np.int32)
    for i in range(m):
        for owner, _ in chains[i]:
            own_in[i, owner] += 1
        own_cp[i, :] = cp[i, :, i]
    own_cnt = np.diagonal(own_in).copy()

    pub_len = [len(ch) - int(n_private[i]) - int(group_count[i].sum()) for i, ch in enumerate(chains)]
    return SimState(
        t=jnp.asarray(t, TIME),
        next_block_time=jnp.asarray(t, TIME),
        best_height_prev=jnp.asarray(
            max(pub_len) if best_height_prev is None else best_height_prev, I32
        ),
        height=jnp.asarray(height),
        n_private=jnp.asarray(n_private),
        stale=jnp.asarray(stale if stale is not None else np.zeros(m, np.int32), I32),
        base_tip_arrival=jnp.asarray(base_tip),
        group_arrival=jnp.asarray(group_arrival),
        group_count=jnp.asarray(group_count),
        overflow=jnp.zeros((), I32),
        cp=jnp.asarray(cp) if exact else None,
        own_cp=jnp.asarray(own_cp),
        own_in=jnp.asarray(own_in),
        own_cnt=jnp.asarray(own_cnt),
    )


def canonical_view(state: SimState, t: int) -> dict:
    """Chain-level observable facts of a SimState, for comparison.

    Group entries with ``arrival <= t`` are folded into the base tip rather
    than listed as in-flight: a selfish reveal with 0 ms propagation stamps
    ``arrival == t`` *after* the sweep's flush, so the entry legitimately
    sits in the buffer until the next flush — it is already observably
    published (every published-height/tip computation compares arrivals
    against the current time), exactly as the reference's revealed block is
    already counted by ``UnpublishedBlocks`` before any event processes it.
    """
    m = state.height.shape[0]
    arrivals = []
    base_eff = []
    for i in range(m):
        expand: list[int] = []
        tip = int(state.base_tip_arrival[i])
        for g in range(state.group_arrival.shape[1]):
            a = int(state.group_arrival[i, g])
            cnt = int(state.group_count[i, g])
            if cnt and a <= t:
                tip = a  # groups are sorted; the last arrived entry wins
            else:
                expand += [a] * cnt
        arrivals.append(expand)
        base_eff.append(tip)
    # Pairwise arrays with their non-authoritative diagonals replaced from
    # own_cnt (tpusim.state module docstring), and the derived
    # own-blocks-above-lca matrix the stale accounting uses.
    ocp = np.asarray(state.own_cp).copy()
    oin = np.asarray(state.own_in).copy()
    ocnt = np.asarray(state.own_cnt)
    np.fill_diagonal(ocp, ocnt)
    np.fill_diagonal(oin, ocnt)
    own_above = (ocnt[:, None] - ocp).tolist()
    if state.cp is None:
        cp = None
    else:
        # Canonicalize the exact tensor's lazily-maintained i == j planes
        # (their authority is own_in, diagonal from own_cnt).
        cp = np.asarray(state.cp).copy()
        for i in range(m):
            cp[i, i, :] = oin[i]
        cp = cp.tolist()
    return {
        "base_tip_arrival_effective": base_eff,
        "height": np.asarray(state.height).tolist(),
        "n_private": np.asarray(state.n_private).tolist(),
        "stale": np.asarray(state.stale).tolist(),
        "inflight_arrivals": arrivals,
        "cp": cp,
        "own_above": own_above,
        "own_in": oin.tolist(),
        "own_cnt": ocnt.tolist(),
    }


def assert_state_matches_chains(
    state: SimState, chains: Sequence[Sequence[Block]], t: int, config: SimConfig
) -> None:
    """Assert a SimState is observationally identical to explicit chains,
    ignoring bookkeeping that chains don't carry (stale, best_height_prev)."""
    expected = state_from_chains(
        chains, t, config, stale=np.asarray(state.stale), best_height_prev=int(state.best_height_prev)
    )
    got, want = canonical_view(state, t), canonical_view(expected, t)
    for key in want:
        assert got[key] == want[key], f"{key}: got {got[key]}, want {want[key]}"


class CompileCount:
    """Live counter handed out by :func:`compile_count_guard` — ``count`` is
    the number of XLA backend compilations observed so far inside the block."""

    def __init__(self) -> None:
        self.count = 0
        self.events: list[str] = []


@contextlib.contextmanager
def compile_count_guard(*, exact: int | None = None, max_compiles: int | None = None):
    """Count XLA backend compilations inside the ``with`` block via
    ``jax.monitoring``'s duration events, and (optionally) assert on exit.

    This is the enforcement half of the JX006 lint rule: the linter flags
    *risk* of per-iteration recompilation statically; this guard pins the
    measured compile count, so a test can state "this batch loop compiles
    exactly once" as an invariant instead of a hope. Usage::

        with compile_count_guard(exact=0):
            engine.run_batch(keys)     # warm cache: must NOT recompile

    The counter recognizes the backend-compile duration event across the jax
    versions this repo supports (``/jax/core/compile/backend_compile_duration``
    on 0.4.x, ``/jax/backend_compile`` on older releases). Counting happens in
    THIS process only, and listener registration is process-global in jax —
    the guard keeps one listener registered forever and gates it with a
    stack of active counters, because 0.4.x has no public unregister API.
    """
    counter = CompileCount()
    _active_counters.append(counter)
    try:
        _ensure_listener()
        yield counter
    finally:
        _active_counters.remove(counter)
    if exact is not None and counter.count != exact:
        raise AssertionError(
            f"expected exactly {exact} XLA compilation(s) in block, observed "
            f"{counter.count}: {counter.events}"
        )
    if max_compiles is not None and counter.count > max_compiles:
        raise AssertionError(
            f"expected <= {max_compiles} XLA compilation(s) in block, observed "
            f"{counter.count}: {counter.events}"
        )


class ThreadCensus:
    """Live census handed out by :func:`thread_leak_guard`."""

    def __init__(self) -> None:
        self.before: set[int] = {
            t.ident for t in threading.enumerate() if t.ident is not None
        }

    def new_threads(self) -> list[threading.Thread]:
        """Threads alive now that were not alive when the guard entered."""
        return [
            t for t in threading.enumerate()
            if t.is_alive() and t.ident not in self.before
        ]


@contextlib.contextmanager
def thread_leak_guard(*, max_daemon_delta: int = 0, settle_s: float = 5.0):
    """Assert the ``with`` block leaks no threads: zero new *non-daemon*
    threads and at most ``max_daemon_delta`` new daemon threads at exit.

    This is the enforcement half of the JX015-JX019 lint pass: the linter
    flags lifecycle *discipline* statically (unjoined non-daemon threads,
    dropped handles); this guard pins the measured thread population, so a
    test can state "this drill leaves the process thread-clean" as an
    invariant instead of a hope. Usage::

        with thread_leak_guard(max_daemon_delta=1):
            run_fleet_drill()   # may keep ONE reusable daemon (watchdog)

    Exit polls briefly (``settle_s``, 20 ms steps) before failing, so
    threads mid-teardown — a joined worker whose OS thread has not yet
    vanished from ``threading.enumerate()`` — do not flake the guard.
    Identity is by thread ident, so a thread that exits and is replaced by
    an equivalent one still counts as a delta (by design: churn is a leak
    with extra steps).
    """
    census = ThreadCensus()
    yield census
    deadline = time.monotonic() + settle_s
    while True:
        new = census.new_threads()
        non_daemon = [t for t in new if not t.daemon]
        daemons = [t for t in new if t.daemon]
        if not non_daemon and len(daemons) <= max_daemon_delta:
            return
        if time.monotonic() >= deadline:
            break
        time.sleep(0.02)
    names = [f"{t.name}{'' if t.daemon else ' (non-daemon)'}" for t in new]
    raise AssertionError(
        f"thread leak: {len(non_daemon)} new non-daemon thread(s) and "
        f"{len(daemons)} new daemon thread(s) (allowed: 0 non-daemon, "
        f"{max_daemon_delta} daemon) still alive {settle_s:.0f}s after "
        f"block exit: {names}"
    )


_active_counters: list[CompileCount] = []
_compile_subscribers: list = []
_listener_installed = False


def _is_backend_compile_event(name: str) -> bool:
    return "backend_compile" in name


def subscribe_backend_compiles(fn):
    """Register ``fn(event_name, secs)`` for every XLA backend compile this
    process performs, on the SAME process-global listener the guard uses (one
    registration, shared — 0.4.x has no unregister API, so every consumer
    must ride one listener instead of stacking its own forever). Returns a
    zero-argument unsubscribe callable. Subscriber exceptions are swallowed:
    a telemetry sink must never be able to fail a compile.

    This is the hook behind :class:`tpusim.telemetry.CompileLedger` — the
    observability half of the compile story, where this guard is the
    assertion half."""
    _ensure_listener()
    _compile_subscribers.append(fn)

    def unsubscribe() -> None:
        if fn in _compile_subscribers:
            _compile_subscribers.remove(fn)

    return unsubscribe


def _ensure_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    import jax.monitoring

    def _on_duration(name: str, secs: float, **kw) -> None:
        if not _is_backend_compile_event(name):
            return
        for counter in _active_counters:
            counter.count += 1
            counter.events.append(name)
        for fn in list(_compile_subscribers):
            try:
                fn(name, secs)
            except Exception:  # noqa: BLE001 — see subscribe_backend_compiles
                pass

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _listener_installed = True
