"""Command-line interface.

Reproduces the reference driver's canonical output format (main.cpp:223-234)
on top of the declarative config system, plus structured JSON emission and a
sweep mode covering the BASELINE.json configurations — the reference's
edit-and-recompile workflow (README.md:21-27) becomes flags/config files.

Examples:
    python -m tpusim --runs 1024 --propagation-ms 10000
    python -m tpusim --hashrates 40,19,12,11,8,5,3,1,1 --selfish 0
    python -m tpusim --config sweep.json --json out.json
    python -m tpusim --runs 1024 --telemetry artifacts/telemetry/run.jsonl
    python -m tpusim report artifacts/telemetry/run.jsonl --format md
    python -m tpusim watch artifacts/telemetry/run.jsonl
    python -m tpusim trace --runs 4 --days 2 --trace-out flight.trace.json
    python -m tpusim trace diff jax_events.jsonl native_events.jsonl
    python -m tpusim trace timeline fleet/ --out orchestration.trace.json
    python -m tpusim perf run --quick
    python -m tpusim perf compare artifacts/perf/calibration_cpu.jsonl new.jsonl
    python -m tpusim fleet propagation --workers 4 --state-dir fleet/
    python -m tpusim fleet propagation --workers 4 --state-dir fleet/ --resume
    python -m tpusim metrics export fleet/ --out artifacts/metrics/fleet.prom
    python -m tpusim metrics serve --state-dir fleet/ --port 9109
    python -m tpusim slo check fleet/
    python -m tpusim serve --state-dir serve/ --port 8700
    python -m tpusim slo check serve/ --profile serve
    python -m tpusim audit fleet/ --lineage artifacts/provenance/lineage.jsonl
    python -m tpusim lineage show rows.jsonl --lineage artifacts/provenance/lineage.jsonl
    python -m tpusim bundle create evidence.tar rows.jsonl artifacts/provenance/
    python -m tpusim bundle verify evidence.tar

The ``report`` subcommand (tpusim.report) renders a ``--telemetry`` JSONL
ledger — or a ``--trace-dir`` XLA trace directory — into a dashboard; the
``watch`` subcommand (tpusim.watch) is its live twin: a terminal dashboard
that tails a growing ledger (``--once`` for a CI/dead-terminal snapshot);
the ``trace`` subcommand (tpusim.flight_export) runs with the device event
flight recorder on and exports a Perfetto timeline / JSONL event log, with
``trace diff`` as the structured cross-backend event-log comparator.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .config import DEFAULT_DURATION_MS, DEFAULT_RUNS, MinerConfig, NetworkConfig, SimConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpusim", description=__doc__)
    p.add_argument("--config", type=Path, help="JSON SimConfig (overrides network flags)")
    p.add_argument("--runs", type=int, default=DEFAULT_RUNS)
    p.add_argument("--duration-ms", type=int, default=DEFAULT_DURATION_MS)
    p.add_argument("--days", type=float, help="duration in days (overrides --duration-ms)")
    p.add_argument(
        "--hashrates",
        type=str,
        default="30,29,12,11,8,5,3,1,1",
        help="comma-separated integer hashrate percentages (must sum to 100)",
    )
    p.add_argument(
        "--propagation-ms",
        type=str,
        default="1000",
        help="propagation in ms: one value for all miners, or comma-separated per miner",
    )
    p.add_argument("--selfish", type=str, default="", help="comma-separated selfish miner indices")
    p.add_argument("--block-interval-s", type=float, default=600.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--batch-size", type=int, default=None,
        help="runs per device batch (default: SimConfig's tuned default)",
    )
    p.add_argument("--mode", choices=("auto", "exact", "fast"), default="auto")
    p.add_argument(
        "--rng",
        choices=("threefry", "xoroshiro"),
        default="threefry",
        help="sampling generator: counter-based threefry (default) or the "
        "reference's sequential xoroshiro128++ streams, bit-compatible with "
        "the native backend",
    )
    p.add_argument(
        "--backend",
        choices=("tpu", "cpp"),
        default="tpu",
        help="execution backend: the JAX engine (default) or the native C++ oracle",
    )
    p.add_argument("--threads", type=int, default=0, help="cpp backend: OS threads (0 = all cores)")
    p.add_argument("--checkpoint", type=Path, help="npz path for batch-level checkpoint/resume")
    p.add_argument("--json", type=Path, help="also write structured results to this path")
    p.add_argument("--single-device", action="store_true", help="disable multi-device sharding")
    p.add_argument(
        "--engine",
        choices=("auto", "pallas", "scan"),
        default="auto",
        help="force the execution engine (pallas = single-TPU VMEM kernel, "
        "draw-identical to scan; auto picks per platform)",
    )
    p.add_argument(
        "--group-slots", type=int, default=None,
        help="in-flight arrival-group buffer slots per (run, miner); "
        "default auto (2 in both modes; 4 reproduces pre-round-5 exact "
        "configs). Part of the sampling identity.",
    )
    p.add_argument(
        "--chunk-steps", type=int, default=None,
        help="scan steps per jitted chunk; default auto. Part of the "
        "sampling identity (sets the step->key mapping).",
    )
    p.add_argument(
        "--tile-runs", type=int, default=None,
        help="pallas engine: runs per kernel tile (multiple of 128); "
        "default measured per mode (512 fast / 256 exact)",
    )
    p.add_argument(
        "--step-block", type=int, default=None,
        help="pallas engine: scan steps per kernel invocation (default 64)",
    )
    p.add_argument("--quiet", action="store_true", help="suppress progress output")
    p.add_argument("--profile", action="store_true", help="print phase/throughput telemetry")
    p.add_argument(
        "--trace-dir", type=Path, help="emit an XLA device trace here (TensorBoard format)"
    )
    p.add_argument(
        "--telemetry", type=Path, metavar="JSONL",
        help="append structured run spans (batches, checkpoints, retries, "
        "device-side sim counters, per-batch convergence stats) here; "
        "render with `tpusim report`, tail live with `tpusim watch`",
    )
    p.add_argument(
        "--ci-target", type=float, default=0.01, metavar="REL_HW",
        help="target relative 95%% CI half-width: the ETA extrapolation in "
        "the --telemetry stats spans, and the stop threshold when "
        "--ci-target-stat arms run-until-confident (default 0.01 = 1%%)",
    )
    from .convergence import STATS

    p.add_argument(
        "--ci-target-stat", default=None, metavar="STAT",
        # One source of truth with the runner's validation: the jax-free
        # convergence statistic registry.
        choices=tuple(s for s, _, _ in STATS),
        help="run-until-confident: stop the batch loop once this statistic's "
        "worst relative 95%% CI half-width (across miners) crosses "
        "--ci-target — --runs then bounds the budget instead of fixing the "
        "count; the closing run span records converged/stop_reason",
    )
    p.add_argument(
        "--chaos", type=Path, metavar="PLAN",
        help="JSON chaos plan (tpusim.chaos): deterministic fault-injection "
        "drill — injected faults land as `chaos` telemetry spans and the "
        "run must survive through the documented recovery paths",
    )
    return p


def config_from_args(args: argparse.Namespace) -> SimConfig:
    if args.config:
        config = SimConfig.from_json(args.config.read_text())
        # Sampling-identity flags still apply on top of a config file —
        # silently dropping them would let a fingerprint "confirm" an
        # identity the user believes they overrode.
        import dataclasses

        overrides = {}
        if args.group_slots is not None:
            overrides["group_slots"] = args.group_slots
        if args.chunk_steps is not None:
            overrides["chunk_steps"] = args.chunk_steps
        return dataclasses.replace(config, **overrides) if overrides else config
    hashrates = [int(x) for x in args.hashrates.split(",")]
    props = [int(x) for x in args.propagation_ms.split(",")]
    if len(props) == 1:
        props = props * len(hashrates)
    if len(props) != len(hashrates):
        raise SystemExit("--propagation-ms must have 1 value or one per miner")
    selfish = {int(x) for x in args.selfish.split(",") if x != ""}
    miners = tuple(
        MinerConfig(hashrate_pct=h, propagation_ms=pr, selfish=(i in selfish))
        for i, (h, pr) in enumerate(zip(hashrates, props))
    )
    duration_ms = int(args.days * 86_400_000) if args.days else args.duration_ms
    kwargs = {}
    if args.batch_size is not None:
        kwargs["batch_size"] = args.batch_size
    if args.group_slots is not None:
        kwargs["group_slots"] = args.group_slots
    if args.chunk_steps is not None:
        kwargs["chunk_steps"] = args.chunk_steps
    return SimConfig(
        network=NetworkConfig(miners=miners, block_interval_s=args.block_interval_s),
        duration_ms=duration_ms,
        runs=args.runs,
        seed=args.seed,
        mode=args.mode,
        rng=args.rng,
        **kwargs,
    )


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "report":
        # Subcommand dispatch ahead of the flat flag parser: the run flags
        # and the report flags share no surface, and a bare leading "report"
        # can never be a value of any run flag.
        from .report import main as report_main

        return report_main(argv[1:])
    if argv and argv[0] == "watch":
        # Same dispatch rule as "report". Imports nothing heavy — the watch
        # dashboard is jax-free by design, so it starts instantly on a
        # machine that is busy running the simulation it observes.
        from .watch import main as watch_main

        return watch_main(argv[1:])
    if argv and argv[0] == "lint":
        # Same dispatch rule as "report". Imports nothing heavy: the linter
        # is pure-AST and must run (fast) in CI before any jax import.
        from .lint.cli import main as lint_main

        return lint_main(argv[1:])
    if argv and argv[0] == "trace":
        if len(argv) > 1 and argv[1] == "timeline":
            # `trace timeline` merges ledgers a fleet already wrote — it is
            # jax-free by design (tpusim.tracing) and must stay usable on a
            # host with no backend, so it dispatches BEFORE the flight
            # exporter (whose module import pulls the device recorder).
            from .tracing import timeline_main

            return timeline_main(argv[2:])
        # Same dispatch rule: run with the event flight recorder enabled and
        # export a Perfetto timeline / JSONL event log (tpusim.flight_export).
        from .flight_export import main as trace_main

        return trace_main(argv[1:])
    if argv and argv[0] == "perf":
        # Same dispatch rule. The module import is jax-free; only `perf run`
        # initializes a backend — `perf compare` (the CI noise gate) and
        # `perf report` must work on a host with none.
        from .perf import main as perf_main

        return perf_main(argv[1:])
    if argv and argv[0] == "metrics":
        # Same dispatch rule. The metrics plane is jax-free by design: the
        # exporter and the scrape endpoint re-read a live state dir through
        # the tolerant ledger loaders and must start instantly on a host
        # with no backend (tpusim.metrics).
        from .metrics import main as metrics_main

        return metrics_main(argv[1:])
    if argv and argv[0] == "slo":
        # Same dispatch rule. `slo check` is the CI gate over the metrics
        # plane — perf-compare exit discipline (0 pass / 1 violation /
        # 2 structural-or-dead-gate), no backend import ever.
        from .metrics import slo_main

        return slo_main(argv[1:])
    if argv and argv[0] == "serve":
        # Same dispatch rule. The service front half is jax-free by design
        # (stdlib ThreadingHTTPServer) — the daemon binds its port and
        # answers /healthz instantly; only its dispatch worker thread pulls
        # the engine stack on the first query (tpusim.serve).
        from .serve import main as serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "fleet":
        # Same dispatch rule. The supervisor is jax-free by design — only
        # its subprocess workers initialize a backend, so a wedged device
        # can never take the supervisor down with it (tpusim.fleet).
        from .fleet import main as fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "audit":
        # Same dispatch rule. The cross-plane consistency gate joins ledgers
        # already on disk — jax-free, perf-compare exit discipline (0 pass /
        # 1 violation / 2 structural-or-dead-gate), runs on any host
        # (tpusim.provenance).
        from .provenance import audit_main

        return audit_main(argv[1:])
    if argv and argv[0] == "lineage":
        # Same dispatch rule. Walks an artifact's recorded parent chain —
        # pure ledger reads, no backend import ever (tpusim.provenance).
        from .provenance import lineage_main

        return lineage_main(argv[1:])
    if argv and argv[0] == "bundle":
        # Same dispatch rule. Seals/verifies evidence tarballs offline —
        # stdlib tarfile + sha256 only (tpusim.provenance).
        from .provenance import bundle_main

        return bundle_main(argv[1:])
    args = build_parser().parse_args(argv)
    try:
        config = config_from_args(args)
    except ValueError as e:
        raise SystemExit(f"error: {e}") from None

    if args.backend == "cpp":
        if args.checkpoint:
            raise SystemExit(
                "error: --checkpoint is only supported on the tpu backend; "
                "the cpp oracle runs to completion in one call"
            )
        if args.profile or args.trace_dir or args.telemetry:
            raise SystemExit(
                "error: --profile/--trace-dir/--telemetry instrument the tpu "
                "backend; the cpp backend reports its own elapsed time in "
                "--json output"
            )
        if args.engine != "auto":
            raise SystemExit(
                "error: --engine picks the JAX execution engine; "
                "the cpp backend has none"
            )
        if args.chaos:
            raise SystemExit(
                "error: --chaos injects faults at the tpu backend's "
                "orchestration seams; the cpp backend has none"
            )
        if args.ci_target_stat:
            raise SystemExit(
                "error: --ci-target-stat drives the tpu backend's batch "
                "loop; the cpp backend runs to completion in one call"
            )
        if args.tile_runs is not None or args.step_block is not None:
            raise SystemExit(
                "error: --tile-runs/--step-block tune the pallas kernel; "
                "the cpp backend has none"
            )
        if args.group_slots is not None or args.chunk_steps is not None:
            raise SystemExit(
                "error: --group-slots/--chunk-steps pin the JAX engine's "
                "sampling identity; the cpp backend's sequential sampling "
                "has neither"
            )
        from .backend.cpp import run_simulation_cpp

        print(f"Running {config.runs} simulations on the native C++ backend.")
        results = run_simulation_cpp(config, threads=args.threads or None)
    else:
        import jax

        from .runner import run_simulation_config

        n_dev = len(jax.devices())
        print(
            f"Running {config.runs} simulations in parallel using {n_dev} "
            f"{jax.devices()[0].platform} device(s)."
        )

        def progress(done: int, total: int) -> None:
            print(f"\r{done * 100 // total}% progress..", end="", flush=True)

        profiler = None
        if args.profile or args.trace_dir:
            from .profiling import Profiler

            profiler = Profiler(trace_dir=str(args.trace_dir) if args.trace_dir else None)

        recorder = None
        if args.telemetry:
            from .telemetry import TelemetryRecorder

            recorder = TelemetryRecorder(args.telemetry)

        chaos = None
        if args.chaos:
            from .chaos import ChaosInjector, load_plan

            chaos = ChaosInjector(load_plan(args.chaos))

        from contextlib import nullcontext

        try:
            with profiler.trace() if profiler else nullcontext():
                results = run_simulation_config(
                    config,
                    use_all_devices=not args.single_device,
                    progress=None if args.quiet else progress,
                    checkpoint_path=args.checkpoint,
                    profiler=profiler,
                    telemetry=recorder,
                    engine=args.engine,
                    tile_runs=args.tile_runs,
                    step_block=args.step_block,
                    chaos=chaos,
                    ci_target_rel=args.ci_target,
                    ci_target_stat=args.ci_target_stat,
                )
        finally:
            if recorder is not None:
                recorder.close()
        if not args.quiet:
            print()
        if profiler is not None and args.profile:
            print("[profile]", profiler.report_json(config.duration_ms, config.network.block_interval_s))
        if recorder is not None and not args.quiet:
            print(f"[telemetry] {args.telemetry} (run_id {recorder.run_id}; "
                  f"render: python -m tpusim report {args.telemetry})")
        if chaos is not None and not args.quiet:
            # Reaching this line IS the drill's pass criterion: every
            # injected fault was survived through a documented recovery path.
            print(f"[chaos] survived {len(chaos.fired)} injected fault(s)")
    print(results.table())
    if results.overflow_total:
        print(f"  [diagnostics: {results.overflow_total} group-slot overflows]")
    if args.json:
        args.json.write_text(results.to_json())
    return 0


if __name__ == "__main__":
    sys.exit(main())
