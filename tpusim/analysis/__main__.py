import sys

from .plots import main

sys.exit(main())
