"""Stale-rate / revenue plots — the counterpart of the reference's
``plot_stale_rate/plot.py:79-110`` figures, generalized.

Two figures over a propagation-time sweep: per-miner stale rate, and relative
revenue change after difficulty retarget. Curves come from the closed-form
oracle (tpusim.analysis.oracle); optionally, simulated points from the TPU
engine are overlaid at a few propagation values so the two models can be
compared on one chart (the reference keeps them separate; the overlay is this
framework's analytical-vs-simulated validation view made visible).

Headless by default (PNG files); ``show=True`` opens interactive windows like
the reference.
"""

from __future__ import annotations

from pathlib import Path
from typing import Sequence

import numpy as np

from .oracle import analytical_net_benefits, analytical_stale_rates

#: The reference's 10-pool distribution (plot_stale_rate/plot.py:8-15).
DEFAULT_POOLS = (0.30, 0.29, 0.12, 0.11, 0.08, 0.05, 0.02, 0.01, 0.01, 0.01)


def _sweep(lo_s: float, hi_s: float, points: int) -> list[float]:
    return np.linspace(lo_s, hi_s, points).tolist()


def plot_stale_rates(
    hashrates: Sequence[float] = DEFAULT_POOLS,
    prop_lo_s: float = 0.1,
    prop_hi_s: float = 60.0,
    points: int = 120,
    block_interval_s: float = 600.0,
    simulated: dict[float, Sequence[float]] | None = None,
    out_path: str | Path | None = None,
    show: bool = False,
):
    """Per-miner stale rate vs propagation time (reference plot.py:79-91).

    ``simulated`` maps propagation seconds -> per-miner simulated stale rates
    to overlay as markers.
    """
    import matplotlib

    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    xs = _sweep(prop_lo_s, prop_hi_s, points)
    rates = [analytical_stale_rates(hashrates, x, block_interval_s) for x in xs]
    pts = sorted(simulated.items()) if simulated else []
    fig, ax = plt.subplots(figsize=(9, 5.5))
    for i, h in enumerate(hashrates):
        (line,) = ax.plot(
            xs, [r[i] * 100 for r in rates], label=f"miner {i} ({h * 100:g}%)"
        )
        if pts:
            ax.plot(
                [p for p, _ in pts],
                [r[i] * 100 for _, r in pts],
                "o",
                color=line.get_color(),
                markersize=4,
            )
    ax.set_xlabel("propagation time (s)")
    ax.set_ylabel("stale rate (%)")
    title = "Stale rate vs propagation time (lines: closed form"
    ax.set_title(title + (", dots: simulated)" if simulated else ")"))
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3)
    if out_path is not None:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    if show:
        plt.show()
    else:
        plt.close(fig)
    return fig


def plot_benefits(
    hashrates: Sequence[float] = DEFAULT_POOLS,
    prop_lo_s: float = 0.1,
    prop_hi_s: float = 60.0,
    points: int = 120,
    block_interval_s: float = 600.0,
    out_path: str | Path | None = None,
    show: bool = False,
):
    """Relative revenue change vs propagation time once difficulty retargets
    (reference plot.py:93-103): big miners gain from everyone's slow blocks."""
    import matplotlib

    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    xs = _sweep(prop_lo_s, prop_hi_s, points)
    benefits = [analytical_net_benefits(hashrates, x, block_interval_s) for x in xs]
    fig, ax = plt.subplots(figsize=(9, 5.5))
    for i, h in enumerate(hashrates):
        ax.plot(xs, [b[i] * 100 for b in benefits], label=f"miner {i} ({h * 100:g}%)")
    ax.axhline(0.0, color="black", linewidth=0.8)
    ax.set_xlabel("propagation time (s)")
    ax.set_ylabel("revenue change after retarget (%)")
    ax.set_title("Net revenue effect of propagation time")
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3)
    if out_path is not None:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    if show:
        plt.show()
    else:
        plt.close(fig)
    return fig


def simulate_overlay(
    hashrates: Sequence[float],
    props_s: Sequence[float],
    runs: int = 256,
    duration_days: float = 60.0,
    block_interval_s: float = 600.0,
    seed: int = 0,
) -> dict[float, list[float]]:
    """Simulated per-miner stale rates at the given propagation times, for
    overlaying on the analytical curves."""
    from ..config import MinerConfig, NetworkConfig, SimConfig
    from ..runner import run_simulation_config

    pct = [round(h * 100) for h in hashrates]
    if sum(pct) != 100:
        raise ValueError("hashrates must round to integer percentages summing to 100")
    out: dict[float, list[float]] = {}
    for prop in props_s:
        net = NetworkConfig(
            miners=tuple(MinerConfig(hashrate_pct=p, propagation_ms=int(prop * 1000)) for p in pct),
            block_interval_s=block_interval_s,
        )
        config = SimConfig(
            network=net,
            duration_ms=int(duration_days * 86_400_000),
            runs=runs,
            batch_size=min(runs, 4096),
            seed=seed,
        )
        res = run_simulation_config(config)
        out[prop] = [m.stale_rate_mean for m in res.miners]
    return out


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="tpusim.analysis", description=__doc__)
    p.add_argument("--out-dir", type=Path, default=Path("plots"))
    p.add_argument("--show", action="store_true", help="open interactive windows instead")
    p.add_argument("--prop-lo-s", type=float, default=0.1)
    p.add_argument("--prop-hi-s", type=float, default=60.0)
    p.add_argument("--block-interval-s", type=float, default=600.0)
    p.add_argument(
        "--simulate",
        type=int,
        default=0,
        metavar="RUNS",
        help="overlay simulated stale rates at a few propagation values (runs per point)",
    )
    args = p.parse_args(argv)

    simulated = None
    if args.simulate:
        props = [1.0, 10.0, 30.0, 60.0]
        simulated = simulate_overlay(DEFAULT_POOLS, props, runs=args.simulate)
    out1 = out2 = None
    if not args.show:
        args.out_dir.mkdir(parents=True, exist_ok=True)
        out1 = args.out_dir / "stale_rates.png"
        out2 = args.out_dir / "net_benefits.png"
    plot_stale_rates(
        prop_lo_s=args.prop_lo_s,
        prop_hi_s=args.prop_hi_s,
        block_interval_s=args.block_interval_s,
        simulated=simulated,
        out_path=out1,
        show=args.show,
    )
    plot_benefits(
        prop_lo_s=args.prop_lo_s,
        prop_hi_s=args.prop_hi_s,
        block_interval_s=args.block_interval_s,
        out_path=out2,
        show=args.show,
    )
    if not args.show:
        print(f"wrote {out1} and {out2}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
