"""Stale-rate / revenue plots — the counterpart of the reference's
``plot_stale_rate/plot.py:79-110`` figures, generalized.

Two figures over a propagation-time sweep: per-miner stale rate, and relative
revenue change after difficulty retarget. Curves come from the closed-form
oracle (tpusim.analysis.oracle); optionally, simulated points from the TPU
engine are overlaid at a few propagation values so the two models can be
compared on one chart (the reference keeps them separate; the overlay is this
framework's analytical-vs-simulated validation view made visible).

Headless by default (PNG files); ``show=True`` opens interactive windows like
the reference.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import Sequence

import numpy as np

from .oracle import analytical_net_benefits, analytical_stale_rates

#: The reference's 10-pool distribution (plot_stale_rate/plot.py:8-15).
DEFAULT_POOLS = (0.30, 0.29, 0.12, 0.11, 0.08, 0.05, 0.02, 0.01, 0.01, 0.01)


def _sweep(lo_s: float, hi_s: float, points: int) -> list[float]:
    return np.linspace(lo_s, hi_s, points).tolist()


def plot_stale_rates(
    hashrates: Sequence[float] = DEFAULT_POOLS,
    prop_lo_s: float = 0.1,
    prop_hi_s: float = 60.0,
    points: int = 120,
    block_interval_s: float = 600.0,
    simulated: dict[float, Sequence[float]] | None = None,
    out_path: str | Path | None = None,
    show: bool = False,
):
    """Per-miner stale rate vs propagation time (reference plot.py:79-91).

    ``simulated`` maps propagation seconds -> per-miner simulated stale rates
    to overlay as markers.
    """
    import matplotlib

    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    xs = _sweep(prop_lo_s, prop_hi_s, points)
    rates = [analytical_stale_rates(hashrates, x, block_interval_s) for x in xs]
    pts = sorted(simulated.items()) if simulated else []
    fig, ax = plt.subplots(figsize=(9, 5.5))
    for i, h in enumerate(hashrates):
        (line,) = ax.plot(
            xs, [r[i] * 100 for r in rates], label=f"miner {i} ({h * 100:g}%)"
        )
        if pts:
            ax.plot(
                [p for p, _ in pts],
                [r[i] * 100 for _, r in pts],
                "o",
                color=line.get_color(),
                markersize=4,
            )
    ax.set_xlabel("propagation time (s)")
    ax.set_ylabel("stale rate (%)")
    title = "Stale rate vs propagation time (lines: closed form"
    ax.set_title(title + (", dots: simulated)" if simulated else ")"))
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3)
    if out_path is not None:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    if show:
        plt.show()
    else:
        plt.close(fig)
    return fig


def plot_benefits(
    hashrates: Sequence[float] = DEFAULT_POOLS,
    prop_lo_s: float = 0.1,
    prop_hi_s: float = 60.0,
    points: int = 120,
    block_interval_s: float = 600.0,
    out_path: str | Path | None = None,
    show: bool = False,
):
    """Relative revenue change vs propagation time once difficulty retargets
    (reference plot.py:93-103): big miners gain from everyone's slow blocks."""
    import matplotlib

    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    xs = _sweep(prop_lo_s, prop_hi_s, points)
    benefits = [analytical_net_benefits(hashrates, x, block_interval_s) for x in xs]
    fig, ax = plt.subplots(figsize=(9, 5.5))
    for i, h in enumerate(hashrates):
        ax.plot(xs, [b[i] * 100 for b in benefits], label=f"miner {i} ({h * 100:g}%)")
    ax.axhline(0.0, color="black", linewidth=0.8)
    ax.set_xlabel("propagation time (s)")
    ax.set_ylabel("revenue change after retarget (%)")
    ax.set_title("Net revenue effect of propagation time")
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3)
    if out_path is not None:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    if show:
        plt.show()
    else:
        plt.close(fig)
    return fig


def plot_selfish_crossing(
    points: Sequence[dict],
    gamma: float = 0.0,
    out_path: str | Path | None = None,
    show: bool = False,
):
    """Selfish-miner block share vs hashrate: measured grid points against the
    honest-income line and the Eyal-Sirer ideal curve (oracle docstring).

    ``points`` are dicts with ``selfish_hashrate_frac``, ``selfish_share``,
    and optionally ``backend``/``runs`` (the schema of
    BASELINE.json ``published.full_scale_grids.selfish_hashrate`` rows and of
    ``sweep_selfish_hashrate_*.jsonl`` after ``selfish_points`` extraction).
    The simulated profitability crossing (share > hashrate) sits measurably
    above the ideal 1/3 because propagation delay costs the attacker reveal
    races; this figure is that result."""
    import matplotlib

    from .oracle import selfish_relative_revenue

    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    xs = np.linspace(0.20, 0.495, 200)
    fig, ax = plt.subplots(figsize=(8, 5.5))
    ax.plot(xs, xs, color="black", linewidth=0.9, linestyle="--",
            label="honest income (share = hashrate)")
    ax.plot(xs, [selfish_relative_revenue(x, gamma) for x in xs],
            color="tab:orange", linewidth=1.2,
            label=f"Eyal-Sirer ideal, gamma={gamma:g} (crossing 1/3)")
    by_backend: dict[str, list[tuple[float, float]]] = {}
    for p in points:
        by_backend.setdefault(p.get("backend", "sim"), []).append(
            (p["selfish_hashrate_frac"], p["selfish_share"])
        )
    styles = {"tpu": ("o", "tab:blue"), "cpp": ("s", "tab:purple"),
              "sim": ("^", "tab:gray")}
    for backend, pts in sorted(by_backend.items()):
        pts = sorted(pts)
        marker, color = styles.get(backend, ("x", "tab:gray"))
        ax.plot([x for x, _ in pts], [y for _, y in pts],
                marker, color=color, markersize=6, linestyle=":",
                label=f"measured ({backend})")
    # Bracket the measured crossing from the point set itself. Noisy low-run
    # points can make the measured shares non-monotonic, leaving lo >= hi —
    # an unbracketed crossing, not a reversed band (mirrors
    # crossing_bracket() in scripts/update_fullscale_published.py).
    below = [x for b in by_backend.values() for x, y in b if y <= x]
    above = [x for b in by_backend.values() for x, y in b if y > x]
    if below and above:
        lo, hi = max(below), min(above)
        if lo < hi:
            ax.axvspan(lo, hi, alpha=0.15, color="tab:red",
                       label=f"measured crossing ({lo * 100:.0f}%, {hi * 100:.0f}%)")
        else:
            ax.plot([], [], " ",
                    label="measured crossing unbracketed (non-monotonic points)")
    ax.set_xlabel("selfish hashrate fraction")
    ax.set_ylabel("block share (relative revenue)")
    ax.set_title("Selfish-mining profitability: simulated vs ideal model")
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3)
    if out_path is not None:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    if show:
        plt.show()
    else:
        plt.close(fig)
    return fig


def plot_hetero_validation(
    hashrates: Sequence[float],
    props_ms: Sequence[float],
    measured: Sequence[float],
    runs: int,
    backend: str = "cpp",
    block_interval_s: float = 600.0,
    out_path: str | Path | None = None,
    show: bool = False,
):
    """Heterogeneous-propagation centralization pressure: per-miner measured
    stale rate vs the closed-form oracle, over each miner's own propagation
    time (marker area ~ hashrate).

    The reference's oracle (plot_stale_rate/plot.py) assumes one propagation
    time for the whole network; tpusim.analysis.oracle generalizes it to
    per-miner values, and this figure validates that generalization against
    the simulated 32-miner log-spaced roster (BASELINE configs[3]) — the
    centralization gradient (fast big miners near-zero stale, slow 1 %
    miners ~10 %) on one chart."""
    import matplotlib

    from .oracle import analytical_stale_rates

    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    props_s = [p / 1000.0 for p in props_ms]
    oracle = analytical_stale_rates(list(hashrates), props_s, block_interval_s)
    order = np.argsort(props_s)
    fig, ax = plt.subplots(figsize=(8.5, 5.5))
    ax.plot(
        [props_s[i] for i in order], [oracle[i] * 100 for i in order],
        color="tab:orange", linewidth=1.2, label="closed-form oracle",
    )
    sizes = [2000.0 * h for h in hashrates]
    ax.scatter(
        props_s, [m * 100 for m in measured], s=sizes, alpha=0.6,
        color="tab:blue", edgecolors="black", linewidths=0.4,
        label=f"simulated ({backend}, {runs} runs; area = hashrate)",
    )
    ax.set_xscale("log")
    ax.set_yscale("log")
    ax.set_xlabel("miner's block propagation time (s)")
    ax.set_ylabel("stale rate (%)")
    ax.set_title("Centralization pressure, 32-miner heterogeneous propagation")
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3, which="both")
    if out_path is not None:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    if show:
        plt.show()
    else:
        plt.close(fig)
    return fig


def load_selfish_grid_points(paths: Sequence[str | Path]) -> list[dict]:
    """Extract selfish-miner (hashrate, share) points from sweep JSONL rows
    (the ``sweep_selfish_hashrate_*.jsonl`` schema); keeps the max-runs row
    per (backend, hashrate)."""
    import json

    best: dict[tuple[str, int], dict] = {}
    for path in paths:
        path = Path(path)
        backend = "cpp" if "native" in path.name or "cpp" in path.name else "tpu"
        for line in path.read_text().splitlines():
            if not line.strip():
                continue
            try:
                r = json.loads(line)
                m0 = r["miners"][0]
                if not m0.get("selfish"):
                    continue
                # Named rows from other selfish grids (e.g. the
                # block-interval x threshold sweep's interval-150s-* points)
                # are a different experiment — mixing them in would shift
                # the rendered crossing band. Unnamed rows (the pre-naming
                # full-scale native artifact) are hashrate-grid by schema.
                name = r.get("point")
                if name is not None and not re.fullmatch(r"selfish-\d+pct", name):
                    continue
                # Backend resolution order: the row's own backend key, then
                # its mode (the cpp backend stamps mode=='cpp'), and only
                # then the filename heuristic — so a legacy cpp-produced file
                # not named 'native'/'cpp' is still attributed correctly.
                backend_r = r.get("backend") or (
                    "cpp" if r.get("mode") == "cpp" else backend
                )
                key = (backend_r, m0["hashrate_pct"])
                if key in best and best[key]["runs"] >= r["runs"]:
                    continue
                best[key] = {
                    "selfish_hashrate_frac": m0["hashrate_pct"] / 100.0,
                    "selfish_share": m0["blocks_share_mean"],
                    "backend": backend_r,
                    "runs": r["runs"],
                }
            except (ValueError, KeyError, IndexError, TypeError):
                continue
    return list(best.values())


def simulate_overlay(
    hashrates: Sequence[float],
    props_s: Sequence[float],
    runs: int = 256,
    duration_days: float = 60.0,
    block_interval_s: float = 600.0,
    seed: int = 0,
) -> dict[float, list[float]]:
    """Simulated per-miner stale rates at the given propagation times, for
    overlaying on the analytical curves."""
    from ..config import MinerConfig, NetworkConfig, SimConfig
    from ..runner import run_simulation_config

    pct = [round(h * 100) for h in hashrates]
    if sum(pct) != 100:
        raise ValueError("hashrates must round to integer percentages summing to 100")
    out: dict[float, list[float]] = {}
    for prop in props_s:
        net = NetworkConfig(
            miners=tuple(MinerConfig(hashrate_pct=p, propagation_ms=int(prop * 1000)) for p in pct),
            block_interval_s=block_interval_s,
        )
        config = SimConfig(
            network=net,
            duration_ms=int(duration_days * 86_400_000),
            runs=runs,
            batch_size=min(runs, 4096),
            seed=seed,
        )
        res = run_simulation_config(config)
        out[prop] = [m.stale_rate_mean for m in res.miners]
    return out


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="tpusim.analysis", description=__doc__)
    p.add_argument("--out-dir", type=Path, default=Path("plots"))
    p.add_argument("--show", action="store_true", help="open interactive windows instead")
    p.add_argument("--prop-lo-s", type=float, default=0.1)
    p.add_argument("--prop-hi-s", type=float, default=60.0)
    p.add_argument("--block-interval-s", type=float, default=600.0)
    p.add_argument(
        "--simulate",
        type=int,
        default=0,
        metavar="RUNS",
        help="overlay simulated stale rates at a few propagation values (runs per point)",
    )
    p.add_argument(
        "--selfish-grid",
        type=Path,
        nargs="+",
        metavar="JSONL",
        help="sweep_selfish_hashrate_*.jsonl files; adds the selfish-crossing "
        "figure (measured share-vs-hashrate against the Eyal-Sirer ideal)",
    )
    p.add_argument(
        "--hetero-grid",
        type=Path,
        metavar="JSONL",
        help="a sweep_hetero32_*.jsonl file; adds the heterogeneous-"
        "propagation validation figure (measured per-miner stale rates vs "
        "the generalized oracle; roster from the hetero32 grid definition)",
    )
    p.add_argument(
        "--only-selfish-grid",
        action="store_true",
        help="suppress the propagation figures (stale_rates/net_benefits) "
        "and write only the artifact-derived ones (--selfish-grid and/or "
        "--hetero-grid) — regeneration scripts must not silently rewrite "
        "the propagation figures, whose committed versions carry a "
        "--simulate overlay",
    )
    args = p.parse_args(argv)
    if args.only_selfish_grid and not (args.selfish_grid or args.hetero_grid):
        p.error("--only-selfish-grid requires --selfish-grid or --hetero-grid")

    if not args.show:
        args.out_dir.mkdir(parents=True, exist_ok=True)
    written = []
    if not args.only_selfish_grid:
        simulated = None
        if args.simulate:
            props = [1.0, 10.0, 30.0, 60.0]
            simulated = simulate_overlay(DEFAULT_POOLS, props, runs=args.simulate)
        out1 = None if args.show else args.out_dir / "stale_rates.png"
        out2 = None if args.show else args.out_dir / "net_benefits.png"
        plot_stale_rates(
            prop_lo_s=args.prop_lo_s,
            prop_hi_s=args.prop_hi_s,
            block_interval_s=args.block_interval_s,
            simulated=simulated,
            out_path=out1,
            show=args.show,
        )
        plot_benefits(
            prop_lo_s=args.prop_lo_s,
            prop_hi_s=args.prop_hi_s,
            block_interval_s=args.block_interval_s,
            out_path=out2,
            show=args.show,
        )
        written += [out1, out2]
    if args.selfish_grid:
        missing = [p for p in args.selfish_grid if not p.exists()]
        if missing:
            print(
                "selfish-grid file(s) not found: "
                + " ".join(str(p) for p in missing),
                file=sys.stderr,
            )
            return 2
        pts = load_selfish_grid_points(args.selfish_grid)
        if not pts:
            print("no selfish points found in the given files", file=sys.stderr)
            return 2
        out3 = None if args.show else args.out_dir / "selfish_crossing.png"
        plot_selfish_crossing(pts, out_path=out3, show=args.show)
        written.append(out3)
    if args.hetero_grid:
        if not args.hetero_grid.exists():
            print(f"hetero-grid file not found: {args.hetero_grid}", file=sys.stderr)
            return 2
        import json

        from ..sweep import baseline_sweeps

        row = None
        for line in args.hetero_grid.read_text().splitlines():
            if not line.strip():
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if r.get("point") == "hetero32" and (
                row is None or r["runs"] > row["runs"]
            ):
                row = r
        if row is None:
            print(f"no hetero32 row in {args.hetero_grid}", file=sys.stderr)
            return 2
        # The artifact rows don't carry per-miner propagation; the grid
        # definition is the authority for the roster.
        (_, cfg), = baseline_sweeps()["hetero32"]()
        miners = cfg.network.miners
        out4 = None if args.show else args.out_dir / "hetero32_validation.png"
        plot_hetero_validation(
            hashrates=[m.hashrate_pct / 100.0 for m in miners],
            props_ms=[m.propagation_ms for m in miners],
            measured=[m["stale_rate_mean"] for m in row["miners"]],
            runs=row["runs"],
            backend=row.get("backend", "?"),
            block_interval_s=cfg.network.block_interval_s,
            out_path=out4,
            show=args.show,
        )
        written.append(out4)
    if not args.show:
        print("wrote " + " ".join(str(w) for w in written))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
