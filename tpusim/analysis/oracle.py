"""Closed-form stale-rate / revenue model — the analytical validation oracle.

Port of the reference's standalone model (reference plot_stale_rate/plot.py:18-77),
generalized to arbitrary hashrate vectors. For an honest network with binary
propagation, a miner's block goes stale either because someone else found a
competing block within the propagation window *before* ours (and then wins the
1-block race under the first-seen rule at gamma=0 — we only win if we find the
next block ourselves), or because any other miner finds a competing block
within the window *after* ours and then also finds the next one.

Used in tests as an independent check of the simulator's honest-path stale
rates across a propagation sweep; exact only to first order in
prop/interval (races involving 3+ blocks are neglected), which is far inside
Monte-Carlo noise for the reference configurations.
"""

from __future__ import annotations

import math
from typing import Sequence


def _p_finds_within(prop_s: float, hashrate: float, block_interval_s: float) -> float:
    """P(a miner with this hashrate share finds a block within prop_s seconds)
    (reference plot.py:18-26): exponential CDF with thinned rate."""
    lam = hashrate / block_interval_s
    return 1.0 - math.exp(-lam * prop_s)


def p_stale_before(prop_s: float, hashrate: float, block_interval_s: float = 600.0) -> float:
    """P(our block goes stale because the rest of the network found one less
    than prop_s before ours and then wins the race) (reference plot.py:28-33)."""
    rest = 1.0 - hashrate
    return _p_finds_within(prop_s, rest, block_interval_s) * rest


def p_stale_after(
    prop_s: float, other_hashrates: Sequence[float], block_interval_s: float = 600.0
) -> float:
    """P(any other miner finds a competing block within prop_s after ours and
    then also finds the next block) (reference plot.py:35-38)."""
    return sum(
        _p_finds_within(prop_s, h, block_interval_s) * h for h in other_hashrates
    )


def analytical_stale_rates(
    hashrates: Sequence[float],
    prop_s: float | Sequence[float],
    block_interval_s: float = 600.0,
) -> list[float]:
    """Per-miner stale rates for an honest network (reference plot.py:40-56).

    ``prop_s`` may be one propagation time (seconds) for all miners or one
    per miner. In the reference's propagation model a block found by ``j``
    at ``t0`` reaches every other miner at ``t0 + prop_j`` (simulation.h
    arrival semantics), so working a same-height race between blocks of
    ``i`` (found ``t1``) and ``j`` through the first-seen tiebreak gives two
    loss channels for ``i``, each with a window set by exactly one miner's
    propagation:

    * **j's block arrives first** — the find-time windows where
      ``t0 + prop_j < t1 + prop_i`` total exactly ``prop_i`` (found-before
      slot ``min(prop_i, prop_j)`` plus found-after slot
      ``max(0, prop_i - prop_j)``): every third party first-sees ``j``'s
      block, and ``i``'s block survives only if ``i`` finds the next block —
      stale with factor ``(1 - h_i)``. Lumping the rest of the network as
      one ``1 - h_i`` process, this is the reference's ``p_stale_before``
      evaluated at *our own* ``prop_i``.
    * **i's block arrives first** — the complementary windows total
      ``prop_j``: ``j`` alone is on its own branch and ``i``'s block goes
      stale only if ``j`` also finds the next block — factor ``h_j``,
      window *j's own* ``prop_j``.

    With homogeneous propagation both reduce exactly to the reference's
    formulas (plot.py:28-38). The heterogeneous form is validated against
    the simulated 32-miner log-spaced roster (tests/test_profiling_plots.py,
    artifacts/plots/hetero32_validation.png): a miner's stale rate rides its
    own propagation (the r5 pre-fix form summed competitors' windows, which
    predicted a near-uniform ~0.6 % where the simulation spans
    0.02 %-10 %).
    """
    n = len(hashrates)
    props = [float(prop_s)] * n if isinstance(prop_s, (int, float)) else [float(p) for p in prop_s]
    rates = []
    for i, h in enumerate(hashrates):
        before = p_stale_before(props[i], h, block_interval_s)
        after = sum(
            _p_finds_within(props[j], hashrates[j], block_interval_s) * hashrates[j]
            for j in range(n)
            if j != i
        )
        rates.append(before + after)
    return rates


def analytical_net_benefits(
    hashrates: Sequence[float],
    prop_s: float | Sequence[float],
    block_interval_s: float = 600.0,
) -> list[float]:
    """Relative revenue change per miner once difficulty retargets — share of
    *non-stale* blocks versus raw hashrate (reference plot.py:58-77)."""
    rates = analytical_stale_rates(hashrates, prop_s, block_interval_s)
    total_stale = sum(h * r for h, r in zip(hashrates, rates))
    total_found = 1.0 - total_stale
    out = []
    for h, r in zip(hashrates, rates):
        actual_share = h * (1.0 - r) / total_found
        out.append((actual_share - h) / h)
    return out


def selfish_relative_revenue(alpha: float, gamma: float = 0.0) -> float:
    """Eyal-Sirer ideal-model relative revenue of a selfish miner with
    hashrate fraction ``alpha`` when honest miners join the attacker's fork
    with probability ``gamma`` ("Majority is not Enough", 2013, eq. 8).

    The reference implements the gamma=0 strategy (simulation.h:62-76,
    149-174: never adopt a competing chain at equal length, publish only to
    match or beat); this closed form is the zero-propagation-delay ideal of
    that strategy, used as the analytical anchor for the full-scale
    selfish-hashrate grid: revenue crosses alpha exactly at alpha = 1/3 when
    gamma = 0, while the simulated crossing sits higher because propagation
    delay costs the attacker reveal races the ideal model gives it for free.
    """
    if not 0.0 <= alpha < 0.5:
        raise ValueError(f"alpha must be in [0, 0.5), got {alpha}")
    a, g = alpha, gamma
    num = a * (1 - a) ** 2 * (4 * a + g * (1 - 2 * a)) - a ** 3
    den = 1 - a * (1 + (2 - a) * a)
    return num / den
