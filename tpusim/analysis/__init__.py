from .oracle import (
    analytical_stale_rates,
    analytical_net_benefits,
    p_stale_before,
    p_stale_after,
)

__all__ = [
    "analytical_stale_rates",
    "analytical_net_benefits",
    "p_stale_before",
    "p_stale_after",
]
