"""Streaming convergence estimation: exact moment accumulators and the CI
derivation behind the ``stats`` telemetry spans.

The ROADMAP's adaptive-precision item ("run-until-confident") needs one
substrate before any driver can exist: a running answer to *how converged is
this simulation right now*. This module supplies it in three layers, all
jax-free (the ``tpusim watch`` dashboard imports this without initializing a
backend):

  * **Moment keys** — :func:`moment_keys` turns one batch's per-run statistic
    leaves (the device-computed ``blocks_found`` / ``blocks_share`` /
    ``stale_rate`` per (run, miner) arrays the engines' shared finalize
    already produces) into exact int64 first and second moments per miner
    plus the run count. The float ratios are quantized to fixed point FIRST
    (:data:`STATS`) so every downstream merge is integer addition — exact,
    associative and permutation-invariant, which is what makes the moments
    BIT-equal across batch splits, dispatch paths and the pallas head/tail
    split (float summation is none of those things; the ±1e-6 slack in the
    xoroshiro batching-invariance test exists because ``blocks_share_sum``
    is a float64 fold). The keys ride ``engine.combine_sums``'s additive
    rule.
  * **Accumulator** — :class:`MomentAccumulator` folds batch moment dicts in
    int64 across a whole run; ``runner.run_simulation_config`` emits its
    :meth:`~MomentAccumulator.snapshot` as one ``stats`` telemetry span per
    batch (same ``run_id`` correlation as every other span).
  * **Derivation** — mean, standard error and the 95 % CI half-width per
    (statistic, miner) from (n, m1, m2), plus the ETA extrapolation: CI
    half-widths shrink as 1/sqrt(n), so the runs still needed to reach a
    target relative half-width are ``n * ((rel_hw / target)^2 - 1)``.

Quantization contract (per statistic): ``q = rint(clamp(x) * scale)`` as
int64. ``blocks_found`` is integer already (scale 1); ``blocks_share`` lives
in [0, 1] and quantizes at 2^-18 (~4e-6 — far under any CI width worth
monitoring); ``stale_rate`` is clamped at :data:`STALE_RATE_CLAMP` = 16 (a
stale rate of 16 is already pathology, and an unclamped ratio — stale can
reach the event bound while found is 1 — would overflow the m2 budget) and
quantizes at 2^-14. int64 overflow budgets at these scales: m2 grows at most
2^36 per run for the ratio statistics, so sums stay exact past 2^27 ≈ 134 M
runs per accumulator — far beyond any single run's plan.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import numpy as np

__all__ = [
    "STATS",
    "STALE_RATE_CLAMP",
    "Z95",
    "moment_keys",
    "MomentAccumulator",
    "derive_moments",
    "format_num",
    "format_eta",
    "snapshot_rows",
    "point_snapshot_rows",
]

#: Two-sided 95 % normal critical value (the CI the dashboards quote).
Z95 = 1.959963984540054

#: Stale-rate values are clamped here before quantization (see module
#: docstring). Documented wherever the moments are surfaced.
STALE_RATE_CLAMP = 16.0

#: (statistic name, fixed-point scale, clamp or None) — the one authority for
#: the quantization contract, shared by the engine's moment emission and
#: every consumer's de-scaling.
STATS: tuple[tuple[str, int, float | None], ...] = (
    ("blocks_found", 1, None),
    ("blocks_share", 1 << 18, None),
    ("stale_rate", 1 << 14, STALE_RATE_CLAMP),
)

#: Key prefix of every moment output (``stats_n``, ``stats_<stat>_m1/m2``);
#: the runner strips this prefix from the stat-sum path exactly like
#: ``tele_``/``flight_`` keys.
PREFIX = "stats_"


def quantize(stat: str, values: np.ndarray) -> np.ndarray:
    """Per-run fixed-point representation of one statistic's values (any
    shape), as int64 — the only lossy step of the moment pipeline, applied
    once per run value so every later reduction is exact."""
    for name, scale, clamp in STATS:
        if name == stat:
            x = np.asarray(values, dtype=np.float64)
            if clamp is not None:
                x = np.minimum(x, clamp)
            return np.rint(x * scale).astype(np.int64)
    raise KeyError(f"unknown statistic {stat!r}")


def moment_keys(per_run: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """One batch's moment keys from its per-run (runs, miners) statistic
    arrays: ``stats_n`` plus int64 ``stats_<stat>_m1``/``_m2`` per miner.
    All values merge additively (``engine.combine_sums``), and integer
    addition makes that merge associative and permutation-invariant bit-for-
    bit — the property the batch-split invariance test pins."""
    out: dict[str, np.ndarray] = {}
    n = None
    for stat, _, _ in STATS:
        q = quantize(stat, per_run[stat])
        n = q.shape[0]
        out[f"{PREFIX}{stat}_m1"] = q.sum(axis=0, dtype=np.int64)
        out[f"{PREFIX}{stat}_m2"] = (q * q).sum(axis=0, dtype=np.int64)
    out[f"{PREFIX}n"] = np.int64(n)
    return out


def derive_moments(
    n: int, m1: np.ndarray, m2: np.ndarray, scale: int
) -> tuple[np.ndarray, np.ndarray | None]:
    """(mean, standard error) per miner from exact moment sums; the variance
    is the usual unbiased ``(m2 - m1^2/n) / (n - 1)``, computed in float64
    (m1^2 would overflow int64 long before the sums themselves do). A
    single-run accumulator has no variance estimate: se is None, and the
    dashboards must render "n/a" instead of a fake zero-width CI."""
    m1f = np.asarray(m1, dtype=np.float64)
    mean = m1f / (n * scale)
    if n < 2:
        return mean, None
    var_q = (np.asarray(m2, dtype=np.float64) - m1f * m1f / n) / (n - 1)
    se = np.sqrt(np.maximum(var_q, 0.0) / n) / scale
    return mean, se


def format_num(x: Any, digits: int = 4) -> str:
    """Human rendering of one snapshot number; None (underivable — n < 2, or
    an all-zero-mean statistic) renders as "n/a", never a fabricated 0.
    Shared by `tpusim watch` and the report convergence panels so the two
    surfaces cannot drift apart."""
    if x is None:
        return "n/a"
    return f"{float(x):.{digits}g}"


def format_eta(eta_runs: Any, eta_s: Any) -> str:
    """Human rendering of one snapshot's ETA pair (runs + seconds at the
    measured rate) — the one implementation behind both dashboards."""
    if eta_runs is None:
        return "n/a"
    if eta_runs == 0:
        return "target met"
    txt = f"~{float(eta_runs):.3g} runs"
    if eta_s is not None:
        s = float(eta_s)
        txt += f" ({s:.1f} s)" if s < 120 else f" ({s / 60:.1f} min)"
    return txt


def snapshot_rows(per_stat: dict[str, Any]) -> list[list[str]]:
    """The convergence table rows ([stat, worst rel hw, max hw95, eta]) from
    one ``stats`` span's ``stats`` attr — THE shared row builder behind the
    `tpusim watch` panel and the report convergence panel, so the two
    dashboards render one ledger structurally identically. Tolerates foreign
    or partial entries (missing keys, all-None hw95 lists) with "n/a"
    instead of raising: both surfaces promise crash-tolerant rendering of
    arbitrary ledgers."""
    rows = []
    for stat, entry in (per_stat or {}).items():
        if not isinstance(entry, dict):
            continue
        hw = entry.get("hw95")
        hw_max = (
            max(v for v in hw if v is not None)
            if isinstance(hw, list) and any(v is not None for v in hw) else None
        )
        rows.append([
            str(stat),
            format_num(entry.get("rel_hw_max")),
            format_num(hw_max),
            format_eta(entry.get("eta_runs"), entry.get("eta_s")),
        ])
    return rows


def point_snapshot_rows(stats_spans: list[dict]) -> list[list[str]] | None:
    """Per-POINT convergence rows from segment-aware ``stats`` spans — the
    packed-sweep spans (tpusim.packed) that carry a ``point`` attr naming
    their grid segment. One row per point from its NEWEST span:
    ``[point, runs, worst rel hw across stats, status]``. Returns None when
    no span names a point (a plain single-run ledger), so both dashboards
    fall back to the blended table. THE shared extraction behind the
    ``tpusim watch`` packed panel and the report twin, tolerant of
    foreign/partial entries like every other ledger consumer."""
    latest: dict[str, dict] = {}
    order: list[str] = []
    for sp in stats_spans:
        attrs = sp.get("attrs") or {}
        pt = attrs.get("point")
        if not isinstance(pt, str):
            continue
        if pt not in latest:
            order.append(pt)
        latest[pt] = attrs
    if not latest:
        return None
    rows = []
    for pt in order:
        a = latest[pt]
        per_stat = a.get("stats") or {}
        rels = [
            e.get("rel_hw_max") for e in per_stat.values()
            if isinstance(e, dict)
        ]
        rels = [r for r in rels if isinstance(r, (int, float))]
        conv = a.get("converged")
        if conv is True:
            status = "converged"
        elif conv is False:
            status = f"round {a.get('round', '?')}, {a.get('lanes', '?')} lanes"
        else:
            status = "done"
        done = a.get("runs_done", a.get("runs"))
        total = a.get("runs_total")
        runs = f"{done}/{total}" if total else str(done)
        rows.append([pt, runs, format_num(max(rels) if rels else None), status])
    return rows


def _sig(x: float | None) -> float | None:
    """6-significant-digit rounding for span compactness."""
    if x is None:
        return None
    return float(f"{float(x):.6g}")


@dataclasses.dataclass
class MomentAccumulator:
    """Run-scoped fold of per-batch moment keys (exact int64 throughout).

    Session-scoped like the ``tele_`` counters: a checkpoint-resumed run
    starts a fresh accumulator (moments are telemetry, not statistics — the
    checkpointed stat sums are unaffected)."""

    n: int = 0
    m1: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    m2: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)

    def add(self, stats: dict[str, Any]) -> None:
        """Fold one batch's ``stats_*`` keys (a ``run_batch`` output's moment
        group, already host numpy)."""
        self.n += int(stats[f"{PREFIX}n"])
        for stat, _, _ in STATS:
            for which, store in (("m1", self.m1), ("m2", self.m2)):
                v = np.asarray(stats[f"{PREFIX}{stat}_{which}"], dtype=np.int64)
                store[stat] = v if stat not in store else store[stat] + v

    def snapshot(
        self,
        *,
        target_rel_hw: float | None = None,
        rate_runs_per_s: float | None = None,
    ) -> dict[str, dict[str, Any]]:
        """JSON-ready per-statistic convergence state for one ``stats`` span:
        per-miner mean/se/95 %-half-width lists, the worst relative
        half-width across miners (the number that must cross the target),
        and the ETA extrapolation toward ``target_rel_hw`` at
        ``rate_runs_per_s``. Fields that cannot be derived yet (n < 2, or a
        statistic whose means are all zero) are None, never fabricated."""
        out: dict[str, dict[str, Any]] = {}
        for stat, scale, _ in STATS:
            if stat not in self.m1:
                continue
            mean, se = derive_moments(self.n, self.m1[stat], self.m2[stat], scale)
            entry: dict[str, Any] = {"mean": [_sig(v) for v in mean]}
            if se is None:
                entry.update(se=None, hw95=None, rel_hw_max=None,
                             eta_runs=None, eta_s=None)
                out[stat] = entry
                continue
            hw = Z95 * se
            entry["se"] = [_sig(v) for v in se]
            entry["hw95"] = [_sig(v) for v in hw]
            nz = np.abs(mean) > 0
            rel = float(np.max(hw[nz] / np.abs(mean[nz]))) if nz.any() else None
            entry["rel_hw_max"] = _sig(rel)
            eta_runs = eta_s = None
            if rel is not None and target_rel_hw and target_rel_hw > 0:
                # Half-widths shrink as 1/sqrt(n): runs needed for the target
                # is n * (rel/target)^2, so the remaining distance is the
                # difference (0 once the target is met).
                eta_runs = max(0, math.ceil(self.n * (rel / target_rel_hw) ** 2) - self.n)
                if rate_runs_per_s and rate_runs_per_s > 0:
                    eta_s = _sig(eta_runs / rate_runs_per_s)
            entry["eta_runs"] = eta_runs
            entry["eta_s"] = eta_s
            out[stat] = entry
        return out
