"""Provenance & audit plane: content-addressed result lineage, the
cross-plane consistency gate (``tpusim audit``), and sealed evidence bundles.

The repo's six observability planes (telemetry spans, flight rings,
convergence moments, perf ledger, trace trees, metrics/SLO) each record
*that* things happened; none records *what produced what*, and nothing
cross-checks them against each other — a healed fleet row, a perf
trajectory point and a sweep JSONL line are all anonymous JSON. This module
(jax-free, like telemetry/metrics/fleet) is the missing ledger:

  * **Lineage records.** Every artifact-producing seam — runner run
    completion, sweep rows (sequential AND packed), fleet worker rows,
    ``perf run`` rows, checkpoint save/load, flight/trace exports — appends
    one content-addressed record to an append-only lineage ledger via the
    shared torn-line-repairing :func:`tpusim.telemetry.append_jsonl_line`
    (fsync'd: a SIGKILL cannot tear the provenance chain mid-record). A
    record's ``content_sha256`` is the sha256 of the artifact's canonical
    JSON — the address rows resolve to and parents cite — and its
    ``artifact_id`` is the sha256 of the whole record, so a mutated ledger
    line fails its own hash. ``parents`` form the lineage DAG: a
    resumed-from-checkpoint row cites the checkpoint it healed from
    (checkpoint addresses are deterministic over ``(fingerprint,
    runs_done)``, so a replacement fleet worker resolves the dead worker's
    save without ever reading the ledger), and a perf row cites the run
    that measured it.
  * **``tpusim audit``** — joins lineage + telemetry spans + fleet ledger +
    perf ledger + checkpoints and verifies the :data:`INVARIANTS` the
    planes already imply, with the perf-compare/SLO exit discipline
    (0 pass / 1 violation / 2 structural-or-dead-gate; an EMPTY lineage
    ledger can never pass green).
  * **``tpusim lineage show``** — walk one artifact's parent chain
    (row → run → checkpoint_load → checkpoint) as a terminal tree.
  * **``tpusim bundle create|verify``** — a sealed evidence tarball
    (ledgers + a manifest of per-file sha256 hashes) that ``verify``
    re-hashes fully offline; a flipped byte fails loud.

Arming is environment-scoped: setting :data:`PROVENANCE_ENV`
(``TPUSIM_PROVENANCE``) to a ledger path arms every seam in the process AND
its children (fleet workers inherit it, so one ledger spans the whole
fleet). Unset, every seam is a host-side no-op behind
:func:`lineage_armed` — nothing is traced, the compiled device programs are
byte-identical and warmed dispatch stays at zero recompiles (pinned by
tests/test_provenance.py, the chaos/flight zero-overhead discipline).

    TPUSIM_PROVENANCE=artifacts/provenance/lineage.jsonl \\
        python -m tpusim.sweep propagation --out rows.jsonl
    python -m tpusim audit . --lineage artifacts/provenance/lineage.jsonl
    python -m tpusim lineage show rows.jsonl --lineage artifacts/provenance/lineage.jsonl
    python -m tpusim bundle create evidence.tar rows.jsonl artifacts/provenance
    python -m tpusim bundle verify evidence.tar
"""

from __future__ import annotations

import argparse
import hashlib
import io
import json
import logging
import os
import sys
import tarfile
import time
from pathlib import Path
from typing import Any, Iterable

from .telemetry import append_jsonl_line

logger = logging.getLogger("tpusim")

__all__ = [
    "PROVENANCE_ENV",
    "SCHEMA",
    "KINDS",
    "INVARIANTS",
    "LineageWriter",
    "canonical_json",
    "content_address",
    "checkpoint_content",
    "checkpoint_address",
    "sha256_file",
    "lineage_armed",
    "active_writer",
    "emit_lineage",
    "lineage_last",
    "lineage_note_parents",
    "lineage_take_parents",
    "load_lineage",
    "summarize_lineage",
    "run_audit",
    "audit_main",
    "lineage_main",
    "bundle_main",
]

#: Environment variable naming the lineage ledger path. Set = every
#: artifact-producing seam in this process (and its subprocesses — fleet
#: workers inherit the environment) appends records there; unset = every
#: seam is a no-op.
PROVENANCE_ENV = "TPUSIM_PROVENANCE"

#: Lineage record schema version.
SCHEMA = 1

#: The artifact-kind registry: ``(kind, help)`` per kind — the ONE place
#: the lineage-record vocabulary is declared. ``tpusim lint`` (JX020) pins
#: this tuple against the live ``emit_lineage("...")`` call sites in the
#: configured lineage-writer modules, both directions, so an
#: artifact-producing seam cannot be added (or renamed) without the
#: registry — and the audit gate — knowing about it.
KINDS = (
    ("run", "one run_simulation_config completion (content: the result dict)"),
    ("sweep_row", "one sweep output row, sequential or packed (content: the row)"),
    ("fleet_row", "a single-point fleet worker's published row (content: the row)"),
    ("perf_row", "one perf-ledger benchmark row (content: the row)"),
    ("checkpoint", "a durable checkpoint save (content: fingerprint + runs_done)"),
    ("checkpoint_load", "a checkpoint resume, citing the checkpoint it loaded"),
    ("flight_export", "an exported flight/trace artifact (content: the file sha256)"),
    ("served_query", "one `tpusim serve` answer (content: the served row; "
     "cache hits cite the original answer as parent)"),
)

#: The cross-plane invariants ``tpusim audit`` verifies: ``(name, help)``
#: per invariant. Mirrored by the marker-anchored README audit-invariant
#: table (``tpusim-lint: audit-invariant-table``), pinned both directions
#: by JX020 — an invariant without a doc row, or a doc row without an
#: implementation, fails the lint gate.
INVARIANTS = (
    ("record-hash",
     "every lineage record re-hashes to its own artifact_id"),
    ("parent-resolvable",
     "every cited parent address resolves to a lineage record"),
    ("row-lineage",
     "every result/perf row resolves by content hash to a lineage record"),
    ("runs-consistent",
     "rows' runs match their lineage records; closing-span run totals "
     "match the lineage run records of the same run_id"),
    ("checkpoint-fingerprint",
     "every checkpoint npz's embedded fingerprint has a matching lineage "
     "checkpoint record"),
    ("heal-parented",
     "a fleet-healed (requeued then done) state dir has a row whose parent "
     "chain reaches the checkpoint it resumed from"),
    ("env-rev",
     "a perf row's recorded git rev/dirty flag matches its lineage record"),
)

_KIND_NAMES = tuple(k for k, _ in KINDS)


# ---------------------------------------------------------------------------
# Content addressing.


def canonical_json(obj: Any) -> str:
    """The one canonical serialization content addresses are computed over:
    sorted keys, no whitespace. Key-order and formatting differences between
    a row as written and a row as re-read therefore never change its
    address; any VALUE change does."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def content_address(obj: Any) -> str:
    """sha256 hex of ``obj``'s canonical JSON — the content address rows
    resolve to and parents cite."""
    return hashlib.sha256(canonical_json(obj).encode()).hexdigest()


def checkpoint_content(fingerprint: str, runs_done: int) -> dict[str, Any]:
    """The canonical content of one durable checkpoint save. Deterministic
    over ``(fingerprint, runs_done)`` so a LOADER — possibly a replacement
    fleet worker in a different process — recomputes the saved checkpoint's
    address without reading the ledger."""
    return {
        "kind": "checkpoint",
        "fingerprint": fingerprint,
        "runs_done": int(runs_done),
    }


def checkpoint_address(fingerprint: str, runs_done: int) -> str:
    return content_address(checkpoint_content(fingerprint, runs_done))


def sha256_file(path: str | Path) -> str:
    h = hashlib.sha256()
    with Path(path).open("rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _record_id(rec: dict[str, Any]) -> str:
    """A record's own tamper-evident hash: sha256 over the full record
    minus ``artifact_id`` itself."""
    return content_address({k: v for k, v in rec.items() if k != "artifact_id"})


# ---------------------------------------------------------------------------
# The writer.


class LineageWriter:
    """Append-only lineage ledger writer. All host-side, jax-free; writes go
    through the shared torn-line repair (:func:`append_jsonl_line`) with
    fsync-on-append, so a record either survives a SIGKILL whole or was
    never acknowledged — the provenance chain is never torn mid-record.

    Besides writing, the writer carries two bits of in-process joining
    state the seams use to build the DAG without threading artifact ids
    through every call signature: ``last(kind)`` (the newest address
    emitted under a kind — how a sweep row finds the run that produced it)
    and a parent mailbox keyed by point name (how a packed resume hands its
    checkpoint_load address to the row emitted later).

    A failed write degrades like telemetry (warn once, disarm the writer,
    the run continues) — and fails LOUD downstream instead: the missing
    records turn `tpusim audit` red."""

    def __init__(self, path: str | Path, *, fsync: bool = True):
        self.path = Path(path)
        self.fsync = fsync
        self.disabled = False
        self._env: dict[str, Any] | None = None
        self._last: dict[str, str] = {}
        self._parents: dict[str, list[str]] = {}

    def _env_attrs(self) -> dict[str, Any]:
        # Cached once per writer: the env fingerprint shells out to git.
        if self._env is None:
            from .perf import environment_fingerprint

            env = environment_fingerprint()
            self._env = {
                "git_rev": env.get("git_rev"),
                "git_dirty": env.get("git_dirty"),
                "env_sha256": content_address(env),
            }
        return self._env

    def emit(
        self,
        kind: str,
        *,
        content: Any = None,
        parents: Iterable[str | None] = (),
        key: str | None = None,
        **attrs: Any,
    ) -> str | None:
        """Append one lineage record; returns the artifact's address (its
        ``content_sha256`` when ``content`` is given, its ``artifact_id``
        otherwise), or None when the writer is disarmed. ``key`` also files
        the address in the parent mailbox under that key."""
        if kind not in _KIND_NAMES:
            raise ValueError(f"unknown lineage kind {kind!r}; register it in KINDS")
        if self.disabled:
            return None
        addr = content_address(content) if content is not None else None
        rec: dict[str, Any] = {
            "schema": SCHEMA,
            "kind": kind,
            "t": round(time.time(), 3),
            "content_sha256": addr,
            "parents": [p for p in parents if p],
            **self._env_attrs(),
            **{k: v for k, v in attrs.items() if v is not None},
        }
        rec["artifact_id"] = _record_id(rec)
        out = addr or rec["artifact_id"]
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            append_jsonl_line(self.path, json.dumps(rec), fsync=self.fsync)
        except OSError as e:
            # The telemetry ENOSPC discipline: warn once, disarm, keep the
            # run alive. The gap fails loud later — audit can't resolve the
            # rows this writer stopped recording.
            self.disabled = True
            logger.warning(
                "disabling lineage ledger %s after write failure (%s: %s); "
                "`tpusim audit` over these artifacts will fail",
                self.path, type(e).__name__, e,
            )
            return None
        self._last[kind] = out
        if key is not None:
            self._parents.setdefault(key, []).append(out)
        return out

    def last(self, kind: str) -> str | None:
        return self._last.get(kind)

    def note_parents(self, key: str, *addrs: str | None) -> None:
        good = [a for a in addrs if a]
        if good:
            self._parents.setdefault(key, []).extend(good)

    def take_parents(self, key: str) -> list[str]:
        return self._parents.pop(key, [])


_WRITERS: dict[str, LineageWriter] = {}


def lineage_armed() -> bool:
    """Whether the provenance plane is armed for this process. The seams
    guard on this (the ``if chaos is not None`` discipline) so a disarmed
    run pays nothing — not even argument construction."""
    return bool(os.environ.get(PROVENANCE_ENV))


def active_writer() -> LineageWriter | None:
    """The process-wide writer for the env-armed ledger path (one per
    distinct path, cached so ``last``/mailbox state joins records across
    modules), or None when disarmed."""
    path = os.environ.get(PROVENANCE_ENV)
    if not path:
        return None
    w = _WRITERS.get(path)
    if w is None:
        w = _WRITERS[path] = LineageWriter(path)
    return w


def emit_lineage(
    kind: str,
    *,
    content: Any = None,
    parents: Iterable[str | None] = (),
    key: str | None = None,
    **attrs: Any,
) -> str | None:
    """Module-level seam entry point: append one record to the env-armed
    ledger (no-op returning None when disarmed). THE call every
    artifact-producing seam makes — ``tpusim lint`` (JX020) statically
    cross-checks these call sites against :data:`KINDS`."""
    w = active_writer()
    if w is None:
        return None
    return w.emit(kind, content=content, parents=parents, key=key, **attrs)


def lineage_last(kind: str) -> str | None:
    w = active_writer()
    return None if w is None else w.last(kind)


def lineage_note_parents(key: str, *addrs: str | None) -> None:
    w = active_writer()
    if w is not None:
        w.note_parents(key, *addrs)


def lineage_take_parents(key: str) -> list[str]:
    w = active_writer()
    return [] if w is None else w.take_parents(key)


# ---------------------------------------------------------------------------
# Loaders.


def load_lineage(path: str | Path, *, strict: bool = False) -> list[dict]:
    """Read a lineage ledger back. Tolerant by default (skip torn/foreign
    lines — the load_spans policy, since a live writer may still be
    appending); ``strict=True`` raises ValueError with ``path:line`` on any
    unparseable line or any record whose ``artifact_id`` does not re-hash
    (the harvest validator: collected evidence must be whole)."""
    path = Path(path)
    records: list[dict] = []
    if not path.exists():
        if strict:
            raise ValueError(f"{path}: lineage ledger does not exist")
        return records
    for i, line in enumerate(
        path.read_text(errors="replace").splitlines(), start=1
    ):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if strict:
                raise ValueError(f"{path}:{i}: unparseable lineage line")
            continue
        if not isinstance(rec, dict) or "artifact_id" not in rec or "kind" not in rec:
            if strict:
                raise ValueError(f"{path}:{i}: not a lineage record: {line[:80]}")
            continue
        if strict and _record_id(rec) != rec["artifact_id"]:
            raise ValueError(
                f"{path}:{i}: lineage record fails its own hash "
                f"(artifact_id {str(rec['artifact_id'])[:12]}…) — mutated ledger"
            )
        records.append(rec)
    return records


def summarize_lineage(records: list[dict]) -> dict[str, Any] | None:
    """Digest a lineage ledger into the one summary dict both dashboards
    render (the summarize_fleet_spans discipline): record/kind counts, DAG
    edge count, newest record time. None when there are no records."""
    if not records:
        return None
    kinds: dict[str, int] = {}
    edges = 0
    newest = 0.0
    dirty = 0
    for rec in records:
        kinds[str(rec.get("kind"))] = kinds.get(str(rec.get("kind")), 0) + 1
        parents = rec.get("parents")
        edges += len(parents) if isinstance(parents, list) else 0
        t = rec.get("t")
        if isinstance(t, (int, float)):
            newest = max(newest, float(t))
        if rec.get("git_dirty"):
            dirty += 1
    return {
        "records": len(records),
        "kinds": kinds,
        "edges": edges,
        "newest_t": newest or None,
        "dirty_records": dirty,
    }


# ---------------------------------------------------------------------------
# Artifact scanning: classify everything under the audited roots.


def _classify_jsonl_line(row: Any) -> str | None:
    """Which plane one parsed JSONL object belongs to. Foreign/partial
    objects classify as None and are skipped — every plane's own loaders
    are tolerant, and the audit join must be too."""
    if not isinstance(row, dict):
        return None
    if "artifact_id" in row and "kind" in row:
        return "lineage"
    if isinstance(row.get("span"), str):
        return "span"
    if isinstance(row.get("event"), str):
        return "event"
    if "scenario" in row and "metric" in row and "samples" in row:
        return "perf_row"
    if (
        "point" in row and "runs" in row and "backend" in row
        and "elapsed_s" in row
    ):
        return "result_row"
    return None


def _checkpoint_fingerprint_of(path: Path) -> str | None:
    """The ``__config__`` fingerprint embedded in one checkpoint npz, or
    None when the file is unreadable/foreign (a torn checkpoint is a
    *recoverable* runtime condition — the runner restarts from zero — so
    audit skips it rather than failing)."""
    try:
        import numpy as np

        with np.load(path, allow_pickle=False) as data:
            if "__config__" not in data.files:
                return None
            return str(data["__config__"])
    except Exception:  # torn zip, foreign npz, missing numpy
        return None


def scan_artifacts(
    roots: list[Path], lineage_paths: list[Path] | None = None
) -> dict[str, Any]:
    """Walk ``roots`` (files or directories) and bucket everything found:
    lineage records, result rows, perf rows, telemetry spans, fleet event
    ledgers, checkpoint fingerprints. Returns the scan dict ``run_audit``
    consumes."""
    jsonl: list[Path] = []
    npz: list[Path] = []
    for root in roots:
        if root.is_dir():
            jsonl.extend(sorted(root.rglob("*.jsonl")))
            npz.extend(sorted(root.rglob("*.npz")))
        elif root.suffix == ".jsonl":
            jsonl.append(root)
        elif root.suffix == ".npz":
            npz.append(root)
    for extra in lineage_paths or []:
        if extra not in jsonl and extra.exists():
            jsonl.append(extra)

    scan: dict[str, Any] = {
        "lineage": [],        # records
        "lineage_files": [],
        "result_rows": [],    # (path, lineno, row)
        "perf_rows": [],      # (path, lineno, row)
        "spans": [],
        "fleet_ledgers": {},  # path -> [events]
        "checkpoints": [],    # (path, fingerprint)
        "files": len(jsonl) + len(npz),
    }
    for path in jsonl:
        try:
            text = path.read_text(errors="replace")
        except OSError:
            continue
        saw_lineage = False
        for i, line in enumerate(text.splitlines(), start=1):
            if not line.strip():
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn trailing line: tolerated, like every loader
            plane = _classify_jsonl_line(row)
            if plane == "lineage":
                scan["lineage"].append(row)
                saw_lineage = True
            elif plane == "span":
                scan["spans"].append(row)
            elif plane == "event":
                scan["fleet_ledgers"].setdefault(path, []).append(row)
            elif plane == "perf_row":
                scan["perf_rows"].append((path, i, row))
            elif plane == "result_row":
                scan["result_rows"].append((path, i, row))
        if saw_lineage:
            scan["lineage_files"].append(path)
    for path in npz:
        if path.name.endswith(".tmp.npz"):
            continue  # swept, never adopted — not an artifact
        fp = _checkpoint_fingerprint_of(path)
        if fp is not None:
            scan["checkpoints"].append((path, fp))
    return scan


# ---------------------------------------------------------------------------
# The audit gate.


def _ancestor_kinds(
    addr: str, by_addr: dict[str, dict], limit: int = 10000
) -> set[str]:
    """Kinds reachable through the parent DAG from one address (cycle- and
    depth-guarded: a mutated ledger must not hang the auditor)."""
    kinds: set[str] = set()
    seen: set[str] = set()
    stack = [addr]
    while stack and len(seen) < limit:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        rec = by_addr.get(cur)
        if rec is None:
            continue
        kinds.add(str(rec.get("kind")))
        parents = rec.get("parents")
        if isinstance(parents, list):
            stack.extend(str(p) for p in parents)
    return kinds


def run_audit(scan: dict[str, Any]) -> tuple[list[tuple[str, str]], dict[str, int]]:
    """Verify :data:`INVARIANTS` over one artifact scan. Returns
    ``(violations, checked)``: violations as ``(invariant, message)`` pairs
    and the per-invariant count of facts checked (a zero-checked invariant
    simply had no joinable facts in this artifact set)."""
    violations: list[tuple[str, str]] = []
    checked = {name: 0 for name, _ in INVARIANTS}
    records: list[dict] = scan["lineage"]

    by_addr: dict[str, dict] = {}
    by_fingerprint_ckpt: set[str] = set()
    for rec in records:
        addr = rec.get("content_sha256") or rec.get("artifact_id")
        if isinstance(addr, str):
            by_addr.setdefault(addr, rec)
        aid = rec.get("artifact_id")
        if isinstance(aid, str):
            by_addr.setdefault(aid, rec)
        if rec.get("kind") == "checkpoint" and isinstance(
            rec.get("config_fingerprint"), str
        ):
            by_fingerprint_ckpt.add(rec["config_fingerprint"])

    # record-hash: a mutated ledger line fails its own hash.
    for rec in records:
        checked["record-hash"] += 1
        if _record_id(rec) != rec.get("artifact_id"):
            violations.append((
                "record-hash",
                f"lineage record {str(rec.get('artifact_id'))[:12]}… "
                f"(kind {rec.get('kind')}) does not re-hash to its "
                f"artifact_id — mutated ledger line",
            ))

    # parent-resolvable: the DAG has no dangling edges.
    for rec in records:
        parents = rec.get("parents")
        if not isinstance(parents, list):
            continue
        for p in parents:
            checked["parent-resolvable"] += 1
            if str(p) not in by_addr:
                violations.append((
                    "parent-resolvable",
                    f"record {str(rec.get('artifact_id'))[:12]}… (kind "
                    f"{rec.get('kind')}) cites parent {str(p)[:12]}… which "
                    f"no lineage record resolves",
                ))

    # row-lineage: every row on disk resolves by content hash.
    row_addr: dict[int, str] = {}
    for plane in ("result_rows", "perf_rows"):
        for path, lineno, row in scan[plane]:
            checked["row-lineage"] += 1
            addr = content_address(row)
            row_addr[id(row)] = addr
            if addr not in by_addr:
                label = row.get("point") or row.get("scenario") or "?"
                violations.append((
                    "row-lineage",
                    f"{path}:{lineno}: row ({label}) has no lineage record "
                    f"for content address {addr[:12]}… — unrecorded or "
                    f"mutated artifact",
                ))

    # runs-consistent, part 1: a row's runs equals its lineage record's.
    for path, lineno, row in scan["result_rows"]:
        rec = by_addr.get(row_addr.get(id(row), ""))
        if rec is None or "runs" not in rec:
            continue
        checked["runs-consistent"] += 1
        if rec.get("runs") != row.get("runs"):
            violations.append((
                "runs-consistent",
                f"{path}:{lineno}: row runs={row.get('runs')} but its "
                f"lineage record says runs={rec.get('runs')}",
            ))
    # runs-consistent, part 2: closing-span totals vs lineage run records,
    # joined by run_id (packed closing spans carry no runs attr and fleet
    # closing spans carry fleet=True — both sides exclude them).
    span_runs: dict[str, int] = {}
    for sp in scan["spans"]:
        if sp.get("span") != "run":
            continue
        attrs = sp.get("attrs") or {}
        rid = sp.get("run_id")
        runs = attrs.get("runs")
        if attrs.get("fleet") or not isinstance(rid, str):
            continue
        if isinstance(runs, int) and not isinstance(runs, bool):
            span_runs[rid] = span_runs.get(rid, 0) + runs
    rec_runs: dict[str, int] = {}
    for rec in records:
        if rec.get("kind") != "run":
            continue
        rid = rec.get("run_id")
        runs = rec.get("runs")
        if isinstance(rid, str) and isinstance(runs, int):
            rec_runs[rid] = rec_runs.get(rid, 0) + runs
    for rid in sorted(set(span_runs) & set(rec_runs)):
        checked["runs-consistent"] += 1
        if span_runs[rid] != rec_runs[rid]:
            violations.append((
                "runs-consistent",
                f"run_id {rid}: closing run spans total {span_runs[rid]} "
                f"runs but lineage run records total {rec_runs[rid]}",
            ))

    # checkpoint-fingerprint: every durable npz is known to the ledger.
    for path, fp in scan["checkpoints"]:
        checked["checkpoint-fingerprint"] += 1
        if fp not in by_fingerprint_ckpt:
            violations.append((
                "checkpoint-fingerprint",
                f"{path}: checkpoint fingerprint has no matching lineage "
                f"checkpoint record — save seam bypassed the ledger",
            ))

    # heal-parented: a requeued-then-done fleet state dir (with at least
    # one durable checkpoint recorded — a pre-first-save kill legitimately
    # restarts from zero, parentless) must have a row whose chain reaches
    # the checkpoint it resumed from.
    for ledger_path, events in scan["fleet_ledgers"].items():
        requeued = {
            e.get("point") for e in events if e.get("event") == "requeue"
        }
        done = {e.get("point") for e in events if e.get("event") == "done"}
        healed = {p for p in requeued & done if p}
        if not healed or not by_fingerprint_ckpt:
            continue
        checked["heal-parented"] += 1
        state_dir = ledger_path.parent
        reaches = False
        for path, _, row in scan["result_rows"]:
            if state_dir not in path.parents and path.parent != state_dir:
                continue
            rec = by_addr.get(row_addr.get(id(row), ""))
            if rec is None:
                continue
            kinds = _ancestor_kinds(
                rec.get("content_sha256") or rec.get("artifact_id"), by_addr
            )
            if "checkpoint" in kinds or "checkpoint_load" in kinds:
                reaches = True
                break
        if not reaches:
            violations.append((
                "heal-parented",
                f"{ledger_path}: point(s) {sorted(map(str, healed))} were "
                f"requeued and healed but no row's parent chain reaches a "
                f"checkpoint record — the heal lineage is broken",
            ))

    # env-rev: the perf ledger and the lineage ledger agree on code identity.
    for path, lineno, row in scan["perf_rows"]:
        rec = by_addr.get(row_addr.get(id(row), ""))
        if rec is None:
            continue
        env = row.get("env") if isinstance(row.get("env"), dict) else {}
        checked["env-rev"] += 1
        if env.get("git_rev") != rec.get("git_rev") or bool(
            env.get("git_dirty")
        ) != bool(rec.get("git_dirty")):
            violations.append((
                "env-rev",
                f"{path}:{lineno}: perf row env records rev "
                f"{env.get('git_rev')!r} (dirty={env.get('git_dirty')!r}) "
                f"but its lineage record says {rec.get('git_rev')!r} "
                f"(dirty={rec.get('git_dirty')!r})",
            ))

    return violations, checked


# ---------------------------------------------------------------------------
# CLI: `tpusim audit`.


def _find_lineage_paths(roots: list[Path], explicit: Path | None) -> list[Path]:
    if explicit is not None:
        return [explicit]
    found: list[Path] = []
    env = os.environ.get(PROVENANCE_ENV)
    if env and Path(env).exists():
        found.append(Path(env))
    for root in roots:
        if root.is_dir():
            found.extend(sorted(root.rglob("lineage.jsonl")))
        elif root.name == "lineage.jsonl":
            found.append(root)
    return found


def audit_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpusim audit",
        description="Cross-plane consistency gate: join the lineage ledger "
        "with telemetry spans, the fleet work ledger, the perf ledger and "
        "checkpoints, and verify the provenance invariants (exit 0 pass / "
        "1 violation / 2 structural-or-dead-gate).",
    )
    ap.add_argument(
        "paths", nargs="+", type=Path,
        help="artifact roots to audit: state dirs and/or ledger files "
        "(scanned recursively for *.jsonl and *.npz)",
    )
    ap.add_argument(
        "--lineage", type=Path, metavar="JSONL",
        help="the lineage ledger (default: $TPUSIM_PROVENANCE plus every "
        "lineage.jsonl found under the audited roots)",
    )
    ap.add_argument("--quiet", action="store_true", help="suppress the summary table")
    args = ap.parse_args(argv)

    missing = [p for p in args.paths if not p.exists()]
    if missing:
        print(
            f"error: no such artifact root(s): "
            f"{', '.join(str(p) for p in missing)} (a gate over nothing is "
            f"a dead gate)", file=sys.stderr,
        )
        return 2
    lineage_paths = _find_lineage_paths(args.paths, args.lineage)
    scan = scan_artifacts(args.paths, lineage_paths)
    if not scan["lineage"]:
        print(
            "error: no lineage records found "
            f"({', '.join(str(p) for p in lineage_paths) or 'no ledger located'})"
            " — an empty lineage ledger can never pass green (dead gate)",
            file=sys.stderr,
        )
        return 2

    violations, checked = run_audit(scan)
    if not args.quiet:
        from .report import text_table

        by_inv: dict[str, int] = {}
        for name, _ in violations:
            by_inv[name] = by_inv.get(name, 0) + 1
        rows = [
            [name, str(checked[name]), str(by_inv.get(name, 0)),
             "FAIL" if by_inv.get(name) else ("ok" if checked[name] else "—")]
            for name, _ in INVARIANTS
        ]
        print("\n".join(text_table(
            ["invariant", "checked", "violations", "status"], rows
        )))
        summary = summarize_lineage(scan["lineage"]) or {}
        print(
            f"[audit] {summary.get('records', 0)} lineage record(s), "
            f"{len(scan['result_rows'])} result row(s), "
            f"{len(scan['perf_rows'])} perf row(s), "
            f"{len(scan['spans'])} span(s), "
            f"{len(scan['checkpoints'])} checkpoint(s) "
            f"across {scan['files']} file(s)"
        )
    if violations:
        for name, msg in violations:
            print(f"error: [{name}] {msg}", file=sys.stderr)
        print(f"error: {len(violations)} provenance violation(s)", file=sys.stderr)
        return 1
    return 0


# ---------------------------------------------------------------------------
# CLI: `tpusim lineage show`.


def _resolve_target(
    target: str, line: int | None, by_addr: dict[str, dict]
) -> tuple[str, dict | None] | None:
    """Resolve a CLI target — an address (prefix) or a rows-file path — to
    ``(address, record-or-None)``."""
    p = Path(target)
    if p.exists() and p.suffix == ".jsonl":
        rows = []
        for raw in p.read_text(errors="replace").splitlines():
            if not raw.strip():
                continue
            try:
                row = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if _classify_jsonl_line(row) in ("result_row", "perf_row"):
                rows.append(row)
        if not rows:
            return None
        idx = (line - 1) if line is not None else len(rows) - 1
        if not (0 <= idx < len(rows)):
            return None
        addr = content_address(rows[idx])
        return addr, by_addr.get(addr)
    matches = sorted({
        a for a in by_addr if a.startswith(target)
    }) if len(target) >= 8 else []
    if len(matches) == 1:
        return matches[0], by_addr[matches[0]]
    return None


def _render_tree(
    addr: str, by_addr: dict[str, dict], prefix: str = "", seen=None
) -> list[str]:
    seen = set() if seen is None else seen
    rec = by_addr.get(addr)
    if rec is None:
        return [f"{prefix}?? {addr[:12]}… (unresolved)"]
    label = str(rec.get("kind"))
    bits = [f"{label} {addr[:12]}…"]
    for field in ("point", "scenario", "runs", "run_id", "git_rev"):
        v = rec.get(field)
        if v is not None:
            bits.append(f"{field}={v}")
    if rec.get("git_dirty"):
        bits.append("dirty")
    lines = [prefix + "  ".join(bits)]
    if addr in seen:
        lines[-1] += "  (cycle)"
        return lines
    seen.add(addr)
    parents = [str(p) for p in rec.get("parents") or []]
    pad = prefix.replace("└─ ", "   ").replace("├─ ", "│  ")
    for i, parent in enumerate(parents):
        last = i == len(parents) - 1
        branch = "└─ " if last else "├─ "
        lines.extend(_render_tree(parent, by_addr, pad + branch, seen))
    return lines


def lineage_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpusim lineage",
        description="Walk one artifact's provenance chain "
        "(row → run → checkpoint_load → checkpoint) as a terminal tree.",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_show = sub.add_parser("show", help="render an artifact's parent chain")
    p_show.add_argument(
        "target",
        help="an artifact address (sha256 hex, >= 8-char prefix) or a rows "
        ".jsonl path (defaults to its last row)",
    )
    p_show.add_argument(
        "--line", type=int, default=None,
        help="1-based row number when TARGET is a rows file",
    )
    p_show.add_argument(
        "--lineage", type=Path, metavar="JSONL",
        help="the lineage ledger (default: $TPUSIM_PROVENANCE)",
    )
    args = ap.parse_args(argv)

    lineage_paths = _find_lineage_paths([], args.lineage)
    records: list[dict] = []
    for p in lineage_paths:
        records.extend(load_lineage(p))
    if not records:
        print("error: no lineage records (pass --lineage or set "
              f"{PROVENANCE_ENV})", file=sys.stderr)
        return 2
    by_addr: dict[str, dict] = {}
    for rec in records:
        for a in (rec.get("content_sha256"), rec.get("artifact_id")):
            if isinstance(a, str):
                by_addr.setdefault(a, rec)
    resolved = _resolve_target(args.target, args.line, by_addr)
    if resolved is None:
        print(
            f"error: cannot resolve {args.target!r} to one artifact "
            f"(unknown/ambiguous address, or no rows in the file)",
            file=sys.stderr,
        )
        return 1
    addr, rec = resolved
    if rec is None:
        print(
            f"error: row hashes to {addr[:12]}… but no lineage record "
            f"resolves it — unrecorded or mutated artifact", file=sys.stderr,
        )
        return 1
    print("\n".join(_render_tree(addr, by_addr)))
    return 0


# ---------------------------------------------------------------------------
# CLI: `tpusim bundle create|verify`.

_BUNDLE_MANIFEST = "manifest.json"
_BUNDLE_SUFFIXES = (".jsonl", ".json", ".npz", ".prom", ".txt")


def _bundle_mode(path: Path) -> str:
    return "gz" if path.name.endswith((".tar.gz", ".tgz")) else ""


def bundle_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpusim bundle",
        description="Sealed evidence bundles: a tarball of ledgers plus a "
        "manifest of per-file sha256 hashes that `verify` re-hashes fully "
        "offline — the portable debug/repro bundle.",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_cre = sub.add_parser("create", help="seal artifacts into a bundle")
    p_cre.add_argument("out", type=Path, help="bundle path (.tar or .tar.gz)")
    p_cre.add_argument(
        "paths", nargs="+", type=Path,
        help="artifact files/dirs to seal (ledgers, rows, checkpoints)",
    )
    p_ver = sub.add_parser("verify", help="re-hash a bundle offline")
    p_ver.add_argument("bundle", type=Path)
    args = ap.parse_args(argv)

    if args.cmd == "create":
        files: list[Path] = []
        for p in args.paths:
            if p.is_dir():
                files.extend(
                    f for f in sorted(p.rglob("*"))
                    if f.is_file() and f.suffix in _BUNDLE_SUFFIXES
                )
            elif p.is_file():
                files.append(p)
            else:
                print(f"error: no such path: {p}", file=sys.stderr)
                return 2
        if not files:
            print("error: nothing to seal (an empty bundle is no evidence)",
                  file=sys.stderr)
            return 2
        seen: set[str] = set()
        manifest: dict[str, Any] = {"schema": SCHEMA, "files": []}
        entries: list[tuple[str, Path]] = []
        for f in files:
            # Stable, collision-free member names: the relative shape is
            # kept when possible, uniquified otherwise.
            name = f.as_posix().lstrip("/").replace("..", "__")
            while name in seen:
                name = "_/" + name
            seen.add(name)
            entries.append((name, f))
            manifest["files"].append({
                "path": name,
                "sha256": sha256_file(f),
                "size": f.stat().st_size,
            })
        n_records = 0
        for name, f in entries:
            if f.name == "lineage.jsonl":
                try:
                    n_records += len(load_lineage(f, strict=True))
                except ValueError as e:
                    print(f"error: refusing to seal a broken lineage ledger: {e}",
                          file=sys.stderr)
                    return 2
        manifest["lineage_records"] = n_records
        manifest["created"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        args.out.parent.mkdir(parents=True, exist_ok=True)
        mode = "w:" + _bundle_mode(args.out)
        with tarfile.open(args.out, mode.rstrip(":")) as tar:
            blob = json.dumps(manifest, indent=2).encode()
            info = tarfile.TarInfo(_BUNDLE_MANIFEST)
            info.size = len(blob)
            tar.addfile(info, io.BytesIO(blob))
            for name, f in entries:
                tar.add(f, arcname=name)
        print(
            f"[bundle] sealed {len(entries)} file(s), {n_records} lineage "
            f"record(s) into {args.out}"
        )
        return 0

    # verify
    try:
        with tarfile.open(args.bundle, "r:*") as tar:
            member = tar.extractfile(_BUNDLE_MANIFEST)
            if member is None:
                raise ValueError(f"no {_BUNDLE_MANIFEST} member")
            manifest = json.loads(member.read().decode())
            listed = manifest.get("files")
            if not isinstance(listed, list) or not listed:
                raise ValueError("manifest lists no files")
            bad: list[str] = []
            for entry in listed:
                name, want = entry.get("path"), entry.get("sha256")
                blob = tar.extractfile(str(name))
                if blob is None:
                    bad.append(f"{name}: listed in manifest but missing")
                    continue
                h = hashlib.sha256()
                for chunk in iter(lambda: blob.read(1 << 20), b""):
                    h.update(chunk)
                if h.hexdigest() != want:
                    bad.append(
                        f"{name}: sha256 mismatch (manifest {str(want)[:12]}…, "
                        f"actual {h.hexdigest()[:12]}…)"
                    )
    except (OSError, tarfile.TarError, ValueError, json.JSONDecodeError) as e:
        print(f"error: not a verifiable bundle: {e}", file=sys.stderr)
        return 2
    if bad:
        for line in bad:
            print(f"error: {line}", file=sys.stderr)
        print(f"error: bundle verification FAILED ({len(bad)} file(s))",
              file=sys.stderr)
        return 1
    print(f"[bundle] verified {len(listed)} file(s): all hashes match")
    return 0


if __name__ == "__main__":
    sys.exit(audit_main())
