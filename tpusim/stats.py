"""Aggregated simulation results.

The reference accumulates per-run ``MinerStats`` into ``stats_total`` and
prints each field divided by ``SIM_RUNS`` (main.cpp:214-216,230-231) — i.e. a
mean of per-run ratios. ``SimResults.from_sums`` reproduces that reduction
exactly; getting it wrong would bias every stale-rate comparison against the
C++ oracle (ratio-of-sums and mean-of-ratios differ at the 1e-4 level)."""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np


@dataclasses.dataclass(frozen=True)
class MinerStats:
    """Cross-run averages for one miner (reference main.cpp:13-41)."""

    miner_id: int
    hashrate_pct: int
    selfish: bool
    blocks_found_mean: float
    blocks_share_mean: float
    stale_rate_mean: float
    stale_blocks_mean: float


@dataclasses.dataclass(frozen=True)
class SimResults:
    runs: int
    duration_ms: int
    miners: tuple[MinerStats, ...]
    best_height_mean: float
    overflow_total: int
    mode: str
    elapsed_s: float | None = None
    compile_s: float | None = None

    @staticmethod
    def from_sums(sums: dict[str, Any], config, mode: str, elapsed_s: float | None = None,
                  compile_s: float | None = None) -> "SimResults":
        runs = int(sums["runs"])
        found = np.asarray(sums["blocks_found_sum"], dtype=np.float64)
        share = np.asarray(sums["blocks_share_sum"], dtype=np.float64)
        stale_rate = np.asarray(sums["stale_rate_sum"], dtype=np.float64)
        stale_blocks = np.asarray(sums["stale_blocks_sum"], dtype=np.float64)
        miners = tuple(
            MinerStats(
                miner_id=i,
                hashrate_pct=mc.hashrate_pct,
                selfish=mc.selfish,
                blocks_found_mean=float(found[i]) / runs,
                blocks_share_mean=float(share[i]) / runs,
                stale_rate_mean=float(stale_rate[i]) / runs,
                stale_blocks_mean=float(stale_blocks[i]) / runs,
            )
            for i, mc in enumerate(config.network.miners)
        )
        return SimResults(
            runs=runs,
            duration_ms=config.duration_ms,
            miners=miners,
            best_height_mean=float(sums["best_height_sum"]) / runs,
            overflow_total=int(sums["overflow_sum"]),
            mode=mode,
            elapsed_s=elapsed_s,
            compile_s=compile_s,
        )

    @property
    def duration_days(self) -> int:
        return int(self.duration_ms / 86_400_000)

    def table(self) -> str:
        """The reference's canonical human-readable output (main.cpp:223-234),
        including its integer division of blocks_found by the run count."""
        lines = [
            f"After running {self.runs} simulations for {self.duration_days}d each, on average:"
        ]
        for ms in self.miners:
            # round(), not int(): blocks_found_mean is found_sum / runs, and
            # the float64 product mean * runs can land 1 ulp below the exact
            # integer sum, which int() would truncate to sum - 1.
            found_int = round(ms.blocks_found_mean * self.runs) // self.runs
            line = (
                f"  - Miner {ms.miner_id} ({ms.hashrate_pct}% of network hashrate) found "
                f"{found_int} blocks i.e. {ms.blocks_share_mean * 100:g}% of blocks. "
                f"Stale rate: {ms.stale_rate_mean * 100:g}%."
            )
            if ms.selfish:
                line += " ('selfish mining' strategy)"
            lines.append(line)
        return "\n".join(lines)

    def to_dict(self) -> dict[str, Any]:
        return {
            "runs": self.runs,
            "duration_ms": self.duration_ms,
            "mode": self.mode,
            "elapsed_s": self.elapsed_s,
            "compile_s": self.compile_s,
            "best_height_mean": self.best_height_mean,
            "overflow_total": self.overflow_total,
            "miners": [dataclasses.asdict(m) for m in self.miners],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)
