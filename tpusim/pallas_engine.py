"""Pallas TPU kernel engine: the event loop with run-tile state resident in
VMEM for a whole chunk.

The scan engine (tpusim.engine) pays one HBM round-trip of the entire state
tree per event step — the lax.scan carry lives in HBM, so at ~1 KB of state
per run each of the ~105k steps of a simulated year re-reads and re-writes
every byte. This module re-expresses the same step as a Pallas kernel over a
2D grid ``(run_tiles, step_blocks)``:

  * state arrays are laid out **runs-last** ``(..., R)`` so independent runs
    ride the 128-wide lane dimension of the VPU (the scan engine's runs-first
    layout puts the tiny miner axis on lanes and wastes them);
  * every state BlockSpec indexes by run-tile only — Pallas keeps a revisited
    block in VMEM across the inner (step-block) grid dimension and writes it
    back to HBM once per tile, so state traffic drops from per-step to
    per-chunk;
  * the threefry bits are the **same draws** as the scan engine —
    ``random.bits(fold_in(run_key, 1+chunk), (steps, 2))`` per run, generated
    in transposed ``(steps, 2, R)`` layout and streamed one step-block at a
    time into VMEM — so the kernel's results are bit-identical to the scan
    engine's and the two are cross-checked for exact equality in
    tests/test_pallas_engine.py.

Both consensus representations of tpusim.state are implemented: the pairwise
fast mode (own_cp / own_in / own_cnt) for honest rosters and the exact mode
(common-prefix owner-count tensor ``cp``, private counters, the gamma=0
reveal/race machinery) for selfish ones. The only unsupported combination is
``mode="fast"`` forced onto a selfish roster, which stays on the scan engine.
Semantics contract: reference main.cpp:128-192 event loop,
simulation.h:62-174 model, via SURVEY.md §2.1.
"""

from __future__ import annotations

import logging
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .config import SimConfig
from .engine import (
    DEPTH_BUCKETS,
    Engine,
    SimCounters,
    apply_count_rebase,
    combine_sums,
)
from .flight import (
    KIND_ARRIVAL,
    KIND_FIND,
    KIND_REORG,
    KIND_STALE,
    N_FIELDS,
    FlightRecorder,
    advance_base,
)
from .sampling import winner_thresholds32
from .state import (
    INF_TIME,
    INTERVAL_CAP,
    NEG_TIME_CAP,
    SimState,
    rebase,
)

__all__ = ["PallasEngine", "FAST_TILE_RUNS", "EXACT_TILE_RUNS", "VMEM_BUDGET"]

#: Default run-tile widths (VPU lanes per grid cell), set from v5e
#: measurements — see PallasEngine.__init__ for the rationale.
FAST_TILE_RUNS = 512
EXACT_TILE_RUNS = 256

#: Scoped-VMEM budget the kernel's estimated footprint is guarded against
#: (just under the 16 MiB scoped limit of the v5e generation the tile
#: defaults were measured on). Also surfaced per batch in the telemetry
#: ledger's memory attrs, so dashboards show headroom, not only usage.
VMEM_BUDGET = 15_500_000

logger = logging.getLogger("tpusim")

I32 = jnp.int32
U32 = jnp.uint32

#: State leaf order in the kernel's ref lists, per mode. ``shape`` templates
#: use M (miners), K (group slots); the trailing runs axis is implicit.
_FAST_LEAVES = (
    "t", "nbt", "height", "stale", "base", "garr", "gcnt", "ocp", "oin", "ocnt", "ovf",
)
#: NOTE: the exact kernel's "ocp" leaf holds own_cp TRANSPOSED ([j, i] —
#: see _state_to_kernel): every adoption-select value then reads as a
#: plane-dim broadcast of ``cpb``; the untransposed orientation would need a
#: sublane<->plane transpose per step, which Mosaic lowers poorly.
_EXACT_LEAVES = (
    "t", "nbt", "bhp", "height", "npriv", "stale", "base", "garr", "gcnt",
    "cp", "ocp", "oin", "ocnt", "ovf",
)
#: Telemetry counter leaves (engine.SimCounters, runs-last), appended after
#: the state leaves in the kernel's ref lists: per-run max single-reorg own
#: pops, stale-event count, active steps, stale-events-by-miner, reorg-depth
#: histogram. VMEM-resident like the state, so the per-event cost is one
#: (M, R) reduction and no extra HBM traffic beyond ~(12 + 4*(M+8)) bytes per
#: run per chunk. NOT part of _leaf_shapes: the roofline traffic model
#: (profiling.state_bytes_per_run) counts simulation state.
_TELE_LEAVES = ("mre", "sev", "act", "sbm", "rdh")

#: Flight-recorder leaves (tpusim.flight), appended after the telemetry
#: leaves when ``SimConfig.flight_capacity > 0``: the packed event ring
#: (capacity, N_FIELDS, R), the event count (1, R) and the chunk-origin
#: limbs (3, R) — absolute time as the base-2^30 [hi, lo] pair plus the
#: absolute-height base h_base (FlightRecorder.h_base, the count-re-base
#: accumulator). With the default capacity 0 they do not exist and the
#: kernel is byte-identical to a recorder-less build.
_FLIGHT_LEAVES = ("fbuf", "fcnt", "fbase")


def _leaf_shapes(m: int, k: int, exact: bool) -> list[tuple[int, ...]]:
    if exact:
        return [
            (1,), (1,), (1,), (m,), (m,), (m,), (m,), (m, k), (m, k),
            (m, m, m), (m, m), (m, m), (m,), (1,),
        ]
    return [(1,), (1,), (m,), (m,), (m,), (m, k), (m, k), (m, m), (m, m), (m,), (1,)]


#: Which state leaves carry block COUNTS (packed to int16 when
#: SimConfig.resolved_count_dtype says the bound fits) vs times/diagnostics
#: (always int32). Parallel to _FAST_LEAVES / _EXACT_LEAVES.
_COUNT_LEAVES = frozenset(
    {"bhp", "height", "npriv", "stale", "gcnt", "cp", "ocp", "oin", "ocnt"}
)


def _leaf_dtypes(m: int, k: int, exact: bool, count_dtype,
                 count_rebase: bool = False) -> list:
    """Per-leaf dtypes parallel to :func:`_leaf_shapes` — the packed-state
    authority shared by the kernel's out_shape list and the roofline traffic
    model (profiling.state_bytes_per_run). Under ``count_rebase`` the
    ``stale`` leaf stays int32: it is the one monotone accumulator the
    chunk-boundary re-base does not shift (tpusim.state.rebase_counts), so
    its packed bound would still be the full-duration one."""
    names = _EXACT_LEAVES if exact else _FAST_LEAVES
    counts = _COUNT_LEAVES - {"stale"} if count_rebase else _COUNT_LEAVES
    return [count_dtype if n in counts else I32 for n in names]


def _make_kernel(
    *, exact: bool, any_selfish: bool, sb: int, mean_interval_ms: float,
    n_state: int, superstep: int = 1, flight_capacity: int = 0,
    rng_batch: bool = True, count_dtype=I32, gather: bool = True
):
    """Build the step-block kernel for one mode. Ref order: bits, cap, lo,
    hi, prop, selfish, then ``n_state`` input state refs (HBM-aliased to the
    outputs), then ``n_state`` output state refs (the live, VMEM-resident
    copies). ``superstep`` events are unrolled per fori_loop iteration —
    event e still reads bits row e, so draws (and results) are identical for
    every width. ``flight_capacity`` > 0 appends the event-recorder leaves
    and the per-step ring writes (tpusim.flight row semantics, runs-last).

    ``rng_batch`` (SimConfig.rng_batch): the streamed ``bits`` block holds
    PRE-MAPPED int32 (winner index, interval ms) rows — the host hoisted the
    threshold compares and the log1p out of the kernel into one vectorized
    pass per chunk — so the per-event sampler work shrinks to a single
    one-hot compare. False streams the raw uint32 threefry words and maps
    them per event (the legacy path). ``count_dtype`` (int16 when the
    packed-state bound fits) types every _COUNT_LEAVES ref and all count
    arithmetic; values are identical, the VMEM residency halves.

    ``gather`` (SimConfig.consensus_gather): the sweep's b-indexed reads —
    ``own_cnt[b]``, ``own_cp[:, b]``, ``own_in[b, :]`` and the ``cp[b]``
    plane — use per-lane ``take_along_axis`` on the winner index the
    best-chain min already computes, instead of multiplying the whole
    tensor by the one-hot and reducing (O(M^3 x R) MACs for the cp plane
    become O(M^2 x R) moves). Same entries, bit-identical values; the
    legacy contractions stay behind the knob for A/B and because a
    sublane-axis dynamic gather is exactly the op class Mosaic may lower
    poorly on some TPU generations (next-TPU-window checklist)."""
    fcap = flight_capacity
    cdt = count_dtype

    def kernel(bits_ref, cap_ref, lo_ref, hi_ref, prop_ref, selfish_ref, *state_refs):
        ins, outs = state_refs[:n_state], state_refs[n_state:]
        names = (_EXACT_LEAVES if exact else _FAST_LEAVES) + _TELE_LEAVES
        if fcap:
            names = names + _FLIGHT_LEAVES

        # First step block of this run tile: seed the VMEM-resident output
        # blocks from the inputs. They persist across the inner grid
        # dimension (their block index depends only on the tile) and are
        # written back once.
        @pl.when(pl.program_id(1) == 0)
        def _():
            for src, dst in zip(ins, outs):
                dst[...] = src[...]

        m, k, _ = outs[names.index("garr")].shape
        cap = cap_ref[...]
        lo = lo_ref[...]  # (M, 1) broadcasts against (M, R)
        hi = hi_ref[...]
        prop = prop_ref[...]
        selfish = selfish_ref[...] != 0  # (M, 1)
        kidx = jax.lax.broadcasted_iota(I32, (1, k, 1), 1)  # (1, K, 1)
        midx = jax.lax.broadcasted_iota(I32, (m, 1), 0)  # (M, 1)
        # Identity mask for the cpb diagonal, built directly at its consumer
        # rank: Mosaic cannot shape-cast a 2D eye to 3D
        # ("infer-vector-layout: unsupported shape cast").
        iot = lambda shape, d: jax.lax.broadcasted_iota(I32, shape, d)
        eye3 = iot((m, m, 1), 0) == iot((m, m, 1), 1)
        # Literals, not captured jnp constants (pallas kernels cannot close
        # over device arrays).
        inf = jnp.int32(int(INF_TIME))
        neg_gate = jnp.int32(int(NEG_TIME_CAP) - 1)
        icap = jnp.float32(int(INTERVAL_CAP))

        # The group buffer has two trace-time implementations with identical
        # semantics (bit-identical state; cross-checked against the scan
        # engine): the generic K-slot one-hot machinery, and a split-slot
        # specialization for K=2 in either mode. The specialization exists
        # purely for the VPU: an (M, K, R) op tiles its minor (K, R) dims
        # onto 8x128 vregs, so K=2 uses 2 of 8 sublanes — 75% of the vector
        # unit idles. Carrying the slots as 2xK (M, R) arrays through the
        # step loop instead makes every group op fully dense; ablation
        # timing attributed ~50% of the fast step to exactly these ops.
        # K=2 is the auto default in BOTH modes since round 5 (measured
        # overflow/accuracy basis in SimConfig.resolved_group_slots);
        # group_slots>=3 takes the generic path, overflow-merge
        # diagnostics counted either way.
        split2 = k == 2

        def push_groups(garr, gcnt, arrival, count, do):
            """Append an (arrival, count) group per miner where ``do`` is set
            (tpusim.state._push_groups, runs-last). ``count`` broadcasts
            against (M, R). Returns (garr, gcnt, overflow_increment)."""
            n = jnp.sum((gcnt > 0).astype(I32), axis=1)  # (M, R)
            last_idx = jnp.maximum(n - 1, 0)
            onehot_last = kidx == last_idx[:, None, :]  # (M, K, R)
            last_arr = jnp.sum(jnp.where(onehot_last, garr, 0), axis=1)
            merge = do & (n > 0) & (last_arr == arrival)
            overflowed = do & ~merge & (n == k)
            write_idx = jnp.where(merge | overflowed, last_idx, jnp.minimum(n, k - 1))
            onehot_wr = (kidx == write_idx[:, None, :]) & do[:, None, :]
            garr = jnp.where(onehot_wr, arrival[:, None, :], garr)
            accum = (merge | overflowed)[:, None, :]
            cnt3 = jnp.broadcast_to(count.astype(cdt), merge.shape)[:, None, :]
            gcnt = jnp.where(onehot_wr, jnp.where(accum, gcnt + cnt3, cnt3), gcnt)
            return garr, gcnt, jnp.sum(overflowed.astype(I32), axis=0, keepdims=True)

        def push_groups2(a0, a1, c0, c1, arrival, count, do):
            """push_groups on split K=2 slots, all (M, R). Case-for-case
            equal to the generic path: append to the first empty slot, merge
            an equal-arrival push into the last occupied slot, accumulate
            into slot 1 on overflow."""
            e0 = c0 > 0  # slots fill left to right: c1 > 0 implies c0 > 0
            e1 = c1 > 0
            last_arr = jnp.where(e1, a1, a0)
            merge = do & e0 & (last_arr == arrival)
            overflowed = do & ~merge & e1
            w0 = do & (~e0 | (merge & ~e1))
            w1 = do & e0 & (e1 | ~merge)
            accum = merge | overflowed
            cnt = jnp.broadcast_to(count.astype(cdt), merge.shape)
            a0 = jnp.where(w0, arrival, a0)
            c0 = jnp.where(w0, jnp.where(accum, c0 + cnt, cnt), c0)
            a1 = jnp.where(w1, arrival, a1)
            c1 = jnp.where(w1, jnp.where(accum, c1 + cnt, cnt), c1)
            return a0, a1, c0, c1, jnp.sum(overflowed.astype(I32), axis=0, keepdims=True)

        def step(s, carry):
            st = dict(zip(names, carry))
            t, nbt = st["t"], st["nbt"]
            height, stale, base = st["height"], st["stale"], st["base"]
            garr, gcnt, ovf = st["garr"], st["gcnt"], st["ovf"]
            # Step-entry snapshots the flight rows need: the event time and
            # the pre-push groups (arrival classification, tpusim.flight).
            told = t
            old_garr = st["garr"]

            active = t < cap  # (1, R)
            found_due = active & (t == nbt)
            if rng_batch:
                # Batched wide generation: the (winner, interval) mapping ran
                # once per chunk on the host side of the kernel boundary —
                # the streamed rows are already int32 (index, ms) draws, so
                # the per-event sampler work is ONE equality compare against
                # the miner iota (and the per-step log1p is gone from the
                # VPU's critical path entirely).
                wq = bits_ref[s, 0, :][None, :]  # (1, R) winner index
                dt = bits_ref[s, 1, :][None, :]  # (1, R) interval ms
                ow = (midx == wq) & found_due  # (M, R)
            else:
                bw = bits_ref[s, 0, :][None, :]  # (1, R) uint32
                bi = bits_ref[s, 1, :][None, :]
                # Winner one-hot straight from the cumulative thresholds
                # (simulation.h:213-221): miner m wins iff lo[m] <= u < hi[m];
                # the last interval is closed on the right, clamping the
                # ~96/2^32 overflow draws to the last miner exactly like
                # winner_from_bits.
                is_last = midx == m - 1  # (M, 1)
                ow = (bw >= lo) & ((bw < hi) | is_last) & found_due  # (M, R)
                # Interval draw (simulation.h:205-210, tpusim.sampling).
                # Mosaic has no uint32->float32 cast; after >>8 the value
                # fits in 24 bits, so the int32 detour is exact.
                u = (bi >> U32(8)).astype(I32).astype(jnp.float32) * jnp.float32(2.0**-24)
                dt = jnp.minimum(
                    -jnp.log1p(-u) * jnp.float32(mean_interval_ms), icap
                ).astype(I32)
            owi = ow.astype(cdt)

            # --- FoundBlock (simulation.h:62-76). In both modes a find
            # moves only the (M, R) own-count vector (tpusim.state
            # found_block): the new block sits on the lazily-maintained
            # diagonals, so no M^2/M^3 traffic in the hot find path.
            ocnt = st["ocnt"] + owi
            if exact:
                npriv, bhp, cp = st["npriv"], st["bhp"], st["cp"]
                if any_selfish:
                    sel_w = jnp.any(ow & selfish, axis=0, keepdims=True)  # (1, R)
                    npriv_w = jnp.sum(npriv * owi, axis=0, keepdims=True, dtype=cdt)
                    height_w = jnp.sum(height * owi, axis=0, keepdims=True, dtype=cdt)
                    is_race = sel_w & (npriv_w == 1) & (bhp == height_w)
                    private_append = sel_w & ~is_race
                    push_do = ow & ~private_append
                    push_count = jnp.where(is_race, 2, 1).astype(cdt)  # (1, R)
                    npriv = npriv + jnp.where(
                        ow,
                        jnp.where(private_append, 1, jnp.where(is_race, -1, 0)),
                        0,
                    ).astype(cdt)
                else:
                    push_do = ow
                    push_count = jnp.ones((), cdt)
            else:
                push_do = ow
                push_count = jnp.ones((), cdt)

            arrival = t + prop  # (M, R)
            if split2:
                a0, a1 = st["garr"]
                c0, c1 = st["gcnt"]
                a0, a1, c0, c1, over = push_groups2(
                    a0, a1, c0, c1, arrival, push_count, push_do
                )
            else:
                garr, gcnt, over = push_groups(garr, gcnt, arrival, push_count, push_do)
            ovf = ovf + over
            height = height + owi
            h_found = height  # post-find, pre-adopt chain lengths
            nbt = jnp.where(found_due, t + dt, nbt)

            # --- Notify sweep (flush + best + reveal + reorg), gated like
            # tpusim.state.notify(do=...): a sub-NEG_TIME_CAP flush time is a
            # no-op, and the reveal/adopt masks carry the gate.
            do = active & ~(found_due & (nbt == t))
            t_flush = jnp.where(do, t, neg_gate)  # (1, R)
            if split2:
                # Split-slot flush: sortedness (a0 <= a1 when both live, INF
                # in empty slots) makes the arrived set {f0, f0&f1}.
                f0 = a0 <= t_flush  # (M, R)
                f1 = a1 <= t_flush
                base = jnp.where(f1, a1, jnp.where(f0, a0, base))
                a0, a1 = jnp.where(f1, inf, jnp.where(f0, a1, a0)), jnp.where(f0, inf, a1)
                c0, c1 = jnp.where(f1, 0, jnp.where(f0, c1, c0)), jnp.where(f0, 0, c1)
                unarrived = c0 + c1
            else:
                arrived = garr <= t_flush[:, None, :]  # (M, K, R)
                n_f = jnp.sum(arrived.astype(I32), axis=1)  # (M, R)
                onehot_tip = kidx == (n_f - 1)[:, None, :]
                flushed_tip = jnp.sum(jnp.where(onehot_tip, garr, 0), axis=1)
                base = jnp.where(n_f > 0, flushed_tip, base)
                # Compact: shifted[m, d] = garr[m, d + n_f[m]] via a K x K
                # one-hot sel[m, d, s] = (s == d + n_f[m]); src K rides axis 2.
                sel = kidx[:, None, :, :] == (kidx[:, :, None, :] + n_f[:, None, None, :])
                garr = jnp.sum(jnp.where(sel, garr[:, None, :, :], 0), axis=2)
                garr = jnp.where(jnp.any(sel, axis=2), garr, inf)
                gcnt = jnp.sum(jnp.where(sel, gcnt[:, None, :, :], 0), axis=2, dtype=cdt)
                unarrived = jnp.sum(gcnt, axis=1, dtype=cdt)

            # Best published chain, first-seen tiebreak (main.cpp:68-82).
            pub = height - unarrived  # (M, R)
            if exact:
                pub = pub - npriv
            best_h = jnp.max(pub, axis=0, keepdims=True)  # (1, R)
            cand = pub == best_h
            tipm = jnp.where(cand, base, inf)
            best_tip = jnp.min(tipm, axis=0, keepdims=True)
            winners_b = cand & (tipm == best_tip)
            # First true along the miner axis without a cumsum. Always < m
            # (>= 1 candidate exists) — the per-lane index the gather path
            # reads rows with.
            first_idx = jnp.min(jnp.where(winners_b, midx, m), axis=0, keepdims=True)
            onehot_b = midx == first_idx  # (M, R)
            b32 = onehot_b.astype(cdt)

            def takeb(arr, axis):
                """arr[..., b, ...] along ``axis`` for the per-lane winner
                index (1, R): one gather per lane instead of a whole-tensor
                one-hot contract-and-sum. Keeps a size-1 ``axis`` dim."""
                tgt = arr.shape[:axis] + (1,) + arr.shape[axis + 1:]
                idx = jnp.broadcast_to(
                    first_idx.reshape((1,) * (arr.ndim - 1) + (-1,)), tgt
                )
                return jnp.take_along_axis(arr, idx, axis=axis)

            if exact and any_selfish:
                # --- Selfish reveal (simulation.h:149-174), before reorg.
                lead = height - best_h  # (M, R)
                sc = npriv
                can_reveal = selfish & (lead >= 0) & (sc > lead) & do
                reveal_n = jnp.where((sc > 1) & (lead == 1), sc, sc - lead)
                if split2:
                    a0, a1, c0, c1, over = push_groups2(
                        a0, a1, c0, c1, t + prop, reveal_n, can_reveal
                    )
                else:
                    garr, gcnt, over = push_groups(
                        garr, gcnt, t + prop, reveal_n, can_reveal
                    )
                ovf = ovf + over
                npriv = jnp.where(can_reveal, sc - reveal_n, sc)

            # --- Reorg (simulation.h:124-142): adopt when strictly longer
            # than the full local chain (private blocks included).
            adopt = (best_h > height) & do  # (M, R)

            # Shared diagonal corrections (tpusim.state.notify): ocnt is the
            # authority for every stale diagonal read. Gather path reads b's
            # rows by the per-lane index; legacy contracts with the one-hot.
            ocp, oin = st["ocp"], st["oin"]
            if gather:
                unpub_b = takeb(height, 0) - best_h  # (1, R)
                cnt_b = takeb(ocnt, 0)  # (1, R)
                if exact:
                    # Exact ocp is stored transposed ([j, i], see
                    # _EXACT_LEAVES); own_cp[:, b] is its b-th plane.
                    oc_b = takeb(ocp, 0)[0]  # (M, R)
                else:
                    oc_b = takeb(ocp, 1)[:, 0, :]  # (M, R) own_cp[:, b]
                oc_bb = takeb(oc_b, 0)
            else:
                unpub_b = jnp.sum(height * b32, axis=0, keepdims=True, dtype=cdt) - best_h  # (1, R)
                cnt_b = jnp.sum(ocnt * b32, axis=0, keepdims=True, dtype=cdt)  # (1, R)
                if exact:
                    oc_b = jnp.sum(ocp * b32[:, None, :], axis=0, dtype=cdt)  # (M, R)
                else:
                    oc_b = jnp.sum(ocp * b32[None, :, :], axis=1, dtype=cdt)  # (M, R) own_cp[:, b]
                oc_bb = jnp.sum(oc_b * b32, axis=0, keepdims=True, dtype=cdt)
            oc_b = oc_b + b32 * (cnt_b - oc_bb)
            # Own blocks above lca(:, b) — reorg stale accounting. The
            # per-miner pop count also feeds the telemetry counters below,
            # exactly like the scan engine's stale delta (engine._count_step).
            d_stale = jnp.where(adopt, ocnt - oc_b, 0)
            stale = stale + d_stale
            if gather:
                row_b = takeb(oin, 0)[0]  # (M, R) own_in[b, :]
                row_bb = takeb(row_b, 0)
            else:
                row_b = jnp.sum(oin * b32[:, None, :], axis=0, dtype=cdt)  # (M, R) own_in[b, :]
                row_bb = jnp.sum(row_b * b32, axis=0, keepdims=True, dtype=cdt)
            row_b = row_b + b32 * (cnt_b - row_bb)
            row_bpub = row_b - unpub_b * b32  # (M, R) composition of b_pub

            if exact:
                # cpb[j, o] = cp[b, j, o]. Its j == b row is stale (an
                # i == j plane of the stored tensor) but every consumer
                # below excludes it via ~onehot_b masks, so it needs no
                # correction (tpusim.state.notify).
                if gather:
                    cpb = takeb(cp, 0)[0]  # (M, M, R) — one plane move,
                    # where the one-hot path contracted the whole
                    # (M, M, M, R) tensor (the single hottest exact-mode op)
                    cpb_diag = jnp.take_along_axis(
                        cpb, iot((m, 1, 1), 0), axis=1
                    )[:, 0, :]  # (M, R) cp[b, i, i] — static diagonal gather
                else:
                    cpb = jnp.sum(cp * b32[:, None, None, :], axis=0, dtype=cdt)  # (M, M, R)
                    cpb_diag = jnp.sum(jnp.where(eye3, cpb, 0), axis=1, dtype=cdt)  # (M, R) cp[b, i, i]
                # Factored closed-form update (tpusim.state.notify — entry-
                # for-entry equal to the historical 3-level case analysis):
                #   Y[j] = (a_j | b_j) ? b_pub : cpb[j]
                #   W[i] = b_i ? b_pub : cpb[i]
                #   cp[i,j] = a_i ? Y[j] : (a_j ? W[i] : cp[i,j])
                # Two selects over the (M, M, M, R) tensor instead of three,
                # and no composed cond masks.
                ab = adopt | onehot_b  # (M, R)
                y_val = jnp.where(ab[:, None, :], row_bpub[None, :, :], cpb)  # (M, M, R)
                w_val = jnp.where(onehot_b[:, None, :], row_bpub[None, :, :], cpb)
                cp = jnp.where(
                    adopt[:, None, None, :],
                    y_val[None, :, :, :],
                    jnp.where(adopt[None, :, None, :], w_val[:, None, :, :], cp),
                )
                # own_cp from the o == i slices of the same update, written
                # in its transposed [j, i] orientation: the a_i-case value
                # Y[j, i] = (a_j|b_j) ? row_bpub[i] : cpb[j, i] IS y_val
                # read as (j, i) — no transpose needed (the whole point of
                # the transposed storage); the a_j-case value W[i, i] is the
                # (M, R) vector wo below.
                wo = jnp.where(onehot_b, row_bpub, cpb_diag)  # (M, R)
                ocp = jnp.where(
                    adopt[None, :, :],  # a_i (i on sublanes in [j, i])
                    y_val,
                    jnp.where(adopt[:, None, :], wo[None, :, :], ocp),
                )
                npriv = jnp.where(adopt, 0, npriv)
                bhp = jnp.where(do, best_h, bhp)
            else:
                # Fast pairwise approximation (tpusim.state.notify): the two
                # nested selects collapse to one under the combined mask —
                # both replacement values broadcast from (M, R) vectors
                # selected by a_i alone (see the scan twin).
                col_cp = oc_b - unpub_b * b32
                ocp = jnp.where(
                    adopt[:, None, :] | adopt[None, :, :],
                    jnp.where(adopt, row_bpub, col_cp)[:, None, :],
                    ocp,
                )
            oin = jnp.where(adopt[:, None, :], row_bpub[None, :, :], oin)
            ocnt = jnp.where(adopt, row_bpub, ocnt)

            height = jnp.where(adopt, best_h, height)
            base = jnp.where(adopt, best_tip, base)
            if split2:
                a0 = jnp.where(adopt, inf, a0)
                a1 = jnp.where(adopt, inf, a1)
                c0 = jnp.where(adopt, 0, c0)
                c1 = jnp.where(adopt, 0, c1)
                # Cut-through (main.cpp:173-182).
                p0 = jnp.where(a0 > t, a0, inf)
                p1 = jnp.where(a1 > t, a1, inf)
                earliest = jnp.min(jnp.minimum(p0, p1), axis=0)[None, :]  # (1, R)
            else:
                garr = jnp.where(adopt[:, None, :], inf, garr)
                gcnt = jnp.where(adopt[:, None, :], 0, gcnt)
                # Cut-through (main.cpp:173-182).
                pending = jnp.where(garr > t[:, None, :], garr, inf)
                earliest = jnp.min(pending, axis=(0, 1))[None, :]  # (1, R)
            t = jnp.where(active, jnp.maximum(jnp.minimum(nbt, earliest), t), t)

            # Telemetry counters (engine.SimCounters semantics, bit-equal to
            # the scan engine's by construction: same masks, same operands).
            # Widened to int32 for the counter leaves, which stay wide
            # regardless of the packed count dtype (engine._count_step).
            dmax = jnp.max(d_stale, axis=0, keepdims=True).astype(I32)  # (1, R)

            if fcap:
                # Flight recorder (tpusim.flight.record_step, runs-last): up
                # to two ring rows per step — find-or-arrival, then
                # stale-or-reorg — same masks and operands as the scan
                # engine's recorder, so the buffers are pinned bit-equal.
                fbuf, fcnt, fbase = st["fbuf"], st["fcnt"], st["fbase"]
                b_hi, b_lo = fbase[0:1, :], fbase[1:2, :]
                # Absolute-height origin (flight.FlightRecorder.h_base):
                # int32 promotion makes the add exact for packed heights.
                h_b = fbase[2:3, :]
                cidx = iot((fcap, 1, 1), 0)
                fidx = iot((1, N_FIELDS, 1), 1)

                def krow(kind, miner, hgt, depth):
                    vals = (kind, miner, hgt, depth, b_hi, b_lo + told)
                    row = vals[0].astype(I32)[:, None, :]
                    for f in range(1, N_FIELDS):
                        row = jnp.where(fidx == f, vals[f].astype(I32)[:, None, :], row)
                    return row  # (1, F, R)

                def kpush(fcnt, fbuf, rec, kind, miner, hgt, depth):
                    slot = jax.lax.rem(fcnt, jnp.int32(fcap))  # (1, R)
                    onehot = cidx == slot  # (C, 1, R)
                    fbuf = jnp.where(onehot & rec, krow(kind, miner, hgt, depth), fbuf)
                    return fcnt + rec.astype(I32), fbuf

                if split2:
                    a0o, a1o = old_garr
                    pmin_per = jnp.minimum(
                        jnp.where(a0o <= told, a0o, inf),
                        jnp.where(a1o <= told, a1o, inf),
                    )  # (M, R)
                else:
                    pmin_per = jnp.min(
                        jnp.where(old_garr <= told, old_garr, inf), axis=1
                    )
                pmin = jnp.min(pmin_per, axis=0, keepdims=True)  # (1, R)
                flushed = do & (pmin < inf)
                arr_miner = jnp.min(
                    jnp.where(pmin_per == pmin, midx, m), axis=0, keepdims=True
                )
                rec1 = found_due | flushed
                kind1 = jnp.where(found_due, KIND_FIND, KIND_ARRIVAL)
                w_idx = jnp.sum(midx * owi, axis=0, keepdims=True)  # (1, R)
                miner1 = jnp.where(found_due, w_idx, arr_miner)
                h1 = jnp.sum(
                    jnp.where(midx == miner1, jnp.where(found_due, h_found, height), 0),
                    axis=0, keepdims=True,
                )
                rec2 = jnp.any(adopt, axis=0, keepdims=True)
                kind2 = jnp.where(dmax > 0, KIND_STALE, KIND_REORG)
                score = jnp.where(adopt, d_stale, -1)
                miner2 = jnp.min(
                    jnp.where(adopt & (score == jnp.max(score, axis=0, keepdims=True)),
                              midx, m),
                    axis=0, keepdims=True,
                )
                h2 = jnp.sum(jnp.where(midx == miner2, height, 0), axis=0, keepdims=True)
                fcnt, fbuf = kpush(fcnt, fbuf, rec1, kind1, miner1, h1 + h_b,
                                   jnp.zeros_like(dmax))
                fcnt, fbuf = kpush(fcnt, fbuf, rec2, kind2, miner2, h2 + h_b, dmax)
                st.update(fbuf=fbuf, fcnt=fcnt)

            st.update(
                mre=jnp.maximum(st["mre"], dmax),
                sev=st["sev"] + (dmax > 0).astype(I32),
                act=st["act"] + active.astype(I32),
                sbm=st["sbm"] + (d_stale > 0).astype(I32),
                rdh=st["rdh"]
                + ((iot((DEPTH_BUCKETS, 1), 0) == jnp.minimum(dmax, DEPTH_BUCKETS) - 1)
                   & (dmax > 0)).astype(I32),
            )
            st.update(t=t, nbt=nbt, height=height, stale=stale, base=base,
                      ovf=ovf, ocp=ocp, oin=oin, ocnt=ocnt)
            if split2:
                st.update(garr=(a0, a1), gcnt=(c0, c1))
            else:
                st.update(garr=garr, gcnt=gcnt)
            if exact:
                st.update(npriv=npriv, bhp=bhp, cp=cp)
            return tuple(st[name] for name in names)

        def load(ref, name: str):
            val = ref[...]
            if split2 and name in ("garr", "gcnt"):
                return (val[:, 0, :], val[:, 1, :])
            return val

        def stored(val, name: str):
            if split2 and name in ("garr", "gcnt"):
                # Rebuild the (M, K, R) layout with a K-broadcast select (a
                # middle-axis concatenate does not lower in Mosaic).
                return jnp.where(kidx == 0, val[0][:, None, :], val[1][:, None, :])
            return val

        def superblock(s, carry):
            for j in range(superstep):
                carry = step(s * superstep + j, carry)
            return carry

        carry = tuple(load(ref, name) for ref, name in zip(outs, names))
        carry = jax.lax.fori_loop(0, sb // superstep, superblock, carry)
        for ref, val, name in zip(outs, carry, names):
            ref[...] = stored(val, name)

    return kernel


class PallasEngine(Engine):
    """Engine with the per-chunk execution replaced by the VMEM-resident
    Pallas kernel. Same host loop, same init/finalize, same draws — the
    outputs are bit-identical to the scan engine on any supported config.
    "Same finalize" carries the streaming-moment telemetry with it: the
    per-run statistic leaves (including ``blocks_found_per_run``) come from
    the one shared ``finalize_fn``, so the ``stats_*`` moment keys are
    bit-equal scan-vs-pallas by construction, and a tile-misaligned batch's
    head/tail split merges them exactly through ``combine_sums``'s additive
    int64 rule (pinned by tests/test_convergence.py).
    Single-controller device meshes shard the batch's runs axis and run the
    kernel on every device (run-level parallelism of reference
    main.cpp:195-220 at kernel speed); multi-controller meshes and
    fast-mode-with-selfish rosters stay on the scan engine.

    ``tile_runs`` lanes of independent runs per grid cell (multiple of 128);
    ``step_block`` scan steps per kernel invocation — state stays in VMEM
    across step blocks of the same tile, bits stream in per block.
    """

    def __init__(
        self,
        config: SimConfig,
        mesh=None,
        *,
        tile_runs: int | None = None,
        step_block: int = 64,
        interpret: bool = False,
        vmem_guard: bool = True,
        packed: bool = False,
    ):
        if packed and not config.rng_batch:
            # Under rng_batch the kernel consumes PRE-MAPPED (winner,
            # interval) rows — thresholds and the mean interval live in the
            # XLA pre-pass, which handles per-run params like any other
            # vectorized op. The legacy raw-words path bakes them into the
            # kernel body, so packing requires the batched sampler.
            raise ValueError(
                "packed pallas engines need rng_batch=True (the kernel's "
                "sampler params become per-run tensors in the XLA pre-pass)"
            )
        if mesh is not None and jax.process_count() > 1:
            raise ValueError(
                "PallasEngine shards batches over single-controller meshes "
                "only; multi-controller runs use the scan engine"
            )
        if config.network.any_selfish and config.resolved_mode != "exact":
            raise ValueError(
                "PallasEngine needs exact mode for selfish rosters (fast-mode "
                "selfish approximation stays on the scan engine)"
            )
        if config.rng != "threefry":
            raise ValueError(
                "PallasEngine draws threefry bits outside the kernel; "
                "rng='xoroshiro' runs on the scan engine"
            )
        if tile_runs is None:
            # Measured on v5e (16 MiB scoped VMEM), 8192 runs x 365 d: fast
            # mode peaks at 512 lanes (1877 yr/s vs 1749 at 1024 with K=2);
            # exact mode's (M, M, M, tile) cp tensor and its contraction
            # temporaries blow the scoped-VMEM limit at 512 (17.4 MiB) and
            # lower at 256.
            tile_runs = (
                EXACT_TILE_RUNS if config.resolved_mode == "exact" else FAST_TILE_RUNS
            )
            # Multi-run-per-kernel-instance grid for SMALL batches: a batch
            # below the measured tile used to route wholly to the scan twin
            # (run_batch's misalignment split). Shrinking the auto tile to
            # the largest 128-multiple the batch fills keeps the runs on the
            # kernel with every VPU lane busy — a batch of 256 runs as ONE
            # 256-lane tile (grid cell) instead of zero kernel runs; the
            # vmem_est guard below scales with the shrunk tile accordingly.
            # Explicit tile_runs is never overridden.
            if config.batch_size < tile_runs:
                tile_runs = max(128, (config.batch_size // 128) * 128)
        if tile_runs % 128 != 0:
            raise ValueError("tile_runs must be a multiple of 128")
        if step_block < 1:
            raise ValueError(f"step_block must be >= 1, got {step_block}")
        # Refuse configs whose per-tile state cannot fit scoped VMEM *before*
        # handing the kernel to Mosaic: an oversized kernel (e.g. 32 miners in
        # exact mode — the cp block alone is m^3*tile*4 = 33 MB at tile 256)
        # can grind the remote compiler for tens of minutes instead of
        # failing, and make_engine's scan fallback never gets a chance. The
        # factor 10 is anchored on the measured 9-miner exact footprint
        # (17.4 MiB at tile 512 = state-bytes x tile x ~10 for the
        # contraction temporaries). The interpreter has no such limit, so
        # interpret=True skips the guard (it is the debug path for exactly
        # these configs). ``vmem_guard=False`` is the bring-up escape hatch
        # (scripts/tpu_smoke.py --no-vmem-guard) for re-calibrating the
        # estimate against what the real compiler accepts: the conservative
        # x10 factor is anchored on a kernel generation whose temporaries
        # have since shrunk, and only a hardware compile can say by how much.
        m, k = config.network.n_miners, config.resolved_group_slots
        exact = config.resolved_mode == "exact"
        from .state import COUNT_DTYPES

        cdt = COUNT_DTYPES[config.resolved_count_dtype]
        # dtype-aware state footprint: packed int16 count leaves halve their
        # VMEM residency (the whole point of SimConfig.state_dtype; under
        # count_rebase the stale leaf stays int32 — _leaf_dtypes).
        state_bytes = sum(
            math.prod(s) * jnp.dtype(d).itemsize
            for s, d in zip(
                _leaf_shapes(m, k, exact),
                _leaf_dtypes(m, k, exact, cdt, config.count_rebase),
            )
        )
        vmem_est = state_bytes * tile_runs * 10
        # The flight ring is VMEM-resident storage plus one (C, F, tile) row
        # select per recorded event — bulk, not contraction temporaries, so a
        # x2 allowance instead of the state's x10.
        vmem_est += config.flight_capacity * N_FIELDS * 4 * tile_runs * 2
        if vmem_est > VMEM_BUDGET and not interpret and vmem_guard:
            raise ValueError(
                f"estimated kernel VMEM footprint {vmem_est / 1e6:.1f} MB exceeds "
                f"the 16 MB scoped limit ({m} miners, {'exact' if exact else 'fast'} "
                f"mode, tile_runs={tile_runs}); use the scan engine"
            )
        super().__init__(config, mesh, packed=packed)
        #: The guard's estimate, kept for the telemetry memory attrs
        #: (memory_attrs): the per-batch ledger reports estimate vs. budget.
        self.vmem_est = int(vmem_est)
        # The kernel consumes whole step blocks. The scan engine's auto
        # sizing is 64-aligned on every platform; silently changing an
        # explicitly requested chunk_steps would fork the sampling identity
        # between platforms, so refuse instead (make_engine then falls back
        # to the scan engine).
        self.step_block = step_block
        if self.chunk_steps % step_block != 0:
            raise ValueError(
                f"chunk_steps ({self.chunk_steps}) must be a multiple of "
                f"step_block ({step_block}) for the pallas engine"
            )
        # The kernel unrolls whole supersteps inside a step block; re-resolve
        # K against step_block (Engine resolved it against chunk_steps, a
        # multiple of step_block, so an explicit valid K stays unchanged and
        # the auto default can only shrink).
        from .engine import resolve_superstep

        self.superstep = resolve_superstep(
            config.superstep, step_block, exact=self.exact
        )
        self.tile_runs = tile_runs
        self.interpret = interpret

        net = config.network
        thr = winner_thresholds32(np.array([mc.hashrate_pct for mc in net.miners]))
        lo = np.concatenate([[0], thr[:-1]]).astype(np.uint32)
        self._lo = jnp.asarray(lo[:, None])
        self._hi = jnp.asarray(thr[:, None])
        self._prop = jnp.asarray(
            np.array([mc.propagation_ms for mc in net.miners], np.int32)[:, None]
        )
        self._selfish = jnp.asarray(
            np.array([mc.selfish for mc in net.miners], np.int32)[:, None]
        )
        # Replace the scan chunk in BOTH batch paths: _chunk drives the
        # host-loop path, _chunk_impl is what _device_loop (jitted lazily, so
        # this assignment lands before the first trace) closes over — with a
        # mesh, the shard-mapped device loop then runs the kernel on every
        # device against its local run shard (pallas_call operands inside
        # shard_map are the per-device shards).
        if mesh is None:
            self._chunk = jax.jit(self._pallas_chunk)
        else:
            from jax.sharding import PartitionSpec as P

            from .compat import shard_map

            rep_params = jax.tree_util.tree_map(lambda _: P(), self.params)
            self._chunk = jax.jit(
                shard_map(
                    self._pallas_chunk, mesh=mesh,
                    in_specs=(P("runs"), P("runs"), P("runs"), P("runs"), P(), rep_params),
                    out_specs=(P("runs"), P("runs"), P("runs")),
                    check_vma=False,
                )
            )
        self._chunk_impl = self._pallas_chunk
        self._scan_fallback: Engine | None = None

    def reuse_key(self) -> tuple:
        # The kernel BAKES what the scan engine takes as runtime params: the
        # winner thresholds / propagation / selfish flags are captured
        # constants of the jitted _pallas_chunk and the mean interval is a
        # Python float inside the kernel body — so pallas reuse additionally
        # requires the full roster, the interval, and the tiling knobs.
        # PACKED engines bake none of that: propagation/selfish stream in as
        # per-run (M, R) kernel refs and the sampler params live in the XLA
        # pre-pass, so only the tiling knobs extend the scan key.
        c = self.config
        if self.packed:
            return super().reuse_key() + (
                self.tile_runs, self.step_block, self.interpret,
            )
        roster = tuple(
            (mc.hashrate_pct, mc.propagation_ms, mc.selfish)
            for mc in c.network.miners
        )
        return super().reuse_key() + (
            roster, c.network.block_interval_s, self.tile_runs,
            self.step_block, self.interpret,
        )

    def rebind(self, config: SimConfig, key: tuple) -> "PallasEngine":
        super().rebind(config, key)
        if self._scan_fallback is not None:
            import dataclasses

            twin_cfg = dataclasses.replace(config, chunk_steps=self.chunk_steps)
            # Validate with a FRESH twin's key (construction is cheap): the
            # pallas key subsumes every scan-baked value today, but the twin
            # guard must not depend on that staying true.
            self._scan_fallback.rebind(twin_cfg, Engine(twin_cfg).reuse_key())
        return self

    def memory_attrs(self) -> dict[str, int]:
        """The scan model's per-run state footprint plus this kernel's
        VMEM-residency estimate against the scoped budget — the number the
        __init__ guard refuses on, now visible per batch in the ledger."""
        attrs = super().memory_attrs()
        attrs["vmem_est_bytes"] = self.vmem_est
        attrs["vmem_budget_bytes"] = VMEM_BUDGET
        return attrs

    def scan_twin(self) -> Engine:
        """A scan engine pinned to this engine's resolved chunk_steps — the
        identical sampling identity, so its results are bit-for-bit what the
        kernel would produce. The one place the pinning rule lives."""
        if self._scan_fallback is None:
            import dataclasses

            self._scan_fallback = Engine(
                dataclasses.replace(self.config, chunk_steps=self.chunk_steps),
                packed=self.packed,
            )
        # The twin serves the same logical batch: it inherits the fault-
        # injection seam and the pipelined-fetch watchdog (refreshed on
        # every call — the runner may attach/detach chaos between batches).
        self._scan_fallback.chaos = self.chaos
        self._scan_fallback.flag_fetch_timeout_s = self.flag_fetch_timeout_s
        if self.packed:
            # Packed runtime inputs travel with the batch, not the config:
            # the twin must see the SAME per-run params/durations this
            # engine was dispatched with.
            self._scan_fallback.params = self.params
            self._scan_fallback.run_durations = self.run_durations
            self._scan_fallback.max_chunks = self.max_chunks
        return self._scan_fallback

    def run_batch(self, keys, *, host_loop: bool = False, pipelined: bool = False):
        """Tile-misaligned batches split: the aligned prefix runs on the
        kernel, the remainder on the draw-identical scan twin. With a mesh
        the alignment unit is ``tile_runs`` per device (every device's shard
        must be whole tiles)."""
        n = keys.shape[0]
        unit = self.tile_runs * (1 if self.mesh is None else self.mesh.devices.size)
        rem = n % unit
        if rem == 0:
            return super().run_batch(keys, host_loop=host_loop, pipelined=pipelined)
        if self.packed:
            # The head/tail split slices KEYS but the per-run params and
            # durations ride on the engine — a silent split would misalign
            # them. The packed dispatcher pads every dispatch to the tile
            # unit (tpusim.packed._pad_width), so this is a caller bug.
            raise ValueError(
                f"packed pallas dispatch of {n} runs is not a multiple of "
                f"{unit} (tile_runs x devices); pad the pack width"
            )
        logger.info(
            "batch of %d is not a multiple of %d (tile_runs x devices); "
            "%d run(s) take the scan engine",
            n, unit, rem,
        )
        if n < unit:
            return self.scan_twin().run_batch(
                keys, host_loop=host_loop, pipelined=pipelined
            )
        head = super().run_batch(keys[: n - rem], host_loop=host_loop, pipelined=pipelined)
        tail = self.scan_twin().run_batch(
            keys[n - rem:], host_loop=host_loop, pipelined=pipelined
        )
        return combine_sums(head, tail)

    def run_batch_async(self, keys):
        """Async dispatch only for whole-tile batches; a misaligned batch
        needs the head/tail split of :meth:`run_batch`, which is inherently
        synchronous — wrap its (already computed) result instead."""
        n = keys.shape[0]
        unit = self.tile_runs * (1 if self.mesh is None else self.mesh.devices.size)
        if n % unit == 0:
            return super().run_batch_async(keys)
        out = self.run_batch(keys)
        return lambda: out

    def _state_to_kernel(self, state: SimState):
        """SimState (runs-first) -> ordered runs-last leaf tuple. The exact
        kernel's own_cp leaf is transposed to [j, i] (see _EXACT_LEAVES);
        the swap happens here in XLA, once per chunk."""
        tr = lambda x: jnp.moveaxis(x, 0, -1)
        if self.exact:
            return (
                state.t[None, :], state.next_block_time[None, :],
                state.best_height_prev[None, :],
                tr(state.height), tr(state.n_private), tr(state.stale),
                tr(state.base_tip_arrival), tr(state.group_arrival),
                tr(state.group_count), tr(state.cp),
                tr(state.own_cp).swapaxes(0, 1), tr(state.own_in),
                tr(state.own_cnt), state.overflow[None, :],
            )
        return (
            state.t[None, :], state.next_block_time[None, :],
            tr(state.height), tr(state.stale), tr(state.base_tip_arrival),
            tr(state.group_arrival), tr(state.group_count),
            tr(state.own_cp), tr(state.own_in), tr(state.own_cnt),
            state.overflow[None, :],
        )

    def _state_from_kernel(self, state: SimState, out) -> SimState:
        bk = lambda x: jnp.moveaxis(x, -1, 0)
        if self.exact:
            (t, nbt, bhp, height, npriv, stale, base, garr, gcnt, cp,
             ocp, oin, ocnt, ovf) = out
            return state._replace(
                t=t[0], next_block_time=nbt[0], best_height_prev=bhp[0],
                height=bk(height), n_private=bk(npriv), stale=bk(stale),
                base_tip_arrival=bk(base), group_arrival=bk(garr),
                group_count=bk(gcnt), cp=bk(cp),
                own_cp=bk(ocp.swapaxes(0, 1)), own_in=bk(oin),
                own_cnt=bk(ocnt), overflow=ovf[0],
            )
        t, nbt, height, stale, base, garr, gcnt, ocp, oin, ocnt, ovf = out
        return state._replace(
            t=t[0], next_block_time=nbt[0],
            height=bk(height), stale=bk(stale), base_tip_arrival=bk(base),
            group_arrival=bk(garr), group_count=bk(gcnt),
            own_cp=bk(ocp), own_in=bk(oin), own_cnt=bk(ocnt), overflow=ovf[0],
        )

    def _pallas_chunk(self, state: SimState, aux, cap, keys, chunk_idx, params):
        n = cap.shape[0]
        m, k = self.n_miners, self.config.resolved_group_slots
        steps, sb, tile = self.chunk_steps, self.step_block, self.tile_runs
        if n % tile != 0:
            raise ValueError(f"batch ({n}) must be a multiple of tile_runs ({tile})")

        # Same draws as the scan engine, already transposed to (steps, 2, R).
        bits = jax.vmap(
            lambda kk: jax.random.bits(jax.random.fold_in(kk, 1 + chunk_idx), (steps, 2), U32),
            out_axes=2,
        )(keys)
        if self.config.rng_batch:
            # Batched wide generation (SimConfig.rng_batch): map the whole
            # chunk's winner/interval words in ONE vectorized XLA pass and
            # stream pre-mapped int32 (index, ms) rows into the kernel — the
            # same elementwise maps as the scan engine's batched path
            # (sampling.winners_from_bits / interval_from_bits), so the two
            # engines stay bit-equal draw for draw.
            from .sampling import interval_from_bits, winners_from_bits

            bits = jnp.stack(
                [
                    winners_from_bits(bits[:, 0, :], params.thresholds),
                    interval_from_bits(bits[:, 1, :], params.mean_interval_ms),
                ],
                axis=1,
            )

        st = self._state_to_kernel(state)
        # Telemetry counters ride as extra runs-last kernel leaves after the
        # state (engine.SimCounters order: reorg_max, stale_events,
        # active_steps, stale_by_miner, reorg_depth_hist), aliased in-out
        # like every state leaf.
        ctr = aux[0]
        st = st + (ctr.reorg_max[None, :], ctr.stale_events[None, :],
                   ctr.active_steps[None, :],
                   jnp.moveaxis(ctr.stale_by_miner, 0, -1),
                   jnp.moveaxis(ctr.reorg_depth_hist, 0, -1))
        cdt = self.count_dtype
        shapes = [s + (n,) for s in _leaf_shapes(m, k, self.exact)]
        dtypes = list(_leaf_dtypes(m, k, self.exact, cdt, self.count_rebase))
        shapes += [(1, n)] * 3 + [(m, n), (DEPTH_BUCKETS, n)]
        dtypes += [I32] * len(_TELE_LEAVES)
        fcap = self.flight_capacity
        if fcap:
            # Flight-recorder leaves (tpusim.flight): ring, count, and the
            # chunk-origin limbs — absolute time as a base-2^30 pair plus
            # the absolute-height base (read-only in-kernel; the
            # post-rebase advances below are the writers).
            fr: FlightRecorder = aux[-2]
            st = st + (jnp.moveaxis(fr.buf, 0, -1), fr.count[None, :],
                       jnp.stack([fr.base_hi, fr.base_lo, fr.h_base]))
            shapes += [(fcap, N_FIELDS, n), (1, n), (3, n)]
            dtypes += [I32] * len(_FLIGHT_LEAVES)

        def tile_spec(shape):
            block = shape[:-1] + (tile,)
            ndim = len(shape)

            def index_map(i, j, nd=ndim):
                return (0,) * (nd - 1) + (i,)

            return pl.BlockSpec(block, index_map, memory_space=pltpu.VMEM)

        def const_spec(shape):
            nd = len(shape)
            return pl.BlockSpec(shape, lambda i, j, nd=nd: (0,) * nd, memory_space=pltpu.VMEM)

        if self.packed:
            # Grid packing: propagation delays and selfish flags become
            # per-run (M, R) kernel refs, tiled like the state (the kernel
            # body broadcasts (M, tile) exactly as it broadcast (M, 1), so
            # the per-lane arithmetic is bit-identical). The sampler params
            # (thresholds, mean interval) already rode the per-run XLA
            # pre-pass above under rng_batch — which packed mode requires —
            # so the kernel itself needs no sampler inputs at all; the
            # lo/hi refs stay as unused (M, 1) placeholders and the baked
            # mean is dead code behind the rng_batch branch.
            prop_in = jnp.moveaxis(params.prop_ms, 0, -1)
            selfish_in = jnp.moveaxis(params.selfish.astype(I32), 0, -1)
            prop_spec = selfish_spec = tile_spec((m, n))
            mean_for_kernel = 0.0
        else:
            prop_in, selfish_in = self._prop, self._selfish
            prop_spec = selfish_spec = const_spec((m, 1))
            # self.params.mean_interval_ms is the concrete Python float; the
            # traced `params` copy would be a captured constant in the
            # kernel.
            mean_for_kernel = float(self.params.mean_interval_ms)

        kernel = _make_kernel(
            exact=self.exact, any_selfish=self.any_selfish, sb=sb,
            mean_interval_ms=mean_for_kernel,
            n_state=len(shapes), superstep=self.superstep,
            flight_capacity=fcap, rng_batch=self.config.rng_batch,
            count_dtype=cdt, gather=self.config.consensus_gather,
        )
        grid = (n // tile, steps // sb)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((sb, 2, tile), lambda i, j: (j, 0, i), memory_space=pltpu.VMEM),
                tile_spec((1, n)),  # cap
                const_spec((m, 1)),  # lo
                const_spec((m, 1)),  # hi
                prop_spec,  # prop (per-run (M, R) when packed)
                selfish_spec,  # selfish (per-run (M, R) when packed)
                *[tile_spec(s) for s in shapes],
            ],
            out_specs=[tile_spec(s) for s in shapes],
            out_shape=[jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes)],
            input_output_aliases={6 + i: i for i in range(len(shapes))},
            interpret=self.interpret,
        )(bits, cap[None, :], self._lo, self._hi, prop_in, selfish_in, *st)

        n_tail = len(_TELE_LEAVES) + (len(_FLIGHT_LEAVES) if fcap else 0)
        out, tail = out[: len(out) - n_tail], out[len(out) - n_tail:]
        new_ctr = SimCounters(
            tail[0][0], tail[1][0], tail[2][0],
            jnp.moveaxis(tail[3], -1, 0), jnp.moveaxis(tail[4], -1, 0),
        )
        new_state, elapsed = jax.vmap(rebase)(self._state_from_kernel(state, out))
        new_fr = None
        if fcap:
            fb, fc, fbase = tail[5:]
            new_fr = advance_base(
                FlightRecorder(
                    buf=jnp.moveaxis(fb, -1, 0), count=fc[0],
                    base_hi=fbase[0], base_lo=fbase[1], h_base=fbase[2],
                ),
                elapsed,
            )
        new_cb = aux[-1]
        if self.count_rebase:
            # Count re-base outside the kernel, in plain XLA — the kernel
            # never sees it (engine.apply_count_rebase wraps
            # tpusim.state.rebase_counts; the scan twin runs the identical
            # code, so the two engines stay bit-equal by construction).
            new_state, new_cb, new_fr = apply_count_rebase(
                new_state, new_cb, new_fr, batched=True
            )
        return new_state, (new_ctr, new_fr, new_cb), elapsed
