"""Pallas TPU kernel engine: the event loop with run-tile state resident in
VMEM for a whole chunk.

The scan engine (tpusim.engine) pays one HBM round-trip of the entire state
tree per event step — the lax.scan carry lives in HBM, so at ~1 KB of state
per run each of the ~105k steps of a simulated year re-reads and re-writes
every byte. This module re-expresses the same step as a Pallas kernel over a
2D grid ``(run_tiles, step_blocks)``:

  * state arrays are laid out **runs-last** ``(..., R)`` so independent runs
    ride the 128-wide lane dimension of the VPU (the scan engine's runs-first
    layout puts the tiny miner axis on lanes and wastes them);
  * every state BlockSpec indexes by run-tile only — Pallas keeps a revisited
    block in VMEM across the inner (step-block) grid dimension and writes it
    back to HBM once per tile, so state traffic drops from per-step to
    per-chunk;
  * the threefry bits are the **same draws** as the scan engine —
    ``random.bits(fold_in(run_key, 1+chunk), (steps, 2))`` per run, generated
    in transposed ``(steps, 2, R)`` layout and streamed one step-block at a
    time into VMEM — so the kernel's results are bit-identical to the scan
    engine's and the two are cross-checked for exact equality in
    tests/test_pallas_engine.py.

The kernel implements the honest fast-mode automaton (tpusim.state with
``any_selfish=False``: no private counters, no reveal, pairwise own_above /
own_in consensus bookkeeping). Selfish or exact-mode configurations stay on
the scan engine — `PallasEngine` refuses them. Semantics contract: reference
main.cpp:128-192 event loop, simulation.h:62-142 model, via SURVEY.md §2.1.
"""

from __future__ import annotations

import functools
import logging

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .config import SimConfig
from .engine import Engine
from .sampling import winner_thresholds32
from .state import (
    INF_TIME,
    INTERVAL_CAP,
    NEG_TIME_CAP,
    SimState,
    rebase,
)

__all__ = ["PallasEngine"]

logger = logging.getLogger("tpusim")

I32 = jnp.int32
U32 = jnp.uint32


def _step_block_kernel(
    # inputs streamed / revisited per grid cell
    bits_ref,  # (SB, 2, R) uint32 — this step-block's draws
    cap_ref,  # (1, R) int32
    lo_ref,  # (M, 1) uint32 winner interval lower bounds
    hi_ref,  # (M, 1) uint32 winner interval upper bounds
    prop_ref,  # (M, 1) int32 propagation delays
    # state input refs: copied into the output refs at the first step block
    # of each tile (outputs are write-only until then); HBM-aliased to the
    # outputs so the buffers are shared
    t_in, nbt_in, height_in, stale_in, base_in,
    garr_in, gcnt_in, oa_in, oin_in, ovf_in,
    # state output refs (revisited: resident in VMEM across step blocks)
    t_ref,  # (1, R) int32
    nbt_ref,  # (1, R) int32
    height_ref,  # (M, R) int32
    stale_ref,  # (M, R) int32
    base_ref,  # (M, R) int32
    garr_ref,  # (M, K, R) int32
    gcnt_ref,  # (M, K, R) int32
    oa_ref,  # (M, M, R) int32 own_above
    oin_ref,  # (M, M, R) int32 own_in
    ovf_ref,  # (1, R) int32
    *,
    sb: int,
    mean_interval_ms: float,
):
    m, k, r = garr_ref.shape

    # First step block of this run tile: seed the VMEM-resident output blocks
    # from the inputs. They persist across the inner grid dimension (the
    # block index depends only on the tile) and are written back once.
    @pl.when(pl.program_id(1) == 0)
    def _():
        for src, dst in [
            (t_in, t_ref), (nbt_in, nbt_ref), (height_in, height_ref),
            (stale_in, stale_ref), (base_in, base_ref), (garr_in, garr_ref),
            (gcnt_in, gcnt_ref), (oa_in, oa_ref), (oin_in, oin_ref),
            (ovf_in, ovf_ref),
        ]:
            dst[...] = src[...]

    cap = cap_ref[...]
    lo = lo_ref[...]  # (M, 1) broadcasts against (M, R)
    hi = hi_ref[...]
    prop = prop_ref[...]
    kidx = jax.lax.broadcasted_iota(I32, (1, k, 1), 1)  # (1, K, 1)
    midx = jax.lax.broadcasted_iota(I32, (m, 1), 0)  # (M, 1)
    # Literals, not captured jnp constants (pallas kernels cannot close over
    # device arrays).
    inf = jnp.int32(int(INF_TIME))
    neg_gate = jnp.int32(int(NEG_TIME_CAP) - 1)
    icap = jnp.float32(int(INTERVAL_CAP))

    def step(s, carry):
        t, nbt, height, stale, base, garr, gcnt, oa, oin, ovf = carry
        bw = bits_ref[s, 0, :][None, :]  # (1, R) uint32
        bi = bits_ref[s, 1, :][None, :]

        active = t < cap  # (1, R)
        found_due = active & (t == nbt)
        # Winner one-hot straight from the cumulative thresholds
        # (simulation.h:213-221): miner m wins iff lo[m] <= u < hi[m]; the
        # last interval is closed on the right, clamping the ~96/2^32
        # overflow draws to the last miner exactly like winner_from_bits.
        is_last = midx == m - 1  # (M, 1)
        ow = (bw >= lo) & ((bw < hi) | is_last) & found_due  # (M, R)
        # Interval draw (simulation.h:205-210 semantics, see tpusim.sampling).
        u = (bi >> U32(8)).astype(jnp.float32) * jnp.float32(2.0**-24)
        dt = jnp.minimum(-jnp.log1p(-u) * jnp.float32(mean_interval_ms), icap).astype(I32)

        # --- FoundBlock (honest: append one block arriving at t + prop).
        arrival = t + prop  # (M, R)
        n = jnp.sum((gcnt > 0).astype(I32), axis=1)  # (M, R)
        last_idx = jnp.maximum(n - 1, 0)
        onehot_last = kidx == last_idx[:, None, :]  # (M, K, R)
        last_arr = jnp.sum(jnp.where(onehot_last, garr, 0), axis=1)
        merge = ow & (n > 0) & (last_arr == arrival)
        overflowed = ow & ~merge & (n == k)
        write_idx = jnp.where(merge | overflowed, last_idx, jnp.minimum(n, k - 1))
        onehot_wr = (kidx == write_idx[:, None, :]) & ow[:, None, :]
        garr = jnp.where(onehot_wr, arrival[:, None, :], garr)
        accum = (merge | overflowed)[:, None, :]
        gcnt = jnp.where(onehot_wr, jnp.where(accum, gcnt + 1, 1), gcnt)
        ovf = ovf + jnp.sum(overflowed.astype(I32), axis=0, keepdims=True)
        height = height + ow.astype(I32)
        oa = oa + (ow[:, None, :] & ~ow[None, :, :]).astype(I32)
        oin = oin + (ow[:, None, :] & ow[None, :, :]).astype(I32)
        nbt = jnp.where(found_due, t + dt, nbt)

        # --- Notify sweep (flush + best chain + reorg), gated like
        # tpusim.state.notify(do=...): a sub-NEG_TIME_CAP flush time is a
        # no-op, and adopt is masked.
        do = active & ~(found_due & (nbt == t))
        t_flush = jnp.where(do, t, neg_gate)  # (1, R)
        arrived = garr <= t_flush[:, None, :]  # (M, K, R)
        n_f = jnp.sum(arrived.astype(I32), axis=1)  # (M, R)
        onehot_tip = kidx == (n_f - 1)[:, None, :]
        flushed_tip = jnp.sum(jnp.where(onehot_tip, garr, 0), axis=1)
        base = jnp.where(n_f > 0, flushed_tip, base)
        # Compact: shifted[m, d] = garr[m, d + n_f[m]] via a K x K one-hot
        # sel[m, d, s] = (s == d + n_f[m]); src K rides axis 2.
        sel = kidx[:, None, :, :] == (kidx[:, :, None, :] + n_f[:, None, None, :])  # (M,Kd,Ks,R)
        garr = jnp.sum(jnp.where(sel, garr[:, None, :, :], 0), axis=2)
        garr = jnp.where(jnp.any(sel, axis=2), garr, inf)
        gcnt = jnp.sum(jnp.where(sel, gcnt[:, None, :, :], 0), axis=2)

        # Best published chain, first-seen tiebreak (main.cpp:68-82).
        pub = height - jnp.sum(gcnt, axis=1)  # (M, R)
        best_h = jnp.max(pub, axis=0, keepdims=True)  # (1, R)
        cand = pub == best_h
        tipm = jnp.where(cand, base, inf)
        best_tip = jnp.min(tipm, axis=0, keepdims=True)
        winners_b = cand & (tipm == best_tip)
        # First true along the miner axis, without a cumsum (Mosaic-friendly).
        first_idx = jnp.min(jnp.where(winners_b, midx, m), axis=0, keepdims=True)
        onehot_b = midx == first_idx  # (M, R)

        # Reorg (simulation.h:124-142).
        adopt = (best_h > height) & do  # (M, R)
        oab = jnp.sum(oa * onehot_b.astype(I32)[None, :, :], axis=1)  # (M, R) own_above[:, b]
        stale = stale + jnp.where(adopt, oab, 0)
        oa = jnp.where(adopt[None, :, :], oab[:, None, :], oa)
        oa = jnp.where(adopt[:, None, :], 0, oa)
        oin_b = jnp.sum(oin * onehot_b.astype(I32)[:, None, :], axis=0)  # (M, R) own_in[b, :]
        unpub_b = jnp.sum(height * onehot_b.astype(I32), axis=0, keepdims=True) - best_h
        oin_bpub = oin_b - unpub_b * onehot_b.astype(I32)
        oin = jnp.where(adopt[:, None, :], oin_bpub[None, :, :], oin)
        height = jnp.where(adopt, best_h, height)
        garr = jnp.where(adopt[:, None, :], inf, garr)
        gcnt = jnp.where(adopt[:, None, :], 0, gcnt)
        base = jnp.where(adopt, best_tip, base)

        # Cut-through (main.cpp:173-182).
        pending = jnp.where(garr > t[:, None, :], garr, inf)
        earliest = jnp.min(pending, axis=(0, 1))[None, :]  # (1, R)
        t = jnp.where(active, jnp.maximum(jnp.minimum(nbt, earliest), t), t)
        return t, nbt, height, stale, base, garr, gcnt, oa, oin, ovf

    carry = (
        t_ref[...], nbt_ref[...], height_ref[...], stale_ref[...], base_ref[...],
        garr_ref[...], gcnt_ref[...], oa_ref[...], oin_ref[...], ovf_ref[...],
    )
    carry = jax.lax.fori_loop(0, sb, step, carry)
    (t_ref[...], nbt_ref[...], height_ref[...], stale_ref[...], base_ref[...],
     garr_ref[...], gcnt_ref[...], oa_ref[...], oin_ref[...], ovf_ref[...]) = carry


class PallasEngine(Engine):
    """Engine with the per-chunk execution replaced by the VMEM-resident
    Pallas kernel. Same host loop, same init/finalize, same draws — the
    outputs are bit-identical to the scan engine on any honest fast-mode
    config. Refuses selfish/exact configurations and device meshes (those
    run on the scan engine).

    ``tile_runs`` lanes of independent runs per grid cell (multiple of 128);
    ``step_block`` scan steps per kernel invocation — state stays in VMEM
    across step blocks of the same tile, bits stream in per block.
    """

    def __init__(
        self,
        config: SimConfig,
        mesh=None,
        *,
        tile_runs: int = 512,
        step_block: int = 64,
        interpret: bool = False,
    ):
        if mesh is not None:
            raise ValueError("PallasEngine is single-device; shard batches at the runner level")
        if config.network.any_selfish or config.resolved_mode != "fast":
            raise ValueError("PallasEngine implements the honest fast-mode path only")
        if tile_runs % 128 != 0:
            raise ValueError("tile_runs must be a multiple of 128")
        super().__init__(config, None)
        # The kernel consumes whole step blocks. The scan engine's auto
        # sizing is 64-aligned on every platform; silently changing an
        # explicitly requested chunk_steps would fork the sampling identity
        # between platforms, so refuse instead (make_engine then falls back
        # to the scan engine).
        self.step_block = step_block
        if self.chunk_steps % step_block != 0:
            raise ValueError(
                f"chunk_steps ({self.chunk_steps}) must be a multiple of "
                f"step_block ({step_block}) for the pallas engine"
            )
        self.tile_runs = tile_runs
        self.interpret = interpret

        net = config.network
        thr = winner_thresholds32(np.array([mc.hashrate_pct for mc in net.miners]))
        lo = np.concatenate([[0], thr[:-1]]).astype(np.uint32)
        self._lo = jnp.asarray(lo[:, None])
        self._hi = jnp.asarray(thr[:, None])
        self._prop = jnp.asarray(
            np.array([mc.propagation_ms for mc in net.miners], np.int32)[:, None]
        )
        self._chunk = jax.jit(self._pallas_chunk)
        self._scan_fallback: Engine | None = None

    def scan_twin(self) -> Engine:
        """A scan engine pinned to this engine's resolved chunk_steps — the
        identical sampling identity, so its results are bit-for-bit what the
        kernel would produce. The one place the pinning rule lives."""
        if self._scan_fallback is None:
            import dataclasses

            self._scan_fallback = Engine(
                dataclasses.replace(self.config, chunk_steps=self.chunk_steps)
            )
        return self._scan_fallback

    def run_batch(self, keys):
        """Tile-misaligned batches split: the aligned prefix runs on the
        kernel, the remainder on the draw-identical scan twin."""
        n = keys.shape[0]
        rem = n % self.tile_runs
        if rem == 0:
            return super().run_batch(keys)
        logger.info(
            "batch of %d is not a multiple of tile_runs=%d; %d run(s) take the scan engine",
            n, self.tile_runs, rem,
        )
        if n < self.tile_runs:
            return self.scan_twin().run_batch(keys)
        head = super().run_batch(keys[: n - rem])
        tail = self.scan_twin().run_batch(keys[n - rem:])
        return {k: head[k] + tail[k] for k in head}

    def _pallas_chunk(self, state: SimState, cap, keys, chunk_idx, params):
        n = cap.shape[0]
        m, k = self.n_miners, self.config.group_slots
        steps, sb, tile = self.chunk_steps, self.step_block, self.tile_runs
        if n % tile != 0:
            raise ValueError(f"batch ({n}) must be a multiple of tile_runs ({tile})")

        # Same draws as the scan engine, already transposed to (steps, 2, R).
        bits = jax.vmap(
            lambda kk: jax.random.bits(jax.random.fold_in(kk, 1 + chunk_idx), (steps, 2), U32),
            out_axes=2,
        )(keys)

        # SimState (runs-first) -> kernel layout (runs-last).
        tr = lambda x: jnp.moveaxis(x, 0, -1)
        st = (
            state.t[None, :], state.next_block_time[None, :],
            tr(state.height), tr(state.stale), tr(state.base_tip_arrival),
            tr(state.group_arrival), tr(state.group_count),
            tr(state.own_above), tr(state.own_in), state.overflow[None, :],
        )

        state_shapes = [
            ((1, n), I32), ((1, n), I32), ((m, n), I32), ((m, n), I32), ((m, n), I32),
            ((m, k, n), I32), ((m, k, n), I32), ((m, m, n), I32), ((m, m, n), I32),
            ((1, n), I32),
        ]

        def tile_spec(shape):
            block = shape[:-1] + (tile,)
            ndim = len(shape)

            def index_map(i, j, nd=ndim):
                return (0,) * (nd - 1) + (i,)

            return pl.BlockSpec(block, index_map, memory_space=pltpu.VMEM)

        # self.params.mean_interval_ms is the concrete Python float; the
        # traced `params` copy would be a captured constant in the kernel.
        kernel = functools.partial(
            _step_block_kernel, sb=sb, mean_interval_ms=float(self.params.mean_interval_ms)
        )
        grid = (n // tile, steps // sb)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((sb, 2, tile), lambda i, j: (j, 0, i), memory_space=pltpu.VMEM),
                tile_spec((1, n)),  # cap
                pl.BlockSpec((m, 1), lambda i, j: (0, 0), memory_space=pltpu.VMEM),  # lo
                pl.BlockSpec((m, 1), lambda i, j: (0, 0), memory_space=pltpu.VMEM),  # hi
                pl.BlockSpec((m, 1), lambda i, j: (0, 0), memory_space=pltpu.VMEM),  # prop
                *[tile_spec(s) for s, _ in state_shapes],
            ],
            out_specs=[tile_spec(s) for s, _ in state_shapes],
            out_shape=[jax.ShapeDtypeStruct(s, d) for s, d in state_shapes],
            input_output_aliases={5 + i: i for i in range(len(state_shapes))},
            interpret=self.interpret,
        )(bits, cap[None, :], self._lo, self._hi, self._prop, *st)

        (t, nbt, height, stale, base, garr, gcnt, oa, oin, ovf) = out
        bk = lambda x: jnp.moveaxis(x, -1, 0)
        new_state = state._replace(
            t=t[0], next_block_time=nbt[0],
            height=bk(height), stale=bk(stale), base_tip_arrival=bk(base),
            group_arrival=bk(garr), group_count=bk(gcnt),
            own_above=bk(oa), own_in=bk(oin), overflow=ovf[0],
        )
        return jax.vmap(rebase)(new_state)
