"""Event-driven simulation engine: one lax.scan step per event, vmapped over runs.

Reformulates the reference event loop (``RunSimulation``, main.cpp:128-192) as a
fixed-trip-count ``jax.lax.scan`` over the O(1) automaton of :mod:`tpusim.state`:

  reference iteration                      scan step
  ------------------------------------     ------------------------------------
  while (cur_time == next_block_time)      one found-event per step; the notify
      PickFinder + FoundBlock              is skipped while another same-ms find
      next_block_time += interval          is due, reproducing the while-drain
  BestChain + NotifyBestChain(all)         notify() (flush, best, reveal, reorg)
  best_chain_size = best.size()            best_height_prev
  cut-through to min(next_block,           t = max(min(next_block_time,
      EarliestArrival)                         earliest_arrival), t)

Each run sees a different event count, so the scan runs a Poisson upper bound
of steps with a per-run done mask; a run that would exceed the bound (tail
probability ~1e-13 at the default margin) is flagged ``truncated`` rather than
silently biased. RNG is counter-based: every (run, step) derives its interval
and winner keys by fold_in, so draws are independent of execution order —
replacing the reference's two per-run xoroshiro streams (main.cpp:131-134).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

from .config import SimConfig
from .sampling import draw_interval_ms, draw_winner
from .state import (
    I64,
    SimParams,
    SimState,
    earliest_arrival,
    final_stats,
    found_block,
    init_state,
    make_params,
    notify,
)

__all__ = ["default_n_steps", "simulate_run", "simulate_batch", "batch_stat_sums"]


def default_n_steps(duration_ms: int, block_interval_s: float) -> int:
    """Upper bound on event-loop iterations: found events + arrival events
    <= 2x the block count. Sized at mean + 8 sigma of the Poisson block count
    (per-run overflow probability ~1e-13)."""
    mu = duration_ms / (block_interval_s * 1000.0)
    return int(2.0 * (mu + 8.0 * math.sqrt(mu + 1.0))) + 16


def _tree_select(pred: jax.Array, new, old):
    return jax.tree_util.tree_map(lambda a, b: jnp.where(pred, a, b), new, old)


def _step(state: SimState, step_idx: jax.Array, run_key: jax.Array, params: SimParams) -> SimState:
    duration = jnp.asarray(params.duration_ms, I64)
    active = state.t < duration

    kf = jax.random.fold_in(run_key, step_idx)
    w = draw_winner(jax.random.fold_in(kf, 1), params.thresholds)
    dt = draw_interval_ms(jax.random.fold_in(kf, 0), params.mean_interval_ns)

    found_due = active & (state.t == state.next_block_time)
    after_found = found_block(state, params, w)
    after_found = after_found._replace(next_block_time=state.t + dt)
    state1 = _tree_select(found_due, after_found, state)

    # Another find due at the same millisecond: defer the notify, matching the
    # reference's while-drain (main.cpp:151-157). Between two same-ms finds no
    # published state changes (all stamps are in the future), so deferral is
    # only load-bearing for 0ms-propagation configs.
    skip_notify = found_due & (state1.next_block_time == state.t)
    notified = notify(state1, params)
    state2 = _tree_select(active & ~skip_notify, notified, state1)

    # Cut-through to the next event (main.cpp:173-182). The max() guard keeps
    # time in place when a same-ms find is still pending (unflushed arrivals
    # could otherwise pull the min below cur_time).
    new_t = jnp.maximum(jnp.minimum(state2.next_block_time, earliest_arrival(state2)), state2.t)
    state3 = state2._replace(t=new_t)
    return _tree_select(active, state3, state)


def simulate_run(
    run_key: jax.Array, params: SimParams, n_steps: int, n_miners: int, group_slots: int, exact: bool
) -> dict[str, jax.Array]:
    """Simulate one full run and return its per-miner stats."""
    state = init_state(n_miners, group_slots, exact)
    first_interval = draw_interval_ms(jax.random.fold_in(run_key, n_steps), params.mean_interval_ns)
    state = state._replace(next_block_time=first_interval)

    def body(carry: SimState, idx: jax.Array):
        return _step(carry, idx, run_key, params), None

    state, _ = jax.lax.scan(body, state, jnp.arange(n_steps))
    return final_stats(state, params)


@partial(jax.jit, static_argnames=("n_steps", "n_miners", "group_slots", "exact"))
def simulate_batch(
    keys: jax.Array, params: SimParams, n_steps: int, n_miners: int, group_slots: int, exact: bool
) -> dict[str, jax.Array]:
    """vmap of :func:`simulate_run` over a batch of run keys.

    This is the TPU replacement for the reference's thread fan-out
    (main.cpp:205-213): runs become a vectorized leading axis instead of
    std::async tasks."""
    sim = partial(
        simulate_run,
        params=params,
        n_steps=n_steps,
        n_miners=n_miners,
        group_slots=group_slots,
        exact=exact,
    )
    return jax.vmap(sim)(keys)


def batch_stat_sums(per_run: dict[str, jax.Array]) -> dict[str, jax.Array]:
    """Reduce per-run stats to the sums the runner accumulates across batches.

    Mirrors ``MinerStats::operator+=`` accumulation (main.cpp:34-40,214-216):
    ratios are summed per run and divided by the run count at the very end, so
    the reported stale rate is a mean of per-run ratios, not a ratio of sums.
    """
    return {
        "blocks_found_sum": jnp.sum(per_run["blocks_found"], axis=0),
        "blocks_share_sum": jnp.sum(per_run["blocks_share"], axis=0, dtype=jnp.float64),
        "stale_rate_sum": jnp.sum(per_run["stale_rate"], axis=0, dtype=jnp.float64),
        "stale_blocks_sum": jnp.sum(per_run["stale_blocks"], axis=0),
        "best_height_sum": jnp.sum(per_run["best_height"]),
        "overflow_sum": jnp.sum(per_run["overflow"]),
        "truncated_sum": jnp.sum(per_run["truncated"].astype(jnp.int64)),
        "runs": jnp.asarray(per_run["truncated"].shape[0], jnp.int64),
    }


def make_batch_fn(config: SimConfig):
    """Build (params, jitted batch fn keys->stat sums) for a config."""
    params = make_params(config)
    n_steps = config.max_steps or default_n_steps(config.duration_ms, config.network.block_interval_s)
    exact = config.resolved_mode == "exact"
    m = config.network.n_miners

    def batch_fn(keys: jax.Array) -> dict[str, jax.Array]:
        per_run = simulate_batch(
            keys, params, n_steps=n_steps, n_miners=m, group_slots=config.group_slots, exact=exact
        )
        return batch_stat_sums(per_run)

    return params, batch_fn
